"""Prediction cache + single-flight request dedup: the content-hash
front layer of the serving stack (ISSUE 10).

Real million-user traffic is hot-key-heavy (Zipf-distributed), and
before this layer every repeated request paid the full queue + staging
+ device cost. Clipper's prediction cache (PAPERS.md) is the front-door
answer: hash the request CONTENT (the idiom serve/faults.py already
uses for request-sticky fault draws), key it by what actually
determines the answer — the live model version, its serving precision,
and the input bytes — and serve repeats without touching the pipeline.
Three cooperating mechanisms, front to back:

1. **Response cache** (`PredictionCache`): a bounded LRU keyed by
   `(live version, infer_dtype, rows, sha256(input bytes))`. A hit
   costs one hash + one dict lookup — no queue, no staging, no device
   dispatch. Entries record the version that COMPUTED them; a read
   re-checks it against the key's version (captured at insert, checked
   at read), and the registry invalidates the whole cache atomically on
   every live-route change (promote, rollback, dtype activation), so a
   stale-version hit is structurally impossible: keys are derived from
   the CURRENT live route, inserts are refused when the computing
   version no longer matches the key (canary results, mid-promote
   races), and an epoch stamp drops any in-flight insert that raced an
   invalidation.
2. **Single-flight collapse** (`CacheFront`): concurrent identical
   misses share ONE in-flight computation. The first miss (the leader)
   dispatches through the batcher; followers park on the leader's
   flight and resolve from its bytes. A leader failure fails every
   follower with the leader's error — errors are never cached, and the
   next identical request elects a fresh leader.
3. **Intra-batch dedup** (batcher-side, `DynamicBatcher(dedup=True)`):
   identical rows inside one coalesced drain dispatch once and fan out,
   shrinking the padded bucket — the within-drain sibling of (2).

Observability is first-class, not skipped on the fast path: a cache
hit still records the per-version/per-dtype metrics populations and a
request trace (`cache.lookup` / `cache.hit` spans; over-SLO hits land
in the tracer's exemplar ring like any slow request), hit responses
carry `X-Trace-Id`, and hit/miss/collapse/evict counters plus the hit
ratio surface in `/metrics` (JSON and Prometheus).

Concurrency: all cache state (`_entries`, `_flights`, the counters)
mutates under ONE named lock (`cache.state`, lint rule DML008); the
lock is never held across a batcher submit, an engine call, or a
future resolution — follower fan-out happens after release, the same
hygiene ServeMetrics.snapshot applies to its percentile math.
"""

from __future__ import annotations

import hashlib
import logging
import time
from collections import OrderedDict
from concurrent.futures import Future
from dataclasses import dataclass, field
from typing import Any, Optional

import numpy as np

from distributedmnist_tpu.analysis.locks import make_lock
from distributedmnist_tpu.serve import trace
from distributedmnist_tpu.serve.resilience import DeadlineExceeded

log = logging.getLogger("distributedmnist_tpu")


def content_key(version: Optional[str], infer_dtype: Optional[str],
                x: np.ndarray) -> tuple:
    """The cache key: (live version, serving precision, row count,
    sha256 of the canonical input bytes) — the faults.py content-hash
    idiom applied to request bytes. Version and dtype come from the
    CURRENT live route, so entries written under a demoted route are
    unreachable the instant a promote lands."""
    return (version, infer_dtype, int(x.shape[0]),
            hashlib.sha256(x.tobytes()).digest())


@dataclass
class _Entry:
    """One cached response: the logits bytes plus the identity of the
    engine set that computed them (checked again at read) and the
    monotonic insert stamp the TTL ages against (ISSUE 14 satellite)."""

    logits: np.ndarray
    version: Optional[str]
    infer_dtype: Optional[str]
    t_insert: float = 0.0


@dataclass
class _Follower:
    """One collapsed request parked on a flight: resolved from the
    leader's bytes (or failed with the leader's error) by the leader's
    done-callback."""

    rid: int
    trace_id: Optional[str]
    future: Future
    t0: float
    rows: int


@dataclass
class _Flight:
    """One in-flight computation shared by all concurrent identical
    misses. The leader's batcher future drives it; followers accumulate
    under the cache lock and are fanned out when the leader resolves."""

    key: tuple
    version: Optional[str]
    infer_dtype: Optional[str]
    epoch: int
    followers: list = field(default_factory=list)


class PredictionCache:
    """Bounded LRU response cache with invalidation epochs.

    Thread-safe; every mutation of `_entries`/`_flights` happens under
    the named `cache.state` lock (lint DML008 enforces the shape for
    all of serve/). `invalidate()` is the registry hook: promote,
    rollback and dtype activation call it atomically with the routing
    swap, clearing every entry and bumping the epoch so in-flight
    single-flight inserts that raced the swap are dropped, not cached.
    """

    def __init__(self, capacity: int = 4096,
                 ttl_s: Optional[float] = None):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        if ttl_s is not None and ttl_s <= 0:
            raise ValueError(f"ttl_s must be > 0, got {ttl_s}")
        self.capacity = capacity
        # Bounded staleness (ISSUE 14 satellite): entries expire by
        # MONOTONIC age — a wall-clock step must never mass-expire (or
        # immortalize) the cache (the DML004 discipline). An expired
        # entry is dropped at lookup time and the lookup counts as a
        # miss; None = no TTL (the PR 10 behavior).
        self.ttl_s = ttl_s
        self._lock = make_lock("cache.state")
        self._entries: "OrderedDict[tuple, _Entry]" = OrderedDict()
        self._flights: dict[tuple, _Flight] = {}
        self._epoch = 0
        self._hits = 0
        self._hit_rows = 0
        self._misses = 0
        self._collapsed = 0
        self._inserts = 0
        self._evictions = 0
        self._invalidations = 0
        self._stale_drops = 0
        self._expired = 0

    def _expired_locked(self, entry: _Entry, now: float) -> bool:
        """Caller holds the lock: True (and counted) when the entry
        has aged past the TTL."""
        if self.ttl_s is None or now - entry.t_insert <= self.ttl_s:
            return False
        self._expired += 1
        return True

    # -- direct surface (unit tests, simple callers) -----------------------

    def lookup(self, key: tuple) -> Optional[np.ndarray]:
        """LRU lookup; returns a copy of the cached logits or None.
        The entry's recorded computing version is re-checked against
        the key's version (captured at insert, checked at read): a
        mismatched entry is dropped and counted, never served."""
        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                self._misses += 1
                return None
            if self._expired_locked(entry, time.monotonic()):
                # aged past the TTL: dropped, counted, recomputed —
                # an expired hit IS a miss (ISSUE 14 satellite)
                del self._entries[key]
                self._misses += 1
                return None
            if entry.version != key[0] or entry.infer_dtype != key[1]:
                # defense in depth: the key embeds (version, dtype), so
                # this can only fire on a corrupted insert — but a
                # stale byte served once is worse than a dropped entry
                del self._entries[key]
                self._stale_drops += 1
                self._misses += 1
                return None
            self._entries.move_to_end(key)
            self._hits += 1
            self._hit_rows += entry.logits.shape[0]
            return np.array(entry.logits)

    def probe(self, key: tuple) -> Optional[np.ndarray]:
        """Shed-path lookup (ISSUE 18): the tenancy layer consults the
        cache BEFORE a quota or watermark shed — a hit costs zero
        device work, so serving it never needed the capacity the shed
        protects, and it must never be 429/503'd. A hit counts (and
        refreshes LRU recency) exactly like lookup's; a MISS counts
        nothing — the request was never going to dispatch, so a probe
        miss says nothing about the cache's effectiveness and must not
        dilute the hit ratio the /metrics surface reports."""
        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                return None
            if self._expired_locked(entry, time.monotonic()):
                del self._entries[key]
                return None
            if entry.version != key[0] or entry.infer_dtype != key[1]:
                del self._entries[key]
                self._stale_drops += 1
                return None
            self._entries.move_to_end(key)
            self._hits += 1
            self._hit_rows += entry.logits.shape[0]
            return np.array(entry.logits)

    def insert(self, key: tuple, logits: np.ndarray,
               computed_version: Optional[str],
               computed_dtype: Optional[str],
               epoch: Optional[int] = None) -> bool:
        """Insert a computed response. Refused (False, counted) when
        the COMPUTING version/dtype differ from the key's — a canary
        result or a mid-promote race must never be served as the live
        answer — or when `epoch` predates an invalidation."""
        with self._lock:
            if epoch is not None and epoch != self._epoch:
                self._stale_drops += 1
                return False
            if computed_version != key[0] or computed_dtype != key[1]:
                self._stale_drops += 1
                return False
            self._entries[key] = _Entry(
                np.array(logits, copy=True), computed_version,
                computed_dtype, t_insert=time.monotonic())
            self._entries.move_to_end(key)
            self._inserts += 1
            while len(self._entries) > self.capacity:
                self._entries.popitem(last=False)
                self._evictions += 1
            return True

    def invalidate(self, reason: Optional[str] = None) -> None:
        """Drop every entry and bump the epoch (the registry's
        live-route-change hook). In-flight single-flight leaders keep
        computing — their followers still resolve — but their inserts
        are refused by the epoch check."""
        with self._lock:
            dropped = len(self._entries)
            self._entries.clear()
            self._epoch += 1
            self._invalidations += 1
        if dropped or reason:
            log.info("prediction cache invalidated (%s): %d entries "
                     "dropped", reason or "unspecified", dropped)

    def align_epoch(self, epoch: int, reason: Optional[str] = None) -> bool:
        """Adopt a fleet-assigned invalidation epoch (ISSUE 19: the
        worker-side landing of the gateway's cluster-epoch fan-out,
        called only from serve.apply_cluster_epoch). A FORWARD move
        drops every entry exactly like invalidate() — entries computed
        under the previous cluster epoch must never serve under the
        new one — and pins this cache's epoch to the cluster's, so
        in-flight leader inserts keyed to the old epoch are refused by
        the insert() check. A replayed or stale epoch (<= current) is
        a no-op: fan-out retries must not wipe a warm shard. Returns
        True when the move happened."""
        with self._lock:
            if epoch <= self._epoch:
                return False
            dropped = len(self._entries)
            self._entries.clear()
            self._epoch = epoch
            self._invalidations += 1
        log.info("prediction cache aligned to cluster epoch %d (%s): "
                 "%d entries dropped", epoch, reason or "unspecified",
                 dropped)
        return True

    def epoch(self) -> int:
        with self._lock:
            return self._epoch

    def flights(self) -> int:
        """In-flight single-flight computations (leader dispatched,
        not yet resolved)."""
        with self._lock:
            return len(self._flights)

    def stats(self) -> dict:
        """The counters `/metrics` exposes (JSON `cache` block; the
        Prometheus exposition flattens the same dict)."""
        with self._lock:
            lookups = self._hits + self._misses
            return {
                "capacity": self.capacity,
                "ttl_s": self.ttl_s,
                "expired": self._expired,
                "entries": len(self._entries),
                "inflight_keys": len(self._flights),
                "hits": self._hits,
                "hit_rows": self._hit_rows,
                "misses": self._misses,
                "collapsed": self._collapsed,
                "inserts": self._inserts,
                "evictions": self._evictions,
                "invalidations": self._invalidations,
                "stale_drops": self._stale_drops,
                "epoch": self._epoch,
                "hit_ratio": (round(self._hits / lookups, 4)
                              if lookups else None),
            }


class CacheFront:
    """The submit()-shaped front layer: cache lookup + single-flight
    collapse in front of a DynamicBatcher.

    Duck-types the batcher's client surface (`submit` returning a
    Future with `.version`/`.trace_id` attributes, `pending_rows`,
    `inflight_batches`, `stop`), so serve.py's HTTP handler and the
    bench drive it unchanged. With no live version (server warming) it
    passes straight through — the batcher's NoLiveModel 503 semantics
    are preserved, nothing is keyed on a route that does not exist.
    """

    def __init__(self, batcher, router, cache: PredictionCache,
                 metrics=None):
        self.batcher = batcher
        self.router = router
        self.cache = cache
        self.metrics = metrics

    # -- batcher-surface proxies (bench drain predicate, stop) -------------

    def pending_rows(self) -> int:
        return self.batcher.pending_rows()

    def inflight_batches(self) -> int:
        return self.batcher.inflight_batches()

    def stop(self, drain: bool = True) -> None:
        self.batcher.stop(drain=drain)

    # -- the front door ----------------------------------------------------

    def _live_route(self) -> tuple:
        """(live version, live infer_dtype) read atomically where the
        router supports it (one lock crossing — two separate reads
        could interleave with a promote and key a mixed route)."""
        fn = getattr(self.router, "live_route", None)
        if callable(fn):
            return fn()
        return (self.router.live_version(),
                getattr(self.router, "live_infer_dtype",
                        lambda: None)())

    def submit(self, x, deadline_s: Optional[float] = None,
               route: Optional[str] = None,
               route_label: Optional[str] = None,
               tags: Optional[dict] = None) -> Future:
        """Cache-or-collapse-or-dispatch. Returns a Future resolving to
        the request's (n, 10) logits:

        - **hit**: already resolved, version-tagged, trace finished
          (cache.lookup + cache.hit spans; X-Trace-Id rides the future
          exactly like a computed response) — sub-millisecond, zero
          device work;
        - **collapsed miss**: parked on the identical in-flight
          leader's flight, resolved (or failed) with the leader;
        - **leading miss**: dispatched through the batcher as usual
          (the batcher owns its trace), with the result cached on
          completion unless the computing version no longer matches.

        `route` pins the dispatch to a named infer_dtype (the
        cascade's stage requests); `route_label` (defaulting to the
        route) replaces the live dtype in the cache key, so a pinned
        stage's bytes are keyed — and only ever served — under the
        precision that computed them, never the live route's label.
        `tags` (the tenancy layer's attribution, ISSUE 18) pass
        through to the batcher for a leading miss — hits and collapsed
        followers never reach a queue, so they carry none.
        """
        x = self.router._as_images(x)
        n = x.shape[0]
        t0 = time.monotonic()
        if deadline_s is not None and t0 >= deadline_s:
            # mirror the batcher's shed-at-submit contract: an expired
            # request costs nothing, not even a hash
            if self.metrics is not None:
                self.metrics.record_deadline_shed(n)
            raise DeadlineExceeded(
                "deadline already expired at submit "
                f"({(t0 - deadline_s) * 1e3:.1f} ms ago)")
        version, infer_dtype = self._live_route()
        if version is None:
            # warming / drained of versions: nothing to key on; the
            # pipeline's NoLiveModel 503 path is authoritative
            return self.batcher.submit(x, deadline_s=deadline_s,
                                       route=route,
                                       **({"tags": tags} if tags
                                          else {}))
        if route_label is None:
            route_label = route
        if route_label is not None:
            infer_dtype = route_label
        key = content_key(version, infer_dtype, x)
        cache = self.cache
        tr = trace.active()
        hit: Optional[_Entry] = None
        flight: Optional[_Flight] = None
        follower: Optional[_Follower] = None
        leading = False
        with cache._lock:
            entry = cache._entries.get(key)
            if (entry is not None
                    and cache._expired_locked(entry, time.monotonic())):
                # aged past the TTL (ISSUE 14 satellite): drop and
                # fall through to the miss path — the next identical
                # request recomputes under single-flight as usual
                del cache._entries[key]
                entry = None
            if entry is not None and entry.version == version \
                    and entry.infer_dtype == infer_dtype:
                cache._entries.move_to_end(key)
                cache._hits += 1
                cache._hit_rows += n
                hit = entry
            else:
                if entry is not None:
                    # version/dtype mismatch inside a matching key:
                    # corrupted insert — drop, never serve (checked at
                    # read, the invalidation-race backstop)
                    del cache._entries[key]
                    cache._stale_drops += 1
                cache._misses += 1
                flight = cache._flights.get(key)
                if flight is not None:
                    # Follower registration happens UNDER the cache
                    # lock, and the leader's done-callback pops the
                    # flight under the same lock — a registered
                    # follower can therefore never be skipped, and its
                    # trace is open before the leader could finish it.
                    cache._collapsed += 1
                    rid = self.batcher.next_rid()
                    fut: Future = Future()
                    tid = (tr.start_request(rid, rows=n,
                                            deadline_s=deadline_s,
                                            t0=t0)
                           if tr is not None else None)
                    fut.trace_id = tid
                    # Collapsed-follower marker: harnesses that audit
                    # per-request outcomes (the chaos leg's poison-
                    # isolation ledger, ISSUE 12) must be able to tell
                    # a leader's failure from its followers' echoes of
                    # the same error — one injected fault, one rid,
                    # N futures.
                    fut.collapsed = True
                    follower = _Follower(rid, tid, fut, t0, n)
                    flight.followers.append(follower)
                    # span recorded UNDER the lock, like the trace
                    # start above: once the lock drops the leader's
                    # done-callback may finish this trace, and a span
                    # added after the finish would be silently dropped
                    trace.add_span("cache.lookup", t0,
                                   time.monotonic(), rids=(rid,),
                                   collapsed=True)
                else:
                    flight = _Flight(key, version, infer_dtype,
                                     cache._epoch)
                    cache._flights[key] = flight
                    leading = True
        if hit is not None:
            return self._resolve_hit(hit, n, t0, deadline_s)
        if not leading:
            return follower.future
        return self._lead(flight, x, deadline_s, route, tags=tags)

    def _resolve_hit(self, entry: _Entry, n: int, t0: float,
                     deadline_s: Optional[float]) -> Future:
        """Build the already-resolved Future for a cache hit, with the
        full observability a computed response gets: metrics
        populations (per-version AND per-dtype — a hit must never
        silently skip accounting), a finished trace whose id rides the
        future (X-Trace-Id), and an over-SLO hit landing in the
        tracer's exemplar ring like any other slow request."""
        tr = trace.active()
        tid = None
        if tr is not None:
            rid = self.batcher.next_rid()
            tid = tr.start_request(rid, rows=n, deadline_s=deadline_s,
                                   t0=t0)
            now = time.monotonic()
            tr.add_span("cache.lookup", t0, now, rids=(rid,))
            tr.add_span("cache.hit", now, now, rids=(rid,),
                        version=entry.version,
                        infer_dtype=entry.infer_dtype)
            tr.finish_request(rid)
        if self.metrics is not None:
            self.metrics.record_cache_hit(
                time.monotonic() - t0, rows=n, version=entry.version,
                infer_dtype=entry.infer_dtype)
        fut: Future = Future()
        fut.trace_id = tid
        fut.version = entry.version
        fut.cache_hit = True        # outcome-audit marker (chaos leg)
        fut.set_result(np.array(entry.logits))
        return fut

    def _lead(self, flight: _Flight, x, deadline_s,
              route: Optional[str] = None,
              tags: Optional[dict] = None) -> Future:
        """Dispatch the leader through the batcher. The leader's OWN
        future is the batcher's (its trace, version tag and error
        semantics are untouched); the flight resolves from it."""
        try:
            # tags only when they carry attribution: absent tenancy,
            # the call keeps the pre-ISSUE-18 submit shape (duck-typed
            # batcher fakes across the suite depend on it)
            bf = self.batcher.submit(x, deadline_s=deadline_s,
                                     key=flight.key[3], route=route,
                                     **({"tags": tags} if tags
                                        else {}))
        except BaseException as e:
            # Rejected / DeadlineExceeded / stopped batcher: the flight
            # never got a computation — followers that slipped in
            # between registration and here fail with the same error.
            self._fail_flight(flight, e)
            raise
        bf.add_done_callback(
            lambda done, fl=flight: self._flight_done(fl, done))
        return bf

    def _fail_flight(self, flight: _Flight, err: BaseException) -> None:
        cache = self.cache
        with cache._lock:
            cache._flights.pop(flight.key, None)
            followers = list(flight.followers)
            flight.followers.clear()
        self._fan_out(flight, followers, None, None, err)

    def _flight_done(self, flight: _Flight, bf: Future) -> None:
        """The leader resolved (completion thread, or inline for an
        already-done future): cache the bytes if they are still the
        live route's answer, then fan the flight's followers out —
        futures resolve OUTSIDE the cache lock."""
        err: Optional[BaseException] = None
        logits = None
        try:
            logits = bf.result()
        except BaseException as e:   # leader error: followers share it,
            err = e                  # nothing is ever cached
        computed_version = getattr(bf, "version", None)
        cache = self.cache
        with cache._lock:
            fl = cache._flights.pop(flight.key, None)
            followers = list(fl.followers) if fl is not None else []
            if fl is not None:
                fl.followers.clear()
        if err is None:
            # insert() re-checks the computing version against the
            # key's and the flight's epoch against the current one: a
            # promote/rollback/dtype-activation that raced this flight
            # (or a canary/mid-swap computation) is refused and counted
            # — the bytes still answer THESE requests, which were
            # admitted under the old route exactly like any in-flight
            # batch across a promote, but are never served to future
            # lookups.
            cache.insert(flight.key, logits, computed_version,
                         flight.key[1], epoch=flight.epoch)
        self._fan_out(flight, followers, logits, computed_version, err)

    def _fan_out(self, flight: _Flight, followers: list, logits,
                 computed_version,
                 err: Optional[BaseException]) -> None:
        """Resolve (or fail) every follower, finishing each trace
        BEFORE its future resolves — the Server-Timing contract the
        batcher keeps, kept here too. Each follower gets its OWN copy
        of the bytes (the cache's copy-on-hit discipline): one
        caller's in-place edit of its result must never corrupt a
        concurrent identical request's."""
        tr = trace.active()
        now = time.monotonic()
        for f in followers:
            try:
                if tr is not None and f.trace_id is not None:
                    tr.add_span("cache.collapse", f.t0, now,
                                rids=(f.rid,),
                                version=computed_version,
                                error=(type(err).__name__
                                       if err is not None else None))
                    tr.finish_request(f.rid, error=err)
                if err is not None:
                    f.future.set_exception(err)
                    continue
                if self.metrics is not None:
                    self.metrics.record_cache_hit(
                        now - f.t0, rows=f.rows,
                        version=computed_version,
                        infer_dtype=flight.key[1], collapsed=True)
                f.future.version = computed_version
                f.future.set_result(np.array(logits))
            except Exception:        # one bad follower must not strand
                log.exception("cache follower fan-out failed")


def build_cache_front(cfg, batcher, router, registry, metrics=None):
    """(front, cache) per Config: the CacheFront wired in front of the
    batcher with the registry's invalidation hook installed, or
    (batcher, None) when cfg.serve_cache is off — callers submit to
    whatever comes back."""
    if not cfg.serve_cache:
        return batcher, None
    cache = PredictionCache(cfg.serve_cache_capacity,
                            ttl_s=cfg.serve_cache_ttl_s)
    if hasattr(registry, "set_cache"):
        registry.set_cache(cache)
    return CacheFront(batcher, router, cache, metrics=metrics), cache
