"""SLO-aware adaptive batch scheduling: cost-model bucket packing and
Clipper-style adaptive coalescing (ISSUE 4).

Two pieces, both pure policy (no jax, no threads of their own), so the
batcher stays the single owner of dispatch mechanics:

- **plan_segments** — the batch former. The naive dispatch pads a whole
  queue drain to its single smallest covering bucket, so a 9-row drain
  runs the bucket-16 program and burns 44% of its FLOPs on padding. But
  engine warmup MEASURES what each bucket's compiled program actually
  costs (engine.bucket_costs), and Clockwork's observation applies: once
  per-program costs are known and stable, the scheduler should exploit
  them. plan_segments partitions one FIFO drain into several
  bucket-shaped dispatches whenever the cost table says split beats pad
  (20 rows -> 16+4 instead of 32), feeding the pipelined in-flight
  window several right-sized batches instead of one oversized padded
  one. Requests are never split across dispatches (a request's future
  resolves from exactly one fetch), and FIFO order is preserved, so the
  only degree of freedom is WHERE to cut — an exact dynamic program
  over request boundaries, O(requests x buckets) via the
  furthest-fill-per-bucket reduction.

- **AdaptiveController** — the coalescing-wait controller. A fixed
  max_wait_us is wrong at both ends of the load curve: too long when
  the system is violating its SLO (queueing delay it can't afford), too
  short when there is latency headroom that could buy occupancy.
  Clipper's AIMD batch-parameter search, applied to the wait knob:
  multiplicative step-DOWN of the effective wait on every observed SLO
  violation, small additive creep-UP after a window of comfortably
  under-SLO requests. The configured max_wait_us stays a hard cap, the
  floor is zero wait (one-row immediacy) — the controller can never
  push latency ABOVE the static configuration, only trade within it.
  An arrival-rate EWMA additionally caps the wait at the time the
  current rate needs to FILL max_batch rows: waiting longer than the
  fill time buys nothing at any load.
"""

from __future__ import annotations

import math
import time
from bisect import bisect_right
from typing import Mapping, Optional, Sequence

from distributedmnist_tpu.analysis.locks import make_lock


def fit_dispatch_cost(costs: Mapping[int, float]) -> tuple[float, float]:
    """Least-squares affine fit of a measured bucket-cost ladder:
    cost(b) ~= overhead + per_row * b, both clamped non-negative.

    Raw per-bucket medians carry timing noise comparable to the gap
    between ADJACENT rungs (a 2-row program and a 4-row program are the
    same microseconds of compute behind ~ms of dispatch overhead), so
    comparing raw entries at the margin plans on noise. The affine fit
    pools the whole ladder into the two quantities that actually govern
    split-vs-pad: what one more DISPATCH costs (overhead — the case
    against splitting) and what one more BUCKET ROW costs (per_row —
    the price of padding, the case for it). Returns (overhead_s,
    per_row_s)."""
    bs = sorted(costs)
    n = len(bs)
    if n == 0:
        raise ValueError("empty cost table")
    ys = [max(costs[b], 0.0) for b in bs]
    if n == 1:
        return ys[0], 0.0
    mx = sum(bs) / n
    my = sum(ys) / n
    sxx = sum((x - mx) ** 2 for x in bs)
    sxy = sum((x - mx) * (y - my) for x, y in zip(bs, ys))
    per_row = max(sxy / sxx, 0.0) if sxx else 0.0
    overhead = max(my - per_row * mx, 0.0)
    return overhead, per_row


# One-slot fit memo: the cost table only ever changes by whole-reference
# swap (engine.warmup / a promote re-pointing the live engine), but
# plan_segments runs once per queue drain on the dispatch hot path —
# re-fitting identical data up to ~1000x/sec between swaps is pure
# waste. Keyed on table identity + ladder; a stale-read race just
# recomputes (single atomic tuple assignment, no lock needed).
_fit_memo = None   # (costs_obj, buckets_tuple, (overhead_s, per_row_s))


def _fitted(costs: Mapping[int, float],
            buckets: Sequence[int]) -> tuple[float, float]:
    global _fit_memo
    memo = _fit_memo
    bkey = tuple(buckets)
    if memo is not None and memo[0] is costs and memo[1] == bkey:
        return memo[2]
    fit = fit_dispatch_cost({b: costs[b] for b in buckets})
    _fit_memo = (costs, bkey, fit)
    return fit


def plan_segments(sizes: Sequence[int], buckets: Sequence[int],
                  costs: Mapping[int, float],
                  pad_bias: float = 2.0) -> list[int]:
    """Partition a FIFO drain into contiguous dispatch segments.

    `sizes` are the per-request row counts of one coalesced drain, in
    queue order; `buckets` the engine's ascending bucket ladder; `costs`
    the measured seconds-per-dispatch of each bucket's compiled program
    (engine.bucket_costs() — end-to-end infer time, so per-dispatch host
    overhead is priced in, not assumed away). A dispatch into bucket b
    carrying r real rows is priced off the ladder's affine fit
    (fit_dispatch_cost):

        overhead + per_row * (r + pad_bias * (b - r))

    i.e. a PADDED row costs pad_bias x a real row's fitted compute.
    pad_bias=1 is pure modeled wall-clock; the default 2 leans the
    near-tie decisions toward less padding, because a padded row does
    not just burn its own compute — under sustained load it displaces a
    real row from the same finite dispatch budget (the padding-waste
    FLOPs are the capacity the scheduler exists to reclaim), and on a
    noisy host the fitted costs of split-vs-pad near-ties sit inside
    timing noise anyway. Returns request counts per segment
    (sum == len(sizes)); [len(sizes)] means "don't split".

    Exact DP over request boundaries (a request's rows can never span
    two dispatches — its future resolves from exactly one fetch):
    dp[j] = min cost to dispatch the first j requests. From position i
    each bucket b reaches at most the furthest j with rows(i..j) <= b —
    filling a bucket with MORE requests at the same cost can never hurt
    (any later plan over the leftovers only shrinks), so only the
    furthest fill per bucket needs relaxing. Ties break toward FEWER
    segments: equal modeled cost must not churn extra dispatches.
    """
    k = len(sizes)
    if k <= 1:
        return [k] if k else []
    if any(b not in costs for b in buckets):
        # No confident cost model (e.g. a stub engine, or pre-warmup):
        # fall back to the single covering dispatch.
        return [k]
    overhead, per_row = _fitted(costs, buckets)
    prefix = [0]
    for s in sizes:
        prefix.append(prefix[-1] + s)
    INF = (math.inf, math.inf)
    dp: list[tuple] = [INF] * (k + 1)     # (cost, n_segments)
    back = [0] * (k + 1)
    dp[0] = (0.0, 0)
    for i in range(k):
        if dp[i] is INF:
            continue
        cost_i, segs_i = dp[i]
        for b in buckets:
            j = bisect_right(prefix, prefix[i] + b) - 1
            if j <= i:
                continue                  # bucket can't carry request i
            rows = prefix[j] - prefix[i]
            seg_cost = overhead + per_row * (
                rows + pad_bias * (b - rows))
            cand = (cost_i + seg_cost, segs_i + 1)
            if cand < dp[j]:
                dp[j] = cand
                back[j] = i
    if dp[k] is INF:
        # A request larger than the top bucket can't be planned; the
        # engine's own bucket_for would reject it too. Don't split.
        return [k]
    cuts = []
    j = k
    while j > 0:
        cuts.append(j)
        j = back[j]
    cuts.append(0)
    cuts.reverse()
    return [b - a for a, b in zip(cuts, cuts[1:])]


# -- multi-tenant WFQ / EDF policy (ISSUE 18) -------------------------------
#
# Pure decision functions for the tenancy layer (serve/tenancy.py).
# Like plan_segments, they own the POLICY and nothing else: the
# GlobalScheduler calls them under its own named lock with plain dicts
# and lists, so the accounting is deterministic and unit-testable
# without threads, and the lint's DML017 containment check stays about
# WHERE the tenancy state is mutated (under the scheduler lock), not
# about what these functions compute.


def estimate_dispatch_s(rows: int, buckets: Sequence[int],
                        costs: Mapping[int, float],
                        default_per_row_s: float = 1e-3) -> float:
    """Price a prospective dispatch of `rows` real rows against a
    model's measured bucket-cost ladder: the affine fit evaluated at
    the covering bucket (padding included — the program runs the whole
    bucket regardless). Clockwork's premise is that these costs are
    known and stable, so deadline feasibility can be decided BEFORE
    queueing delay is spent. With no complete cost table (stub engine,
    pre-warmup, explorer fakes) falls back to a row-proportional unit
    price so policy stays total rather than guessing zero."""
    if rows <= 0:
        return 0.0
    if costs and buckets and all(b in costs for b in buckets):
        overhead, per_row = _fitted(costs, buckets)
        b = next((x for x in buckets if x >= rows), buckets[-1])
        return overhead + per_row * max(b, rows)
    return default_per_row_s * rows


def edf_pick(heads: Sequence[tuple], now: float) -> tuple:
    """Earliest-feasible-deadline selection across model queues.

    `heads` holds one (key, deadline, est_cost_s) per non-empty queue —
    the head-of-line request's ABSOLUTE deadline (None = best-effort)
    and the modeled cost of dispatching it now. Returns
    (pick, infeasible): `pick` is the key with the earliest deadline
    among heads that can still MAKE their deadline if dispatched now
    (best-effort heads rank after every deadlined head; ties break by
    input order), or None when nothing is feasible. `infeasible` lists
    the keys whose head cannot meet its deadline even with immediate
    dispatch — Clockwork's rule is to shed those NOW (504) rather than
    let a doomed request occupy a batch slot and poison the requests
    behind it."""
    infeasible = []
    feas = []
    for i, (key, deadline, cost_s) in enumerate(heads):
        if deadline is not None and now + cost_s > deadline:
            infeasible.append(key)
        else:
            feas.append((deadline if deadline is not None else math.inf,
                         i, key))
    if not feas:
        return None, infeasible
    feas.sort()
    return feas[0][2], infeasible


def drr_grant(ring: Sequence, cursor: int, deficits: dict,
              weights: Mapping, quantum: float, head_costs: Mapping,
              max_rounds: int = 1024) -> tuple:
    """One weighted deficit-round-robin grant decision (pure).

    `ring` is the fixed visit order of flows (tenants); `cursor` the
    ring index of the LAST granted flow; `deficits` the per-flow credit
    balances (mutated in place — the caller owns them and holds the
    scheduler lock); `head_costs` maps each BACKLOGGED flow to the
    modeled cost of its head-of-line work (absent = idle). Each visit
    credits the flow `quantum * weight` and grants the first flow whose
    balance covers its head — so over any interval every backlogged
    flow's service converges to its weight share, and a flow is granted
    within a bounded number of visits (drr_skip_bound) no matter how
    heavy the others are: starvation-freedom by construction. Idle
    flows' balances reset to zero (no hoarding credit while absent).
    Returns (flow, new_cursor, rounds_scanned); (None, cursor, 0) when
    nothing is backlogged. Raises RuntimeError after `max_rounds` full
    scans — quantum misconfigured so badly that no head is ever
    affordable, which callers treat as an assertion, not a wait."""
    if not ring or not head_costs:
        return None, cursor, 0
    n = len(ring)
    for f in ring:
        if f not in head_costs:
            deficits[f] = 0.0
    pos = cursor % n
    for rounds in range(max_rounds):
        for _ in range(n):
            pos = (pos + 1) % n
            f = ring[pos]
            if f not in head_costs:
                continue
            deficits[f] = (deficits.get(f, 0.0)
                           + quantum * weights.get(f, 1.0))
            if deficits[f] >= head_costs[f] - 1e-12:
                return f, pos, rounds
    raise RuntimeError(
        f"drr_grant: no flow affordable after {max_rounds} full scans "
        f"(quantum={quantum}, heads={dict(head_costs)}) — quantum is "
        "misconfigured relative to the cost model")


def drr_charge(deficits: dict, flow, cost: float) -> None:
    """Debit a granted flow's balance by the work actually dispatched.
    Clamped at zero: grant required coverage, so a negative balance can
    only mean the dispatched run was re-priced larger than the grant —
    carrying debt forward would punish the flow twice."""
    deficits[flow] = max(deficits.get(flow, 0.0) - cost, 0.0)


def drr_skip_bound(n_flows: int, max_cost: float, quantum: float,
                   min_weight: float) -> int:
    """Closed-form starvation bound for drr_grant: a backlogged flow is
    granted within this many consecutive GRANTS to other flows. Each
    full ring scan credits the flow quantum*weight, and it needs at
    most ceil(max_cost / that) scans to afford its head; between scans
    at most n_flows-1 other grants interleave. The tenancy layer
    asserts its observed consecutive-skip counters stay under this —
    the invariant the explorer machine checks on every schedule."""
    per_scan = max(quantum * min_weight, 1e-12)
    scans = max(int(math.ceil(max_cost / per_scan)), 1)
    return max(n_flows, 1) * (scans + 1)


def fastlane_eligible(enabled: bool, pending_rows: int) -> bool:
    """The bypass lane's admission rule (ISSUE 14), pure policy like
    everything in this module: a submit may skip the coalescing path
    only when the lane is on AND the queue is EMPTY. A non-empty queue
    means there is traffic worth coalescing with — jumping it would
    both reorder FIFO service and starve the drain of exactly the rows
    that make batching pay. The second half of the decision (a free
    in-flight window slot) is a semaphore try-acquire with a side
    effect, so it stays in the batcher, made under the same queue lock
    as this predicate — the drain/stop/shed invariants (and the PR 11
    explored machines) see one atomic lane decision."""
    return enabled and pending_rows == 0


class AdaptiveController:
    """AIMD effective-wait controller + arrival-rate EWMA (thread-safe).

    `on_arrival` is called by every accepted submit, `on_latency` with
    every request's end-to-end latency at fan-out; `effective_wait_s`
    is read once per drain by the dispatch thread. With no SLO
    configured the AIMD half is inert and the effective wait is the
    static max_wait_s (minus the fill-time cap) — the controller is
    always safe to leave in the loop.
    """

    def __init__(self, max_wait_s: float, slo_s: Optional[float] = None,
                 max_batch: Optional[int] = None, headroom: float = 0.8,
                 decrease: float = 0.5, increase_frac: float = 0.05,
                 window: int = 32, rate_tau_s: float = 1.0):
        if max_wait_s < 0:
            raise ValueError(f"max_wait_s must be >= 0, got {max_wait_s}")
        if slo_s is not None and slo_s <= 0:
            raise ValueError(f"slo_s must be > 0, got {slo_s}")
        if not 0 < decrease < 1:
            raise ValueError(f"decrease must be in (0,1), got {decrease}")
        self.max_wait_s = float(max_wait_s)
        self.slo_s = slo_s
        self.max_batch = max_batch
        self.headroom = headroom
        self.decrease = decrease
        self.increase_s = increase_frac * self.max_wait_s
        self.window = window
        self.rate_tau_s = rate_tau_s
        self._lock = make_lock("scheduler.aimd")
        self._wait_s = self.max_wait_s    # start at the configured point
        self._rate = 0.0                  # rows/sec EWMA
        self._t_last: Optional[float] = None
        self._win_n = 0                   # under-SLO samples this window
        self._win_max = 0.0
        self._violations = 0
        self._increases = 0
        self._fastpath = 0                # bypass-lane dispatches seen

    # -- inputs ------------------------------------------------------------

    def on_arrival(self, rows: int = 1, now: Optional[float] = None,
                   coalesced: bool = True) -> None:
        """One accepted request of `rows` rows; feeds the arrival-rate
        EWMA (irregular-interval exponential decay, tau=rate_tau_s).

        `coalesced=False` marks a fast-lane bypass (ISSUE 14): counted,
        but EXCLUDED from the rate EWMA — the fill-time cap prices how
        fast the QUEUE fills toward max_batch, and a request that never
        entered the queue must not make the controller believe drains
        fill faster than they do (which would shorten the wait exactly
        when the lane is already serving the lone-request traffic the
        wait exists to protect)."""
        if now is None:
            now = time.monotonic()
        with self._lock:
            if not coalesced:
                self._fastpath += 1
                return
            if self._t_last is None:
                self._t_last = now
                return
            dt = max(now - self._t_last, 1e-9)
            self._t_last = now
            decay = math.exp(-dt / self.rate_tau_s)
            self._rate = decay * self._rate + (1.0 - decay) * (rows / dt)

    def on_latency(self, seconds: float) -> None:
        """One request's end-to-end latency. AIMD: a violation halves
        the effective wait immediately (and restarts the headroom
        window); `window` consecutive under-SLO samples whose max sits
        below headroom*SLO earn one additive step back up, never past
        the max_wait_s hard cap."""
        if self.slo_s is None:
            return
        with self._lock:
            if seconds > self.slo_s:
                self._wait_s *= self.decrease
                self._violations += 1
                self._win_n = 0
                self._win_max = 0.0
                return
            self._win_n += 1
            self._win_max = max(self._win_max, seconds)
            if self._win_n >= self.window:
                if self._win_max < self.headroom * self.slo_s:
                    self._wait_s = min(self.max_wait_s,
                                       self._wait_s + self.increase_s)
                    self._increases += 1
                self._win_n = 0
                self._win_max = 0.0

    # -- outputs -----------------------------------------------------------

    def arrival_rate(self) -> float:
        with self._lock:
            return self._rate

    def effective_wait_s(self) -> float:
        """The coalescing wait the next drain should use: the AIMD
        point, capped by the time the current arrival rate needs to
        fill max_batch rows, clamped into [0, max_wait_s]."""
        with self._lock:
            w = self._wait_s
            if self.max_batch and self._rate > 0:
                w = min(w, self.max_batch / self._rate)
            return min(max(w, 0.0), self.max_wait_s)

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "slo_ms": (round(self.slo_s * 1e3, 3)
                           if self.slo_s is not None else None),
                "max_wait_us": round(self.max_wait_s * 1e6, 1),
                "aimd_wait_us": round(self._wait_s * 1e6, 1),
                "arrival_rate_rows_per_sec": round(self._rate, 1),
                "violations": self._violations,
                "increases": self._increases,
                "fastpath_dispatches": self._fastpath,
            }
