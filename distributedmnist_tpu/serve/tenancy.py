"""Multi-tenant, multi-model serving: per-model queues under one
Clockwork-style global scheduler (ISSUE 18).

The pre-tenancy stack serves ONE model: a registry of versions behind
one router, one DynamicBatcher queue, one live route. This module
generalizes it along two axes without touching that single-model path:

- **ModelCatalog** — many coexisting models (MLP + LeNet), each with
  its OWN registry/router/EngineFactory/batcher built by the same
  `registry.build_serving(cfg)` that boots a single-model server, so
  every model keeps its own bucket geometry, measured warmup cost
  table, dtype variants and independent promote/rollback/cascade
  state. The per-model batchers ARE the per-model queues; nothing
  about their dispatch mechanics changes.

- **GlobalScheduler** — ONE scheduler owning every dispatch decision
  across tenants and models (Gujarati et al., Clockwork, OSDI 2020:
  centralize the decisions, price them with a measured cost model).
  Admission maps the `X-Tenant` header to a configured SLO class
  (quota + deadline + weight); a token bucket enforces the quota with
  429 + Retry-After semantics (Crankshaw et al., Clipper, NSDI 2017:
  shed at the front door per class, don't absorb overload into queue
  delay); dispatch order across the per-tenant/per-model queues is
  weighted deficit-round-robin (scheduler.drr_grant) so a heavy
  tenant's burst cannot starve a light tenant — the consecutive-skip
  bound is ASSERTED every grant, not hoped. Which model's queue drains
  next is earliest-feasible-deadline (scheduler.edf_pick) priced by
  the live engine's per-bucket cost table; a head that cannot make its
  deadline even if dispatched NOW is shed immediately with 504 instead
  of poisoning the batch behind it. Engine residency is scheduler-
  owned: a cold model's warmup is a priced, scheduled event on a warm
  thread — never a surprise on the dispatch hot path.

Shed order is deliberate (ISSUE 18 satellite): before a quota or
watermark shed, the scheduler probes the prediction cache
(cache.probe) — a cached answer costs zero device work, so it is
served even over quota, never 429/503'd.

All tenancy accounting (`_tokens`, `_deficits`, `_skips`, `_granted`,
`_pending_rows`, `_queues`, `_cursor`) is mutated ONLY under the
scheduler's named condition `tenancy.sched` — the project lint's
DML017 enforces this containment mechanically.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import time
from collections import deque
from concurrent.futures import Future
from typing import Optional

import numpy as np

from distributedmnist_tpu.analysis.locks import make_condition, make_thread
from distributedmnist_tpu.serve import scheduler as policy
from distributedmnist_tpu.serve.batcher import DynamicBatcher, Rejected
from distributedmnist_tpu.serve.resilience import DeadlineExceeded

log = logging.getLogger("serve.tenancy")


class QuotaExceeded(RuntimeError):
    """Tenant over its token-bucket quota: 429 semantics. Carries the
    bucket's modeled refill time so serve.py can stamp Retry-After —
    the client is told WHEN a token will exist, not just to go away."""

    status = 429

    def __init__(self, msg: str, retry_after_s: float = 1.0):
        super().__init__(msg)
        self.retry_after_s = max(retry_after_s, 0.0)


@dataclasses.dataclass(frozen=True)
class SLOClass:
    """One tenant admission class: the configured quota, deadline and
    scheduling weight the X-Tenant header maps to. `qps=None` means
    unlimited (no token bucket); `deadline_ms=None` means best-effort
    (no default deadline, EDF ranks it after every deadlined head);
    `model=None` routes to the catalog's default model."""

    name: str
    qps: Optional[float] = None
    burst: float = 1.0
    deadline_ms: Optional[float] = None
    weight: float = 1.0
    model: Optional[str] = None

    def __post_init__(self):
        if self.qps is not None and self.qps <= 0:
            raise ValueError(f"tenant {self.name}: qps must be > 0")
        if self.burst < 1.0:
            raise ValueError(f"tenant {self.name}: burst must be >= 1")
        if self.deadline_ms is not None and self.deadline_ms <= 0:
            raise ValueError(
                f"tenant {self.name}: deadline_ms must be > 0")
        if self.weight <= 0:
            raise ValueError(f"tenant {self.name}: weight must be > 0")


def parse_tenants(spec: str) -> dict:
    """Parse --serve-tenants: `name:k=v,k=v;name2:...` with keys
    qps, burst, deadline_ms, weight, model. Returns {name: SLOClass}
    ALWAYS containing a "default" class (unlimited, weight 1) — the
    class an absent or unknown X-Tenant header resolves to; a spec
    entry named `default` overrides it. Raises ValueError on anything
    malformed — a misconfigured admission table must fail the boot,
    not silently admit everything."""
    classes = {}
    for part in (spec or "").split(";"):
        part = part.strip()
        if not part:
            continue
        name, sep, body = part.partition(":")
        name = name.strip()
        if not name:
            raise ValueError(f"tenant spec {part!r}: empty name")
        kwargs: dict = {}
        if sep:
            for kv in body.split(","):
                kv = kv.strip()
                if not kv:
                    continue
                k, eq, v = kv.partition("=")
                if not eq:
                    raise ValueError(
                        f"tenant {name}: expected k=v, got {kv!r}")
                k = k.strip()
                v = v.strip()
                if k in ("qps", "burst", "deadline_ms", "weight"):
                    kwargs[k] = float(v)
                elif k == "model":
                    kwargs[k] = v
                else:
                    raise ValueError(f"tenant {name}: unknown key {k!r}")
        if name in classes:
            raise ValueError(f"tenant {name} specified twice")
        classes[name] = SLOClass(name=name, **kwargs)
    classes.setdefault("default", SLOClass(name="default"))
    return classes


def token_admit(tokens: float, t_last: float, now: float,
                qps: Optional[float], burst: float) -> tuple:
    """One pure token-bucket admission step. Returns
    (ok, tokens_after, retry_after_s): refill at `qps` tokens/sec since
    `t_last`, capped at `burst`; admission costs one token. With no
    rate the bucket is inert (always ok). `retry_after_s` is the exact
    time until one token exists — the Retry-After header's value."""
    if qps is None or qps <= 0:
        return True, tokens, 0.0
    tokens = min(burst, tokens + max(now - t_last, 0.0) * qps)
    if tokens >= 1.0:
        return True, tokens - 1.0, 0.0
    return False, tokens, (1.0 - tokens) / qps


@dataclasses.dataclass
class CatalogEntry:
    """One model's full serving stack inside the catalog: the same
    registry/router/factory triple `build_serving` boots for a
    single-model server, plus the model's OWN DynamicBatcher (its
    per-model queue) and optional prediction-cache front."""

    name: str
    registry: "object"
    router: "object"
    factory: "object"
    batcher: DynamicBatcher
    front: "object" = None          # CacheFront when caching is on
    cache: "object" = None          # PredictionCache or None
    warmup_s: Optional[float] = None
    warmup_compile_events: Optional[int] = None

    def resident(self) -> bool:
        """Live and dispatchable right now — residency is read here by
        the scheduler, but only ITS warm decisions change it."""
        return self.router.live_version() is not None

    def submit_target(self):
        return self.front if self.front is not None else self.batcher


class ModelCatalog:
    """The multi-model generalization of ModelRegistry's single tree:
    an ordered set of CatalogEntry, one per model name, each with its
    own version lifecycle, bucket geometry and cost tables. Built once
    at boot (build_catalog) and read-only afterwards — per-model
    lifecycle churn (promote/rollback/cascade) happens inside each
    entry's registry, exactly as in a single-model server."""

    def __init__(self):
        self._models: dict = {}

    def add(self, entry: CatalogEntry) -> None:
        if entry.name in self._models:
            raise ValueError(f"model {entry.name!r} already in catalog")
        self._models[entry.name] = entry

    def get(self, name: str) -> CatalogEntry:
        try:
            return self._models[name]
        except KeyError:
            raise KeyError(
                f"unknown model {name!r}; catalog has {self.names()}")

    def names(self) -> list:
        return list(self._models)

    def default(self) -> str:
        return next(iter(self._models))

    def entries(self) -> list:
        return list(self._models.values())

    def ensure_live(self, name: str, seed: int = 0,
                    infer_dtype: str = "float32") -> CatalogEntry:
        """Boot one model to live: bootstrap (load-or-init + warm +
        promote, serialized by the registry's admin lock — concurrent
        callers are safe) and best-effort dtype-variant activation.
        Idempotent: a live entry returns immediately. This is the ONE
        residency transition; the GlobalScheduler calls it from its
        priced warm thread, eager boots call it directly."""
        entry = self.get(name)
        if entry.resident():
            return entry
        t0 = time.monotonic()
        mv = entry.registry.bootstrap(seed=seed)
        entry.warmup_s = time.monotonic() - t0
        entry.warmup_compile_events = mv.warmup_compile_events
        log.info("catalog: %s live as %s (%s) in %.2fs — %d compile "
                 "events", name, mv.version, mv.source, entry.warmup_s,
                 mv.warmup_compile_events)
        if infer_dtype != "float32":
            try:
                entry.registry.activate_infer_dtype(mv.version,
                                                    infer_dtype)
            except Exception:
                log.exception("catalog: %s infer dtype %s refused; "
                              "float32 stays live", name, infer_dtype)
        return entry

    def stop(self, drain: bool = True) -> None:
        for entry in self._models.values():
            entry.batcher.stop(drain=drain)

    def describe(self) -> dict:
        out = {}
        for name, e in self._models.items():
            out[name] = {
                "resident": e.resident(),
                "live_version": e.router.live_version(),
                "live_infer_dtype": e.router.live_infer_dtype(),
                "buckets": list(e.factory.buckets),
                "max_batch": e.factory.max_batch,
                "warmup_s": (round(e.warmup_s, 3)
                             if e.warmup_s is not None else None),
                "warmup_compile_events": e.warmup_compile_events,
                "pending_rows": e.batcher.pending_rows(),
            }
        return out


def _model_ckpt_dir(base: Optional[str], name: str) -> Optional[str]:
    """Each model loads from its OWN checkpoint subtree
    (`<base>/<model>`): pointing two heterogeneous models at one tree
    would restore one model's params into the other's apply fn."""
    return os.path.join(base, name) if base else None


def build_catalog(cfg, metrics=None) -> ModelCatalog:
    """Boot the multi-model catalog: one `build_serving(cfg)` per name
    in cfg.serve_models (falling back to the single cfg.model — the
    compatibility path), each on its own checkpoint subtree, with its
    own started DynamicBatcher and (under --serve-cache) its own
    prediction-cache front. Nothing is warmed here — residency is the
    GlobalScheduler's (or an eager boot's) decision."""
    from distributedmnist_tpu.serve.registry import build_serving

    names = [s.strip() for s in (cfg.serve_models or "").split(",")
             if s.strip()]
    if not names:
        names = [cfg.model]
    catalog = ModelCatalog()
    for name in dict.fromkeys(names):
        mcfg = dataclasses.replace(
            cfg, model=name,
            checkpoint_dir=_model_ckpt_dir(cfg.checkpoint_dir, name))
        registry, router, factory = build_serving(mcfg, metrics=metrics)
        # The fast lane stays OFF under tenancy: a bypassing submit
        # would dispatch before the GlobalScheduler's WFQ/EDF grant —
        # and the one scheduler owning EVERY dispatch decision is the
        # point of this layer.
        batcher = DynamicBatcher(
            router, max_batch=mcfg.serve_max_batch,
            max_wait_us=mcfg.serve_max_wait_us,
            queue_depth=mcfg.serve_queue_depth,
            max_inflight=mcfg.serve_max_inflight,
            slo_ms=mcfg.serve_slo_ms, adaptive=mcfg.serve_adaptive,
            dedup=mcfg.serve_dedup, metrics=metrics).start()
        front = cache = None
        if cfg.serve_cache:
            from distributedmnist_tpu.serve.cache import build_cache_front
            front, cache = build_cache_front(mcfg, batcher, router,
                                             registry, metrics=metrics)
        catalog.add(CatalogEntry(name=name, registry=registry,
                                 router=router, factory=factory,
                                 batcher=batcher, front=front,
                                 cache=cache))
    return catalog


@dataclasses.dataclass
class _Pending:
    """One admitted request parked in a per-(tenant, model) queue,
    waiting for the scheduler's grant."""

    x: "object"
    n: int
    tenant: str
    model: str
    t_enqueue: float
    deadline: Optional[float]          # absolute monotonic, or None
    route: Optional[str]
    future: Future = dataclasses.field(default_factory=Future)


class GlobalScheduler:
    """The one dispatch authority over a ModelCatalog (see module
    docstring). submit() admits (quota -> watermark, cache-probing
    before either sheds) into per-(tenant, model) queues; the grant
    thread picks tenant by weighted DRR and model by EDF priced off
    the live cost tables, sheds infeasible heads with 504, schedules
    cold-model warmups on a warm thread, and forwards granted runs
    into the model's own batcher with {tenant, model} span tags."""

    def __init__(self, catalog: ModelCatalog, tenants: dict,
                 metrics=None, quantum_s: float = 0.005,
                 tenant_queue_rows: int = 4096, seed: int = 0,
                 infer_dtype: str = "float32",
                 warmup_est_s: float = 5.0):
        if quantum_s <= 0:
            raise ValueError(f"quantum_s must be > 0, got {quantum_s}")
        self.catalog = catalog
        self.metrics = metrics
        self.quantum_s = quantum_s
        self.tenant_queue_rows = tenant_queue_rows
        self.seed = seed
        self.infer_dtype = infer_dtype
        self.warmup_est_s = warmup_est_s
        # Dispatch pacing (Clockwork): a model is grantable only while
        # its batcher stages fewer than this many max_batch multiples —
        # past that, its backlog waits in the per-tenant queues where
        # the WFQ/EDF arbitration still owns the order. 2 = one batch
        # forming plus one queued behind the in-flight window.
        self.staging_rows_factor = 2
        self._classes = dict(tenants)
        self._classes.setdefault("default", SLOClass(name="default"))
        for cls in self._classes.values():
            if cls.model is not None:
                catalog.get(cls.model)   # fail the boot on a bad route
        # The scheduler's ONE named condition: every piece of tenancy
        # accounting below is mutated only while it is held (DML017).
        self._cond = make_condition("tenancy.sched")
        self._ring = sorted(self._classes)     # fixed DRR visit order
        self._cursor = 0
        self._queues: dict = {}        # (tenant, model) -> deque
        self._tokens: dict = {}        # tenant -> [tokens, t_last]
        self._deficits: dict = {}      # tenant -> DRR credit (seconds)
        self._skips: dict = {}         # tenant -> consecutive passes
        self._granted: dict = {}       # tenant -> rows ever granted
        self._pending_rows: dict = {}  # tenant -> rows queued now
        self._warming: set = set()     # models with a warm in flight
        self._max_head_cost_s = 0.0    # running max, the bound's basis
        self.max_skip_observed = 0
        self._stop = False
        self._drain = True
        self._thread = None
        for name, cls in self._classes.items():
            self._tokens[name] = [cls.burst, time.monotonic()]
            self._deficits[name] = 0.0
            self._skips[name] = 0
            self._granted[name] = 0
            self._pending_rows[name] = 0

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "GlobalScheduler":
        if self._thread is not None:
            raise RuntimeError("scheduler already started")
        self._thread = make_thread(target=self._loop,
                                   name="serve-tenancy-sched",
                                   daemon=True)
        self._thread.start()
        return self

    def stop(self, drain: bool = True) -> None:
        """Close admission; with drain=True the grant loop keeps
        dispatching until every queue is empty (cold-model heads are
        shed — a stop must not wait on a warmup), then the catalog's
        batchers drain and stop."""
        with self._cond:
            self._stop = True
            self._drain = drain
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=30.0)
        self.catalog.stop(drain=drain)

    # -- admission (the front door) ----------------------------------------

    def submit(self, x, tenant: Optional[str] = None,
               deadline_s: Optional[float] = None,
               route: Optional[str] = None,
               model: Optional[str] = None) -> Future:
        """Admit one request. Resolution order: tenant -> SLO class
        (unknown/absent tenants collapse into "default" — bounded
        metric cardinality, no accidental anonymous classes), model ->
        explicit arg, else the class route, else the catalog default.
        Quota breach raises QuotaExceeded (429 + retry_after_s) and a
        full tenant queue raises Rejected (503) — but either shed
        first probes the prediction cache, and a hit is served at zero
        device cost instead. An absent deadline inherits the class
        default; an already-expired one is shed 504 at the door."""
        cls = self._classes.get(tenant if tenant is not None
                                else "default")
        if cls is None:
            cls = self._classes["default"]
        name = cls.name
        model = model or cls.model or self.catalog.default()
        entry = self.catalog.get(model)
        x = np.asarray(x)
        n = int(x.shape[0]) if x.ndim >= 2 else 1
        now = time.monotonic()
        if deadline_s is None and cls.deadline_ms is not None:
            deadline_s = now + cls.deadline_ms / 1e3
        if deadline_s is not None and now >= deadline_s:
            if self.metrics is not None:
                self.metrics.record_deadline_shed(n)
                self.metrics.record_tenant_shed(name, "deadline", n)
            raise DeadlineExceeded(
                "deadline already expired at admission "
                f"({(now - deadline_s) * 1e3:.1f} ms ago)")
        req = _Pending(x=x, n=n, tenant=name, model=model,
                       t_enqueue=now, deadline=deadline_s, route=route)
        shed_exc = None
        with self._cond:
            if self._stop:
                raise RuntimeError("tenancy scheduler is stopped")
            tokens, t_last = self._tokens[name]
            ok, tokens, retry_after = token_admit(
                tokens, t_last, now, cls.qps, cls.burst)
            self._tokens[name] = [tokens, now]
            if not ok:
                shed_exc = QuotaExceeded(
                    f"tenant {name!r} over quota ({cls.qps:g} qps, "
                    f"burst {cls.burst:g}); retry in {retry_after:.3f}s",
                    retry_after_s=retry_after)
            elif self._pending_rows[name] + n > self.tenant_queue_rows:
                shed_exc = Rejected(
                    f"tenant {name!r} queue at "
                    f"{self._pending_rows[name]} pending rows; "
                    f"watermark {self.tenant_queue_rows} would be "
                    f"exceeded by {n} more")
            else:
                self._queues.setdefault((name, model),
                                        deque()).append(req)
                self._pending_rows[name] += n
                self._cond.notify_all()
        if shed_exc is None:
            if self.metrics is not None:
                self.metrics.record_tenant_request(name, model, n)
            return req.future
        # The cache-aware shed (ISSUE 18 satellite): a cached answer
        # costs zero device work — serve it even over quota. Probed
        # OUTSIDE the scheduler condition; cache.probe counts no miss.
        hit = self._cache_probe(entry, x)
        if hit is not None:
            if self.metrics is not None:
                self.metrics.record_tenant_cache_hit(name, n)
                self.metrics.record_tenant_request(name, model, n)
            req.future.set_result(hit)
            return req.future
        if self.metrics is not None:
            kind = ("quota" if isinstance(shed_exc, QuotaExceeded)
                    else "watermark")
            self.metrics.record_tenant_shed(name, kind, n)
            if isinstance(shed_exc, Rejected):
                self.metrics.record_reject(n)
        raise shed_exc

    def _cache_probe(self, entry: CatalogEntry, x) -> Optional[np.ndarray]:
        if entry.cache is None:
            return None
        from distributedmnist_tpu.serve.cache import content_key
        version = entry.router.live_version()
        if version is None:
            return None
        dtype = entry.router.live_infer_dtype()
        try:
            imgs = entry.router._as_images(x)
            return entry.cache.probe(content_key(version, dtype, imgs))
        except Exception:
            return None      # a probe must never turn a shed into a 500

    # -- the grant loop ----------------------------------------------------

    def _loop(self) -> None:
        while True:
            sheds: list = []
            grant = None
            warm = None
            with self._cond:
                while (not self._stop
                       and not any(self._queues.values())):
                    self._cond.wait(0.1)
                if self._stop and (not self._drain
                                   or not any(self._queues.values())):
                    break
                grant, sheds, warm = self._grant_locked(time.monotonic())
                if grant is None and not sheds and warm is None:
                    # backlog exists but nothing is dispatchable yet
                    # (e.g. every head's model is still warming):
                    # park until a warm completes or new work arrives
                    self._cond.wait(0.01)
            for req, why in sheds:
                self._shed(req, why)
            if warm is not None:
                self._spawn_warm(warm)
            if grant is not None:
                self._forward(*grant)

    def _grant_locked(self, now: float):
        """One scheduling decision under self._cond. Returns
        (grant, sheds, warm): `grant` is (tenant, model, [requests])
        to forward outside the lock, `sheds` the infeasible requests
        to 504 (futures resolve OUTSIDE the lock — DML009), `warm` a
        cold model name that needs a scheduled warmup."""
        sheds: list = []
        warm = None
        # Per-tenant EDF pick across that tenant's model queues; heads
        # priced off each model's measured cost table. Cold models
        # don't compete in EDF — their backlog schedules a warmup, and
        # their heads are feasibility-checked against the PRICED
        # warmup (est or measured) so doomed waits shed now.
        head_costs: dict = {}
        picks: dict = {}
        for (tenant, model), q in self._queues.items():
            if not q:
                continue
            entry = self.catalog.get(model)
            if not entry.resident():
                if model not in self._warming:
                    self._warming.add(model)
                    warm = model
                wait_s = (entry.warmup_s if entry.warmup_s is not None
                          else self.warmup_est_s)
                while q:
                    head = q[0]
                    cost = wait_s + self._price(entry, head.n)
                    if (head.deadline is not None
                            and now + cost > head.deadline):
                        q.popleft()
                        self._pending_rows[tenant] -= head.n
                        sheds.append((head, cost))
                    else:
                        break
                continue
            if (entry.batcher.pending_rows()
                    >= self.staging_rows_factor * entry.factory.max_batch):
                # Clockwork pacing: the model's staging already holds
                # enough rows to keep its device busy — granting more
                # now would only move queue depth downstream, past the
                # scheduler's arbitration. The backlog stays HERE,
                # where WFQ/EDF still decide its order; _complete()
                # notifies the grant loop the moment capacity frees.
                continue
            head = q[0]
            cost = self._price(entry, head.n)
            prev = picks.get(tenant)
            pick, infeasible = policy.edf_pick(
                ([prev] if prev else []) + [(model, head.deadline,
                                             cost)], now)
            for bad_model in infeasible:
                bq = self._queues[(tenant, bad_model)]
                bad = bq.popleft()
                self._pending_rows[tenant] -= bad.n
                sheds.append((bad, self._price(
                    self.catalog.get(bad_model), bad.n)))
            if pick is not None:
                if prev is None or pick != prev[0]:
                    bq = self._queues[(tenant, pick)]
                    h = bq[0]
                    picks[tenant] = (pick, h.deadline,
                                     self._price(self.catalog.get(pick),
                                                 h.n))
                head_costs[tenant] = picks[tenant][2]
        if not head_costs:
            return None, sheds, warm
        weights = {t: c.weight for t, c in self._classes.items()}
        tenant, self._cursor, _ = policy.drr_grant(
            self._ring, self._cursor, self._deficits, weights,
            self.quantum_s, head_costs)
        model = picks[tenant][0]
        entry = self.catalog.get(model)
        q = self._queues[(tenant, model)]
        run: list = []
        rows = 0
        while q:
            head = q[0]
            if rows + head.n > entry.factory.max_batch:
                break
            cost = self._price(entry, head.n)
            if run and self._deficits[tenant] < cost:
                break
            q.popleft()
            policy.drr_charge(self._deficits, tenant, cost)
            self._pending_rows[tenant] -= head.n
            rows += head.n
            run.append(head)
        self._granted[tenant] += rows
        # Starvation-freedom, asserted: every OTHER tenant whose head
        # was feasible this round was passed over once; none may ever
        # be passed over more than the closed-form DRR bound. The
        # bound prices the RUNNING max head cost, not just this
        # round's — skips legitimately accrued under an expensive head
        # must not trip a bound shrunk by a later cheap one.
        self._max_head_cost_s = max(self._max_head_cost_s,
                                    max(head_costs.values()))
        bound = policy.drr_skip_bound(
            len(self._ring), self._max_head_cost_s, self.quantum_s,
            min(w for w in weights.values()))
        self._skips[tenant] = 0
        for other in head_costs:
            if other == tenant:
                continue
            self._skips[other] += 1
            self.max_skip_observed = max(self.max_skip_observed,
                                         self._skips[other])
            assert self._skips[other] <= bound, (
                f"WFQ starvation: tenant {other!r} passed over "
                f"{self._skips[other]} consecutive grants "
                f"(bound {bound}) — deficit accounting is broken")
        return (tenant, model, run), sheds, warm

    def _price(self, entry: CatalogEntry, rows: int) -> float:
        return policy.estimate_dispatch_s(rows, list(entry.factory.buckets),
                                          entry.router.bucket_costs())

    def _shed(self, req: _Pending, cost_s: float) -> None:
        """Fail one infeasible request NOW (504) — off the lock."""
        if self.metrics is not None:
            self.metrics.record_deadline_shed(req.n)
            self.metrics.record_tenant_shed(req.tenant, "deadline",
                                            req.n)
        req.future.set_exception(DeadlineExceeded(
            f"infeasible: modeled {req.model} dispatch of {req.n} rows "
            f"needs {cost_s * 1e3:.1f} ms but the deadline is "
            f"{(req.deadline - req.t_enqueue) * 1e3:.1f} ms out; shed "
            "before it could poison a batch"))

    def _spawn_warm(self, model: str) -> None:
        """The scheduler-owned residency transition: a cold model's
        backlog schedules its warmup HERE, on a dedicated warm thread
        — the grant loop keeps dispatching resident models meanwhile,
        and the cold queue's feasibility is priced with the warmup
        until it completes."""
        def _warm():
            try:
                self.catalog.ensure_live(model, seed=self.seed,
                                         infer_dtype=self.infer_dtype)
            except Exception:
                log.exception("scheduled warmup of %s failed", model)
            finally:
                with self._cond:
                    self._warming.discard(model)
                    self._cond.notify_all()
        make_thread(target=_warm, name=f"serve-tenancy-warm-{model}",
                    daemon=True).start()

    def _forward(self, tenant: str, model: str, run: list) -> None:
        """Hand one granted run to the model's own batcher (or cache
        front), off the scheduler lock, chaining each inner future to
        the caller's and stamping per-tenant completion metrics."""
        entry = self.catalog.get(model)
        target = entry.submit_target()
        if self.metrics is not None:
            self.metrics.record_tenant_dispatch(
                tenant, model, sum(r.n for r in run))
        for req in run:
            try:
                inner = target.submit(
                    req.x, deadline_s=req.deadline, route=req.route,
                    tags={"tenant": tenant, "model": model})
            except BaseException as e:
                req.future.set_exception(e)
                continue
            inner.add_done_callback(
                lambda f, r=req: self._complete(r, f))

    def _complete(self, req: _Pending, inner: Future) -> None:
        # Capacity freed downstream: wake the grant loop so a model
        # parked at its staging cap is re-considered immediately
        # instead of on the next poll tick.
        with self._cond:
            self._cond.notify_all()
        done = time.monotonic()
        cls = self._classes.get(req.tenant)
        slo_ok = None
        if req.deadline is not None:
            slo_ok = done <= req.deadline
        elif cls is not None and cls.deadline_ms is not None:
            slo_ok = (done - req.t_enqueue) <= cls.deadline_ms / 1e3
        if self.metrics is not None:
            self.metrics.record_tenant_done(
                req.tenant, done - req.t_enqueue, slo_ok)
        err = inner.exception()
        if err is not None:
            req.future.set_exception(err)
        else:
            req.future.set_result(inner.result())

    # -- admin surface -----------------------------------------------------

    def classes(self) -> dict:
        """The live SLO-class table (name -> SLOClass), copied."""
        with self._cond:
            return dict(self._classes)

    def set_quota(self, tenant: str, qps: Optional[float] = None,
                  burst: Optional[float] = None) -> SLOClass:
        """Live-update one tenant's quota (POST /tenants/{id}/quota).
        The bucket refills to the new burst so a LOOSENED quota takes
        effect immediately instead of waiting out old debt. Raises
        KeyError for an unknown tenant (404 semantics)."""
        with self._cond:
            cls = self._classes.get(tenant)
            if cls is None:
                raise KeyError(f"unknown tenant {tenant!r}")
            cls = dataclasses.replace(
                cls, qps=qps if qps is not None else cls.qps,
                burst=burst if burst is not None else cls.burst)
            self._classes[tenant] = cls
            self._tokens[tenant] = [cls.burst, time.monotonic()]
            return cls

    def queued_rows(self) -> int:
        with self._cond:
            return sum(self._pending_rows.values())

    def snapshot(self) -> dict:
        """The GET /tenants surface: per-tenant admission config and
        live scheduler accounting, plus the catalog's residency map."""
        with self._cond:
            tenants = {}
            total_granted = sum(self._granted.values()) or 1
            for name in self._ring:
                cls = self._classes[name]
                tenants[name] = {
                    "qps": cls.qps,
                    "burst": cls.burst,
                    "deadline_ms": cls.deadline_ms,
                    "weight": cls.weight,
                    "model": cls.model,
                    "tokens": round(self._tokens[name][0], 3),
                    "deficit_s": round(self._deficits[name], 6),
                    "queued_rows": self._pending_rows[name],
                    "granted_rows": self._granted[name],
                    "granted_share": round(
                        self._granted[name] / total_granted, 4),
                    "consecutive_skips": self._skips[name],
                }
            return {
                "quantum_s": self.quantum_s,
                "tenant_queue_rows": self.tenant_queue_rows,
                "max_skip_observed": self.max_skip_observed,
                "warming": sorted(self._warming),
                "tenants": tenants,
                "models": self.catalog.describe(),
            }


def build_tenancy(cfg, metrics=None) -> tuple:
    """serve.py's one-call boot for the tenancy layer: parse the class
    table, build the catalog, start the scheduler. Returns
    (catalog, scheduler). Callers own eager residency (ensure_live per
    model) — or leave it to the scheduler's priced warm path."""
    classes = parse_tenants(cfg.serve_tenants)
    catalog = build_catalog(cfg, metrics=metrics)
    sched = GlobalScheduler(
        catalog, classes, metrics=metrics,
        quantum_s=cfg.serve_tenant_quantum_us / 1e6,
        tenant_queue_rows=cfg.serve_queue_depth, seed=cfg.seed,
        infer_dtype=cfg.serve_infer_dtype).start()
    return catalog, sched
