"""Batched inference engine: a jitted forward-only step with a bucketed
shape cache.

The training hot path's winning disciplines transfer directly to serving
(ISSUE 1): device-resident params, shape-stable compiled programs, and
batch-shaped dispatch. Requests arrive at arbitrary sizes; compiling a
forward program per size would recompile constantly, so sizes are rounded
up to a fixed ladder of power-of-two **buckets** (each a multiple of the
mesh's data-parallel width so the batch axis shards evenly). An n-row
request pads to the smallest covering bucket, runs the ONE compiled
program for that bucket, and slices the first n rows of the result —
steady state therefore runs with zero recompiles, asserted in tests via
utils.CompileCounter (jax.monitoring events), the same compile-stability
contract the trainer's scanned superstep relies on.

One engine serves one (model, dtype): the jitted forward is a single
function whose per-bucket specializations are jit's own shape cache, and
utils/compile_cache.py's persistent XLA cache makes bucket warmup after a
process restart a disk hit instead of a recompile.
"""

from __future__ import annotations

import dataclasses
import logging
import statistics
import time
from typing import Any, Optional, Sequence

import numpy as np

from distributedmnist_tpu.analysis.locks import make_lock
from distributedmnist_tpu.analysis.sanitize import (blocking,
                                                    resource_acquire,
                                                    resource_release)
from distributedmnist_tpu.serve import trace
from distributedmnist_tpu.serve.faults import failpoint
from distributedmnist_tpu.utils import (CompileCounter,
                                        enable_compilation_cache, round_up)

log = logging.getLogger("distributedmnist_tpu")

IMAGE_SHAPE = (28, 28, 1)
IMAGE_SIZE = 28 * 28

# The fast lane's resident-staging ceiling (ISSUE 14): only rungs at or
# below this keep a donated device buffer warm — large rungs are batch
# territory, where the pooled staging path's costs amortize anyway.
FASTLANE_MAX_BUCKET = 32


def fast_row_bucket(buckets) -> Optional[int]:
    """The one bucket rung the row-staged fast path can serve (ISSUE
    14): a single-row request always covers into the SMALLEST rung, so
    that is the only rung whose row-staging program is ever reachable —
    and when that rung is 1, the exact-fit route already skips staging
    entirely, so no row program exists at all. Shared with the static
    compile-surface auditor (analysis/jaxcheck.py), whose reachable-key
    universe must agree with what warmup compiles."""
    ladder = sorted(set(buckets))
    b = ladder[0]
    return b if 1 < b <= FASTLANE_MAX_BUCKET else None


@dataclasses.dataclass
class InferenceHandle:
    """A dispatched-but-unfetched forward: the device-side logits plus
    what fetch() needs to slice the real rows back out and recycle the
    host staging buffer. Produced by InferenceEngine.dispatch(), consumed
    exactly once by InferenceEngine.fetch()."""

    logits: Any                   # device array, (bucket, 10)
    n: int                        # real rows (the rest is padding)
    bucket: int
    staging: Optional[np.ndarray]  # recycled on fetch; None after
    version: Optional[str] = None  # the model version that computed it
    #   (serve/registry.py labels; metrics split populations on it)
    infer_dtype: Optional[str] = None  # the computing engine's serving
    #   precision (ISSUE 7; metrics by_dtype attribution)
    # Fast-lane handle (ISSUE 14): no pooled staging buffer to recycle
    # (exact-fit or row-staged resident dispatch); one-shot enforcement
    # then rides the logits reference instead of the staging one.
    resident: bool = False


def make_buckets(max_batch: int, n_chips: int,
                 min_bucket: int = 1) -> tuple[int, ...]:
    """The bucket ladder: powers of two scaled to multiples of n_chips,
    doubling from round_up(min_bucket, n_chips) until max_batch is
    covered. The top bucket is the first rung >= max_batch, so every
    admissible request size has a covering bucket."""
    if max_batch < 1:
        raise ValueError(f"max_batch must be >= 1, got {max_batch}")
    b = round_up(max(min_bucket, 1), n_chips)
    ladder = [b]
    while ladder[-1] < max_batch:
        ladder.append(ladder[-1] * 2)
    return tuple(ladder)


class InferenceEngine:
    """Forward-only inference over the 'data' mesh axis with pad-and-slice
    batch bucketing.

    infer(x) takes uint8 images, shape (n, 28, 28, 1) or (n, 784), and
    returns float logits (n, 10). Rows pad with zeros up to the covering
    bucket; padded rows are computed and discarded (their cost is the
    occupancy loss the batcher's occupancy histogram makes visible).
    """

    def __init__(self, model, params, mesh, dtype=None,
                 max_batch: int = 512,
                 buckets: Optional[Sequence[int]] = None,
                 version: Optional[str] = None,
                 infer_dtype: str = "float32",
                 fused_mode: Optional[str] = None):
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        from distributedmnist_tpu.ops import fused as fused_lib
        from distributedmnist_tpu.parallel import replicated
        from distributedmnist_tpu.parallel.mesh import DATA_AXIS

        enable_compilation_cache()
        self._compiles = CompileCounter.instance()
        self.version = version
        self.mesh = mesh
        self.n_chips = int(np.prod(mesh.devices.shape))
        self.platform = mesh.devices.flat[0].platform
        self.dtype = dtype if dtype is not None else jnp.float32
        # The serving precision (ISSUE 7): "float32" runs the
        # training-identical forward (the parity oracle — same model
        # apply, same numerics as eval); "bfloat16"/"int8" run the
        # inference fast path (serve/quantize.py — folded input
        # normalization, inference conv route, fused dense epilogues,
        # int8 weights per-output-channel quantized at THIS build).
        # fused_mode resolves the Pallas-vs-XLA hot-op route against
        # the mesh's platform, ops.fused.resolve-style.
        self.infer_dtype = infer_dtype
        self.fused_mode = fused_lib.resolve(fused_mode or "auto",
                                            self.platform)
        self.max_batch = max_batch
        self.buckets = (tuple(sorted(set(buckets))) if buckets
                        else make_buckets(max_batch, self.n_chips))
        if any(b % self.n_chips for b in self.buckets):
            raise ValueError(
                f"buckets {self.buckets} must be multiples of the "
                f"data-parallel width {self.n_chips}")
        self._x_sharding = NamedSharding(mesh, P(DATA_AXIS, None, None,
                                                 None))
        out_spec = NamedSharding(mesh, P(DATA_AXIS, None))

        if infer_dtype == "float32":
            def forward(params, x_u8):
                # cast + /255 in-step: fuses into the first conv/matmul,
                # and the host->device copy stays uint8 (4x smaller than
                # f32).
                x = x_u8.astype(self.dtype) / 255.0
                logits = model.apply({"params": params}, x)
                return jax.lax.with_sharding_constraint(logits, out_spec)
        else:
            from distributedmnist_tpu.serve.quantize import \
                prepare_inference

            params, fast_forward = prepare_inference(
                model, params, infer_dtype, self.fused_mode)

            def forward(params, x_u8):
                logits = fast_forward(params, x_u8)
                return jax.lax.with_sharding_constraint(logits, out_spec)
        self.params = jax.device_put(params, replicated(mesh))

        # Donated input: the uint8 batch buffer is dead after the gather/
        # cast, so XLA may reuse it (a no-op with a warning on backends
        # without donation, e.g. CPU — harmless).
        self._forward = jax.jit(forward, donate_argnums=1)

        # The row-staged fast path (ISSUE 14): a single-row request
        # covering the smallest rung stages ON DEVICE — the resident
        # (bucket, 28, 28, 1) zero buffer is donated into a program
        # that writes row 0 and runs the same forward body, returning
        # the updated buffer to stay resident for the next dispatch.
        # Rows past 0 are never written, so the zero padding survives
        # every reuse; the host->device copy shrinks from bucket rows
        # to ONE row and the host-side pad vanishes. One jitted
        # function whose per-bucket specialization is jit's own shape
        # cache, exactly like _forward — warmed (and audited by
        # analysis/jaxcheck.py) as its own compile key.
        def stage_row(params, buf, row):
            staged = jax.lax.dynamic_update_slice(buf, row,
                                                  (0, 0, 0, 0))
            return forward(params, staged), staged

        self._fast_row = jax.jit(stage_row, donate_argnums=1)
        # Resident state for that path: the live device buffer plus the
        # single-flight lock the lane's contention-fallback contract
        # hangs off (a busy buffer means "fall back to the pooled
        # path", never "wait"). Populated by warmup's fast-lane pass;
        # None when the geometry has no row-staged rung (smallest rung
        # 1, or past FASTLANE_MAX_BUCKET).
        self._fast_row_b = fast_row_bucket(self.buckets)
        # lint: allow[DML010] construction-time init before any thread can hold the lane lock
        self._fast_row_buf = None
        # Priced at warmup (the Clockwork discipline applied to the
        # lane itself): the row-staged program only serves when its
        # measured cost is no worse than the covering bucket's pooled
        # dispatch — on a sharded multi-chip mesh the on-device row
        # update can cost collectives the host-side pad never pays,
        # and a "fast" path that measures slower must disable itself,
        # not be believed. False until warmup proves it.
        self._fast_row_ok = False
        self._fast_row_cost = None
        self._fast_row_lock = make_lock("engine.fastlane")
        # Host staging buffers, one free-list per bucket: dispatch() pads
        # requests into a pooled (bucket, 28, 28, 1) uint8 array instead
        # of allocating np.zeros + np.concatenate per call; fetch()
        # returns the buffer to the pool. Pool size is therefore bounded
        # by the caller's dispatched-but-unfetched window (the batcher's
        # max_inflight), never by traffic volume. A buffer is only
        # recycled AFTER its batch's device->host value fetch, so reuse
        # can never race the device still reading it, even if device_put
        # were zero-copy on some backend.
        self._staging_pool: dict[int, list[np.ndarray]] = {
            b: [] for b in self.buckets}
        self._staging_lock = make_lock("engine.staging")
        # Per-bucket measured dispatch cost (median end-to-end infer
        # seconds, timed by warmup AFTER each bucket compiles). This is
        # the Clockwork insight the batch former runs on: per-program
        # costs are predictable, so keep them instead of throwing the
        # warmup timings away. Empty until warmup() runs.
        self._bucket_cost: dict[int, float] = {}
        # The tail sibling of the median table: per-bucket p95 dispatch
        # cost from the same warmup samples. The fleet's hedged
        # dispatch (serve/fleet.py) triggers on "this batch is already
        # slower than the p95 estimate" — a threshold the MEDIAN would
        # set too aggressively (half of all healthy batches exceed it).
        self._bucket_cost_p95: dict[int, float] = {}

    # -- bucketing ---------------------------------------------------------

    def bucket_for(self, n: int) -> int:
        """Smallest bucket covering n rows."""
        if n < 1:
            raise ValueError(f"need at least one row, got {n}")
        for b in self.buckets:
            if b >= n:
                return b
        raise ValueError(
            f"batch of {n} rows exceeds the top bucket "
            f"{self.buckets[-1]} (raise max_batch)")

    @staticmethod
    def _as_images(x) -> np.ndarray:
        x = np.asarray(x)
        if x.dtype != np.uint8:
            raise TypeError(f"expected uint8 pixels, got {x.dtype}")
        if x.ndim == 2 and x.shape[1] == IMAGE_SIZE:
            x = x.reshape(-1, *IMAGE_SHAPE)
        if x.ndim != 4 or x.shape[1:] != IMAGE_SHAPE:
            raise ValueError(
                f"expected (n, 28, 28, 1) or (n, 784) images, "
                f"got shape {x.shape}")
        return x

    # -- staging pool ------------------------------------------------------

    def _staging_take(self, bucket: int) -> np.ndarray:
        # Balance-checked (ISSUE 8): every checkout here is matched by
        # a recycle — fetch()'s finally on the normal path, dispatch()'s
        # own error path otherwise — and the sanitizer asserts the net
        # is zero at drain (the PR 5 leak class).
        resource_acquire("engine.staging")
        with self._staging_lock:
            pool = self._staging_pool[bucket]
            if pool:
                return pool.pop()
        return np.empty((bucket, *IMAGE_SHAPE), np.uint8)

    def staging_buffers(self) -> dict[int, int]:
        """Per-bucket free-list sizes (tests assert the pool stays
        bounded by the in-flight window, not traffic)."""
        with self._staging_lock:
            return {b: len(p) for b, p in self._staging_pool.items()}

    # -- inference ---------------------------------------------------------

    def dispatch(self, x) -> InferenceHandle:
        """Phase 1 of infer(): pad `x` — one uint8 image array or a list
        of them (coalesced requests; staged directly, no intermediate
        concatenate) — into a pooled staging buffer, device_put, enqueue
        the jitted forward, and return WITHOUT fetching. JAX dispatch is
        async, so the device computes this batch while the caller stages
        the next one — the trainer's bounded in-flight overlap, ported
        to serving."""
        import jax

        parts = ([self._as_images(p) for p in x]
                 if isinstance(x, (list, tuple))
                 else [self._as_images(x)])
        n = sum(p.shape[0] for p in parts)
        b = self.bucket_for(n)
        # Fault-injection seam (serve/faults.py; inert when no injector
        # is installed). Fired BEFORE the staging take so an injected
        # dispatch error never strands a pooled buffer.
        failpoint("engine.dispatch", version=self.version, rows=n,
                  bucket=b)
        # Host staging span (ISSUE 9): pad + device_put + enqueue —
        # request ids inherit from the batcher's enclosing
        # batch.dispatch span (thread-local), so the engine needs no
        # rid plumbing of its own.
        sp = trace.begin_span("engine.staging", rows=n, bucket=b,
                              version=self.version)
        try:
            staging = self._staging_take(b)
            # The checkout is exception-safe: a real backend error in
            # device_put/dispatch (not the pre-take failpoint) must
            # recycle the buffer HERE — otherwise the batcher's
            # keep-serving failure path would bleed one pooled buffer
            # per failed dispatch, the dispatch-side twin of the PR 5
            # fetch leak (the sanitizer's engine.staging balance pins
            # this).
            dispatched = False
            try:
                off = 0
                for p in parts:
                    staging[off:off + p.shape[0]] = p
                    off += p.shape[0]
                if n < b:
                    staging[n:] = 0
                x_dev = jax.device_put(staging, self._x_sharding)
                logits = self._forward(self.params, x_dev)
                dispatched = True
            finally:
                if not dispatched:
                    with self._staging_lock:
                        self._staging_pool[b].append(staging)
                    resource_release("engine.staging")
        finally:
            trace.end_span(sp)
        return InferenceHandle(logits=logits, n=n, bucket=b,
                               staging=staging, version=self.version,
                               infer_dtype=self.infer_dtype)

    def dispatch_fast(self, x) -> Optional[InferenceHandle]:
        """The fast lane's dispatch (ISSUE 14): stage WITHOUT the
        pooled pad+device_put round-trip when a resident route fits,
        or return None so the caller falls back to the ordinary
        dispatch() path (the lane-contention fallback — never an
        error, never a wait). Two resident routes:

        - **exact fit** (n == covering bucket): the request array IS
          the bucket shape, so it stages directly — no pool checkout,
          no pad, no zero-fill;
        - **row-staged** (n == 1 into a smallest rung > 1): the warm
          donated device buffer takes the one row on device
          (dynamic_update_slice fused into the forward's program), so
          the host->device copy is one row instead of a padded bucket.
          Single-flight per buffer: a concurrent holder means fall
          back, because two donations of one buffer would race.

        Thread-safe and callable from any submit thread; the batcher's
        lane decision (queue empty + free window slot, under the queue
        lock) is what bounds concurrency upstream."""
        import jax

        x = self._as_images(x)
        n = x.shape[0]
        b = self.bucket_for(n)
        row_staged = (n == 1 and b == self._fast_row_b
                      and self._fast_row_ok
                      and self._fast_row_buf is not None)
        if n != b and not row_staged:
            return None
        # Same seam as dispatch(): a chaos schedule that poisons
        # engine dispatches must cover the fast lane too.
        failpoint("engine.dispatch", version=self.version, rows=n,
                  bucket=b)
        sp = trace.begin_span("engine.staging", rows=n, bucket=b,
                              version=self.version, resident=True)
        try:
            if n == b:
                # lint: allow[DML012] the engine IS the staging path: exact-fit fast-lane device_put
                x_dev = jax.device_put(np.ascontiguousarray(x),
                                       self._x_sharding)
                logits = self._forward(self.params, x_dev)
            else:
                if not self._fast_row_lock.acquire(blocking=False):
                    return None      # buffer busy: pooled path decides
                try:
                    # lint: allow[DML012] the engine IS the staging path: one-row fast-lane device_put
                    row = jax.device_put(np.ascontiguousarray(x))
                    # lint: allow[DML010] guarded by the try-acquired engine.fastlane lock above (non-blocking acquire, invisible to the lexical `with` inference)
                    logits, self._fast_row_buf = self._fast_row(
                        self.params, self._fast_row_buf, row)
                finally:
                    self._fast_row_lock.release()
        finally:
            trace.end_span(sp)
        return InferenceHandle(logits=logits, n=n, bucket=b,
                               staging=None, resident=True,
                               version=self.version,
                               infer_dtype=self.infer_dtype)

    def fetch(self, handle: InferenceHandle) -> np.ndarray:
        """Phase 2: the device->host VALUE fetch (blocks until the
        batch's compute is done — the result bytes a client would be
        sent, the StepTimer.barrier argument) plus the slice back to the
        real rows. Recycles the handle's staging buffer; one-shot."""
        if handle.resident:
            # Fast-lane handle (ISSUE 14): no pooled buffer to recycle;
            # one-shot rides the logits reference instead.
            if handle.logits is None:
                raise RuntimeError("handle already fetched")
            try:
                failpoint("engine.fetch", version=handle.version,
                          rows=handle.n)
                blocking("engine.fetch device->host sync")
                return np.asarray(handle.logits)[:handle.n]
            finally:
                handle.logits = None
        if handle.staging is None:
            raise RuntimeError("handle already fetched")
        # The staging buffer is recycled whether the fetch succeeds or
        # fails (injected fault or real device error): by the time the
        # value fetch returns OR raises, this batch's execution is
        # over, so reuse cannot race the device — and a sustained
        # fetch-failure storm (exactly what the circuit breaker exists
        # for) must not bleed one pool buffer per failed batch.
        try:
            # Fault-injection seam: an injected fetch error is
            # attributable to THIS handle's version — the chaos
            # schedule that forces a breaker trip keys on it.
            failpoint("engine.fetch", version=handle.version,
                      rows=handle.n)
            # Sanitizer seam (ISSUE 8): this value fetch blocks until
            # the device finishes the batch — flagged if any hot-path
            # lock is held on this thread (device compute must never
            # run under the registry/fleet/batcher locks).
            blocking("engine.fetch device->host sync")
            return np.asarray(handle.logits)[:handle.n]
        finally:
            with self._staging_lock:
                self._staging_pool[handle.bucket].append(handle.staging)
            handle.staging = None
            resource_release("engine.staging")

    def infer(self, x) -> np.ndarray:
        """Logits (n, 10) for n uint8 images; pad-and-slice through the
        covering bucket. Synchronous composition of dispatch() + fetch(),
        so per-request latency measured around infer() is honest
        end-to-end time."""
        return self.fetch(self.dispatch(x))

    def warmup(self, cost_samples: int = 5) -> int:
        """Compile (or load from the persistent cache) every bucket's
        program, then time each bucket's COMPILED program cost_samples
        times and record the median in the per-bucket cost table
        (bucket_costs()) — the batch former's price list. Returns the
        number of compile events the warmup cost; after this, steady
        state is recompile-free by construction. Re-running refreshes
        the cost table (the registry's verification pass therefore
        leaves the more-settled second measurement in place)."""
        before = self._compiles.snapshot()
        costs = {}
        costs_p95 = {}
        for b in self.buckets:
            x = np.zeros((b, *IMAGE_SHAPE), np.uint8)
            self.infer(x)              # compile (or cache hit) first —
            samples = []               # timings must never include it
            for _ in range(max(1, cost_samples)):
                t0 = time.perf_counter()
                self.infer(x)
                samples.append(time.perf_counter() - t0)
            costs[b] = statistics.median(samples)
            samples.sort()
            costs_p95[b] = samples[min(len(samples) - 1,
                                       int(0.95 * len(samples)))]
        # The fast lane's row-staging program (ISSUE 14) is its own
        # compile key: warm it here so the first fast-lane dispatch
        # after a promote pays a cache hit, not an XLA compile — the
        # same Clockwork bar every bucket rung clears (the registry's
        # verification re-run proves zero residual compiles for this
        # key too, and analysis/jaxcheck.py audits it statically) —
        # and PRICE it against the covering bucket's pooled dispatch,
        # disabling the route where it measures slower.
        self._warm_fastlane(costs)
        # One reference swap, not per-bucket mutation: a dispatch-thread
        # bucket_costs() read mid-warmup sees the old complete table or
        # the new complete table, never a half-written one.
        self._bucket_cost = costs
        self._bucket_cost_p95 = costs_p95
        n = self._compiles.snapshot() - before
        log.info("serve engine warm [%s]: %d buckets %s (%d compile "
                 "events); bucket cost ms %s", self.infer_dtype,
                 len(self.buckets), list(self.buckets), n,
                 {b: round(c * 1e3, 3)
                  for b, c in sorted(self._bucket_cost.items())})
        return n

    def _warm_fastlane(self, costs: dict) -> None:
        """Commit the resident device buffer, compile the row-staged
        fast program (a no-op for geometries whose smallest rung is 1 —
        the exact-fit route shares the ordinary per-bucket programs, so
        there is nothing extra to warm), then PRICE it: the route is
        enabled only when its measured single-row cost is no worse
        than the covering bucket's pooled dispatch (`costs`, this
        warmup's measurements). Runs at every warmup, so a
        re-measurement pass re-proves the key warm and re-prices the
        route for free.

        Deliberately unconditional — warmed even for deployments whose
        batcher never enables the lane: the row program is part of the
        engine's warm surface exactly like a bucket rung, because an
        operator flipping --serve-fastlane on (or a future admin lane
        toggle) must never be the moment a cold key is discovered
        (Clockwork's rule, again). The cost is one persistent-cache-
        absorbed compile plus a ~25 KB uint8 buffer per engine."""
        import jax

        b = self._fast_row_b
        if b is None:
            return
        with self._fast_row_lock:
            if self._fast_row_buf is None:
                # lint: allow[DML012] warmup-time resident-buffer commit, never per-request
                self._fast_row_buf = jax.device_put(
                    np.zeros((b, *IMAGE_SHAPE), np.uint8),
                    self._x_sharding)
            row = np.zeros((1, *IMAGE_SHAPE), np.uint8)
            samples = []
            for i in range(4):
                t0 = time.perf_counter()
                # lint: allow[DML012] warmup-time row placement priming the fast path's compile key
                row_dev = jax.device_put(row)
                logits, self._fast_row_buf = self._fast_row(
                    self.params, self._fast_row_buf, row_dev)
                np.asarray(logits)    # block: compile + timing honest
                if i:                 # first call may pay the compile
                    samples.append(time.perf_counter() - t0)
            self._fast_row_cost = statistics.median(samples)
            self._fast_row_ok = (self._fast_row_cost
                                 <= costs.get(b, float("inf")))
        if not self._fast_row_ok:
            log.info(
                "fast lane: row-staged b%d route DISABLED on this "
                "host (%.3f ms vs pooled %.3f ms) — exact-fit and "
                "queue-bypass still serve", b,
                self._fast_row_cost * 1e3,
                costs.get(b, float("nan")) * 1e3)

    def bucket_costs(self) -> dict[int, float]:
        """Measured seconds-per-dispatch of each bucket's compiled
        program (median over warmup samples; end-to-end infer, so
        per-dispatch host overhead is included). Empty before warmup —
        the batch former treats that as 'no cost model, don't split'."""
        return self._bucket_cost

    def bucket_costs_p95(self) -> dict[int, float]:
        """p95 seconds-per-dispatch per bucket from the warmup samples
        — the hedge-trigger price list (a batch slower than this is
        already in its tail). Empty before warmup, which disables
        hedging the same way it disables the batch former."""
        return self._bucket_cost_p95

    def compile_events(self) -> int:
        """Process-wide compile-request count (utils.CompileCounter);
        take deltas around a steady-state window to assert zero
        recompiles."""
        return self._compiles.snapshot()


def build_model_and_mesh(cfg):
    """The (model, mesh, dtype) triple every serving engine of a process
    is built over — shared by build_engine and the model registry's
    EngineFactory so all versions compile the same program geometry.
    Rejects training-only knobs rather than silently ignoring them."""
    import jax.numpy as jnp

    from distributedmnist_tpu import models
    from distributedmnist_tpu.parallel import get_devices, make_mesh

    if cfg.model_parallel != 1:
        raise ValueError(
            "the serving engine shards over the 'data' axis only; "
            f"model_parallel={cfg.model_parallel} is rejected rather "
            "than silently ignored")
    if cfg.grad_accum != 1:
        raise ValueError(
            f"grad_accum={cfg.grad_accum} is a training knob with no "
            "meaning for forward-only serving — rejected rather than "
            "silently ignored")
    devices = get_devices(cfg.device, cfg.num_devices)
    mesh = make_mesh(devices)
    dtype = jnp.bfloat16 if cfg.dtype == "bfloat16" else jnp.float32
    model = models.build(cfg.model, dtype=dtype, fused=cfg.fused_kernels,
                         platform=devices[0].platform, conv=cfg.conv_impl)
    return model, mesh, dtype


def build_engine(cfg) -> InferenceEngine:
    """InferenceEngine from a Config: the model/dtype/mesh the training
    CLI would build, params restored from cfg.checkpoint_dir when one
    exists there (a served model is usually a trained one), fresh-init
    otherwise (load harnesses measure throughput, not accuracy).

    The single-version path. Serving that must roll new checkpoints in
    without dropping traffic goes through serve/registry.py's
    ModelRegistry + Router instead (serve.py does)."""
    import jax
    import jax.numpy as jnp

    from distributedmnist_tpu import optim
    from distributedmnist_tpu.trainer import init_state

    if cfg.serve_infer_dtype == "auto":
        raise ValueError(
            "serve_infer_dtype='auto' needs the registry's parity gate "
            "to pick a variant (serve/registry.py); the single-engine "
            "path takes a concrete dtype")
    model, mesh, dtype = build_model_and_mesh(cfg)
    tx = optim.build(cfg.optimizer, cfg.learning_rate, cfg.momentum,
                     flat=cfg.flat_optimizer)
    state = init_state(jax.random.PRNGKey(cfg.seed), model, tx,
                       jnp.zeros((1, 28, 28, 1)))
    restored = False
    if cfg.checkpoint_dir:
        from distributedmnist_tpu.checkpoint import Checkpointer

        from distributedmnist_tpu.parallel import replicated
        state = jax.device_put(state, replicated(mesh))
        ckpt = Checkpointer(cfg.checkpoint_dir)
        try:
            state, restored = ckpt.maybe_restore(state)
        finally:
            ckpt.close()
        if restored:
            log.info("serving params restored from step %d",
                     int(state.step))
    return InferenceEngine(model, state.params, mesh, dtype=dtype,
                           max_batch=cfg.serve_max_batch,
                           infer_dtype=cfg.serve_infer_dtype,
                           fused_mode=cfg.fused_kernels)
