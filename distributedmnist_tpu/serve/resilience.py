"""Serving resilience policies (ISSUE 5): deadline shedding, failed-
batch bisection support, and the per-version circuit breaker with
auto-rollback.

After PRs 1-4 the serving stack had exactly one failure behavior:
queue-watermark 503. Clipper treats bounded-latency degradation as a
first-class contract and Clockwork shows predictability requires
handling the UNHAPPY path as deliberately as the happy one; this module
is the policy half of that (serve/faults.py is the harness that proves
it works). Three policies, all pure decision logic — the batcher stays
the single owner of dispatch mechanics, the registry of version state:

- **Deadline propagation**: a client-supplied budget (the X-Deadline-Ms
  HTTP header in serve.py) rides each request into the batcher, which
  sheds expired requests at pop time — BEFORE dispatch — failing their
  futures with DeadlineExceeded (504 semantics). A request whose
  deadline already passed must cost zero device work and return fast;
  computing logits nobody is waiting for is pure capacity theft under
  load (the Clipper argument, extended from admission to dispatch).

- **Poison-batch bisection** (mechanics live in the batcher's dispatch
  loop, switched by ResiliencePolicy.bisect): a failed multi-request
  dispatch is retried by recursively splitting it along request
  boundaries — cohort-mates succeed on the re-dispatch, only the
  culprit request keeps failing and gets the 500. Splits land on
  existing bucket rungs (a sub-segment's covering bucket is always on
  the ladder), so isolation never compiles a new shape.

- **CircuitBreaker + auto-rollback**: a sliding-window failure-ratio
  tracker per engine version. When the live version's window trips,
  ResiliencePolicy demotes it and promotes the newest healthy resident
  from the ModelRegistry (the PR 3 rollback path, now closed-loop),
  emitting a rollback event — a bad promote heals in one breaker
  window instead of waiting for a human on the admin API.

- **HealthTracker** (ISSUE 6): the sliding-window health score behind
  the replica fleet's dispatch pick (serve/fleet.py). The breaker
  answers one binary question (exclude or not); the tracker keeps the
  richer per-key signal — n-weighted success ratio plus a latency EWMA
  — that /healthz, /metrics and the fleet's least-loaded pick surface.
  A sick replica is a different diagnosis from a sick version: the
  fleet keys its breaker and tracker by REPLICA id and routes around a
  tripped replica, while the version breaker above keeps rolling bad
  PROMOTES back — the two act on disjoint failure domains.
"""

from __future__ import annotations

import logging
import time
from collections import deque
from typing import Optional

from distributedmnist_tpu.analysis.locks import make_lock, make_thread

log = logging.getLogger("distributedmnist_tpu")


class DeadlineExceeded(RuntimeError):
    """The request's client-supplied deadline passed before its batch
    dispatched: shed with 504 semantics (serve.py maps it, with a
    Retry-After derived from the current pipeline state)."""

    status = 504


class CircuitBreaker:
    """Sliding-window failure-ratio breaker, one window per version.

    record(version, ok) feeds every request outcome; it returns True
    exactly when THIS record tripped the breaker for that version —
    failures/window >= failure_ratio with at least min_requests of
    volume inside window_s. A tripped version enters a cooldown during
    which it cannot re-trip (the rollback it triggered needs time to
    take effect; re-tripping on the tail of in-flight failures would
    flap). Thread-safe: outcomes arrive from the completion thread,
    snapshots from HTTP threads.
    """

    def __init__(self, window_s: float = 5.0, min_requests: int = 20,
                 failure_ratio: float = 0.5, cooldown_s: float = 30.0):
        if window_s <= 0:
            raise ValueError(f"window_s must be > 0, got {window_s}")
        if min_requests < 1:
            raise ValueError(
                f"min_requests must be >= 1, got {min_requests}")
        if not 0.0 < failure_ratio <= 1.0:
            raise ValueError(
                f"failure_ratio must be in (0, 1], got {failure_ratio}")
        if cooldown_s < 0:
            raise ValueError(f"cooldown_s must be >= 0, got {cooldown_s}")
        self.window_s = window_s
        self.min_requests = min_requests
        self.failure_ratio = failure_ratio
        self.cooldown_s = cooldown_s
        self._lock = make_lock("resilience.breaker")
        # version -> deque[(t, ok, n)] — n-weighted so one failed batch
        # of k requests carries its real volume
        self._windows: dict[str, deque] = {}
        self._cooldown_until: dict[str, float] = {}
        self._trips = 0

    def record(self, version: str, ok: bool, n: int = 1,
               now: Optional[float] = None) -> bool:
        if now is None:
            now = time.monotonic()
        with self._lock:
            win = self._windows.setdefault(version, deque())
            win.append((now, ok, n))
            cutoff = now - self.window_s
            while win and win[0][0] < cutoff:
                win.popleft()
            if now < self._cooldown_until.get(version, 0.0):
                return False
            total = sum(w[2] for w in win)
            if total < self.min_requests:
                return False
            failures = sum(w[2] for w in win if not w[1])
            if failures / total < self.failure_ratio:
                return False
            # Trip: start the cooldown and clear the window so the
            # in-flight failure tail doesn't immediately re-accumulate.
            self._trips += 1
            self._cooldown_until[version] = now + self.cooldown_s
            win.clear()
            return True

    def trips(self) -> int:
        with self._lock:
            return self._trips

    def in_cooldown(self, key: str, now: Optional[float] = None) -> bool:
        """True while `key` (a version, or a replica id in the fleet's
        per-replica breaker) is inside a trip's cooldown — the fleet's
        dispatch pick excludes such replicas instead of waiting for
        their failures to resolve futures."""
        if now is None:
            now = time.monotonic()
        with self._lock:
            return now < self._cooldown_until.get(key, 0.0)

    def reset(self, key: str) -> None:
        """Forget `key`'s window AND cooldown: an operator rejoining a
        drained/repaired replica gets a fresh health slate — old
        failures from before the repair must not re-trip it on its
        first post-rejoin batch."""
        with self._lock:
            self._windows.pop(key, None)
            self._cooldown_until.pop(key, None)

    def snapshot(self) -> dict:
        with self._lock:
            now = time.monotonic()
            return {
                "window_s": self.window_s,
                "min_requests": self.min_requests,
                "failure_ratio": self.failure_ratio,
                "trips": self._trips,
                "by_version": {
                    v: {"volume": sum(w[2] for w in win),
                        "failures": sum(w[2] for w in win if not w[1]),
                        "cooldown_remaining_s": round(max(
                            self._cooldown_until.get(v, 0.0) - now,
                            0.0), 3)}
                    for v, win in self._windows.items()},
            }


class HealthTracker:
    """Per-key sliding-window health score (ISSUE 6): n-weighted
    success ratio over the last `window_s` seconds plus a latency EWMA.

    The replica fleet records every batch outcome here (keyed by
    replica id) alongside the per-replica CircuitBreaker: the breaker
    decides EXCLUSION (binary, with cooldown hysteresis), the tracker
    keeps the continuous score an operator reads off /healthz to see a
    replica degrading BEFORE it trips. score() is 1.0 with no data —
    an idle replica is presumed healthy, not suspect. Thread-safe.
    """

    def __init__(self, window_s: float = 30.0, ewma_alpha: float = 0.2):
        if window_s <= 0:
            raise ValueError(f"window_s must be > 0, got {window_s}")
        if not 0.0 < ewma_alpha <= 1.0:
            raise ValueError(
                f"ewma_alpha must be in (0, 1], got {ewma_alpha}")
        self.window_s = window_s
        self.ewma_alpha = ewma_alpha
        self._lock = make_lock("resilience.health")
        self._windows: dict[str, deque] = {}   # key -> (t, ok, n)
        self._ewma_s: dict[str, float] = {}

    def record(self, key: str, ok: bool, n: int = 1,
               latency_s: Optional[float] = None,
               now: Optional[float] = None) -> None:
        if now is None:
            now = time.monotonic()
        with self._lock:
            win = self._windows.setdefault(key, deque())
            win.append((now, ok, n))
            cutoff = now - self.window_s
            while win and win[0][0] < cutoff:
                win.popleft()
            if latency_s is not None:
                prev = self._ewma_s.get(key)
                self._ewma_s[key] = (
                    latency_s if prev is None
                    else prev + self.ewma_alpha * (latency_s - prev))

    def score(self, key: str, now: Optional[float] = None) -> float:
        """Success ratio over the live window; 1.0 with no samples."""
        if now is None:
            now = time.monotonic()
        with self._lock:
            win = self._windows.get(key)
            if not win:
                return 1.0
            cutoff = now - self.window_s
            total = ok = 0
            for t, o, n in win:
                if t < cutoff:
                    continue
                total += n
                if o:
                    ok += n
            return ok / total if total else 1.0

    def reset(self, key: str) -> None:
        with self._lock:
            self._windows.pop(key, None)
            self._ewma_s.pop(key, None)

    def snapshot(self) -> dict:
        with self._lock:
            now = time.monotonic()
            out = {}
            for key, win in self._windows.items():
                cutoff = now - self.window_s
                live = [(t, o, n) for t, o, n in win if t >= cutoff]
                total = sum(n for _, _, n in live)
                fails = sum(n for _, o, n in live if not o)
                ewma = self._ewma_s.get(key)
                out[key] = {
                    "volume": total,
                    "failures": fails,
                    "success_ratio": (round((total - fails) / total, 4)
                                      if total else None),
                    "latency_ewma_ms": (round(ewma * 1e3, 3)
                                        if ewma is not None else None),
                }
            return out


class ResiliencePolicy:
    """The batcher/server-facing bundle of the three policies.

    The batcher calls exactly two things: `bisect` (a bool gating the
    dispatch-failure bisection path) and `record_outcome(version, ok,
    n)` at every batch fan-out. A breaker trip on the LIVE version
    demotes it and promotes the newest healthy registry resident on a
    dedicated daemon thread — never the completion thread, which must
    keep fanning out results while the roll happens (the registry's
    admin lock may be held by a slow warmup, and rollback must not
    stall live fan-out behind it).
    """

    def __init__(self, bisect: bool = True,
                 breaker: Optional[CircuitBreaker] = None,
                 registry=None, metrics=None):
        self.bisect = bisect
        self.breaker = breaker
        self.registry = registry
        self.metrics = metrics

    def record_outcome(self, version: Optional[str], ok: bool,
                       n: int = 1) -> None:
        """One batch's fan-out result (version-tagged). Feeds the
        breaker; a trip triggers the async rollback."""
        if self.breaker is None or version is None:
            return
        if self.breaker.record(version, ok, n=n):
            self._tripped(version)

    def _tripped(self, version: str) -> None:
        log.warning("circuit breaker TRIPPED for version %s", version)
        if self.metrics is not None:
            self.metrics.record_breaker_trip(version)
        if self.registry is None:
            return
        make_thread(target=self._rollback, args=(version,),
                    name="serve-rollback", daemon=True).start()

    def _rollback(self, version: str) -> None:
        try:
            target = self.registry.rollback(
                version, reason=f"circuit breaker tripped on {version}")
        except Exception:
            log.exception("auto-rollback from %s failed", version)
            return
        if target is not None:
            if self.metrics is not None:
                self.metrics.record_rollback(version, target.version)
            log.warning("auto-rollback: %s -> %s", version,
                        target.version)

    def snapshot(self) -> dict:
        return {
            "bisect": self.bisect,
            "breaker": (self.breaker.snapshot()
                        if self.breaker is not None else None),
        }


def build_resilience(cfg, registry=None, metrics=None) -> ResiliencePolicy:
    """ResiliencePolicy from Config knobs — the wiring serve.py and the
    bench share (one construction, no drift in defaults)."""
    breaker = CircuitBreaker(
        window_s=cfg.serve_breaker_window_s,
        min_requests=cfg.serve_breaker_min_requests,
        failure_ratio=cfg.serve_breaker_ratio)
    return ResiliencePolicy(bisect=cfg.serve_bisect, breaker=breaker,
                            registry=registry, metrics=metrics)
