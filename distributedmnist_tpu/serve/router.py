"""Version-aware request router: the engine-shaped layer between the
dynamic batcher and the per-version InferenceEngines.

The batcher (serve/batcher.py) talks to ONE engine-shaped object. Before
this layer that object was a single InferenceEngine, which froze the
process on whatever params it started with. The Router keeps that exact
surface — dispatch()/fetch(), max_batch/buckets/platform, _as_images —
but resolves WHICH engine serves each batch at dispatch time:

- **live**: the default target. set_live() swaps it atomically under a
  lock the dispatch thread crosses once per batch; a handle captures its
  engine at dispatch, so a batch dispatched on the old version fetches
  from the old version even if the swap lands mid-flight. No request can
  ever mix versions: routing is per-BATCH, and every row of a batch runs
  one compiled program of one engine.
- **canary**: a configured fraction of batches routes to a candidate FOR
  REAL (clients get its results). Results are version-tagged end to end
  (handle.version -> ServeMetrics.by_version), so the canary population's
  latency/volume is separable from the live population's.
- **shadow**: a sampled fraction of live batches is DUPLICATED to a
  candidate. The client always gets the live result; the shadow result
  is fetched on a dedicated drain thread (never the completion thread,
  whose strict FIFO fan-out would let a slow candidate inflate live
  p99), compared (argmax agreement + max abs logit diff, recorded in
  metrics), and discarded. A shadow failure is recorded and swallowed —
  a broken candidate must never break live traffic.

Every engine a Router accepts must share its bucket ladder/max_batch
(set_* assert it): a swap can therefore never introduce a new compile
geometry, which is what keeps the zero-recompile contract true across
swaps (Clockwork's rule: no model takes live traffic before its programs
are compiled — enforced upstream by ModelRegistry, which only hands over
pre-warmed engines).
"""

from __future__ import annotations

import dataclasses
import logging
import random
import threading
import time
from typing import Any, Optional, Sequence

import numpy as np

from distributedmnist_tpu.analysis.locks import (make_fifo, make_lock,
                                                 make_thread)
from distributedmnist_tpu.serve import trace
from distributedmnist_tpu.serve.engine import InferenceEngine
from distributedmnist_tpu.serve.faults import failpoint

log = logging.getLogger("distributedmnist_tpu")


class NoLiveModel(RuntimeError):
    """dispatch() with no live version: the server is warming (or every
    version was retired). 503 semantics, like batcher.Rejected."""

    status = 503


@dataclasses.dataclass
class _Target:
    engine: Any
    version: str
    fraction: float = 1.0


@dataclasses.dataclass
class RoutedHandle:
    """A dispatched batch plus the engine that computed it (so fetch
    lands on the right version regardless of swaps in between) and,
    when shadowed, the duplicate in-flight on the candidate."""

    handle: Any                   # the target engine's InferenceHandle
    engine: Any
    version: str
    n: int
    bucket: int
    canary: bool = False
    shadow_handle: Any = None
    shadow_engine: Any = None
    shadow_version: Optional[str] = None
    # The serving precision of the engine that computed this batch
    # (ISSUE 7): rides the handle to metrics exactly like version, so
    # per-dtype populations are attributable end to end.
    infer_dtype: Optional[str] = None
    # The fleet replica this router belongs to (ISSUE 6): dispatch now
    # targets (version, replica), and the tag rides the handle end to
    # end so metrics can attribute each batch to the replica that
    # COMPUTED it. None on a standalone (single-replica) router.
    replica: Optional[str] = None


class Router:
    """Engine-shaped dispatch()/fetch() over a swappable set of versioned
    engines. Constructed from the shared engine geometry (max_batch /
    buckets / platform / n_chips) so the batcher can be built and accept
    requests BEFORE any version is live — early submits fail their
    futures with NoLiveModel (503), they don't crash the pipeline."""

    # Outstanding shadow duplications (dispatched or queued for
    # comparison) are capped: past this, sampled batches SKIP the
    # duplicate instead of growing the queue — a wedged candidate must
    # cost bounded memory (each outstanding duplicate pins a staging
    # buffer, a device batch and the live result), never an OOM.
    SHADOW_CAP = 64

    # Capability flag the registry probes before wiring a cascade
    # (ISSUE 17): routers that can resolve a pinned infer_dtype to a
    # live-version alternate engine. Engine-shaped doubles and the
    # fleet front (no per-dtype alternates) lack it, so enable_cascade
    # refuses them instead of failing at dispatch time.
    supports_alternates = True

    def __init__(self, max_batch: int, buckets: Sequence[int],
                 platform: str, n_chips: int = 1, metrics=None,
                 seed: int = 0, shadow_cap: Optional[int] = None,
                 replica: Optional[str] = None):
        self.max_batch = max_batch
        self.buckets = tuple(buckets)
        self.platform = platform
        self.n_chips = n_chips
        self.metrics = metrics
        # The fleet replica id this router serves (None standalone):
        # stamped onto every RoutedHandle so a batch is attributable to
        # (version, replica) end to end.
        self.replica = replica
        # `is None`, not `or`: an explicit 0 (duplicate nothing — every
        # sampled batch counts as dropped) must be honored.
        self.shadow_cap = (self.SHADOW_CAP if shadow_cap is None
                           else shadow_cap)
        self._lock = make_lock("router.routes")
        self._live: Optional[_Target] = None
        self._canary: Optional[_Target] = None
        self._shadow: Optional[_Target] = None
        # Pinned-route table for the LIVE version (ISSUE 17): maps
        # infer_dtype -> warmed engine of that precision. Swapped
        # atomically with _live in set_live so a pinned dispatch can
        # never pair the new version's alternates with the old live.
        self._alternates: dict = {}
        # Routing draws happen under the lock on the single dispatch
        # thread; seeded so canary/shadow sampling is reproducible in
        # tests and bench replays.
        self._rng = random.Random(seed)
        # Shadow comparisons drain on their own daemon thread: the
        # completion thread resolves LIVE futures strictly FIFO, so a
        # slow shadow candidate blocking inside fetch() would inflate
        # live p99 for every batch queued behind it — exactly the
        # "candidate must never hurt live traffic" violation shadow
        # mode exists to prevent. Engine.fetch is thread-safe and
        # order-independent (staging pool is locked), so out-of-order
        # shadow fetches are fine.
        # Named FIFO factory (ISSUE 11): bare SimpleQueue in
        # production, explorable under the schedule explorer.
        self._shadow_q = make_fifo("router.shadow_q")
        self._shadow_pending = 0
        self._shadow_pending_lock = make_lock("router.shadow_pending")
        self._shadow_thread: Optional[threading.Thread] = None

    # Engine-shape parity: borrow the engine's own implementations —
    # both read only self.buckets / plain arrays, and a single copy
    # cannot drift.
    _as_images = staticmethod(InferenceEngine._as_images)
    bucket_for = InferenceEngine.bucket_for

    # -- version wiring (called by ModelRegistry / admin) -----------------

    def _check_compatible(self, engine) -> None:
        if (tuple(engine.buckets) != self.buckets
                or engine.max_batch != self.max_batch):
            raise ValueError(
                "engine geometry mismatch: router serves buckets "
                f"{self.buckets} (max_batch {self.max_batch}), engine has "
                f"{tuple(engine.buckets)} (max_batch {engine.max_batch}) "
                "— all versions must share one compile geometry")

    def set_live(self, engine, version: str,
                 alternates: Optional[dict] = None) -> None:
        """Atomic hot-swap: the next dispatched batch runs `version`;
        batches already in flight fetch from the engine their handle
        captured. Clears a candidate role the promoted version held.
        `alternates` maps infer_dtype -> warmed engine of THIS version
        for pinned-route dispatches (the cascade's stage requests);
        omitted, the table holds just the live engine under its own
        dtype — pinning to anything else raises NoLiveModel."""
        self._check_compatible(engine)
        if alternates is not None:
            for alt in alternates.values():
                self._check_compatible(alt)
            alternates = dict(alternates)
        else:
            alternates = {
                (getattr(engine, "infer_dtype", None) or "float32"):
                    engine}
        with self._lock:
            prev = self._live.version if self._live else None
            self._live = _Target(engine, version)
            self._alternates = alternates
            if self._canary and self._canary.version == version:
                self._canary = None
            if self._shadow and self._shadow.version == version:
                self._shadow = None
        log.info("router: live version %s -> %s (alternates: %s)",
                 prev, version, sorted(alternates))

    def set_shadow(self, engine, version: str, fraction: float) -> None:
        self._check_compatible(engine)
        if not 0.0 < fraction <= 1.0:
            raise ValueError(f"shadow fraction must be in (0, 1], "
                             f"got {fraction}")
        with self._lock:
            self._shadow = _Target(engine, version, fraction)
        log.info("router: shadowing %.0f%% of live traffic to %s",
                 100 * fraction, version)

    def set_canary(self, engine, version: str, fraction: float) -> None:
        self._check_compatible(engine)
        if not 0.0 < fraction < 1.0:
            raise ValueError(f"canary fraction must be in (0, 1), "
                             f"got {fraction}")
        with self._lock:
            self._canary = _Target(engine, version, fraction)
        log.info("router: canarying %.0f%% of traffic to %s",
                 100 * fraction, version)

    def clear_candidates(self) -> None:
        with self._lock:
            self._canary = self._shadow = None

    def live_version(self) -> Optional[str]:
        with self._lock:
            return self._live.version if self._live else None

    def live_infer_dtype(self) -> Optional[str]:
        """The live engine's serving precision (None while warming or
        for engine-shaped doubles without one) — the /healthz and
        GET /models 'which precision is live' surface (ISSUE 7)."""
        with self._lock:
            live = self._live
        if live is None:
            return None
        return getattr(live.engine, "infer_dtype", None)

    def live_route(self) -> tuple:
        """(live version, live infer_dtype) under ONE lock crossing —
        the prediction cache's key basis (ISSUE 10). Two separate
        live_version()/live_infer_dtype() reads could interleave with
        a promote and key an entry on a (version, dtype) pair that was
        never live together; this read cannot."""
        with self._lock:
            live = self._live
        if live is None:
            return (None, None)
        return (live.version, getattr(live.engine, "infer_dtype", None))

    def routes(self) -> dict:
        """The current routing table (for GET /models and tests)."""
        with self._lock:
            return {
                "live": self._live.version if self._live else None,
                "canary": ({"version": self._canary.version,
                            "fraction": self._canary.fraction}
                           if self._canary else None),
                "shadow": ({"version": self._shadow.version,
                            "fraction": self._shadow.fraction}
                           if self._shadow else None),
                "alternates": sorted(self._alternates),
            }

    def versions_in_route(self) -> set:
        """Versions currently holding a routing role (must not be
        evicted from the registry)."""
        with self._lock:
            return {t.version for t in (self._live, self._canary,
                                        self._shadow) if t is not None}

    def bucket_costs(self) -> dict:
        """The LIVE engine's measured per-bucket cost table (empty while
        no version is live). Every resident engine shares one bucket
        geometry, so the live table is a sound plan basis for canary
        dispatches too; a promote atomically re-points this at the new
        version's freshly re-measured costs — the registry's warmup
        refreshes the table as part of making a version promotable."""
        with self._lock:
            live = self._live
        if live is None:
            return {}
        costs = getattr(live.engine, "bucket_costs", None)
        # engine-shaped doubles without a cost table plan as "don't
        # split", same as a pre-warmup engine
        return costs() if callable(costs) else {}

    def bucket_costs_p95(self) -> dict:
        """The live engine's p95 cost table (the fleet's hedge-trigger
        basis); empty while no version is live or for engine-shaped
        doubles without one — which disables hedging, not serving."""
        with self._lock:
            live = self._live
        if live is None:
            return {}
        costs = getattr(live.engine, "bucket_costs_p95", None)
        return costs() if callable(costs) else {}

    # -- the engine surface the batcher drives ----------------------------

    def dispatch(self, x, infer_dtype: Optional[str] = None
                 ) -> RoutedHandle:
        if infer_dtype is not None:
            # Pinned route resolved BEFORE the seeded draws below so a
            # cascade's stage dispatches never perturb the canary/
            # shadow sampling sequence of interleaved live traffic.
            return self._dispatch_pinned(x, infer_dtype)
        with self._lock:
            live, canary, shadow = self._live, self._canary, self._shadow
            route_draw = self._rng.random()
            shadow_draw = self._rng.random()
        if live is None:
            raise NoLiveModel(
                "no warmed model version is live (server warming?)")
        target, is_canary = live, False
        if canary is not None and route_draw < canary.fraction:
            target, is_canary = canary, True
        h = target.engine.dispatch(x)
        rh = RoutedHandle(handle=h, engine=target.engine,
                          version=target.version, n=h.n, bucket=h.bucket,
                          canary=is_canary, replica=self.replica,
                          infer_dtype=getattr(target.engine,
                                              "infer_dtype", None))
        # Shadow only duplicates LIVE-routed batches: the canary and
        # shadow populations stay disjoint, so their metrics are
        # separately attributable.
        if (shadow is not None and not is_canary
                and shadow_draw < shadow.fraction):
            # Claim an outstanding-duplication slot BEFORE dispatching:
            # a wedged candidate stalls the drain thread, and unbounded
            # duplication would pin a staging buffer + device batch +
            # live result per entry until OOM. Past the cap the sample
            # is skipped (dropped, counted) — live traffic never pays.
            with self._shadow_pending_lock:
                claim = self._shadow_pending < self.shadow_cap
                if claim:
                    self._shadow_pending += 1
            if not claim:
                if self.metrics is not None:
                    self.metrics.record_shadow_drop()
            else:
                sp = trace.begin_span("router.shadow",
                                      version=shadow.version)
                try:
                    # Fault-injection seam for the candidate fan-out
                    # (serve/faults.py): an injected shadow fault must
                    # be swallowed+counted exactly like a real broken
                    # candidate — live traffic never pays.
                    failpoint("router.shadow", version=shadow.version)
                    rh.shadow_handle = shadow.engine.dispatch(x)
                    rh.shadow_engine = shadow.engine
                    rh.shadow_version = shadow.version
                except Exception as se:
                    # A broken candidate must never take down live
                    # traffic.
                    log.exception("shadow dispatch to %s failed",
                                  shadow.version)
                    trace.end_span(sp, error=type(se).__name__)
                    with self._shadow_pending_lock:
                        self._shadow_pending -= 1
                    if self.metrics is not None:
                        self.metrics.record_shadow_error()
                finally:
                    trace.end_span(sp)
        return rh

    def _dispatch_pinned(self, x, infer_dtype: str) -> RoutedHandle:
        """Dispatch on the LIVE version's engine of a named precision
        (the cascade's stage requests — `fast`/stage 1 pins the cheap
        dtype, escalations and `exact` pin float32). Pinned dispatches
        skip canary/shadow deliberately: a stage result must be
        version-deterministic (its rows are compared/merged against the
        sibling stage), and the candidate populations are defined over
        live-routed coalesced dispatches only. A missing alternate is
        NoLiveModel — status 503, systemic, so the batcher fails the
        whole batch without futile bisection."""
        with self._lock:
            live = self._live
            engine = self._alternates.get(infer_dtype)
        if live is None:
            raise NoLiveModel(
                "no warmed model version is live (server warming?)")
        if engine is None:
            raise NoLiveModel(
                f"no live {infer_dtype!r} route for version "
                f"{live.version} (variant not promoted with the "
                "cascade, or demoted by a re-gate)")
        h = engine.dispatch(x)
        return RoutedHandle(handle=h, engine=engine,
                            version=live.version, n=h.n, bucket=h.bucket,
                            canary=False, replica=self.replica,
                            infer_dtype=getattr(engine, "infer_dtype",
                                                None))

    def dispatch_fast(self, x) -> Optional[RoutedHandle]:
        """The fast lane's routed dispatch (ISSUE 14): resolve the live
        target once and try its engine's resident staging route,
        returning None whenever the full dispatch() semantics are
        needed instead — a configured candidate (canary fractions and
        shadow duplication are defined over COALESCED dispatches; the
        bypass must not silently thin either population), an engine
        without a fast route, or a busy resident buffer. The caller
        (DynamicBatcher's lane) falls back to dispatch() on the same
        thread, so declining the lane costs a hand-off, never an
        error. NoLiveModel still raises — warming is a 503, not a
        fallback."""
        with self._lock:
            live, canary, shadow = self._live, self._canary, self._shadow
        if live is None:
            raise NoLiveModel(
                "no warmed model version is live (server warming?)")
        if canary is not None or shadow is not None:
            return None
        fast = getattr(live.engine, "dispatch_fast", None)
        if not callable(fast):
            return None
        h = fast(x)
        if h is None:
            return None
        return RoutedHandle(handle=h, engine=live.engine,
                            version=live.version, n=h.n, bucket=h.bucket,
                            replica=self.replica,
                            infer_dtype=getattr(live.engine,
                                                "infer_dtype", None))

    def fetch(self, rh: RoutedHandle) -> np.ndarray:
        try:
            out = rh.engine.fetch(rh.handle)
        except Exception:
            # The live fetch failing is the batcher's failure path; the
            # shadow duplicate must still drain (its staging buffer and
            # pending slot would leak otherwise). out=None skips the
            # comparison.
            if rh.shadow_handle is not None:
                self._enqueue_shadow(rh, None)
            raise
        if rh.shadow_handle is not None:
            # Hand the comparison to the drain thread and return the
            # live result NOW: the completion thread must not wait out
            # the candidate's compute before resolving live futures.
            # (The pending slot was claimed at dispatch; released by
            # the drain thread after the comparison lands.)
            self._enqueue_shadow(rh, out)
        # The client-facing result is ALWAYS the routed target's output;
        # shadow results never leave the drain thread.
        return out

    def _enqueue_shadow(self, rh: RoutedHandle, out) -> None:
        with self._shadow_pending_lock:
            if self._shadow_thread is None:
                self._shadow_thread = make_thread(
                    target=self._shadow_loop, name="serve-shadow",
                    daemon=True)
                self._shadow_thread.start()
        self._shadow_q.put((rh, out))

    def _shadow_loop(self) -> None:
        while True:
            rh, out = self._shadow_q.get()
            try:
                shadow_out = rh.shadow_engine.fetch(rh.shadow_handle)
                if self.metrics is not None and out is not None:
                    agree = int(np.sum(out.argmax(-1)
                                       == shadow_out.argmax(-1)))
                    diff = float(np.max(np.abs(
                        out.astype(np.float32)
                        - shadow_out.astype(np.float32))))
                    self.metrics.record_shadow(
                        rh.version, rh.shadow_version, rows=rh.n,
                        agree_rows=agree, max_abs_diff=diff)
            except Exception:
                log.exception("shadow fetch from %s failed",
                              rh.shadow_version)
                if self.metrics is not None:
                    self.metrics.record_shadow_error()
            finally:
                with self._shadow_pending_lock:
                    self._shadow_pending -= 1

    def shadow_pending(self) -> int:
        """Shadow comparisons enqueued but not yet recorded."""
        with self._shadow_pending_lock:
            return self._shadow_pending

    def drain_shadow(self, timeout: float = 30.0) -> None:
        """Bounded wait for all queued shadow comparisons to land in
        metrics (tests and orderly shutdowns; live traffic never needs
        this)."""
        deadline = time.monotonic() + timeout
        while self.shadow_pending():
            if time.monotonic() > deadline:
                raise RuntimeError(
                    f"{self.shadow_pending()} shadow comparison(s) "
                    f"still pending after {timeout:g}s")
            time.sleep(0.005)

    def infer(self, x) -> np.ndarray:
        return self.fetch(self.dispatch(x))
