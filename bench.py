#!/usr/bin/env python
"""Benchmark: steady-state training throughput, printed as ONE JSON line.

Metric: images/sec/chip on the LeNet-5 data-parallel workload
[BASELINE.json metric: "MNIST images/sec/chip"; config 4: global batch 512].
The full fused step (fwd+bwd+allreduce+update, on-device batch gather) is
timed after a compile/warmup phase, on every visible device of the default
backend (the real TPU chip under the driver).

vs_baseline: the reference publishes no numbers (BASELINE.md — empty mount,
published={}); the only quantitative anchor is the driver's north-star
target "≥99% in <30s on a v4-8 with near-linear scaling", which implies
roughly 10 epochs * 60k images / 30s / 8 chips = 2500 images/sec/chip.
vs_baseline is value / 2500 — i.e. >1.0 means faster than the target rate.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

TARGET_IPS_PER_CHIP = 2500.0


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--global-batch", type=int, default=512)
    p.add_argument("--warmup-steps", type=int, default=20)
    p.add_argument("--bench-steps", type=int, default=200,
                   help="must be >= 1")
    p.add_argument("--steps-per-call", type=int, default=None,
                   help="optimizer steps fused per dispatch via lax.scan "
                        "(default: 1 on cpu, 32 on tpu)")
    p.add_argument("--model", default="lenet")
    p.add_argument("--dtype", default="float32")
    args = p.parse_args(argv)
    if args.bench_steps < 1:
        p.error("--bench-steps must be >= 1")

    import jax
    import jax.numpy as jnp

    from distributedmnist_tpu import models, optim
    from distributedmnist_tpu.data import load_mnist
    from distributedmnist_tpu.data.loader import DeviceDataset, IndexStream
    from distributedmnist_tpu.parallel import make_mesh, replicated
    from distributedmnist_tpu.trainer import init_state, make_train_step

    from distributedmnist_tpu.utils import round_up

    devs = jax.devices()
    n_chips = len(devs)
    gb = round_up(args.global_batch, n_chips)
    mesh = make_mesh(devs)
    dtype = jnp.bfloat16 if args.dtype == "bfloat16" else jnp.float32

    data = load_mnist(synthetic=True, seed=0)  # pixels identical cost to real
    ds = DeviceDataset(data, mesh)
    model = models.build(args.model, dtype=dtype,
                         platform=devs[0].platform)
    tx = optim.build("adam", 1e-3)
    state = jax.device_put(
        init_state(jax.random.PRNGKey(0), model, tx,
                   jnp.zeros((1, 28, 28, 1))),
        replicated(mesh))
    step_fn = make_train_step(model, tx, mesh, mode="auto", dtype=dtype)
    stream = IndexStream(ds.train_n, gb, seed=0, mesh=mesh)

    # CPU's collective rendezvous deadlocks under concurrent in-flight
    # programs (small host thread pool); TPU pipelines safely.
    sync_every_step = devs[0].platform == "cpu"
    spc = (max(1, args.steps_per_call) if args.steps_per_call is not None
           else (1 if sync_every_step else 32))

    def run(n_steps):
        """Run >= n_steps optimizer steps in blocks of spc; returns the
        exact step count executed."""
        metrics = None
        blocks = max(1, -(-n_steps // spc))
        for _ in range(blocks):
            state_box[0], metrics = step_fn(state_box[0], ds.train_x,
                                            ds.train_y,
                                            stream.next_block(spc))
            if sync_every_step:
                jax.block_until_ready(metrics["loss"])
        jax.block_until_ready(metrics["loss"])
        return blocks * spc

    state_box = [state]
    run(args.warmup_steps)
    t0 = time.perf_counter()
    n_run = run(args.bench_steps)
    elapsed = time.perf_counter() - t0

    ips = n_run * gb / elapsed
    value = ips / n_chips
    print(json.dumps({
        "metric": "train_images_per_sec_per_chip",
        "value": round(value, 1),
        "unit": "images/sec/chip",
        "vs_baseline": round(value / TARGET_IPS_PER_CHIP, 3),
        "detail": {
            "model": args.model,
            "global_batch": gb,
            "n_chips": n_chips,
            "backend": devs[0].platform,
            "dtype": args.dtype,
            "bench_steps": n_run,
            "steps_per_call": spc,
            "step_ms": round(1000 * elapsed / n_run, 3),
        },
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
