#!/usr/bin/env python
"""Benchmark, printed as ONE JSON line. Two modes for the two halves of
the BASELINE metric ("MNIST images/sec/chip; wall-clock to 99% test
accuracy"):

- throughput (default): steady-state training images/sec/chip on the
  LeNet-5 data-parallel workload [config 4: global batch 512]. The full
  fused step (fwd+bwd+allreduce+update, on-device batch gather) is timed
  after a compile/warmup phase, on every visible device of the default
  backend (the real TPU chip under the driver).
- time-to-accuracy: wall-clock seconds for a full training run to reach
  --target-accuracy (train + eval, compile excluded from neither — this is
  the end-to-end number a user experiences).

The measurement runs in a supervised worker subprocess: TPU runtime claims
through tunneled/pooled backends can wedge forever before the first
program runs (observed on this host's axon relay: the claim leg
intermittently never completes while a fresh process succeeds). The
supervisor watches worker stderr/stdout activity and kills + retries a
worker that goes silent for --stall-timeout seconds, so one wedged claim
cannot turn the benchmark into a hang. --inline bypasses supervision.

vs_baseline: the reference publishes no numbers (BASELINE.md — empty mount,
published={}); the only quantitative anchor is the driver's north-star
target ">=99% in <30s on a v4-8 with near-linear scaling". For throughput
that implies roughly 10 epochs * 60k images / 30s / 8 chips = 2500
images/sec/chip and vs_baseline = value / 2500; for time-to-accuracy
vs_baseline = 30 / value. Either way >1.0 beats the target.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

TARGET_IPS_PER_CHIP = 2500.0
TARGET_WALL_S = 30.0


def _mark(msg: str) -> None:
    """Progress marker on stderr — the supervisor's liveness signal."""
    print(f"bench: {msg}", file=sys.stderr, flush=True)


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--mode", choices=["throughput", "time-to-accuracy"],
                   default="throughput")
    p.add_argument("--target-accuracy", type=float, default=0.99)
    p.add_argument("--data-dir", default=None,
                   help="real MNIST IDX/npz dir; synthetic fallback")
    p.add_argument("--max-epochs", type=int, default=20)
    p.add_argument("--global-batch", type=int, default=512)
    p.add_argument("--warmup-steps", type=int, default=None,
                   help="[throughput] compile/warmup steps (default 20)")
    p.add_argument("--bench-steps", type=int, default=None,
                   help="[throughput] timed steps, >= 1 "
                        "(default: 2048 on tpu, 64 on cpu)")
    p.add_argument("--steps-per-call", type=int, default=None,
                   help="optimizer steps fused per dispatch via lax.scan "
                        "(default: 1 on cpu; on tpu 256 in throughput mode, "
                        "largest divisor <= 256 of the eval cadence in "
                        "time-to-accuracy mode)")
    p.add_argument("--model", default="lenet")
    p.add_argument("--dtype", default="float32")
    p.add_argument("--repeats", type=int, default=None,
                   help="[throughput] timed windows, median reported "
                        "(default: 3 on tpu, 1 on cpu)")
    p.add_argument("--stall-timeout", type=float, default=300.0,
                   help="kill+retry the worker if it is silent this long")
    p.add_argument("--max-attempts", type=int, default=3,
                   help="worker attempts before giving up")
    p.add_argument("--inline", action="store_true",
                   help="run in-process (no supervisor subprocess)")
    args = p.parse_args(argv)

    # Cheap arg-only validation FIRST: a deterministic usage error must
    # exit 2 immediately, not be retried in supervised subprocesses.
    if args.mode == "time-to-accuracy":
        # throughput-only knobs are rejected, not silently ignored
        # (--warmup-steps especially would read as LR warmup here)
        if (args.warmup_steps is not None or args.bench_steps is not None
                or args.repeats is not None):
            p.error("--warmup-steps/--bench-steps/--repeats are "
                    "throughput-mode flags; time-to-accuracy takes "
                    "--max-epochs and --steps-per-call")
    else:
        args.warmup_steps = (20 if args.warmup_steps is None
                             else args.warmup_steps)
        # bench_steps default is platform-dependent; resolved in the
        # worker once the backend is known.
        if args.bench_steps is not None and args.bench_steps < 1:
            p.error("--bench-steps must be >= 1")
        if args.repeats is not None and args.repeats < 1:
            p.error("--repeats must be >= 1")

    from distributedmnist_tpu.utils import supervise

    if not args.inline and not supervise.is_worker():
        # Last-resort fallback: if every attempt on the default backend
        # fails (e.g. the TPU runtime is down hard), record a
        # clearly-labelled CPU number (detail.backend says "cpu") rather
        # than nothing. Unsetting PALLAS_AXON_POOL_IPS disables this
        # host's TPU plugin registration (the repo-wide convention, cf.
        # conftest.py); JAX_PLATFORMS=cpu forces the backend.
        return supervise.run_supervised(
            os.path.abspath(__file__),
            list(sys.argv[1:] if argv is None else argv),
            accept=supervise.json_record_acceptor("metric"),
            stall_timeout=args.stall_timeout, attempts=args.max_attempts,
            fallback_env={"JAX_PLATFORMS": "cpu",
                          "PALLAS_AXON_POOL_IPS": None})
    if args.mode == "time-to-accuracy":
        return _time_to_accuracy(args)

    import jax
    import jax.numpy as jnp

    from distributedmnist_tpu import models, optim
    from distributedmnist_tpu.data import load_mnist
    from distributedmnist_tpu.data.loader import DeviceDataset, IndexStream
    from distributedmnist_tpu.parallel import make_mesh, replicated
    from distributedmnist_tpu.trainer import init_state, make_train_step

    from distributedmnist_tpu.utils import enable_compilation_cache, round_up

    enable_compilation_cache()
    devs = jax.devices()
    _mark(f"backend up: {len(devs)}x {devs[0].platform}")
    n_chips = len(devs)
    gb = round_up(args.global_batch, n_chips)
    mesh = make_mesh(devs)
    dtype = jnp.bfloat16 if args.dtype == "bfloat16" else jnp.float32

    # --data-dir is honored (real pixels cost the same as synthetic ones,
    # but silently dropping a user flag is worse than loading the data)
    data = load_mnist(args.data_dir, synthetic=args.data_dir is None, seed=0)
    ds = DeviceDataset(data, mesh)
    model = models.build(args.model, dtype=dtype,
                         platform=devs[0].platform)
    tx = optim.build("adam", 1e-3)
    state = jax.device_put(
        init_state(jax.random.PRNGKey(0), model, tx,
                   jnp.zeros((1, 28, 28, 1))),
        replicated(mesh))
    step_fn = make_train_step(model, tx, mesh, mode="auto", dtype=dtype)
    stream = IndexStream(ds.train_n, gb, seed=0, mesh=mesh)

    # CPU's collective rendezvous deadlocks under concurrent in-flight
    # programs (small host thread pool); TPU pipelines safely.
    sync_every_step = devs[0].platform == "cpu"
    spc = (max(1, args.steps_per_call) if args.steps_per_call is not None
           else (1 if sync_every_step else 256))
    if args.bench_steps is None:
        args.bench_steps = 64 if sync_every_step else 2048

    from distributedmnist_tpu.utils import StepTimer

    last_mark = [time.monotonic()]

    def run(n_steps):
        """Run >= n_steps optimizer steps in blocks of spc; returns the
        exact step count executed."""
        metrics = None
        blocks = max(1, -(-n_steps // spc))
        for b in range(blocks):
            state_box[0], metrics = step_fn(state_box[0], ds.train_x,
                                            ds.train_y,
                                            stream.next_block(spc))
            if sync_every_step:
                jax.block_until_ready(metrics["loss"])
            # Periodic liveness for the supervisor: a legitimately long
            # window (slow backend, big --bench-steps) must not read as a
            # silent stall and get the healthy worker killed.
            if time.monotonic() - last_mark[0] > 15:
                _mark(f"block {b + 1}/{blocks}")
                last_mark[0] = time.monotonic()
        # The clock stops on a device->host VALUE fetch of the final
        # block's loss: its dependency chain covers every queued block,
        # and on pooled/tunneled backends block_until_ready can return
        # before execution completes (StepTimer.barrier) — fetched bytes
        # are the only proof the work happened.
        StepTimer.barrier(metrics["loss"])
        return blocks * spc

    state_box = [state]
    _mark("state initialized; compiling + warmup")
    run(args.warmup_steps)
    _mark("warmup done; timing")
    # Repeated timed windows, median reported: run-to-run variance on a
    # tunneled/pooled backend is substantial, and one window would make
    # the recorded number a lottery. 1 repeat on CPU (each window is
    # minutes there).
    repeats = args.repeats if args.repeats is not None \
        else (1 if sync_every_step else 3)
    windows = []
    n_run = 0
    for r in range(repeats):
        t0 = time.perf_counter()
        n_run = run(args.bench_steps)
        windows.append(n_run * gb / (time.perf_counter() - t0) / n_chips)
        _mark(f"window {r + 1}/{repeats}: {windows[-1]:.0f} img/s/chip")

    import statistics
    value = statistics.median(windows)
    print(json.dumps({
        "metric": "train_images_per_sec_per_chip",
        "value": round(value, 1),
        "unit": "images/sec/chip",
        "vs_baseline": round(value / TARGET_IPS_PER_CHIP, 3),
        "detail": {
            "model": args.model,
            "data": ds.source,
            "global_batch": gb,
            "n_chips": n_chips,
            "backend": devs[0].platform,
            "dtype": args.dtype,
            "bench_steps": n_run,
            "steps_per_call": spc,
            "step_ms": round(1000 * gb / value / n_chips, 3) if value
            else None,
            "windows_img_s_chip": [round(w, 1) for w in windows],
        },
    }))
    return 0


def _time_to_accuracy(args) -> int:
    import logging

    import jax

    from distributedmnist_tpu import trainer
    from distributedmnist_tpu.config import Config
    from distributedmnist_tpu.utils import round_up

    # fit()'s INFO eval/summary lines double as the supervisor's liveness
    # signal (and give the driver progress visibility).
    logging.basicConfig(level=logging.INFO, stream=sys.stderr)

    n_chips = len(jax.devices())
    _mark(f"backend up: {n_chips} devices")
    gb = round_up(args.global_batch, n_chips)
    cfg = Config(model=args.model, optimizer="adam", learning_rate=2e-3,
                 lr_schedule="cosine",
                 data_dir=args.data_dir, synthetic=args.data_dir is None,
                 batch_size=gb,
                 epochs=args.max_epochs,
                 eval_every=100, log_every=0,
                 target_accuracy=args.target_accuracy,
                 steps_per_call=args.steps_per_call,
                 dtype=args.dtype)
    out = trainer.fit(cfg)
    wall = out["wall_clock_to_target_s"]
    reached = wall is not None
    # Both outcomes report fit()'s own training clock so the two numbers
    # span the same interval (a missed run must not look slower merely by
    # charging data-load/model-init setup that a reached run never pays).
    value = wall if reached else out["wall_clock_s"]
    # vs_baseline only counts when the accuracy half of the target was met;
    # a fast run that never reached target is a miss (0.0), not a win.
    vs = round(TARGET_WALL_S / value, 3) if (reached and value) else 0.0
    print(json.dumps({
        "metric": "wall_clock_to_target_accuracy",
        "value": round(value, 2),
        "unit": "seconds",
        "vs_baseline": vs,
        "detail": {
            "reached_target": reached,
            "target_accuracy": args.target_accuracy,
            "final_accuracy": round(out["test_accuracy"], 4),
            "steps": out["steps"],
            "data": out["data"],
            "model": args.model,
            "global_batch": out["global_batch"],
            "n_chips": n_chips,
            "backend": jax.devices()[0].platform,
            "dtype": args.dtype,
        },
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
