#!/usr/bin/env python
"""Benchmark, printed as ONE JSON line. Four modes; the first two are the
two halves of the BASELINE metric ("MNIST images/sec/chip; wall-clock to
99% test accuracy"):

- throughput (default): steady-state training images/sec/chip on the
  LeNet-5 data-parallel workload [config 4: global batch 512]. The full
  fused step (fwd+bwd+allreduce+update, on-device batch gather) is timed
  after a compile/warmup phase, on every visible device of the default
  backend (the real TPU chip under the driver).
- time-to-accuracy: wall-clock seconds for a full training run to reach
  --target-accuracy (train + eval, compile excluded from neither — this is
  the end-to-end number a user experiences). Repeated --trials, median.
- sweep: img/s/chip at several per-chip batch sizes. The small-batch end
  is the 8-chip regime (global batch 512 on 8 chips = 64 rows/chip), so a
  1-chip sweep plus a psum-cost estimate yields the quantitative 8-chip
  scaling argument recorded in BASELINE.md.
- smoke: one supervised end-to-end gate on the default backend — train a
  few scanned blocks, eval, checkpoint save, then restore+resume in the
  same process; JSON verdict. Cheap enough to run every round; catches
  TPU-path regressions the CPU test suite can't.
- serve (also: `python bench.py serve`): load harness for the batched
  inference engine (distributedmnist_tpu/serve/). A closed-loop phase
  (--serve-clients back-to-back clients) measures serving capacity in
  images/sec/chip — the headline value — then an open-loop phase replays
  Poisson arrivals at each --serve-qps target, yielding the
  latency-vs-throughput table (p50/p95/p99 per point) plus
  batch-occupancy and backpressure-rejection counts. The engine warms
  its compile buckets first; steady state is asserted recompile-free
  (detail.recompiles_after_warmup).

The measurement runs in a supervised worker subprocess: TPU runtime claims
through tunneled/pooled backends can wedge forever before the first
program runs (observed on this host's axon relay: the claim leg
intermittently never completes while a fresh process succeeds). The
supervisor watches worker stderr/stdout activity and kills + retries a
worker that goes silent for --stall-timeout seconds, so one wedged claim
cannot turn the benchmark into a hang. --inline bypasses supervision.

vs_baseline: the reference publishes no numbers (BASELINE.md — empty mount,
published={}); the only quantitative anchor is the driver's north-star
target ">=99% in <30s on a v4-8 with near-linear scaling". For throughput
that implies roughly 10 epochs * 60k images / 30s / 8 chips = 2500
images/sec/chip and vs_baseline = value / 2500; for time-to-accuracy
vs_baseline = 30 / value. Either way >1.0 beats the target.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

from distributedmnist_tpu.analysis.locks import make_thread

TARGET_IPS_PER_CHIP = 2500.0
TARGET_WALL_S = 30.0

# The tuned time-to-accuracy recipe's cosine decay horizon, in steps —
# pinned to the horizon the 5-seed LR grid was collected under (20 epochs
# x 117 steps/epoch at global batch 512 on the 60k-row task). Without the
# pin, trainer.fit derives decay_steps from epochs x steps_per_epoch, so
# the --max-epochs trial-BUDGET knob would silently reshape the LR curve
# the tuning evidence justifies (round-4 verdict, weak #2).
TTA_DECAY_STEPS = 2340


def _mark(msg: str) -> None:
    """Progress marker on stderr — the supervisor's liveness signal."""
    print(f"bench: {msg}", file=sys.stderr, flush=True)


def _barrier_marked(sync, every: float = 15.0) -> None:
    """StepTimer.barrier with liveness marks emitted every `every` seconds
    from a helper thread while the device->host fetch is in flight."""
    import threading

    from distributedmnist_tpu.utils import StepTimer

    done = threading.Event()

    def beat():
        t0 = time.monotonic()
        while not done.wait(every):
            _mark(f"waiting on device ({time.monotonic() - t0:.0f}s)")

    t = make_thread(target=beat, name="bench-barrier-beat", daemon=True)
    t.start()
    try:
        StepTimer.barrier(sync)
    finally:
        done.set()
        t.join(timeout=5)


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    modes = ["throughput", "time-to-accuracy", "sweep", "smoke", "serve"]
    p.add_argument("mode_pos", nargs="?", choices=modes, default=None,
                   metavar="mode",
                   help="positional alias for --mode "
                        "(e.g. `python bench.py serve`)")
    p.add_argument("--mode", choices=modes, default=None)
    p.add_argument("--target-accuracy", type=float, default=0.99)
    p.add_argument("--data-dir", default=None,
                   help="real MNIST IDX/npz dir; synthetic fallback")
    p.add_argument("--max-epochs", type=int, default=20)
    p.add_argument("--global-batch", type=int, default=None,
                   help="global batch (default 512; sweep mode rejects "
                        "this — it takes --sweep-batches)")
    p.add_argument("--warmup-steps", type=int, default=None,
                   help="[throughput/sweep] compile/warmup steps "
                        "(default 20)")
    p.add_argument("--bench-steps", type=int, default=None,
                   help="[throughput/sweep] timed steps, >= 1 "
                        "(default: 8192 on tpu, 64 on cpu)")
    p.add_argument("--steps-per-call", type=int, default=None,
                   help="optimizer steps fused per dispatch via lax.scan "
                        "(default: 1 on cpu; on tpu 256 in throughput mode, "
                        "largest divisor <= 256 of the eval cadence in "
                        "time-to-accuracy mode)")
    p.add_argument("--model", default="lenet")
    p.add_argument("--dtype", default="float32")
    p.add_argument("--repeats", type=int, default=None,
                   help="[throughput/sweep] timed windows, median reported "
                        "(default: 3 on tpu, 1 on cpu)")
    p.add_argument("--trials", type=int, default=None,
                   help="[time-to-accuracy] full training runs, median "
                        "reported (default: 3 on tpu, 1 on cpu)")
    p.add_argument("--sweep-batches", default="64,128,256,512",
                   help="[sweep] comma-separated per-chip batch sizes")
    p.add_argument("--stall-timeout", type=float, default=300.0,
                   help="kill+retry the worker if it is silent this long")
    p.add_argument("--max-attempts", type=int, default=3,
                   help="worker attempts before giving up")
    p.add_argument("--inline", action="store_true",
                   help="run in-process (no supervisor subprocess)")
    p.add_argument("--serve-qps", default=None,
                   help="[serve] comma-separated open-loop Poisson QPS "
                        "targets (default: 50,200 on cpu; "
                        "1000,4000,16000 on tpu)")
    p.add_argument("--serve-duration", type=float, default=None,
                   help="[serve] seconds per load phase "
                        "(default: 2 on cpu, 10 on tpu)")
    p.add_argument("--serve-clients", type=int, default=None,
                   help="[serve] closed-loop concurrent clients "
                        "(default: 8 on cpu, 64 on tpu)")
    p.add_argument("--serve-rows", type=int, default=1,
                   help="[serve] images per request (default 1)")
    p.add_argument("--serve-max-batch", type=int, default=None,
                   help="[serve] rows per dispatch cap / top compile "
                        "bucket (default: 128 on cpu, 512 on tpu)")
    p.add_argument("--serve-max-wait-us", type=int, default=None,
                   help="[serve] batch-coalescing wait bound "
                        "(default 1000)")
    p.add_argument("--serve-queue-depth", type=int, default=None,
                   help="[serve] backpressure watermark in pending rows "
                        "(default 4096)")
    p.add_argument("--serve-max-inflight", type=int, default=None,
                   help="[serve] pipelined dispatch window for the "
                        "headline phase (default 4); the capacity phase "
                        "always also runs at 1 for the serial baseline")
    p.add_argument("--serve-slo-ms", type=float, default=None,
                   help="[serve] per-request latency SLO driving the "
                        "adaptive coalescing controller (default: none "
                        "— the controller is inert beyond its "
                        "arrival-rate fill cap)")
    p.add_argument("--no-adaptive", action="store_true", default=None,
                   help="[serve] pin the static coalescing wait instead "
                        "of the SLO-aware adaptive controller")
    p.add_argument("--serve-replicas", type=int, default=None,
                   help="[serve] engine replicas behind the fleet "
                        "dispatcher (serve/fleet.py); >= 2 adds the "
                        "fleet closed-loop leg (per-replica balance + "
                        "scaling efficiency vs one replica) and, with "
                        "--chaos, a replica-kill storm proving failover "
                        "rescues the killed replica's cohorts "
                        "(default 1)")
    p.add_argument("--serve-hedge", action="store_true", default=None,
                   help="[serve] enable hedged tail dispatch in the "
                        "fleet (duplicate overdue batches on a free "
                        "healthy sibling)")
    p.add_argument("--serve-infer-dtype", default=None,
                   choices=["float32", "bfloat16", "int8", "auto"],
                   help="[serve] serving precision for the headline "
                        "phases: float32 = training-identical reference "
                        "forward; bfloat16/int8 = the quantized+fused "
                        "fast path behind the parity gate; auto = "
                        "cheapest parity-passing variant (default "
                        "float32)")
    p.add_argument("--zipf", action="store_true", default=None,
                   help="[serve] add the hot-key leg (ISSUE 10): a "
                        "seeded Zipf-distributed request mix driven "
                        "closed-loop with the prediction cache + "
                        "single-flight front OFF then ON — hit ratio, "
                        "goodput ratio, p99 and device-dispatch "
                        "counts in one record, cached responses "
                        "parity-checked byte-identical against "
                        "computed ones")
    p.add_argument("--zipf-cache-off", action="store_true", default=None,
                   help="[serve] run the --zipf leg WITHOUT the cache-"
                        "on phase (a cache-off control record); "
                        "--baseline refuses deltas between cache-on "
                        "and cache-off zipf records the same way it "
                        "refuses cross-dtype ones")
    p.add_argument("--serve-cache", action="store_true", default=None,
                   help="[serve] wire the prediction cache + single-"
                        "flight front (serve/cache.py) into the --chaos "
                        "drill, with the registry's invalidation hook "
                        "installed so the forced rollback exercises the "
                        "epoch bump mid-storm; the leg then asserts the "
                        "poison-isolation ledger EXACT on a leader "
                        "basis — cached hits and collapsed followers "
                        "must not distort the injector's poisoned-set "
                        "accounting (ISSUE 12 satellite; the ROADMAP "
                        "follow-up PR 10 left open)")
    p.add_argument("--serve-cache-capacity", type=int, default=None,
                   help="[serve] prediction-cache capacity in entries "
                        "for the --zipf leg and --serve-cache chaos "
                        "drill (default 4096)")
    p.add_argument("--lowlat", action="store_true", default=None,
                   help="[serve] add the single-request low-latency "
                        "leg (ISSUE 14): one closed-loop client of "
                        "1-row requests through the coalescing path "
                        "vs the bypass fast lane (and the parity-gated "
                        "megakernel variant, models that have one) — "
                        "p50/p99 side by side (bar: p50 >= 1.5x "
                        "better, p99 no worse), fastpath span "
                        "attribution >= 0.95 on every over-SLO "
                        "request, zero recompiles")
    p.add_argument("--dtype-sweep", action="store_true", default=None,
                   help="[serve] add the inference fast-path leg: warm "
                        "+ parity-gate bf16 and int8 variants, then "
                        "run f32/bf16/int8 closed-loop back-to-back in "
                        "this process — one record with per-dtype "
                        "img/s/chip, parity metrics, bucket cost "
                        "tables and recompile counts (must stay 0)")
    p.add_argument("--cascade", action="store_true", default=None,
                   help="[serve] add the confidence-gated cascade leg "
                        "(ISSUE 17): warm + parity-gate int8, calibrate "
                        "the escalation threshold on the held-out batch "
                        "(composed-accuracy gate), then run "
                        "exact/fast/balanced (+ a stressed-threshold "
                        "point) closed-loop back-to-back on one seeded "
                        "stream — the goodput-vs-accuracy frontier with "
                        "measured end-to-end agreement, escalation "
                        "fractions, and recompile counts (must stay 0; "
                        "bars: cascade goodput >= 1.5x f32 at "
                        "agreement >= 0.995)")
    p.add_argument("--multimodel", action="store_true", default=None,
                   help="[serve] add the multi-tenant leg (ISSUE 18): "
                        "boot MLP and LeNet in ONE process behind the "
                        "global WFQ/EDF scheduler, measure the light "
                        "tenant's p99 solo, then add a heavy burst "
                        "tenant routed at the other model and report "
                        "the light tenant's mixed p99 (bar: <= 1.5x "
                        "solo), per-tenant SLO attainment, the "
                        "dispatch-share/weight-share fairness ratios "
                        "(bar: within [0.8, 1.25]), and the recompile "
                        "count (must stay 0 across both phases)")
    p.add_argument("--trace-replay", default=None, metavar="SPEC",
                   help="[serve] add the workload-realism leg "
                        "(ISSUE 20): replay a seeded deterministic "
                        "arrival trace (serve/workload.py spec string, "
                        "e.g. 'square:qps=60,burst=4,period=2,"
                        "duration=4') open-loop against a static "
                        "floor-provisioned config, reporting SLO "
                        "attainment and chip-seconds per million "
                        "served requests; with --autoscale the SAME "
                        "schedule replays again under the closed-loop "
                        "autoscaler and the record carries both phases "
                        "plus the scale-action log and flap audit")
    p.add_argument("--autoscale", action="store_true", default=None,
                   help="[serve] run the --trace-replay leg's second "
                        "phase under the closed-loop autoscaler "
                        "(serve/autoscale.py window actuator): "
                        "hysteresis + cooldown control over the live "
                        "saturation surface, scale moving only along "
                        "the pre-warmed bucket ladder (the recompile "
                        "bar still applies)")
    p.add_argument("--baseline", default=None, metavar="BENCH_serve.json",
                   help="[serve] a prior BENCH_serve_r*.json to diff "
                        "against: prints a delta table and REFUSES "
                        "(nonzero exit) when detail.host.device_kind "
                        "differs — CPU records must never masquerade as "
                        "TPU headlines (ROADMAP)")
    p.add_argument("--gateway", type=int, default=None, metavar="N",
                   help="[serve] bench the horizontal scale-out gateway "
                        "(ISSUE 19): boot `serve.py --gateway 1` then "
                        "`--gateway N` as real multi-process fleets and "
                        "drive them over HTTP, reporting closed-loop "
                        "gateway_scaling_efficiency (aggregate img/s at "
                        "N workers vs N x the 1-worker run), an "
                        "open-loop latency point, the Zipf sharded-"
                        "cache leg (each hot key served by exactly one "
                        "worker's cache, per-worker hit counters "
                        "asserted), a fleet-wide fresh-version promote "
                        "under load (zero mixed-epoch replies), "
                        "per-worker steady-window recompile counts "
                        "(must be 0) and the host_contention_x honesty "
                        "probe; the in-process legs (--zipf/--chaos/"
                        "...) are refused alongside it")
    p.add_argument("--chaos", action="store_true", default=None,
                   help="[serve] add the resilience leg: a seeded "
                        "fault-injection schedule (>=1%% request-sticky "
                        "poison dispatch faults + a forced mid-run "
                        "circuit-breaker trip) driven open-loop, "
                        "reporting availability, p99-under-faults, "
                        "shed/bisect/rollback counts and the "
                        "recompile count (must stay 0 — bisection "
                        "reuses existing bucket programs)")
    p.add_argument("--trace", action="store_true", default=None,
                   help="[serve] add the request-tracing leg (ISSUE 9): "
                        "open-loop traffic under an installed tracer, a "
                        "per-request stage-attribution table for every "
                        "over-SLO request (queue vs staging vs device "
                        "vs fetch vs rescue, unattributed residue "
                        "reported), and a Chrome trace-event artifact "
                        "written beside the BENCH_serve record; with "
                        "--chaos the chaos leg is traced too and the "
                        "record asserts failover-rescue and "
                        "bisect-split spans appear")
    p.add_argument("--swap-during-load", action="store_true", default=None,
                   help="[serve] add a closed-loop phase with a REAL "
                        "model roll mid-window: load + pre-warm a second "
                        "version while clients hammer the live one, "
                        "promote it atomically, and report swap-window "
                        "p99 vs steady-state p99 plus the post-warm "
                        "recompile count (must be 0)")
    p.add_argument("--artifact-dir", default=None,
                   help="[serve] directory for the BENCH_serve_r*.json "
                        "artifact (default: bench.py's own directory)")
    p.add_argument("--no-artifact", action="store_true", default=None,
                   help="[serve] don't write the BENCH_serve_r*.json "
                        "artifact")
    args = p.parse_args(argv)

    # Cheap arg-only validation FIRST: a deterministic usage error must
    # exit 2 immediately, not be retried in supervised subprocesses.
    if args.mode_pos is not None:
        if args.mode is not None and args.mode != args.mode_pos:
            p.error(f"positional mode {args.mode_pos!r} contradicts "
                    f"--mode {args.mode!r}")
        args.mode = args.mode_pos
    if args.mode is None:
        args.mode = "throughput"
    serve_flags = {"--serve-qps": args.serve_qps,
                   "--serve-duration": args.serve_duration,
                   "--serve-clients": args.serve_clients,
                   "--serve-max-batch": args.serve_max_batch,
                   "--serve-max-wait-us": args.serve_max_wait_us,
                   "--serve-queue-depth": args.serve_queue_depth,
                   "--serve-max-inflight": args.serve_max_inflight,
                   "--serve-slo-ms": args.serve_slo_ms,
                   "--no-adaptive": args.no_adaptive,
                   "--serve-replicas": args.serve_replicas,
                   "--serve-hedge": args.serve_hedge,
                   "--serve-infer-dtype": args.serve_infer_dtype,
                   "--zipf": args.zipf,
                   "--zipf-cache-off": args.zipf_cache_off,
                   "--lowlat": args.lowlat,
                   "--serve-cache": args.serve_cache,
                   "--serve-cache-capacity": args.serve_cache_capacity,
                   "--dtype-sweep": args.dtype_sweep,
                   "--cascade": args.cascade,
                   "--multimodel": args.multimodel,
                   "--trace-replay": args.trace_replay,
                   "--autoscale": args.autoscale,
                   "--baseline": args.baseline,
                   "--chaos": args.chaos,
                   "--trace": args.trace,
                   "--swap-during-load": args.swap_during_load,
                   "--gateway": args.gateway,
                   "--artifact-dir": args.artifact_dir,
                   "--no-artifact": args.no_artifact}
    if args.mode != "serve":
        given = [k for k, v in serve_flags.items() if v is not None]
        if given or args.serve_rows != 1:
            p.error(f"{', '.join(given) or '--serve-rows'} are serve-"
                    "mode flags; rejected rather than silently ignored")
    if args.mode == "serve":
        # Training measurement knobs are meaningless against the serving
        # engine; reject them (the repo-wide principle).
        if (args.warmup_steps is not None or args.bench_steps is not None
                or args.repeats is not None or args.trials is not None
                or args.steps_per_call is not None
                or args.global_batch is not None
                or args.data_dir is not None):
            p.error("serve mode takes --model/--dtype and the --serve-* "
                    "flags; training measurement flags belong to the "
                    "other modes")
        if args.serve_rows < 1:
            p.error("--serve-rows must be >= 1")
        if args.serve_max_batch is not None and args.serve_max_batch < 1:
            p.error("--serve-max-batch must be >= 1")
        if (args.serve_max_wait_us is not None
                and args.serve_max_wait_us < 0):
            p.error("--serve-max-wait-us must be >= 0 "
                    "(0 = no coalescing wait)")
        if args.serve_queue_depth is not None and args.serve_queue_depth < 1:
            p.error("--serve-queue-depth must be >= 1")
        if (args.serve_max_inflight is not None
                and args.serve_max_inflight < 1):
            p.error("--serve-max-inflight must be >= 1")
        if args.serve_duration is not None and args.serve_duration <= 0:
            p.error("--serve-duration must be > 0")
        if args.serve_clients is not None and args.serve_clients < 1:
            p.error("--serve-clients must be >= 1")
        if args.serve_qps is not None:
            try:
                args.serve_qps = sorted(
                    {float(q) for q in args.serve_qps.split(",")})
            except ValueError:
                p.error("--serve-qps must be comma-separated numbers")
            if not args.serve_qps or args.serve_qps[0] <= 0:
                p.error("--serve-qps targets must be positive")
        if args.serve_slo_ms is not None and args.serve_slo_ms <= 0:
            p.error("--serve-slo-ms must be > 0")
        if (args.serve_cache_capacity is not None
                and args.serve_cache_capacity < 1):
            p.error("--serve-cache-capacity must be >= 1")
        if args.zipf_cache_off and not args.zipf:
            p.error("--zipf-cache-off modifies the --zipf leg; pass "
                    "--zipf too")
        if args.autoscale and not args.trace_replay:
            p.error("--autoscale modifies the --trace-replay leg; pass "
                    "--trace-replay too (the autoscaler is only "
                    "measurable against a changing arrival rate)")
        if args.trace_replay is not None:
            # A malformed trace spec is a usage error NOW (exit 2) —
            # it must never replay *something else* minutes into a run.
            from distributedmnist_tpu.serve.workload import (
                parse_trace_spec)
            try:
                parse_trace_spec(args.trace_replay)
            except ValueError as e:
                p.error(f"--trace-replay: {e}")
        if args.serve_cache and not args.chaos:
            p.error("--serve-cache wires the cache front into the "
                    "--chaos drill (the hot-key cache leg is --zipf); "
                    "pass --chaos too")
        if args.serve_replicas is not None and args.serve_replicas < 1:
            p.error("--serve-replicas must be >= 1")
        if args.chaos:
            # Validate the PROGRAMMATIC chaos schedules at argparse time
            # (ISSUE 8 satellite): PR 5 gated user-typed --serve-faults
            # specs in serve.py, but the bench builds its own specs from
            # code — a failpoint-name typo there would die minutes into
            # the run (or worse, silently inject nothing pre-PR 5
            # hardening). Both template shapes (single-engine and
            # fleet replica-kill) are exercised with placeholder ids;
            # the runtime fills in the real live version / replica.
            from distributedmnist_tpu.serve.faults import parse_spec
            for template in (chaos_fault_spec("v0", None),
                             chaos_fault_spec("v0", "r0")):
                try:
                    parse_spec(template)
                except ValueError as e:
                    p.error(f"chaos schedule template is invalid: {e}")
        if args.gateway is not None:
            if args.gateway < 1:
                p.error("--gateway must be >= 1 workers")
            # The gateway bench drives real serve.py processes over
            # HTTP and runs its OWN zipf/promote/recompile legs; the
            # in-process legs read engine/registry state this process
            # does not hold. Rejected rather than silently ignored.
            for flag, val in (("--zipf", args.zipf),
                              ("--zipf-cache-off", args.zipf_cache_off),
                              ("--chaos", args.chaos),
                              ("--trace", args.trace),
                              ("--lowlat", args.lowlat),
                              ("--dtype-sweep", args.dtype_sweep),
                              ("--cascade", args.cascade),
                              ("--multimodel", args.multimodel),
                              ("--swap-during-load",
                               args.swap_during_load),
                              ("--serve-cache", args.serve_cache),
                              ("--serve-hedge", args.serve_hedge),
                              ("--trace-replay", args.trace_replay),
                              ("--autoscale", args.autoscale)):
                if val:
                    p.error(f"{flag} is an in-process serve leg; the "
                            "--gateway fleet bench has its own "
                            "sharded-cache, promote-under-load and "
                            "recompile legs")
            if args.serve_replicas is not None:
                p.error("--serve-replicas multiplies engines INSIDE "
                        "one process; with --gateway the workers are "
                        "the replication axis")
            if args.serve_qps is not None:
                p.error("--gateway picks its open-loop target from "
                        "the measured fleet capacity; --serve-qps "
                        "belongs to the in-process sweep")
        if args.baseline is not None:
            # An unreadable/shapeless baseline is a usage error NOW; the
            # device_kind REFUSAL must wait for the backend (the worker
            # compares against the live mesh before any load phase).
            try:
                with open(args.baseline) as f:
                    base = json.load(f)
            except (OSError, ValueError) as e:
                p.error(f"--baseline {args.baseline!r}: {e}")
            detail = base.get("detail") if isinstance(base, dict) else None
            host = (detail.get("host") if isinstance(detail, dict)
                    else None)
            kind = (host.get("device_kind") if isinstance(host, dict)
                    else None)
            if not kind:
                p.error(f"--baseline {args.baseline!r} has no "
                        "detail.host.device_kind — not a "
                        "BENCH_serve_r*.json artifact (pre-provenance "
                        "records can't be safely compared)")
        # LAST among the validations (its mkdir is a side effect; every
        # pure usage error above must fire first): fail a bad artifact
        # dir NOW — discovering it after the multi-minute load phases
        # would lose the whole record.
        if args.artifact_dir is not None and not args.no_artifact:
            if not args.artifact_dir:
                p.error("--artifact-dir needs a non-empty path "
                        "(or use --no-artifact)")
            try:
                os.makedirs(args.artifact_dir, exist_ok=True)
            except OSError as e:
                p.error(f"--artifact-dir {args.artifact_dir!r}: {e}")
    elif args.mode in ("throughput", "sweep"):
        if args.trials is not None:
            p.error("--trials is a time-to-accuracy flag; throughput/"
                    "sweep take --repeats")
        args.warmup_steps = (20 if args.warmup_steps is None
                             else args.warmup_steps)
        # bench_steps default is platform-dependent; resolved in the
        # worker once the backend is known.
        if args.bench_steps is not None and args.bench_steps < 1:
            p.error("--bench-steps must be >= 1")
        if args.repeats is not None and args.repeats < 1:
            p.error("--repeats must be >= 1")
        if args.mode == "sweep":
            if args.global_batch is not None:
                p.error("--global-batch is meaningless in sweep mode "
                        "(the curve comes from --sweep-batches); "
                        "rejected rather than silently ignored")
            try:
                args.sweep_batches = sorted(
                    {int(b) for b in args.sweep_batches.split(",")})
            except ValueError:
                p.error("--sweep-batches must be comma-separated ints")
            if not args.sweep_batches or args.sweep_batches[0] < 1:
                p.error("--sweep-batches must be positive")
    elif args.mode == "smoke":
        # smoke is a fixed-shape gate; measurement knobs are rejected,
        # not silently ignored (same principle as the other modes).
        if (args.warmup_steps is not None or args.bench_steps is not None
                or args.repeats is not None or args.trials is not None
                or args.steps_per_call is not None):
            p.error("smoke mode takes only --model/--dtype/--data-dir/"
                    "--global-batch; measurement flags belong to "
                    "throughput/sweep/time-to-accuracy")
    elif args.mode == "time-to-accuracy":
        # throughput-only knobs are rejected, not silently ignored
        # (--warmup-steps especially would read as LR warmup here)
        if (args.warmup_steps is not None or args.bench_steps is not None
                or args.repeats is not None):
            p.error("--warmup-steps/--bench-steps/--repeats are "
                    "throughput-mode flags; time-to-accuracy takes "
                    "--max-epochs, --trials and --steps-per-call")
        if args.trials is not None and args.trials < 1:
            p.error("--trials must be >= 1")
    if args.global_batch is None:
        args.global_batch = 512

    from distributedmnist_tpu.utils import supervise

    if not args.inline and not supervise.is_worker():
        # Last-resort fallback: if every attempt on the default backend
        # fails (e.g. the TPU runtime is down hard), record a
        # clearly-labelled CPU number (detail.backend says "cpu") rather
        # than nothing. Unsetting PALLAS_AXON_POOL_IPS disables this
        # host's TPU plugin registration (the repo-wide convention, cf.
        # conftest.py); JAX_PLATFORMS=cpu forces the backend.
        return supervise.run_supervised(
            os.path.abspath(__file__),
            list(sys.argv[1:] if argv is None else argv),
            accept=supervise.json_record_acceptor("metric"),
            stall_timeout=args.stall_timeout, attempts=args.max_attempts,
            fallback_env={"JAX_PLATFORMS": "cpu",
                          "PALLAS_AXON_POOL_IPS": None})
    if args.mode == "time-to-accuracy":
        return _time_to_accuracy(args)
    if args.mode == "smoke":
        return _smoke(args)
    if args.mode == "sweep":
        return _sweep(args)
    if args.mode == "serve":
        return _serve_gateway(args) if args.gateway else _serve(args)
    return _throughput(args)


class _Runner:
    """Shared backend/data/model setup + per-batch-size throughput
    measurement for the throughput and sweep modes."""

    def __init__(self, args):
        import jax
        import jax.numpy as jnp

        from distributedmnist_tpu import models
        from distributedmnist_tpu.data import load_mnist
        from distributedmnist_tpu.data.loader import DeviceDataset
        from distributedmnist_tpu.parallel import make_mesh
        from distributedmnist_tpu.utils import enable_compilation_cache

        enable_compilation_cache()
        # Recorded BEFORE the mode functions resolve the default: an
        # explicit --bench-steps is honored exactly; the default window
        # scales with the (possibly auto-deepened) block size so the
        # bounded in-flight cap always genuinely binds mid-window.
        self.user_bench_steps = args.bench_steps is not None
        self.devs = jax.devices()
        _mark(f"backend up: {len(self.devs)}x {self.devs[0].platform}")
        self.n_chips = len(self.devs)
        self.mesh = make_mesh(self.devs)
        self.dtype = (jnp.bfloat16 if args.dtype == "bfloat16"
                      else jnp.float32)
        # --data-dir is honored (real pixels cost the same as synthetic
        # ones, but silently dropping a user flag is worse than loading)
        data = load_mnist(args.data_dir, synthetic=args.data_dir is None,
                          seed=0)
        # Production defaults: packed pixel rows + flat optimizer update
        # (config.py pixel_format/flat_optimizer) — what fit() runs.
        self.ds = DeviceDataset(data, self.mesh, pixel_format="packed")
        self.model = models.build(args.model, dtype=self.dtype,
                                  platform=self.devs[0].platform)
        # CPU's collective rendezvous deadlocks under concurrent in-flight
        # programs (small host thread pool); TPU pipelines safely.
        self.sync_every_step = self.devs[0].platform == "cpu"

    def measure(self, args, gb: int, bench_steps: int) -> dict:
        """Median img/s/chip over repeated timed windows at global batch
        gb. Fresh state per call so every batch size starts identically."""
        import jax
        import jax.numpy as jnp

        from distributedmnist_tpu import optim
        from distributedmnist_tpu.data.loader import IndexStream
        from distributedmnist_tpu.parallel import replicated
        from distributedmnist_tpu.trainer import (init_state,
                                                  make_train_step)

        tx = optim.build("adam", 1e-3, flat=True)
        state = jax.device_put(
            init_state(jax.random.PRNGKey(0), self.model, tx,
                       jnp.zeros((1, 28, 28, 1))),
            replicated(self.mesh))
        step_fn = make_train_step(self.model, tx, self.mesh, mode="auto",
                                  dtype=self.dtype,
                                  pixel_format="packed")
        stream = IndexStream(self.ds.train_n, gb, seed=0, mesh=self.mesh)
        # Auto-deepened dispatch blocks: the fixed per-block cost
        # (dispatch + the relay round-trip of each drain/closing fetch)
        # is amortized over spc steps, and a block whose device time
        # sits at or below one relay RTT (~140 ms) pays it in the
        # measured rate — the round-4 sweep measured b=64 slower PER
        # STEP than b=128 purely from that fixed cost (SWEEP_r04.json,
        # round-4 verdict weak #1), and even the b=512 headline's
        # 256-step blocks (~125 ms) lost ~2-3% to it. The depth targets
        # 1024 x 512 per-chip rows per block (~0.5 s of device time on
        # the plateau, several RTTs deep), clamped to [256, 4096]:
        # measured same-window at b=512, spc 256/512/1024/2048 ->
        # 1.033/1.055/1.065/1.058 M img/s/chip (flat from 1024), and at
        # b=64, spc 2048 vs 4096 -> 0.1172 vs 0.1169 ms/step (flat).
        # The 256 floor only ever RAISES post-knee depths to the
        # production-default block size (those >=1 ms steps are already
        # RTT-immune either way); the scale-down with batch plus the
        # 4096 cap are what bound window length. The scan body compiles
        # once regardless of k, so deeper blocks cost no extra compile,
        # and each curve point RECORDS its steps_per_call. Production
        # fit()'s AUTO depth is additionally capped by the eval/
        # checkpoint cadence (trainer._pick_steps_per_call — block
        # edges must land on eval steps), so a cadence-200 training run
        # cannot reach this depth automatically; the --steps-per-call
        # knob can, and the sweep measures what the hardware does at
        # each batch under the depth a throughput-minded user would
        # pick.
        if args.steps_per_call is not None:
            spc = max(1, args.steps_per_call)
        elif self.sync_every_step:
            spc = 1
        else:
            per_chip_b = max(1, gb // self.n_chips)
            spc = min(4096, max(256, 1024 * 512 // per_chip_b))
        # Keep the production queueing regime honest under deepened
        # blocks (round-2 verdict, weak #5): the DEFAULT timed window
        # always spans 32 blocks — twice the 16-deep in-flight cap — so
        # the cap genuinely binds for the second half of every window
        # regardless of spc. An explicit --bench-steps is honored as
        # given (the CPU contract tests rely on tiny exact windows).
        if not self.user_bench_steps and not self.sync_every_step:
            bench_steps = max(bench_steps, 32 * spc)

        state_box = [state]

        last_mark = [time.monotonic()]
        # Same bounded dispatch window as trainer.fit() (max_inflight:
        # 1 on CPU, 16 on TPU), so the benchmark measures the exact
        # queueing regime production training runs — not a deeper,
        # slightly more favorable one (round-2 verdict, weak #5). For
        # the cap to actually bind mid-window the timed window must span
        # more than max_inflight blocks — the default TPU window is
        # scaled to 32 blocks (= 2x the cap) above, whatever spc is;
        # blocks 17..32 each wait on the oldest in-flight result before
        # dispatching.
        from collections import deque

        from distributedmnist_tpu.utils import StepTimer
        max_inflight = 1 if self.sync_every_step else 16
        inflight: deque = deque()

        def run(n_steps):
            """Run >= n_steps optimizer steps in blocks of spc; returns
            the exact step count executed."""
            metrics = None
            blocks = max(1, -(-n_steps // spc))
            for b in range(blocks):
                while len(inflight) >= max_inflight:
                    StepTimer.barrier(inflight.popleft())
                state_box[0], metrics = step_fn(
                    state_box[0], self.ds.train_x, self.ds.train_y,
                    stream.next_block(spc))
                inflight.append(metrics["loss"])
                if self.sync_every_step:
                    jax.block_until_ready(metrics["loss"])
                # On the synchronous CPU path the wall-clock lives in
                # THIS loop (a window takes minutes), so liveness marks
                # must come from here too or the supervisor reads the
                # silence as a stall and kills a healthy worker.
                if time.monotonic() - last_mark[0] > 15:
                    _mark(f"block {b + 1}/{blocks}")
                    last_mark[0] = time.monotonic()
            # The clock stops on a device->host VALUE fetch of the final
            # block's loss: its dependency chain covers every queued
            # block, and on pooled/tunneled backends block_until_ready
            # can return before execution completes (StepTimer.barrier) —
            # fetched bytes are the only proof the work happened. On TPU
            # dispatch is async and finishes in milliseconds, so the
            # wall-clock lives in THIS wait — _barrier_marked emits
            # liveness from a helper thread while it blocks.
            _barrier_marked(metrics["loss"])
            inflight.clear()   # final fetch's dependency chain covers all
            return blocks * spc

        _mark(f"b={gb}: compiling + warmup")
        run(args.warmup_steps)
        # Repeated timed windows, median reported: run-to-run variance on
        # a tunneled/pooled backend is substantial, and one window would
        # make the recorded number a lottery. 1 repeat on CPU (each
        # window is minutes there).
        repeats = args.repeats if args.repeats is not None \
            else (1 if self.sync_every_step else 3)
        windows = []
        n_run = 0
        for r in range(repeats):
            t0 = time.perf_counter()
            n_run = run(bench_steps)
            windows.append(n_run * gb
                           / (time.perf_counter() - t0) / self.n_chips)
            _mark(f"b={gb} window {r + 1}/{repeats}: "
                  f"{windows[-1]:.0f} img/s/chip")

        import statistics
        value = statistics.median(windows)
        return {"img_s_chip": value, "windows": windows,
                "bench_steps": n_run, "steps_per_call": spc,
                "step_ms": (1000 * gb / value / self.n_chips
                            if value else None)}


def _throughput(args) -> int:
    from distributedmnist_tpu.utils import round_up

    r = _Runner(args)
    gb = round_up(args.global_batch, r.n_chips)
    # >=8192-step windows amortize the closing value fetch (~140 ms on
    # the relay) to <0.02 ms/step; measure() additionally scales the
    # default window to 32 blocks — twice the 16-deep inflight cap — so
    # the production queueing barrier genuinely fires for the second
    # half of every window (round-3 advice) even when the block size is
    # auto-deepened at small per-chip batch.
    if args.bench_steps is None:
        args.bench_steps = 64 if r.sync_every_step else 8192
    m = r.measure(args, gb, args.bench_steps)
    value = m["img_s_chip"]
    print(json.dumps({
        "metric": "train_images_per_sec_per_chip",
        "value": round(value, 1),
        "unit": "images/sec/chip",
        "vs_baseline": round(value / TARGET_IPS_PER_CHIP, 3),
        "detail": {
            "model": args.model,
            "data": r.ds.source,
            "global_batch": gb,
            "n_chips": r.n_chips,
            "backend": r.devs[0].platform,
            "dtype": args.dtype,
            "bench_steps": m["bench_steps"],
            "steps_per_call": m["steps_per_call"],
            "step_ms": (round(m["step_ms"], 3)
                        if m["step_ms"] is not None else None),
            "windows_img_s_chip": [round(w, 1) for w in m["windows"]],
        },
    }))
    return 0


def _sweep(args) -> int:
    """Batch sweep + the 8-chip scaling estimate (BASELINE.md 'Scaling').

    Per-chip batch b on 1 chip is compute-identical to global batch
    8b on 8 chips; the only extra 8-chip cost is the gradient allreduce
    over ICI. predicted-8-chip img/s/chip at global 512 = measured
    img/s/chip at b=64, discounted by the modeled allreduce time.
    """
    r = _Runner(args)
    if args.bench_steps is None:
        args.bench_steps = 64 if r.sync_every_step else 8192
    curve = {}
    for b in args.sweep_batches:
        # b is the PER-CHIP batch; the measured global batch scales with
        # the visible chips so the curve means the same thing on a 1-chip
        # and an 8-chip host. Every point runs the same 32-block window
        # shape (measure() scales the default step count with the
        # auto-deepened block size), so the closing value fetch and the
        # in-flight cap behave identically across the curve instead of
        # taxing the small-batch points the strong-scaling prediction is
        # computed from.
        gb = b * r.n_chips
        m = r.measure(args, gb, args.bench_steps)
        curve[b] = {"img_s_chip": round(m["img_s_chip"], 1),
                    "step_ms": round(m["step_ms"], 4),
                    "steps_per_call": m["steps_per_call"]}

    # Gradient allreduce cost model (f32 grads, ring allreduce over ICI):
    # bytes on the wire per chip ~= 2 * grad_bytes * (n-1)/n.
    import jax
    import jax.numpy as jnp

    from distributedmnist_tpu import optim
    from distributedmnist_tpu.trainer import init_state
    # Param count via eval_shape: no device work mid-benchmark.
    state_shape = jax.eval_shape(
        lambda k: init_state(k, r.model, optim.build("adam", 1e-3),
                             jnp.zeros((1, 28, 28, 1))),
        jax.random.PRNGKey(0))
    n_params = sum(x.size for x in jax.tree.leaves(state_shape.params))
    grad_bytes = n_params * 4
    ici_gbps = 45.0   # conservative v5e ICI per-link bandwidth (GB/s)
    allreduce_ms = 2 * grad_bytes * (8 - 1) / 8 / (ici_gbps * 1e9) * 1e3
    # When the benchmark host itself has >1 chip, the measured step
    # ALREADY contains the real XLA-inserted allreduce — adding the
    # model on top would double-count it. The model term only bridges a
    # 1-chip measurement to the 8-chip prediction.
    modeled_ms = allreduce_ms if r.n_chips == 1 else 0.0
    # Strong scaling: global batch fixed at 8x the SMALLEST per-chip
    # batch (config 4's global 512 = 64/chip on 8 chips) — the per-chip
    # step is overhead-dominated there, so speedup is sub-linear.
    smallest = min(curve)
    strong_step_ms = curve[smallest]["step_ms"] + modeled_ms
    strong_img_s_chip = smallest / strong_step_ms * 1e3
    # Weak scaling: per-chip batch held at the curve's PEAK — the
    # operating point a user should run — not the largest measured size:
    # the curve bends down once the conv backward goes HBM-bound
    # (BASELINE.md "knee"), and anchoring past the knee would report the
    # worst point as "the" weak-scaling number. The only 8-chip overhead
    # at the peak is the allreduce, so efficiency is near 1 — the
    # north_star's "near-linear images/sec scaling to 8 chips".
    # Both anchors are REPORTED (round-4 advice): the peak is the
    # headline (the operating point), and the fixed largest-batch block
    # sits alongside it so a noisy argmax can't silently move the number
    # a reader compares across rounds.
    peak = max(curve, key=lambda b: curve[b]["img_s_chip"])
    largest = max(curve)

    def _weak_block(b: int) -> dict:
        step_ms = curve[b]["step_ms"] + modeled_ms
        img_s_chip = b / step_ms * 1e3
        return {
            "per_chip_batch": b,
            "global_batch_8chip": 8 * b,
            "step_ms": round(step_ms, 4),
            "img_s_chip": round(img_s_chip, 1),
            "global_img_s": round(8 * img_s_chip, 1),
            "efficiency_vs_1chip": round(
                img_s_chip / curve[b]["img_s_chip"], 4),
        }

    # Sensitivity band (round-2 verdict, weak #3): the prediction rests on
    # two transferred quantities — the modeled allreduce and the 1-chip
    # measured step time (whose fixed per-scan-iteration cost could shift
    # once XLA partitions the program). Recompute the prediction over
    # {1x, 2x} modeled allreduce and {0.8x, 1.0x, 1.2x} measured step
    # cost; the min/max bound is what the first real 8-chip run should
    # land inside. When the measuring host already has >1 chip the
    # allreduce is real (modeled_ms = 0) and only the cost band remains.
    def _band(base_ms: float, per_chip_b: int) -> list[float]:
        preds = [per_chip_b / (base_ms * f + ar * modeled_ms) * 1e3
                 for f in (0.8, 1.0, 1.2) for ar in (1, 2)]
        return [round(min(preds), 1), round(max(preds), 1)]

    prediction_range = {
        "strong_img_s_chip": _band(curve[smallest]["step_ms"], smallest),
        "weak_img_s_chip": _band(curve[peak]["step_ms"], peak),
        "grid": {"allreduce_x": [1, 2], "fixed_cost_x": [0.8, 1.0, 1.2]},
    }
    value = strong_img_s_chip
    print(json.dumps({
        "metric": "predicted_8chip_images_per_sec_per_chip",
        "value": round(value, 1),
        "unit": "images/sec/chip",
        "vs_baseline": round(value / TARGET_IPS_PER_CHIP, 3),
        "detail": {
            "model": args.model,
            "backend": r.devs[0].platform,
            "dtype": args.dtype,
            "n_chips_measured": r.n_chips,
            "curve_img_s_chip": {str(k): v for k, v in curve.items()},
            "n_params": n_params,
            "grad_bytes_f32": grad_bytes,
            "ici_assumed_gbps": ici_gbps,
            "allreduce_ms_est": round(allreduce_ms, 4),
            "allreduce_modeled": r.n_chips == 1,
            "strong_scaling": {
                "per_chip_batch": smallest,
                "global_batch_8chip": 8 * smallest,
                "step_ms": round(strong_step_ms, 4),
                "img_s_chip": round(strong_img_s_chip, 1),
                "global_img_s": round(8 * strong_img_s_chip, 1),
            },
            "weak_scaling": {"anchor": "peak", **_weak_block(peak)},
            "weak_scaling_at_largest": {"anchor": "largest",
                                        **_weak_block(largest)},
            "prediction_range": prediction_range,
        },
    }))
    return 0


def _smoke(args) -> int:
    """End-to-end gate on the default backend: train + eval + checkpoint
    save, then restore + resume. One JSON verdict line."""
    import logging
    import tempfile

    import jax

    from distributedmnist_tpu import trainer
    from distributedmnist_tpu.config import Config
    from distributedmnist_tpu.utils import round_up

    logging.basicConfig(level=logging.INFO, stream=sys.stderr)
    devs = jax.devices()
    _mark(f"backend up: {len(devs)} devices")
    gb = round_up(min(args.global_batch, 256), len(devs))
    legs = []
    with tempfile.TemporaryDirectory() as ckpt_dir:
        cfg = Config(model=args.model, optimizer="adam",
                     learning_rate=1e-3, synthetic=args.data_dir is None,
                     data_dir=args.data_dir, batch_size=gb,
                     steps=64, eval_every=32, log_every=0,
                     target_accuracy=None, checkpoint_dir=ckpt_dir,
                     checkpoint_every=32, dtype=args.dtype)
        out1 = trainer.fit(cfg)
        assert out1["steps"] == 64, out1
        legs += ["train", "eval", "checkpoint-save"]
        _mark("first run done; restoring + resuming")
        out2 = trainer.fit(cfg.replace(steps=96))
        assert out2["restored"] is True, out2
        assert out2["steps"] == 96, out2
        legs.append("restore-resume")
        # Accuracy floor (round-2 verdict, weak #6): a silent numerical
        # regression that still completes 96 steps must FAIL the gate,
        # not pass it. 96 adam steps at b<=256 sit ~0.95 on the calibrated
        # task for both models; 0.85 is a loose floor, not a target.
        assert out2["test_accuracy"] >= 0.85, (
            f"smoke accuracy floor: {out2['test_accuracy']:.4f} < 0.85")
        legs.append("accuracy-floor")
    print(json.dumps({
        "metric": "tpu_smoke",
        "value": 1.0,
        "unit": "ok",
        "vs_baseline": 1.0,
        "detail": {
            "backend": devs[0].platform,
            "n_chips": len(devs),
            "model": args.model,
            "data": out2["data"],
            "legs": legs,
            "final_accuracy": round(out2["test_accuracy"], 4),
            # out1's number: the resume run fits in a single dispatch
            # block, which never opens a throughput window. It is a
            # 64-step window dominated by in-loop eval/checkpoint fetch
            # boundaries — an order of magnitude BELOW the steady-state
            # number (THROUGHPUT_r*.json); the caveat fields mark it so
            # nobody diffs it against the real benchmark (round-4
            # verdict, weak #4).
            "images_per_sec_per_chip":
                round(out1["images_per_sec_per_chip"], 1),
            "short_window": True,
            "window_steps": 64,
        },
    }))
    return 0


def _serve_closed_loop(batcher, metrics, reqs, clients: int,
                       duration: float) -> dict:
    """Closed loop: each client waits for its result before the next
    submit, so concurrency == clients and the batcher coalesces to its
    natural occupancy — serving capacity, not queue-melt throughput.
    `reqs` is a list of pre-built request arrays each client cycles
    through (one entry = the classic fixed-size load; a seeded
    mixed-size list = the ragged-arrival leg). A short unmeasured ramp
    absorbs phase cold-start (client thread spawn, allocator warmup) so
    back-to-back phases compare fairly."""
    import threading

    from distributedmnist_tpu.serve import Rejected

    client_errors: list = []
    ramp = min(0.5, duration * 0.2)
    stop_at = time.monotonic() + ramp + duration

    def client(offset: int):
        k = offset                  # stagger starts so the size mix
        while time.monotonic() < stop_at:   # interleaves across clients
            try:
                batcher.submit(reqs[k % len(reqs)]).result(timeout=120)
                k += 1
            except Rejected:
                time.sleep(0.001)   # shed: brief client backoff
            except BaseException as e:
                # A dead client thread deflates the capacity headline
                # silently; record and fail the bench after join.
                client_errors.append(e)
                return

    threads = [make_thread(target=client, args=(i,),
                           name=f"bench-client-{i}", daemon=True)
               for i in range(clients)]
    for t in threads:
        t.start()
    time.sleep(ramp)
    metrics.reset()                  # measurement starts post-ramp
    for t in threads:
        t.join()
    if client_errors:
        raise RuntimeError(
            f"{len(client_errors)} of {clients} closed-loop clients "
            "died; the capacity headline would be measured against a "
            "degraded pool") from client_errors[0]
    # Clients unblock at set_result, BEFORE the completion thread
    # records the batch's metrics — wait for the in-flight count (which
    # drops only after metrics land) so the final batch's samples are in
    # THIS snapshot, not leaked past the next phase's reset().
    _drain_or_die(batcher, timeout=120)
    return metrics.snapshot()


def _drain_or_die(batcher, timeout: float) -> None:
    """Bounded wait for the pipeline to fully drain (empty queue AND
    zero in-flight, which the batcher guarantees means every future
    resolved and every metrics record landed). A wedged pipeline fails
    the bench instead of hanging it."""
    deadline = time.monotonic() + timeout
    while batcher.pending_rows() or batcher.inflight_batches():
        if time.monotonic() > deadline:
            raise RuntimeError(
                f"serve pipeline failed to drain within {timeout:g}s "
                f"({batcher.pending_rows()} rows pending, "
                f"{batcher.inflight_batches()} batches in flight) — "
                "wedged dispatch/fetch?")
        time.sleep(0.005)


def _serve_open_loop(batcher, metrics, reqs, qps: float, duration: float,
                     max_wait_us: int) -> tuple[int, dict]:
    """Open loop: Poisson arrivals at the target QPS, cycling through
    the `reqs` request list (fixed-size or the ragged mix). Submissions
    don't wait for results (metrics record latency at completion), so
    queue growth and backpressure rejections are visible exactly when
    the target exceeds capacity. Returns (submitted, metrics snapshot)
    after the queue and in-flight window have drained."""
    import random

    from distributedmnist_tpu.serve import Rejected

    arrivals = random.Random(0)
    metrics.reset()
    t_end = time.monotonic() + duration
    next_t = time.monotonic()
    submitted = 0
    while next_t < t_end:
        now = time.monotonic()
        if next_t > now:
            time.sleep(next_t - now)
        try:
            batcher.submit(reqs[submitted % len(reqs)])
            submitted += 1
        except Rejected:
            pass                # recorded by metrics
        next_t += arrivals.expovariate(qps)
    _drain_or_die(batcher, timeout=120 + max_wait_us / 1e6)
    return submitted, metrics.snapshot()


def _serve_ragged_leg(router, metrics, factory, make_batcher,
                      pipelined: int, clients: int, duration: float,
                      qps: float, max_wait_us: int,
                      max_size: int = 20) -> dict:
    """The batch-former proof leg (ISSUE 4 acceptance): one FIXED
    mixed-size request stream — sizes uniform on {1..min(20, max_batch)},
    seeded, identical across sub-phases — replayed closed-loop (capacity
    + waste at natural occupancy) and open-loop (waste under Poisson
    arrivals at a sub-capacity rate), each with the cost-model batch
    former OFF (pad the whole drain to one covering bucket) and ON
    (split when the measured cost table says split beats pad). The
    scheduler's win is then a measured padding_waste_ratio reduction at
    no-worse goodput, not a claim. Adaptation is pinned off in BOTH
    sub-phases so the comparison isolates the former.

    Both sub-phases coalesce with the SAME wait, derived from the
    measured cost table rather than the serving default: one full-batch
    service time (fitted overhead + per_row * top_bucket — the classic
    batching balance point, and itself an application of 'exploit the
    predictable per-program costs'). A 1 ms wait on a host whose batch
    service time is tens of ms never assembles a multi-request drain,
    and a drain of ONE request can neither pad interestingly nor be
    split at all — the former would be measured on traffic that never
    exercises it."""
    import numpy as np

    from distributedmnist_tpu.serve.scheduler import fit_dispatch_cost

    max_size = min(max_size, factory.max_batch)
    rng = np.random.default_rng(7)
    sizes = [int(s) for s in rng.integers(1, max_size + 1, 256)]
    reqs = [rng.integers(0, 256, (n, 28, 28, 1), dtype=np.uint8)
            for n in sizes]
    overhead_s, per_row_s = fit_dispatch_cost(router.bucket_costs())
    ragged_wait_us = max(max_wait_us, int(
        (overhead_s + per_row_s * factory.buckets[-1]) * 1e6))

    def phase(split: bool) -> dict:
        tag = "former-on" if split else "former-off"
        b = make_batcher(pipelined, split=split, adaptive=False,
                         wait_us=ragged_wait_us)
        try:
            _mark(f"ragged closed loop [{tag}]: {clients} clients "
                  f"x {duration:.0f}s, sizes U[1,{max_size}], "
                  f"wait {ragged_wait_us}us")
            closed = _serve_closed_loop(b, metrics, reqs, clients,
                                        duration)
            _mark(f"ragged open loop [{tag}] qps={qps:g}")
            _, openl = _serve_open_loop(b, metrics, reqs, qps, duration,
                                        ragged_wait_us)
        finally:
            b.stop()
        keep = ("rows_per_sec", "requests_per_sec", "latency_ms",
                "padding_waste_ratio", "padded_rows", "dispatched_rows",
                "bucket_dispatches", "mean_rows_per_batch", "batches",
                "rejected_requests")
        return {"closed": {k: closed[k] for k in keep},
                "open": {k: openl[k] for k in keep}}

    off = phase(split=False)
    on = phase(split=True)

    def ratio(a, b):
        return round(a / b, 3) if a is not None and b else None

    leg = {
        "sizes": f"uniform[1..{max_size}]",
        "seed": 7,
        "open_loop_qps": qps,
        "coalesce_wait_us": ragged_wait_us,
        "former_off": off,
        "former_on": on,
        # the headline pair: FLOPs burned on padding, and goodput —
        # split must cut the former without costing the latter
        "closed_waste_off": off["closed"]["padding_waste_ratio"],
        "closed_waste_on": on["closed"]["padding_waste_ratio"],
        "closed_waste_reduction_x": ratio(
            off["closed"]["padding_waste_ratio"],
            on["closed"]["padding_waste_ratio"]),
        "closed_goodput_ratio": ratio(on["closed"]["rows_per_sec"],
                                      off["closed"]["rows_per_sec"]),
        "open_waste_off": off["open"]["padding_waste_ratio"],
        "open_waste_on": on["open"]["padding_waste_ratio"],
        "open_waste_reduction_x": ratio(
            off["open"]["padding_waste_ratio"],
            on["open"]["padding_waste_ratio"]),
    }
    _mark(f"ragged: closed waste {leg['closed_waste_off']} -> "
          f"{leg['closed_waste_on']} "
          f"({leg['closed_waste_reduction_x']}x reduction), goodput "
          f"ratio {leg['closed_goodput_ratio']}; open waste "
          f"{leg['open_waste_off']} -> {leg['open_waste_on']}")
    return leg


def _serve_fleet_leg(fleet, metrics, make_batcher, clients: int,
                     duration: float, req) -> dict:
    """The replica-scaling proof leg (ISSUE 6): the SAME fleet measured
    closed-loop twice — first with every replica but r0 drained (the
    honest replicas=1 baseline: same engines, same warm state, no
    rebuild, and the drain/rejoin admin path exercised under load),
    then with the full fleet — reporting per-replica dispatch balance
    (the cost-aware pick must spread within 25%) and scaling efficiency
    (fleet capacity over N x single-replica capacity; ~1.0 on disjoint
    mesh slices, necessarily < 1 for logical replicas sharing one
    chip's compute, which the record's provenance block discloses)."""
    ids = fleet.replica_ids()
    for rid in ids[1:]:
        fleet.drain(rid)
    b = make_batcher(fleet.per_replica_inflight)
    try:
        _mark(f"fleet closed loop [1/{len(ids)} replicas]: {clients} "
              f"clients x {duration:.0f}s")
        single = _serve_closed_loop(b, metrics, [req], clients, duration)
    finally:
        b.stop()
    for rid in ids[1:]:
        fleet.rejoin(rid)
    before = {r["id"]: r["dispatched_batches"]
              for r in fleet.snapshot()["replicas"]}
    b = make_batcher(fleet.max_inflight_total)
    try:
        _mark(f"fleet closed loop [{len(ids)} replicas]: {clients} "
              f"clients x {duration:.0f}s")
        full = _serve_closed_loop(b, metrics, [req], clients, duration)
    finally:
        b.stop()
    counts = {r["id"]: r["dispatched_batches"] - before[r["id"]]
              for r in fleet.snapshot()["replicas"]}
    lo, hi = min(counts.values()), max(counts.values())
    balance_ratio = round(hi / lo, 3) if lo else None
    single_rate = single["rows_per_sec"]
    efficiency = (round(full["rows_per_sec"]
                        / (len(ids) * single_rate), 3)
                  if single_rate else None)
    leg = {
        "replicas": len(ids),
        "single_replica_rows_per_sec": single_rate,
        "fleet_rows_per_sec": full["rows_per_sec"],
        "scaling_efficiency": efficiency,
        "per_replica_dispatches": counts,
        "dispatch_balance_ratio": balance_ratio,
        # ISSUE 6 acceptance: per-replica dispatch counts within 25%
        "balance_ok": (balance_ratio is not None
                       and balance_ratio <= 1.25),
        "single_latency_ms": single["latency_ms"],
        "fleet_latency_ms": full["latency_ms"],
    }
    _mark(f"fleet: {single_rate:.0f} -> {full['rows_per_sec']:.0f} "
          f"rows/s over {len(ids)} replicas (efficiency {efficiency}), "
          f"dispatch balance {counts} (ratio {balance_ratio})")
    return leg


def _serve_dtype_sweep(registry, router, factory, metrics, make_batcher,
                       compiles, pipelined: int, clients: int,
                       duration: float) -> dict:
    """The inference fast-path proof leg (ISSUE 7 acceptance): warm +
    parity-gate the bf16 and int8 variants of the live version, then
    run float32 / bfloat16 / int8 closed-loop BACK-TO-BACK in this one
    process — same request stream, same batcher knobs, same silicon —
    so the per-dtype img/s/chip numbers are a controlled comparison
    inside one record, not a cross-run guess.

    The request stream is a seeded mixed-size mix (uniform sizes up to
    32) so drains land across the bucket ladder's mid rungs, where the
    fast path's win actually lives; every sub-phase coalesces with the
    SAME cost-derived wait (one full-batch service time off the f32
    table — the ragged leg's balance point). Each dtype phase asserts
    its own recompile count (the variants were fully pre-warmed and
    gate-verified, so steady state must stay 0), and the leg reports
    each variant's parity verdict + per-dtype bucket cost table — the
    same tables the PR 4 batch former and the PR 6 hedge threshold
    re-price from at promote time. A variant the gate REFUSED shows up
    as skipped-with-reason, never as a measured leg."""
    import numpy as np

    from distributedmnist_tpu.serve.scheduler import fit_dispatch_cost

    version = registry.live_version()
    restore_dtype = router.live_infer_dtype() or "float32"
    max_size = min(32, factory.max_batch)
    rng = np.random.default_rng(11)
    sizes = [int(s) for s in rng.integers(1, max_size + 1, 256)]
    reqs = [rng.integers(0, 256, (n, 28, 28, 1), dtype=np.uint8)
            for n in sizes]
    warmup_events = 0
    skipped = {}
    for dt in ("bfloat16", "int8"):
        # Warmup-compile accounting by COUNTER DELTA around the call,
        # not by the variant's own bookkeeping: a variant the headline
        # activation already warmed compiles nothing here (delta 0 —
        # its events predate the caller's steady_from snapshot and
        # counting them again would over-subtract into a negative
        # recompile figure), while a gate-REFUSED variant's engines
        # still compiled before the gate ran and those events must be
        # excluded from the steady window even though the build raised.
        before_compiles = compiles.snapshot()
        try:
            registry.add_variant(version, dt)
        except Exception as e:
            # the refusal (with its parity verdict) is the leg's
            # result for this dtype — never a silently-measured one
            skipped[dt] = f"{type(e).__name__}: {e}"
            _mark(f"dtype sweep: {dt} variant REFUSED ({e})")
        warmup_events += compiles.snapshot() - before_compiles
    # f32 cost table exists (bootstrap warmup); derive the shared wait
    overhead_s, per_row_s = fit_dispatch_cost(router.bucket_costs())
    wait_us = max(2000, int(
        (overhead_s + per_row_s * factory.buckets[-1]) * 1e6))
    n_chips = factory.total_chips
    mv = registry.get(version)
    legs = {}
    for dt in ("float32", "bfloat16", "int8"):
        if dt in skipped:
            legs[dt] = {"skipped": skipped[dt]}
            continue
        registry.promote(version, infer_dtype=dt)
        steady = compiles.snapshot()
        b = make_batcher(pipelined, adaptive=False, wait_us=wait_us)
        try:
            _mark(f"dtype sweep closed loop [{dt}]: {clients} clients "
                  f"x {duration:.0f}s, sizes U[1,{max_size}], wait "
                  f"{wait_us}us")
            closed = _serve_closed_loop(b, metrics, reqs, clients,
                                        duration)
        finally:
            b.stop()
        vi = mv.variants.get(dt)
        legs[dt] = {
            "img_s_chip": round(closed["rows_per_sec"] / n_chips, 1),
            "requests_per_sec": closed["requests_per_sec"],
            "latency_ms": closed["latency_ms"],
            "mean_rows_per_batch": closed["mean_rows_per_batch"],
            "by_dtype": closed["by_dtype"],
            # steady state under an ALREADY-warmed, gate-verified
            # variant: any nonzero count here is a jit cache that
            # failed to key on dtype
            "recompiles_after_warmup": compiles.snapshot() - steady,
            "bucket_cost_ms": {str(bk): round(c * 1e3, 3)
                               for bk, c in sorted(
                                   router.bucket_costs().items())},
            "parity": vi.parity if vi is not None else None,
        }
        _mark(f"dtype sweep [{dt}]: {legs[dt]['img_s_chip']} img/s/chip "
              f"(p99 {closed['latency_ms']['p99']} ms, "
              f"{legs[dt]['recompiles_after_warmup']} recompiles)")
    registry.promote(version, infer_dtype=restore_dtype)
    f32 = legs.get("float32", {}).get("img_s_chip")
    speedups = {dt: (round(leg["img_s_chip"] / f32, 3)
                     if f32 and "img_s_chip" in leg else None)
                for dt, leg in legs.items() if dt != "float32"}
    measured = {dt: s for dt, s in speedups.items() if s is not None}
    best = max(measured, key=measured.get) if measured else None
    leg = {
        "sizes": f"uniform[1..{max_size}]",
        "seed": 11,
        "coalesce_wait_us": wait_us,
        "clients": clients,
        "duration_s": duration,
        "legs": legs,
        "speedup_vs_float32": speedups,
        "best_dtype": best,
        "best_speedup": measured.get(best),
        # the variants' legitimate warmup compiles, for the caller's
        # whole-run recompile exclusion (same treatment as --swap's)
        "variant_warmup_compile_events": warmup_events,
    }
    _mark(f"dtype sweep: speedups vs f32 {speedups} (best {best})")
    return leg


def _serve_cascade_leg(registry, router, factory, metrics, make_batcher,
                       compiles, pipelined: int, clients: int,
                       duration: float) -> dict:
    """The confidence-gated cascade leg (ISSUE 17 acceptance): warm +
    parity-gate the int8 variant, calibrate the cascade's confidence
    threshold on the held-out batch (the composed-accuracy gate), then
    drive ONE seeded mixed-size request stream closed-loop through the
    three accuracy classes back-to-back — `exact` (the f32-only
    baseline), `fast` (the int8-only ceiling), `balanced` (the cascade)
    — plus a stressed operating point with the threshold overridden to
    the stream's median cheap-stage margin, so the record shows the
    goodput-vs-accuracy FRONTIER, not one point.

    Each phase runs on its own batcher with the same cost-derived
    coalescing wait and asserts its own recompile count stays 0: the
    cascade's escalation re-submissions ride the normal coalescing path
    through programs the warmup already compiled, so a nonzero count
    here means the cascade leaked a new jit key. End-to-end argmax
    agreement vs the f32 baseline is MEASURED on the stream (not
    inferred from the gate), and the escalation fraction comes from the
    serving metrics of each phase's own window. A gate refusal is the
    leg's result (skipped-with-reason), never a silently-measured
    cascade."""
    import numpy as np

    from distributedmnist_tpu.serve.cascade import (CascadeFront,
                                                    softmax_margin)
    from distributedmnist_tpu.serve.scheduler import fit_dispatch_cost

    version = registry.live_version()
    restore_dtype = router.live_infer_dtype() or "float32"
    max_size = min(32, factory.max_batch)
    rng = np.random.default_rng(13)
    sizes = [int(s) for s in rng.integers(1, max_size + 1, 256)]
    reqs = [rng.integers(0, 256, (n, 28, 28, 1), dtype=np.uint8)
            for n in sizes]
    # Warmup-compile accounting by counter delta (same treatment as the
    # dtype sweep): the int8 variant build + the calibration pass are
    # legitimate off-hot-path warmup, excluded from the caller's
    # whole-run recompile check via variant_warmup_compile_events.
    before_compiles = compiles.snapshot()
    try:
        registry.add_variant(version, "int8")
        state = registry.enable_cascade(version)
    except Exception as e:
        warmup = compiles.snapshot() - before_compiles
        _mark(f"cascade leg: REFUSED ({e})")
        return {"skipped": f"{type(e).__name__}: {e}",
                "variant_warmup_compile_events": warmup}
    warmup_events = compiles.snapshot() - before_compiles
    calibrated = dict(state.calibration)
    _mark(f"cascade leg: calibrated threshold "
          f"{state.threshold:.6f} (cheap {state.cheap_dtype}, gate "
          f"composed_agreement {calibrated.get('composed_agreement')}, "
          f"escalation {calibrated.get('escalation_fraction')})")
    # The host's physical ceiling for this frontier: the warmup-
    # measured full-bucket cost ratio between the f32 reference and
    # the cheap stage. The 1.5x goodput bar presumes a host where the
    # cheap variant's compute win is at least that large (TPU int8,
    # or the r06-class CPU where int8 measured 2.35x); on a host
    # whose ceiling sits BELOW the bar (e.g. weight-only int8 on a
    # 1-core XLA-CPU box — PARITY.md's route disclosure) no cascade
    # can clear it, and the record says so explicitly instead of
    # letting an unreachable bar read as a cascade regression.
    top = factory.buckets[-1]
    registry.promote(version, infer_dtype=state.cheap_dtype)
    cheap_costs = dict(router.bucket_costs())
    registry.promote(version, infer_dtype="float32")
    f32_costs = dict(router.bucket_costs())
    compute_ceiling = (round(f32_costs[top] / cheap_costs[top], 3)
                       if cheap_costs.get(top) and f32_costs.get(top)
                       else None)
    # f32 cost table exists (bootstrap warmup); derive the shared wait
    overhead_s, per_row_s = fit_dispatch_cost(f32_costs)
    wait_us = max(2000, int(
        (overhead_s + per_row_s * factory.buckets[-1]) * 1e6))
    n_chips = factory.total_chips
    _mark(f"cascade leg: host compute ceiling {compute_ceiling}x "
          f"(f32 {round(f32_costs[top] * 1e3, 2)} ms vs "
          f"{state.cheap_dtype} {round(cheap_costs[top] * 1e3, 2)} ms "
          f"per {top}-row bucket)")

    # -- measured end-to-end agreement + the stressed threshold -------
    # One warmed batcher, pairwise-concurrent submits: every probe
    # request runs through all three classes, giving (a) the MEASURED
    # argmax agreement of the cascade and the int8 ceiling against the
    # f32 baseline on this stream — the frontier's accuracy axis — and
    # (b) the cheap-stage margins whose median becomes the stressed
    # phase's override threshold (~half the rows escalate there).
    probe = reqs[:64]
    agree = {"fast": 0, "balanced": 0}
    total_rows = 0
    margins: list = []
    b = make_batcher(pipelined, adaptive=False, wait_us=wait_us)
    front = CascadeFront(b, b, router, registry, metrics=metrics)
    try:
        for x in probe:
            futs = {cls: front.submit(x, accuracy_class=cls)
                    for cls in ("exact", "fast", "balanced")}
            out = {cls: f.result(timeout=120) for cls, f in futs.items()}
            ref = out["exact"].argmax(axis=1)
            for cls in ("fast", "balanced"):
                agree[cls] += int((out[cls].argmax(axis=1) == ref).sum())
            margins.extend(
                np.asarray(softmax_margin(out["fast"])).tolist())
            total_rows += x.shape[0]
        _drain_or_die(b, timeout=120)
    finally:
        b.stop()
    agreement = {cls: round(n / total_rows, 5) for cls, n in agree.items()}
    stressed_threshold = float(min(0.999999, max(
        1e-9, float(np.median(np.asarray(margins))))))
    _mark(f"cascade agreement vs f32 on {total_rows} rows: "
          f"balanced {agreement['balanced']}, fast {agreement['fast']}; "
          f"median cheap-stage margin {stressed_threshold:.6f}")

    # -- the frontier: four closed-loop phases on one stream ----------
    phases = [("exact", None), ("fast", None), ("balanced", None),
              ("balanced_stressed", stressed_threshold)]
    legs = {}
    for name, override in phases:
        cls = "balanced" if name == "balanced_stressed" else name
        if override is not None:
            try:
                # judged by the SAME composed gate as calibration —
                # a refused override is reported, never measured
                registry.set_cascade_threshold(version, override)
            except RuntimeError as e:
                legs[name] = {"skipped": f"{type(e).__name__}: {e}"}
                _mark(f"cascade [{name}]: override REFUSED ({e})")
                continue
        steady = compiles.snapshot()
        b = make_batcher(pipelined, adaptive=False, wait_us=wait_us)
        front = CascadeFront(b, b, router, registry, metrics=metrics,
                             default_class=cls)
        try:
            _mark(f"cascade closed loop [{name}]: {clients} clients x "
                  f"{duration:.0f}s, sizes U[1,{max_size}], wait "
                  f"{wait_us}us")
            closed = _serve_closed_loop(front, metrics, reqs, clients,
                                        duration)
        finally:
            b.stop()
        ca = closed.get("cascade", {})
        legs[name] = {
            "accuracy_class": cls,
            "threshold": (override if override is not None
                          else state.threshold),
            "img_s_chip": round(closed["rows_per_sec"] / n_chips, 1),
            "requests_per_sec": closed["requests_per_sec"],
            "latency_ms": closed["latency_ms"],
            "mean_rows_per_batch": closed["mean_rows_per_batch"],
            "by_dtype": closed["by_dtype"],
            "stage_rows": ca.get("stage_rows"),
            "escalation_fraction": ca.get("escalation_fraction"),
            "degraded_requests": ca.get("degraded_requests"),
            # steady state over pre-warmed, gate-verified programs:
            # escalation re-submission must never mint a new jit key
            "recompiles_after_warmup": compiles.snapshot() - steady,
        }
        _mark(f"cascade [{name}]: {legs[name]['img_s_chip']} img/s/chip "
              f"(p99 {closed['latency_ms']['p99']} ms, escalation "
              f"{legs[name]['escalation_fraction']}, "
              f"{legs[name]['recompiles_after_warmup']} recompiles)")
    # restore the calibrated threshold (the stressed override is a
    # bench operating point, not the state a later leg should inherit)
    final_state = registry.enable_cascade(version)
    registry.promote(version, infer_dtype=restore_dtype)

    f32 = legs.get("exact", {}).get("img_s_chip")
    goodput = {name: (round(leg["img_s_chip"] / f32, 3)
                      if f32 and "img_s_chip" in leg else None)
               for name, leg in legs.items() if name != "exact"}
    cascade_goodput = goodput.get("balanced")
    int8_goodput = goodput.get("fast")
    # the cascade's OWN property, host-independent: the balanced class
    # retains the cheap stage's throughput (escalation overhead priced
    # in) while the composed gate holds accuracy — "int8 goodput at
    # f32 accuracy" as a ratio against the int8-only ceiling
    efficiency = (round(cascade_goodput / int8_goodput, 3)
                  if cascade_goodput and int8_goodput else None)
    leg = {
        "sizes": f"uniform[1..{max_size}]",
        "seed": 13,
        "coalesce_wait_us": wait_us,
        "clients": clients,
        "duration_s": duration,
        "cheap_dtype": state.cheap_dtype,
        "calibration": calibrated,
        "stressed_threshold": stressed_threshold,
        # the frontier's accuracy axis: MEASURED end-to-end argmax
        # agreement vs the f32 baseline on the probe stream
        "agreement_vs_f32": agreement,
        "agreement_rows": total_rows,
        "legs": legs,
        "goodput_vs_f32": goodput,
        # this host's warmup-measured full-bucket cost ratio — the
        # frontier's physical ceiling; a bar above the ceiling is a
        # host limitation, not a cascade regression, and the record
        # keeps the two distinguishable (same provenance stance as
        # --baseline's cross-silicon refusal)
        "host_full_bucket_cost_ms": {
            "float32": round(f32_costs[top] * 1e3, 3),
            state.cheap_dtype: round(cheap_costs[top] * 1e3, 3)},
        "host_compute_ceiling": compute_ceiling,
        # ISSUE 17 acceptance: cascade goodput >= 1.5x the f32-only
        # baseline at >= 0.995 measured end-to-end agreement
        "goodput_bar": 1.5,
        "goodput_bar_reachable": (compute_ceiling is not None
                                  and compute_ceiling >= 1.5),
        "goodput_ok": (cascade_goodput is not None
                       and cascade_goodput >= 1.5),
        # host-independent cascade property: balanced retains the
        # int8-only ceiling's throughput (>= 0.9x) at composed
        # accuracy — the escalation machinery itself costs ~nothing
        # when the calibrated threshold says nothing needs escalating
        "cascade_efficiency_vs_fast": efficiency,
        "efficiency_ok": efficiency is not None and efficiency >= 0.9,
        "agreement_ok": agreement["balanced"] >= 0.995,
        "final_threshold": final_state.threshold,
        # the variant + calibration warmup compiles, for the caller's
        # whole-run recompile exclusion (same treatment as --swap's)
        "variant_warmup_compile_events": warmup_events,
    }
    _mark(f"cascade frontier: goodput vs f32 {goodput} "
          f"(agreement {agreement}, goodput_ok {leg['goodput_ok']}, "
          f"efficiency vs fast {efficiency}, "
          f"agreement_ok {leg['agreement_ok']})")
    if not leg["goodput_bar_reachable"]:
        _mark(f"cascade leg: the 1.5x goodput bar is UNREACHABLE on "
              f"this host — the {state.cheap_dtype} compute ceiling "
              f"is {compute_ceiling}x f32 (weight-only quantization "
              "on XLA CPU, PARITY.md route disclosure); goodput_ok "
              "reflects the host, not the cascade")
    return leg


def _serve_multimodel_leg(compiles, duration: float, rows: int) -> dict:
    """The multi-tenant leg (ISSUE 18 acceptance): MLP and LeNet
    resident in ONE process behind the global WFQ/EDF scheduler, on
    their own catalog + scheduler (the main single-model stack stays
    untouched). Phase A measures the light tenant's p99 alone; phase B
    adds a heavy burst tenant routed at the OTHER model and re-measures
    the light tenant under contention. Both tenants run window-kept
    pumps (always backlogged) so the granted-row split is the
    SCHEDULER's decision, not client pacing — the dispatch-share /
    weight-share fairness ratio is meaningful only against sustained
    demand. Bars recorded (not raised, the cascade leg's stance):
    light mixed p99 <= 1.5x solo, both fairness ratios in [0.8, 1.25],
    zero steady-state recompiles across both phases."""
    import collections

    import numpy as np

    from distributedmnist_tpu.config import Config
    from distributedmnist_tpu.serve import ServeMetrics
    from distributedmnist_tpu.serve.tenancy import build_tenancy

    weights = {"light": 2.0, "heavy": 1.0}
    # Quantum well BELOW the per-grant head costs: DRR shares converge
    # to the weights only when affording a head takes multiple credit
    # scans — a quantum that covers every head on its first visit
    # degenerates to round-robin (grant frequency, not service time,
    # would be equalized).
    cfg = Config(
        model="mlp", serve_models="mlp,lenet",
        serve_tenants=(f"light:weight={weights['light']:g},"
                       "deadline_ms=5000,model=mlp;"
                       f"heavy:weight={weights['heavy']:g},"
                       "model=lenet"),
        serve_max_batch=16, serve_max_wait_us=500,
        serve_tenant_quantum_us=200.0)
    metrics = ServeMetrics()
    boot_from = compiles.snapshot()
    catalog, sched = build_tenancy(cfg, metrics=metrics)
    lat = {"solo": [], "light": [], "heavy": []}
    try:
        for name in catalog.names():     # eager residency, as serve.py
            catalog.ensure_live(name, seed=cfg.seed)
        # The FULL boot compile delta, not the per-entry engine-warmup
        # counters: building two models also compiles parity-gate and
        # first-dispatch programs, and the whole-run recompile
        # exclusion below must cover everything boot cost or the
        # headline record mis-reports catalog warmup as steady-state
        # recompiles.
        warmup_compiles = compiles.snapshot() - boot_from
        steady_from = compiles.snapshot()
        _mark(f"multimodel leg: {catalog.names()} resident "
              f"({warmup_compiles} warmup compiles); light solo "
              f"{duration:.0f}s then +heavy burst {duration:.0f}s")

        # The host's cross-model compute-contention ceiling: time an
        # mlp dispatch alone, then with a continuous lenet storm
        # sharing the silicon — ROUTER-direct, no queues, so the ratio
        # is pure device contention, which no scheduler can remove.
        # On shared chips (this CPU; logical replicas) the 1.5x p99
        # bar is unreachable whenever the ceiling alone exceeds it —
        # the record keeps host limits distinguishable from scheduler
        # regressions (the cascade leg's goodput_bar_reachable
        # stance).
        probe_rng = np.random.default_rng(7)
        xm = probe_rng.integers(0, 256, (8, 28, 28, 1), dtype=np.uint8)
        xl = probe_rng.integers(0, 256, (16, 28, 28, 1),
                                dtype=np.uint8)
        mlp_router = catalog.get("mlp").router
        lenet_router = catalog.get("lenet").router

        def _median_infer_ms(n=30):
            times = []
            for _ in range(n):
                t0 = time.monotonic()
                mlp_router.infer(xm)
                times.append(time.monotonic() - t0)
            return float(np.median(times)) * 1e3

        alone_ms = _median_infer_ms()
        storm_stop = [False]

        def _storm():
            while not storm_stop[0]:
                lenet_router.infer(xl)

        storm = make_thread(target=_storm, name="bench-mm-storm",
                            daemon=True)
        storm.start()
        try:
            contended_ms = _median_infer_ms()
        finally:
            storm_stop[0] = True
            storm.join()
        contention_x = round(contended_ms / alone_ms, 3) if alone_ms \
            else None
        _mark(f"multimodel: host cross-model contention ceiling "
              f"{contention_x}x (mlp {alone_ms:.2f} -> "
              f"{contended_ms:.2f} ms under a lenet storm)")

        errors: list = []

        def pump(tenant, window, stop_at, lats, model=None):
            rng = np.random.default_rng(sum(map(ord, tenant)))
            x = rng.integers(0, 256, (rows, 28, 28, 1), dtype=np.uint8)
            outstanding = collections.deque()
            while time.monotonic() < stop_at:
                try:
                    while (len(outstanding) < window
                           and time.monotonic() < stop_at):
                        outstanding.append(
                            (time.monotonic(),
                             sched.submit(x, tenant=tenant,
                                          model=model)))
                    t0, fut = outstanding.popleft()
                    fut.result(timeout=120)
                    lats.append(time.monotonic() - t0)
                except BaseException as e:
                    errors.append(e)
                    return
            while outstanding:
                t0, fut = outstanding.popleft()
                try:
                    fut.result(timeout=120)
                    lats.append(time.monotonic() - t0)
                except BaseException as e:
                    errors.append(e)
                    return

        def phase(pumps):
            threads = [make_thread(target=pump, args=spec,
                                   name=f"bench-mm-{spec[0]}",
                                   daemon=True)
                       for spec in pumps]
            granted0 = {t: s["granted_rows"] for t, s in
                        sched.snapshot()["tenants"].items()}
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            if errors:
                raise RuntimeError(
                    "multimodel pump died; the fairness split would "
                    "be measured against a degraded tenant") \
                    from errors[0]
            return {t: s["granted_rows"] - granted0[t] for t, s in
                    sched.snapshot()["tenants"].items()}

        def p99_ms(samples):
            return (round(float(np.percentile(samples, 99)) * 1e3, 3)
                    if samples else None)

        # Phase A: the light tenant alone — its uncontended p99.
        phase([("light", 8, time.monotonic() + duration, lat["solo"])])
        solo_p99 = p99_ms(lat["solo"])
        # Phase B: the same light pump + a heavy burst at the OTHER
        # model, 8x the outstanding window — the latency-protection
        # measurement (light keeps its real, low demand).
        stop_at = time.monotonic() + duration
        phase([("light", 8, stop_at, lat["light"]),
               ("heavy", 64, stop_at, lat["heavy"])])
        mixed_p99 = p99_ms(lat["light"])
        # Phase C: BOTH tenants saturated (equal deep windows) on ONE
        # shared model — the fairness measurement. The dispatch split
        # is the scheduler's decision only where the tenants compete
        # for the same bounded staging: the pacing cap keeps their
        # backlogs in the per-tenant queues, and every row that
        # reaches the device got there by a DRR grant. (Phase B's
        # split reflects demand — light idles between round trips —
        # and separate models dispatch in parallel, so neither phase B
        # number is an arbitration signal.) lenet, the expensive
        # model, so head costs dwarf the quantum.
        stop_at = time.monotonic() + duration
        granted = phase([("light", 64, stop_at, [], "lenet"),
                         ("heavy", 64, stop_at, [], "lenet")])
        recompiles = compiles.snapshot() - steady_from
    finally:
        sched.stop()

    backlogged = {t: w for t, w in weights.items() if granted.get(t)}
    total_rows = sum(granted[t] for t in backlogged) or 1
    total_weight = sum(backlogged.values())
    fairness = {}
    for t, w in backlogged.items():
        # one shared model in phase C: equal per-row cost, so the
        # row share IS the service-time share DRR equalizes
        share = granted[t] / total_rows
        weight_share = w / total_weight
        fairness[t] = {
            "granted_rows": granted[t],
            "dispatch_share": round(share, 4),
            "weight_share": round(weight_share, 4),
            "ratio": round(share / weight_share, 3),
        }
    degradation = (round(mixed_p99 / solo_p99, 3)
                   if mixed_p99 and solo_p99 else None)
    bt = metrics.snapshot()["by_tenant"]
    leg = {
        "models": ["mlp", "lenet"],
        "weights": weights,
        "duration_s_per_phase": duration,
        "rows_per_request": rows,
        "light_solo_p99_ms": solo_p99,
        "light_mixed_p99_ms": mixed_p99,
        "heavy_mixed_p99_ms": p99_ms(lat["heavy"]),
        "light_p99_degradation_x": degradation,
        # ISSUE 18 acceptance: a heavy burst degrades the light
        # tenant's p99 by at most 1.5x its solo baseline. Reachable
        # only where the two models don't contend for the same
        # silicon — the probe above measured this host's floor, and
        # on shared chips light_p99_ok reflects the host, not the
        # scheduler (see host_contention_x).
        "light_p99_bar": 1.5,
        "host_contention_x": contention_x,
        "light_p99_bar_reachable": (contention_x is not None
                                    and contention_x <= 1.5),
        "light_p99_ok": (degradation is not None
                         and degradation <= 1.5),
        "fairness_model": "lenet",
        "fairness": fairness,
        # and each backlogged tenant's dispatch share tracks its
        # weight share within [0.8, 1.25]
        "fairness_ok": all(0.8 <= f["ratio"] <= 1.25
                           for f in fairness.values()),
        "slo_attainment": {t: bt.get(t, {}).get("slo_attainment")
                           for t in weights},
        "max_skip_observed": sched.max_skip_observed,
        "recompiles_after_warmup": recompiles,
        "warmup_compile_events": warmup_compiles,
    }
    _mark(f"multimodel: light p99 {solo_p99} -> {mixed_p99} ms "
          f"({degradation}x, ok {leg['light_p99_ok']}), fairness "
          f"{ {t: f['ratio'] for t, f in fairness.items()} } "
          f"(ok {leg['fairness_ok']}), {recompiles} recompiles")
    if not leg["light_p99_bar_reachable"]:
        _mark(f"multimodel leg: the 1.5x light-p99 bar is UNREACHABLE "
              f"on this host — cross-model compute contention alone "
              f"costs {contention_x}x on shared silicon (one XLA-CPU "
              "device serves both models); light_p99_ok reflects the "
              "host, not the scheduler")
    return leg


def _serve_zipf_leg(router, metrics, factory, make_batcher,
                    pipelined: int, clients: int, duration: float,
                    cache_on: bool = True,
                    cache_capacity: int = 4096) -> dict:
    """The hot-key proof leg (ISSUE 10 acceptance): a seeded
    Zipf-distributed request mix — what real million-user traffic looks
    like — driven closed-loop twice over the SAME request sequence:
    first with the prediction-cache front OFF (every repeat pays full
    queue + staging + device cost), then ON (bounded LRU + single-
    flight collapse + intra-batch dedup). The record carries hit ratio,
    goodput ratio, p99 and the device-dispatch counts side by side, so
    the cache's win is a measured ratio on one host, not a claim.

    Parity is checked IN the leg: for fresh probe keys, the computed
    (miss) response and the subsequent cached (hit) response must be
    byte-identical — the cache may only ever return exactly the bytes
    the pipeline produced. `cache_on=False` (--zipf-cache-off) runs
    only the control phase; the resulting record is marked
    `cache_enabled: false` and --baseline refuses deltas across that
    boundary exactly like cross-dtype ones."""
    import numpy as np

    from distributedmnist_tpu.serve.cache import (CacheFront,
                                                  PredictionCache)

    n_keys = 64
    zipf_s = 1.1
    rng = np.random.default_rng(29)
    max_rows = min(4, factory.max_batch)
    keys = [rng.integers(0, 256, (int(sz), 28, 28, 1), dtype=np.uint8)
            for sz in rng.integers(1, max_rows + 1, n_keys)]
    weights = 1.0 / np.arange(1, n_keys + 1) ** zipf_s
    weights /= weights.sum()
    order = rng.choice(n_keys, size=2048, p=weights)
    reqs = [keys[i] for i in order]

    def keep(snap: dict) -> dict:
        return {"rows_per_sec": snap["rows_per_sec"],
                "requests_per_sec": snap["requests_per_sec"],
                "latency_ms": snap["latency_ms"],
                "requests": snap["requests"],
                "batches": snap["batches"],
                "dispatched_rows": snap["dispatched_rows"],
                "rejected_requests": snap["rejected_requests"]}

    b = make_batcher(pipelined, adaptive=False)
    try:
        _mark(f"zipf closed loop [cache off]: {clients} clients x "
              f"{duration:.0f}s, {n_keys} keys, s={zipf_s}")
        off_snap = _serve_closed_loop(b, metrics, reqs, clients,
                                      duration)
    finally:
        b.stop()
    off = keep(off_snap)

    leg = {
        "distinct_keys": n_keys,
        "zipf_s": zipf_s,
        "seed": 29,
        "max_rows_per_request": max_rows,
        "clients": clients,
        "duration_s": duration,
        "cache_enabled": cache_on,
        "cache_capacity": cache_capacity if cache_on else None,
        "cache_off": off,
        "cache_on": None,
    }
    if not cache_on:
        _mark(f"zipf [cache off only]: {off['rows_per_sec']:.0f} "
              f"rows/s, p99 {off['latency_ms']['p99']} ms, "
              f"{off['batches']} device dispatches")
        return leg

    cache = PredictionCache(cache_capacity)
    b2 = make_batcher(pipelined, adaptive=False, dedup=True)
    front = CacheFront(b2, router, cache, metrics=metrics)
    parity_probes = 0
    parity_ok = True
    try:
        _mark(f"zipf closed loop [cache on]: {clients} clients x "
              f"{duration:.0f}s (capacity {cache_capacity})")
        on_snap = _serve_closed_loop(front, metrics, reqs, clients,
                                     duration)
        # Byte-identity parity: a FRESH probe's first submit computes
        # (miss -> pipeline), its second is served from the cache; the
        # two responses must be the same bytes, always.
        probe_rng = np.random.default_rng(31)
        for _ in range(8):
            probe = probe_rng.integers(0, 256, (2, 28, 28, 1),
                                       dtype=np.uint8)
            computed = front.submit(probe).result(timeout=60)
            cached = front.submit(probe).result(timeout=60)
            parity_probes += 1
            if computed.tobytes() != cached.tobytes():
                parity_ok = False
        _drain_or_die(front, timeout=60)
    finally:
        b2.stop()
    on = keep(on_snap)
    stats = cache.stats()
    dedup = on_snap.get("dedup", {})
    hit_ratio = stats["hit_ratio"]
    goodput_x = (round(on["rows_per_sec"] / off["rows_per_sec"], 3)
                 if off["rows_per_sec"] else None)
    leg.update({
        "cache_on": {**on, "cache": stats, "dedup": dedup},
        # ISSUE 10 acceptance: hit ratio >= 0.5 on the Zipf mix,
        # goodput >= 2x the cache-off leg, device dispatches strictly
        # lower, cached bytes identical to computed ones
        "hit_ratio": hit_ratio,
        "hit_ratio_ok": hit_ratio is not None and hit_ratio >= 0.5,
        "goodput_x": goodput_x,
        "goodput_ok": goodput_x is not None and goodput_x >= 2.0,
        "p99_off_ms": off["latency_ms"]["p99"],
        "p99_on_ms": on["latency_ms"]["p99"],
        "device_dispatches_off": off["batches"],
        "device_dispatches_on": on["batches"],
        # The fewer-dispatches bar, NORMALIZED per served request
        # (ISSUE 14 satellite): the raw absolute comparison flaked
        # under full-suite load — a starved cache-off phase could
        # serve so few requests that its absolute dispatch count
        # undercut the cache-on phase's ~n_keys unique computations.
        # Dispatches PER REQUEST is load-invariant: the cache's whole
        # point is that repeats stop costing device dispatches, so the
        # on-phase rate must sit strictly below the off-phase rate at
        # any throughput the host manages.
        "device_dispatches_per_request_off": (
            round(off["batches"] / off["requests"], 4)
            if off["requests"] else None),
        "device_dispatches_per_request_on": (
            round(on["batches"] / on["requests"], 4)
            if on["requests"] else None),
        "device_dispatch_lower": (
            off["requests"] > 0 and on["requests"] > 0
            and (on["batches"] / on["requests"])
            < (off["batches"] / off["requests"])),
        "single_flight_collapsed": stats["collapsed"],
        "parity_probes": parity_probes,
        "parity_ok": parity_ok,
    })
    _mark(f"zipf: hit ratio {hit_ratio} (bar >= 0.5), goodput "
          f"{off['rows_per_sec']:.0f} -> {on['rows_per_sec']:.0f} "
          f"rows/s ({goodput_x}x, bar >= 2x), device dispatches "
          f"{off['batches']} -> {on['batches']}, p99 "
          f"{off['latency_ms']['p99']} -> {on['latency_ms']['p99']} "
          f"ms, {stats['collapsed']} collapsed, parity "
          f"{'ok' if parity_ok else 'FAILED'} ({parity_probes} probes)")
    return leg


def _serve_lowlat_leg(registry, router, factory, metrics, make_batcher,
                      compiles, duration: float, max_wait_us: int,
                      model: str) -> dict:
    """The single-request low-latency proof leg (ISSUE 14): ONE
    closed-loop client (1 in flight, qps << capacity by construction)
    driving 1-row requests through the SAME pipeline twice — first
    down the ordinary coalescing path (a lone request pays the
    coalesce wait plus two queue hand-offs), then with the bypass lane
    on (empty queue + free slot -> dispatch on the caller's thread,
    device-resident staging when the geometry has it). The headline is
    the measured p50 ratio (bar >= 1.5x) with p99 no worse; then, when
    the model has one, the parity-gated whole-net megakernel variant
    is promoted and the fast phase re-runs on it.

    Attribution is proven, not assumed: a sub-phase re-runs the fast
    lane under an installed tracer with a microscopic SLO so EVERY
    request lands in the exemplar ring, and the leg reports the worst
    attributed fraction across those over-SLO requests (bar >= 0.95 —
    a lane stage missing its span would show up as residue here). The
    timed phases stay tracer-off, pricing the production pipeline.

    Recompile accounting: the megakernel variant's warmup compiles are
    legitimate off-hot-path warmup (returned for the whole-run
    exclusion, the dtype-sweep precedent); everything else in the leg
    must run on already-warm programs."""
    import numpy as np

    from distributedmnist_tpu.serve import trace as trace_lib
    from distributedmnist_tpu.serve.quantize import variant_supported

    req = np.random.default_rng(11).integers(0, 256, (1, 28, 28, 1),
                                             dtype=np.uint8)
    live = registry.live_version()
    steady_from = compiles.snapshot()
    variant_warmups = 0

    def keep(snap: dict) -> dict:
        return {"requests": snap["requests"],
                "requests_per_sec": snap["requests_per_sec"],
                "latency_ms": snap["latency_ms"],
                "fastpath": snap["fastpath"],
                "staging_ms": snap["staging_ms"],
                "fetch_ms": snap["fetch_ms"]}

    def phase(tag: str, fastlane: bool) -> dict:
        b = make_batcher(1, adaptive=False, fastlane=fastlane)
        try:
            _mark(f"lowlat closed loop [{tag}]: 1 client x "
                  f"{duration:.0f}s, 1-row requests, wait "
                  f"{max_wait_us}us")
            snap = _serve_closed_loop(b, metrics, [req], 1, duration)
        finally:
            b.stop()
        out = keep(snap)
        _mark(f"lowlat [{tag}]: p50 {out['latency_ms']['p50']} ms, "
              f"p99 {out['latency_ms']['p99']} ms, "
              f"{out['fastpath']['dispatches']} fastpath dispatches "
              f"over {out['requests']} requests")
        return out

    batched = phase("batched", fastlane=False)
    fast = phase("fastlane", fastlane=True)

    mega = None
    mega_parity = None
    if variant_supported(model, "megakernel"):
        _mark("lowlat: warming + gating the megakernel variant")
        before = compiles.snapshot()
        vi = registry.add_variant(live, "megakernel")
        variant_warmups = compiles.snapshot() - before
        mega_parity = vi.parity
        registry.promote(live, infer_dtype="megakernel")
        try:
            mega = phase("fastlane+megakernel", fastlane=True)
        finally:
            # later legs (swap/chaos) price the f32 base as always
            registry.promote(live, infer_dtype="float32")

    # Attribution sub-phase: a realistic sub-p50 SLO, so the audited
    # population is genuinely slow requests (the ones whose budget an
    # operator would chase) and every one of them lands in the
    # exemplar ring with its stage blame computable.
    att_slo_ms = 0.5
    tracer = trace_lib.install(trace_lib.Tracer(capacity=1024,
                                                sample=1.0,
                                                slo_ms=att_slo_ms,
                                                seed=23))
    b = make_batcher(1, adaptive=False, fastlane=True)
    try:
        for _ in range(64):
            b.submit(req).result(timeout=60)
        _drain_or_die(b, timeout=60)
    finally:
        b.stop()
        trace_lib.uninstall()
    fracs = [trace_lib.attribute_stages(tr)["attributed_frac"]
             for tr in tracer.traces() if tr["over_slo"]]
    att_min = round(min(fracs), 4) if fracs else None
    census = _span_census(tracer)

    recompiles = compiles.snapshot() - steady_from - variant_warmups
    p50_b = batched["latency_ms"]["p50"]
    p50_f = fast["latency_ms"]["p50"]
    _cands = [p for p in (p50_f, (mega or {}).get("latency_ms",
                                                  {}).get("p50"))
              if p is not None]
    best = min(_cands) if _cands else None
    improvement = (round(p50_b / p50_f, 3) if p50_b and p50_f
                   else None)
    p99_ok = (fast["latency_ms"]["p99"] is not None
              and batched["latency_ms"]["p99"] is not None
              and fast["latency_ms"]["p99"]
              <= batched["latency_ms"]["p99"])
    leg = {
        "clients": 1,
        "rows_per_request": 1,
        "duration_s": duration,
        "coalesce_wait_us": max_wait_us,
        "batched": batched,
        "fastlane": fast,
        "megakernel": mega,
        "megakernel_parity": mega_parity,
        # ISSUE 14 acceptance: p50 >= 1.5x better at qps << capacity,
        # p99 no worse, zero recompiles, >= 0.95 attribution on every
        # over-SLO request
        "p50_batched_ms": p50_b,
        "p50_fastlane_ms": p50_f,
        "p50_best_ms": best,
        "p50_improvement_x": improvement,
        "p50_ok": improvement is not None and improvement >= 1.5,
        "p99_ok": p99_ok,
        "fastpath_dispatches": fast["fastpath"]["dispatches"],
        "fastpath_lane_fraction": fast["fastpath"]["lane_fraction"],
        "recompiles": recompiles,
        "recompiles_ok": recompiles == 0,
        "variant_warmup_compile_events": variant_warmups,
        "attribution": {
            "slo_ms": att_slo_ms,
            "over_slo_requests": len(fracs),
            "min_attributed_frac": att_min,
            "fastpath_spans": census["spans"].get("fastpath", 0),
            "ok": att_min is not None and att_min >= 0.95,
        },
    }
    _mark(f"lowlat: p50 {p50_b} -> {p50_f} ms "
          f"({improvement}x, bar >= 1.5x), p99 "
          f"{batched['latency_ms']['p99']} -> "
          f"{fast['latency_ms']['p99']} ms (no-worse "
          f"{'ok' if p99_ok else 'FAILED'}), megakernel p50 "
          f"{(mega or {}).get('latency_ms', {}).get('p50')} ms, "
          f"attribution min {att_min} over {len(fracs)} over-SLO "
          f"requests, {recompiles} recompiles")
    return leg


def _trace_attribution_rows(traces: list) -> list:
    """Per-request stage-attribution table rows for EVERY given trace
    (slowest first): total wall clock, per-stage blame, and the
    unattributed residue — the bench never hides what the spans failed
    to explain. Callers cap what they PRINT/record, never what the
    acceptance minimum is computed over."""
    from distributedmnist_tpu.serve import trace as trace_lib

    rows = []
    for t in sorted(traces, key=lambda t: -t["duration_ms"]):
        att = trace_lib.attribute_stages(t)
        rows.append({
            "trace_id": t["trace_id"],
            "status": t["status"],
            "over_slo": t["over_slo"],
            "total_ms": round(t["duration_ms"], 3),
            "stages_ms": {k: round(v, 3)
                          for k, v in sorted(att["stages_ms"].items())},
            "residue_ms": round(att["residue_ms"], 3),
            "attributed_frac": round(att["attributed_frac"], 4),
        })
    return rows


def _span_census(tracer) -> dict:
    """Distinct-span counts by name across every retained trace (the
    chaos-leg assertion basis: failover rescues and bisect splits must
    appear as STRUCTURED child spans, not only as counters)."""
    seen: set = set()
    census: dict = {}
    parented: dict = {}
    for t in tracer.traces():
        for s in t["spans"]:
            if s["id"] in seen:
                continue
            seen.add(s["id"])
            census[s["name"]] = census.get(s["name"], 0) + 1
            if s["parent"] is not None:
                parented[s["name"]] = parented.get(s["name"], 0) + 1
    return {"spans": census, "parented": parented}


def _serve_trace_leg(router, metrics, factory, make_batcher,
                     pipelined: int, duration: float, qps: float,
                     chrome_events: list) -> dict:
    """The tail-attribution proof leg (ISSUE 9 acceptance): a seeded
    mixed-size open-loop window under an installed tracer, then a
    stage-attribution table for every over-SLO request — p99 blame
    (queue vs staging vs device vs fetch vs rescue) with the
    unattributed residue reported per request, >= 95% of each over-SLO
    request's wall clock attributed to named stages.

    The SLO is derived from the measured cost tables (one coalescing
    wait + two full-batch service times): requests beyond it are
    genuinely queue/tail-shaped, not the happy path. On a quiet host
    that beats the SLO everywhere, the table falls back to the slowest
    retained traces — labeled, so the record never pretends an
    over-SLO population that wasn't there."""
    import numpy as np

    from distributedmnist_tpu.serve import trace as trace_lib
    from distributedmnist_tpu.serve.scheduler import fit_dispatch_cost

    overhead_s, per_row_s = fit_dispatch_cost(router.bucket_costs())
    svc_s = overhead_s + per_row_s * factory.buckets[-1]
    wait_us = max(2000, int(3e6 / qps), int(svc_s * 1e6))
    slo_ms = wait_us / 1e3 + 2 * svc_s * 1e3
    tracer = trace_lib.install(trace_lib.Tracer(
        capacity=4096, sample=1.0, slo_ms=slo_ms, seed=17))
    rng = np.random.default_rng(11)
    sizes = [int(s) for s in
             rng.integers(1, min(8, factory.max_batch) + 1, 128)]
    reqs = [rng.integers(0, 256, (n, 28, 28, 1), dtype=np.uint8)
            for n in sizes]
    b = make_batcher(pipelined, adaptive=False, wait_us=wait_us)
    try:
        _mark(f"trace leg: open loop qps={qps:g} x {duration:.0f}s, "
              f"slo {slo_ms:.1f} ms, wait {wait_us} us")
        _serve_open_loop(b, metrics, reqs, qps, duration, wait_us)
    finally:
        b.stop()
        trace_lib.uninstall()
    traces = tracer.traces()
    over = [t for t in traces if t["over_slo"]]
    basis = "over_slo"
    table_src = over
    if not table_src:
        basis = "slowest"
        table_src = traces
    rows = _trace_attribution_rows(table_src)
    # The acceptance minimum runs over the WHOLE population ("each
    # over-SLO request"); only the printed/recorded table is capped.
    min_attr = min((r["attributed_frac"] for r in rows), default=None)
    table = rows[:32]
    stages_seen = sorted({s for r in table for s in r["stages_ms"]})
    _mark(f"trace: {len(traces)} retained, {len(over)} over-SLO "
          f"(attribution basis: {basis}, {len(rows)} checked, "
          f"{len(table)} shown); min attributed frac {min_attr}")
    hdr = (f"{'trace':>10} {'st':>3} {'total':>9} "
           + "".join(f"{s[:8]:>9}" for s in stages_seen)
           + f" {'residue':>9} {'attr':>7}")
    _mark(hdr)
    for r in table:
        _mark(f"{r['trace_id']:>10} {r['status'][:3]:>3} "
              f"{r['total_ms']:>9.3f} "
              + "".join(f"{r['stages_ms'].get(s, 0.0):>9.3f}"
                        for s in stages_seen)
              + f" {r['residue_ms']:>9.3f} "
              f"{r['attributed_frac'] * 100:>6.2f}%")
    snap = tracer.snapshot()
    chrome_events.extend(tracer.export_chrome()["traceEvents"])
    return {
        "slo_ms": round(slo_ms, 3),
        "coalesce_wait_us": wait_us,
        "qps": qps,
        "sample": 1.0,
        "requests_traced": snap["requests_finished"],
        "traces_retained": len(traces),
        "over_slo_requests": len(over),
        "attribution_basis": basis,
        "attribution_checked": len(rows),
        "attribution": table,
        "min_attributed_frac": min_attr,
        # ISSUE 9 acceptance: >= 95% of each over-SLO request's wall
        # clock attributed to named stages
        "attribution_ok": (min_attr is not None and min_attr >= 0.95),
        "open_spans_at_drain": snap["open_spans"],
        "span_census": _span_census(tracer)["spans"],
    }


def _serve_trace_replay_leg(router, metrics, factory, make_batcher,
                            spec: str, seed: int, autoscale: bool,
                            slo_ms: float, chaos: bool = False) -> dict:
    """The workload-realism leg (ISSUE 20): replay ONE seeded
    deterministic arrival schedule (serve/workload.py) open-loop
    against a static floor-provisioned config and — with --autoscale —
    again under the closed-loop autoscaler, on the identical schedule
    (same seed, byte-identical arrivals, byte-identical request
    content per key). Headlines: SLO attainment (within-SLO
    completions over ALL arrivals — sheds are misses) and chip-seconds
    per million within-SLO requests, the autoscaler's spend integrated
    from its own action log so the artifact's cost claim is auditable.

    The static phase is trough-provisioned on purpose (window =
    floor, bucket ceiling = the smallest bucket covering the trace's
    largest request): the autoscaler's job is exactly to buy burst
    capacity that static trough provisioning lacks and give it back in
    the quiet phases. Scale moves only along the engine's pre-warmed
    bucket ladder, so the whole-run recompiles_after_warmup==0 bar
    covers this leg too. Zero flaps holds by construction (any action
    inside the cooldown window is suppressed, so consecutive actions
    are always >= cooldown_s apart) and is still AUDITED from the
    action log, not asserted."""
    import hashlib

    import numpy as np

    from distributedmnist_tpu.serve import Rejected, workload
    from distributedmnist_tpu.serve.autoscale import (Autoscaler,
                                                      WindowActuator,
                                                      batcher_signals)

    legs = workload.parse_trace_spec(spec)
    events = workload.materialize(legs, seed)
    dur = workload.total_duration(legs)
    if not events:
        raise RuntimeError(f"trace spec {spec!r} with seed {seed} "
                           "materialized zero arrivals")
    # (key, rows) -> byte-stable request content: the cache/dedup
    # identity follows the trace's key mix exactly, and a regression
    # run from the recorded seed replays the same bytes.
    pool: dict = {}
    for e in events:
        k = (e.key, e.rows)
        if k not in pool:
            r = np.random.default_rng([seed, e.key, e.rows])
            pool[k] = r.integers(0, 256, (e.rows, 28, 28, 1),
                                 dtype=np.uint8)
    buckets = list(factory.buckets)
    max_rows = max(e.rows for e in events)
    base_idx = next((i for i, b in enumerate(buckets) if b >= max_rows),
                    len(buckets) - 1)
    base_max_batch = buckets[base_idx]
    floor = 1
    # ceiling: one window unit per remaining bucket rung (capped) so
    # every grow step buys a real capacity rung
    ceiling = max(floor + 1,
                  min(8, floor + (len(buckets) - 1 - base_idx)))

    def replay(batcher) -> dict:
        done: list = []             # (latency_s, errored) per completion
        sheds = 0
        lag_max = 0.0
        t0 = time.monotonic()
        for e in events:
            target = t0 + e.t
            now = time.monotonic()
            if target > now:
                time.sleep(target - now)
            else:
                lag_max = max(lag_max, now - target)
            try:
                fut = batcher.submit(pool[(e.key, e.rows)])
            except Rejected:
                sheds += 1
                continue
            ts = time.monotonic()
            fut.add_done_callback(
                lambda f, ts=ts: done.append(
                    (time.monotonic() - ts,
                     f.exception() is not None)))
        _drain_or_die(batcher, timeout=120)
        total = len(events)
        served = sum(1 for _, err in done if not err)
        within = sum(1 for lat, err in done
                     if not err and lat * 1e3 <= slo_ms)
        lats = sorted(lat * 1e3 for lat, err in done if not err)

        def q(p: float):
            return (round(lats[min(len(lats) - 1, int(p * len(lats)))],
                          2) if lats else None)

        return {"arrivals": total, "served": served,
                "shed": sheds + sum(1 for _, err in done if err),
                "within_slo": within,
                "slo_attainment": round(within / total, 4),
                "latency_ms": {"p50": q(0.50), "p90": q(0.90),
                               "p99": q(0.99)},
                "max_submit_lag_ms": round(lag_max * 1e3, 2)}

    def per_m(chip_s: float, within: int):
        # chip-seconds per million WITHIN-SLO requests: spend over
        # goodput, not over arrivals — capacity that missed the SLO
        # earns nothing
        return (round(chip_s / within * 1e6, 1) if within else None)

    leg = {
        "spec": spec, "seed": seed,
        "autoscale_enabled": bool(autoscale),
        "slo_ms": slo_ms,
        "legs": workload.describe(legs),
        "events": len(events),
        "duration_s": round(dur, 3),
        # the replay-determinism receipt: rerunning this spec+seed
        # must materialize a schedule hashing to exactly this
        "schedule_sha256": hashlib.sha256(
            workload.schedule_bytes(events)).hexdigest(),
        "floor_units": floor, "ceiling_units": ceiling,
        "base_max_batch": base_max_batch,
    }

    _mark(f"trace replay [static floor={floor}, "
          f"max_batch={base_max_batch}]: {len(events)} arrivals over "
          f"{dur:.1f}s ({spec})")
    metrics.reset()
    b = make_batcher(floor, max_batch=base_max_batch)
    try:
        static = replay(b)
    finally:
        b.stop()
    static["units"] = floor
    static["chip_seconds"] = round(floor * dur, 3)
    static["chip_seconds_per_m_requests"] = per_m(
        static["chip_seconds"], static["within_slo"])
    leg["static"] = static
    _mark(f"trace replay [static]: attainment "
          f"{static['slo_attainment']:.3f}, "
          f"{static['shed']} shed, p99 {static['latency_ms']['p99']} "
          f"ms, {static['chip_seconds']} chip-s")

    autoscaled = None
    if autoscale:
        _mark(f"trace replay [autoscaled {floor}..{ceiling}]: same "
              "schedule under the closed-loop controller")
        metrics.reset()
        # construction-time window = ceiling (the parked-permit
        # design: the actuator narrows by parking permits, so the
        # semaphore itself never resizes); then start at the SAME
        # trough provisioning the static phase ran
        b = make_batcher(ceiling, max_batch=base_max_batch)
        actuator = WindowActuator(b, floor=floor, ceiling=ceiling,
                                  base_max_batch=base_max_batch)
        actuator.scale_to(floor)
        ctl = Autoscaler(
            actuator,
            batcher_signals(b, metrics=metrics, slo_ms=slo_ms),
            high=0.6, low=0.15,
            cooldown_s=max(0.3, dur / 24), interval_s=0.05,
            metrics=metrics)
        ctl.start()
        try:
            autoscaled = replay(b)
        finally:
            ctl.stop()
            b.stop()
        actions = list(ctl.actions)
        # chip-seconds = integral of scale units over the trace,
        # piecewise-constant from the action log (t_s offsets are
        # from controller start, which immediately precedes replay
        # start; clamped to the trace window)
        chip_s, last_t, units = 0.0, 0.0, float(floor)
        for a in actions:
            t = min(max(a["t_s"], 0.0), dur)
            chip_s += units * max(0.0, t - last_t)
            last_t, units = t, float(a["achieved_units"])
        chip_s += units * max(0.0, dur - last_t)
        autoscaled["chip_seconds"] = round(chip_s, 3)
        autoscaled["chip_seconds_per_m_requests"] = per_m(
            chip_s, autoscaled["within_slo"])
        autoscaled["scale_actions"] = len(actions)
        autoscaled["actions"] = actions
        autoscaled["suppressed"] = ctl.suppressed
        autoscaled["saturated_ticks"] = ctl.saturated_ticks
        autoscaled["controller_errors"] = ctl.errors
        autoscaled["flaps"] = ctl.flaps()
        autoscaled["final_units"] = actuator.current()
        autoscaled["cost_basis"] = actuator.cost_basis
        leg["autoscaled"] = autoscaled
        _mark(f"trace replay [autoscaled]: attainment "
              f"{autoscaled['slo_attainment']:.3f}, "
              f"{len(actions)} scale actions "
              f"({autoscaled['suppressed']} suppressed, "
              f"{autoscaled['flaps']} flaps, "
              f"{autoscaled['saturated_ticks']} saturated ticks), "
              f"{autoscaled['chip_seconds']} chip-s")

        # The acceptance bars, with the honest-miss disclosure: when
        # the static control already attains ~everything (the host
        # outruns the trace), there is no headroom for the autoscaler
        # to buy and the record says so instead of claiming a win.
        reachable = static["slo_attainment"] < 0.995
        st_cpm = static["chip_seconds_per_m_requests"]
        as_cpm = autoscaled["chip_seconds_per_m_requests"]
        leg["bars"] = {
            "slo_bar_reachable": reachable,
            "slo_attainment_improved": (
                autoscaled["slo_attainment"] > static["slo_attainment"]
                if reachable else None),
            "chip_seconds_no_worse": (
                as_cpm is not None and st_cpm is not None
                and as_cpm <= st_cpm * 1.02
                if reachable else None),
            "zero_flaps": autoscaled["flaps"] == 0,
            "scaled_up_under_load": any(
                a["direction"] == "grow" for a in actions),
        }
    # headline fields (the --baseline delta rows read these): the
    # autoscaled phase when it ran, the static control otherwise
    head = autoscaled if autoscaled is not None else static
    leg["slo_attainment"] = head["slo_attainment"]
    leg["chip_seconds_per_m_requests"] = (
        head["chip_seconds_per_m_requests"])
    leg["scale_actions"] = (autoscaled or {}).get("scale_actions", 0)

    if chaos and autoscale:
        # PR 5 chaos under the trace (the README's "scale-up during a
        # fault storm" row): the SAME schedule, autoscaled, with a
        # seeded dispatch-latency + poison schedule installed — the
        # injected latency inflates the saturation surface, so the
        # controller should buy capacity DURING the storm; the leg
        # records whether it did and what that cost.
        from distributedmnist_tpu.serve import faults
        fault_spec = "engine.dispatch:p=0.05,latency_ms=5"
        _mark(f"trace replay [autoscaled + chaos {fault_spec!r}]")
        metrics.reset()
        faults.install(faults.FaultInjector.from_spec(fault_spec,
                                                      seed=seed))
        b = make_batcher(ceiling, max_batch=base_max_batch)
        actuator = WindowActuator(b, floor=floor, ceiling=ceiling,
                                  base_max_batch=base_max_batch)
        actuator.scale_to(floor)
        ctl = Autoscaler(
            actuator,
            batcher_signals(b, metrics=metrics, slo_ms=slo_ms),
            high=0.6, low=0.15,
            cooldown_s=max(0.3, dur / 24), interval_s=0.05,
            metrics=metrics)
        ctl.start()
        try:
            under = replay(b)
        finally:
            ctl.stop()
            b.stop()
            faults.uninstall()
        under["scale_actions"] = len(ctl.actions)
        under["grew_during_storm"] = any(
            a["direction"] == "grow" for a in ctl.actions)
        under["flaps"] = ctl.flaps()
        under["fault_spec"] = fault_spec
        leg["chaos"] = under
        _mark(f"trace replay [chaos]: attainment "
              f"{under['slo_attainment']:.3f}, grew_during_storm="
              f"{under['grew_during_storm']}")
    return leg


def chaos_fault_spec(live_version: str, kill_target) -> str:
    """The chaos leg's programmatic fault schedule, in one place so the
    argparse-time gate and the leg itself cannot drift (ISSUE 8
    satellite: PR 5 validated user-typed specs at serve.py argparse;
    this validates the bench's OWN constructed specs the same way —
    main() runs both template shapes through faults.parse_spec before
    any load phase).

    - request-sticky poison on ~1.5% of dispatches (bisection's food),
    - a fetch storm pinned to `live_version` after 40 clean batches
      (the forced breaker trip; rollback un-matches the rule and ends
      the storm, count=200 is the broken-rollback backstop),
    - with `kill_target` (fleet runs): two small replica-kill bursts on
      that replica — fetch-side then dispatch-side — timed to complete
      BEFORE the version storm opens (overlapping them would kill a
      rescue on the only sibling: unsurvivable at N=2 by construction,
      and a different scenario from the replica fault class this storm
      proves is absorbed)."""
    spec = ("batch.dispatch:mode=request,p=0.015;"
            f"engine.fetch:p=1,count=200,after=40,version={live_version}")
    if kill_target is not None:
        spec += (f";replica.fetch:p=1,replica={kill_target},"
                 "after=2,count=4"
                 f";replica.dispatch:p=1,replica={kill_target},"
                 "after=8,count=4")
    return spec


def _serve_chaos_leg(registry, router, factory, metrics, make_batcher,
                     compiles, pipelined: int, duration: float,
                     qps: float,
                     cache_capacity: Optional[int] = None) -> dict:
    """The resilience proof leg (ISSUE 5 acceptance): a seeded fault
    schedule driven open-loop against the full resilience stack, with
    every request's outcome tracked individually.

    Schedule (deterministic — seeded injector + seeded arrivals):

    - **poison requests**: request-sticky dispatch faults on ~1.5% of
      requests (`batch.dispatch:mode=request`). A poisoned request
      fails every dispatch containing it, so its cohort only survives
      if bisection isolates the culprit — the leg checks EXACT
      isolation: requests failed by dispatch injection == distinct
      requests the injector poisoned (no cohort-mate was misblamed, no
      culprit slipped through).
    - **a forced breaker trip**: after a warm stretch, fetch faults
      pinned to the live version (`engine.fetch:p=1,version=...`)
      blast its failure window; the circuit breaker must trip and
      auto-promote the healthy fallback resident loaded up front —
      after which the rule no longer matches and traffic recovers
      inside the same measured window.
    - **deadline sheds**: a slice of requests carries an unmeetable
      X-Deadline-Ms-style budget; they must be shed pre-dispatch
      (counted, zero device work).

    Availability is reported over the non-injected population (the
    culprits themselves, deadline sheds and watermark rejects are the
    fault load, not collateral): anything ELSE failing means a
    resilience path broke its neighbors. The whole leg must also stay
    recompile-free — bisection sub-segments and the rollback target
    both reuse programs already on the bucket ladder.

    With `cache_capacity` set (--serve-cache, ISSUE 12 satellite) the
    whole drill runs THROUGH the prediction cache + single-flight
    front, with the registry's invalidation hook installed so the
    forced rollback exercises the epoch bump mid-storm. The poison
    ledger is then asserted on a LEADER basis: a poisoned rid only
    ever belongs to a flight leader (followers never reach dispatch,
    hits never leave the cache), so client failures from dispatch
    injection minus collapsed-follower echoes must equal the
    injector's distinct poisoned set exactly — cached and collapsed
    traffic must not distort the accounting."""
    import random

    import numpy as np

    from distributedmnist_tpu.serve import (CircuitBreaker,
                                            DeadlineExceeded, Rejected,
                                            ResiliencePolicy, faults)
    from distributedmnist_tpu.serve.faults import InjectedFault
    from distributedmnist_tpu.serve.scheduler import fit_dispatch_cost

    live = registry.live_version()
    fallback = registry.add(factory.init_params(202),
                            version="v-chaos-fallback",
                            source="fresh-init")
    steady_from = compiles.snapshot()    # fallback warmup excluded
    # A tight breaker so the trip lands well inside the leg: ~1.5s of
    # outcomes, a dozen requests of volume, half failing.
    breaker = CircuitBreaker(window_s=1.5, min_requests=12,
                             failure_ratio=0.5, cooldown_s=60.0)
    res = ResiliencePolicy(bisect=True, breaker=breaker,
                           registry=registry, metrics=metrics)
    # Cohort-sized coalescing: poison isolation is only exercised when
    # drains hold several requests, so the wait covers ~3 Poisson
    # inter-arrivals at the driven rate (or the measured full-batch
    # service time if that is longer — the ragged leg's balance point).
    overhead_s, per_row_s = fit_dispatch_cost(router.bucket_costs())
    wait_us = max(int(3e6 / qps), 2000, int(
        (overhead_s + per_row_s * factory.buckets[-1]) * 1e6))
    chaos_duration = max(3.0 * duration, 6.0)
    # The storm: every fetch on the live version fails once 40 batches
    # have served clean. The breaker must trip and roll back — rollback
    # is what ENDS the storm (the rule stops matching the new live
    # version); count=200 is only the backstop against a broken
    # rollback turning the leg into a total outage.
    # The schedule (chaos_fault_spec — shared with main()'s argparse
    # gate): the replica-kill storm rides along on fleet runs only.
    # Kill windows: victim crossings 3-6 at fetch (its in-flight
    # batches die holding results), 9-12 at dispatch (it refuses new
    # work) — roughly overall batches 6-24, the victim serving ~half.
    # The bursts are small enough that the victim's breaker NEED not
    # trip for availability to hold — failover, not exclusion, is what
    # the replica storm proves; rescue dispatches reuse the sibling's
    # compiled bucket programs, so the whole storm stays recompile-free.
    fleet = router if getattr(router, "n_replicas", 1) > 1 else None
    kill_target = fleet.replica_ids()[-1] if fleet is not None else None
    spec = chaos_fault_spec(live, kill_target)
    inj = faults.install(faults.FaultInjector.from_spec(spec, seed=23))
    _mark(f"chaos: schedule {spec!r} (seed 23), {chaos_duration:.0f}s "
          f"open loop at qps={qps:g}, wait {wait_us}us, fallback "
          f"{fallback.version} resident")

    rng = np.random.default_rng(13)
    sizes = [int(s)
             for s in rng.integers(1, min(8, factory.max_batch) + 1, 256)]
    reqs = [rng.integers(0, 256, (n, 28, 28, 1), dtype=np.uint8)
            for n in sizes]
    batcher = make_batcher(pipelined, adaptive=False, wait_us=wait_us,
                           resilience=res)
    cache = None
    submitter = batcher
    if cache_capacity is not None:
        from distributedmnist_tpu.serve.cache import (CacheFront,
                                                      PredictionCache)

        cache = PredictionCache(cache_capacity)
        # The real invalidation hook, not a test double: the forced
        # breaker rollback mid-storm must bump the epoch atomically
        # with the route swap, dropping any single-flight insert that
        # raced it.
        registry.set_cache(cache)
        submitter = CacheFront(batcher, router, cache, metrics=metrics)
        _mark(f"chaos: prediction cache front ON "
              f"(capacity {cache_capacity})")
    outcomes: list = []
    futures: list = []
    poison_echoes = 0        # collapsed followers re-raising a leader's
    #   injected dispatch fault (one rid, N futures)
    cache_hits_ok = 0
    try:
        metrics.reset()
        arrivals = random.Random(3)
        t_end = time.monotonic() + chaos_duration
        next_t = time.monotonic()
        i = 0
        while next_t < t_end:
            now = time.monotonic()
            if next_t > now:
                time.sleep(next_t - now)
            deadline = None
            if i % 25 == 7:
                # an unmeetable budget: must shed pre-dispatch
                deadline = time.monotonic() + 5e-4
            try:
                futures.append(submitter.submit(reqs[i % len(reqs)],
                                                deadline_s=deadline))
            except DeadlineExceeded:
                outcomes.append("deadline")
            except Rejected:
                outcomes.append("rejected")
            i += 1
            next_t += arrivals.expovariate(qps)
        _drain_or_die(submitter, timeout=120)
        for fut in futures:
            try:
                fut.result(timeout=60)
                outcomes.append("ok")
                if getattr(fut, "cache_hit", False):
                    cache_hits_ok += 1
            except InjectedFault as e:
                outcomes.append(f"injected:{e.point}")
                if (e.point == "batch.dispatch"
                        and getattr(fut, "collapsed", False)):
                    poison_echoes += 1
            except DeadlineExceeded:
                outcomes.append("deadline")
            except Rejected:
                # only reachable through the cache front: a follower
                # echoing its leader's submit-time rejection — fault
                # load, not collateral
                outcomes.append("rejected")
            except Exception:
                outcomes.append("other")
        snap = metrics.snapshot()
    finally:
        faults.uninstall()
        submitter.stop()
        if cache is not None:
            registry.set_cache(None)

    n = len(outcomes)
    n_ok = outcomes.count("ok")
    n_poison = outcomes.count("injected:batch.dispatch")
    n_fetch = outcomes.count("injected:engine.fetch")
    # replica-kill faults that ESCAPED failover (no healthy sibling at
    # rescue time): injected load, excluded from availability, but the
    # fleet storm's rescued_exactly flag demands ZERO of them
    n_replica = sum(1 for o in outcomes
                    if o.startswith("injected:replica."))
    n_deadline = outcomes.count("deadline")
    n_rejected = outcomes.count("rejected")
    n_other = (n - n_ok - n_poison - n_fetch - n_replica - n_deadline
               - n_rejected)
    denom = max(n_ok + n_other, 1)
    availability = n_ok / denom
    poisoned = inj.poisoned()
    # The leader-basis poison count: every poisoned rid belongs to
    # exactly one dispatched (leader) request; collapsed followers
    # re-raise the SAME fault instance without a rid of their own.
    # Without the cache front poison_echoes is 0 and this is n_poison.
    n_poison_leaders = n_poison - poison_echoes
    events = registry.events()
    rollbacks = [e for e in events if e.get("event") == "rollback"]
    recompiles = compiles.snapshot() - steady_from
    resil = snap["resilience"]
    leg = {
        "spec": spec,
        "injector_seed": 23,
        "arrivals_seed": 3,
        "qps": qps,
        "duration_s": round(chaos_duration, 3),
        "coalesce_wait_us": wait_us,
        "requests": n,
        "ok": n_ok,
        # the injected fault load, split by class
        "injected_dispatch_faults": n_poison,
        "injected_fetch_faults": n_fetch,
        "injected_replica_faults_surfaced": n_replica,
        "deadline_shed": n_deadline,
        "rejected": n_rejected,
        "other_failures": n_other,
        # ISSUE 5 acceptance: non-injected traffic must stay >= 99%
        # available, every poison isolated exactly, rollback engaged,
        # and the whole storm recompile-free
        "availability_excluding_injected": round(availability, 5),
        "availability_ok": availability >= 0.99,
        "p99_under_faults_ms": snap["latency_ms"]["p99"],
        "poison_unique": len(poisoned),
        "poison_isolated_exact": n_poison_leaders == len(poisoned) > 0,
        "bisect_splits": resil["bisect_splits"],
        "bisect_rescued_requests": resil["bisect_rescued_requests"],
        "deadline_shed_metric": resil["deadline_shed_requests"],
        "breaker_trips": breaker.trips(),
        "rollbacks": len(rollbacks),
        "rollback_events": rollbacks,
        "rollback_engaged": (len(rollbacks) >= 1
                             and registry.live_version()
                             == fallback.version),
        "live_version_after": registry.live_version(),
        "fallback_warmup_compile_events": fallback.warmup_compile_events,
        "recompiles_during_chaos": recompiles,
        # the fleet's rescue counters (0 without --serve-replicas >= 2):
        # how many batches redundancy saved that retry could not
        "failovers": snap["fleet"]["failovers_total"],
        "hedges": snap["fleet"]["hedges"],
    }
    if cache is not None:
        stats = cache.stats()
        leg["cache"] = {
            "enabled": True,
            "capacity": cache_capacity,
            "stats": stats,
            "cache_hits_ok": cache_hits_ok,
            "poison_client_failures": n_poison,
            "poison_follower_echoes": poison_echoes,
            "poison_leaders": n_poison_leaders,
            # ISSUE 12 satellite acceptance: the ledger holds EXACTLY
            # with the cache front on — hits bypass the failpoints
            # without inventing rids, followers echo without drawing,
            # and errors are never cached (a poisoned key re-elects a
            # fresh leader with a fresh rid)
            "ledger_exact": n_poison_leaders == len(poisoned) > 0,
        }
        _mark(f"chaos cache: {stats['hits']} hits "
              f"({cache_hits_ok} served ok), {stats['collapsed']} "
              f"collapsed, {poison_echoes} poison echoes, "
              f"{n_poison_leaders} poison leaders vs "
              f"{len(poisoned)} poisoned rids — ledger "
              f"{'EXACT' if leg['cache']['ledger_exact'] else 'OFF'}; "
              f"{stats['invalidations']} invalidations "
              f"(rollback epoch bump), {stats['stale_drops']} stale "
              "drops")
    if fleet is not None:
        kill_fires = sum(
            r["fires"] for r in inj.snapshot()["rules"]
            if r["point"].startswith("replica."))
        surfaced_by_point: dict = {}
        for o in outcomes:
            if o.startswith("injected:replica."):
                surfaced_by_point[o] = surfaced_by_point.get(o, 0) + 1
        leg["replica_kill"] = {
            "target": kill_target,
            "fires": kill_fires,
            "surfaced_failures": n_replica,
            "surfaced_by_point": surfaced_by_point,
            # ISSUE 6 acceptance: the killed replica's cohorts were ALL
            # rescued on the sibling — the storm fired, failover caught
            # every burst, and no replica fault reached a client
            "rescued_exactly": kill_fires > 0 and n_replica == 0,
            "failovers": dict(snap["fleet"]["failovers"]),
            "replica_trips": snap["fleet"]["replica_trips"],
            "fleet_after": fleet.snapshot(),
        }
    _mark(f"chaos: {n} requests — {n_ok} ok, {n_poison} poison culprits "
          f"(unique {len(poisoned)}, exact isolation "
          f"{leg['poison_isolated_exact']}), {n_fetch} trip victims, "
          f"{n_deadline} deadline-shed, {n_other} OTHER failures; "
          f"availability {availability:.4f}; "
          f"{resil['bisect_rescued_requests']} cohort-mates rescued in "
          f"{resil['bisect_splits']} splits; breaker trips "
          f"{breaker.trips()}, rollback -> {leg['live_version_after']}; "
          f"{recompiles} recompiles")
    return leg


def _baseline_delta(record: dict, baseline: dict, path: str) -> dict:
    """The --baseline comparison block: current-vs-prior deltas on the
    stable serve signals (device_kind equality was enforced before any
    load phase ran). Printed to stderr as a small table AND embedded in
    the record so the artifact itself carries the round-over-round
    story."""
    cur_d, base_d = record["detail"], baseline.get("detail", {})

    def pct(cur, prev):
        return (round(100.0 * (cur - prev) / prev, 1)
                if cur is not None and prev else None)

    cur_chaos = cur_d.get("chaos") or {}
    base_chaos = base_d.get("chaos") or {}
    rows = {
        "img_s_chip": (record["value"], baseline.get("value")),
        "closed_p99_ms": (
            cur_d["closed_loop"]["latency_ms"]["p99"],
            base_d.get("closed_loop", {}).get("latency_ms", {})
            .get("p99")),
        "ragged_closed_waste": (
            (cur_d.get("ragged") or {}).get("closed_waste_on"),
            (base_d.get("ragged") or {}).get("closed_waste_on")),
        "recompiles_after_warmup": (
            cur_d["recompiles_after_warmup"],
            base_d.get("recompiles_after_warmup")),
        # the chaos-leg signals (ISSUE 6 satellite): resilience must
        # not regress round-over-round any more than throughput may —
        # a delta table that only compares the happy path would let an
        # availability regression ship behind a throughput win. Rows
        # are None-vs-None when either round ran without --chaos.
        "chaos_availability": (
            cur_chaos.get("availability_excluding_injected"),
            base_chaos.get("availability_excluding_injected")),
        "chaos_p99_under_faults_ms": (
            cur_chaos.get("p99_under_faults_ms"),
            base_chaos.get("p99_under_faults_ms")),
        "chaos_failovers": (cur_chaos.get("failovers"),
                            base_chaos.get("failovers")),
        # the fast-path signal (ISSUE 7): best dtype speedup vs f32 in
        # the same-record sweep (None-vs-None without --dtype-sweep)
        "dtype_sweep_best_speedup": (
            (cur_d.get("dtype_sweep") or {}).get("best_speedup"),
            (base_d.get("dtype_sweep") or {}).get("best_speedup")),
        # the hot-key cache signals (ISSUE 10): None-vs-None without
        # --zipf; cache-on-vs-cache-off mixes were REFUSED before any
        # load phase, so these rows always compare like with like
        "zipf_hit_ratio": (
            (cur_d.get("zipf") or {}).get("hit_ratio"),
            (base_d.get("zipf") or {}).get("hit_ratio")),
        "zipf_goodput_x": (
            (cur_d.get("zipf") or {}).get("goodput_x"),
            (base_d.get("zipf") or {}).get("goodput_x")),
        "zipf_p99_on_ms": (
            (cur_d.get("zipf") or {}).get("p99_on_ms"),
            (base_d.get("zipf") or {}).get("p99_on_ms")),
        # the fast-lane signals (ISSUE 14): None-vs-None without
        # --lowlat
        "lowlat_p50_improvement_x": (
            (cur_d.get("lowlat") or {}).get("p50_improvement_x"),
            (base_d.get("lowlat") or {}).get("p50_improvement_x")),
        "lowlat_p50_fastlane_ms": (
            (cur_d.get("lowlat") or {}).get("p50_fastlane_ms"),
            (base_d.get("lowlat") or {}).get("p50_fastlane_ms")),
        # the cascade-frontier signals (ISSUE 17): measured end-to-end
        # agreement of the balanced class vs the f32 baseline, the
        # balanced-vs-int8-ceiling efficiency (host-independent), and
        # the calibrated escalation fraction. None-vs-None when either
        # round ran without --cascade — and like every other gated
        # row, a gained/lost leg between rounds prints as prev/cur
        # with no percentage rather than hiding the asymmetry.
        "cascade_agreement": (
            ((cur_d.get("cascade") or {}).get("agreement_vs_f32")
             or {}).get("balanced"),
            ((base_d.get("cascade") or {}).get("agreement_vs_f32")
             or {}).get("balanced")),
        "cascade_efficiency": (
            (cur_d.get("cascade") or {}).get(
                "cascade_efficiency_vs_fast"),
            (base_d.get("cascade") or {}).get(
                "cascade_efficiency_vs_fast")),
        "cascade_escalation_rate": (
            (((cur_d.get("cascade") or {}).get("legs")
              or {}).get("balanced") or {}).get("escalation_fraction"),
            (((base_d.get("cascade") or {}).get("legs")
              or {}).get("balanced") or {}).get("escalation_fraction")),
        # the workload-realism rows (ISSUE 20): None-vs-None without
        # --trace-replay; autoscale-on-vs-off mixes were REFUSED
        # before any load phase, so attainment and chip-cost always
        # compare like with like
        "trace_slo_attainment": (
            (cur_d.get("trace_replay") or {}).get("slo_attainment"),
            (base_d.get("trace_replay") or {}).get("slo_attainment")),
        "trace_chip_s_per_m_requests": (
            (cur_d.get("trace_replay") or {}).get(
                "chip_seconds_per_m_requests"),
            (base_d.get("trace_replay") or {}).get(
                "chip_seconds_per_m_requests")),
        "trace_scale_actions": (
            (cur_d.get("trace_replay") or {}).get("scale_actions"),
            (base_d.get("trace_replay") or {}).get("scale_actions")),
        # the compile-surface provenance row (ISSUE 12): static key
        # count side by side; the fingerprint-set hash comparison is
        # appended below the table (hashes don't delta as percentages).
        # None-vs-None against pre-ISSUE 12 records.
        "compile_surface_keys": (
            (cur_d.get("compile_surface") or {}).get("static_keys"),
            (base_d.get("compile_surface") or {}).get("static_keys")),
        # the gateway fleet rows (ISSUE 19): worker count, closed-loop
        # scaling efficiency and the Zipf sharded-cache hit ratio.
        # None-vs-None on in-process records (gateway-vs-single mixes
        # were REFUSED before any load phase, so these always compare
        # fleet with fleet).
        "gateway_workers": (
            (cur_d.get("gateway") or {}).get("workers"),
            (base_d.get("gateway") or {}).get("workers")),
        "gateway_scaling_efficiency": (
            (cur_d.get("gateway") or {}).get("scaling_efficiency"),
            (base_d.get("gateway") or {}).get("scaling_efficiency")),
        "gateway_shard_hit_ratio": (
            ((cur_d.get("gateway") or {}).get("zipf")
             or {}).get("shard_hit_ratio"),
            ((base_d.get("gateway") or {}).get("zipf")
             or {}).get("shard_hit_ratio")),
    }
    delta = {"path": path,
             "baseline_value": baseline.get("value"),
             "baseline_device_kind": base_d.get("host", {})
             .get("device_kind")}
    _mark(f"baseline delta vs {os.path.basename(path)} "
          f"(device_kind {delta['baseline_device_kind']}):")
    for name, (cur, prev) in rows.items():
        d = pct(cur, prev)
        delta[name] = {"current": cur, "baseline": prev,
                       "delta_pct": d}
        _mark(f"  {name:<24} {prev} -> {cur}"
              f" ({'+' if d is not None and d >= 0 else ''}{d}%)"
              if d is not None else
              f"  {name:<24} {prev} -> {cur}")
    cur_cs = cur_d.get("compile_surface") or {}
    base_cs = base_d.get("compile_surface") or {}
    cur_h = cur_cs.get("fingerprint_set_hash")
    base_h = base_cs.get("fingerprint_set_hash")
    delta["compile_surface"] = {
        "current_hash": cur_h,
        "baseline_hash": base_h,
        "match": (cur_h == base_h if cur_h and base_h else None),
    }
    if cur_h and base_h:
        verdict = ("MATCH" if cur_h == base_h
                   else "CHANGED — the compiled serving graphs differ "
                        "between rounds")
        _mark(f"  {'compile_surface_hash':<24} {base_h} -> {cur_h} "
              f"({verdict})")
    return delta


def _next_serve_artifact(artifact_dir: str) -> str:
    """Next free BENCH_serve_r*.json path: the serve perf trajectory,
    one artifact per bench run, machine-readable like the committed
    BENCH_r*/THROUGHPUT_r* training records."""
    import re

    rounds = [int(m.group(1)) for f in os.listdir(artifact_dir)
              for m in [re.match(r"BENCH_serve_r(\d+)\.json$", f)] if m]
    n = (max(rounds) if rounds else 0) + 1
    return os.path.join(artifact_dir, f"BENCH_serve_r{n:02d}.json")


def _git_provenance() -> dict:
    """The code identity behind a serve artifact: commit hash plus a
    dirty flag, so cross-round deltas can be tied to CODE, not just
    silicon (a record from an uncommitted tree must say so). Best
    effort: a non-repo checkout or missing git yields Nones, never a
    failed bench."""
    import subprocess

    root = os.path.dirname(os.path.abspath(__file__))
    prov = {"git_commit": None, "git_dirty": None}
    try:
        r = subprocess.run(["git", "rev-parse", "HEAD"], cwd=root,
                           capture_output=True, text=True, timeout=10)
        if r.returncode == 0:
            prov["git_commit"] = r.stdout.strip()
            d = subprocess.run(["git", "status", "--porcelain"],
                               cwd=root, capture_output=True, text=True,
                               timeout=10)
            if d.returncode == 0:
                prov["git_dirty"] = bool(d.stdout.strip())
    except (OSError, subprocess.SubprocessError):
        pass
    return prov


def _host_provenance(factory, infer_dtype: str = "float32") -> dict:
    """Host + accelerator + code identity for the serve artifact: which
    machine, which silicon, and which commit produced the number.
    `device_kind` is the honest chip name ('cpu' on the virtual mesh,
    'TPU v4' etc. on real hardware); chip_count restates the
    normalization denominator. `infer_dtype` + `fused_kernels` record
    which PRECISION and hot-op route produced the headline (ISSUE 7
    satellite): an int8 record must be as self-locating as a CPU one —
    --baseline refuses cross-dtype deltas exactly like cross-silicon."""
    import platform as platform_mod
    import socket

    from distributedmnist_tpu.ops import fused as fused_lib

    return {
        "hostname": socket.gethostname(),
        "platform": platform_mod.platform(),
        "machine": platform_mod.machine(),
        "cpu_count": os.cpu_count(),
        "backend": factory.platform,
        "device_kind": factory.mesh.devices.flat[0].device_kind,
        # the whole fleet's distinct chips (== the per-replica count on
        # a single-replica build) — the img/s/chip denominator
        "chip_count": getattr(factory, "total_chips", factory.n_chips),
        # the headline engines' serving precision + resolved fused mode
        "infer_dtype": infer_dtype,
        "fused_kernels": fused_lib.resolve(
            getattr(factory, "fused", "auto"), factory.platform),
        **_git_provenance(),
    }


def _serve_swap_window(registry, factory, batcher, metrics, req,
                       clients: int, duration: float, compiles,
                       seed: int = 101) -> dict:
    """Closed-loop window with a REAL model roll in the middle: after a
    quarter of the window, load + pre-warm a second (fresh-init) version
    on THIS thread while the clients keep hammering the live one, then
    atomically promote it. Returns the swap record: whole-window latency
    snapshot (spanning pre/during/post swap), the candidate's warmup
    cost, and the compile-event count from post-warm to drain — the
    recompiles_after_swap == 0 acceptance signal."""
    import threading

    from distributedmnist_tpu.serve import Rejected

    client_errors: list = []
    stop_evt = threading.Event()

    def client():
        while not stop_evt.is_set():
            try:
                batcher.submit(req).result(timeout=120)
            except Rejected:
                time.sleep(0.001)
            except BaseException as e:
                client_errors.append(e)
                return

    threads = [make_thread(target=client, name=f"bench-swap-client-{i}",
                           daemon=True)
               for i in range(clients)]
    for t in threads:
        t.start()
    time.sleep(min(0.5, duration * 0.2))     # unmeasured ramp
    metrics.reset()
    t_win0 = time.monotonic()
    time.sleep(duration * 0.25)              # steady traffic pre-swap
    version = "v-swap"
    t_load0 = time.monotonic()
    mv = registry.add(factory.init_params(seed), version=version,
                      source="fresh-init")   # load + pre-warm: hot path
    #                                          keeps serving throughout
    steady_from = compiles.snapshot()        # post-warm, pre-promote
    registry.promote(version)
    t_swap = time.monotonic()
    _mark(f"hot-swap: {version} warmed in {t_swap - t_load0:.2f}s "
          f"({mv.warmup_compile_events} compile events), promoted")
    # post-swap tail: the new version takes ALL traffic inside the same
    # measured window, so a cold bucket would show up in THIS p99
    time.sleep(max(duration * 0.5, 0.5))
    stop_evt.set()
    for t in threads:
        t.join()
    if client_errors:
        raise RuntimeError(
            f"{len(client_errors)} of {clients} swap-window clients "
            "died — a hot-swap must not fail requests") \
            from client_errors[0]
    _drain_or_die(batcher, timeout=120)
    recompiles = compiles.snapshot() - steady_from
    snap = metrics.snapshot()
    return {
        "version": version,
        "window_s": round(time.monotonic() - t_win0, 3),
        "load_warm_s": round(mv.warmup_s, 3),
        "warmup_compile_events": mv.warmup_compile_events,
        "recompiles_after_swap": recompiles,
        "swap_window": snap,
    }


def _serve(args) -> int:
    """Serving load harness: closed-loop capacity (the headline
    images/sec/chip) measured at the pipelined in-flight window AND at
    the serial inflight=1 baseline — the overlap win is a measured
    ratio, not a claim — plus an open-loop Poisson QPS sweep giving the
    latency-vs-throughput table (with an inflight=1 p99 comparison point
    at the lowest, sub-capacity target), and optionally
    (--swap-during-load) a closed-loop window crossing a real pre-warmed
    hot-swap. Same perf discipline as the training bench: bucket warmup
    (compile) excluded from every window, per-chip normalization, and a
    recompile counter proving steady state ran shape-stable. The whole
    record is also written to a BENCH_serve_r*.json artifact
    (--artifact-dir / --no-artifact)."""
    import numpy as np

    from distributedmnist_tpu.config import Config
    from distributedmnist_tpu.serve import (DynamicBatcher, ServeMetrics,
                                            build_serving)
    from distributedmnist_tpu.utils import CompileCounter

    from distributedmnist_tpu.serve import build_resilience

    cfg = Config(model=args.model, dtype=args.dtype,
                 serve_replicas=args.serve_replicas or 1,
                 serve_hedge=bool(args.serve_hedge))
    metrics = ServeMetrics()
    # Resolve backend-dependent defaults AFTER the backend is up (the
    # same pattern as bench_steps): CPU phases are kept short — each
    # sweep point costs its full wall-clock duration. build_serving
    # loads no version, so the probe-then-rebuild costs nothing.
    registry, router, factory = build_serving(cfg.replace(
        serve_max_batch=(cfg.serve_max_batch
                         if args.serve_max_batch is None
                         else args.serve_max_batch)), metrics=metrics)
    backend = factory.platform
    on_cpu = backend == "cpu"
    _mark(f"backend up: {factory.total_chips}x {backend}")
    if args.serve_max_batch is None and on_cpu:
        # rebuild with the CPU-sized bucket ladder (cheap: CPU compiles
        # are fast and the persistent cache absorbs repeats)
        registry, router, factory = build_serving(
            cfg.replace(serve_max_batch=128), metrics=metrics)
    # The replica fleet, when benching one (--serve-replicas >= 2): the
    # fleet leg and the chaos replica-kill storm hang off it; img/s/chip
    # normalizes by the WHOLE fleet's chips (a 2-replica fleet on 2x
    # the silicon must not report 2x the per-chip number).
    fleet = router if getattr(router, "n_replicas", 1) > 1 else None
    n_chips = factory.total_chips
    # `is None` checks, not `or`: an explicit 0 (e.g. --serve-max-wait-us
    # 0 to measure the no-coalescing latency floor) must be honored.
    max_wait_us = (cfg.serve_max_wait_us if args.serve_max_wait_us is None
                   else args.serve_max_wait_us)
    queue_depth = (cfg.serve_queue_depth if args.serve_queue_depth is None
                   else args.serve_queue_depth)
    duration = ((2.0 if on_cpu else 10.0) if args.serve_duration is None
                else args.serve_duration)
    clients = ((8 if on_cpu else 64) if args.serve_clients is None
               else args.serve_clients)
    qps_sweep = (([50.0, 200.0] if on_cpu
                  else [1000.0, 4000.0, 16000.0])
                 if args.serve_qps is None else args.serve_qps)
    rows = args.serve_rows
    # The headline phase's pipeline depth. Unlike serve.py's auto rule
    # (1 on CPU), the bench defaults to a real window even on CPU: the
    # whole point of this harness is to MEASURE the overlap win against
    # the always-run inflight=1 serial baseline.
    pipelined = (4 if args.serve_max_inflight is None
                 else args.serve_max_inflight)

    baseline_rec = None
    if args.baseline:
        with open(args.baseline) as f:
            baseline_rec = json.load(f)       # shape pre-validated
        if baseline_rec["detail"].get("gateway") is not None:
            # A gateway-fleet record's aggregate img/s (N processes)
            # is no baseline for a single-process run — as
            # incomparable as cross-silicon (ISSUE 19).
            _mark(f"REFUSING --baseline {args.baseline}: it is a "
                  "--gateway fleet record "
                  f"({baseline_rec['detail']['gateway'].get('workers')}"
                  " workers); this run is single-process — compare "
                  "gateway rounds with bench.py serve --gateway N "
                  "--baseline <gateway record>")
            return 4
        base_kind = baseline_rec["detail"]["host"]["device_kind"]
        this_kind = _host_provenance(factory)["device_kind"]
        if base_kind != this_kind:
            # The ROADMAP warning, mechanized: refuse BEFORE any load
            # phase — a delta table across different silicon is exactly
            # the CPU-record-as-TPU-headline confusion this flag exists
            # to prevent.
            _mark(f"REFUSING --baseline {args.baseline}: it was "
                  f"measured on device_kind={base_kind!r}, this host "
                  f"is {this_kind!r} — cross-silicon serve deltas are "
                  "meaningless (ROADMAP: CPU records must not "
                  "masquerade as TPU headlines)")
            return 4
        # Cache-on-vs-cache-off zipf records are as incomparable as
        # cross-dtype ones (ISSUE 10): a hot-key goodput number with
        # the cache on must never print a delta against a cache-off
        # control round (or vice versa).
        base_zipf = baseline_rec["detail"].get("zipf")
        if args.zipf and isinstance(base_zipf, dict):
            cur_cache_on = not args.zipf_cache_off
            base_cache_on = bool(base_zipf.get("cache_enabled"))
            if cur_cache_on != base_cache_on:
                _mark(f"REFUSING --baseline {args.baseline}: its zipf "
                      f"leg ran cache_enabled={base_cache_on}, this "
                      f"run is cache_enabled={cur_cache_on} — "
                      "cache-on-vs-cache-off serve deltas are "
                      "meaningless (an uncached control must not "
                      "masquerade as a cache regression, nor a cached "
                      "round as a pipeline win)")
                return 4
        # Autoscale-on-vs-off trace-replay records are equally
        # incomparable (ISSUE 20): the static control's attainment
        # must never print a delta against an autoscaled round.
        base_tr = baseline_rec["detail"].get("trace_replay")
        if args.trace_replay and isinstance(base_tr, dict):
            cur_as = bool(args.autoscale)
            base_as = bool(base_tr.get("autoscale_enabled"))
            if cur_as != base_as:
                _mark(f"REFUSING --baseline {args.baseline}: its "
                      "trace-replay leg ran autoscale_enabled="
                      f"{base_as}, this run is autoscale_enabled="
                      f"{cur_as} — autoscale-on-vs-off trace deltas "
                      "are meaningless (a static control must not "
                      "masquerade as an autoscaler regression, nor an "
                      "autoscaled round as a static win)")
                return 4

    _mark(f"warming {len(factory.buckets)} buckets "
          f"{list(factory.buckets)}")
    boot = registry.bootstrap(seed=cfg.seed)   # load + pre-warm + promote
    warm_compiles = boot.warmup_compile_events
    # Headline serving precision (ISSUE 7): warm + parity-gate the
    # requested variant(s) and promote the pick BEFORE any measured
    # phase. An explicitly requested dtype whose variant the gate
    # refuses fails the bench — the measurement was asked for at a
    # precision that must never serve.
    if args.serve_infer_dtype and args.serve_infer_dtype != "float32":
        _mark(f"activating inference fast path: "
              f"{args.serve_infer_dtype}")
        registry.activate_infer_dtype(boot.version,
                                      args.serve_infer_dtype)
    headline_dtype = router.live_infer_dtype() or "float32"
    if baseline_rec is not None:
        base_dtype = (baseline_rec["detail"]["host"].get("infer_dtype")
                      or "float32")   # pre-ISSUE 7 records were all f32
        if base_dtype != headline_dtype:
            _mark(f"REFUSING --baseline {args.baseline}: it was "
                  f"measured at infer_dtype={base_dtype!r}, this run "
                  f"serves {headline_dtype!r} — cross-dtype serve "
                  "deltas are meaningless (an int8 record must not "
                  "masquerade as an f32 win)")
            return 4
    compiles = CompileCounter.instance()
    steady_from = compiles.snapshot()

    rng = np.random.default_rng(0)
    req = rng.integers(0, 256, (rows, 28, 28, 1), dtype=np.uint8)

    # Every bench batcher runs WITH the resilience stack wired (bisect +
    # breaker + rid/deadline plumbing), exactly as serve.py wires it:
    # the happy-path headline therefore PRICES the resilience layer —
    # chaos-off capacity within noise of the pre-ISSUE 5 record is the
    # no-tax proof, not an unwired best case. The chaos leg swaps in its
    # own tighter-windowed policy.
    default_resilience = build_resilience(cfg, registry=registry,
                                          metrics=metrics)

    def make_batcher(max_inflight: int, split: bool = True,
                     adaptive: bool = None, wait_us: int = None,
                     resilience=None,
                     dedup: bool = False,
                     fastlane: bool = False,
                     max_batch: int = None) -> DynamicBatcher:
        if adaptive is None:
            adaptive = not args.no_adaptive
        return DynamicBatcher(router, max_batch=(factory.max_batch
                                                 if max_batch is None
                                                 else max_batch),
                              max_wait_us=(max_wait_us if wait_us is None
                                           else wait_us),
                              queue_depth=queue_depth,
                              max_inflight=max_inflight,
                              slo_ms=args.serve_slo_ms,
                              adaptive=adaptive, split=split,
                              resilience=(default_resilience
                                          if resilience is None
                                          else resilience),
                              dedup=dedup, fastlane=fastlane,
                              metrics=metrics).start()

    # Phase 1 — serial baseline: inflight=1 is the pre-pipeline chain
    # (stage, dispatch, fetch, fan out, repeat), the honest denominator
    # of the overlap win; plus one sub-capacity open-loop point so the
    # pipelined p99 has a latency comparison, not just a rate one.
    low_qps = min(qps_sweep)
    serial = make_batcher(1)
    _mark(f"closed loop [inflight=1]: {clients} clients x {duration:.0f}s")
    closed_serial = _serve_closed_loop(serial, metrics, [req], clients,
                                       duration)
    serial_value = closed_serial["rows_per_sec"] / n_chips
    _mark(f"closed loop [inflight=1]: {serial_value:.0f} img/s/chip "
          f"(p99 {closed_serial['latency_ms']['p99']} ms)")
    _mark(f"open loop [inflight=1] qps={low_qps:g}")
    _, open_serial = _serve_open_loop(serial, metrics, [req], low_qps,
                                      duration, max_wait_us)
    serial.stop()

    # Phase 2 — the pipelined window: the headline capacity and the
    # full QPS sweep.
    piped = make_batcher(pipelined)
    _mark(f"closed loop [inflight={piped.max_inflight}]: "
          f"{clients} clients x {duration:.0f}s")
    closed = _serve_closed_loop(piped, metrics, [req], clients, duration)
    value = closed["rows_per_sec"] / n_chips
    speedup = value / max(serial_value, 1e-9)
    _mark(f"closed loop [inflight={piped.max_inflight}]: {value:.0f} "
          f"img/s/chip (p99 {closed['latency_ms']['p99']} ms, "
          f"{speedup:.2f}x serial)")

    table = []
    for qps in qps_sweep:
        submitted, snap = _serve_open_loop(piped, metrics, [req], qps,
                                           duration, max_wait_us)
        table.append({
            "qps_target": qps,
            "qps_submitted": round(submitted / duration, 1),
            "requests_per_sec": snap["requests_per_sec"],
            "img_s_chip": round(snap["rows_per_sec"] / n_chips, 1),
            "latency_ms": snap["latency_ms"],
            "mean_rows_per_batch": snap["mean_rows_per_batch"],
            "batch_occupancy": snap["batch_occupancy"],
            "rejected_requests": snap["rejected_requests"],
            "inflight_mean": snap["inflight_mean"],
            "inflight_max": snap["inflight_max"],
        })
        _mark(f"open loop qps={qps:g}: p50="
              f"{snap['latency_ms']['p50']} ms, "
              f"{snap['rejected_requests']} rejected")

    # Phase 3 — the ragged-arrival leg: the batch former's measured
    # win (padding-waste reduction at no-worse goodput) on a fixed
    # mixed-size request stream, former off vs on. Runs on its own
    # batchers; the pipelined batcher stays up for the optional swap
    # phase below.
    ragged = _serve_ragged_leg(router, metrics, factory, make_batcher,
                               pipelined, clients, duration, low_qps,
                               max_wait_us)

    # Phase 3a (optional) — the single-request low-latency leg
    # (ISSUE 14): one closed-loop client, 1-row requests, coalescing
    # path vs the bypass fast lane (and the megakernel variant where
    # the model has one), with the fastpath attribution sub-phase.
    # Runs on its own batchers; the megakernel variant's warmup
    # compiles are excluded from the whole-run recompile check below.
    lowlat_leg = None
    if args.lowlat:
        lowlat_leg = _serve_lowlat_leg(registry, router, factory,
                                       metrics, make_batcher, compiles,
                                       duration, max_wait_us,
                                       args.model)

    # Phase 3b (optional) — the hot-key leg (ISSUE 10): the SAME
    # Zipf-distributed request mix closed-loop with the prediction
    # cache + single-flight front off then on, on its own batchers —
    # the headline phases above stay cache-less, so the capacity
    # number keeps pricing the raw pipeline.
    zipf_leg = None
    if args.zipf:
        zipf_leg = _serve_zipf_leg(
            router, metrics, factory, make_batcher, pipelined, clients,
            duration, cache_on=not args.zipf_cache_off,
            cache_capacity=args.serve_cache_capacity or 4096)

    # Phase 3c (optional) — the request-tracing leg (ISSUE 9): a
    # mixed-size open-loop window under an installed tracer, per-
    # request stage attribution for the over-SLO tail, and the Chrome
    # trace artifact. Runs on its own batcher with its own tracer —
    # every other phase stays tracer-off, so the headline numbers
    # price a PRODUCTION (uninstalled) pipeline.
    trace_leg = None
    chrome_events: list = []
    if args.trace:
        trace_leg = _serve_trace_leg(router, metrics, factory,
                                     make_batcher, pipelined, duration,
                                     low_qps, chrome_events)

    # Phase 3d (optional) — the workload-realism leg (ISSUE 20): a
    # seeded deterministic trace replayed against a static trough-
    # provisioned config and (with --autoscale) under the closed-loop
    # autoscaler — SLO attainment and chip-seconds per million
    # within-SLO requests on the identical schedule, scale moving only
    # along the warmed bucket ladder (covered by the whole-run
    # recompile check below). With --chaos a third sub-phase replays
    # the trace under a seeded fault storm to show the controller
    # buying capacity through it.
    trace_replay_leg = None
    if args.trace_replay:
        trace_replay_leg = _serve_trace_replay_leg(
            router, metrics, factory, make_batcher, args.trace_replay,
            seed=cfg.seed, autoscale=bool(args.autoscale),
            slo_ms=(args.serve_slo_ms
                    or (25.0 if on_cpu else 10.0)),
            chaos=bool(args.chaos))

    # Phase 4 (optional) — the model roll: closed-loop traffic crossing
    # a real load + pre-warm + atomic promote (ISSUE 3 acceptance:
    # recompiles_after_swap == 0 and swap-window p99 within 1.5x the
    # steady-state p99 on the same host). Runs BEFORE the whole-run
    # recompile check so the candidate's legitimate warmup compiles are
    # excluded from it (steady_from is re-sampled inside).
    swap = None
    if args.swap_during_load:
        _mark(f"swap window [inflight={piped.max_inflight}]: "
              f"{clients} clients, hot-swap mid-window")
        swap = _serve_swap_window(registry, factory, piped, metrics, req,
                                  clients, duration, compiles)
        steady_p99 = closed["latency_ms"]["p99"]
        swap_p99 = swap["swap_window"]["latency_ms"]["p99"]
        swap["steady_p99_ms"] = steady_p99
        swap["swap_window_p99_ms"] = swap_p99
        swap["p99_ratio_vs_steady"] = (
            round(swap_p99 / steady_p99, 3)
            if steady_p99 and swap_p99 is not None else None)
        # The decomposed tail: the new version serves ONLY after the
        # promote, so its by_version p99 is the pure post-swap
        # population — the Clockwork claim ("no cold buckets after the
        # swap") in one number. The whole-window ratio above
        # additionally charges the candidate's warmup-time host-CPU
        # contention to the OLD version's requests, which on a
        # shared-core (CPU) host dominates the window; on a TPU host
        # compile is host-side work while serving compute is on-device,
        # so the two ratios converge.
        post = swap["swap_window"]["by_version"].get(swap["version"])
        post_p99 = post["latency_ms"]["p99"] if post else None
        swap["post_swap_p99_ms"] = post_p99
        swap["post_swap_p99_ratio_vs_steady"] = (
            round(post_p99 / steady_p99, 3)
            if steady_p99 and post_p99 is not None else None)
        _mark(f"swap window: p99 {swap_p99} ms vs steady {steady_p99} ms"
              f" (ratio {swap['p99_ratio_vs_steady']}; post-swap "
              f"population {post_p99} ms, ratio "
              f"{swap['post_swap_p99_ratio_vs_steady']}), "
              f"{swap['recompiles_after_swap']} recompiles after swap")
    piped.stop()

    # Phase 4b (fleet runs only) — the replica-scaling leg (ISSUE 6):
    # the same warmed fleet closed-loop at one active replica (siblings
    # drained) and at full strength, for the dispatch-balance and
    # scaling-efficiency numbers. Uses the admin drain/rejoin path
    # itself, so the bench exercises it on every fleet run.
    fleet_leg = None
    if fleet is not None:
        fleet_leg = _serve_fleet_leg(fleet, metrics, make_batcher,
                                     clients, duration, req)

    # Phase 4c (optional) — the dtype sweep (ISSUE 7 acceptance):
    # f32/bf16/int8 closed-loop back-to-back behind the parity gate,
    # before the chaos leg so an injected storm can't contaminate the
    # comparison. Variant warmups are legitimate warmup compiles,
    # excluded from the whole-run recompile check below.
    dtype_sweep = None
    if args.dtype_sweep:
        dtype_sweep = _serve_dtype_sweep(registry, router, factory,
                                         metrics, make_batcher, compiles,
                                         pipelined, clients, duration)

    # Phase 4d (optional) — the confidence-gated cascade leg
    # (ISSUE 17): the goodput-vs-accuracy frontier — f32-only, int8-
    # only, and the calibrated cascade (plus a stressed operating
    # point) on one seeded stream, with measured end-to-end agreement
    # and per-phase escalation fractions. Also before the chaos leg so
    # an injected storm can't contaminate the frontier; the int8
    # variant + calibration warmups are excluded from the whole-run
    # recompile check below.
    cascade_leg = None
    if args.cascade:
        cascade_leg = _serve_cascade_leg(registry, router, factory,
                                         metrics, make_batcher, compiles,
                                         pipelined, clients, duration)

    # Phase 4e (optional) — the multi-tenant leg (ISSUE 18): MLP +
    # LeNet behind the global WFQ/EDF scheduler on their own catalog,
    # the light tenant's solo-vs-contended p99, the fairness ratios
    # and per-tenant SLO attainment. Before the chaos leg for the same
    # contamination reason; the catalog's two model warmups are
    # excluded from the whole-run recompile check below.
    multimodel_leg = None
    if args.multimodel:
        multimodel_leg = _serve_multimodel_leg(compiles, duration, rows)

    # Phase 5 (optional) — the chaos leg (ISSUE 5 acceptance): seeded
    # fault schedule against the resilience stack, after the clean
    # phases so an injected storm can't contaminate the happy-path
    # numbers. Runs on its own batcher; leaves the fallback version
    # live when the forced breaker trip rolled back.
    chaos = None
    if args.chaos:
        # With --trace the chaos leg runs under its own tracer: the
        # acceptance check is that a failover rescue and a bisect
        # split appear as STRUCTURED spans in real request traces, not
        # only as counters.
        chaos_tracer = None
        if args.trace:
            from distributedmnist_tpu.serve import trace as trace_lib
            chaos_tracer = trace_lib.install(trace_lib.Tracer(
                capacity=4096, sample=1.0, slo_ms=args.serve_slo_ms,
                seed=17))
        try:
            # 2x the sub-capacity sweep rate: drains must coalesce
            # several requests for poison isolation to have cohorts to
            # rescue
            chaos = _serve_chaos_leg(
                registry, router, factory, metrics, make_batcher,
                compiles, pipelined, duration, 2 * low_qps,
                cache_capacity=((args.serve_cache_capacity or 4096)
                                if args.serve_cache else None))
        finally:
            if chaos_tracer is not None:
                trace_lib.uninstall()
        if chaos_tracer is not None:
            census = _span_census(chaos_tracer)
            n_bisect = census["spans"].get("bisect.split", 0)
            n_rescue = (census["spans"].get("fleet.failover.fetch", 0)
                        + census["spans"].get("fleet.failover.dispatch",
                                              0))
            n_rescue_parented = (
                census["parented"].get("fleet.failover.fetch", 0)
                + census["parented"].get("fleet.failover.dispatch", 0))
            trace_leg["chaos"] = {
                "bisect_split_spans": n_bisect,
                "bisect_dispatch_spans":
                    census["spans"].get("bisect.dispatch", 0),
                "failover_rescue_spans": n_rescue,
                "failover_rescue_spans_parented": n_rescue_parented,
                "deadline_shed_spans":
                    census["spans"].get("deadline.shed", 0),
                # ISSUE 9 acceptance: the chaos trace shows >= 1 bisect
                # split and (fleet runs) >= 1 failover rescue as
                # structured child spans
                "bisect_split_ok": n_bisect >= 1,
                "failover_rescue_ok": (
                    n_rescue_parented >= 1 if fleet is not None
                    else None),
            }
            _mark(f"chaos trace: {n_bisect} bisect.split spans, "
                  f"{n_rescue} failover rescue spans "
                  f"({n_rescue_parented} parented), "
                  f"{trace_leg['chaos']['deadline_shed_spans']} "
                  "deadline.shed spans")
            # distinct pid: tid numbers are per-export, and merged
            # metadata under one pid would relabel the first leg's
            # tracks (see Tracer.export_chrome)
            chrome_events.extend(chaos_tracer.export_chrome(
                pid=2, process_name="distributedmnist-serve-chaos"
            )["traceEvents"])

    recompiles = compiles.snapshot() - steady_from
    if swap is not None:
        # the candidate's warmup compiles are warmup, not steady-state
        # recompiles — same exclusion the boot warmup gets
        recompiles -= swap["warmup_compile_events"]
    if chaos is not None:
        # same exclusion for the chaos fallback's off-hot-path warmup
        recompiles -= chaos["fallback_warmup_compile_events"]
    if dtype_sweep is not None:
        # and for the sweep variants' off-hot-path warmups
        recompiles -= dtype_sweep["variant_warmup_compile_events"]
    if cascade_leg is not None:
        # and for the cascade leg's int8 + calibration warmup
        recompiles -= cascade_leg["variant_warmup_compile_events"]
    if multimodel_leg is not None:
        # and for the tenancy catalog's two per-model warmups
        recompiles -= multimodel_leg["warmup_compile_events"]
    if lowlat_leg is not None:
        # and for the lowlat leg's megakernel variant warmup
        recompiles -= lowlat_leg["variant_warmup_compile_events"]
    if recompiles:
        _mark(f"WARNING: {recompiles} compile events after warmup — "
              "steady state was supposed to be shape-stable")
    open_piped_low = next(r for r in table
                          if r["qps_target"] == low_qps)
    # Compile-surface provenance (ISSUE 12 satellite): the static jit
    # cache-key count and fingerprint-set hash of THIS record's serving
    # geometry at its headline precision, computed by the abstract
    # auditor (analysis/jaxcheck.py) on the canonical CPU trace basis —
    # so a --baseline delta shows when two rounds' compiled surfaces
    # silently diverged, alongside the host provenance that already
    # guards silicon and dtype.
    from distributedmnist_tpu.analysis import jaxcheck

    compile_surface = jaxcheck.compile_surface_summary(
        args.model, factory.buckets, factory.max_batch, headline_dtype,
        fused_kernels=cfg.fused_kernels, cfg_dtype=args.dtype)
    _mark(f"compile surface: {compile_surface['static_keys']} static "
          f"keys at {headline_dtype}, fingerprint set "
          f"{compile_surface['fingerprint_set_hash']}")
    record = {
        "metric": "serve_images_per_sec_per_chip",
        "value": round(value, 1),
        "unit": "images/sec/chip",
        # Serving shares the training north-star rate target: a system
        # meeting 2,500 img/s/chip in training should serve at least as
        # fast forward-only.
        "vs_baseline": round(value / TARGET_IPS_PER_CHIP, 3),
        "detail": {
            "model": args.model,
            "dtype": args.dtype,
            "backend": backend,
            "n_chips": n_chips,
            # Provenance: where this number was measured. CPU-host
            # numbers (like the 1.08x PR 2 result) must never be
            # conflated with TPU headlines when comparing rounds — the
            # host block makes every BENCH_serve_r*.json self-locating.
            "host": _host_provenance(factory, infer_dtype=headline_dtype),
            # The static compile surface this record serves from
            # (ISSUE 12): key count + fingerprint-set hash, the
            # --baseline delta's compile-surface provenance row.
            "compile_surface": compile_surface,
            "buckets": list(factory.buckets),
            "max_batch": factory.max_batch,
            "max_wait_us": max_wait_us,
            "queue_depth": queue_depth,
            "max_inflight": piped.max_inflight,
            "rows_per_request": rows,
            "clients": clients,
            "duration_s": duration,
            "params": boot.source,
            "live_version_final": registry.live_version(),
            "warmup_compile_events": warm_compiles,
            "recompiles_after_warmup": recompiles,
            "bucket_cost_ms": {str(b): round(c * 1e3, 3)
                               for b, c in sorted(
                                   router.bucket_costs().items())},
            "slo_ms": args.serve_slo_ms,
            "adaptive": not args.no_adaptive,
            "closed_loop": closed,
            "qps_sweep": table,
            "ragged": ragged,
            # The hot-key leg (ISSUE 10; None without --zipf): hit
            # ratio, goodput ratio, p99 and device-dispatch counts for
            # the same Zipf mix with the prediction cache off vs on,
            # plus the byte-identity parity probes and the
            # single-flight collapse count. cache_enabled marks
            # control (--zipf-cache-off) records — --baseline refuses
            # deltas across that boundary.
            "zipf": zipf_leg,
            # The single-request low-latency leg (ISSUE 14; None
            # without --lowlat): batched-vs-fastlane p50/p99 at one
            # in-flight client, the megakernel phase + parity verdict,
            # the fastpath attribution floor, and the lane counters.
            "lowlat": lowlat_leg,
            # The workload-realism leg (ISSUE 20; None without
            # --trace-replay): the seeded trace spec + schedule hash,
            # static-vs-autoscaled SLO attainment and chip-seconds per
            # million within-SLO requests, the full scale-action log
            # with priced decisions, the flap audit (zero by
            # construction, counted from the log), and the acceptance
            # bars with the slo_bar_reachable honesty disclosure.
            "trace_replay": trace_replay_leg,
            "swap": swap,
            "chaos": chaos,
            # The tracing leg (ISSUE 9; None without --trace): the SLO
            # basis, the per-over-SLO-request stage-attribution table
            # (residue reported per request), the span census, and —
            # with --chaos — the structured-span assertions for
            # failover rescues and bisect splits.
            "trace": trace_leg,
            # The inference fast-path leg (ISSUE 7; None without
            # --dtype-sweep): per-dtype closed-loop capacity, parity
            # verdicts, per-dtype bucket cost tables, per-dtype
            # recompile counts (all 0), and the speedup-vs-f32 pair the
            # acceptance bar reads.
            "dtype_sweep": dtype_sweep,
            # The cascade leg (ISSUE 17; None without --cascade): the
            # goodput-vs-accuracy frontier (exact/fast/balanced + the
            # stressed point), the calibrated threshold + gate record,
            # measured end-to-end agreement vs f32, per-phase
            # escalation fractions and recompile counts, and the
            # goodput_ok/agreement_ok acceptance bars.
            "cascade": cascade_leg,
            # The multi-tenant leg (ISSUE 18; None without
            # --multimodel): two models behind the global scheduler,
            # light-tenant p99 solo vs under a heavy burst (bar:
            # <= 1.5x), per-tenant dispatch-share/weight-share
            # fairness ratios (bar: [0.8, 1.25]), SLO attainment, the
            # observed DRR skip maximum, and the recompile count.
            "multimodel": multimodel_leg,
            # The fleet block (ISSUE 6; None on single-replica runs):
            # per-replica provenance — which devices each replica owns
            # and whether the slices are disjoint silicon or logical
            # replicas on shared chips — plus the scaling leg and the
            # end-of-run fleet state (dispatch totals, failovers,
            # health).
            "replicas": ({
                "count": fleet.n_replicas,
                "per_replica_inflight": fleet.per_replica_inflight,
                "per_replica_chips": factory.n_chips,
                "disjoint_devices": (factory.total_chips
                                     == factory.n_chips
                                     * fleet.n_replicas),
                "provenance": [
                    {"id": rep.rid,
                     "devices": [str(d) for d in
                                 factory.meshes[i].devices.flat]}
                    for i, rep in enumerate(fleet.replicas)],
                "fleet_leg": fleet_leg,
                "final": fleet.snapshot(),
            } if fleet is not None else None),
            # The measured overlap win (ISSUE 2 acceptance): pipelined
            # capacity over the serial chain, and sub-capacity open-loop
            # latency at both depths — pipelining must buy throughput
            # without costing the lightly-loaded p99.
            "inflight_comparison": {
                "serial_img_s_chip": round(serial_value, 1),
                "pipelined_img_s_chip": round(value, 1),
                "speedup": round(speedup, 3),
                "closed_loop_serial": closed_serial,
                "open_loop_qps": low_qps,
                "open_loop_serial_latency_ms": open_serial["latency_ms"],
                "open_loop_pipelined_latency_ms":
                    open_piped_low["latency_ms"],
            },
        },
    }
    if baseline_rec is not None:
        record["detail"]["baseline"] = _baseline_delta(
            record, baseline_rec, args.baseline)
    print(json.dumps(record))
    if not args.no_artifact:
        # Best-effort: the record is already on stdout; an unwritable
        # DEFAULT dir (no --artifact-dir given, so never pre-validated —
        # e.g. a read-only checkout) must not turn a completed run into
        # a nonzero exit.
        artifact_dir = args.artifact_dir or os.path.dirname(
            os.path.abspath(__file__))
        try:
            path = _next_serve_artifact(artifact_dir)
            with open(path, "w") as f:
                json.dump(record, f, indent=1)
                f.write("\n")
            _mark(f"artifact: {path}")
            if args.trace and chrome_events:
                # the Chrome trace-event artifact rides beside the
                # record (same round number): load it in
                # chrome://tracing or ui.perfetto.dev
                tpath = path[:-len(".json")] + "_trace.json"
                with open(tpath, "w") as f:
                    json.dump({"traceEvents": chrome_events,
                               "displayTimeUnit": "ms"}, f)
                    f.write("\n")
                _mark(f"trace artifact: {tpath}")
        except OSError as e:
            _mark(f"WARNING: artifact not written ({e}); the record "
                  "above is the only copy")
    return 0


def _gw_http(port: int, method: str, path: str, body=None,
             timeout: float = 60.0) -> tuple:
    """One urllib round-trip to the gateway (or a worker) on 127.0.0.1:
    (status, headers dict, parsed-JSON-or-raw). Non-2xx answers come
    back as values, never exceptions — the harness asserts on status
    codes explicitly."""
    import urllib.error
    import urllib.request

    headers = {}
    if isinstance(body, (bytes, bytearray)):
        headers["Content-Type"] = "application/json"
    req = urllib.request.Request(f"http://127.0.0.1:{port}{path}",
                                 data=body, method=method,
                                 headers=headers)
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            raw, status, hdrs = r.read(), r.status, dict(r.headers)
    except urllib.error.HTTPError as e:
        raw, status, hdrs = e.read(), e.code, dict(e.headers)
    try:
        return status, hdrs, json.loads(raw)
    except ValueError:
        return status, hdrs, raw


def _gw_lat_ms(lat_s: list) -> dict:
    import numpy as np

    if not lat_s:
        return {"p50": None, "p95": None, "p99": None}
    a = np.asarray(lat_s)
    return {"p50": round(float(np.percentile(a, 50)) * 1e3, 3),
            "p95": round(float(np.percentile(a, 95)) * 1e3, 3),
            "p99": round(float(np.percentile(a, 99)) * 1e3, 3)}


class _GatewayFleet:
    """Handle on a spawned `serve.py --gateway N` process tree. The
    bench process itself never imports jax — every number is measured
    over HTTP exactly as an operator's client would see it, and the
    per-worker cache/compile evidence is polled DIRECTLY on the worker
    ports the gateway_ready line announces."""

    def __init__(self, args, n_workers: int):
        import subprocess
        import tempfile

        repo = os.path.dirname(os.path.abspath(__file__))
        argv = [sys.executable, os.path.join(repo, "serve.py"),
                "--model", args.model, "--gateway", str(n_workers),
                "--serve-cache", "--port", "0", "--metrics-every", "30",
                "--serve-max-batch",
                str(16 if args.serve_max_batch is None
                    else args.serve_max_batch)]
        for flag, val in (("--serve-max-wait-us", args.serve_max_wait_us),
                          ("--serve-queue-depth", args.serve_queue_depth),
                          ("--serve-slo-ms", args.serve_slo_ms),
                          ("--serve-infer-dtype", args.serve_infer_dtype),
                          ("--serve-cache-capacity",
                           args.serve_cache_capacity)):
            if val is not None:
                argv += [flag, str(val)]
        if args.no_adaptive:
            argv.append("--no-adaptive")
        self.n = n_workers
        self._errf = tempfile.NamedTemporaryFile(
            mode="w+", suffix=".gateway.stderr", delete=False)
        self.proc = subprocess.Popen(argv, stdout=subprocess.PIPE,
                                     stderr=self._errf, text=True,
                                     cwd=repo)
        self.port, self.worker_ports = None, []
        deadline = time.monotonic() + 900
        while time.monotonic() < deadline:
            line = self.proc.stdout.readline()
            if not line:
                break
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if rec.get("metric") == "gateway_ready":
                self.port = rec["port"]
                self.worker_ports = list(rec["worker_ports"])
                break
        if self.port is None:
            self.stop()
            raise RuntimeError("gateway never announced readiness; "
                               "stderr tail:\n" + self._stderr_tail())
        make_thread(target=self._drain, name="bench-gw-drain",
                    daemon=True).start()

    def _drain(self):
        # keep reading the gateway's stdout (periodic metrics lines) so
        # the pipe never fills and stalls it
        for _ in self.proc.stdout:
            pass

    def _stderr_tail(self) -> str:
        try:
            self._errf.flush()
            with open(self._errf.name) as f:
                return f.read()[-4000:]
        except OSError:
            return "<unavailable>"

    def wait_healthy(self, want_dtype: str = None,
                     deadline_s: float = 900.0) -> dict:
        deadline = time.monotonic() + deadline_s
        payload = None
        while time.monotonic() < deadline:
            if self.proc.poll() is not None:
                raise RuntimeError(
                    f"gateway exited rc={self.proc.returncode} while "
                    "warming; stderr tail:\n" + self._stderr_tail())
            try:
                st, _, payload = _gw_http(self.port, "GET", "/healthz",
                                          timeout=10.0)
            except OSError:
                st = None
            if st == 200 and isinstance(payload, dict):
                rows = payload.get("workers") or []
                if (len(rows) == self.n
                        and all(r.get("ok") for r in rows)
                        and (want_dtype is None
                             or all(r.get("live_infer_dtype")
                                    == want_dtype for r in rows))):
                    return payload
            time.sleep(0.5)
        raise RuntimeError("gateway fleet never became healthy: "
                           f"{payload}; stderr tail:\n"
                           + self._stderr_tail())

    def worker_stats(self) -> dict:
        """Per-worker cache hit/miss + compile counters (the sharded-
        cache and steady-state-recompile evidence is per WORKER — the
        gateway deliberately holds no cache and no engine of its own)."""
        out = {}
        for wp in self.worker_ports:
            st, _, payload = _gw_http(wp, "GET", "/metrics",
                                      timeout=10.0)
            cache = (payload.get("cache") or {}) if st == 200 else {}
            out[wp] = {
                "hits": cache.get("hits", 0),
                "misses": cache.get("misses", 0),
                "compiles_total": (payload.get("compiles_total")
                                   if st == 200 else None)}
        return out

    def stop(self):
        import signal as signal_mod
        import subprocess

        if self.proc.poll() is None:
            self.proc.send_signal(signal_mod.SIGTERM)
            try:
                self.proc.wait(timeout=60)
            except subprocess.TimeoutExpired:
                self.proc.kill()
                self.proc.wait(timeout=30)
        try:
            self._errf.close()
            os.unlink(self._errf.name)
        except OSError:
            pass


def _gw_closed_loop(port: int, clients: int, duration: float,
                    rows: int, seed: int) -> dict:
    """Closed-loop fleet capacity over HTTP: `clients` persistent
    connections, every request a UNIQUE body (capacity must price real
    inference, not cache hits). 503 backpressure is counted and retried
    after a short pause — shed-and-retry is the documented client
    contract."""
    import http.client

    import numpy as np

    t_start = time.perf_counter() + 0.2        # common start line
    t_end = t_start + duration
    lats, oks, sheds, errs = [], [], [], []

    def drive(tid: int):
        rng = np.random.default_rng(10_000 * seed + tid)
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=60)
        my_lat, my_ok, my_shed, my_err = [], 0, 0, 0
        while time.perf_counter() < t_start:
            time.sleep(0.005)
        while time.perf_counter() < t_end:
            body = rng.integers(0, 256, rows * 784,
                                dtype=np.uint8).tobytes()
            t0 = time.perf_counter()
            try:
                conn.request("POST", "/predict", body,
                             {"Content-Type":
                              "application/octet-stream"})
                r = conn.getresponse()
                r.read()
                status = r.status
            except (OSError, http.client.HTTPException):
                conn.close()
                conn = http.client.HTTPConnection("127.0.0.1", port,
                                                  timeout=60)
                my_err += 1
                continue
            if status == 200:
                my_ok += 1
                my_lat.append(time.perf_counter() - t0)
            elif status == 503:
                my_shed += 1
                time.sleep(0.002)
            else:
                my_err += 1
        conn.close()
        lats.append(my_lat)
        oks.append(my_ok)
        sheds.append(my_shed)
        errs.append(my_err)

    threads = [make_thread(target=drive, name=f"bench-gw-closed-{i}",
                           daemon=False, args=(i,))
               for i in range(clients)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    done = sum(oks)
    return {
        "requests_ok": done,
        "requests_per_sec": round(done / duration, 1),
        "rows_per_sec": round(done * rows / duration, 1),
        "latency_ms": _gw_lat_ms(sorted(
            x for chunk in lats for x in chunk)),
        "shed_503": sum(sheds),
        "transport_errors": sum(errs),
        "clients": clients,
        "duration_s": duration,
    }


def _gw_open_loop(port: int, qps: float, duration: float, rows: int,
                  seed: int, pool: int = 16) -> dict:
    """Open-loop Poisson arrivals at `qps`: latency measured from the
    SCHEDULED arrival (coordinated-omission-safe), a worker pool
    pulling a precomputed arrival schedule."""
    import http.client
    import queue as queue_mod

    import numpy as np

    rng = np.random.default_rng(seed)
    t0 = time.perf_counter() + 0.2
    arrivals = queue_mod.Queue()
    t, n_sched = t0, 0
    for gap in rng.exponential(1.0 / qps, int(qps * duration) + 64):
        t += gap
        if t >= t0 + duration:
            break
        arrivals.put(t)
        n_sched += 1
    lats, oks, sheds, errs = [], [], [], []

    def drive(tid: int):
        body_rng = np.random.default_rng(77_000 * seed + tid)
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=60)
        my_lat, my_ok, my_shed, my_err = [], 0, 0, 0
        while True:
            try:
                sched = arrivals.get_nowait()
            except queue_mod.Empty:
                break
            now = time.perf_counter()
            if sched > now:
                time.sleep(sched - now)
            body = body_rng.integers(0, 256, rows * 784,
                                     dtype=np.uint8).tobytes()
            try:
                conn.request("POST", "/predict", body,
                             {"Content-Type":
                              "application/octet-stream"})
                r = conn.getresponse()
                r.read()
                status = r.status
            except (OSError, http.client.HTTPException):
                conn.close()
                conn = http.client.HTTPConnection("127.0.0.1", port,
                                                  timeout=60)
                my_err += 1
                continue
            if status == 200:
                my_ok += 1
                my_lat.append(time.perf_counter() - sched)
            elif status == 503:
                my_shed += 1
            else:
                my_err += 1
        conn.close()
        lats.append(my_lat)
        oks.append(my_ok)
        sheds.append(my_shed)
        errs.append(my_err)

    threads = [make_thread(target=drive, name=f"bench-gw-open-{i}",
                           daemon=False, args=(i,))
               for i in range(pool)]
    for t_ in threads:
        t_.start()
    for t_ in threads:
        t_.join()
    done = sum(oks)
    return {
        "qps_target": round(qps, 1),
        "scheduled": n_sched,
        "requests_ok": done,
        "latency_ms": _gw_lat_ms(sorted(
            x for chunk in lats for x in chunk)),
        "shed_503": sum(sheds),
        "transport_errors": sum(errs),
    }


def _gw_zipf_leg(fleet: "_GatewayFleet", rows: int, n_keys: int = 32,
                 draws: int = 400, alpha: float = 1.1) -> dict:
    """The sharded-cache leg: a Zipf mix over a fixed key set, each key
    a byte-identical body, so the ring's affinity routing turns N
    per-worker caches into one sharded cache. Evidence is per worker:
    every key lands on exactly ONE worker (X-Gateway-Worker is
    single-valued per key — sharded, never duplicated) and the
    hit-ratio delta comes from the workers' own cache counters."""
    import http.client

    import numpy as np

    rng = np.random.default_rng(42)
    bodies = [rng.integers(0, 256, rows * 784, dtype=np.uint8).tobytes()
              for _ in range(n_keys)]
    ranks = np.arange(1, n_keys + 1, dtype=np.float64)
    prob = ranks ** -alpha
    prob /= prob.sum()
    seq = rng.choice(n_keys, size=draws, p=prob)
    before = fleet.worker_stats()
    owners, lat, ok = {}, [], 0
    conn = http.client.HTTPConnection("127.0.0.1", fleet.port,
                                      timeout=60)
    for k in seq:
        t0 = time.perf_counter()
        conn.request("POST", "/predict", bodies[int(k)],
                     {"Content-Type": "application/octet-stream"})
        r = conn.getresponse()
        r.read()
        if r.status == 200:
            ok += 1
            lat.append(time.perf_counter() - t0)
            owners.setdefault(int(k), set()).add(
                r.getheader("X-Gateway-Worker"))
    conn.close()
    after = fleet.worker_stats()
    per_worker, hits, misses = {}, 0, 0
    for wp in fleet.worker_ports:
        dh = after[wp]["hits"] - before[wp]["hits"]
        dm = after[wp]["misses"] - before[wp]["misses"]
        per_worker[str(wp)] = {"hits": dh, "misses": dm}
        hits += dh
        misses += dm
    return {
        "keys": n_keys,
        "draws": draws,
        "alpha": alpha,
        "requests_ok": ok,
        # hits/(hits+misses) over the leg's own window, summed across
        # the per-worker shards
        "shard_hit_ratio": (round(hits / (hits + misses), 4)
                            if hits + misses else None),
        "per_worker_cache": per_worker,
        "every_key_single_worker": all(
            len(s) == 1 for s in owners.values()),
        "workers_serving_keys": sorted(
            {next(iter(s)) for s in owners.values() if len(s) == 1}),
        "p99_ms": _gw_lat_ms(sorted(lat))["p99"],
    }


def _gw_promote_leg(fleet: "_GatewayFleet", rows: int,
                    clients: int = 4, settle_s: float = 0.75) -> dict:
    """A live fleet promote under load: hammer threads keep unique-body
    traffic flowing while the gateway runs its two-phase prepare/flip.
    Every 200 is recorded as (X-Cluster-Epoch, served version); the leg
    reports the epoch->version map — single-valued means zero torn
    replies — alongside the gateway's own mixed_epoch_rejected counter.
    The promote pause sheds 503s by design; the hammer retries them and
    the count is disclosed."""
    import http.client
    import threading

    import numpy as np

    stop = threading.Event()
    pairs, sheds = [], [0]
    lock = threading.Lock()

    def hammer(tid: int):
        rng = np.random.default_rng(31_000 + tid)
        conn = http.client.HTTPConnection("127.0.0.1", fleet.port,
                                          timeout=60)
        my, my_shed = [], 0
        while not stop.is_set():
            body = rng.integers(0, 256, rows * 784,
                                dtype=np.uint8).tobytes()
            try:
                conn.request("POST", "/predict", body,
                             {"Content-Type":
                              "application/octet-stream"})
                r = conn.getresponse()
                raw = r.read()
                if r.status == 200:
                    my.append((r.getheader("X-Cluster-Epoch"),
                               json.loads(raw).get("version")))
                elif r.status == 503:
                    my_shed += 1
                    time.sleep(0.01)
            except (OSError, http.client.HTTPException):
                conn.close()
                conn = http.client.HTTPConnection(
                    "127.0.0.1", fleet.port, timeout=60)
        conn.close()
        with lock:
            pairs.extend(my)
            sheds[0] += my_shed

    threads = [make_thread(target=hammer, name=f"bench-gw-hammer-{i}",
                           daemon=False, args=(i,))
               for i in range(clients)]
    for t in threads:
        t.start()
    time.sleep(settle_s)
    st, _, verdict = _gw_http(
        fleet.port, "POST", "/models/promote",
        json.dumps({"load": {"fresh": {"seed": 7}}}).encode(),
        timeout=600.0)
    time.sleep(settle_s)
    stop.set()
    for t in threads:
        t.join()
    epoch_versions = {}
    for ep, ver in pairs:
        epoch_versions.setdefault(ep, set()).add(ver)
    torn = {ep: sorted(v) for ep, v in epoch_versions.items()
            if len(v) > 1}
    _, _, gw_now = _gw_http(fleet.port, "GET", "/metrics",
                            timeout=10.0)
    gw_now = gw_now if isinstance(gw_now, dict) else {}
    return {
        "promote_status": st,
        "promoted": (verdict.get("promoted")
                     if isinstance(verdict, dict) else None),
        "cluster_epoch": gw_now.get("cluster_epoch"),
        "responses_during_promote": len(pairs),
        "responses_by_epoch": {
            ep: sum(1 for e, _ in pairs if e == ep)
            for ep in epoch_versions},
        "epoch_version_map": {ep: sorted(v)
                              for ep, v in epoch_versions.items()},
        "torn_epochs": torn,
        "mixed_epoch_rejected": gw_now.get("mixed_epoch_rejected"),
        "zero_mixed_epoch": (gw_now.get("mixed_epoch_rejected") == 0
                             and not torn),
        "shed_503_during_promote": sheds[0],
    }


def _gw_contention_probe(n: int) -> dict:
    """The honesty probe behind gateway_scaling_efficiency: N worker
    processes share ONE host's cores, so N-x scaling is only reachable
    when N compute-bound processes don't slow each other down. Times
    the same numpy matmul loop solo vs N-concurrent —
    host_contention_x well above 1 means the scaling bar was NOT
    reachable on this host and the efficiency number must be read
    against that, exactly like the CPU-vs-TPU provenance rule."""
    import subprocess

    probe = ("import time\nimport numpy as np\n"
             "a = np.random.default_rng(0).standard_normal("
             "(384, 384)).astype(np.float32)\n"
             "t0 = time.perf_counter()\n"
             "for _ in range(300):\n"
             "    a = a @ a\n"
             "    a /= (abs(a).max() + 1.0)\n"
             "print(time.perf_counter() - t0)\n")

    def run_n(k: int) -> list:
        procs = [subprocess.Popen([sys.executable, "-c", probe],
                                  stdout=subprocess.PIPE, text=True)
                 for _ in range(k)]
        out = []
        for pr in procs:
            stdout, _ = pr.communicate(timeout=600)
            out.append(float(stdout.strip()))
        return sorted(out)

    run_n(1)                                  # interpreter/BLAS warmup
    solo = sorted(run_n(1)[0] for _ in range(3))[1]
    conc = run_n(n)[n // 2]
    x = conc / max(solo, 1e-9)
    return {"solo_s": round(solo, 3),
            "concurrent_s": round(conc, 3),
            "concurrency": n,
            "host_contention_x": round(x, 3),
            "scaling_bar_reachable": x <= 1.25}


def _serve_gateway(args) -> int:
    """The horizontal scale-out harness (ISSUE 19): black-box load
    against `serve.py --gateway N` — a front-door process routing over
    N full single-process serving stacks. The bench process never
    imports jax; every number is measured over HTTP exactly as an
    operator's client sees it. Legs: closed-loop capacity at 1 worker
    then at N (scaling_efficiency = img_s_N / (N * img_s_1), the
    1-worker control running behind the SAME gateway so the routing hop
    is priced in both numerator and denominator), an open-loop Poisson
    point at ~half the measured fleet capacity, the Zipf sharded-cache
    leg (per-key single-owner routing + per-worker hit counters), the
    per-worker steady-state recompile check, a live two-phase promote
    under load (zero mixed-epoch responses), and the host-contention
    probe that says whether the N-x scaling bar was even reachable on
    this host's silicon."""
    n = args.gateway
    rows = args.serve_rows
    duration = (3.0 if args.serve_duration is None
                else args.serve_duration)
    clients = 8 if args.serve_clients is None else args.serve_clients

    baseline_rec = None
    if args.baseline:
        with open(args.baseline) as f:
            baseline_rec = json.load(f)          # shape pre-validated
        if baseline_rec["detail"].get("gateway") is None:
            # symmetric with _serve's refusal: a single-process record
            # is no baseline for an N-process aggregate (ISSUE 19)
            _mark(f"REFUSING --baseline {args.baseline}: it is a "
                  "single-process serve record; this run is a "
                  f"--gateway {n} fleet — aggregate-vs-single deltas "
                  "are meaningless (compare single-process rounds "
                  "with bench.py serve --baseline <serve record>)")
            return 4

    # Leg 0 — the 1-worker control behind the same front door.
    _mark("gateway fleet [1 worker]: booting the scaling control")
    fleet1 = _GatewayFleet(args, 1)
    try:
        fleet1.wait_healthy()
        _mark(f"gateway fleet [1 worker]: port {fleet1.port} healthy")
        _gw_closed_loop(fleet1.port, clients, min(1.0, duration),
                        rows, seed=1)            # warm the HTTP path
        closed1 = _gw_closed_loop(fleet1.port, clients, duration,
                                  rows, seed=2)
    finally:
        fleet1.stop()
    img_s_1 = closed1["rows_per_sec"]
    _mark(f"gateway fleet [1 worker]: {img_s_1:.0f} img/s "
          f"(p99 {closed1['latency_ms']['p99']} ms)")

    # Legs 1..n — the N-worker fleet.
    want_dtype = (args.serve_infer_dtype
                  if args.serve_infer_dtype in ("bfloat16", "int8")
                  else None)
    _mark(f"gateway fleet [{n} workers]: booting")
    fleet = _GatewayFleet(args, n)
    try:
        fleet.wait_healthy(want_dtype=want_dtype)
        # worker-reported provenance: the gateway process holds no
        # backend — the workers' own healthz says what silicon answers
        st, _, w0 = _gw_http(fleet.worker_ports[0], "GET", "/healthz",
                             timeout=10.0)
        w0 = w0 if isinstance(w0, dict) else {}
        backend = w0.get("backend")
        device_kind = w0.get("device_kind")
        infer_dtype = w0.get("live_infer_dtype") or "float32"
        if baseline_rec is not None:
            base_kind = baseline_rec["detail"]["host"]["device_kind"]
            if base_kind != device_kind:
                _mark(f"REFUSING --baseline {args.baseline}: it was "
                      f"measured on device_kind={base_kind!r}, these "
                      f"workers report {device_kind!r} — cross-silicon "
                      "serve deltas are meaningless (ROADMAP: CPU "
                      "records must not masquerade as TPU headlines)")
                return 4

        _gw_closed_loop(fleet.port, clients, min(1.0, duration), rows,
                        seed=3)                  # warm every worker
        steady_from = fleet.worker_stats()       # compile snapshot
        _mark(f"closed loop [{n} workers]: {clients} clients x "
              f"{duration:.0f}s")
        closed = _gw_closed_loop(fleet.port, clients, duration, rows,
                                 seed=4)
        img_s_n = closed["rows_per_sec"]
        eff = img_s_n / max(n * img_s_1, 1e-9)
        _mark(f"closed loop [{n} workers]: {img_s_n:.0f} img/s "
              f"aggregate (p99 {closed['latency_ms']['p99']} ms), "
              f"scaling efficiency {eff:.2f}")

        qps = max(1.0, 0.5 * closed["requests_per_sec"])
        open_loop = _gw_open_loop(fleet.port, qps, duration, rows,
                                  seed=5)
        _mark(f"open loop qps={qps:.0f}: p99 "
              f"{open_loop['latency_ms']['p99']} ms, "
              f"{open_loop['shed_503']} shed")

        zipf = _gw_zipf_leg(fleet, rows)
        _mark(f"zipf: shard hit ratio {zipf['shard_hit_ratio']}, "
              f"single-owner={zipf['every_key_single_worker']}, "
              f"{len(zipf['workers_serving_keys'])} workers own keys")

        # steady-state recompile check BEFORE the promote leg: the
        # fresh version's warmup compiles are expected; recompiles in
        # the measured steady window are not.
        steady_to = fleet.worker_stats()
        per_worker_recompiles = {
            str(wp): ((steady_to[wp]["compiles_total"] or 0)
                      - (steady_from[wp]["compiles_total"] or 0))
            for wp in fleet.worker_ports}
        recompiles = sum(per_worker_recompiles.values())
        _mark(f"recompiles after warmup: {recompiles} "
              f"({per_worker_recompiles})")

        promote = _gw_promote_leg(fleet, rows)
        _mark(f"promote under load: epoch {promote['cluster_epoch']}, "
              f"{promote['responses_during_promote']} responses, "
              f"mixed-epoch rejected {promote['mixed_epoch_rejected']},"
              f" torn epochs {promote['torn_epochs'] or 'none'}")

        _, _, gw_metrics = _gw_http(fleet.port, "GET", "/metrics",
                                    timeout=10.0)
        gw_metrics = gw_metrics if isinstance(gw_metrics, dict) else {}
    finally:
        fleet.stop()

    contention = _gw_contention_probe(n)
    bar = ("scaling bar reachable"
           if contention["scaling_bar_reachable"]
           else "scaling bar NOT reachable on this host")
    _mark(f"host contention probe: "
          f"{contention['host_contention_x']}x ({bar})")

    import platform as platform_mod
    import socket

    record = {
        "metric": "gateway_images_per_sec",
        "value": round(img_s_n, 1),
        "unit": "images/sec (fleet aggregate)",
        # no honest per-chip target mapping: N worker processes share
        # ONE host's silicon (see gateway.host_contention_x), so the
        # 2,500 img/s/chip training bar does not apply to the fleet
        # aggregate — vs_baseline stays None rather than flattering
        "vs_baseline": None,
        "detail": {
            "model": args.model,
            "dtype": args.dtype,
            "backend": backend,
            "n_chips": None,
            "host": {
                "hostname": socket.gethostname(),
                "platform": platform_mod.platform(),
                "machine": platform_mod.machine(),
                "cpu_count": os.cpu_count(),
                "backend": backend,
                "device_kind": device_kind,
                # the workers' virtual meshes overlap on shared host
                # silicon — a chip count here would double-count
                "chip_count": None,
                "infer_dtype": infer_dtype,
                "fused_kernels": None,
                **_git_provenance(),
            },
            "rows_per_request": rows,
            "clients": clients,
            "duration_s": duration,
            "closed_loop": closed,
            "recompiles_after_warmup": recompiles,
            "gateway": {
                "workers": n,
                "worker_ports": fleet.worker_ports,
                "img_s_1": round(img_s_1, 1),
                "img_s_n": round(img_s_n, 1),
                "scaling_efficiency": round(eff, 3),
                "host_contention_x": contention["host_contention_x"],
                "scaling_bar_reachable":
                    contention["scaling_bar_reachable"],
                "contention_probe": contention,
                "closed_loop_1worker": closed1,
                "open_loop": open_loop,
                "zipf": zipf,
                "promote": promote,
                "per_worker_recompiles": per_worker_recompiles,
                "final_metrics": {k: gw_metrics.get(k) for k in (
                    "requests", "routed_affinity", "routed_balanced",
                    "failovers", "failover_rescued",
                    "backpressure_503", "paused_503",
                    "mixed_epoch_rejected", "worker_deaths",
                    "promotes", "cluster_epoch")},
            },
        },
    }
    if baseline_rec is not None:
        record["detail"]["baseline"] = _baseline_delta(
            record, baseline_rec, args.baseline)
    print(json.dumps(record))
    if not args.no_artifact:
        # best-effort, like _serve: the record is already on stdout
        artifact_dir = args.artifact_dir or os.path.dirname(
            os.path.abspath(__file__))
        try:
            path = _next_serve_artifact(artifact_dir)
            with open(path, "w") as f:
                json.dump(record, f, indent=1)
                f.write("\n")
            _mark(f"artifact: {path}")
        except OSError as e:
            _mark(f"WARNING: artifact not written ({e}); the record "
                  "above is the only copy")
    return 0


def tta_config(args, gb: int):
    """The tuned time-to-accuracy recipe as a Config. Module-level (and
    contract-tested) so the recipe's invariants are inspectable: the LR
    and decay horizon are PINNED to the values the tuning evidence was
    collected under, independent of the --max-epochs trial budget.

    LR tuned on the calibrated task across 5 seeds (grid 2e-3..1e-2):
    6e-3 crosses 99% in 200-600 steps on EVERY seed where 2e-3 needed
    400-800 (8e-3 is no faster in total; 1e-2 goes high-variance). The
    eval cadence stays 200: an eval costs a full device->host fetch
    (~140 ms on the relay) while 100 train steps cost ~49 ms, so a finer
    cadence pays more in extra evals than it saves in earlier detection.
    The cosine horizon is pinned at TTA_DECAY_STEPS — --max-epochs bounds
    how long a trial may RUN, not how fast the LR decays."""
    from distributedmnist_tpu.config import Config

    # Budget: --max-epochs, but never past the pinned horizon — beyond
    # TTA_DECAY_STEPS the cosine has fully decayed to lr=0 and further
    # steps cannot converge, only burn relay time. The cap is computed
    # from the 60k-row task the recipe is tuned for; a custom --data-dir
    # (unknown row count) keeps the plain epochs budget.
    steps = None
    if args.data_dir is None:
        steps = min(args.max_epochs * (60_000 // gb), TTA_DECAY_STEPS)
    return Config(model=args.model, optimizer="adam", learning_rate=6e-3,
                  lr_schedule="cosine", lr_decay_steps=TTA_DECAY_STEPS,
                  data_dir=args.data_dir, synthetic=args.data_dir is None,
                  batch_size=gb,
                  epochs=args.max_epochs, steps=steps,
                  eval_every=200, log_every=0,
                  target_accuracy=args.target_accuracy,
                  steps_per_call=args.steps_per_call,
                  dtype=args.dtype)


def _time_to_accuracy(args) -> int:
    import logging
    import statistics

    import jax

    from distributedmnist_tpu import trainer
    from distributedmnist_tpu.utils import round_up

    # fit()'s INFO eval/summary lines double as the supervisor's liveness
    # signal (and give the driver progress visibility).
    logging.basicConfig(level=logging.INFO, stream=sys.stderr)

    devs = jax.devices()
    n_chips = len(devs)
    _mark(f"backend up: {n_chips} devices")
    gb = round_up(args.global_batch, n_chips)
    cfg = tta_config(args, gb)
    # Repeated full trials, median reported: a single run's wall-clock has
    # multi-x run-to-run spread on a tunneled backend (relay latency), so
    # one sample would make the recorded number a lottery. Trial 1 pays
    # compile (persistent-cache warm at best); later trials additionally
    # hit the in-process executable cache — the spread in detail.trials_s
    # is the honest picture. 1 trial on CPU (each is minutes).
    #
    # Each trial runs a DISTINCT seed (init + batch order): repeating one
    # trajectory would only measure relay latency, and seed sensitivity is
    # exactly the risk a run-to-99% claim carries (round-2 verdict, weak
    # #1). vs_baseline stays 0 unless EVERY seed reaches the target.
    trials = args.trials if args.trials is not None \
        else (3 if devs[0].platform != "cpu" else 1)
    walls, reached_flags, finals, steps_list = [], [], [], []
    trial_results = []
    for t in range(trials):
        seed = cfg.seed + t
        out = trainer.fit(cfg.replace(seed=seed))
        wall = out["wall_clock_to_target_s"]
        reached = wall is not None
        # Both outcomes report fit()'s own training clock so the two
        # numbers span the same interval (a missed run must not look
        # slower merely by charging data-load/model-init setup that a
        # reached run never pays).
        walls.append(wall if reached else out["wall_clock_s"])
        reached_flags.append(reached)
        finals.append(out["test_accuracy"])
        steps_list.append(out["steps"])
        trial_results.append({
            "seed": seed, "wall_s": round(walls[-1], 2),
            "steps": out["steps"], "evals": out["n_evals"],
            "reached": reached,
            "final_accuracy": round(out["test_accuracy"], 4)})
        _mark(f"trial {t + 1}/{trials} (seed {seed}): {walls[-1]:.2f}s "
              f"(reached={reached})")
    value = statistics.median(walls)
    all_reached = all(reached_flags)
    # vs_baseline only counts when the accuracy half of the target was met
    # in EVERY trial; a fast run that never reached target is a miss
    # (0.0), not a win.
    vs = round(TARGET_WALL_S / value, 3) if (all_reached and value) else 0.0
    print(json.dumps({
        "metric": "wall_clock_to_target_accuracy",
        "value": round(value, 2),
        "unit": "seconds",
        "vs_baseline": vs,
        "detail": {
            "reached_target": all_reached,
            "target_accuracy": args.target_accuracy,
            "trials": trials,
            "trials_s": [round(w, 2) for w in walls],
            # The REPRODUCIBLE primary: wall seconds swing multi-x with
            # relay weather (same code measured 1.49 s and 2.87 s hours
            # apart — BASELINE.md), but the step/eval counts a seed needs
            # to reach target are properties of the code + recipe. A
            # consumer comparing rounds should compare these. REACHED
            # trials only: a budget-exhausted trial's step count is the
            # budget constant, not a time-to-target, and must not
            # contaminate the median (null when no trial reached).
            "steps_to_target_median": (
                int(statistics.median(
                    [s for s, r in zip(steps_list, reached_flags) if r]))
                if any(reached_flags) else None),
            "steps_to_target": [s for s, r
                                in zip(steps_list, reached_flags) if r],
            "evals_to_target": [t["evals"] for t in trial_results
                                if t["reached"]],
            "wall_s_is_weather_dependent": True,
            "trial_results": trial_results,
            "min_s": round(min(walls), 2),
            "max_s": round(max(walls), 2),
            "final_accuracy": round(finals[-1], 4),
            "steps": steps_list[-1],
            "data": out["data"],
            "model": args.model,
            "global_batch": out["global_batch"],
            "n_chips": n_chips,
            "backend": devs[0].platform,
            "dtype": args.dtype,
        },
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
