#!/usr/bin/env python
"""train.py — the framework's entrypoint, mirroring the reference's
`train.py` CLI [BASELINE.json north_star: "the existing train.py entrypoint
gains a --device=tpu flag that selects the JAX path end-to-end with no
CUDA/NCCL import"]. Here the JAX path is the ONLY path; --device selects
tpu vs cpu backends over the same SPMD code.

Examples (the five BASELINE.json workloads as presets):

    python train.py --preset mlp-sgd                # config 1
    python train.py --preset lenet-adam             # config 2
    python train.py --preset mlp-dp2 --device cpu   # config 3 (virtual devs)
    python train.py --preset lenet-dp8              # config 4
    python train.py --preset lenet-multihost \
        --coordinator-address host0:1234 --num-processes 4 --process-id 0
                                                    # config 5
"""

from __future__ import annotations

import argparse
import logging
import sys

from distributedmnist_tpu import config as config_lib


def main(argv=None) -> int:
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s")
    p = argparse.ArgumentParser(description=__doc__,
                                formatter_class=argparse.RawDescriptionHelpFormatter)
    config_lib.add_args(p)
    p.add_argument("--supervise", action="store_true",
                   help="run training in a watchdog-supervised worker "
                        "subprocess and retry if the TPU runtime wedges "
                        "before making progress (pooled-backend claim "
                        "hangs); the summary JSON line is forwarded")
    p.add_argument("--stall-timeout", type=float, default=300.0,
                   help="[--supervise] kill+retry the worker if it is "
                        "silent this long")
    p.add_argument("--max-attempts", type=int, default=3,
                   help="[--supervise] worker attempts before giving up")
    args = p.parse_args(argv)
    cfg = config_lib.from_args(args)

    from distributedmnist_tpu.utils import supervise
    if args.supervise and not supervise.is_worker():
        import os
        worker_argv = [a for a in (sys.argv[1:] if argv is None else argv)
                       if a != "--supervise"]
        return supervise.run_supervised(
            os.path.abspath(__file__), worker_argv,
            accept=supervise.json_record_acceptor("test_accuracy"),
            stall_timeout=args.stall_timeout, attempts=args.max_attempts)

    from distributedmnist_tpu import trainer  # after flags: jax import cost
    summary = trainer.fit(cfg)
    print(trainer.MetricsLogger.summary_line(summary))
    if summary.get("preempted"):
        # fit() absorbed a SIGTERM to force-save the checkpoint and
        # reports it in the summary; at the CLI boundary the signal is
        # RE-DELIVERED after the summary line so process-level semantics
        # stay conventional for external orchestrators (exit status reads
        # terminated-by-SIGTERM, and nothing after fit() keeps running
        # when the scheduler asked us to stop). fit() restored the PRIOR
        # disposition, which is not necessarily one that terminates: a
        # parent that spawned us under nohup/a supervisor may have left
        # SIG_IGN inherited, making the re-delivery a silent no-op
        # (ADVICE r5). The intent here is unconditional conventional
        # termination, so pin SIG_DFL explicitly first.
        import os
        import signal
        signal.signal(signal.SIGTERM, signal.SIG_DFL)
        sys.stdout.flush()
        os.kill(os.getpid(), signal.SIGTERM)
    return 0


if __name__ == "__main__":
    sys.exit(main())
