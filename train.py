#!/usr/bin/env python
"""train.py — the framework's entrypoint, mirroring the reference's
`train.py` CLI [BASELINE.json north_star: "the existing train.py entrypoint
gains a --device=tpu flag that selects the JAX path end-to-end with no
CUDA/NCCL import"]. Here the JAX path is the ONLY path; --device selects
tpu vs cpu backends over the same SPMD code.

Examples (the five BASELINE.json workloads as presets):

    python train.py --preset mlp-sgd                # config 1
    python train.py --preset lenet-adam             # config 2
    python train.py --preset mlp-dp2 --device cpu   # config 3 (virtual devs)
    python train.py --preset lenet-dp8              # config 4
    python train.py --preset lenet-multihost \
        --coordinator-address host0:1234 --num-processes 4 --process-id 0
                                                    # config 5
"""

from __future__ import annotations

import argparse
import logging
import sys

from distributedmnist_tpu import config as config_lib


def main(argv=None) -> int:
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s")
    p = argparse.ArgumentParser(description=__doc__,
                                formatter_class=argparse.RawDescriptionHelpFormatter)
    config_lib.add_args(p)
    cfg = config_lib.from_args(p.parse_args(argv))

    from distributedmnist_tpu import trainer  # after flags: jax import cost
    summary = trainer.fit(cfg)
    print(trainer.MetricsLogger.summary_line(summary))
    return 0


if __name__ == "__main__":
    sys.exit(main())
