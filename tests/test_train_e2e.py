"""End-to-end trainer tests (SURVEY.md §4): full fit() runs on the virtual
8-device CPU mesh with synthetic data — accuracy threshold, early stop,
kill/resume recovery via the fault-injection hook, and preset coverage."""

import numpy as np
import pytest

from distributedmnist_tpu import trainer
from distributedmnist_tpu.config import PRESETS, Config
from distributedmnist_tpu.data import synthetic_mnist


BASE = Config(device="cpu", synthetic=True, log_every=0,
              target_accuracy=None)


@pytest.fixture(scope="module")
def small_data():
    return synthetic_mnist(seed=1, train_n=4096, test_n=1024)


def test_fit_reaches_accuracy(small_data):
    cfg = BASE.replace(model="mlp", optimizer="sgd", learning_rate=0.02,
                       batch_size=256, num_devices=8, steps=200,
                       eval_every=100, target_accuracy=0.9)
    out = trainer.fit(cfg, data=small_data)
    assert out["test_accuracy"] >= 0.9
    assert out["data"] == "synthetic"
    assert out["n_chips"] == 8
    assert out["wall_clock_to_target_s"] is not None


def test_fit_explicit_mode_matches_auto(small_data):
    kw = dict(model="mlp", optimizer="sgd", learning_rate=0.02,
              batch_size=256, num_devices=8, steps=60, eval_every=60)
    a = trainer.fit(BASE.replace(spmd_mode="auto", **kw), data=small_data)
    b = trainer.fit(BASE.replace(spmd_mode="explicit", **kw), data=small_data)
    np.testing.assert_allclose(a["test_accuracy"], b["test_accuracy"],
                               atol=1e-6)


def test_kill_resume_recovery(small_data, tmp_path):
    """The failure-recovery story (SURVEY.md §5): crash mid-run via the
    injection hook, restart, restore from the async checkpoint, finish."""
    ckpt_dir = str(tmp_path / "ckpt")
    kw = dict(model="mlp", optimizer="adam", learning_rate=1e-3,
              batch_size=256, num_devices=8, steps=30, eval_every=1000,
              checkpoint_dir=ckpt_dir, checkpoint_every=10)
    with pytest.raises(trainer.SimulatedFailure):
        trainer.fit(BASE.replace(fail_at_step=20, **kw), data=small_data)

    out = trainer.fit(BASE.replace(**kw), data=small_data)
    assert out["restored"] is True
    assert out["steps"] == 30  # resumed from 20, not restarted from 0


def test_resume_disabled_starts_fresh(small_data, tmp_path):
    ckpt_dir = str(tmp_path / "ckpt2")
    kw = dict(model="mlp", optimizer="sgd", learning_rate=0.02,
              batch_size=256, num_devices=8, steps=10, eval_every=1000,
              checkpoint_dir=ckpt_dir, checkpoint_every=5)
    trainer.fit(BASE.replace(**kw), data=small_data)
    out = trainer.fit(BASE.replace(resume=False, **kw), data=small_data)
    assert out["restored"] is False


def test_fit_through_real_data_dir(tmp_path):
    """Full --data-dir path e2e: synthetic pixels written as REAL-format
    raw IDX fixture files, loaded back through load_mnist (native C++
    reader when the toolchain built it, Python parser otherwise), trained
    to a threshold. If the driver ever mounts real MNIST, this exact path
    produces the real number with no code change."""
    from distributedmnist_tpu.data import native
    from idx_util import write_idx_fixtures

    src = synthetic_mnist(seed=3, train_n=4096, test_n=1024)
    write_idx_fixtures(tmp_path, src)

    native.ensure_built()  # exercise the C++ reader where possible
    cfg = BASE.replace(model="mlp", optimizer="sgd", learning_rate=0.02,
                       batch_size=256, num_devices=8, steps=200,
                       eval_every=200, synthetic=False,
                       data_dir=str(tmp_path))
    out = trainer.fit(cfg)           # no injected data: hits the loader
    assert out["data"] == "real"
    assert out["test_accuracy"] >= 0.85


def test_all_presets_construct():
    # the five BASELINE.json workloads exist and are internally consistent
    assert set(PRESETS) == {"mlp-sgd", "lenet-adam", "mlp-dp2",
                            "lenet-dp8", "lenet-multihost"}
    assert PRESETS["mlp-sgd"].batch_size == 64
    assert PRESETS["mlp-sgd"].optimizer == "sgd"
    assert PRESETS["lenet-dp8"].batch_size == 512
    assert PRESETS["lenet-dp8"].num_devices == 8
    assert PRESETS["lenet-multihost"].checkpoint_dir is not None


def test_cli_args_roundtrip():
    import argparse
    from distributedmnist_tpu import config as config_lib
    p = argparse.ArgumentParser()
    config_lib.add_args(p)
    cfg = config_lib.from_args(p.parse_args(
        ["--preset", "lenet-dp8", "--device", "cpu", "--steps", "5",
         "--synthetic", "--spmd-mode", "explicit"]))
    assert cfg.model == "lenet" and cfg.batch_size == 512
    assert cfg.device == "cpu" and cfg.steps == 5
    assert cfg.synthetic is True and cfg.spmd_mode == "explicit"
