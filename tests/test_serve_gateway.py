"""Horizontal scale-out gateway tests (ISSUE 19, serve/gateway.py).

Three layers, cheapest first:

- HashRing unit tests: deterministic placement, MINIMAL key movement
  on join/leave (the moved set asserted exactly, not just bounded),
  failover order = ring order.
- Gateway routing-core tests driven through in-memory fake transports
  (no sockets): affinity pinning, least-loaded fallback, backpressure
  that sheds instead of spilling, worker-death failover to the next
  ring owner, mixed-epoch rejection, and the two-phase cluster-epoch
  promote with mid-flip rollback.
- ONE multi-process HTTP end-to-end: serve.py --gateway 2 with the
  prediction cache on — pinning observed over real sockets, a
  fleet-wide fresh-version promote bumping the cluster epoch, and a
  worker SIGKILL surviving as a failover, with zero mixed-epoch
  replies throughout.

The fakes answer the worker admin surface the way serve.py does
(epoch echo, healthz with live_version, promote flips the version) so
the Gateway under test runs its real code paths end to end.
"""

import hashlib
import json
import os
import signal
import subprocess
import sys
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from distributedmnist_tpu.serve import gateway as gw_mod
from distributedmnist_tpu.serve.cache import content_key
from distributedmnist_tpu.serve.gateway import (Gateway, HashRing,
                                                ring_key, worker_argv)
from distributedmnist_tpu.serve.metrics import \
    gateway_prometheus_exposition

from conftest import worker_env

pytestmark = pytest.mark.gateway


def _keys(n, tag=b""):
    return [hashlib.sha256(tag + str(i).encode()).digest()
            for i in range(n)]


# -- HashRing ---------------------------------------------------------------


def test_ring_placement_deterministic():
    """Placement is a pure function of the member set: two rings built
    independently (different insertion order) agree on every key."""
    a = HashRing(["w0", "w1", "w2", "w3"])
    b = HashRing(["w3", "w1", "w0", "w2"])
    for k in _keys(300):
        assert a.owner(k) == b.owner(k)
        assert a.owners(k) == b.owners(k)
    assert a.members() == ["w0", "w1", "w2", "w3"]
    # every key lands on a member
    assert {a.owner(k) for k in _keys(300)} <= set(a.members())


def test_ring_join_moves_only_keys_the_joiner_takes():
    """Minimal movement, asserted exactly: adding a member re-maps a
    key if and only if the NEW member now owns it — no key moves
    between two pre-existing members."""
    ring = HashRing(["w0", "w1", "w2", "w3"])
    keys = _keys(1000)
    before = {k: ring.owner(k) for k in keys}
    ring.add("w4")
    moved = {k for k in keys if ring.owner(k) != before[k]}
    assert moved == {k for k in keys if ring.owner(k) == "w4"}
    # and the moved fraction is consistent with ~1/5 ownership, not a
    # rehash-everything (which would move ~4/5)
    assert 0 < len(moved) / len(keys) < 0.45


def test_ring_leave_moves_only_the_leavers_keys_to_successors():
    """Removing a member re-maps exactly its own keys, and each moves
    to its pre-departure failover successor (owners()[1] filtered to
    survivors) — the property that makes death-failover and key
    migration land on the SAME worker."""
    ring = HashRing(["w0", "w1", "w2", "w3"])
    keys = _keys(1000)
    before = {k: ring.owners(k) for k in keys}
    ring.remove("w2")
    for k in keys:
        old = before[k]
        if old[0] != "w2":
            assert ring.owner(k) == old[0], "survivor's key moved"
        else:
            assert ring.owner(k) == old[1], (
                "leaver's key must move to its next ring owner")


def test_ring_owners_is_the_failover_order():
    ring = HashRing(["w0", "w1", "w2", "w3"])
    for k in _keys(100):
        order = ring.owners(k)
        assert order[0] == ring.owner(k)
        assert sorted(order) == ring.members()      # all distinct
        assert ring.owners(k, n=2) == order[:2]     # prefix property


def test_ring_api_errors_and_empty():
    ring = HashRing(["w0"])
    with pytest.raises(ValueError):
        ring.add("w0")
    with pytest.raises(KeyError):
        ring.remove("nope")
    with pytest.raises(ValueError):
        HashRing(vnodes=0)
    ring.remove("w0")
    assert ring.owners(b"k") == [] and ring.owner(b"k") is None
    assert len(ring) == 0 and "w0" not in ring


def test_ring_key_is_the_cache_identity():
    """The ring hashes exactly the tuple the PR 10 cache keys entries
    by — the sharding argument rests on the identities being equal."""
    x = (np.arange(2 * 784, dtype=np.int64) % 251).astype(
        np.uint8).reshape(2, 28, 28, 1)              # two 784-byte rows
    body = x.tobytes()
    ck = content_key("v1", "float32", x)
    assert ck == ("v1", "float32", 2, hashlib.sha256(body).digest())
    base = ring_key(*ck)
    assert ring_key(*content_key("v1", "float32", x)) == base
    assert ring_key(*content_key("v2", "float32", x)) != base
    assert ring_key(*content_key("v1", "int8", x)) != base
    other = np.zeros((2, 28, 28, 1), np.uint8)
    assert ring_key(*content_key("v1", "float32", other)) != base


# -- Gateway core over fake transports --------------------------------------


ROW = bytes(784)


class FakeWorker:
    """In-memory worker transport: answers the serve.py admin surface
    (epoch echo, healthz, load/promote) and stamps /predict replies
    with its current cluster epoch, like a real worker. Scriptable
    failure knobs drive the death/rollback paths."""

    def __init__(self, rid):
        self.rid = rid
        self.calls = []               # (method, path, parsed-or-None)
        self.epoch = None
        self.live_version = "v1"
        self.live_dtype = "float32"
        self.fail_predict = None      # exception to raise on /predict
        self.predict_hook = None      # callable(body, headers) -> tuple
        self.fail_promote = False

    def request(self, method, path, body=None, headers=None,
                timeout_s=None):
        parsed = None
        if method == "POST" and path != "/predict" and body:
            parsed = json.loads(body)
        self.calls.append((method, path, parsed))
        if path == "/cluster/epoch":
            self.epoch = parsed["epoch"]
            return 200, {}, json.dumps(
                {"cluster_epoch": self.epoch}).encode()
        if path == "/healthz":
            return 200, {}, json.dumps(
                {"ok": True, "live_version": self.live_version,
                 "live_infer_dtype": self.live_dtype,
                 "cluster_epoch": self.epoch}).encode()
        if path == "/predict":
            if self.fail_predict is not None:
                raise self.fail_predict
            if self.predict_hook is not None:
                return self.predict_hook(body, headers)
            hdrs = {"X-Cluster-Epoch": str(self.epoch or 0)}
            return 200, hdrs, json.dumps(
                {"worker": self.rid}).encode()
        if path == "/models/load":
            return 200, {}, json.dumps({"version": "v2"}).encode()
        if path == "/models/promote":
            if self.fail_promote:
                return 500, {}, json.dumps(
                    {"error": "injected promote failure"}).encode()
            self.live_version = parsed["version"]
            return 200, {}, json.dumps(
                {"live": self.live_version}).encode()
        raise AssertionError(f"unexpected {method} {path}")

    def predicts(self):
        return [c for c in self.calls if c[1] == "/predict"]

    def close(self):
        pass


def make_gateway(n=3, **kw):
    fakes = {f"w{i}": FakeWorker(f"w{i}") for i in range(n)}
    workers = [gw_mod._Worker(rid=rid, port=9000 + i, transport=t)
               for i, (rid, t) in enumerate(fakes.items())]
    gw = Gateway(workers, **kw)
    gw.start()
    return gw, fakes


def _key_for(gw, body):
    return ring_key("v1", "float32", len(body) // 784,
                    hashlib.sha256(body).digest())


def test_affinity_pins_each_key_to_its_ring_owner():
    gw, fakes = make_gateway()
    for i in range(4):
        body = bytes([i]) * 784
        expect = gw.ring.owner(_key_for(gw, body))
        picked = set()
        for _ in range(5):
            status, hdrs, rbody = gw.handle_predict(body, {})
            assert status == 200, rbody
            picked.add(hdrs["X-Gateway-Worker"])
        assert picked == {expect}, (
            "a hot key must pin to exactly its ring owner")
    snap = gw.snapshot()
    assert snap["routed_affinity"] == 20
    assert snap["routed_balanced"] == 0
    assert snap["mixed_epoch_rejected"] == 0


def test_balanced_fallback_when_uncached():
    """affinity off (fleet runs uncached) -> every request takes the
    fleet's least-loaded pick, which spreads identical bodies across
    workers instead of pinning."""
    gw, fakes = make_gateway(affinity=False)
    picked = set()
    for _ in range(6):
        status, hdrs, _ = gw.handle_predict(ROW, {})
        assert status == 200
        picked.add(hdrs["X-Gateway-Worker"])
    assert len(picked) == 3, "least-loaded + LRU tiebreak must rotate"
    snap = gw.snapshot()
    assert snap["routed_balanced"] == 6 and snap["routed_affinity"] == 0


def test_backpressure_sheds_instead_of_spilling():
    """A saturated ring owner is a 503 — dispatching the key anywhere
    else would compute AND cache it on a non-owner (a duplicate entry
    by construction)."""
    gw, fakes = make_gateway(worker_inflight=2)
    body = b"\x07" * 784
    owner = gw.ring.owner(_key_for(gw, body))
    with gw._cond:
        gw._workers[owner].inflight = 2      # window full
    status, hdrs, rbody = gw.handle_predict(body, {})
    assert status == 503
    assert json.loads(rbody)["reason"] == "backpressure"
    assert hdrs["Retry-After"] == "1"
    assert all(not f.predicts() for f in fakes.values()), (
        "backpressure must never spill the key to a sibling")
    assert gw.snapshot()["backpressure_503"] == 1
    with gw._cond:
        gw._workers[owner].inflight = 0
    status, hdrs, _ = gw.handle_predict(body, {})
    assert status == 200 and hdrs["X-Gateway-Worker"] == owner


def test_worker_death_fails_over_to_next_ring_owner():
    gw, fakes = make_gateway()
    body = b"\x11" * 784
    order = gw.ring.owners(_key_for(gw, body))
    fakes[order[0]].fail_predict = ConnectionRefusedError("refused")
    status, hdrs, rbody = gw.handle_predict(body, {})
    assert status == 200, rbody
    assert hdrs["X-Gateway-Worker"] == order[1], (
        "failover must go to the NEXT owner in ring order")
    snap = gw.snapshot()
    assert snap["worker_deaths"] == 1
    assert snap["failovers"] == 1 and snap["failover_rescued"] == 1
    # the dead worker left the ring, so the key MIGRATED to exactly
    # the worker that rescued it — no second failover needed
    assert order[0] not in gw.ring
    assert gw.ring.owner(_key_for(gw, body)) == order[1]
    status, hdrs, _ = gw.handle_predict(body, {})
    assert status == 200 and hdrs["X-Gateway-Worker"] == order[1]
    assert gw.snapshot()["failovers"] == 1, "no failover on the retry"
    # in-flight accounting drained on both the failed and rescue paths
    with gw._cond:
        assert all(w.inflight == 0 for w in gw._workers.values())


def test_failover_is_tried_exactly_once():
    """Owner dead AND its successor dead -> 502, not a walk of the
    whole ring (the ISSUE contract: ONE redispatch)."""
    gw, fakes = make_gateway()
    body = b"\x13" * 784
    order = gw.ring.owners(_key_for(gw, body))
    fakes[order[0]].fail_predict = ConnectionRefusedError("a")
    fakes[order[1]].fail_predict = ConnectionRefusedError("b")
    status, _, rbody = gw.handle_predict(body, {})
    assert status == 502
    assert "also failed" in json.loads(rbody)["error"]
    assert not fakes[order[2]].predicts(), (
        "the third owner must NOT be tried — one failover only")
    assert gw.snapshot()["worker_deaths"] == 2


def test_all_workers_dead_is_shed_not_crash():
    gw, fakes = make_gateway(n=2)
    for w in list(gw._workers.values()):
        gw._mark_dead(w)
    status, _, rbody = gw.handle_predict(ROW, {})
    assert status == 503
    assert json.loads(rbody)["reason"] == "no_workers"
    code, payload = gw.healthz()
    assert code == 503 and payload["ok"] is False


def test_mixed_epoch_reply_rejected():
    """A reply stamped with a different epoch than the request was
    admitted under must never reach the client (503 + counter) — the
    tripwire behind the bench's zero-mixed-epoch assertion."""
    gw, fakes = make_gateway()
    body = b"\x21" * 784
    owner = gw.ring.owner(_key_for(gw, body))
    fakes[owner].predict_hook = lambda b, h: (
        200, {"X-Cluster-Epoch": "7"}, b'{"worker": "liar"}')
    status, hdrs, rbody = gw.handle_predict(body, {})
    assert status == 503
    assert json.loads(rbody)["reason"] == "mixed_epoch"
    assert gw.snapshot()["mixed_epoch_rejected"] == 1
    # non-200s (e.g. a worker 429/504 verdict) are NOT epoch-checked:
    # sheds carry no payload a client could mix
    fakes[owner].predict_hook = lambda b, h: (
        429, {"X-Cluster-Epoch": "7"}, b'{"error": "quota"}')
    status, _, _ = gw.handle_predict(body, {})
    assert status == 429
    assert gw.snapshot()["mixed_epoch_rejected"] == 1


def test_promote_fanout_two_phase_bumps_cluster_epoch():
    gw, fakes = make_gateway()
    status, _, _ = gw.handle_predict(ROW, {})
    assert status == 200
    code, payload = gw.promote_fanout(load={"fresh": {"seed": 1}})
    assert code == 200, payload
    assert payload == {"promoted": "v2", "cluster_epoch": 1,
                       "workers": ["w0", "w1", "w2"]}
    snap = gw.snapshot()
    assert snap["cluster_epoch"] == 1 and snap["promotes"] == 1
    assert snap["live_version"] == "v2" and snap["paused"] is False
    for f in fakes.values():
        paths = [c[1] for c in f.calls]
        # two-phase order on every worker: prepare, then flip, then
        # the epoch fan-out (the initial epoch-0 seed came first)
        il, ip, ie = (paths.index("/models/load"),
                      paths.index("/models/promote"),
                      len(paths) - 1 - paths[::-1].index("/cluster/epoch"))
        assert il < ip < ie
        assert f.epoch == 1 and f.live_version == "v2"
    # post-promote traffic is admitted AND answered under epoch 1 —
    # nothing mixes
    status, hdrs, _ = gw.handle_predict(ROW, {})
    assert status == 200 and hdrs["X-Cluster-Epoch"] == "1"
    assert gw.snapshot()["mixed_epoch_rejected"] == 0


def test_promote_midflip_failure_rolls_back():
    gw, fakes = make_gateway()
    fakes["w1"].fail_promote = True
    code, payload = gw.promote_fanout(load={})
    assert code == 409
    assert "rolled back" in payload["error"]
    snap = gw.snapshot()
    assert snap["cluster_epoch"] == 0, "a failed flip must not bump"
    assert snap["live_version"] == "v1" and snap["paused"] is False
    # w0 flipped first, then rolled back to the old version
    w0_promotes = [c[2] for c in fakes["w0"].calls
                   if c[1] == "/models/promote"]
    assert [p["version"] for p in w0_promotes] == ["v2", "v1"]
    assert fakes["w0"].live_version == "v1"
    status, _, _ = gw.handle_predict(ROW, {})
    assert status == 200, "traffic resumes after the rollback"


def test_promote_pause_sheds_after_bounded_wait():
    gw, fakes = make_gateway()
    gw.pause_wait_s = 0.05
    with gw._cond:
        gw._paused = True
    t0 = time.monotonic()
    status, _, rbody = gw.handle_predict(ROW, {})
    assert status == 503
    assert json.loads(rbody)["reason"] == "promote_pause"
    assert time.monotonic() - t0 < 5.0
    assert gw.snapshot()["paused_503"] == 1
    with gw._cond:
        gw._paused = False
        gw._cond.notify_all()
    status, _, _ = gw.handle_predict(ROW, {})
    assert status == 200


def test_tenant_headers_forward_and_surface():
    """ISSUE 18 composition: tenant/SLO headers reach the worker
    untouched (its scheduler sees what the client sent), worker
    verdict headers surface back; unrelated headers do neither."""
    gw, fakes = make_gateway(n=1)
    seen = {}

    def hook(body, headers):
        seen.update(headers)
        return 200, {"X-Cluster-Epoch": "0", "X-Trace-Id": "t-123",
                     "Retry-After": "9", "X-Secret": "no"}, b"{}"

    fakes["w0"].predict_hook = hook
    status, hdrs, _ = gw.handle_predict(
        ROW, {"X-Tenant": "free", "X-Deadline-Ms": "50",
              "X-Accuracy-Class": "exact", "X-Nope": "drop-me"})
    assert status == 200
    assert seen["X-Tenant"] == "free"
    assert seen["X-Deadline-Ms"] == "50"
    assert seen["X-Accuracy-Class"] == "exact"
    assert "X-Nope" not in seen
    assert hdrs["X-Trace-Id"] == "t-123"
    assert hdrs["Retry-After"] == "9"
    assert hdrs["X-Gateway-Worker"] == "w0"
    assert "X-Secret" not in hdrs


def test_bad_body_is_400_without_dispatch():
    gw, fakes = make_gateway(n=1)
    for body in (b"", b"x" * 783):
        status, _, rbody = gw.handle_predict(body, {})
        assert status == 400
        assert "784" in json.loads(rbody)["error"]
    assert not fakes["w0"].predicts()


def test_worker_argv_strips_gateway_flags():
    argv = ["--model", "mlp", "--gateway", "2", "--serve-cache",
            "--gateway-vnodes=32", "--gateway-worker-inflight", "4",
            "--port", "7000", "--serve-max-batch", "16"]
    assert worker_argv(argv) == [
        "--model", "mlp", "--serve-cache", "--serve-max-batch", "16",
        "--port", "0"]


def test_gateway_prometheus_exposition():
    gw, fakes = make_gateway()
    for i in range(3):
        gw.handle_predict(bytes([i]) * 784, {})
    text = gateway_prometheus_exposition(gw.snapshot())
    assert "# HELP dmnist_gateway_requests_total" in text
    assert "dmnist_gateway_requests_total 3" in text
    assert "dmnist_gateway_cluster_epoch 0" in text
    assert "dmnist_gateway_workers 3" in text
    assert 'dmnist_gateway_worker_inflight{worker="w0"} 0' in text
    for line in text.splitlines():
        assert line.startswith(("#", "dmnist_gateway_")), line


# -- end-to-end over real processes ----------------------------------------


def _get(url, timeout=10):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return json.loads(r.read())


def _post_json(url, payload, timeout=600):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read())


def _predict(base, body, timeout=75):
    req = urllib.request.Request(
        f"{base}/predict", data=body,
        headers={"Content-Type": "application/octet-stream"})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, dict(r.headers), json.loads(r.read())


def test_gateway_http_end_to_end():
    """serve.py --gateway 2 over real sockets: both workers warm, hot
    keys pin over HTTP, a fleet-wide fresh-version promote bumps the
    cluster epoch with zero mixed-epoch replies, and a SIGKILLed
    worker surfaces as failover rescues, not client errors."""
    env, repo = worker_env()
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    proc = subprocess.Popen(
        [sys.executable, os.path.join(repo, "serve.py"),
         "--model", "mlp", "--device", "cpu", "--serve-max-batch", "16",
         "--serve-cache", "--gateway", "2", "--port", "0",
         "--metrics-every", "5"],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        env=env, cwd=repo)
    try:
        port = None
        deadline = time.monotonic() + 300
        while time.monotonic() < deadline:
            line = proc.stdout.readline()
            assert line, "gateway exited before announcing readiness"
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if rec.get("metric") == "gateway_ready":
                port = rec["port"]
                assert rec["workers"] == 2
                assert len(rec["worker_ports"]) == 2
                break
        assert port is not None, "no gateway_ready line"
        base = f"http://127.0.0.1:{port}"

        # every worker warm (gateway /healthz aggregates worker rows)
        deadline = time.monotonic() + 300
        while time.monotonic() < deadline:
            try:
                payload = _get(f"{base}/healthz")
            except urllib.error.HTTPError as e:
                assert e.code == 503
                payload = json.loads(e.read())
            if payload["ok"] and all(
                    r.get("ok") for r in payload["workers"]):
                break
            time.sleep(0.5)
        else:
            pytest.fail(f"fleet never became healthy: {payload}")
        assert payload["cluster_epoch"] == 0
        assert all(r["cluster_epoch"] == 0 for r in payload["workers"])

        # hot keys pin: each body repeats onto ONE worker, via sockets
        pin = {}
        for i in range(6):
            body = bytes([i]) * 784
            owners = set()
            for _ in range(3):
                status, hdrs, out = _predict(base, body)
                assert status == 200, out
                assert hdrs["X-Cluster-Epoch"] == "0"
                assert len(out["classes"]) == out["n"] == 1
                owners.add(hdrs["X-Gateway-Worker"])
            assert len(owners) == 1, "hot key bounced between workers"
            pin[i] = owners.pop()
        assert len(set(pin.values())) == 2, (
            "6 distinct keys should shard across both workers "
            f"(got {pin})")

        # fleet-wide promote of a fresh version: cluster epoch 0 -> 1,
        # stamped on every subsequent reply, no mixed-epoch rejects
        out = _post_json(f"{base}/models/promote",
                         {"load": {"fresh": {"seed": 3}}})
        assert out["cluster_epoch"] == 1, out
        v2 = out["promoted"]
        payload = _get(f"{base}/healthz")
        assert payload["cluster_epoch"] == 1
        assert all(r["cluster_epoch"] == 1 and r["live_version"] == v2
                   for r in payload["workers"])
        status, hdrs, _ = _predict(base, bytes([1]) * 784)
        assert status == 200 and hdrs["X-Cluster-Epoch"] == "1"

        # kill one worker outright: distinct keys keep answering 200
        # (the one that routed to the corpse comes back as a rescue)
        os.kill(_gateway_children(proc.pid)[0], signal.SIGKILL)
        for i in range(10, 30):
            status, hdrs, out = _predict(base, bytes([i]) * 784)
            assert status == 200, (i, out)
        snap = _get(f"{base}/metrics")
        assert snap["worker_deaths"] == 1, snap
        assert snap["failover_rescued"] == snap["failovers"] >= 1
        assert snap["workers_active"] == 1
        assert snap["mixed_epoch_rejected"] == 0
        prom = urllib.request.urlopen(
            f"{base}/metrics?format=prometheus", timeout=10).read()
        assert b"dmnist_gateway_worker_deaths_total 1" in prom
    finally:
        proc.terminate()
        try:
            proc.wait(timeout=60)
        except subprocess.TimeoutExpired:
            proc.kill()


def _gateway_children(gateway_pid):
    """The worker pids: direct children of the gateway process, read
    from /proc (Linux; field 4 of /proc/<pid>/stat is the ppid)."""
    kids = []
    for pid in os.listdir("/proc"):
        if not pid.isdigit():
            continue
        try:
            with open(f"/proc/{pid}/stat") as f:
                if f.read().rsplit(")", 1)[1].split()[1] == \
                        str(gateway_pid):
                    kids.append(int(pid))
        except OSError:
            continue
    assert kids, f"gateway {gateway_pid} has no child workers"
    return sorted(kids)
