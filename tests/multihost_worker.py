"""Worker process for the localhost multi-host test (SURVEY.md §4:
multi-host simulated by multiple processes with jax.distributed.initialize
on localhost ports). Each worker owns 4 virtual CPU devices; N workers form
one 4N-device global mesh and run the REAL multi-host code path:
DCN-style rendezvous, per-process batch assembly, global collectives.

--data-pipeline stream additionally runs the streaming host pipeline
(data/host_loader.HostStream) under process_count > 1 — the one property
that justifies its existence: each process host-gathers ONLY the rows of
its own addressable 'data' shards, never the full global batch. The worker
instruments the numpy gather to prove it, and runs the device-resident
pipeline on the same seed so the test can assert trajectory equivalence.
--stream-source selects the host-gather backend: 'numpy' (locality-
instrumented) or 'tfdata' (the north_star's literal per-host tf.data
pipeline; trajectory equivalence only — it materializes the full block
per host by documented design).
"""

import argparse
import json
import os
import sys

import numpy as np


_TRACKED_ROWS: set = set()


class _TrackingArray(np.ndarray):
    """numpy view that records every row index touched by fancy
    integer-array indexing — the gather HostStream's per-device placement
    callback performs."""

    def __getitem__(self, item):
        if isinstance(item, np.ndarray) and item.dtype.kind in "iu":
            _TRACKED_ROWS.update(np.asarray(item).ravel().tolist())
        return np.asarray(super().__getitem__(item))


def _expected_stream_rows(cfg, data, steps: int) -> set:
    """Rows this process's addressable devices own, replayed from the
    canonical IndexStream: the 'data' axis position of each addressable
    device maps to a column range of every global batch."""
    import jax

    from distributedmnist_tpu.data.loader import IndexStream
    from distributedmnist_tpu.parallel import get_devices, make_mesh

    mesh = make_mesh(get_devices(cfg.device, cfg.num_devices))
    mesh_devs = list(mesh.devices.flat)
    shard = cfg.batch_size // len(mesh_devs)
    cols = np.concatenate([
        np.arange(i * shard, (i + 1) * shard)
        for i, d in enumerate(mesh_devs)
        if d.process_index == jax.process_index()])
    ref = IndexStream(data["train_x"].shape[0], cfg.batch_size,
                      cfg.seed, mesh)
    expected: set = set()
    full: set = set()
    for s in range(steps):
        idx = ref.indices_for_step(s)
        expected.update(idx[cols].tolist())
        full.update(idx.tolist())
    return expected, full


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("process_id", type=int)
    p.add_argument("num_processes", type=int)
    p.add_argument("port")
    p.add_argument("--ckpt-dir", default=None)
    p.add_argument("--fail-at", type=int, default=None)
    p.add_argument("--data-pipeline", choices=["device", "stream"],
                   default="device")
    p.add_argument("--stream-source", choices=["numpy", "tfdata"],
                   default="numpy")
    p.add_argument("--steps", type=int, default=6)
    args = p.parse_args()

    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=4")

    from distributedmnist_tpu import trainer
    from distributedmnist_tpu.config import Config
    from distributedmnist_tpu.data import synthetic_mnist

    data = synthetic_mnist(seed=1, train_n=1024, test_n=256)
    cfg = Config(model="mlp", optimizer="sgd", learning_rate=0.02,
                 batch_size=64, steps=args.steps, eval_every=6,
                 device="cpu",
                 synthetic=True, log_every=0, target_accuracy=None,
                 coordinator_address=f"localhost:{args.port}",
                 num_processes=args.num_processes,
                 process_id=args.process_id,
                 checkpoint_dir=args.ckpt_dir, checkpoint_every=3,
                 fail_at_step=args.fail_at)
    try:
        out = trainer.fit(cfg, data=data)
    except trainer.SimulatedFailure:
        print("MHFAILED injected", flush=True)
        return 0
    result = {
        "process_id": args.process_id,
        "steps": out["steps"],
        "accuracy": out["test_accuracy"],
        "n_chips": out["n_chips"],
        "n_processes": out["n_processes"],
        "multihost": out["multihost"],
        "restored": out["restored"],
        "preempted": out["preempted"],
    }

    if args.data_pipeline == "stream":
        # Same seed, same data, streaming pipeline — with the host
        # gather instrumented. The rendezvous from the first fit is
        # reused (maybe_initialize is idempotent).
        tracked = dict(
            data,
            train_x=data["train_x"].view(_TrackingArray),
            train_y=data["train_y"].view(_TrackingArray))
        s_out = trainer.fit(cfg.replace(data_pipeline="stream",
                                        stream_source=args.stream_source,
                                        checkpoint_dir=None),
                            data=tracked)
        result.update({
            "stream_source": args.stream_source,
            "stream_accuracy": s_out["test_accuracy"],
            "stream_steps": s_out["steps"],
        })
        if args.stream_source == "numpy":
            # Gather locality is a numpy-source property only: tfdata
            # materializes the full block per host by documented design
            # (host_loader.py:34-43), so the row instrument applies to
            # the numpy backend.
            expected, full = _expected_stream_rows(cfg, data,
                                                   s_out["steps"])
            result.update({
                "stream_rows_touched": len(_TRACKED_ROWS),
                "stream_rows_expected": len(expected),
                # the defining multi-host property: ONLY addressable-
                # shard rows were ever host-gathered by this process — a
                # strict subset of what the global batches contained
                "stream_rows_ok": _TRACKED_ROWS == expected,
                "stream_full_batch_avoided": len(expected) < len(full),
            })

    print("MHRESULT " + json.dumps(result), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
