"""Worker process for the localhost multi-host test (SURVEY.md §4:
multi-host simulated by multiple processes with jax.distributed.initialize
on localhost ports). Each worker owns 4 virtual CPU devices; N workers form
one 4N-device global mesh and run the REAL multi-host code path:
DCN-style rendezvous, per-process batch assembly, global collectives."""

import json
import os
import sys


def main() -> int:
    process_id = int(sys.argv[1])
    num_processes = int(sys.argv[2])
    port = sys.argv[3]
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=4")

    from distributedmnist_tpu import trainer
    from distributedmnist_tpu.config import Config
    from distributedmnist_tpu.data import synthetic_mnist

    ckpt_dir = sys.argv[4] if len(sys.argv) > 4 else None
    fail_at = int(sys.argv[5]) if len(sys.argv) > 5 else None

    data = synthetic_mnist(seed=1, train_n=1024, test_n=256)
    cfg = Config(model="mlp", optimizer="sgd", learning_rate=0.02,
                 batch_size=64, steps=6, eval_every=6, device="cpu",
                 synthetic=True, log_every=0, target_accuracy=None,
                 coordinator_address=f"localhost:{port}",
                 num_processes=num_processes, process_id=process_id,
                 checkpoint_dir=ckpt_dir, checkpoint_every=3,
                 fail_at_step=fail_at)
    try:
        out = trainer.fit(cfg, data=data)
    except trainer.SimulatedFailure:
        print("MHFAILED injected", flush=True)
        return 0
    print("MHRESULT " + json.dumps({
        "process_id": process_id,
        "steps": out["steps"],
        "accuracy": out["test_accuracy"],
        "n_chips": out["n_chips"],
        "n_processes": out["n_processes"],
        "multihost": out["multihost"],
        "restored": out["restored"],
    }), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
