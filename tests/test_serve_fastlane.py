"""The single-request low-latency fast lane (ISSUE 14): the batcher's
bypass lane (empty queue + free window slot -> dispatch on the caller's
thread), the engine's device-resident staging routes (exact fit +
row-staged donated buffer behind the warmup cost gate), the router's
lane rule (candidates keep the full dispatch semantics), the whole-net
MLP inference megakernel behind the registry's parity gate, the
prediction cache's TTL / bounded staleness, and the scheduler's lane
policy + wait pricing."""

import time
from concurrent.futures import Future

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributedmnist_tpu import models
from distributedmnist_tpu.parallel import make_mesh
from distributedmnist_tpu.serve import (DynamicBatcher, InferenceEngine,
                                        ServeMetrics)
from distributedmnist_tpu.serve.engine import (FASTLANE_MAX_BUCKET,
                                               fast_row_bucket)
from distributedmnist_tpu.trainer import init_state
from distributedmnist_tpu.utils import CompileCounter


def _params(model, seed=0):
    from distributedmnist_tpu import optim

    tx = optim.build("sgd", 0.1)
    return init_state(jax.random.PRNGKey(seed), model, tx,
                      jnp.zeros((1, 28, 28, 1))).params


@pytest.fixture(scope="module")
def engine(eight_devices):
    mesh = make_mesh(eight_devices)
    model = models.build("mlp", platform="cpu")
    eng = InferenceEngine(model, _params(model), mesh, max_batch=32)
    eng.warmup()
    return eng


# -- engine: resident staging routes ---------------------------------------


def test_fast_row_bucket_rule():
    """Only the smallest rung is row-stageable (a 1-row request always
    covers into it), and only when it is > 1 (exact fit already skips
    staging) and small enough to be lane territory."""
    assert fast_row_bucket((8, 16, 32)) == 8
    assert fast_row_bucket((1, 2, 4)) is None       # exact fit covers n=1
    assert fast_row_bucket((64, 128)) is None       # past the ceiling
    assert fast_row_bucket((FASTLANE_MAX_BUCKET, 64)) \
        == FASTLANE_MAX_BUCKET


def test_exact_fit_dispatch_fast_parity(engine):
    """n == covering bucket: the request stages directly — same bytes
    as the pooled path, no staging-pool traffic."""
    x = np.arange(8 * 784, dtype=np.uint8).reshape(8, 28, 28, 1) % 251
    before = dict(engine.staging_buffers())
    h = engine.dispatch_fast(x)
    assert h is not None and h.resident and h.bucket == 8
    out = engine.fetch(h)
    np.testing.assert_array_equal(out, engine.infer(x))
    # the resident route never touched the pooled free lists beyond
    # what the reference infer() itself did
    assert engine.staging_buffers().keys() == before.keys()


def test_row_staged_dispatch_fast_parity_and_reuse(engine):
    """The donated resident buffer serves repeated single-row requests
    with exact parity — including across DIFFERENT rows, proving the
    buffer's zero tail survives reuse."""
    engine._fast_row_ok = True      # force past the host cost gate
    for fill in (0, 255, 13, 200):
        x = np.full((1, 28, 28, 1), fill, np.uint8)
        h = engine.dispatch_fast(x)
        assert h is not None and h.resident and h.bucket == 8
        np.testing.assert_array_equal(engine.fetch(h), engine.infer(x))


def test_row_staged_zero_recompiles_after_warmup(engine):
    engine._fast_row_ok = True
    cc = CompileCounter.instance()
    before = cc.snapshot()
    for _ in range(3):
        engine.fetch(engine.dispatch_fast(
            np.zeros((1, 28, 28, 1), np.uint8)))
    engine.fetch(engine.dispatch_fast(
        np.zeros((8, 28, 28, 1), np.uint8)))        # exact fit too
    assert cc.snapshot() - before == 0


def test_resident_handle_is_one_shot(engine):
    engine._fast_row_ok = True
    h = engine.dispatch_fast(np.zeros((1, 28, 28, 1), np.uint8))
    engine.fetch(h)
    with pytest.raises(RuntimeError, match="already fetched"):
        engine.fetch(h)


def test_row_route_contention_falls_back_to_none(engine):
    """A busy resident buffer declines the route (the caller's pooled
    fallback) instead of waiting — two donations of one buffer would
    race."""
    engine._fast_row_ok = True
    assert engine._fast_row_lock.acquire(blocking=False)
    try:
        assert engine.dispatch_fast(
            np.zeros((1, 28, 28, 1), np.uint8)) is None
    finally:
        engine._fast_row_lock.release()


def test_no_resident_route_returns_none(engine):
    # 3 rows: neither an exact fit nor a single row
    assert engine.dispatch_fast(
        np.zeros((3, 28, 28, 1), np.uint8)) is None


def test_cost_gate_disables_row_route(engine):
    """warmup PRICES the row-staged program; where it measures slower
    than the covering bucket's pooled dispatch the route must disable
    itself (exact fit and the queue bypass still serve)."""
    assert engine._fast_row_cost is not None
    ok = engine._fast_row_ok = False
    try:
        assert engine.dispatch_fast(
            np.zeros((1, 28, 28, 1), np.uint8)) is None
    finally:
        engine._fast_row_ok = ok


# -- batcher: the bypass lane ----------------------------------------------


class _Engine:
    """Engine-shaped fake: instant dispatch/fetch, optional fast
    route, dispatch accounting."""

    max_batch = 8
    buckets = (4, 8)
    platform = "cpu"
    version = "v1"
    infer_dtype = "float32"

    def __init__(self, fast=True, fail_dispatch=0, fail_fetch=0):
        self.fast = fast
        self.fail_dispatch = fail_dispatch
        self.fail_fetch = fail_fetch
        self.dispatches = 0
        self.fast_dispatches = 0

    @staticmethod
    def _as_images(x):
        return np.asarray(x, dtype=np.uint8)

    def bucket_for(self, n):
        for b in self.buckets:
            if b >= n:
                return b
        raise ValueError(n)

    def bucket_costs(self):
        return {}

    def _handle(self, n):
        import types

        return types.SimpleNamespace(
            n=n, bucket=self.bucket_for(n), version=self.version,
            infer_dtype=self.infer_dtype, replica=None,
            logits=np.full((n, 10), 3.0, np.float32))

    def dispatch(self, parts):
        if self.fail_dispatch > 0:
            self.fail_dispatch -= 1
            raise RuntimeError("injected dispatch fault")
        self.dispatches += 1
        return self._handle(sum(np.asarray(p).shape[0] for p in parts))

    def dispatch_fast(self, x):
        if not self.fast:
            return None
        if self.fail_dispatch > 0:
            self.fail_dispatch -= 1
            raise RuntimeError("injected dispatch fault")
        self.fast_dispatches += 1
        return self._handle(np.asarray(x).shape[0])

    def fetch(self, handle):
        if self.fail_fetch > 0:
            self.fail_fetch -= 1
            raise RuntimeError("injected fetch fault")
        return handle.logits


def _batcher(engine, metrics=None, **kw):
    kw.setdefault("max_batch", 8)
    kw.setdefault("max_inflight", 1)
    kw.setdefault("adaptive", False)
    kw.setdefault("fastlane", True)
    return DynamicBatcher(engine, metrics=metrics, **kw).start()


def test_fastlane_resolves_inline_on_idle_pipeline():
    metrics = ServeMetrics()
    eng = _Engine()
    b = _batcher(eng, metrics)
    try:
        fut = b.submit(np.zeros((1, 4), np.uint8))
        # the whole pipeline ran on THIS thread: already resolved
        assert fut.done()
        assert fut.result().shape == (1, 10)
        assert fut.version == "v1"
        assert eng.fast_dispatches == 1 and eng.dispatches == 0
        snap = metrics.snapshot()
        assert snap["fastpath"]["dispatches"] == 1
        assert snap["fastpath"]["lane_fraction"] == 1.0
        assert snap["requests"] == 1 and snap["batches"] == 1
    finally:
        b.stop()


def test_fastlane_without_engine_fast_route_still_bypasses():
    """An engine with no dispatch_fast (the fleet, test doubles) still
    gets the queue bypass: dispatch happens on the caller's thread via
    the ordinary dispatch()."""
    metrics = ServeMetrics()
    eng = _Engine(fast=False)
    eng.dispatch_fast = None        # not callable
    b = _batcher(eng, metrics)
    try:
        fut = b.submit(np.zeros((2, 4), np.uint8))
        assert fut.done() and fut.result().shape == (2, 10)
        assert metrics.snapshot()["fastpath"]["dispatches"] == 1
    finally:
        b.stop()


def test_fastlane_closes_under_contention():
    """A non-empty queue (or a held window slot) routes submits down
    the coalescing path — the lane trades nothing under load."""
    metrics = ServeMetrics()
    eng = _Engine()
    b = _batcher(eng, metrics, max_wait_us=50_000)
    try:
        # hold the only window slot so the lane cannot open, then
        # submit: the request must take the queue
        assert b._slots.acquire(blocking=False)
        try:
            fut = b.submit(np.zeros((1, 4), np.uint8))
            assert not fut.done()   # queued, not inline
        finally:
            b._slots.release()
        assert fut.result(timeout=30).shape == (1, 10)
        snap = metrics.snapshot()
        assert snap["fastpath"]["dispatches"] == 0
        assert eng.dispatches == 1
    finally:
        b.stop()


def test_fastlane_disabled_by_default():
    eng = _Engine()
    b = DynamicBatcher(eng, max_batch=8, max_inflight=1,
                       adaptive=False).start()
    try:
        fut = b.submit(np.zeros((1, 4), np.uint8))
        assert fut.result(timeout=30).shape == (1, 10)
        assert eng.fast_dispatches == 0
    finally:
        b.stop()


def test_fastlane_dispatch_failure_fails_future_and_keeps_serving():
    metrics = ServeMetrics()
    eng = _Engine(fail_dispatch=1)
    b = _batcher(eng, metrics)
    try:
        fut = b.submit(np.zeros((1, 4), np.uint8))
        with pytest.raises(RuntimeError, match="injected dispatch"):
            fut.result(timeout=30)
        # the slot was released: the lane serves the next request
        fut2 = b.submit(np.zeros((1, 4), np.uint8))
        assert fut2.result(timeout=30).shape == (1, 10)
        assert b.inflight_batches() == 0
    finally:
        b.stop()


def test_fastlane_fetch_failure_fails_future_and_keeps_serving():
    metrics = ServeMetrics()
    eng = _Engine(fail_fetch=1)
    b = _batcher(eng, metrics)
    try:
        fut = b.submit(np.zeros((1, 4), np.uint8))
        with pytest.raises(RuntimeError, match="injected fetch"):
            fut.result(timeout=30)
        fut2 = b.submit(np.zeros((1, 4), np.uint8))
        assert fut2.result(timeout=30).shape == (1, 10)
        assert b.inflight_batches() == 0
        assert metrics.snapshot()["resilience"][
            "fetch_error_requests"] == 1
    finally:
        b.stop()


def test_fastlane_expired_deadline_still_shed_at_submit():
    from distributedmnist_tpu.serve import DeadlineExceeded

    b = _batcher(_Engine())
    try:
        with pytest.raises(DeadlineExceeded):
            b.submit(np.zeros((1, 4), np.uint8),
                     deadline_s=time.monotonic() - 0.01)
    finally:
        b.stop()


def test_fastlane_deadline_expiring_at_dispatch_sheds(monkeypatch):
    """A deadline that expires between submit's entry check and the
    lane dispatch is shed at zero device cost — deadline semantics
    must not depend on which lane the request took."""
    from distributedmnist_tpu.serve import DeadlineExceeded

    metrics = ServeMetrics()
    eng = _Engine()
    b = _batcher(eng, metrics)
    try:
        real = time.monotonic
        deadline = real() + 0.0005
        calls = {"n": 0}

        def late(_real=real):
            # submit's entry stamp lands before the deadline; the
            # lane's dispatch-time stamp lands after it
            calls["n"] += 1
            return _real() + (0.0 if calls["n"] <= 1 else 0.01)

        monkeypatch.setattr(
            "distributedmnist_tpu.serve.batcher.time.monotonic", late)
        fut = b.submit(np.zeros((1, 4), np.uint8),
                       deadline_s=deadline)
        monkeypatch.undo()
        with pytest.raises(DeadlineExceeded, match="fast-lane"):
            fut.result(timeout=30)
        assert eng.dispatches == 0 and eng.fast_dispatches == 0
        snap = metrics.snapshot()
        assert snap["resilience"]["deadline_shed_requests"] == 1
        # the slot was released: the lane still serves
        fut2 = b.submit(np.zeros((1, 4), np.uint8))
        assert fut2.result(timeout=30).shape == (1, 10)
        assert b.inflight_batches() == 0
    finally:
        b.stop()


def test_fastlane_traces_cover_the_request():
    """fastpath.admit + fastpath + the engine stages cover an over-SLO
    lane request's wall clock >= 0.95 — the leg's acceptance bar, here
    on the deterministic fake (no device noise)."""
    from distributedmnist_tpu.serve import trace as trace_lib

    class _SlowFetch(_Engine):
        # realistic (ms-scale) service time: the bar is defined over
        # genuinely slow requests, not µs-scale fakes where the fixed
        # ~10µs bookkeeping tail would dominate the ratio
        def fetch(self, handle):
            time.sleep(0.002)
            return super().fetch(handle)

    tracer = trace_lib.install(trace_lib.Tracer(
        capacity=64, sample=1.0, slo_ms=1e-6, seed=5))
    b = _batcher(_SlowFetch())
    try:
        for _ in range(4):
            b.submit(np.zeros((1, 4), np.uint8)).result(timeout=30)
    finally:
        b.stop()
        trace_lib.uninstall()
    traces = [t for t in tracer.traces() if t["over_slo"]]
    assert traces
    for t in traces:
        names = {s["name"] for s in t["spans"]}
        assert {"request", "fastpath", "fastpath.admit"} <= names
        att = trace_lib.attribute_stages(t)
        assert att["attributed_frac"] >= 0.95, (
            att, [(s["name"], s["dur"]) for s in t["spans"]])


def test_fastlane_stop_resolves_everything():
    b = _batcher(_Engine())
    futs = [b.submit(np.zeros((1, 4), np.uint8)) for _ in range(5)]
    b.stop()
    assert all(f.done() for f in futs)
    assert b.pending_rows() == 0 and b.inflight_batches() == 0


# -- router: the lane rule -------------------------------------------------


class _RouterEngine(_Engine):
    pass


def _router(**kw):
    from distributedmnist_tpu.serve import Router

    return Router(max_batch=8, buckets=(4, 8), platform="cpu", **kw)


def test_router_dispatch_fast_routes_live():
    r = _router()
    eng = _RouterEngine()
    r.set_live(eng, "v1")
    h = r.dispatch_fast(np.zeros((1, 4), np.uint8))
    assert h is not None and h.version == "v1"
    assert eng.fast_dispatches == 1
    np.testing.assert_array_equal(
        r.fetch(h), np.full((1, 10), 3.0, np.float32))


def test_router_dispatch_fast_declines_with_candidates():
    """Canary fractions and shadow duplication are defined over
    coalesced dispatches: a configured candidate closes the shortcut
    (the full dispatch() path serves instead)."""
    r = _router()
    live, cand = _RouterEngine(), _RouterEngine()
    r.set_live(live, "v1")
    r.set_canary(cand, "v2", 0.5)
    assert r.dispatch_fast(np.zeros((1, 4), np.uint8)) is None
    r.clear_candidates()
    r.set_shadow(cand, "v2", 0.5)
    assert r.dispatch_fast(np.zeros((1, 4), np.uint8)) is None
    r.clear_candidates()
    assert r.dispatch_fast(np.zeros((1, 4), np.uint8)) is not None


def test_router_dispatch_fast_no_live_raises():
    from distributedmnist_tpu.serve import NoLiveModel

    with pytest.raises(NoLiveModel):
        _router().dispatch_fast(np.zeros((1, 4), np.uint8))


# -- scheduler: lane policy + wait pricing ---------------------------------


def test_fastlane_eligible_rule():
    from distributedmnist_tpu.serve.scheduler import fastlane_eligible

    assert fastlane_eligible(True, 0)
    assert not fastlane_eligible(True, 1)
    assert not fastlane_eligible(False, 0)


def test_controller_excludes_fastpath_from_rate_ewma():
    from distributedmnist_tpu.serve.scheduler import AdaptiveController

    c = AdaptiveController(0.001, max_batch=8)
    t = time.monotonic()
    c.on_arrival(1, now=t)
    for i in range(50):
        c.on_arrival(1, now=t + 0.001 * (i + 1), coalesced=False)
    # bypassed arrivals never feed the fill-time cap's rate estimate
    assert c.arrival_rate() == 0.0
    assert c.snapshot()["fastpath_dispatches"] == 50
    for i in range(50):
        c.on_arrival(1, now=t + 0.1 + 0.001 * (i + 1))
    assert c.arrival_rate() > 0.0


# -- megakernel (ops/fused.py + quantize + registry gate) ------------------


@pytest.mark.quant
def test_megakernel_interpret_matches_reference_at_rungs():
    from distributedmnist_tpu.ops import fused

    rng = np.random.default_rng(3)
    w1 = jnp.asarray(rng.normal(size=(784, 128)).astype(np.float32)
                     * 0.05)
    b1 = jnp.asarray(rng.normal(size=(128,)).astype(np.float32))
    w2 = jnp.asarray(rng.normal(size=(128, 10)).astype(np.float32)
                     * 0.1)
    b2 = jnp.asarray(rng.normal(size=(10,)).astype(np.float32))
    for m in (1, 4, 8, 32):
        x = jnp.asarray(rng.normal(size=(m, 784)).astype(np.float32))
        ref = fused.mlp_megakernel_reference(x, w1, b1, w2, b2)
        out = fused.mlp_megakernel(x, w1, b1, w2, b2,
                                   fused.PALLAS_INTERPRET)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)
        xla = fused.mlp_megakernel(x, w1, b1, w2, b2, fused.XLA)
        np.testing.assert_array_equal(np.asarray(xla), np.asarray(ref))


@pytest.mark.quant
def test_megakernel_unresolved_mode_rejected():
    from distributedmnist_tpu.ops import fused

    with pytest.raises(ValueError, match="unresolved"):
        fused.mlp_megakernel(jnp.zeros((1, 784)), jnp.zeros((784, 128)),
                             jnp.zeros((128,)), jnp.zeros((128, 10)),
                             jnp.zeros((10,)), mode="auto")


@pytest.mark.quant
def test_variant_supported_rule():
    from distributedmnist_tpu.serve.quantize import variant_supported

    assert variant_supported("mlp", "megakernel")
    assert not variant_supported("lenet", "megakernel")
    assert variant_supported("lenet", "int8")
    model = models.build("mlp", platform="cpu")
    assert variant_supported(model, "megakernel")
    lenet = models.build("lenet", platform="cpu")
    assert not variant_supported(lenet, "megakernel")


@pytest.mark.quant
def test_prepare_inference_megakernel_parity(eight_devices):
    """The served megakernel forward (folded /255, one fused call)
    tracks the training-identical f32 reference within the PARITY.md
    gate on real engine dispatches."""
    from distributedmnist_tpu.utils import parity_check

    mesh = make_mesh(eight_devices)
    model = models.build("mlp", platform="cpu")
    params = _params(model)
    ref = InferenceEngine(model, params, mesh, max_batch=32)
    mk = InferenceEngine(model, params, mesh, max_batch=32,
                         infer_dtype="megakernel")
    x = np.random.default_rng(5).integers(
        0, 256, (24, 28, 28, 1), dtype=np.uint8)
    rep = parity_check(ref.infer(x), mk.infer(x),
                       min_agreement=0.995, max_rel_diff=0.01)
    assert rep["passed"], rep


@pytest.mark.quant
def test_prepare_inference_megakernel_refuses_lenet():
    from distributedmnist_tpu.serve.quantize import prepare_inference

    lenet = models.build("lenet", platform="cpu")
    with pytest.raises(ValueError, match="no megakernel"):
        prepare_inference(lenet, {"x": np.zeros(1)}, "megakernel",
                          "xla")


@pytest.mark.quant
def test_megakernel_in_parity_gates_and_auto_skips_lenet():
    from distributedmnist_tpu.serve import PARITY_GATES

    assert "megakernel" in PARITY_GATES
    agree, rel = PARITY_GATES["megakernel"]
    # a pure-kernel f32 variant gates far tighter than low precision
    assert rel <= min(PARITY_GATES["bfloat16"][1],
                      PARITY_GATES["int8"][1])


# -- prediction-cache TTL / bounded staleness ------------------------------


@pytest.mark.cache
def test_cache_ttl_expires_by_monotonic_age():
    from distributedmnist_tpu.serve import PredictionCache, content_key

    c = PredictionCache(capacity=8, ttl_s=0.05)
    key = content_key("v1", "float32", np.zeros((1, 784), np.uint8))
    logits = np.ones((1, 10), np.float32)
    assert c.insert(key, logits, "v1", "float32")
    assert c.lookup(key) is not None            # fresh: a hit
    time.sleep(0.06)
    assert c.lookup(key) is None                # aged out: a miss
    s = c.stats()
    assert s["expired"] == 1 and s["ttl_s"] == 0.05
    assert s["misses"] >= 1 and s["entries"] == 0
    # re-insert restarts the clock
    assert c.insert(key, logits, "v1", "float32")
    assert c.lookup(key) is not None


@pytest.mark.cache
def test_cache_ttl_validation_and_default_off():
    from distributedmnist_tpu.serve import PredictionCache

    with pytest.raises(ValueError, match="ttl_s"):
        PredictionCache(8, ttl_s=0.0)
    c = PredictionCache(8)
    assert c.stats()["ttl_s"] is None and c.stats()["expired"] == 0


@pytest.mark.cache
def test_cache_front_ttl_expired_hit_recomputes():
    """Through the CacheFront's inline-hit path: an expired entry is
    dropped, the request recomputes (fresh single-flight leader), and
    the expiry is counted."""
    from distributedmnist_tpu.serve import CacheFront, PredictionCache

    class _Route:
        @staticmethod
        def _as_images(x):
            return np.asarray(x, dtype=np.uint8)

        def live_route(self):
            return ("v1", "float32")

    class _Batcher:
        def __init__(self):
            self.submits = 0

        def next_rid(self):
            return 1

        def submit(self, x, deadline_s=None, key=None, route=None):
            self.submits += 1
            fut = Future()
            fut.trace_id = None
            fut.version = "v1"
            fut.set_result(np.full((x.shape[0], 10), 2.0, np.float32))
            return fut

    cache = PredictionCache(8, ttl_s=0.05)
    batcher = _Batcher()
    front = CacheFront(batcher, _Route(), cache)
    x = np.zeros((1, 784), np.uint8)
    front.submit(x).result(timeout=5)
    assert batcher.submits == 1
    front.submit(x).result(timeout=5)
    assert batcher.submits == 1                 # served from cache
    time.sleep(0.06)
    front.submit(x).result(timeout=5)
    assert batcher.submits == 2                 # expired -> recomputed
    assert cache.stats()["expired"] == 1


# -- the parity gate on TRAINED weights (ISSUE 14 satellite) ---------------


@pytest.mark.slow
@pytest.mark.quant
def test_trained_checkpoint_through_parity_gate_end_to_end(tmp_path):
    """CI exercises the registry's parity gate on REAL learned weights,
    not only calibrated-synthetic init: a short train run writes a
    checkpoint, the registry restores it params-only, warms it, gates
    the int8 AND megakernel variants against the trained f32 reference,
    and the gated megakernel serves a fast-lane request end to end."""
    from distributedmnist_tpu import trainer
    from distributedmnist_tpu.config import Config
    from distributedmnist_tpu.serve import build_serving

    ck = str(tmp_path / "ck")
    cfg = Config(device="cpu", num_devices=8, synthetic=True,
                 model="mlp", optimizer="sgd", learning_rate=0.05,
                 fused_kernels="xla", batch_size=256, steps=100,
                 eval_every=100, log_every=0, target_accuracy=None,
                 checkpoint_dir=ck, checkpoint_every=50,
                 serve_max_batch=16)
    out = trainer.fit(cfg)
    assert out["steps"] == 100

    metrics = ServeMetrics()
    registry, router, factory = build_serving(cfg, metrics=metrics)
    mv = registry.load_latest()
    assert mv.source.startswith("checkpoint")
    assert mv.step == 100
    registry.promote(mv.version)
    # trained logits spread far wider than fresh-init ones, which is
    # exactly what makes this the honest gate exercise (PARITY.md)
    for dt in ("int8", "megakernel"):
        vi = registry.add_variant(mv.version, dt)
        assert vi.state == "ready", (dt, vi.last_error)
        assert vi.parity["passed"] is True, (dt, vi.parity)
    registry.promote(mv.version, infer_dtype="megakernel")
    b = DynamicBatcher(router, max_batch=16, metrics=metrics,
                       fastlane=True, adaptive=False,
                       max_inflight=1).start()
    try:
        x = np.random.default_rng(9).integers(
            0, 256, (1, 784), dtype=np.uint8)
        fut = b.submit(x)
        assert fut.result(timeout=60).shape == (1, 10)
        np.testing.assert_array_equal(fut.result(), router.infer(x))
    finally:
        b.stop()
    assert metrics.snapshot()["fastpath"]["dispatches"] == 1


@pytest.mark.cache
def test_cache_expired_prometheus_series():
    from distributedmnist_tpu.serve import prometheus_exposition

    text = prometheus_exposition(
        ServeMetrics().snapshot(),
        cache={"hits": 1, "misses": 1, "expired": 3})
    assert "dmnist_serve_cache_expired_total 3" in text
    assert "# HELP dmnist_serve_cache_expired_total" in text
