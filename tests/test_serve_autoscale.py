"""serve/autoscale.py: the Signals pressure surface, the Autoscaler's
hysteresis + cooldown + floor/ceiling decision discipline (zero flaps
by construction, disclosed saturation, absorbed actuator deaths), the
WindowActuator against a REAL DynamicBatcher (parked-permit window
moves, the pre-warmed bucket ladder, honest partial narrows), the
GatewayActuator over a gateway-shaped fake (LIFO drain of autoscaled
workers, boot members protected), cost-model pricing on every action
record, and the ServeMetrics/Prometheus export of the whole loop."""

import threading
import time

import numpy as np
import pytest

from distributedmnist_tpu.serve import DynamicBatcher, ServeMetrics
from distributedmnist_tpu.serve import metrics as metrics_mod
from distributedmnist_tpu.serve.autoscale import (Autoscaler,
                                                  GatewayActuator,
                                                  Signals,
                                                  WindowActuator,
                                                  batcher_signals)
from tests.test_serve_batcher import StubEngine

pytestmark = pytest.mark.autoscale


# -- fakes -----------------------------------------------------------------


class FakeActuator:
    kind = "fake"
    cost_basis = "fake-units"

    def __init__(self, floor=1, ceiling=4, per_unit_rows=100.0,
                 fail_next=0):
        self.floor = floor
        self.ceiling = ceiling
        self.units = floor
        self.calls = []
        self.per_unit_rows = per_unit_rows
        self.fail_next = fail_next

    def current(self):
        return self.units

    def scale_to(self, units):
        self.calls.append(units)
        if self.fail_next > 0:
            self.fail_next -= 1
            raise RuntimeError("actuation failed (injected)")
        self.units = min(max(units, self.floor), self.ceiling)
        return self.units

    def capacity_rows_per_s(self, units):
        if self.per_unit_rows is None:
            return None
        return self.per_unit_rows * min(max(units, 1), self.ceiling)

    def chip_fraction(self, units):
        return float(min(max(units, 1), self.ceiling))

    def close(self):
        pass


class _Box:
    """Mutable signal source: tests set the pressure a tick will see."""

    def __init__(self, queue_frac=0.0, shed=0):
        self.sig = Signals(queue_frac=queue_frac, inflight_frac=0.0,
                           shed_delta=shed)

    def read(self):
        return self.sig


def _asc(act, box, **kw):
    kw.setdefault("high", 0.75)
    kw.setdefault("low", 0.25)
    kw.setdefault("cooldown_s", 0.0)
    kw.setdefault("interval_s", 0.05)
    return Autoscaler(act, box.read, **kw)


# -- the pressure surface --------------------------------------------------


def test_pressure_is_max_of_normalized_signals():
    assert Signals(0.4, 0.7, 0).pressure() == pytest.approx(0.7)
    assert Signals(0.9, 0.1, 0).pressure() == pytest.approx(0.9)
    # shedding pins pressure to saturation regardless of the gauges
    assert Signals(0.0, 0.0, 3).pressure() == 1.0
    # p99 at 2x the SLO reads pressure 2.0 — a breach alone must clear
    # any sane high watermark
    assert Signals(0.1, 0.1, 0, p99_ms=20.0,
                   slo_ms=10.0).pressure() == pytest.approx(2.0)
    # no SLO configured: the latency term is inert
    assert Signals(0.1, 0.1, 0, p99_ms=20.0).pressure() == \
        pytest.approx(0.1)


def test_batcher_signals_reads_the_live_surface():
    eng = StubEngine(max_batch=16)
    b = DynamicBatcher(eng, max_wait_us=1000, queue_depth=64,
                       max_inflight=2).start()
    m = ServeMetrics()
    try:
        read = batcher_signals(b, metrics=m, slo_ms=10.0)
        sig = read()
        assert sig.queue_frac == 0.0 and sig.inflight_frac == 0.0
        assert sig.shed_delta == 0 and sig.slo_ms == 10.0
        # a rejection between ticks surfaces as shed_delta once, then
        # the baseline advances — shed is a DELTA, not a level
        m.record_reject(rows=4)
        assert read().shed_delta == 1
        assert read().shed_delta == 0
    finally:
        b.stop()


# -- decision discipline ---------------------------------------------------


def test_hysteresis_bands_gate_grow_and_shrink():
    act = FakeActuator(floor=1, ceiling=4)
    act.units = 2
    box = _Box()
    asc = _asc(act, box)
    box.sig = Signals(0.9, 0.0, 0)               # above high: grow
    a = asc.tick()
    assert a["direction"] == "grow" and act.units == 3
    box.sig = Signals(0.5, 0.0, 0)               # dead band: hold
    assert asc.tick() is None and act.units == 3
    box.sig = Signals(0.1, 0.0, 0)               # below low: shrink
    a = asc.tick()
    assert a["direction"] == "shrink" and act.units == 2


def test_cooldown_suppresses_and_flaps_stay_zero():
    act = FakeActuator(floor=1, ceiling=4)
    box = _Box(queue_frac=0.9)
    asc = _asc(act, box, cooldown_s=60.0)
    assert asc.tick()["direction"] == "grow"
    # an immediate reversal attempt lands INSIDE the cooldown window
    box.sig = Signals(0.0, 0.0, 0)
    assert asc.tick() is None
    assert asc.suppressed == 1
    assert asc.flaps() == 0, "cooldown exists to make this zero"
    assert len(asc.actions) == 1


def test_ceiling_is_disclosed_saturation_not_silent_clamping():
    act = FakeActuator(floor=1, ceiling=2)
    act.units = 2
    box = _Box(queue_frac=1.0)
    asc = _asc(act, box)
    assert asc.tick() is None
    assert asc.saturated_ticks == 1
    assert act.calls == [], "a saturated tick must not actuate"


def test_floor_holds_and_quiet_trough_does_not_underflow():
    act = FakeActuator(floor=2, ceiling=4)
    act.units = 2
    box = _Box(queue_frac=0.0)
    asc = _asc(act, box)
    assert asc.tick() is None and act.units == 2
    assert act.calls == []


def test_actuator_death_is_counted_and_loop_survives():
    act = FakeActuator(floor=1, ceiling=4, fail_next=1)
    box = _Box(queue_frac=0.9)
    asc = _asc(act, box)
    assert asc.tick() is None
    assert asc.errors == 1 and asc.actions == []
    # next tick retries against fresh state and succeeds
    assert asc.tick()["direction"] == "grow"
    assert act.units == 2


def test_actions_are_priced_on_the_cost_model():
    act = FakeActuator(floor=1, ceiling=4, per_unit_rows=100.0)
    box = _Box(queue_frac=0.9)
    asc = _asc(act, box)
    a = asc.tick()
    assert a["price_chip_s_per_s"] == pytest.approx(1.0)
    assert a["predicted_gain_rows_per_s"] == pytest.approx(100.0)
    assert a["cost_basis"] == "fake-units"
    assert a["from_units"] == 1 and a["achieved_units"] == 2
    # an incomplete cost table prices as unknown, never a guess
    act2 = FakeActuator(floor=1, ceiling=4, per_unit_rows=None)
    a2 = _asc(act2, _Box(queue_frac=0.9)).tick()
    assert a2["predicted_gain_rows_per_s"] is None


def test_constructor_rejects_inverted_bands_and_bounds():
    act = FakeActuator(floor=1, ceiling=4)
    with pytest.raises(ValueError):
        _asc(act, _Box(), high=0.3, low=0.5)
    with pytest.raises(ValueError):
        _asc(act, _Box(), cooldown_s=-1.0)
    with pytest.raises(ValueError):
        Autoscaler(act, _Box().read, floor=5, ceiling=4)
    with pytest.raises(ValueError):
        WindowActuator(object(), floor=3, ceiling=2)
    with pytest.raises(ValueError):
        GatewayActuator(object(), floor=0, ceiling=2)


def test_started_loop_acts_and_stop_joins():
    act = FakeActuator(floor=1, ceiling=4)
    box = _Box(queue_frac=0.9)
    asc = _asc(act, box, interval_s=0.01).start()
    deadline = time.monotonic() + 10.0
    while not asc.actions and time.monotonic() < deadline:
        time.sleep(0.01)
    asc.stop()
    assert asc._thread is None
    assert asc.actions and asc.actions[0]["direction"] == "grow"
    n = len(asc.actions)
    time.sleep(0.05)
    assert len(asc.actions) == n, "loop still acting after stop()"
    d = asc.describe()
    assert d["actuator"] == "fake" and d["scale"] == act.units


# -- WindowActuator against the real batcher -------------------------------


def test_window_actuator_walks_window_and_bucket_ladder():
    eng = StubEngine(max_batch=16)          # buckets (4, 8, 16)
    b = DynamicBatcher(eng, max_wait_us=1000, queue_depth=64,
                       max_inflight=4).start()
    try:
        act = WindowActuator(b, floor=1, ceiling=4, base_max_batch=4)
        # unit u: window u, bucket u-1 rungs above the base, clamped
        # to the warmed ladder top — NEVER a new jit key
        assert act.plan(1) == (1, 4)
        assert act.plan(2) == (2, 8)
        assert act.plan(3) == (3, 16)
        assert act.plan(4) == (4, 16)
        assert act.scale_to(1) == 1
        assert b.window() == 1 and b.max_batch == 4
        assert act.scale_to(4) == 4
        assert b.window() == 4 and b.max_batch == 16
        # out-of-range targets clamp to [floor, ceiling]
        assert act.scale_to(99) == 4
        assert act.current() == 4
        # requests still serve at every scale (park/unpark kept the
        # semaphore balanced)
        assert act.scale_to(2) == 2
        fut = b.submit(np.zeros((3, 28, 28, 1), np.uint8))
        assert fut.result(timeout=10).shape == (3, 10)
    finally:
        b.stop()


def test_window_actuator_reports_partial_narrow_honestly():
    """Narrowing must park permits the in-flight pipeline is still
    holding — a full pipeline yields a PARTIAL narrow (returned
    honestly; the next tick retries), never a blocked control loop."""
    gate = threading.Event()
    eng = StubEngine(max_batch=16, gate=gate)
    b = DynamicBatcher(eng, max_wait_us=100, queue_depth=64,
                       max_inflight=2).start()
    try:
        act = WindowActuator(b, floor=1, ceiling=2)
        # two SEPARATE dispatches must occupy both window slots — wait
        # for the first to be in flight before submitting the second,
        # or the former coalesces them into one batch
        futs = [b.submit(np.zeros((1, 28, 28, 1), np.uint8))]
        assert eng.in_call.wait(timeout=10)
        eng.in_call.clear()
        futs.append(b.submit(np.zeros((1, 28, 28, 1), np.uint8)))
        assert eng.in_call.wait(timeout=10)
        deadline = time.monotonic() + 10.0
        while eng.inflight < 2 and time.monotonic() < deadline:
            time.sleep(0.005)
        assert eng.inflight == 2, "pipeline never filled both slots"
        t0 = time.monotonic()
        got = act.scale_to(1)
        assert got == 2, f"narrow should be refused while full, got {got}"
        assert time.monotonic() - t0 < 10.0
        gate.set()
        for f in futs:
            f.result(timeout=10)
        deadline = time.monotonic() + 10.0
        while act.scale_to(1) != 1:
            assert time.monotonic() < deadline, (
                "narrow never completed after the pipeline drained")
        assert b.window() == 1
    finally:
        gate.set()
        b.stop()


def test_window_actuator_prices_capacity_from_the_cost_table():
    eng = StubEngine(max_batch=16)
    eng.costs = eng.linear_costs()          # complete, compute-priced
    b = DynamicBatcher(eng, max_wait_us=1000, queue_depth=64,
                       max_inflight=2).start()
    try:
        act = WindowActuator(b, floor=1, ceiling=2, base_max_batch=4)
        cap = act.capacity_rows_per_s(1)
        assert cap is not None and cap > 0
        assert act.chip_fraction(2) == 2.0
        assert act.cost_basis == "inflight-window-slot-seconds"
    finally:
        b.stop()
    # no cost table yet: pricing reports unknown instead of a guess
    eng2 = StubEngine(max_batch=16)
    b2 = DynamicBatcher(eng2, max_wait_us=1000, queue_depth=64,
                        max_inflight=2).start()
    try:
        act2 = WindowActuator(b2, floor=1, ceiling=2)
        assert act2.capacity_rows_per_s(1) is None
    finally:
        b2.stop()


# -- GatewayActuator over a gateway-shaped fake ----------------------------


class _FakeWorker:
    def __init__(self, rid):
        self.rid = rid
        self.state = "active"


class _FakeGateway:
    def __init__(self, boot=("g1",)):
        self.workers = {r: _FakeWorker(r) for r in boot}
        self.joined = []

    def _active(self):
        return [w for w in self.workers.values()
                if w.state == "active"]

    def add_worker(self, worker):
        if worker.rid in self.workers:
            raise ValueError(f"worker {worker.rid!r} already joined")
        self.joined.append(worker.rid)
        self.workers[worker.rid] = worker

    def drain_worker(self, rid, timeout_s=30.0):
        w = self.workers.get(rid)
        if w is None or w.state != "active":
            raise ValueError(f"no active worker {rid!r} to drain")
        if len(self._active()) <= 1:
            raise ValueError("cannot drain the last active worker")
        w.state = "drained"
        del self.workers[rid]
        return w


def test_gateway_actuator_spawns_and_drains_lifo():
    gw = _FakeGateway(boot=("g1",))
    terminated = []
    act = GatewayActuator(
        gw, floor=1, ceiling=3,
        spawn=_FakeWorker, terminate=terminated.append,
        per_worker_rows_per_s=500.0)
    assert act.current() == 1
    assert act.scale_to(3) == 3
    assert gw.joined == ["as1", "as2"]
    # shrink drains the YOUNGEST autoscaled workers first; the
    # boot-time member is untouchable while grown workers remain
    assert act.scale_to(1) == 1
    assert [w.rid for w in terminated] == ["as2", "as1"]
    assert list(gw.workers) == ["g1"]
    assert act.capacity_rows_per_s(2) == pytest.approx(1000.0)
    assert act.cost_basis == "worker-chip-seconds"
    # floor clamps an underflow request at the actuator too
    assert act.scale_to(0) == 1


def test_gateway_actuator_death_mid_grow_propagates_to_the_loop():
    gw = _FakeGateway(boot=("g1",))

    def dying_spawn(rid):
        raise RuntimeError("spawn failed (injected)")

    act = GatewayActuator(gw, floor=1, ceiling=3, spawn=dying_spawn,
                          terminate=lambda w: None)
    asc = _asc(act, _Box(queue_frac=0.9))
    assert asc.tick() is None
    assert asc.errors == 1
    assert act.current() == 1, "failed grow must not leak members"


# -- metrics + Prometheus export -------------------------------------------


def test_autoscale_metrics_snapshot_and_prometheus_series():
    m = ServeMetrics()
    act = FakeActuator(floor=1, ceiling=2)
    box = _Box(queue_frac=0.9)
    asc = _asc(act, box, cooldown_s=60.0, metrics=m)
    asc.tick()                               # grow 1 -> 2 (applied)
    asc.tick()                               # at ceiling: saturated
    box.sig = Signals(0.0, 0.0, 0)
    asc.tick()                               # in cooldown: suppressed
    s = m.snapshot()["autoscale"]
    assert s["scale"] == 2
    assert s["decisions"] == {"grow": 1}
    assert s["suppressed"] == 1 and s["saturated_ticks"] == 1
    assert s["last_cost_chip_s"] == pytest.approx(1.0)
    text = metrics_mod.prometheus_exposition(m.snapshot())
    for series in ("dmnist_serve_autoscale_scale 2",
                   'dmnist_serve_autoscale_decisions_total'
                   '{direction="grow"} 1',
                   "dmnist_serve_autoscale_suppressed_total 1",
                   "dmnist_serve_autoscale_saturated_total 1",
                   "dmnist_serve_autoscale_last_cost_chip_seconds 1"):
        assert series in text, f"missing series {series!r}"
    # no autoscaler running: the scale gauge is ABSENT, not zero (a
    # zero would read as "scaled to nothing" on a dashboard)
    idle = metrics_mod.prometheus_exposition(ServeMetrics().snapshot())
    assert "dmnist_serve_autoscale_scale " not in idle
