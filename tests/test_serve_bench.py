"""CLI contracts of the serving stack: `bench.py serve` (positional mode
spelling included) emits the one-line serve_images_per_sec_per_chip
record with latency percentiles, occupancy and a recompile-free steady
state; serve.py's selftest and HTTP modes run end-to-end on CPU; flag
validation rejects cross-mode misuse before any backend comes up."""

import json
import os
import signal
import subprocess
import sys
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from tests.conftest import worker_env


def _run_cli(script, extra, timeout=600):
    env, repo = worker_env()
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    return subprocess.run(
        [sys.executable, os.path.join(repo, script)] + extra,
        capture_output=True, text=True, env=env, cwd=repo,
        timeout=timeout)


SERVE_ARGS = ["--inline", "--model", "mlp", "--serve-duration", "0.5",
              "--serve-qps", "40", "--serve-clients", "2",
              "--serve-max-batch", "16", "--serve-max-wait-us", "2000",
              "--no-artifact"]


def test_bench_serve_contract():
    """`python bench.py serve` (the acceptance-criteria spelling)
    completes the serial-vs-pipelined capacity phases and the QPS sweep
    and emits the parseable record — including p50/p95/p99, batch
    occupancy, the inflight comparison, and zero steady-state
    recompiles."""
    out = _run_cli("bench.py", ["serve"] + SERVE_ARGS)
    assert out.returncode == 0, out.stderr[-2000:]
    lines = [l for l in out.stdout.splitlines() if l.strip()]
    assert len(lines) == 1, f"expected ONE JSON line, got {out.stdout!r}"
    rec = json.loads(lines[0])
    assert set(rec) == {"metric", "value", "unit", "vs_baseline", "detail"}
    assert rec["metric"] == "serve_images_per_sec_per_chip"
    assert rec["unit"] == "images/sec/chip"
    assert rec["value"] > 0 and rec["vs_baseline"] > 0
    d = rec["detail"]
    # steady state after bucket warmup must be recompile-free
    assert d["warmup_compile_events"] > 0
    assert d["recompiles_after_warmup"] == 0
    # provenance: the artifact must be self-locating (a CPU-host number
    # can never be conflated with a TPU headline)
    host = d["host"]
    assert host["backend"] == d["backend"]
    assert host["chip_count"] == d["n_chips"]
    assert host["device_kind"] and host["hostname"] and host["platform"]
    assert d["swap"] is None               # not requested in this run
    # compile-surface provenance (ISSUE 12): f32 headline = one dtype
    # over the record's own bucket ladder plus the fast lane's
    # row-staged key (the smallest rung is 8 > 1 here — ISSUE 14)
    cs = d["compile_surface"]
    assert cs["static_keys"] == len(d["buckets"]) + 1
    assert cs["infer_dtypes"] == ["float32"]
    assert len(cs["fingerprint_set_hash"]) == 16
    assert cs["findings"] == 0
    assert d["params"] == "fresh-init"
    assert d["live_version_final"]
    assert d["max_inflight"] == 4          # the bench's pipelined default
    closed = d["closed_loop"]
    for q in ("p50", "p95", "p99"):
        assert closed["latency_ms"][q] is not None
    assert closed["batch_occupancy"], "no occupancy histogram"
    assert closed["rows_per_sec"] > 0
    assert closed["inflight_max"] >= 1
    # the open-loop sweep ran and carries the latency-vs-throughput table
    assert len(d["qps_sweep"]) == 1
    point = d["qps_sweep"][0]
    assert point["qps_target"] == 40.0
    assert point["latency_ms"]["p99"] is not None
    assert point["img_s_chip"] > 0
    assert d["buckets"] == [8, 16]
    # the warmup-measured cost table rides the record (the batch
    # former's price list), one entry per bucket
    assert sorted(int(k) for k in d["bucket_cost_ms"]) == d["buckets"]
    assert all(v > 0 for v in d["bucket_cost_ms"].values())
    assert d["adaptive"] is True and d["slo_ms"] is None
    assert closed["effective_wait_us"]["last"] is not None
    # the ragged-arrival leg ran both former sub-phases and carries the
    # waste/goodput comparison (the >=2x acceptance bar applies to the
    # full-ladder CPU/TPU hosts, not this 2-bucket mini config — here
    # only the structure and accounting are asserted)
    rag = d["ragged"]
    assert rag["sizes"] == "uniform[1..16]"     # capped at max_batch
    assert rag["coalesce_wait_us"] >= 2000
    for sub in ("former_off", "former_on"):
        for leg in ("closed", "open"):
            s = rag[sub][leg]
            assert s["padding_waste_ratio"] is not None
            assert s["dispatched_rows"] >= s["padded_rows"] >= 0
            assert s["rows_per_sec"] > 0
    assert rag["closed_waste_reduction_x"] is not None
    assert rag["closed_goodput_ratio"] is not None
    # the serial-vs-pipelined comparison is measured, not claimed
    cmp = d["inflight_comparison"]
    assert cmp["serial_img_s_chip"] > 0
    assert cmp["pipelined_img_s_chip"] > 0
    assert cmp["speedup"] == pytest.approx(
        cmp["pipelined_img_s_chip"] / cmp["serial_img_s_chip"], rel=0.01)
    assert cmp["closed_loop_serial"]["inflight_max"] == 1
    assert cmp["open_loop_serial_latency_ms"]["p99"] is not None
    assert cmp["open_loop_pipelined_latency_ms"]["p99"] is not None


@pytest.mark.slow
def test_bench_serve_writes_artifact(tmp_path):
    """The serve perf trajectory is machine-readable: a full (longer)
    load run writes BENCH_serve_r01.json into --artifact-dir, its content
    byte-identical in meaning to the stdout record, and a second run
    picks the next round number instead of clobbering."""
    args = ["serve", "--inline", "--model", "mlp",
            "--serve-duration", "1.5", "--serve-qps", "40",
            "--serve-clients", "4", "--serve-max-batch", "16",
            "--serve-max-wait-us", "2000",
            "--artifact-dir", str(tmp_path)]
    out = _run_cli("bench.py", args)
    assert out.returncode == 0, out.stderr[-2000:]
    path = tmp_path / "BENCH_serve_r01.json"
    assert path.exists(), list(tmp_path.iterdir())
    rec = json.loads(out.stdout.strip())
    art = json.loads(path.read_text())
    assert art == rec
    (tmp_path / "BENCH_serve_r07.json").write_text("{}")
    out = _run_cli("bench.py", args)
    assert out.returncode == 0, out.stderr[-2000:]
    assert (tmp_path / "BENCH_serve_r08.json").exists()


def test_bench_serve_rejects_training_flags():
    out = _run_cli("bench.py", ["serve", "--repeats", "2"], timeout=60)
    assert out.returncode == 2
    out = _run_cli("bench.py", ["serve", "--global-batch", "64"],
                   timeout=60)
    assert out.returncode == 2


def test_bench_training_modes_reject_serve_flags():
    out = _run_cli("bench.py", ["--serve-qps", "100"], timeout=60)
    assert out.returncode == 2
    out = _run_cli("bench.py", ["smoke", "--serve-clients", "4"],
                   timeout=60)
    assert out.returncode == 2
    out = _run_cli("bench.py", ["throughput", "--swap-during-load"],
                   timeout=60)
    assert out.returncode == 2


def test_bench_positional_mode_conflict_rejected():
    out = _run_cli("bench.py", ["serve", "--mode", "smoke"], timeout=60)
    assert out.returncode == 2


def test_bench_serve_inflight_flag_validated():
    out = _run_cli("bench.py", ["serve", "--serve-max-inflight", "0"],
                   timeout=60)
    assert out.returncode == 2
    # serve-only flag rejected outside serve mode
    out = _run_cli("bench.py", ["smoke", "--serve-max-inflight", "2"],
                   timeout=60)
    assert out.returncode == 2


def test_bench_serve_baseline_flag_validated(tmp_path):
    """--baseline usage errors exit 2 before any backend comes up: an
    unreadable file, a record without host provenance (pre-PR 3
    artifacts can't be safely compared), and use outside serve mode."""
    out = _run_cli("bench.py", ["serve", "--baseline", "/nope.json"],
                   timeout=60)
    assert out.returncode == 2
    old = tmp_path / "old.json"
    for detail in ({}, None, "not-a-dict", {"host": None}):
        old.write_text(json.dumps({"metric": "serve", "value": 1.0,
                                   "detail": detail}))
        out = _run_cli("bench.py", ["serve", "--baseline", str(old)],
                       timeout=60)
        assert out.returncode == 2, detail
        assert "device_kind" in out.stderr, detail
    out = _run_cli("bench.py", ["smoke", "--baseline", str(old)],
                   timeout=60)
    assert out.returncode == 2
    out = _run_cli("bench.py", ["serve", "--serve-slo-ms", "0"],
                   timeout=60)
    assert out.returncode == 2
    # --no-adaptive is serve-only, like every other --serve knob
    out = _run_cli("bench.py", ["throughput", "--no-adaptive"],
                   timeout=60)
    assert out.returncode == 2


def test_bench_serve_baseline_device_kind_mismatch_refused(tmp_path):
    """The ROADMAP warning, mechanized: a baseline measured on different
    silicon is refused with a nonzero exit BEFORE any load phase — a
    CPU host must not print a delta table against a TPU record."""
    base = tmp_path / "BENCH_serve_r99.json"
    base.write_text(json.dumps({
        "metric": "serve_images_per_sec_per_chip", "value": 12345.0,
        "detail": {"host": {"device_kind": "TPU v99"},
                   "recompiles_after_warmup": 0,
                   "closed_loop": {"latency_ms": {"p99": 1.0}}}}))
    out = _run_cli("bench.py",
                   ["serve", "--baseline", str(base)] + SERVE_ARGS)
    assert out.returncode == 4, (out.returncode, out.stderr[-500:])
    assert "REFUSING" in out.stderr and "TPU v99" in out.stderr
    assert not out.stdout.strip(), "refusal must not emit a record"


def test_bench_dtype_sweep_flags_validated():
    """--dtype-sweep / --serve-infer-dtype are serve-only flags,
    rejected elsewhere like every other --serve knob."""
    out = _run_cli("bench.py", ["throughput", "--dtype-sweep"],
                   timeout=60)
    assert out.returncode == 2
    out = _run_cli("bench.py", ["smoke", "--serve-infer-dtype", "int8"],
                   timeout=60)
    assert out.returncode == 2
    out = _run_cli("bench.py", ["serve", "--serve-infer-dtype", "fp4"],
                   timeout=60)
    assert out.returncode == 2


def test_bench_serve_baseline_dtype_mismatch_refused(tmp_path):
    """An int8 record must not masquerade as an f32 win (ISSUE 7
    satellite): same silicon, different serving precision — refused
    with the same exit-4 semantics as cross-silicon, before any
    measured phase."""
    base = tmp_path / "BENCH_serve_r98.json"
    base.write_text(json.dumps({
        "metric": "serve_images_per_sec_per_chip", "value": 999.0,
        "detail": {"host": {"device_kind": "cpu",
                            "infer_dtype": "int8"},
                   "recompiles_after_warmup": 0,
                   "closed_loop": {"latency_ms": {"p99": 1.0}}}}))
    out = _run_cli("bench.py",
                   ["serve", "--baseline", str(base)] + SERVE_ARGS)
    assert out.returncode == 4, (out.returncode, out.stderr[-500:])
    assert "infer_dtype" in out.stderr and "int8" in out.stderr
    assert not out.stdout.strip(), "refusal must not emit a record"


def test_bench_zipf_flags_validated():
    """--zipf / --zipf-cache-off / --serve-cache-capacity are
    serve-only flags with the usual exit-2 validation."""
    out = _run_cli("bench.py", ["throughput", "--zipf"], timeout=60)
    assert out.returncode == 2
    out = _run_cli("bench.py", ["smoke", "--serve-cache-capacity", "64"],
                   timeout=60)
    assert out.returncode == 2
    out = _run_cli("bench.py", ["serve", "--serve-cache-capacity", "0"],
                   timeout=60)
    assert out.returncode == 2
    # --zipf-cache-off without --zipf is a contradiction, not a no-op
    out = _run_cli("bench.py", ["serve", "--zipf-cache-off"], timeout=60)
    assert out.returncode == 2


@pytest.mark.cache
def test_bench_serve_zipf_contract():
    """`bench.py serve --zipf` (the acceptance-criteria spelling): the
    record carries the hot-key leg — cache-off vs cache-on over the
    same seeded Zipf mix, hit ratio >= 0.5, strictly fewer device
    dispatches with the cache on, byte-identical cached responses
    (parity probes), single-flight collapse counters, and zero
    steady-state recompiles. The >= 2x goodput bar applies to the
    real-duration artifact runs; here the structure and the
    hit/dispatch/parity invariants are asserted."""
    out = _run_cli("bench.py", ["serve", "--zipf"] + SERVE_ARGS)
    assert out.returncode == 0, out.stderr[-2000:]
    rec = json.loads(out.stdout.strip())
    d = rec["detail"]
    assert d["recompiles_after_warmup"] == 0
    z = d["zipf"]
    assert z["cache_enabled"] is True
    assert z["distinct_keys"] == 64 and z["zipf_s"] == 1.1
    off, on = z["cache_off"], z["cache_on"]
    assert off["rows_per_sec"] > 0 and on["rows_per_sec"] > 0
    assert z["hit_ratio"] is not None and z["hit_ratio_ok"], z
    assert z["goodput_x"] is not None and z["goodput_x"] > 0
    # load-tolerant (ISSUE 14 satellite): the bar is dispatches PER
    # SERVED REQUEST, so a full-suite-load-starved phase can't flip it
    assert z["device_dispatch_lower"], (
        f"cache on must dispatch strictly fewer batches per request: "
        f"{z['device_dispatches_per_request_on']} vs "
        f"{z['device_dispatches_per_request_off']}")
    assert z["device_dispatches_per_request_on"] is not None
    assert z["device_dispatches_per_request_off"] is not None
    assert z["parity_probes"] >= 1 and z["parity_ok"] is True
    cache = on["cache"]
    assert cache["hits"] > 0 and cache["inserts"] > 0
    assert cache["hit_ratio"] == z["hit_ratio"]
    assert z["p99_off_ms"] is not None and z["p99_on_ms"] is not None
    # baseline delta rows exist for the zipf signals (None-vs-None
    # handling is the chaos rows' precedent; here just shape)
    assert "single_flight_collapsed" in z


def test_bench_lowlat_flag_validated():
    """--lowlat is a serve-only flag with the usual exit-2 validation."""
    out = _run_cli("bench.py", ["throughput", "--lowlat"], timeout=60)
    assert out.returncode == 2
    out = _run_cli("bench.py", ["smoke", "--lowlat"], timeout=60)
    assert out.returncode == 2


def test_bench_serve_lowlat_contract():
    """`bench.py serve --lowlat` (the acceptance-criteria spelling):
    the record carries the single-request low-latency leg — batched vs
    fastlane p50/p99 at one in-flight client, the megakernel phase
    behind a PASSED parity gate, zero steady-state recompiles (variant
    warmup excluded), the fastpath lane counters, and the over-SLO
    attribution floor. The >= 1.5x p50 bar and >= 0.95 attribution
    bar apply to the real-duration artifact runs on a quiet host; here
    the structure, parity, recompile and lane invariants are
    asserted."""
    out = _run_cli("bench.py", ["serve", "--lowlat"] + SERVE_ARGS)
    assert out.returncode == 0, out.stderr[-2000:]
    rec = json.loads(out.stdout.strip())
    d = rec["detail"]
    assert d["recompiles_after_warmup"] == 0
    ll = d["lowlat"]
    assert ll["clients"] == 1 and ll["rows_per_request"] == 1
    for phase in ("batched", "fastlane"):
        assert ll[phase]["latency_ms"]["p50"] is not None, phase
        assert ll[phase]["requests"] > 0, phase
    # the lane actually engaged: every fastlane-phase request bypassed
    assert ll["fastlane"]["fastpath"]["dispatches"] > 0
    assert ll["fastlane"]["fastpath"]["lane_fraction"] == 1.0
    assert ll["batched"]["fastpath"]["dispatches"] == 0
    assert ll["p50_improvement_x"] is not None \
        and ll["p50_improvement_x"] > 0
    assert isinstance(ll["p50_ok"], bool) and isinstance(
        ll["p99_ok"], bool)
    # the megakernel variant served the third phase behind its gate
    assert ll["megakernel"] is not None
    assert ll["megakernel_parity"]["passed"] is True
    assert ll["megakernel"]["fastpath"]["dispatches"] > 0
    # the leg itself ran recompile-free (megakernel warmup excluded)
    assert ll["recompiles"] == 0 and ll["recompiles_ok"] is True
    assert ll["variant_warmup_compile_events"] > 0
    att = ll["attribution"]
    assert att["fastpath_spans"] > 0
    assert att["over_slo_requests"] >= 0
    assert "min_attributed_frac" in att and "ok" in att


def test_bench_serve_baseline_zipf_cache_mismatch_refused(tmp_path):
    """A cache-on zipf run must refuse a --baseline whose zipf leg ran
    cache-off (and vice versa) — the same exit-4 semantics as
    cross-silicon and cross-dtype deltas, before any load phase."""
    base = tmp_path / "BENCH_serve_r97.json"
    base.write_text(json.dumps({
        "metric": "serve_images_per_sec_per_chip", "value": 100.0,
        "detail": {"host": {"device_kind": "cpu"},
                   "zipf": {"cache_enabled": False},
                   "recompiles_after_warmup": 0,
                   "closed_loop": {"latency_ms": {"p99": 1.0}}}}))
    out = _run_cli("bench.py", ["serve", "--zipf", "--baseline",
                                str(base)] + SERVE_ARGS)
    assert out.returncode == 4, (out.returncode, out.stderr[-500:])
    assert "cache_enabled" in out.stderr
    assert not out.stdout.strip(), "refusal must not emit a record"


def test_serve_http_fastlane_end_to_end():
    """serve.py --serve-fastlane: a lone request at an idle pipeline is
    served through the bypass lane (fastpath counters in /metrics +
    the Prometheus lane series), byte-identical semantics otherwise."""
    env, repo = worker_env()
    proc, port = _start_server(repo, env, extra=["--serve-fastlane"])
    try:
        base = f"http://127.0.0.1:{port}"
        ok = _wait_healthy(base)
        body = np.full((1, 784), 21, np.uint8).tobytes()
        rs = []
        for _ in range(3):
            resp = urllib.request.urlopen(f"{base}/predict", data=body,
                                          timeout=30)
            rs.append(json.loads(resp.read()))
        assert all(r["classes"] == rs[0]["classes"] for r in rs)
        assert all(r["version"] == ok["live_version"] for r in rs)
        m = _get_json(f"{base}/metrics")
        fp = m["fastpath"]
        assert fp["dispatches"] >= 1 and fp["rows"] >= 1
        assert m["adaptive"]["fastpath_dispatches"] >= 1
        prom = urllib.request.urlopen(
            f"{base}/metrics?format=prometheus", timeout=10
        ).read().decode()
        assert "dmnist_serve_fastpath_dispatches_total" in prom
        assert "# HELP dmnist_serve_fastpath_dispatches_total" in prom
    finally:
        proc.send_signal(signal.SIGTERM)
        try:
            proc.communicate(timeout=60)
        except subprocess.TimeoutExpired:
            proc.kill()


def test_serve_cache_ttl_flag_validated():
    """--serve-cache-ttl-s must be > 0 (usage error before any backend
    work)."""
    env, repo = worker_env()
    out = subprocess.run(
        [sys.executable, os.path.join(repo, "serve.py"),
         "--serve-cache-ttl-s", "0"],
        capture_output=True, text=True, env=env, cwd=repo, timeout=60)
    assert out.returncode == 2
    assert "serve-cache-ttl-s" in out.stderr


@pytest.mark.cache
def test_serve_http_cache_end_to_end():
    """serve.py --serve-cache --serve-dedup --serve-trace: repeated
    identical POST /predict bodies hit the cache (visible in /metrics'
    `cache` block and the Prometheus cache series), hit responses stay
    version-tagged AND carry X-Trace-Id, and a model roll via the
    admin promote invalidates the cache."""
    env, repo = worker_env()
    proc, port = _start_server(
        repo, env, extra=["--serve-cache", "--serve-dedup",
                          "--serve-trace"])
    try:
        base = f"http://127.0.0.1:{port}"
        ok = _wait_healthy(base)
        body = np.full((2, 784), 37, np.uint8).tobytes()
        rs = []
        for _ in range(3):
            resp = urllib.request.urlopen(f"{base}/predict", data=body,
                                          timeout=30)
            assert resp.headers.get("X-Trace-Id")
            rs.append(json.loads(resp.read()))
        assert all(r["classes"] == rs[0]["classes"] for r in rs)
        assert all(r["version"] == ok["live_version"] for r in rs)
        m = _get_json(f"{base}/metrics")
        c = m["cache"]
        assert c["hits"] >= 1 and c["hit_ratio"] > 0
        assert c["entries"] >= 1
        prom = urllib.request.urlopen(
            f"{base}/metrics?format=prometheus", timeout=10
        ).read().decode()
        assert "dmnist_serve_cache_hits_total" in prom
        assert "# HELP dmnist_serve_cache_hits_total" in prom
        assert "dmnist_serve_cache_hit_ratio" in prom
    finally:
        proc.send_signal(signal.SIGTERM)
        try:
            out, _ = proc.communicate(timeout=60)
        except subprocess.TimeoutExpired:
            proc.kill()
            raise
    assert proc.returncode == 0
    records = [json.loads(l) for l in out.splitlines() if l.strip()]
    summary = [r for r in records if r.get("metric") == "serve_summary"]
    assert summary and summary[-1]["cache"]["hits"] >= 1


@pytest.mark.quant
def test_bench_serve_dtype_sweep_contract():
    """`bench.py serve --dtype-sweep` (the acceptance-criteria
    spelling): one record carrying f32/bf16/int8 closed-loop legs
    back-to-back — per-dtype img/s/chip, the parity verdicts that
    gated the variants, per-dtype bucket cost tables, zero recompiles
    per dtype — plus the infer_dtype/fused provenance in detail.host."""
    out = _run_cli("bench.py", [
        "serve", "--inline", "--model", "lenet", "--dtype-sweep",
        "--serve-duration", "0.4", "--serve-qps", "30",
        "--serve-clients", "2", "--serve-max-batch", "8",
        "--serve-max-wait-us", "2000", "--no-artifact"])
    assert out.returncode == 0, out.stderr[-2000:]
    rec = json.loads(out.stdout.strip())
    d = rec["detail"]
    assert d["host"]["infer_dtype"] == "float32"      # headline engine
    assert d["host"]["fused_kernels"] == "xla"        # resolved for CPU
    sweep = d["dtype_sweep"]
    legs = sweep["legs"]
    assert set(legs) == {"float32", "bfloat16", "int8"}
    for dt in ("float32", "bfloat16", "int8"):
        leg = legs[dt]
        assert "skipped" not in leg, (dt, leg)        # lenet gates pass
        assert leg["img_s_chip"] > 0
        assert leg["recompiles_after_warmup"] == 0
        assert leg["bucket_cost_ms"]                  # per-dtype table
        # the measured window really served THIS precision
        assert set(leg["by_dtype"]) == {dt}
    for dt in ("bfloat16", "int8"):
        p = legs[dt]["parity"]
        assert p["passed"] is True
        assert p["argmax_agreement"] >= 0.995
        assert sweep["speedup_vs_float32"][dt] is not None
    assert sweep["best_dtype"] in ("bfloat16", "int8")
    # variant warmups excluded, steady state shape-stable end to end
    assert d["recompiles_after_warmup"] == 0


def test_serve_request_timeout_flag_validated():
    out = _run_cli("serve.py", ["--request-timeout", "0"], timeout=60)
    assert out.returncode == 2
    out = _run_cli("serve.py", ["--serve-max-inflight", "0"], timeout=60)
    assert out.returncode == 2


def test_serve_selftest_contract():
    out = _run_cli("serve.py", ["--model", "mlp", "--device", "cpu",
                                "--serve-max-batch", "16",
                                "--selftest", "32"])
    assert out.returncode == 0, out.stderr[-2000:]
    rec = json.loads(out.stdout.splitlines()[-1])
    assert rec["metric"] == "serve_selftest"
    assert rec["requests_driven"] == 32
    assert rec["rows"] > 0 and rec["batches"] > 0
    assert rec["latency_ms"]["p50"] is not None
    assert rec["batch_occupancy"]


def _start_server(repo, env, extra=()):
    """Launch serve.py --port 0, return (proc, port) once the port is
    announced. The server is still WARMING at that point — /healthz is
    503 until the initial model has every bucket compiled."""
    proc = subprocess.Popen(
        [sys.executable, os.path.join(repo, "serve.py"), "--model", "mlp",
         "--device", "cpu", "--serve-max-batch", "16", "--port", "0",
         "--metrics-every", "0.5"] + list(extra),
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        env=env, cwd=repo)
    deadline = time.monotonic() + 120
    while time.monotonic() < deadline:
        line = proc.stdout.readline()
        assert line, "serve.py exited before announcing readiness"
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        if rec.get("metric") == "serve_ready":
            return proc, rec["port"]
    pytest.fail("no serve_ready line")


def _get_json(url, timeout=10):
    return json.loads(urllib.request.urlopen(url, timeout=timeout).read())


def _post_json(url, payload, timeout=120):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    return json.loads(urllib.request.urlopen(req, timeout=timeout).read())


def _wait_healthy(base, timeout=120) -> dict:
    """Poll /healthz until it flips to 200 (warmup complete); returns
    the healthy payload."""
    deadline = time.monotonic() + timeout
    last = None
    while time.monotonic() < deadline:
        try:
            return _get_json(f"{base}/healthz")
        except urllib.error.HTTPError as e:
            # 503 while warming IS the contract — keep polling
            last = json.loads(e.read())
            assert e.code == 503, e.code
            assert last["ok"] is False
            time.sleep(0.1)
    pytest.fail(f"/healthz never became healthy: {last}")


def test_serve_http_end_to_end():
    """serve.py --port 0: ready announcement, /healthz 503-while-warming
    then a real state payload, POST /predict (version-tagged), /metrics
    heartbeat shape, 400 on a malformed body, SIGTERM -> clean summary.
    The metrics lines carry the conventional 'metric' key, so a
    supervise.json_record_acceptor sees a serving process as alive."""
    env, repo = worker_env()
    proc, port = _start_server(repo, env)
    try:
        base = f"http://127.0.0.1:{port}"
        # healthz flips 503 -> 200 only once warmup completes, and then
        # reports REAL state, not a hardcoded ok
        ok = _wait_healthy(base)
        assert ok["ok"] is True and ok["state"] == "running"
        assert ok["live_version"]
        assert isinstance(ok["pending_rows"], int)
        assert isinstance(ok["inflight_batches"], int)
        assert ok["versions"] >= 1

        body = np.full((3, 784), 128, np.uint8).tobytes()
        r = json.loads(urllib.request.urlopen(
            f"{base}/predict", data=body, timeout=30).read())
        assert r["n"] == 3 and len(r["classes"]) == 3
        assert all(0 <= c <= 9 for c in r["classes"])
        assert r["version"] == ok["live_version"]

        m = json.loads(urllib.request.urlopen(
            f"{base}/metrics", timeout=10).read())
        assert m["metric"] == "serve_stats" and m["requests"] >= 1
        assert r["version"] in m["by_version"]
        # the operator snapshot carries live pipeline gauges and the
        # adaptive controller's state, not just window counters
        q = m["queue"]
        assert q["pending_rows"] >= 0 and q["inflight_batches"] >= 0
        assert q["max_inflight"] >= 1 and q["queue_depth_watermark"] >= 1
        assert m["adaptive"]["aimd_wait_us"] > 0    # default: adaptive on
        assert m["padding_waste_ratio"] is not None
        assert m["bucket_dispatches"]
        assert m["effective_wait_us"]["last"] is not None

        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(f"{base}/predict", data=b"not-784",
                                   timeout=10)
        assert ei.value.code == 400
    finally:
        proc.send_signal(signal.SIGTERM)
        try:
            out, _ = proc.communicate(timeout=60)
        except subprocess.TimeoutExpired:
            proc.kill()
            raise
    assert proc.returncode == 0
    records = [json.loads(l) for l in out.splitlines() if l.strip()]
    summary = [r for r in records if r.get("metric") == "serve_summary"]
    assert summary and summary[-1]["requests"] >= 1


def _save_mlp_checkpoint(ckpt_dir: str, step: int, seed: int = 3) -> None:
    """Commit a full-train-state checkpoint the serving process can
    roll to (the admin/SIGHUP tests' 'a trainer finished' stand-in)."""
    import jax
    import jax.numpy as jnp

    from distributedmnist_tpu import models, optim
    from distributedmnist_tpu.checkpoint import Checkpointer
    from distributedmnist_tpu.parallel import make_mesh, replicated
    from distributedmnist_tpu.trainer import init_state

    mesh = make_mesh(jax.devices()[:8])
    model = models.build("mlp", fused="xla")
    state = init_state(jax.random.PRNGKey(seed), model,
                       optim.build("adam", 1e-3),
                       jnp.zeros((1, 28, 28, 1)))
    state = state.replace(step=jnp.asarray(step, jnp.int32))
    state = jax.device_put(state, replicated(mesh))
    ckpt = Checkpointer(ckpt_dir, async_save=False)
    ckpt.save(step, state)
    ckpt.wait()
    ckpt.close()


def test_serve_admin_model_lifecycle(tmp_path):
    """The model-lifecycle admin surface end-to-end over HTTP: boot
    fresh-init (empty checkpoint dir), load a newly committed checkpoint
    via POST /models/load (params-only restore + pre-warm, live traffic
    unaffected), promote it atomically, roll again via SIGHUP, and put
    the demoted version back in play as a canary."""
    ckpt_dir = str(tmp_path / "ck")
    env, repo = worker_env()
    proc, port = _start_server(repo, env,
                               extra=["--checkpoint-dir", ckpt_dir])
    try:
        base = f"http://127.0.0.1:{port}"
        boot = _wait_healthy(base)["live_version"]

        models_view = _get_json(f"{base}/models")
        assert models_view["routes"]["live"] == boot
        assert [v["version"] for v in models_view["versions"]] == [boot]
        assert models_view["versions"][0]["source"] == "fresh-init"
        # the warmup-measured cost table is surfaced per version
        assert models_view["versions"][0]["bucket_cost_ms"]

        # roll 1: explicit admin load + promote
        _save_mlp_checkpoint(ckpt_dir, step=5)
        loaded = _post_json(f"{base}/models/load", {})
        assert loaded["version"] == "step-5"
        assert loaded["state"] == "ready"       # promotable, NOT live
        assert loaded["warmup_compile_events"] > 0
        assert _get_json(f"{base}/models")["routes"]["live"] == boot

        promoted = _post_json(f"{base}/models/promote",
                              {"version": "step-5"})
        assert promoted["live"] == "step-5"
        body = np.full((2, 784), 7, np.uint8).tobytes()
        r = json.loads(urllib.request.urlopen(
            f"{base}/predict", data=body, timeout=30).read())
        assert r["version"] == "step-5"

        # promote of an unknown version is a 404, not a crash
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post_json(f"{base}/models/promote", {"version": "nope"})
        assert ei.value.code == 404
        # malformed fraction is a client error (400), not a lifecycle
        # conflict (409) or a server fault (500)
        for bad in ("lots", None):
            with pytest.raises(urllib.error.HTTPError) as ei:
                _post_json(f"{base}/models/promote",
                           {"version": boot, "mode": "canary",
                            "fraction": bad})
            assert ei.value.code == 400, bad

        # roll 2: SIGHUP = load latest from --checkpoint-dir + promote
        _save_mlp_checkpoint(ckpt_dir, step=9, seed=4)
        proc.send_signal(signal.SIGHUP)
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            if _get_json(f"{base}/models")["routes"]["live"] == "step-9":
                break
            time.sleep(0.2)
        else:
            pytest.fail("SIGHUP reload never promoted step-9")

        # the demoted version is still resident: stage it as a canary
        canary = _post_json(f"{base}/models/promote",
                            {"version": "step-5", "mode": "canary",
                             "fraction": 0.25})
        assert canary["canary"] == {"version": "step-5",
                                    "fraction": 0.25}
        assert _get_json(f"{base}/healthz")["live_version"] == "step-9"
    finally:
        proc.send_signal(signal.SIGTERM)
        try:
            proc.communicate(timeout=60)
        except subprocess.TimeoutExpired:
            proc.kill()
            raise
    assert proc.returncode == 0


def test_healthz_state_machine_recovers_from_failed_boot():
    """ServerState.healthz: 503 while warming, 200 once ANY path puts a
    live version up (including recovery after a failed boot via admin
    load+promote), and draining is terminal 503 — a repaired server
    must not stay unroutable forever."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "serve_mod", os.path.join(worker_env()[1], "serve.py"))
    serve_mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(serve_mod)

    class StubRegistry:
        live = None

        def live_version(self):
            return self.live

        def describe(self):
            return {"versions": [1] if self.live else []}

    class StubBatcher:
        def pending_rows(self):
            return 0

        def inflight_batches(self):
            return 0

    state = serve_mod.ServerState()
    reg, b = StubRegistry(), StubBatcher()
    code, payload = state.healthz(reg, b)
    assert code == 503 and payload["state"] == "warming"

    state.phase = "failed"                      # boot load died
    code, _ = state.healthz(reg, b)
    assert code == 503
    reg.live = "step-5"                         # admin repaired it
    code, payload = state.healthz(reg, b)
    assert code == 200 and payload["state"] == "running"
    assert payload["live_version"] == "step-5"

    state.begin_drain()                         # SIGTERM: terminal
    code, payload = state.healthz(reg, b)
    assert code == 503 and payload["state"] == "draining"
    # draining can never be resurrected — not by the warm thread, not
    # by a healthz poll that sees a live version
    state.mark_running()
    code, payload = state.healthz(reg, b)
    assert code == 503 and payload["state"] == "draining"


@pytest.mark.chaos
def test_serve_http_under_injected_restore_failure(tmp_path):
    """ISSUE 5 satellite: with the boot checkpoint restore failing
    (injected registry.restore fault — fired before orbax touches
    disk, so a bare committed-step dir suffices), the server must stay
    up and honestly 503-unhealthy — never crash, never flap to
    running. /healthz reports the unhealthy state, GET /models
    surfaces the failed version WITH last_error, /predict sheds with
    Retry-After, admin load maps the failure to 409, and SIGTERM still
    exits clean."""
    ck = tmp_path / "ck"
    (ck / "5").mkdir(parents=True)
    env, repo = worker_env()
    proc, port = _start_server(
        repo, env, extra=["--checkpoint-dir", str(ck),
                          "--serve-faults",
                          "registry.restore:p=1,error=injected boot "
                          "restore failure"])
    try:
        base = f"http://127.0.0.1:{port}"
        # the warm thread fails fast; poll until the failed version
        # shows up, asserting healthz stays 503 the whole way
        deadline = time.monotonic() + 120
        versions = []
        while time.monotonic() < deadline:
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(f"{base}/healthz", timeout=10)
            assert ei.value.code == 503
            payload = json.loads(ei.value.read())
            assert payload["ok"] is False
            assert payload["state"] in ("warming", "failed")
            assert payload["live_version"] is None
            versions = _get_json(f"{base}/models")["versions"]
            if versions and versions[0]["state"] == "failed":
                break
            time.sleep(0.1)
        else:
            pytest.fail("failed restore never surfaced in GET /models")
        failed = versions[0]
        assert failed["version"] == "step-5"
        assert "injected boot restore failure" in failed["last_error"]
        assert failed["last_error_at"] is not None

        # /predict sheds (no live model) with a Retry-After header
        body = np.full((1, 784), 3, np.uint8).tobytes()
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(f"{base}/predict", data=body,
                                   timeout=10)
        assert ei.value.code == 503
        assert int(ei.value.headers["Retry-After"]) >= 1

        # admin load hits the same injected failure -> 409, not a crash
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post_json(f"{base}/models/load", {})
        assert ei.value.code == 409
        assert "injected boot restore" in json.loads(
            ei.value.read())["error"]
        # still 503 after the failed admin load — no flap to running
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(f"{base}/healthz", timeout=10)
        assert ei.value.code == 503
    finally:
        proc.send_signal(signal.SIGTERM)
        try:
            proc.communicate(timeout=60)
        except subprocess.TimeoutExpired:
            proc.kill()
            raise
    assert proc.returncode == 0


def test_serve_http_deadline_header_and_rollback_surface():
    """X-Deadline-Ms end-to-end: malformed -> 400; an already-expired
    budget -> 504 with a pipeline-derived Retry-After (shed before
    dispatch); a generous budget serves normally. /healthz carries the
    rollback surface (zero events on a healthy server) and /metrics
    the resilience counters + breaker snapshot."""
    env, repo = worker_env()
    proc, port = _start_server(repo, env)
    try:
        base = f"http://127.0.0.1:{port}"
        _wait_healthy(base)
        body = np.full((2, 784), 9, np.uint8).tobytes()

        def predict(deadline_ms):
            req = urllib.request.Request(
                f"{base}/predict", data=body,
                headers={"X-Deadline-Ms": deadline_ms})
            return json.loads(urllib.request.urlopen(
                req, timeout=30).read())

        assert predict("30000")["n"] == 2        # generous budget: 200

        for bad in ("not-a-number", "-5", "nan", "inf"):
            with pytest.raises(urllib.error.HTTPError) as ei:
                predict(bad)
            assert ei.value.code == 400, bad

        with pytest.raises(urllib.error.HTTPError) as ei:
            predict("0.0001")                    # expired at submit
        assert ei.value.code == 504
        assert int(ei.value.headers["Retry-After"]) >= 1
        assert "deadline" in json.loads(ei.value.read())["error"]

        ok = _get_json(f"{base}/healthz")
        assert ok["rollbacks"] == 0 and ok["last_rollback"] is None

        m = _get_json(f"{base}/metrics")
        res = m["resilience"]
        assert res["deadline_shed_requests"] >= 1
        assert res["rollbacks"] == 0
        pol = m["resilience_policy"]
        assert pol["bisect"] is True
        assert pol["breaker"]["trips"] == 0
    finally:
        proc.send_signal(signal.SIGTERM)
        try:
            proc.communicate(timeout=60)
        except subprocess.TimeoutExpired:
            proc.kill()
            raise
    assert proc.returncode == 0


@pytest.mark.chaos
def test_bench_serve_chaos_contract():
    """`bench.py serve --chaos` (the acceptance-criteria spelling): the
    seeded fault schedule yields >=1% injected dispatch faults with
    EXACT poison isolation (cohort-mates all succeed), a forced
    breaker trip with auto-rollback to the healthy fallback, deadline
    sheds, availability 1.0 over non-injected traffic, and zero
    recompiles through the whole storm — plus the git provenance the
    record now carries."""
    out = _run_cli("bench.py", ["serve", "--chaos"] + SERVE_ARGS)
    assert out.returncode == 0, out.stderr[-2000:]
    rec = json.loads(out.stdout.strip())
    d = rec["detail"]
    assert d["host"]["git_commit"] and len(d["host"]["git_commit"]) == 40
    assert d["host"]["git_dirty"] in (True, False)
    c = d["chaos"]
    assert c["requests"] > 100
    assert c["injected_dispatch_faults"] > 0
    assert c["poison_isolated_exact"] is True
    assert c["injected_fetch_faults"] > 0        # the storm really blew
    assert c["breaker_trips"] == 1
    assert c["rollbacks"] >= 1
    assert c["rollback_engaged"] is True
    assert c["live_version_after"] == "v-chaos-fallback"
    assert d["live_version_final"] == "v-chaos-fallback"
    assert c["deadline_shed"] > 0
    assert c["other_failures"] == 0
    assert c["availability_ok"] is True
    assert c["availability_excluding_injected"] >= 0.99
    assert c["p99_under_faults_ms"] is not None
    assert c["recompiles_during_chaos"] == 0
    assert d["recompiles_after_warmup"] == 0     # whole-run discipline
    assert c["bisect_rescued_requests"] >= 1


@pytest.mark.chaos
@pytest.mark.cache
def test_bench_serve_chaos_cache_ledger():
    """`bench.py serve --chaos --serve-cache` (the ROADMAP follow-up
    PR 10 left open): the whole chaos drill runs through the
    prediction cache + single-flight front with the registry's
    invalidation hook live — and the poison-isolation ledger stays
    EXACT on a leader basis: client failures from dispatch injection,
    minus collapsed-follower echoes, equal the injector's distinct
    poisoned set; cached hits and collapsed followers distort
    nothing. The forced rollback's epoch bump is exercised mid-storm
    (>= 1 invalidation), and the resilience acceptance bars all still
    hold behind the cache front."""
    out = _run_cli("bench.py", ["serve", "--chaos", "--serve-cache"]
                   + SERVE_ARGS)
    assert out.returncode == 0, out.stderr[-2000:]
    rec = json.loads(out.stdout.strip())
    c = rec["detail"]["chaos"]
    cache = c["cache"]
    assert cache["enabled"] is True and cache["capacity"] == 4096
    # the ledger (ISSUE 12 satellite acceptance)
    assert cache["ledger_exact"] is True
    assert c["poison_isolated_exact"] is True
    assert (cache["poison_leaders"]
            == cache["poison_client_failures"]
            - cache["poison_follower_echoes"]
            == c["poison_unique"] > 0)
    # the cache really fronted the drill: the 256-request mix repeats,
    # so hits happen — and every hit was served ok without a rid draw
    stats = cache["stats"]
    assert stats["hits"] >= 1
    assert cache["cache_hits_ok"] >= 1
    # the rollback's atomic epoch bump fired mid-storm
    assert c["rollback_engaged"] is True
    assert stats["invalidations"] >= 1
    # resilience bars unchanged behind the front
    assert c["availability_ok"] is True
    assert c["other_failures"] == 0
    assert c["breaker_trips"] == 1
    assert c["recompiles_during_chaos"] == 0


def test_bench_serve_cache_flag_requires_chaos():
    out = _run_cli("bench.py", ["serve", "--serve-cache"] + SERVE_ARGS)
    assert out.returncode == 2
    assert "--chaos" in out.stderr


def test_bench_serve_swap_during_load():
    """`bench.py serve --swap-during-load`: the record carries the swap
    block — a real mid-window load + pre-warm + promote with ZERO
    recompiles after the candidate's warmup, and the swap-window p99
    measured against the steady-state p99."""
    out = _run_cli("bench.py", ["serve", "--swap-during-load"]
                   + SERVE_ARGS)
    assert out.returncode == 0, out.stderr[-2000:]
    rec = json.loads(out.stdout.strip())
    d = rec["detail"]
    swap = d["swap"]
    assert swap["version"] == "v-swap"
    assert swap["warmup_compile_events"] > 0     # candidate DID compile,
    assert swap["recompiles_after_swap"] == 0    # but off the hot path
    assert d["recompiles_after_warmup"] == 0     # whole-run discipline
    assert swap["swap_window_p99_ms"] is not None
    assert swap["load_warm_s"] > 0
    # both versions took traffic inside the swap window
    assert set(swap["swap_window"]["by_version"]) == {"v1", "v-swap"}
    assert d["live_version_final"] == "v-swap"
    # the decomposed post-promote tail (the pure new-version population)
    # is reported alongside the whole-window ratio
    assert swap["post_swap_p99_ms"] is not None
    assert swap["post_swap_p99_ratio_vs_steady"] is not None


def test_baseline_delta_includes_chaos_leg_rows():
    """ISSUE 6 satellite: the --baseline delta table carries the
    chaos-leg resilience signals (availability, failovers,
    p99-under-faults) alongside the happy-path columns — and degrades
    to None-vs-None rows when either round ran without --chaos."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "bench_mod", os.path.join(worker_env()[1], "bench.py"))
    bench_mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench_mod)

    def rec(value, chaos):
        return {"value": value, "detail": {
            "closed_loop": {"latency_ms": {"p99": 5.0}},
            "ragged": None,
            "recompiles_after_warmup": 0,
            "chaos": chaos,
            "host": {"device_kind": "cpu"}}}

    cur = rec(100.0, {"availability_excluding_injected": 1.0,
                      "p99_under_faults_ms": 40.0, "failovers": 29})
    base = rec(90.0, {"availability_excluding_injected": 0.995,
                      "p99_under_faults_ms": 50.0, "failovers": 0})
    delta = bench_mod._baseline_delta(cur, base, "BENCH_serve_r04.json")
    assert delta["chaos_availability"]["current"] == 1.0
    assert delta["chaos_availability"]["baseline"] == 0.995
    assert delta["chaos_p99_under_faults_ms"]["delta_pct"] == -20.0
    assert delta["chaos_failovers"]["current"] == 29
    # a chaos-less round degrades to empty rows, not a KeyError
    delta = bench_mod._baseline_delta(rec(100.0, None), base, "x.json")
    assert delta["chaos_availability"]["current"] is None


@pytest.mark.jaxcheck
def test_baseline_delta_includes_compile_surface_row():
    """ISSUE 12 satellite: the --baseline delta table carries the
    compile-surface provenance row (static key count) plus the
    fingerprint-set hash comparison, degrading to None against
    pre-ISSUE 12 records."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "bench_mod2", os.path.join(worker_env()[1], "bench.py"))
    bench_mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench_mod)

    def rec(value, surface):
        return {"value": value, "detail": {
            "closed_loop": {"latency_ms": {"p99": 5.0}},
            "ragged": None,
            "recompiles_after_warmup": 0,
            "chaos": None,
            "compile_surface": surface,
            "host": {"device_kind": "cpu"}}}

    cur = rec(100.0, {"static_keys": 10,
                      "fingerprint_set_hash": "aaaa"})
    base = rec(90.0, {"static_keys": 8,
                      "fingerprint_set_hash": "bbbb"})
    delta = bench_mod._baseline_delta(cur, base, "BENCH_serve_r08.json")
    assert delta["compile_surface_keys"]["current"] == 10
    assert delta["compile_surface_keys"]["baseline"] == 8
    assert delta["compile_surface"]["match"] is False
    same = bench_mod._baseline_delta(
        cur, rec(90.0, {"static_keys": 10,
                        "fingerprint_set_hash": "aaaa"}), "x.json")
    assert same["compile_surface"]["match"] is True
    # pre-ISSUE 12 baseline: None rows, no hash verdict, no KeyError
    old = bench_mod._baseline_delta(cur, rec(90.0, None), "x.json")
    assert old["compile_surface_keys"]["baseline"] is None
    assert old["compile_surface"]["match"] is None
