"""CLI contracts of the serving stack: `bench.py serve` (positional mode
spelling included) emits the one-line serve_images_per_sec_per_chip
record with latency percentiles, occupancy and a recompile-free steady
state; serve.py's selftest and HTTP modes run end-to-end on CPU; flag
validation rejects cross-mode misuse before any backend comes up."""

import json
import os
import signal
import subprocess
import sys
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from tests.conftest import worker_env


def _run_cli(script, extra, timeout=600):
    env, repo = worker_env()
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    return subprocess.run(
        [sys.executable, os.path.join(repo, script)] + extra,
        capture_output=True, text=True, env=env, cwd=repo,
        timeout=timeout)


SERVE_ARGS = ["--inline", "--model", "mlp", "--serve-duration", "0.5",
              "--serve-qps", "40", "--serve-clients", "2",
              "--serve-max-batch", "16", "--serve-max-wait-us", "2000",
              "--no-artifact"]


def test_bench_serve_contract():
    """`python bench.py serve` (the acceptance-criteria spelling)
    completes the serial-vs-pipelined capacity phases and the QPS sweep
    and emits the parseable record — including p50/p95/p99, batch
    occupancy, the inflight comparison, and zero steady-state
    recompiles."""
    out = _run_cli("bench.py", ["serve"] + SERVE_ARGS)
    assert out.returncode == 0, out.stderr[-2000:]
    lines = [l for l in out.stdout.splitlines() if l.strip()]
    assert len(lines) == 1, f"expected ONE JSON line, got {out.stdout!r}"
    rec = json.loads(lines[0])
    assert set(rec) == {"metric", "value", "unit", "vs_baseline", "detail"}
    assert rec["metric"] == "serve_images_per_sec_per_chip"
    assert rec["unit"] == "images/sec/chip"
    assert rec["value"] > 0 and rec["vs_baseline"] > 0
    d = rec["detail"]
    # steady state after bucket warmup must be recompile-free
    assert d["warmup_compile_events"] > 0
    assert d["recompiles_after_warmup"] == 0
    assert d["max_inflight"] == 4          # the bench's pipelined default
    closed = d["closed_loop"]
    for q in ("p50", "p95", "p99"):
        assert closed["latency_ms"][q] is not None
    assert closed["batch_occupancy"], "no occupancy histogram"
    assert closed["rows_per_sec"] > 0
    assert closed["inflight_max"] >= 1
    # the open-loop sweep ran and carries the latency-vs-throughput table
    assert len(d["qps_sweep"]) == 1
    point = d["qps_sweep"][0]
    assert point["qps_target"] == 40.0
    assert point["latency_ms"]["p99"] is not None
    assert point["img_s_chip"] > 0
    assert d["buckets"] == [8, 16]
    # the serial-vs-pipelined comparison is measured, not claimed
    cmp = d["inflight_comparison"]
    assert cmp["serial_img_s_chip"] > 0
    assert cmp["pipelined_img_s_chip"] > 0
    assert cmp["speedup"] == pytest.approx(
        cmp["pipelined_img_s_chip"] / cmp["serial_img_s_chip"], rel=0.01)
    assert cmp["closed_loop_serial"]["inflight_max"] == 1
    assert cmp["open_loop_serial_latency_ms"]["p99"] is not None
    assert cmp["open_loop_pipelined_latency_ms"]["p99"] is not None


@pytest.mark.slow
def test_bench_serve_writes_artifact(tmp_path):
    """The serve perf trajectory is machine-readable: a full (longer)
    load run writes BENCH_serve_r01.json into --artifact-dir, its content
    byte-identical in meaning to the stdout record, and a second run
    picks the next round number instead of clobbering."""
    args = ["serve", "--inline", "--model", "mlp",
            "--serve-duration", "1.5", "--serve-qps", "40",
            "--serve-clients", "4", "--serve-max-batch", "16",
            "--serve-max-wait-us", "2000",
            "--artifact-dir", str(tmp_path)]
    out = _run_cli("bench.py", args)
    assert out.returncode == 0, out.stderr[-2000:]
    path = tmp_path / "BENCH_serve_r01.json"
    assert path.exists(), list(tmp_path.iterdir())
    rec = json.loads(out.stdout.strip())
    art = json.loads(path.read_text())
    assert art == rec
    (tmp_path / "BENCH_serve_r07.json").write_text("{}")
    out = _run_cli("bench.py", args)
    assert out.returncode == 0, out.stderr[-2000:]
    assert (tmp_path / "BENCH_serve_r08.json").exists()


def test_bench_serve_rejects_training_flags():
    out = _run_cli("bench.py", ["serve", "--repeats", "2"], timeout=60)
    assert out.returncode == 2
    out = _run_cli("bench.py", ["serve", "--global-batch", "64"],
                   timeout=60)
    assert out.returncode == 2


def test_bench_training_modes_reject_serve_flags():
    out = _run_cli("bench.py", ["--serve-qps", "100"], timeout=60)
    assert out.returncode == 2
    out = _run_cli("bench.py", ["smoke", "--serve-clients", "4"],
                   timeout=60)
    assert out.returncode == 2


def test_bench_positional_mode_conflict_rejected():
    out = _run_cli("bench.py", ["serve", "--mode", "smoke"], timeout=60)
    assert out.returncode == 2


def test_bench_serve_inflight_flag_validated():
    out = _run_cli("bench.py", ["serve", "--serve-max-inflight", "0"],
                   timeout=60)
    assert out.returncode == 2
    # serve-only flag rejected outside serve mode
    out = _run_cli("bench.py", ["smoke", "--serve-max-inflight", "2"],
                   timeout=60)
    assert out.returncode == 2


def test_serve_request_timeout_flag_validated():
    out = _run_cli("serve.py", ["--request-timeout", "0"], timeout=60)
    assert out.returncode == 2
    out = _run_cli("serve.py", ["--serve-max-inflight", "0"], timeout=60)
    assert out.returncode == 2


def test_serve_selftest_contract():
    out = _run_cli("serve.py", ["--model", "mlp", "--device", "cpu",
                                "--serve-max-batch", "16",
                                "--selftest", "32"])
    assert out.returncode == 0, out.stderr[-2000:]
    rec = json.loads(out.stdout.splitlines()[-1])
    assert rec["metric"] == "serve_selftest"
    assert rec["requests_driven"] == 32
    assert rec["rows"] > 0 and rec["batches"] > 0
    assert rec["latency_ms"]["p50"] is not None
    assert rec["batch_occupancy"]


def test_serve_http_end_to_end():
    """serve.py --port 0: ready announcement, POST /predict, /metrics
    heartbeat shape, 400 on a malformed body, SIGTERM -> clean summary.
    The metrics lines carry the conventional 'metric' key, so a
    supervise.json_record_acceptor sees a serving process as alive."""
    env, repo = worker_env()
    proc = subprocess.Popen(
        [sys.executable, os.path.join(repo, "serve.py"), "--model", "mlp",
         "--device", "cpu", "--serve-max-batch", "16", "--port", "0",
         "--metrics-every", "0.5"],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        env=env, cwd=repo)
    port = None
    try:
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            line = proc.stdout.readline()
            assert line, "serve.py exited before announcing readiness"
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if rec.get("metric") == "serve_ready":
                port = rec["port"]
                break
        assert port, "no serve_ready line"
        base = f"http://127.0.0.1:{port}"

        body = np.full((3, 784), 128, np.uint8).tobytes()
        r = json.loads(urllib.request.urlopen(
            f"{base}/predict", data=body, timeout=30).read())
        assert r["n"] == 3 and len(r["classes"]) == 3
        assert all(0 <= c <= 9 for c in r["classes"])

        m = json.loads(urllib.request.urlopen(
            f"{base}/metrics", timeout=10).read())
        assert m["metric"] == "serve_stats" and m["requests"] >= 1

        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(f"{base}/predict", data=b"not-784",
                                   timeout=10)
        assert ei.value.code == 400

        ok = json.loads(urllib.request.urlopen(
            f"{base}/healthz", timeout=10).read())
        assert ok == {"ok": True}
    finally:
        proc.send_signal(signal.SIGTERM)
        try:
            out, _ = proc.communicate(timeout=60)
        except subprocess.TimeoutExpired:
            proc.kill()
            raise
    assert proc.returncode == 0
    records = [json.loads(l) for l in out.splitlines() if l.strip()]
    summary = [r for r in records if r.get("metric") == "serve_summary"]
    assert summary and summary[-1]["requests"] >= 1
