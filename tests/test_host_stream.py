"""Streaming host pipeline tests (data/host_loader.py): batch-order parity
with the device-resident pipeline, sharding of the streamed blocks, and
trajectory equivalence through fit()."""

import numpy as np
import pytest

from distributedmnist_tpu import trainer
from distributedmnist_tpu.config import Config
from distributedmnist_tpu.data.host_loader import HostStream
from distributedmnist_tpu.data.loader import IndexStream
from distributedmnist_tpu.parallel import make_mesh


BASE = Config(device="cpu", synthetic=True, log_every=0,
              target_accuracy=None, model="mlp", optimizer="sgd",
              learning_rate=0.02, batch_size=256, num_devices=8,
              steps=16, eval_every=16)


def test_stream_block_shapes_and_sharding(tiny_data, eight_devices):
    mesh = make_mesh(eight_devices)
    hs = HostStream(tiny_data["train_x"], tiny_data["train_y"],
                    global_batch=256, seed=0, mesh=mesh)
    x, y = hs.next_block(3)
    assert x.shape == (3, 256, 28, 28, 1) and y.shape == (3, 256)
    assert hs.step == 3
    # batch axis sharded over 'data': each device holds 256/8 columns
    assert {s.data.shape[1] for s in x.addressable_shards} == {32}


def test_stream_order_matches_index_stream(tiny_data, eight_devices):
    mesh = make_mesh(eight_devices)
    hs = HostStream(tiny_data["train_x"], tiny_data["train_y"],
                    global_batch=128, seed=7, mesh=mesh)
    ref = IndexStream(tiny_data["train_x"].shape[0], 128, seed=7, mesh=mesh)
    x, y = hs.next_block(2)
    idx = np.asarray(ref.next_block(2))
    np.testing.assert_array_equal(np.asarray(y), tiny_data["train_y"][idx])
    np.testing.assert_array_equal(np.asarray(x), tiny_data["train_x"][idx])


def test_fit_stream_equals_device_pipeline(tiny_data):
    a = trainer.fit(BASE, data=tiny_data)
    b = trainer.fit(BASE.replace(data_pipeline="stream"), data=tiny_data)
    assert b["data_pipeline"] == "stream"
    np.testing.assert_allclose(a["test_accuracy"], b["test_accuracy"],
                               atol=1e-6)


def test_stream_with_supersteps(tiny_data):
    out = trainer.fit(BASE.replace(data_pipeline="stream",
                                   steps_per_call=4), data=tiny_data)
    assert out["steps"] == 16


def test_stream_rejects_explicit_mode(tiny_data):
    with pytest.raises(ValueError, match="spmd_mode=auto"):
        trainer.fit(BASE.replace(data_pipeline="stream",
                                 spmd_mode="explicit"), data=tiny_data)


def test_tfdata_source_matches_numpy(tiny_data, eight_devices):
    """The tf.data-backed gather (the north_star's literal per-host
    tf.data pipeline) must yield byte-identical blocks in the same
    order as the numpy backend."""
    pytest.importorskip("tensorflow")
    mesh = make_mesh(eight_devices)
    kw = dict(global_batch=128, seed=7, mesh=mesh)
    a = HostStream(tiny_data["train_x"], tiny_data["train_y"], **kw)
    b = HostStream(tiny_data["train_x"], tiny_data["train_y"],
                   source="tfdata", **kw)
    for k in (2, 2, 3):    # includes a block-size change mid-stream
        xa, ya = a.next_block(k)
        xb, yb = b.next_block(k)
        np.testing.assert_array_equal(np.asarray(xa), np.asarray(xb))
        np.testing.assert_array_equal(np.asarray(ya), np.asarray(yb))
    assert a.step == b.step == 7


def test_fit_tfdata_stream(tiny_data):
    pytest.importorskip("tensorflow")
    a = trainer.fit(BASE.replace(data_pipeline="stream"), data=tiny_data)
    b = trainer.fit(BASE.replace(data_pipeline="stream",
                                 stream_source="tfdata"), data=tiny_data)
    np.testing.assert_allclose(a["test_accuracy"], b["test_accuracy"],
                               atol=1e-6)


def test_unknown_stream_source_rejected(tiny_data, eight_devices):
    with pytest.raises(ValueError, match="host-stream source"):
        HostStream(tiny_data["train_x"], tiny_data["train_y"],
                   global_batch=128, seed=0,
                   mesh=make_mesh(eight_devices), source="parquet")
