"""serve/trace.py (ISSUE 9): the tracer contracts — span trees are
well-formed (every span closed, parents precede children), error and
over-SLO exemplars survive head sampling, the retention ring stays
bounded under sustained load, the uninstalled path is inert, exported
JSON is valid Chrome trace-event format, a failover-rescue trace names
both replicas, and bisect splits appear as structured child spans.

Every test runs under the conftest serve sanitizer fixture (the
filename selects it), so the tracer's own lock is covered by the
ISSUE 8 lock-order / blocking / balance checks too."""

import json
import threading
import time

import numpy as np
import pytest

from distributedmnist_tpu.serve import (DynamicBatcher, ResiliencePolicy,
                                        ServeMetrics, faults)
from distributedmnist_tpu.serve import trace as trace_lib
from distributedmnist_tpu.serve.fleet import ReplicaSet
from tests.test_serve_batcher import StubEngine, _rows
from tests.test_serve_fleet import StubRouter
from tests.test_serve_resilience import PoisonStubEngine, _poison_rows

pytestmark = pytest.mark.trace


@pytest.fixture(autouse=True)
def _no_leaked_tracer():
    """Every test starts and ends tracer-less — a tracer leaked across
    tests would silently record every later suite's serving traffic."""
    trace_lib.uninstall()
    faults.uninstall()
    yield
    trace_lib.uninstall()
    faults.uninstall()


def _run_batcher(tracer, n_requests=8, rows=3, engine=None, **kw):
    """Drive n_requests through a batcher over a stub engine with
    `tracer` installed; returns the resolved futures."""
    trace_lib.install(tracer)
    eng = engine if engine is not None else StubEngine(max_batch=16)
    b = DynamicBatcher(eng, metrics=ServeMetrics(), max_wait_us=2000,
                       **kw).start()
    rng = np.random.default_rng(0)
    try:
        futs = [b.submit(_rows(rng, rows)) for _ in range(n_requests)]
        for f in futs:
            assert f.result(timeout=30).shape == (rows, 10)
    finally:
        b.stop()
        trace_lib.uninstall()
    return futs


# -- inertness (the production default) -----------------------------------


def test_uninstalled_path_is_inert(rng):
    """No tracer: every hook is a no-op, begin/end/add/current cost one
    None check, futures carry no trace id, and serving behaves exactly
    as at HEAD."""
    assert trace_lib.active() is None
    assert trace_lib.begin_span("engine.staging", rows=1) is None
    trace_lib.end_span(None)                    # must not raise
    trace_lib.add_span("queue.wait", 0.0, 1.0, rids=(1,))
    assert trace_lib.current() is None
    eng = StubEngine(max_batch=16)
    b = DynamicBatcher(eng, max_wait_us=1000).start()
    try:
        f = b.submit(_rows(rng, 4))
        assert f.result(timeout=10).shape == (4, 10)
        assert not hasattr(f, "trace_id")
    finally:
        b.stop()


def test_install_refuses_stacking():
    t1 = trace_lib.install(trace_lib.Tracer())
    with pytest.raises(RuntimeError, match="already installed"):
        trace_lib.install(trace_lib.Tracer())
    assert trace_lib.active() is t1
    trace_lib.uninstall()
    assert trace_lib.active() is None


def test_end_span_survives_uninstall():
    """A span begun under one tracer ends cleanly after uninstall (it
    remembers its tracer) — a bench leg tearing its tracer down must
    not crash in-flight stages."""
    tr = trace_lib.install(trace_lib.Tracer())
    sp = trace_lib.begin_span("engine.staging", rids=(1,), rows=1)
    trace_lib.uninstall()
    trace_lib.end_span(sp)
    assert tr.snapshot()["open_spans"] == 0


def test_tracer_rejects_degenerate_configs():
    with pytest.raises(ValueError, match="capacity"):
        trace_lib.Tracer(capacity=0)
    with pytest.raises(ValueError, match="sample"):
        trace_lib.Tracer(sample=1.5)
    with pytest.raises(ValueError, match="slo_ms"):
        trace_lib.Tracer(slo_ms=0)


# -- span-tree shape -------------------------------------------------------


def test_span_tree_well_formed():
    """Every retained trace: a single root, every span closed with a
    nonnegative duration, parent links resolve inside the trace, and
    no child starts before its parent."""
    tracer = trace_lib.Tracer(capacity=64, sample=1.0)
    futs = _run_batcher(tracer, n_requests=8)
    traces = tracer.traces()
    assert len(traces) == 8
    for t in traces:
        names = [s["name"] for s in t["spans"]]
        assert names.count("request") == 1
        # the full single-engine pipeline appears
        for expected in ("queue.wait", "batch.coalesce",
                         "batch.dispatch", "engine.enqueued",
                         "engine.fetch", "batch.fanout"):
            assert expected in names, (expected, names)
        by_id = {s["id"]: s for s in t["spans"]}
        root = next(s for s in t["spans"] if s["name"] == "request")
        for s in t["spans"]:
            assert s["dur"] is not None and s["dur"] >= 0
            assert s["status"] in ("ok", "error")
            if s["parent"] is not None:
                assert s["parent"] in by_id, (s["name"], s["parent"])
                assert by_id[s["parent"]]["t0"] <= s["t0"] + 1e-6
            # request-private spans never precede their root (batch-
            # level spans MAY: a coalesce window opens before a late-
            # joining member's enqueue — that is real, not a bug)
            if s["rids"] == [t["rid"]]:
                assert s["t0"] >= root["t0"] - 1e-6, s["name"]
    snap = tracer.snapshot()
    assert snap["open_spans"] == 0
    assert snap["requests_started"] == snap["requests_finished"] == 8
    # futures carry the trace id serve.py stamps as X-Trace-Id
    ids = {f.trace_id for f in futs}
    assert len(ids) == 8
    assert ids == {t["trace_id"] for t in traces}


def test_engine_staging_span_nests_under_dispatch(eight_devices):
    """Against a REAL engine the engine.staging span appears as a child
    of the batcher's batch.dispatch span (rids inherited through the
    thread-local stack — the engine needs no rid plumbing)."""
    import jax
    import jax.numpy as jnp

    from distributedmnist_tpu import models, optim
    from distributedmnist_tpu.parallel import make_mesh
    from distributedmnist_tpu.serve.engine import InferenceEngine
    from distributedmnist_tpu.trainer import init_state

    mesh = make_mesh(eight_devices[:1])
    model = models.build("mlp", platform="cpu")
    params = init_state(jax.random.PRNGKey(0), model,
                        optim.build("sgd", 0.1),
                        jnp.zeros((1, 28, 28, 1))).params
    eng = InferenceEngine(model, params, mesh, max_batch=8)
    tracer = trace_lib.Tracer(capacity=16, sample=1.0)
    trace_lib.install(tracer)
    b = DynamicBatcher(eng, max_wait_us=1000).start()
    rng = np.random.default_rng(0)
    try:
        assert b.submit(_rows(rng, 3)).result(timeout=60).shape == (3, 10)
    finally:
        b.stop()
        trace_lib.uninstall()
    t = tracer.traces()[-1]
    by_id = {s["id"]: s for s in t["spans"]}
    staging = [s for s in t["spans"] if s["name"] == "engine.staging"]
    assert staging, [s["name"] for s in t["spans"]]
    parent = by_id[staging[0]["parent"]]
    assert parent["name"] == "batch.dispatch"
    assert staging[0]["tags"]["bucket"] >= 3


# -- retention: sampling, exemplars, bounds --------------------------------


def test_ring_bounded_under_sustained_load():
    tracer = trace_lib.Tracer(capacity=4, sample=1.0)
    _run_batcher(tracer, n_requests=30)
    snap = tracer.snapshot()
    assert snap["ring_traces"] <= 4
    assert snap["kept_sampled"] == 30       # all kept, ring evicted
    assert snap["requests_finished"] == 30
    assert len(tracer.traces()) <= 4 + snap["exemplar_traces"]


def test_error_exemplars_survive_zero_sampling(rng):
    """sample=0 drops every OK trace — but an errored request is an
    exemplar and must be retained (the slow/broken requests are the
    ones tail attribution exists for)."""
    tracer = trace_lib.Tracer(capacity=32, sample=0.0)
    trace_lib.install(tracer)
    eng = PoisonStubEngine(max_batch=16)
    b = DynamicBatcher(eng, max_wait_us=1000).start()
    try:
        # poison first and alone (no bisection wired: a cohort
        # containing it would fail WHOLE and drag the OK traces down)
        bad = b.submit(_poison_rows(2))
        with pytest.raises(RuntimeError, match="poison"):
            bad.result(timeout=10)
        ok = [b.submit(_rows(rng, 2)) for _ in range(5)]
        for f in ok:
            assert f.result(timeout=10).shape == (2, 10)
    finally:
        b.stop()
        trace_lib.uninstall()
    snap = tracer.snapshot()
    assert snap["ring_traces"] == 0          # every OK trace sampled out
    assert snap["sampled_out"] == 5
    traces = tracer.traces()
    assert [t["status"] for t in traces] == ["error"]
    assert traces[0]["trace_id"] == bad.trace_id


def test_over_slo_exemplars_survive_zero_sampling():
    """An impossible SLO makes every request over-SLO: all retained as
    exemplars even at sample=0."""
    tracer = trace_lib.Tracer(capacity=32, sample=0.0, slo_ms=1e-6)
    _run_batcher(tracer, n_requests=6)
    snap = tracer.snapshot()
    assert snap["kept_exemplars"] == 6 and snap["sampled_out"] == 0
    assert all(t["over_slo"] for t in tracer.traces())


def test_deadline_shed_trace_is_an_error_exemplar(rng):
    """A queued request shed at pop (ISSUE 5) finishes as an error
    exemplar whose tree carries the shed queue.wait and the
    deadline.shed marker — a 504 is traceable, not just counted."""
    from distributedmnist_tpu.serve.resilience import DeadlineExceeded

    tracer = trace_lib.Tracer(capacity=16, sample=0.0)
    trace_lib.install(tracer)
    eng = StubEngine(max_batch=16)
    gate = threading.Event()
    eng.gate = gate
    b = DynamicBatcher(eng, max_wait_us=1000, max_inflight=1).start()
    try:
        first = b.submit(_rows(rng, 1))
        assert eng.in_call.wait(timeout=10)
        doomed = b.submit(_rows(rng, 2),
                          deadline_s=time.monotonic() + 0.02)
        time.sleep(0.05)
        gate.set()
        first.result(timeout=10)
        with pytest.raises(DeadlineExceeded):
            doomed.result(timeout=10)
    finally:
        b.stop()
        trace_lib.uninstall()
    shed = [t for t in tracer.traces()
            if t["trace_id"] == doomed.trace_id]
    assert len(shed) == 1 and shed[0]["status"] == "error"
    names = [s["name"] for s in shed[0]["spans"]]
    assert "deadline.shed" in names
    qw = next(s for s in shed[0]["spans"] if s["name"] == "queue.wait")
    assert qw["tags"].get("shed") is True


def test_rejected_submit_leaves_no_live_trace(rng):
    """A watermark rejection aborts the just-started trace — the live
    table must not grow with requests that never entered the queue."""
    from distributedmnist_tpu.serve import Rejected

    tracer = trace_lib.Tracer(capacity=16, sample=1.0)
    trace_lib.install(tracer)
    eng = StubEngine(max_batch=16)
    gate = threading.Event()
    eng.gate = gate
    b = DynamicBatcher(eng, max_wait_us=1000, queue_depth=4,
                       max_inflight=1).start()
    try:
        first = b.submit(_rows(rng, 1))
        assert eng.in_call.wait(timeout=10)
        held = b.submit(_rows(rng, 4))        # fills the watermark
        with pytest.raises(Rejected):
            b.submit(_rows(rng, 4))
        gate.set()
        first.result(timeout=10)
        held.result(timeout=10)
    finally:
        b.stop()
        trace_lib.uninstall()
    snap = tracer.snapshot()
    assert snap["aborted"] == 1
    assert snap["live"] == 0


# -- attribution + Server-Timing -------------------------------------------


class SlowFetchEngine(StubEngine):
    """StubEngine whose fetch takes a deliberate ~20 ms: request wall
    clock is then DOMINATED by a known, span-covered stage, so the
    attribution-fraction assertion measures span coverage, not
    scheduler noise on a loaded CI host (microsecond-total stub
    requests have microsecond residues that swing as fractions)."""

    def fetch(self, handle):
        time.sleep(0.02)
        return super().fetch(handle)


def test_attribution_covers_wall_clock():
    """Stage attribution explains nearly all of each request's wall
    clock (queue + staging + device + fetch + fanout); the residue is
    reported, never folded in; stage sums plus residue equal the
    total."""
    tracer = trace_lib.Tracer(capacity=32, sample=1.0)
    _run_batcher(tracer, n_requests=8, engine=SlowFetchEngine(
        max_batch=16))
    fracs = []
    for t in tracer.traces():
        att = trace_lib.attribute_stages(t)
        assert att["total_ms"] == pytest.approx(t["duration_ms"],
                                                rel=1e-6)
        acc = sum(att["stages_ms"].values()) + att["residue_ms"]
        assert acc == pytest.approx(att["total_ms"], rel=1e-6)
        assert "queue" in att["stages_ms"]
        assert att["stages_ms"].get("fetch", 0.0) >= 15.0
        fracs.append(att["attributed_frac"])
    # Load-tolerant coverage bar (the zipf-contract precedent, ISSUE 14
    # satellite): under full-suite load a descheduling blip can land in
    # one request's inter-span gap and inflate ITS residue, which is a
    # property of the contended host, not of the span weaving — the
    # invariant is that coverage is the NORM, so the median must clear
    # the bar and no trace may be mostly unexplained.
    fracs.sort()
    assert fracs[len(fracs) // 2] >= 0.9, fracs
    assert fracs[0] >= 0.5, fracs


def test_server_timing_available_when_result_is():
    """The batcher finishes a trace BEFORE resolving its future, so
    the breakdown is readable the moment result() returns — the
    serve.py Server-Timing contract."""
    tracer = trace_lib.Tracer(capacity=32, sample=1.0)
    futs = _run_batcher(tracer, n_requests=3)
    for f in futs:
        st = tracer.server_timing(f.trace_id)
        assert st is not None and "dur=" in st and "residue" in st
        bd = tracer.breakdown(f.trace_id)
        assert bd["status"] == "ok" and bd["total_ms"] > 0


# -- Chrome trace-event export ---------------------------------------------


def test_chrome_export_is_valid_trace_event_json():
    tracer = trace_lib.Tracer(capacity=32, sample=1.0)
    _run_batcher(tracer, n_requests=5)
    doc = json.loads(json.dumps(tracer.export_chrome()))
    events = doc["traceEvents"]
    assert isinstance(events, list) and events
    assert doc["displayTimeUnit"] == "ms"
    for ev in events:
        assert ev["ph"] in ("X", "M"), ev
        if ev["ph"] == "M":
            assert ev["name"] in ("process_name", "thread_name")
            assert "name" in ev["args"]
            continue
        for key in ("name", "cat", "ts", "dur", "pid", "tid", "args"):
            assert key in ev, (key, ev)
        assert isinstance(ev["ts"], (int, float)) and ev["ts"] >= 0
        assert isinstance(ev["dur"], (int, float)) and ev["dur"] >= 0
        assert isinstance(ev["tid"], int)
        assert ev["args"]["status"] in ("ok", "error")
    # batch-level spans shared by cohort traces are deduped: exactly
    # one X event per distinct span id across all retained traces
    xs = [ev for ev in events if ev["ph"] == "X"]
    distinct = {s["id"] for t in tracer.traces() for s in t["spans"]}
    assert len(xs) == len(distinct)
    # thread metadata names the synthesized in-flight-window track
    threads = {ev["args"]["name"] for ev in events
               if ev["ph"] == "M" and ev["name"] == "thread_name"}
    assert "inflight-window" in threads


# -- resilience + fleet structure ------------------------------------------


def test_bisect_splits_are_structured_child_spans(rng):
    """A poisoned cohort's bisection (ISSUE 5) shows up in the traces:
    bisect.split markers plus bisect.dispatch spans — the culprit's
    trace carries an errored one, a rescued mate's a clean one."""
    tracer = trace_lib.Tracer(capacity=64, sample=1.0)
    trace_lib.install(tracer)
    eng = PoisonStubEngine(max_batch=16)
    gate = threading.Event()
    eng.gate = gate
    b = DynamicBatcher(eng, max_wait_us=50_000, max_inflight=4,
                       resilience=ResiliencePolicy(bisect=True)).start()
    try:
        first = b.submit(_rows(rng, 1))       # holds the pipeline while
        assert eng.in_call.wait(timeout=10)   # a cohort forms
        mates = [b.submit(_rows(rng, 2)) for _ in range(2)]
        bad = b.submit(_poison_rows(2))
        gate.set()
        first.result(timeout=10)
        with pytest.raises(RuntimeError, match="poison"):
            bad.result(timeout=10)
        for f in mates:
            assert f.result(timeout=10).shape == (2, 10)
    finally:
        b.stop()
        trace_lib.uninstall()
    by_id = {t["trace_id"]: t for t in tracer.traces()}
    culprit = by_id[bad.trace_id]
    names = [s["name"] for s in culprit["spans"]]
    assert "bisect.split" in names
    bd = [s for s in culprit["spans"] if s["name"] == "bisect.dispatch"]
    assert any(s["status"] == "error" for s in bd), bd
    mate = by_id[mates[0].trace_id]
    mate_bd = [s for s in mate["spans"]
               if s["name"] == "bisect.dispatch"]
    assert mate_bd and all(s["status"] == "ok" for s in mate_bd)
    # the rescued mate still resolved OK end to end
    assert mate["status"] == "ok"


@pytest.mark.fleet
def test_failover_rescue_trace_names_both_replicas(rng):
    """ISSUE 9 acceptance: a fetch-side replica death rescued on a
    sibling produces a fleet.failover.fetch span naming BOTH replicas,
    nested under the batch's engine.fetch span — and the request still
    resolves OK (redundancy absorbed the fault)."""
    tracer = trace_lib.Tracer(capacity=16, sample=1.0)
    trace_lib.install(tracer)
    routers = [StubRouter("r0"), StubRouter("r1")]
    routers[0].fail_fetch = True
    fleet = ReplicaSet(routers, per_replica_inflight=2)
    b = DynamicBatcher(fleet, max_wait_us=1000, max_inflight=2).start()
    try:
        out = b.submit(_rows(rng, 4)).result(timeout=30)
        assert out.shape == (4, 10)
    finally:
        b.stop()
        trace_lib.uninstall()
    t = tracer.traces()[-1]
    by_id = {s["id"]: s for s in t["spans"]}
    rescue = [s for s in t["spans"]
              if s["name"] == "fleet.failover.fetch"]
    assert len(rescue) == 1, [s["name"] for s in t["spans"]]
    tags = rescue[0]["tags"]
    assert tags["from_replica"] == "r0"
    assert tags["to_replica"] == "r1"
    assert rescue[0]["status"] == "ok"       # the rescue landed
    parent = by_id[rescue[0]["parent"]]
    assert parent["name"] == "engine.fetch"
    assert t["status"] == "ok"


# -- serve.py HTTP surface (e2e) -------------------------------------------


def test_serve_http_trace_surface_end_to_end():
    """serve.py --serve-trace: /predict responses carry X-Trace-Id,
    X-Server-Timing: 1 opts into a Server-Timing stage breakdown,
    GET /trace exports loadable Chrome trace-event JSON, and
    GET /metrics?format=prometheus returns the # TYPE'd text
    exposition including the span-derived stage histograms."""
    import os
    import subprocess
    import sys
    import urllib.error
    import urllib.request

    from conftest import worker_env

    env, repo = worker_env()
    proc = subprocess.Popen(
        [sys.executable, os.path.join(repo, "serve.py"), "--model",
         "mlp", "--device", "cpu", "--serve-max-batch", "16",
         "--serve-trace", "--serve-slo-ms", "5000", "--port", "0",
         "--metrics-every", "5"],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        env=env, cwd=repo)
    try:
        port = None
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            line = proc.stdout.readline()
            assert line, "serve.py exited before announcing readiness"
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if rec.get("metric") == "serve_ready":
                port = rec["port"]
                break
        assert port is not None
        base = f"http://127.0.0.1:{port}"
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            try:
                urllib.request.urlopen(f"{base}/healthz", timeout=30)
                break
            except urllib.error.HTTPError as e:
                assert e.code == 503
                time.sleep(0.2)

        body = np.zeros(784 * 2, np.uint8).tobytes()
        req = urllib.request.Request(f"{base}/predict", data=body,
                                     headers={"X-Server-Timing": "1"})
        resp = urllib.request.urlopen(req, timeout=30)
        out = json.loads(resp.read())
        assert out["n"] == 2
        trace_id = resp.headers.get("X-Trace-Id")
        assert trace_id
        st = resp.headers.get("Server-Timing")
        assert st and "dur=" in st and "residue" in st

        doc = json.loads(urllib.request.urlopen(
            f"{base}/trace", timeout=30).read())
        assert any(ev.get("ph") == "X"
                   and trace_id in ev["args"].get("trace_ids", [])
                   for ev in doc["traceEvents"])

        prom = urllib.request.urlopen(
            f"{base}/metrics?format=prometheus", timeout=30)
        assert prom.headers.get_content_type() == "text/plain"
        text = prom.read().decode()
        assert "# TYPE dmnist_serve_requests_total counter" in text
        assert ("# TYPE dmnist_serve_stage_duration_ms histogram"
                in text)
        assert 'stage="queue.wait"' in text

        m = json.loads(urllib.request.urlopen(
            f"{base}/metrics", timeout=30).read())
        assert m["trace"]["requests_finished"] >= 1
        assert "queue.wait" in m["trace"]["stages"]
    finally:
        proc.terminate()
        try:
            proc.wait(timeout=30)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait(timeout=10)


def test_serve_http_trace_disabled_is_409():
    """Without --serve-trace the /trace endpoint refuses loudly (409 +
    the flag to use), and /predict responses carry no X-Trace-Id —
    asserted through the serve.py handler directly via the CLI
    selftest path being tracer-less (cheap: no server boot)."""
    from distributedmnist_tpu.serve import trace as t

    assert t.active() is None   # module state: default-off everywhere