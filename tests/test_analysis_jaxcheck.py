"""analysis/jaxcheck.py: the static compile-surface auditor (ISSUE 12).

Covers the four hazard classes with planted instances asserting the
exact rule ID (a reachable-but-unwarmed bucket -> JX001, a dead warmup
rung -> JX002, a host-array leak into a jitted forward -> JX003, a
weak-type scalar at the jitted boundary -> JX004), jaxpr-fingerprint
stability (same config twice -> identical; bucket-rung, dtype and a
planted forward edit each distinct, with the changed component named),
the snapshot gate (JX005), the CLI exit contract, and the
repo-at-HEAD gate itself: the committed audit surface must be CLOSED.
"""

import os
import subprocess
import sys

import numpy as np
import pytest

from distributedmnist_tpu.analysis import jaxcheck as jc
from tests.conftest import worker_env

pytestmark = [pytest.mark.analysis, pytest.mark.jaxcheck]


def small_target(**kw):
    kw.setdefault("model", "mlp")
    kw.setdefault("serve_max_batch", 8)
    return jc.AuditTarget(**kw)


def _rules(findings):
    return sorted({f.rule for f in findings})


# -- the closed surface at HEAD --------------------------------------------


def test_small_audit_is_closed():
    """A well-formed deployment shape audits CLOSED: every reachable
    key warmed, every warmed key reachable, no transfer or weak-type
    findings, one fingerprint per (dtype, bucket)."""
    r = jc.audit_target(small_target())
    assert r["findings"] == []
    assert r["static_keys"] == r["warmed_keys"] > 0
    dtypes = set(r["infer_dtypes"])
    # the auto universe: every PARITY_GATES dtype this model supports
    # (the megakernel variant exists for the MLP — ISSUE 14)
    assert dtypes == {"float32", "bfloat16", "int8", "megakernel"}
    assert len(r["fingerprints"]) == len(dtypes) * len(r["buckets"])
    assert all(len(fp) == 16 for fp in r["fingerprints"].values())


def test_megakernel_filtered_by_model_support():
    """The megakernel variant exists for the MLP only: the LeNet
    universe must not contain it (an engine that can never be built
    has no compile keys), and the MLP universe audits it CLOSED."""
    r = jc.audit_target(jc.AuditTarget(model="lenet", serve_max_batch=8))
    assert "megakernel" not in r["infer_dtypes"]
    assert r["findings"] == []


def test_fast_row_key_in_universe_when_smallest_rung_gt_one():
    """A geometry whose smallest rung is > 1 serves single-row
    requests through the row-staged fast program (ISSUE 14): its key
    joins the reachable universe, the real warmup warms it (closure),
    and it carries its own fingerprint."""
    r = jc.audit_target(small_target(n_chips=4))
    assert r["findings"] == []
    assert r["static_keys"] == r["warmed_keys"]
    row_keys = [k for k in r["fingerprints"] if k.endswith("-row")]
    assert row_keys and all("/b4-row" in k for k in row_keys)
    # 1-chip geometry (smallest rung 1): exact-fit covers single rows,
    # so there is no row program and no row key
    r1 = jc.audit_target(small_target())
    assert not any(k.endswith("-row") for k in r1["fingerprints"])


def test_explicit_dtype_narrows_the_universe():
    r = jc.audit_target(small_target(serve_infer_dtype="int8"))
    assert set(r["infer_dtypes"]) == {"float32", "int8"}
    r = jc.audit_target(small_target(serve_infer_dtype="float32"))
    assert set(r["infer_dtypes"]) == {"float32"}


# -- fingerprint stability (ISSUE 12 satellite) ----------------------------


def test_same_config_twice_identical_fingerprints():
    a = jc.audit_target(small_target())
    b = jc.audit_target(small_target())
    assert a["fingerprints"] == b["fingerprints"]
    assert jc.diff_fingerprints(a["fingerprints"],
                                b["fingerprints"]) == []
    assert (jc.fingerprint_set_hash(a["fingerprints"])
            == jc.fingerprint_set_hash(b["fingerprints"]))


def test_bucket_rung_change_distinct_and_named():
    a = jc.audit_target(small_target(buckets=(4, 8)))
    b = jc.audit_target(small_target(buckets=(4, 16),
                                     serve_max_batch=16))
    diff = jc.diff_fingerprints(a["fingerprints"], b["fingerprints"])
    assert diff and all(f.rule == "JX005" for f in diff)
    named = [f for f in diff if "in bucket" in f.message]
    assert named, [f.message for f in diff]
    # the shared rung's fingerprint is bucket-independent only per key:
    # b4 exists in both tables and must agree
    shared = [k for k in a["fingerprints"] if k in b["fingerprints"]]
    assert shared
    assert all(a["fingerprints"][k] == b["fingerprints"][k]
               for k in shared)


def test_dtype_change_distinct_and_named():
    r = jc.audit_target(small_target(buckets=(4,), serve_max_batch=4))
    fps = r["fingerprints"]
    k_f32 = jc.key_str("mlp", "float32", r["fused_mode"], 4)
    k_int8 = jc.key_str("mlp", "int8", r["fused_mode"], 4)
    assert fps[k_f32] != fps[k_int8]
    diff = jc.diff_fingerprints({k_f32: fps[k_f32]},
                                {k_int8: fps[k_int8]})
    assert any("in infer_dtype" in f.message for f in diff), \
        [f.message for f in diff]


def test_planted_forward_edit_changes_fingerprint():
    """An edited forward (same shapes, different graph) produces a
    distinct fingerprint, and the snapshot diff names the key as a
    changed GRAPH, not a changed key component."""
    model = jc._build_model("mlp", "float32", "auto")
    shapes = jc.abstract_params(model)
    fn, avals = jc.abstract_forward(model, "float32", "xla", shapes)

    def edited(p, x_u8):
        return fn(p, x_u8) * 2.0          # the planted graph edit

    key = jc.key_str("mlp", "float32", "xla", 8)
    fp0, f0 = jc.audit_forward(fn, avals, 8, key)
    fp1, f1 = jc.audit_forward(edited, avals, 8, key)
    assert f0 == [] and f1 == []
    assert fp0 != fp1
    diff = jc.diff_fingerprints({key: fp1}, {key: fp0})
    assert len(diff) == 1 and diff[0].rule == "JX005"
    assert "compiled graph changed" in diff[0].message


# -- planted hazards, each named by its rule ID ----------------------------


def test_planted_unwarmed_reachable_bucket_jx001(monkeypatch):
    """A warmup regression that skips the top rung is a
    reachable-but-unwarmed key: JX001, naming the cold bucket."""
    real = jc.warmed_buckets
    monkeypatch.setattr(
        jc, "warmed_buckets",
        lambda buckets, dt: real(buckets, dt) - {max(buckets)})
    r = jc.audit_target(small_target())
    assert _rules(r["findings"]) == ["JX001"]
    top = max(r["buckets"])
    assert all(f.key.endswith(f"/b{top}") for f in r["findings"])
    assert "steady-state" in r["findings"][0].message


def test_planted_unreachable_warmed_bucket_jx002():
    """An explicit ladder with a rung past any admissible request size
    is dead warmup cost: JX002, naming the dead bucket."""
    r = jc.audit_target(small_target(buckets=(4, 8, 64)))
    assert _rules(r["findings"]) == ["JX002"]
    assert all(f.key.endswith("/b64") for f in r["findings"])
    assert "dead warmup cost" in r["findings"][0].message


def test_planted_host_array_leak_jx003():
    """A forward closing over a host ndarray (instead of taking it as
    a staged argument) is caught as a jaxpr const: JX003."""
    model = jc._build_model("mlp", "float32", "auto")
    shapes = jc.abstract_params(model)
    fn, avals = jc.abstract_forward(model, "float32", "xla", shapes)
    leak = np.ones((1, 10), np.float32)

    def leaky(p, x_u8):
        return fn(p, x_u8) + leak         # the planted host-array leak

    key = jc.key_str("mlp", "float32", "xla", 4)
    _, findings = jc.audit_forward(leaky, avals, 4, key)
    assert _rules(findings) == ["JX003"]
    assert "host" in findings[0].message
    assert "(1, 10)" in findings[0].message


def test_planted_weak_type_literal_jx004():
    """A Python scalar reaching the jitted boundary as a traced
    argument is a weak-typed aval: JX004."""
    import jax

    def scaled(p, x_u8):
        return x_u8.astype("float32") * p["scale"]

    key = jc.key_str("mlp", "float32", "xla", 4)
    _, findings = jc.audit_forward(
        scaled, {"scale": 0.5}, 4, key)   # the planted scalar literal
    assert "JX004" in _rules(findings)
    assert any("WEAK-TYPED" in f.message for f in findings)
    # the committed-array spelling of the same forward is clean
    aval = jax.ShapeDtypeStruct((), np.float32)
    _, clean = jc.audit_forward(scaled, {"scale": aval}, 4, key)
    assert clean == []


# -- the snapshot gate -----------------------------------------------------


def test_snapshot_roundtrip_and_drift(tmp_path):
    import jax

    r = jc.audit_target(small_target(buckets=(4,), serve_max_batch=4))
    path = str(tmp_path / "snap.json")
    jc.write_snapshot({"t": r["fingerprints"]}, "unit test", path=path)
    snap = jc.load_snapshot(path)
    assert snap["reason"] == "unit test"
    assert snap["jax_version"] == jax.__version__
    assert jc.diff_fingerprints(r["fingerprints"],
                                snap["fingerprints"]["t"]) == []
    # planted drift: one fingerprint flipped -> JX005 on exactly it
    drifted = dict(r["fingerprints"])
    k = sorted(drifted)[0]
    drifted[k] = "0" * 16
    diff = jc.diff_fingerprints(drifted, snap["fingerprints"]["t"])
    assert len(diff) == 1
    assert diff[0].rule == "JX005" and diff[0].key == k


def test_missing_snapshot_is_a_warning_not_a_finding(tmp_path):
    assert jc.load_snapshot(str(tmp_path / "absent.json")) is None


def test_partial_audit_skips_unaudited_snapshot_labels(tmp_path):
    """A narrowed audit (--models subset / --no-train) gates the
    labels it covers but must NOT read the snapshot's other labels as
    removed keys — only the full default audit may declare a snapshot
    label dead."""
    target = small_target(buckets=(4,), serve_max_batch=4)
    r = jc.audit_target(target)
    path = str(tmp_path / "snap.json")
    jc.write_snapshot({target.label(): r["fingerprints"],
                       "ghost-target": {"ghost/f32/xla/b4": "f" * 16}},
                      "seed", path=path)
    full = jc.run_audit([target], with_train=False,
                        snapshot_file=path, partial=False)
    assert any(f.rule == "JX005" and "ghost" in f.key
               for f in full["findings"])
    part = jc.run_audit([target], with_train=False,
                        snapshot_file=path, partial=True)
    assert part["findings"] == []


def test_update_snapshots_partial_merges(tmp_path, monkeypatch):
    """--update-snapshots from a narrowed audit merges into the
    committed snapshot instead of silently dropping every label the
    audit never produced (which would break the next full gate run)."""
    path = str(tmp_path / "snap.json")
    jc.write_snapshot({"lenet-keep": {"k": "a" * 16}}, "seed",
                      path=path)
    monkeypatch.setattr(jc, "snapshot_path", lambda: path)
    rc = jc.main(["--models", "mlp", "--no-train",
                  "--update-snapshots", "--reason", "partial test"])
    assert rc == 0
    snap = jc.load_snapshot(path)
    assert "lenet-keep" in snap["fingerprints"]       # preserved
    assert any(lbl.startswith("mlp-") for lbl in snap["fingerprints"])
    assert snap["reason"] == "partial test"


def test_update_snapshots_partial_refuses_cross_version(tmp_path,
                                                        monkeypatch):
    """A partial merge over a snapshot written under a DIFFERENT jax
    version would stamp the new version while the unaudited labels
    still carry the old version's jaxpr printing — re-arming the JX005
    gate against exactly the drift the version check excuses. Refused
    (exit 2), snapshot untouched; a full --update-snapshots is the
    documented path."""
    import json

    path = str(tmp_path / "snap.json")
    jc.write_snapshot({"lenet-keep": {"k": "a" * 16}}, "seed",
                      path=path)
    snap = json.load(open(path))
    snap["jax_version"] = "0.0.0"
    json.dump(snap, open(path, "w"))
    monkeypatch.setattr(jc, "snapshot_path", lambda: path)
    rc = jc.main(["--models", "mlp", "--no-train",
                  "--update-snapshots", "--reason", "x"])
    assert rc == 2
    assert jc.load_snapshot(path)["jax_version"] == "0.0.0"  # untouched


# -- compile-surface provenance (the bench block) --------------------------


def test_compile_surface_summary_stable_and_geometry_sensitive():
    # smallest rung 4 > 1: the fast lane's row-staged program is one
    # more key per dtype (ISSUE 14)
    a = jc.compile_surface_summary("mlp", (4, 8), 8, "float32")
    b = jc.compile_surface_summary("mlp", (4, 8), 8, "float32")
    assert a["static_keys"] == 3 and a["findings"] == 0
    assert a["fingerprint_set_hash"] == b["fingerprint_set_hash"]
    c = jc.compile_surface_summary("mlp", (4, 8, 16), 16, "float32")
    assert c["static_keys"] == 4
    assert c["fingerprint_set_hash"] != a["fingerprint_set_hash"]
    d = jc.compile_surface_summary("mlp", (4, 8), 8, "int8")
    assert d["static_keys"] == 6      # (f32 base + int8) x (2 rungs + row)
    assert d["fingerprint_set_hash"] != a["fingerprint_set_hash"]


# -- CLI exit contract + the repo-at-HEAD gate -----------------------------


def _run_cli(extra, timeout=300):
    env, repo = worker_env()
    return subprocess.run(
        [sys.executable, "-m", "distributedmnist_tpu.analysis.jaxcheck"]
        + extra,
        capture_output=True, text=True, env=env, cwd=repo,
        timeout=timeout)


def test_cli_usage_errors_exit_2():
    out = _run_cli(["--models", "resnet"])
    assert out.returncode == 2
    assert "unknown model" in out.stderr
    out = _run_cli(["--update-snapshots"])    # no --reason
    assert out.returncode == 2
    assert "--reason" in out.stderr


def test_cli_list_rules():
    out = _run_cli(["--list-rules"])
    assert out.returncode == 0
    for rule in ("JX001", "JX002", "JX003", "JX004", "JX005"):
        assert rule in out.stdout


def test_repo_at_head_audits_closed():
    """The acceptance criterion scripts/tier1.sh enforces: at HEAD the
    full default audit reports a CLOSED compile surface — static key
    universe == warmed key set for every dtype variant of both models,
    zero transfer/weak-type findings, fingerprints matching the
    committed snapshot — and exits 0."""
    out = _run_cli([])
    assert out.returncode == 0, (out.stdout + "\n" + out.stderr)[-3000:]
    assert "CLOSED, 0 findings" in out.stderr
    assert "no fingerprint snapshot" not in out.stderr, \
        "the snapshot must be committed for the gate to be armed"
