"""Distributed correctness on the 8-virtual-device CPU mesh (SURVEY.md §4):
the DP invariant (psum-of-shard-grads ≡ single-device grads on the full
batch), auto ≡ explicit SPMD mode equivalence, and seed-for-seed
1-device ≡ 8-device training equivalence."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributedmnist_tpu import models, optim
from distributedmnist_tpu.data.loader import DeviceDataset, IndexStream
from distributedmnist_tpu.ops import cross_entropy
from distributedmnist_tpu.parallel import make_mesh, replicated
from distributedmnist_tpu.trainer import (
    TrainState, init_state, make_eval_fn, make_train_step)


def _setup(tiny_data, devices, model_name="mlp", opt="sgd", mode="auto",
           lr=0.1):
    mesh = make_mesh(devices)
    ds = DeviceDataset(tiny_data, mesh)
    model = models.build(model_name, fused="xla")
    tx = optim.build(opt, lr)
    state = jax.device_put(
        init_state(jax.random.PRNGKey(0), model, tx,
                   jnp.zeros((1, 28, 28, 1))),
        replicated(mesh))
    step_fn = make_train_step(model, tx, mesh, mode=mode)
    return mesh, ds, model, tx, state, step_fn


def _run(tiny_data, devices, steps, mode, model_name="mlp", opt="sgd",
         batch=256, seed=0, lr=0.1):
    mesh, ds, model, tx, state, step_fn = _setup(
        tiny_data, devices, model_name, opt, mode, lr)
    stream = IndexStream(ds.train_n, batch, seed=seed, mesh=mesh)
    losses = []
    for _ in range(steps):
        state, m = step_fn(state, ds.train_x, ds.train_y, next(stream))
        losses.append(float(m["loss"]))
    return state, losses


def test_dp_gradients_match_single_device(tiny_data, eight_devices):
    """THE data-parallel invariant: gradients from the sharded step equal
    single-device gradients on the identical global batch."""
    mesh8 = make_mesh(eight_devices)
    ds = DeviceDataset(tiny_data, mesh8)
    model = models.build("mlp", fused="xla")
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 28, 28, 1)))["params"]

    idx = np.arange(256, dtype=np.int32)
    x = tiny_data["train_x"][idx].astype(np.float32) / 255.0
    y = tiny_data["train_y"][idx]

    def loss_fn(p, x, y):
        return cross_entropy(model.apply({"params": p}, x), y)

    ref_grads = jax.grad(loss_fn)(params, x, y)  # single-device oracle

    # sharded path: same batch via the device-resident gather
    from jax.sharding import NamedSharding, PartitionSpec as P
    params8 = jax.device_put(params, replicated(mesh8))
    idx8 = jax.device_put(idx, NamedSharding(mesh8, P("data")))

    @jax.jit
    def sharded_grads(p, train_x, train_y, idx):
        xb = jnp.take(train_x, idx, axis=0).astype(jnp.float32) / 255.0
        yb = jnp.take(train_y, idx, axis=0)
        return jax.grad(loss_fn)(p, xb, yb)

    got = sharded_grads(params8, ds.train_x, ds.train_y, idx8)
    for a, b in zip(jax.tree.leaves(ref_grads), jax.tree.leaves(got)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("model_name,opt", [("mlp", "sgd"), ("lenet", "adam")])
def test_auto_equals_explicit_mode(tiny_data, eight_devices, model_name, opt):
    """jit+sharding-propagation and shard_map+psum must produce identical
    training trajectories (same seed, same batches)."""
    s_auto, l_auto = _run(tiny_data, eight_devices, 5, "auto",
                          model_name, opt)
    s_exp, l_exp = _run(tiny_data, eight_devices, 5, "explicit",
                        model_name, opt)
    np.testing.assert_allclose(l_auto, l_exp, rtol=1e-5)
    # rtol 1e-4, not 1e-5: the two modes lower the gradient all-reduce
    # differently (XLA-inserted vs explicit psum), and their reduction
    # orders differ at the ulp level across jax versions. Adam divides
    # by sqrt(nu), amplifying that over 5 steps to ~2.5e-5 relative on
    # LeNet (observed on jax 0.4.37 CPU); mlp-sgd stays tighter. Still
    # a strong equivalence bound — a real divergence is orders beyond.
    for a, b in zip(jax.tree.leaves(s_auto.params),
                    jax.tree.leaves(s_exp.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-6)


@pytest.mark.parametrize("mode", ["auto", "explicit"])
def test_one_dev_equals_eight_dev(tiny_data, eight_devices, mode):
    """Seed-for-seed 1-chip ≡ 8-chip equivalence (SURVEY.md §7.3) — the
    global batch order is device-count-independent and the psum'd update
    equals the single-device update."""
    s1, l1 = _run(tiny_data, eight_devices[:1], 8, mode)
    s8, l8 = _run(tiny_data, eight_devices, 8, mode)
    np.testing.assert_allclose(l1, l8, rtol=1e-4)
    for a, b in zip(jax.tree.leaves(s1.params), jax.tree.leaves(s8.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


def test_loss_decreases_8dev(tiny_data, eight_devices):
    _, losses = _run(tiny_data, eight_devices, 32, "auto",
                     model_name="mlp", opt="sgd", lr=0.02)
    assert np.mean(losses[-4:]) < np.mean(losses[:4]) * 0.8


def test_eval_fn_counts_correct(tiny_data, eight_devices):
    from distributedmnist_tpu.data.loader import eval_batches
    from jax.sharding import NamedSharding, PartitionSpec as P
    mesh = make_mesh(eight_devices)
    ds = DeviceDataset(tiny_data, mesh)
    model = models.build("mlp", fused="xla")
    params = jax.device_put(
        model.init(jax.random.PRNGKey(0),
                   jnp.zeros((1, 28, 28, 1)))["params"],
        replicated(mesh))
    eval_fn = make_eval_fn(model, mesh)
    idx_mat, mask_mat = eval_batches(ds.test_n, 128)
    spec = NamedSharding(mesh, P(None, "data"))
    correct = int(eval_fn(params, ds.test_x, ds.test_y,
                          jax.device_put(idx_mat, spec),
                          jax.device_put(mask_mat, spec)))
    # oracle: plain numpy/jnp forward over the whole test set
    logits = model.apply(
        {"params": params},
        jnp.asarray(tiny_data["test_x"], jnp.float32) / 255.0)
    want = int((jnp.argmax(logits, -1) == tiny_data["test_y"]).sum())
    assert correct == want


def test_batch_not_divisible_raises(tiny_data, eight_devices):
    from distributedmnist_tpu.config import Config
    from distributedmnist_tpu import trainer
    cfg = Config(batch_size=100, num_devices=8, device="cpu",
                 synthetic=True)
    with pytest.raises(ValueError, match="not divisible"):
        trainer.fit(cfg, data=tiny_data)
