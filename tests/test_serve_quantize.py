"""Quantized + fused inference fast path (ISSUE 7): weight quantization
bounds, fast-path-vs-reference parity for every (model, dtype, kernel
mode), interpret-vs-XLA fused-op equivalence across the serve bucket
ladder, the registry's dtype-variant parity gate (pass AND refuse
paths), zero recompiles across promotes between engines of different
dtypes, the scheduler re-pricing flip, and the staging-pool audit on the
quantized fetch path."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from distributedmnist_tpu import models
from distributedmnist_tpu.ops import fused
from distributedmnist_tpu.parallel import make_mesh
from distributedmnist_tpu.serve import quantize as quantize_lib
from distributedmnist_tpu.serve.engine import InferenceEngine
from distributedmnist_tpu.serve.metrics import ServeMetrics
from distributedmnist_tpu.serve.registry import (EngineFactory,
                                                 ModelRegistry,
                                                 PARITY_GATES)
from distributedmnist_tpu.utils import CompileCounter, parity_check

pytestmark = pytest.mark.quant


# -- quantization ----------------------------------------------------------

def test_quantize_channelwise_dense_roundtrip(rng):
    w = rng.normal(size=(40, 12)).astype(np.float32)
    q, s = quantize_lib.quantize_channelwise(w)
    assert q.dtype == np.int8 and s.shape == (12,)
    assert np.abs(q).max() <= 127
    back = quantize_lib.dequantize(q, s)
    # symmetric rounding: per-channel error bounded by half a step
    assert np.all(np.abs(back - w) <= s / 2 + 1e-7)


def test_quantize_channelwise_conv_and_zero_channel(rng):
    w = rng.normal(size=(5, 5, 3, 8)).astype(np.float32)
    w[..., 2] = 0.0                       # an all-zero output channel
    q, s = quantize_lib.quantize_channelwise(w)
    assert s.shape == (8,)
    assert s[2] == 1.0                    # guarded scale, exact dequant
    np.testing.assert_array_equal(quantize_lib.dequantize(q, s)[..., 2],
                                  0.0)
    with pytest.raises(ValueError, match=">=2-D"):
        quantize_lib.quantize_channelwise(np.zeros(4, np.float32))


def test_quantize_act_dynamic_scale():
    h = jnp.asarray([[0.5, -2.0, 1.0]], jnp.float32)
    q, s = quantize_lib.quantize_act(h)
    assert q.dtype == jnp.int8
    assert abs(float(s) - 2.0 / 127.0) < 1e-9
    np.testing.assert_allclose(np.asarray(q, np.float32) * float(s),
                               np.asarray(h), atol=float(s) / 2 + 1e-9)


# -- fused inference ops: interpret vs XLA across the bucket ladder --------

def test_fused_inference_equivalence_every_bucket_rung(rng):
    """dense_relu_inference (f32 + bf16) and quant_dense (int8) must
    agree between the Pallas-interpret kernel and the XLA reference at
    EVERY rung of a serve bucket ladder — the shapes the engines
    actually dispatch."""
    from distributedmnist_tpu.serve import make_buckets

    k, n = 40, 24
    w = rng.normal(size=(k, n)).astype(np.float32)
    b = rng.normal(size=n).astype(np.float32)
    wq, ws = quantize_lib.quantize_channelwise(w)
    for m in make_buckets(16, 1):                 # 1, 2, 4, 8, 16
        x = rng.normal(size=(m, k)).astype(np.float32)
        ref = np.asarray(fused.dense_relu_inference(
            jnp.asarray(x), jnp.asarray(w), jnp.asarray(b), fused.XLA))
        got = np.asarray(fused.dense_relu_inference(
            jnp.asarray(x), jnp.asarray(w), jnp.asarray(b),
            fused.PALLAS_INTERPRET))
        np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)
        # bf16 operands through the same kernel
        got16 = np.asarray(fused.dense_relu_inference(
            jnp.asarray(x, jnp.bfloat16), jnp.asarray(w, jnp.bfloat16),
            jnp.asarray(b, jnp.bfloat16), fused.PALLAS_INTERPRET))
        ref16 = np.asarray(fused.dense_relu_inference(
            jnp.asarray(x, jnp.bfloat16), jnp.asarray(w, jnp.bfloat16),
            jnp.asarray(b, jnp.bfloat16), fused.XLA))
        np.testing.assert_allclose(got16.astype(np.float32),
                                   ref16.astype(np.float32),
                                   rtol=0.05, atol=0.05)
        # int8: integer accumulation is exact, epilogues must match
        xq = rng.integers(-127, 128, (m, k)).astype(np.int8)
        for relu in (True, False):
            gi = np.asarray(fused.quant_dense(
                jnp.asarray(xq), jnp.asarray(wq), jnp.asarray(ws),
                jnp.asarray(b), relu=relu, mode=fused.PALLAS_INTERPRET))
            ri = np.asarray(fused.quant_dense_reference(
                jnp.asarray(xq), jnp.asarray(wq), jnp.asarray(ws),
                jnp.asarray(b), relu=relu))
            np.testing.assert_allclose(gi, ri, rtol=1e-6, atol=1e-6)


def test_quant_dense_rejects_non_int8():
    with pytest.raises(TypeError, match="int8"):
        fused.quant_dense(jnp.zeros((2, 3), jnp.float32),
                          jnp.zeros((3, 4), jnp.int8),
                          jnp.ones(4), jnp.zeros(4))


# -- the fast path vs the training-identical reference ---------------------

def _reference_logits(model, params, x):
    fwd = jax.jit(lambda p, xu: model.apply(
        {"params": p}, xu.astype(jnp.float32) / 255.0))
    return np.asarray(fwd(params, x))


@pytest.mark.parametrize("name", ["mlp", "lenet"])
@pytest.mark.parametrize("infer_dtype", ["bfloat16", "int8"])
@pytest.mark.parametrize("mode", [fused.XLA, fused.PALLAS_INTERPRET])
def test_fastpath_parity_vs_reference(name, infer_dtype, mode, rng):
    """Every (model, dtype, kernel-route) fast path must track the
    training-precision forward within the PARITY.md relative-diff
    thresholds. LeNet additionally holds full argmax agreement on
    fresh-init params; the fresh-init MLP's logit spread is so tight
    that honest low-precision error flips a few percent of near-tie
    argmaxes — exactly the case the registry gate exists to refuse
    (tested below), so here the MLP asserts the diff bound plus a
    loose agreement floor."""
    model = models.build(name, dtype=jnp.float32, platform="cpu")
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 28, 28, 1)))["params"]
    x = rng.integers(0, 256, (32, 28, 28, 1)).astype(np.uint8)
    ref = _reference_logits(model, params, x)
    prep, fwd = quantize_lib.prepare_inference(model, params,
                                               infer_dtype, mode)
    got = np.asarray(jax.jit(fwd)(jax.device_put(prep), x))
    assert got.dtype == np.float32                # logits always f32
    _, max_rel = PARITY_GATES[infer_dtype]
    rep = parity_check(ref, got, min_agreement=0.9,
                       max_rel_diff=max_rel)
    assert rep["max_rel_logit_diff"] <= max_rel, rep
    if name == "lenet":
        assert rep["argmax_agreement"] == 1.0, rep
    else:
        assert rep["argmax_agreement"] >= 0.9, rep


def test_fastpath_handles_fused_pallas_mlp_param_layout(rng):
    """The MLP built with the fused Pallas hidden layer stores flat
    hidden_kernel/hidden_bias leaves instead of the nn.Dense subtree —
    prepare_inference must read both layouts."""
    model = models.build("mlp", dtype=jnp.float32, fused="pallas",
                         platform="cpu")        # resolves to interpret
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 28, 28, 1)))["params"]
    assert "hidden_kernel" in params            # the flat layout
    prep, fwd = quantize_lib.prepare_inference(model, params, "int8",
                                               fused.XLA)
    x = rng.integers(0, 256, (4, 28, 28, 1)).astype(np.uint8)
    assert np.asarray(jax.jit(fwd)(jax.device_put(prep), x)).shape \
        == (4, 10)


def test_prepare_inference_rejects_bad_inputs():
    model = models.build("mlp", platform="cpu")
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 28, 28, 1)))["params"]
    with pytest.raises(ValueError, match="float32 serves"):
        quantize_lib.prepare_inference(model, params, "float32",
                                       fused.XLA)
    with pytest.raises(ValueError, match="unknown infer dtype"):
        quantize_lib.prepare_inference(model, params, "fp4", fused.XLA)
    with pytest.raises(ValueError, match="RESOLVED"):
        quantize_lib.prepare_inference(model, params, "int8", "auto")


# -- engine level ----------------------------------------------------------

@pytest.fixture(scope="module")
def lenet_pair(eight_devices):
    """A (float32 reference, int8 fast path) engine pair over the same
    fresh-init LeNet params and one small bucket ladder."""
    mesh = make_mesh(eight_devices[:1])
    model = models.build("lenet", platform="cpu")
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 28, 28, 1)))["params"]
    f32 = InferenceEngine(model, params, mesh, max_batch=8)
    q8 = InferenceEngine(model, params, mesh, max_batch=8,
                         infer_dtype="int8")
    f32.warmup()
    q8.warmup()
    return f32, q8


def test_engine_int8_parity_and_tags(lenet_pair, rng):
    f32, q8 = lenet_pair
    assert f32.infer_dtype == "float32" and q8.infer_dtype == "int8"
    assert q8.fused_mode == fused.XLA        # resolved for CPU serving
    x = rng.integers(0, 256, (7, 28, 28, 1)).astype(np.uint8)
    ref = f32.infer(x)
    got = q8.infer(x)
    rep = parity_check(ref, got, *PARITY_GATES["int8"])
    assert rep["passed"], rep
    # the dtype tag rides the handle end to end (metrics by_dtype)
    h = q8.dispatch(x)
    assert h.infer_dtype == "int8"
    q8.fetch(h)


def test_quantized_fetch_failure_recycles_staging(lenet_pair, rng):
    """The staging-pool audit (ISSUE 7 satellite): the quantized path
    must recycle its pooled buffer on fetch FAILURE exactly like the
    f32 path — a fetch-fault storm against an int8 engine must not
    bleed one buffer per failed batch (the PR 5 try/finally, pinned
    for the fast path via the fault injector's engine.fetch point)."""
    from distributedmnist_tpu.serve import faults
    from distributedmnist_tpu.serve.faults import InjectedFault

    _, q8 = lenet_pair
    x = rng.integers(0, 256, (3, 28, 28, 1)).astype(np.uint8)
    q8.infer(x)                              # settle the pool
    before = q8.staging_buffers()
    faults.install(faults.FaultInjector.from_spec("engine.fetch:p=1",
                                                  seed=1))
    try:
        for _ in range(5):
            with pytest.raises(InjectedFault):
                q8.infer(x)
    finally:
        faults.uninstall()
    assert q8.staging_buffers() == before    # success AND failure paths
    assert q8.infer(x).shape == (3, 10)      # and the engine still serves


# -- registry: the dtype-variant parity gate -------------------------------

@pytest.fixture(scope="module")
def lenet_registry(eight_devices):
    """A bootstrapped single-replica LeNet registry with a tiny ladder
    (module-scoped: LeNet bucket compiles are the slow part; the gate
    tests share one warmed instance)."""
    mesh = make_mesh(eight_devices[:1])
    model = models.build("lenet", platform="cpu")
    factory = EngineFactory(model, mesh, max_batch=4)
    metrics = ServeMetrics()
    router = factory.make_router(metrics=metrics)
    registry = ModelRegistry(factory, router)
    registry.bootstrap(seed=0)
    return registry, router, metrics


def test_variant_gate_passes_and_promotes(lenet_registry, rng):
    """Acceptance: bf16 and int8 variants pass the default gate
    (argmax agreement >= 0.995 + the PARITY.md relative-diff bar) on
    the held-out batch, promote by dtype routes them, and GET /models'
    describe() surfaces state + parity + live precision."""
    registry, router, _ = lenet_registry
    version = registry.live_version()
    for dt in ("bfloat16", "int8"):
        vi = registry.add_variant(version, dt)
        assert vi.state == "ready"
        assert vi.parity["passed"] is True
        assert vi.parity["argmax_agreement"] >= 0.995
        assert vi.parity["max_rel_logit_diff"] <= PARITY_GATES[dt][1]
    # idempotent: a ready variant returns as-is, no rebuild
    again = registry.add_variant(version, "int8")
    assert again is registry.get(version).variants["int8"]

    registry.promote(version, infer_dtype="int8")
    assert router.live_infer_dtype() == "int8"
    d = registry.describe()
    assert d["live_infer_dtype"] == "int8"
    vdesc = d["versions"][0]["variants"]
    assert vdesc["int8"]["state"] == "ready"
    assert vdesc["int8"]["parity"]["passed"] is True
    assert vdesc["int8"]["bucket_cost_ms"]          # per-dtype table
    registry.promote(version)                        # back to the base
    assert router.live_infer_dtype() == "float32"


def test_zero_recompiles_across_dtype_promotes(lenet_registry, rng):
    """ISSUE 7 satellite: promotes BETWEEN engines of different dtypes
    must stay steady-state recompile-free at every bucket — each
    engine's jit cache keys on its own (already-warmed) program, so a
    dtype roll can never cost a cold bucket."""
    registry, router, _ = lenet_registry
    version = registry.live_version()
    registry.add_variant(version, "int8")
    compiles = CompileCounter.instance()
    before = compiles.snapshot()
    for dt in ("int8", None, "int8", None):          # roll back and forth
        registry.promote(version, infer_dtype=dt)
        for b in registry.factory.buckets:
            x = rng.integers(0, 256, (b, 28, 28, 1)).astype(np.uint8)
            assert router.infer(x).shape == (b, 10)
    assert compiles.snapshot() - before == 0


def test_variant_gate_refuses_and_records(lenet_registry):
    """A variant failing the gate is REFUSED: state failed, last_error
    naming the threshold, promote(dtype) raises — never silently
    served. (An impossible agreement bar forces the refusal without
    needing a genuinely broken build.)"""
    registry, router, _ = lenet_registry
    version = registry.live_version()
    registry.get(version).variants.pop("bfloat16", None)  # force rebuild
    with pytest.raises(RuntimeError, match="parity gate REFUSED"):
        registry.add_variant(version, "bfloat16", min_agreement=1.01)
    vi = registry.get(version).variants["bfloat16"]
    assert vi.state == "failed"
    assert "argmax agreement" in vi.last_error
    assert vi.last_error_at is not None
    assert vi.engines == []                  # refused engines not pinned
    with pytest.raises(RuntimeError, match="not promotable"):
        registry.promote(version, infer_dtype="bfloat16")
    assert router.live_infer_dtype() == "float32"    # traffic unmoved
    # a retry may clear the failed entry (thresholds back to default)
    vi = registry.add_variant(version, "bfloat16")
    assert vi.state == "ready"
    # custom thresholds against an ALREADY-ready variant re-gate its
    # existing engines instead of returning the default-bar verdict
    with pytest.raises(RuntimeError, match="re-gate REFUSED"):
        registry.add_variant(version, "bfloat16", min_agreement=1.01)
    assert registry.get(version).variants["bfloat16"].state == "failed"
    vi = registry.add_variant(version, "bfloat16")   # default bar again
    assert vi.state == "ready"
    # a LIVE variant failing a re-gate is demoted to the f32 base
    # immediately (event-logged) — a refused precision must stop
    # serving now, not at the next operator promote
    registry.promote(version, infer_dtype="bfloat16")
    assert router.live_infer_dtype() == "bfloat16"
    with pytest.raises(RuntimeError, match="re-gate REFUSED"):
        registry.add_variant(version, "bfloat16", min_agreement=1.01)
    assert router.live_infer_dtype() == "float32"
    demotions = [e for e in registry.events()
                 if e.get("event") == "variant_demoted"]
    assert demotions and demotions[-1]["infer_dtype"] == "bfloat16"
    vi = registry.add_variant(version, "bfloat16")   # clean slate again
    assert vi.state == "ready"


def test_variant_failpoint_drives_refusal(lenet_registry):
    """The registry.variant failpoint: an injected variant failure runs
    the same refused-variant bookkeeping a real compile/parity failure
    would (chaos drills can target the fast-path rollout)."""
    from distributedmnist_tpu.serve import faults
    from distributedmnist_tpu.serve.faults import InjectedFault

    registry, _, _ = lenet_registry
    version = registry.live_version()
    registry.get(version).variants.pop("bfloat16", None)
    faults.install(faults.FaultInjector.from_spec(
        "registry.variant:p=1,dtype=bfloat16", seed=2))
    try:
        with pytest.raises(InjectedFault):
            registry.add_variant(version, "bfloat16")
    finally:
        faults.uninstall()
    vi = registry.get(version).variants["bfloat16"]
    assert vi.state == "failed" and "InjectedFault" in vi.last_error


def test_unknown_variant_dtype_rejected(lenet_registry):
    registry, _, _ = lenet_registry
    with pytest.raises(ValueError, match="unknown variant dtype"):
        registry.add_variant(registry.live_version(), "float16")


def test_auto_pick_serves_cheapest_parity_passing(lenet_registry):
    """The --serve-infer-dtype auto rule: activate warms + gates every
    variant and promotes the cheapest parity-passing one by the warmup
    cost tables (float32 included as a candidate)."""
    registry, router, _ = lenet_registry
    version = registry.live_version()
    pick = registry.activate_infer_dtype(version, "auto")
    assert pick in ("float32", "bfloat16", "int8")
    assert router.live_infer_dtype() == pick
    mv = registry.get(version)
    candidates = {"float32": mv.engines[0]}
    candidates.update({dt: vi.engine for dt, vi in mv.variants.items()
                       if vi.state == "ready"})
    prices = {dt: sum(e.bucket_costs().values())
              for dt, e in candidates.items()}
    assert pick == min(prices, key=prices.get)
    registry.promote(version)                        # restore the base


def test_metrics_split_by_dtype(lenet_registry, rng):
    """by_dtype attribution: batches served after a dtype promote land
    in that precision's population."""
    from distributedmnist_tpu.serve import DynamicBatcher

    registry, router, metrics = lenet_registry
    version = registry.live_version()
    registry.add_variant(version, "int8")
    metrics.reset()
    batcher = DynamicBatcher(router, metrics=metrics).start()
    try:
        registry.promote(version, infer_dtype="int8")
        batcher.submit(rng.integers(0, 256, (2, 784)).astype(np.uint8)
                       ).result(timeout=60)
        registry.promote(version)
        batcher.submit(rng.integers(0, 256, (2, 784)).astype(np.uint8)
                       ).result(timeout=60)
    finally:
        batcher.stop()
    by_dtype = metrics.snapshot()["by_dtype"]
    assert by_dtype["int8"]["rows"] == 2
    assert by_dtype["float32"]["rows"] == 2


# -- scheduler re-pricing ---------------------------------------------------

def test_batch_former_replans_from_quantized_cost_table():
    """ISSUE 7 acceptance: the PR 4 DP former demonstrably re-prices
    when a cheaper per-row cost table (the quantized engine's) is
    installed. Under the f32-shaped table (per-row compute dominates)
    splitting a 20-row drain into 16+4 beats padding to 32; under a
    table the fast path has flattened (same dispatch overhead, per-row
    cost collapsed) the padding is nearly free and the SAME drain plans
    as one covering dispatch — the split decision flips purely on the
    installed table."""
    from distributedmnist_tpu.serve.scheduler import plan_segments

    buckets = (4, 8, 16, 32)
    sizes = [4, 4, 4, 4, 4]                          # 20 rows
    f32_table = {b: 0.001 + 0.004 * b for b in buckets}
    quant_table = {b: 0.001 + 0.00001 * b for b in buckets}
    split = plan_segments(sizes, buckets, f32_table)
    assert len(split) == 2 and sum(split) == 5       # e.g. 4 + 16 rows
    assert plan_segments(sizes, buckets, quant_table) == [5]


# -- serve.py / healthz surface --------------------------------------------

def test_healthz_reports_live_infer_dtype(lenet_registry):
    import serve as serve_cli

    registry, router, _ = lenet_registry

    class _B:
        controller = None

        def pending_rows(self):
            return 0

        def inflight_batches(self):
            return 0

    state = serve_cli.ServerState()
    code, payload = state.healthz(registry, _B())
    assert code == 200
    assert payload["live_infer_dtype"] == router.live_infer_dtype()
