"""distributedmnist_tpu/analysis/lint.py: every rule demonstrated by a
planted violation asserting the exact rule ID, the pragma allowlist
contract (reason REQUIRED), scope boundaries, and the repo-at-HEAD
gate (`python -m distributedmnist_tpu.analysis` exits 0 — the
acceptance criterion scripts/tier1.sh enforces before pytest)."""

import os
import subprocess
import sys

import pytest

from distributedmnist_tpu.analysis import lint

pytestmark = pytest.mark.analysis

SERVE_REL = "distributedmnist_tpu/serve/somemodule.py"
PLUMBING_REL = "distributedmnist_tpu/serve/batcher.py"


def _rules(text, rel=SERVE_REL):
    return [f.rule for f in lint.lint_source(text, rel)]


def _active_rules(text, rel=SERVE_REL):
    findings = lint.lint_source(text, rel)
    active, _ = lint.apply_allowlist(findings, text.splitlines())
    return [f.rule for f in active]


# -- DML001: bare threading primitives ------------------------------------


def test_dml001_bare_lock_flagged_in_serve():
    src = "import threading\nlock = threading.Lock()\n"
    assert _rules(src) == ["DML001"]
    f = lint.lint_source(src, SERVE_REL)[0]
    assert f.line == 2 and "make_lock" in f.message


@pytest.mark.parametrize("prim", ["RLock", "Condition", "Semaphore",
                                  "BoundedSemaphore"])
def test_dml001_all_primitives(prim):
    assert _rules(f"import threading\nx = threading.{prim}()\n") == [
        "DML001"]


def test_dml001_scope_excludes_tests_and_analysis():
    src = "import threading\nlock = threading.Lock()\n"
    assert _rules(src, "tests/test_serve_batcher.py") == []
    assert _rules(src, "distributedmnist_tpu/analysis/locks.py") == []
    assert _rules(src, "distributedmnist_tpu/trainer.py") == []
    # serve.py at repo root IS in scope (it builds the serving process)
    assert _rules(src, "serve.py") == ["DML001"]


def test_dml001_factory_calls_are_clean():
    src = ("from distributedmnist_tpu.analysis.locks import make_lock\n"
           "lock = make_lock('engine.staging')\n")
    assert _rules(src) == []


# -- DML002: bare threads --------------------------------------------------


def test_dml002_bare_thread_flagged():
    src = ("import threading\n"
           "t = threading.Thread(target=print, daemon=True)\n")
    assert _rules(src) == ["DML002"]
    # bench.py is in thread scope too (its client threads)
    assert _rules(src, "bench.py") == ["DML002"]
    assert _rules(src, "tests/test_x.py") == []


def test_dml002_event_is_not_a_thread():
    assert _rules("import threading\ne = threading.Event()\n") == []


# -- DML003: failpoint registry -------------------------------------------


def test_dml003_unknown_failpoint_call():
    src = "failpoint('engine.fetsh', rows=1)\n"
    assert _rules(src) == ["DML003"]
    f = lint.lint_source(src, SERVE_REL)[0]
    assert "engine.fetsh" in f.message


def test_dml003_known_failpoint_clean():
    assert _rules("failpoint('engine.fetch', rows=1)\n") == []


def test_dml003_spec_string_in_parse_spec():
    assert _rules("parse_spec('engine.fetch:p=1;batch.dspatch:p=1')\n"
                  ) == ["DML003"]
    assert _rules("parse_spec('engine.fetch:p=1;batch.dispatch:p=1')\n"
                  ) == []


def test_dml003_spec_shaped_literal_anywhere():
    """The bench's programmatically-concatenated schedules: every
    spec-shaped string constant is checked, in ANY repo file —
    including f-string fragments."""
    src = 'spec = "replica.ftch:p=1,replica=r1"\n'
    assert _rules(src, "bench.py") == ["DML003"]
    assert _rules(src, "tests/test_x.py") == ["DML003"]
    # f-string fragments: the constant piece before the placeholder
    src2 = 'spec = f"engine.fetsh:p=1,version={v}"\n'
    assert _rules(src2, "bench.py") == ["DML003"]
    ok = 'spec = f"engine.fetch:p=1,version={v}"\n'
    assert _rules(ok, "bench.py") == []


def test_dml003_prose_and_plain_strings_not_flagged():
    # docstrings and non-spec-shaped strings are prose, not schedules
    src = ('"""mentions engine.whatever in prose."""\n'
           'x = "registry.state"\n'
           'y = "no colons here"\n')
    assert _rules(src, "bench.py") == []


# -- DML004: wall-clock timing --------------------------------------------


def test_dml004_time_time_flagged_in_scope():
    src = "import time\nt0 = time.time()\n"
    assert _rules(src) == ["DML004"]
    assert _rules(src, "serve.py") == ["DML004"]
    assert _rules(src, "bench.py") == ["DML004"]
    assert _rules(src, "distributedmnist_tpu/trainer.py") == []


def test_dml004_monotonic_clean():
    assert _rules("import time\nt0 = time.monotonic()\n"
                  "t1 = time.perf_counter()\n") == []


# -- DML005: jit outside the engine ---------------------------------------


def test_dml005_jit_outside_engine_flagged():
    src = "import jax\nf = jax.jit(lambda x: x)\n"
    assert _rules(src, "distributedmnist_tpu/serve/router.py") == [
        "DML005"]
    # the engine/warmup construction paths are the sanctioned homes
    assert _rules(src, "distributedmnist_tpu/serve/engine.py") == []
    assert _rules(src, "distributedmnist_tpu/serve/quantize.py") == []
    # outside serve/ the rule does not apply (training jits freely)
    assert _rules(src, "distributedmnist_tpu/trainer.py") == []


# -- DML006: recycle outside finally --------------------------------------


def test_dml006_recycle_outside_finally_flagged():
    src = ("def fetch(self, handle):\n"
           "    out = read(handle)\n"
           "    self._staging_pool[handle.bucket].append(handle.staging)\n"
           "    return out\n")
    assert _rules(src) == ["DML006"]


def test_dml006_recycle_in_finally_clean():
    src = ("def fetch(self, handle):\n"
           "    try:\n"
           "        return read(handle)\n"
           "    finally:\n"
           "        self._staging_pool[handle.bucket].append(\n"
           "            handle.staging)\n")
    assert _rules(src) == []


# -- DML007: span begin without try/finally end ----------------------------


def test_dml007_unprotected_begin_span_flagged():
    src = ("from distributedmnist_tpu.serve import trace\n"
           "def dispatch(self, seg):\n"
           "    sp = trace.begin_span('batch.dispatch', rids=[1])\n"
           "    return self.engine.dispatch(seg)\n")
    # linted at the batcher's path: engine.dispatch is plumbing there
    # (a non-plumbing module would additionally earn DML015)
    assert _rules(src, PLUMBING_REL) == ["DML007"]
    f = lint.lint_source(src, PLUMBING_REL)[0]
    assert f.line == 3 and "end_span" in f.message


def test_dml007_try_finally_end_is_clean():
    src = ("from distributedmnist_tpu.serve import trace\n"
           "def dispatch(self, seg):\n"
           "    sp = trace.begin_span('batch.dispatch')\n"
           "    try:\n"
           "        return self.engine.dispatch(seg)\n"
           "    finally:\n"
           "        trace.end_span(sp)\n")
    assert _rules(src, PLUMBING_REL) == []
    # try/except/finally (the completion-loop shape) is protected too
    src2 = ("from distributedmnist_tpu.serve import trace\n"
            "def fetch(self, h):\n"
            "    sp = trace.begin_span('engine.fetch')\n"
            "    try:\n"
            "        return self.engine.fetch(h)\n"
            "    except Exception as e:\n"
            "        trace.end_span(sp, error=type(e).__name__)\n"
            "        raise\n"
            "    finally:\n"
            "        trace.end_span(sp)\n")
    assert _rules(src2, PLUMBING_REL) == []


def test_dml007_end_outside_finally_not_enough():
    """An end_span only on the happy path is exactly the bug the rule
    exists for — the try must END the span in a finally."""
    src = ("from distributedmnist_tpu.serve import trace\n"
           "def dispatch(self, seg):\n"
           "    sp = trace.begin_span('batch.dispatch')\n"
           "    try:\n"
           "        out = self.engine.dispatch(seg)\n"
           "        trace.end_span(sp)\n"
           "        return out\n"
           "    except Exception:\n"
           "        raise\n")
    assert _rules(src, PLUMBING_REL) == ["DML007"]


def test_dml007_nested_statement_lists_checked():
    """A begin at any nesting depth is checked against ITS OWN
    statement list (the if-guarded begin must still be followed by its
    try)."""
    src = ("from distributedmnist_tpu.serve import trace\n"
           "def f(self):\n"
           "    if self.on:\n"
           "        sp = trace.begin_span('engine.staging')\n"
           "    work()\n")
    assert _rules(src) == ["DML007"]


def test_dml007_scope_is_serve_and_trace_py_exempt():
    src = ("from distributedmnist_tpu.serve import trace\n"
           "sp = trace.begin_span('x.y')\n")
    # tests, bench and the trace facility itself are out of scope
    assert _rules(src, "tests/test_serve_trace.py") == []
    assert _rules(src, "bench.py") == []
    assert _rules(src, "distributedmnist_tpu/serve/trace.py") == []
    assert _rules(src, "serve.py") == ["DML007"]


# -- DML008: cache state mutated outside the cache's named lock ------------


def test_dml008_unlocked_mutations_flagged():
    """Every mutation shape on the cache state containers is flagged
    when it sits outside a `with <...>_lock:` block."""
    for stmt in ("self._entries.pop(k)",
                 "self._entries[k] = v",
                 "self._entries.move_to_end(k)",
                 "self._flights.clear()",
                 "del self._flights[k]",
                 "self._flights.setdefault(k, f)"):
        src = f"def f(self, k, v, f):\n    {stmt}\n"
        assert _rules(src) == ["DML008"], stmt


def test_dml008_under_named_lock_is_clean():
    src = ("def f(self, k, v):\n"
           "    with self._lock:\n"
           "        self._entries[k] = v\n"
           "        self._flights.pop(k, None)\n")
    assert _rules(src) == []
    # a front-layer compound op holding the CACHE's lock is clean too
    src2 = ("def g(cache, k, v):\n"
            "    with cache._lock:\n"
            "        cache._entries[k] = v\n")
    assert _rules(src2) == []


def test_dml008_reads_and_rebinding_are_clean():
    src = ("def f(self, k):\n"
           "    e = self._entries.get(k)\n"
           "    n = len(self._entries)\n"
           "    return e, n\n"
           "def ctor(self):\n"
           "    self._entries = {}\n"       # constructor rebinding
           "    self._flights = {}\n")
    assert _rules(src) == []


def test_dml008_scope_is_serve_package_only():
    src = "def f(self, k, v):\n    self._entries[k] = v\n"
    assert _rules(src, "tests/test_serve_cache.py") == []
    assert _rules(src, "bench.py") == []
    assert _rules(src, "distributedmnist_tpu/trainer.py") == []
    assert _rules(src, "distributedmnist_tpu/serve/cache.py") == [
        "DML008"]


def test_dml008_wrong_lock_shape_not_enough():
    """A `with` that is not a lock (an Event, a file) does not count as
    protection."""
    src = ("def f(self, k, v):\n"
           "    with self._gate:\n"
           "        self._entries[k] = v\n")
    assert _rules(src) == ["DML008"]


# -- DML009: future resolution under a serve lock (ISSUE 11) ---------------


def test_dml009_direct_resolution_under_lock():
    """The pre-ISSUE-11 batcher.stop(drain=False) shape: futures
    failed while holding the queue condition."""
    src = ("from distributedmnist_tpu.analysis.locks import "
           "make_condition\n"
           "class B:\n"
           "    def __init__(self):\n"
           "        self._cond = make_condition('batcher.queue')\n"
           "    def stop(self, req, err):\n"
           "        with self._cond:\n"
           "            req.future.set_exception(err)\n")
    assert _rules(src) == ["DML009"]
    f = lint.lint_source(src, SERVE_REL)[0]
    assert "_cond" in f.message


def test_dml009_interprocedural_through_helper():
    """A helper whose EVERY call site holds the lock is analyzed as
    under it — the resolve inside fires even with no lexical with."""
    src = ("from distributedmnist_tpu.analysis.locks import make_lock\n"
           "class B:\n"
           "    def __init__(self):\n"
           "        self._lock = make_lock('x')\n"
           "    def _fail(self, fut, err):\n"
           "        fut.set_exception(err)\n"
           "    def run(self, fut, err):\n"
           "        with self._lock:\n"
           "            self._fail(fut, err)\n")
    assert _rules(src) == ["DML009"]


def test_dml009_resolve_after_lock_is_clean():
    """Collect-under-lock, resolve-after (the fixed batcher shape) and
    callbacks REGISTERED under the lock (they run later, elsewhere)
    are both fine."""
    src = ("from distributedmnist_tpu.analysis.locks import make_lock\n"
           "class B:\n"
           "    def __init__(self):\n"
           "        self._lock = make_lock('x')\n"
           "    def run(self, fut):\n"
           "        with self._lock:\n"
           "            fut.add_done_callback(\n"
           "                lambda d: d.set_result(None))\n"
           "            dropped = [fut]\n"
           "        for f in dropped:\n"
           "            f.set_result(1)\n")
    assert _rules(src) == []


def test_dml009_helper_with_unlocked_callsite_flags_the_locked_one():
    """A helper called both with and without the lock: the LOCKED call
    site is the finding (the helper itself is not always-under-lock)."""
    src = ("from distributedmnist_tpu.analysis.locks import make_lock\n"
           "class B:\n"
           "    def __init__(self):\n"
           "        self._lock = make_lock('x')\n"
           "    def _fail(self, fut):\n"
           "        fut.set_exception(ValueError())\n"
           "    def locked_path(self, fut):\n"
           "        with self._lock:\n"
           "            self._fail(fut)\n"
           "    def clean_path(self, fut):\n"
           "        self._fail(fut)\n")
    findings = lint.lint_source(src, SERVE_REL)
    assert [f.rule for f in findings] == ["DML009"]
    assert findings[0].line == 9          # the locked call site


def test_dml009_scope_is_serve_and_serve_py():
    src = ("from distributedmnist_tpu.analysis.locks import make_lock\n"
           "class B:\n"
           "    def __init__(self):\n"
           "        self._lock = make_lock('x')\n"
           "    def run(self, fut):\n"
           "        with self._lock:\n"
           "            fut.set_result(1)\n")
    assert _rules(src, "serve.py") == ["DML009"]
    assert _rules(src, "distributedmnist_tpu/trainer.py") == []
    assert _rules(src, "tests/test_serve_batcher.py") == []


# -- DML010: lock-containment inference (ISSUE 11) -------------------------


def test_dml010_inferred_guard_violation():
    src = ("from distributedmnist_tpu.analysis.locks import make_lock\n"
           "class R:\n"
           "    def __init__(self):\n"
           "        self._state = make_lock('registry.state')\n"
           "        self._versions = {}\n"
           "    def a(self, k, v):\n"
           "        with self._state:\n"
           "            self._versions[k] = v\n"
           "    def b(self, k):\n"
           "        with self._state:\n"
           "            del self._versions[k]\n"
           "    def c(self, k):\n"
           "        self._versions.pop(k, None)\n")
    findings = lint.lint_source(src, SERVE_REL)
    assert [f.rule for f in findings] == ["DML010"]
    assert findings[0].line == 13
    assert "_state" in findings[0].message


def test_dml010_propagated_helper_is_clean():
    """_evict_locked-style helpers: every call site holds the lock, so
    the helper's mutations count as guarded."""
    src = ("from distributedmnist_tpu.analysis.locks import make_lock\n"
           "class R:\n"
           "    def __init__(self):\n"
           "        self._state = make_lock('registry.state')\n"
           "        self._versions = {}\n"
           "    def a(self, k, v):\n"
           "        with self._state:\n"
           "            self._versions[k] = v\n"
           "    def b(self, k):\n"
           "        with self._state:\n"
           "            del self._versions[k]\n"
           "    def c(self, k):\n"
           "        with self._state:\n"
           "            self._evict(k)\n"
           "    def _evict(self, k):\n"
           "        self._versions.pop(k, None)\n")
    assert _rules(src) == []


def test_dml010_init_and_single_site_exempt():
    """Constructors build unshared state; a field with fewer than two
    locked mutation sites has no inferred guard to violate."""
    src = ("from distributedmnist_tpu.analysis.locks import make_lock\n"
           "class R:\n"
           "    def __init__(self):\n"
           "        self._state = make_lock('s')\n"
           "        self._table = {}\n"       # init: exempt
           "    def a(self, k, v):\n"
           "        with self._state:\n"
           "            self._table[k] = v\n"  # one locked site only
           "    def c(self, k):\n"
           "        self._table.pop(k, None)\n")
    assert _rules(src) == []


def test_dml010_scope_is_serve_package_only():
    src = ("from distributedmnist_tpu.analysis.locks import make_lock\n"
           "class R:\n"
           "    def __init__(self):\n"
           "        self._state = make_lock('s')\n"
           "        self._t = {}\n"
           "    def a(self, k):\n"
           "        with self._state:\n"
           "            self._t[k] = 1\n"
           "    def b(self, k):\n"
           "        with self._state:\n"
           "            self._t[k] = 2\n"
           "    def c(self, k):\n"
           "        self._t[k] = 3\n")
    assert "DML010" in _rules(src)
    assert _rules(src, "serve.py") == []
    assert _rules(src, "distributedmnist_tpu/trainer.py") == []


# -- DML011: jit-cache-key hazards (ISSUE 11) ------------------------------


def test_dml011_default_device_flagged():
    src = ("import jax\n"
           "def warm(e):\n"
           "    with jax.default_device(jax.devices()[0]):\n"
           "        e.warmup()\n")
    assert _rules(src) == ["DML011"]
    f = lint.lint_source(src, SERVE_REL)[0]
    assert "thread-local" in f.message
    # bench.py and serve.py are in scope; training code is not
    assert _rules(src, "bench.py") == ["DML011"]
    assert _rules(src, "distributedmnist_tpu/trainer.py") == []


def test_dml011_config_update_spelling_flagged():
    src = ("import jax\n"
           "jax.config.update('jax_default_device', None)\n")
    assert _rules(src) == ["DML011"]


def test_dml011_mutable_static_default():
    src = ("import jax\n"
           "def f(x, buckets=[1, 2]):\n"
           "    return x\n"
           "g = jax.jit(f, static_argnames=('buckets',))\n")
    rules = _rules(src, "distributedmnist_tpu/serve/engine.py")
    # engine.py is DML005-exempt, so the jit itself is fine — only the
    # non-hashable static default fires
    assert rules == ["DML011"]


def test_dml011_mutable_literal_at_jitted_callsite():
    src = ("import jax\n"
           "def f(x, buckets=(1, 2)):\n"
           "    return x\n"
           "g = jax.jit(f, static_argnames=('buckets',))\n"
           "y = g(x, buckets=[1, 2])\n")
    rules = _rules(src, "distributedmnist_tpu/serve/engine.py")
    assert rules == ["DML011"]
    f = [x for x in lint.lint_source(
        src, "distributedmnist_tpu/serve/engine.py")][0]
    assert f.line == 5


def test_dml011_hashable_statics_clean():
    src = ("import jax\n"
           "def f(x, buckets=(1, 2)):\n"
           "    return x\n"
           "g = jax.jit(f, static_argnames=('buckets',))\n"
           "y = g(x, buckets=(1, 2))\n"
           "h = jax.jit(f, donate_argnums=1)\n")
    assert _rules(src, "distributedmnist_tpu/serve/engine.py") == []


# -- DML012: implicit host->device conversions (ISSUE 12) ------------------


def test_dml012_jnp_conversions_flagged_outside_staging():
    for call in ("jnp.asarray(rows)", "jnp.array(rows)",
                 "jax.device_put(rows)"):
        src = f"import jax\nimport jax.numpy as jnp\nx = {call}\n"
        assert _rules(src) == ["DML012"], call
    f = lint.lint_source("import jax.numpy as jnp\n"
                         "x = jnp.asarray(r)\n", SERVE_REL)[0]
    assert "staging" in f.message


def test_dml012_scope_staging_path_and_host_side_exempt():
    src = "import jax.numpy as jnp\nx = jnp.asarray(rows)\n"
    # the engine IS the staging path; quantize.py is build-time prep
    assert _rules(src, "distributedmnist_tpu/serve/engine.py") == []
    assert _rules(src, "distributedmnist_tpu/serve/quantize.py") == []
    # the trainer is not serving code; np.asarray is host-side and free
    assert _rules(src, "distributedmnist_tpu/trainer.py") == []
    assert _rules("import numpy as np\nx = np.asarray(rows)\n") == []


def test_dml012_pragma_allowlists_build_time_placement():
    src = ("import jax\n"
           "# lint: allow[DML012] build-time param placement\n"
           "p = jax.device_put(params)\n")
    assert _active_rules(src) == []


# -- DML013: weak-type literals at jitted call sites (ISSUE 12) ------------


def test_dml013_bare_literal_to_jitted_name():
    src = ("import jax\n"
           "g = jax.jit(f)\n"
           "y = g(x, 3.0)\n")
    ENGINE = "distributedmnist_tpu/serve/engine.py"
    assert _rules(src, ENGINE) == ["DML013"]
    f = lint.lint_source(src, ENGINE)[0]
    assert f.line == 3 and "weak-typed" in f.message
    # bench.py is in scope; training code is not
    assert _rules(src, "bench.py") == ["DML013"]
    assert _rules(src, "distributedmnist_tpu/trainer.py") == []


def test_dml013_jitted_attribute_call_site():
    src = ("import jax\n"
           "class E:\n"
           "    def __init__(self):\n"
           "        self._forward = jax.jit(f)\n"
           "    def run(self, p, x):\n"
           "        return self._forward(p, x, 255)\n")
    assert _rules(src,
                  "distributedmnist_tpu/serve/engine.py") == ["DML013"]


def test_dml013_static_args_and_arrays_clean():
    src = ("import jax\n"
           "import numpy as np\n"
           "g = jax.jit(f, static_argnums=(1,))\n"
           "h = jax.jit(f, static_argnames=('k',))\n"
           "y = g(x, 3)\n"                 # static: hashed, not traced
           "z = h(x, k=2.5)\n"             # static by name
           "w = g(x)\n"
           "v = g(x, np.float32(2.0))\n")  # committed np scalar
    assert _rules(src, "distributedmnist_tpu/serve/engine.py") == []


def test_dml013_only_jitted_names_flagged():
    assert _rules("y = plain(x, 3.0)\n") == []


def test_dml013_static_argnames_resolved_at_positional_site():
    # jax resolves static_argnames to POSITIONS via the wrapped
    # signature, so a literal passed positionally into a by-name
    # static param is hashed, not traced — must stay clean
    ENGINE = "distributedmnist_tpu/serve/engine.py"
    src = ("import jax\n"
           "def f(x, k):\n"
           "    return x\n"
           "g = jax.jit(f, static_argnames=('k',))\n"
           "y = g(x, 3)\n")
    assert _rules(src, ENGINE) == []
    # the same signature with the literal in the TRACED slot fires
    src2 = ("import jax\n"
            "def f(x, k):\n"
            "    return x\n"
            "g = jax.jit(f, static_argnames=('k',))\n"
            "y = g(3, k)\n")
    assert _rules(src2, ENGINE) == ["DML013"]


def test_dml013_unknown_signature_with_argnames_stays_quiet():
    # the wrapped signature is not locally visible and static_argnames
    # exists: a positional literal MAY be the static param, so the
    # positional site stays quiet (lint must not fail the gate on
    # correct code) — a non-static KEYWORD literal still fires
    src = ("import jax\n"
           "from m import f\n"
           "g = jax.jit(f, static_argnames=('k',))\n"
           "y = g(x, 3)\n"
           "z = g(x, n=4)\n")
    findings = lint.lint_source(src,
                                "distributedmnist_tpu/serve/engine.py")
    assert [f.rule for f in findings] == ["DML013"]
    assert findings[0].line == 5 and "n=" in findings[0].message


# -- DML014: failpoint coverage cross-check (ISSUE 12) ---------------------

FAULTS_REL = "distributedmnist_tpu/serve/faults.py"
# Synthetic declaration using REAL registry names (the declared set is
# parsed from THIS text, and real names keep the repo's own DML003
# spec-literal scan quiet about these fixtures).
FAULTS_SRC = ("KNOWN_FAILPOINTS = frozenset((\n"
              "    'engine.dispatch', 'engine.fetch', "
              "'batch.dispatch'))\n")


def test_dml014_uncovered_failpoint_flagged():
    texts = {FAULTS_REL: FAULTS_SRC,
             "tests/test_x.py": "POINT = 'engine.dispatch'\n",
             "bench.py": "spec = 'engine.fetch:p=1,count=2'\n"}
    findings = lint.check_failpoint_coverage(texts)
    assert [f.rule for f in findings] == ["DML014"]
    assert "batch.dispatch" in findings[0].message
    assert findings[0].path == FAULTS_REL and findings[0].line == 2


def test_dml014_weave_site_is_not_coverage():
    # the failpoint() call in serve/ is the WEAVE, not an exercise —
    # a name referenced only by its own call site stays uncovered
    texts = {FAULTS_REL: FAULTS_SRC,
             "distributedmnist_tpu/serve/x.py":
                 "failpoint('engine.dispatch')\n"
                 "failpoint('engine.fetch')\n"
                 "failpoint('batch.dispatch')\n"}
    findings = lint.check_failpoint_coverage(texts)
    assert sorted(f.rule for f in findings) == ["DML014"] * 3


def test_dml014_spec_fragments_in_fstrings_count():
    # the bench's concatenated/f-string chaos schedules cover their
    # names piece by piece (the chaos_fault_spec shape)
    texts = {FAULTS_REL: FAULTS_SRC,
             "bench.py":
                 "def spec(v):\n"
                 "    return ('batch.dispatch:mode=request,p=0.1;'\n"
                 "            f'engine.fetch:p=1,version={v}'\n"
                 "            f';engine.dispatch:p=1,after={v}')\n"}
    assert lint.check_failpoint_coverage(texts) == []


def test_dml014_clean_when_all_covered():
    texts = {FAULTS_REL: FAULTS_SRC,
             "tests/test_a.py": ("a = 'engine.dispatch'\n"
                                 "b = 'engine.fetch:p=0.5'\n"
                                 "c = 'batch.dispatch'\n")}
    assert lint.check_failpoint_coverage(texts) == []


def test_dml014_missing_faults_file_is_silent():
    assert lint.check_failpoint_coverage({"tests/t.py": "x = 1\n"}) == []


def test_dml014_lint_selftest_fixtures_are_not_coverage():
    # THIS file's own fixtures must spell real failpoint names (the
    # DML003 spec-literal scan forces that) — if they counted as
    # coverage, DML014 could never fire for exactly those names again
    texts = {FAULTS_REL: FAULTS_SRC,
             "tests/test_analysis_lint.py": ("a = 'engine.dispatch'\n"
                                             "b = 'engine.fetch:p=1'\n"
                                             "c = 'batch.dispatch'\n")}
    findings = lint.check_failpoint_coverage(texts)
    assert sorted(f.rule for f in findings) == ["DML014"] * 3


# -- DML015: dispatch outside the lane-deciding plumbing (ISSUE 14) --------


def test_dml015_direct_dispatch_outside_plumbing_flagged():
    """A serve/ module calling the engine surface directly bypasses
    the batcher's lane decision — metrics/trace/faults would silently
    skip that request path."""
    for call in ("self.engine.dispatch([x])",
                 "engine.dispatch_fast(x)",
                 "self.router.infer(x)"):
        src = f"def f(self, engine, x):\n    return {call}\n"
        assert _rules(src) == ["DML015"], call
    f = lint.lint_source("def f(e, x):\n    return e.dispatch(x)\n",
                         SERVE_REL)[0]
    assert "lane decision" in f.message


def test_dml015_plumbing_modules_and_non_serve_exempt():
    src = "def f(e, x):\n    return e.dispatch(x)\n"
    for rel in ("distributedmnist_tpu/serve/batcher.py",
                "distributedmnist_tpu/serve/router.py",
                "distributedmnist_tpu/serve/fleet.py",
                "distributedmnist_tpu/serve/engine.py",
                "tests/test_serve_engine.py", "bench.py", "serve.py"):
        assert _rules(src, rel) == [], rel


def test_dml015_registry_parity_gate_is_allowlisted():
    """The registry's parity-gate infer() calls are the sanctioned
    admin-path exception — present, and reason-allowlisted rather
    than invisible to the rule."""
    rel = "distributedmnist_tpu/serve/registry.py"
    path = os.path.join(lint.repo_root(), rel)
    text = open(path, encoding="utf-8").read()
    findings = lint.lint_source(text, rel)
    d15 = [f for f in findings if f.rule == "DML015"]
    assert d15, "expected the parity gate's infer() sites to be seen"
    active, allowed = lint.apply_allowlist(findings, text.splitlines())
    assert not [f for f in active if f.rule == "DML015"]
    assert all("parity" in f.allow_reason for f in allowed
               if f.rule == "DML015")


# -- DML017: declared tenancy-state containment (ISSUE 18) -----------------


def test_dml017_single_bare_mutation_flagged():
    """Unlike DML010's inference (which needs >= 2 locked sites to
    learn a guard), the tenancy fields are DECLARED guarded: one
    lock-free mutation site is a finding even with no locked sibling
    anywhere in the module."""
    src = ("class S:\n"
           "    def spend(self, t):\n"
           "        self._tokens[t][0] -= 1.0\n")
    assert _rules(src) == ["DML017"]
    f = lint.lint_source(src, SERVE_REL)[0]
    assert f.line == 3 and "tenancy.sched" in f.message


def test_dml017_condition_guard_and_helper_propagation_clean():
    """Mutations under the named condition are clean, including inside
    a helper whose every call site holds it (the _grant_locked
    shape)."""
    src = ("from distributedmnist_tpu.analysis.locks import "
           "make_condition\n"
           "class S:\n"
           "    def __init__(self):\n"
           "        self._cond = make_condition('tenancy.sched')\n"
           "        self._deficits = {}\n"
           "        self._cursor = 0\n"
           "    def grant(self, t):\n"
           "        with self._cond:\n"
           "            self._charge(t)\n"
           "            self._cursor += 1\n"
           "    def _charge(self, t):\n"
           "        self._deficits[t] = 0.0\n")
    assert _rules(src) == []


def test_dml017_init_exempt_and_serve_scope_only():
    """Constructor initialization is pre-publication; the rule applies
    in serve/ only (analysis/harnesses.py legitimately drives shadow
    state with the same attribute names)."""
    init_only = ("class S:\n"
                 "    def __init__(self):\n"
                 "        self._queues = {}\n"
                 "        self._granted = {}\n")
    assert _rules(init_only) == []
    bare = ("class S:\n"
            "    def f(self, t):\n"
            "        self._queues[t] = []\n")
    assert "DML017" in _rules(bare)
    for rel in ("distributedmnist_tpu/analysis/harnesses.py",
                "tests/test_serve_tenancy.py", "serve.py"):
        assert _rules(bare, rel) == [], rel


def test_dml017_every_declared_attr_covered():
    """The declared set matches the scheduler's documented contract —
    a mutation of ANY of the seven fields trips the rule."""
    for attr in sorted(lint._TENANCY_STATE_ATTRS):
        if attr == "_cursor":
            src = f"class S:\n    def f(self):\n        self.{attr} = 1\n"
        else:
            src = (f"class S:\n    def f(self, k):\n"
                   f"        self.{attr}[k] = 1\n")
        assert _rules(src) == ["DML017"], attr


# -- DML018: cluster-epoch promote-path containment (ISSUE 19) -------------


def test_dml018_bare_epoch_assignment_flagged():
    """Any assignment to a `_cluster_epoch` attribute outside the
    allowed writers is a finding — a second epoch writer bypasses the
    two-phase promote barrier."""
    src = ("class G:\n"
           "    def set_epoch(self, e):\n"
           "        self._cluster_epoch = e\n")
    assert _rules(src) == ["DML018"]
    f = lint.lint_source(src, SERVE_REL)[0]
    assert f.line == 3 and "promote_fanout" in f.message


def test_dml018_augmented_and_annotated_assign_flagged():
    aug = ("class G:\n"
           "    def bump(self):\n"
           "        self._cluster_epoch += 1\n")
    assert _rules(aug) == ["DML018"]
    ann = ("class G:\n"
           "    def fix(self, e):\n"
           "        self._cluster_epoch: int = e\n")
    assert _rules(ann) == ["DML018"]


def test_dml018_allowed_writers_clean():
    """Construction, the gateway's promote flip, and the worker-side
    receiving end are the ONLY legitimate epoch writers."""
    for fn in ("__init__", "__post_init__", "promote_fanout",
               "apply_cluster_epoch"):
        src = (f"class G:\n"
               f"    def {fn}(self):\n"
               f"        self._cluster_epoch = 0\n")
        assert _rules(src) == [], fn
    # module-level helper spelling of the worker receiving end (the
    # serve.py shape: apply_cluster_epoch(state, cache, epoch))
    helper = ("def apply_cluster_epoch(state, cache, epoch):\n"
              "    state._cluster_epoch = epoch\n")
    assert _rules(helper) == []


def test_dml018_nested_function_not_laundered():
    """A closure nested inside an allowed writer is still that nested
    function's own code path — the enclosing-name check uses the
    INNERMOST function, so promote_fanout cannot launder a deferred
    epoch write through a callback."""
    src = ("class G:\n"
           "    def promote_fanout(self):\n"
           "        def later(e):\n"
           "            self._cluster_epoch = e\n"
           "        return later\n")
    assert _rules(src) == ["DML018"]


def test_dml018_module_level_and_scope():
    """A module-level assignment is flagged; the rule applies to
    serve/ and serve.py only (tests legitimately build gateway doubles
    with epoch fields)."""
    top = "class G:\n    pass\ng = G()\ng._cluster_epoch = 3\n"
    assert _rules(top) == ["DML018"]
    assert "module level" in lint.lint_source(top, SERVE_REL)[0].message
    bare = ("class G:\n"
            "    def poke(self, e):\n"
            "        self._cluster_epoch = e\n")
    assert _rules(bare, "serve.py") == ["DML018"]
    for rel in ("tests/test_serve_gateway.py", "bench.py",
                "distributedmnist_tpu/analysis/harnesses.py"):
        assert _rules(bare, rel) == [], rel


def test_dml018_real_promote_path_is_clean():
    """The shipped gateway + worker epoch paths pass their own rule
    (the repo-at-HEAD gate covers this too; asserting directly keeps
    the failure local if either file grows a stray writer)."""
    root = lint.repo_root()
    for rel in ("distributedmnist_tpu/serve/gateway.py", "serve.py"):
        with open(os.path.join(root, rel)) as fh:
            src = fh.read()
        found = [f.rule for f in lint.lint_source(src, rel)
                 if f.rule == "DML018"]
        assert found == [], rel


# -- DML019: autoscale actuation containment (ISSUE 20) --------------------


def test_dml019_bare_actuation_call_flagged():
    """Any apply_scale/add_worker/drain_worker call outside an
    Actuator's scale_to is a finding — a second actuation writer races
    the control loop's decisions and un-prices its accounting."""
    src = ("class H:\n"
           "    def widen(self, b):\n"
           "        b.apply_scale(window=4)\n")
    assert _rules(src) == ["DML019"]
    f = lint.lint_source(src, SERVE_REL)[0]
    assert f.line == 3 and "scale_to" in f.message


def test_dml019_every_fenced_call_covered():
    for attr in sorted(lint._ACTUATION_CALLS):
        src = (f"class H:\n"
               f"    def go(self, g):\n"
               f"        g.{attr}(1)\n")
        assert _rules(src) == ["DML019"], attr


def test_dml019_allowed_caller_clean():
    """scale_to — the actuator interface both implementations live
    behind — is the ONE legitimate caller."""
    src = ("class A:\n"
           "    def scale_to(self, u):\n"
           "        self._batcher.apply_scale(window=u)\n"
           "        self._gateway.add_worker(u)\n"
           "        self._gateway.drain_worker(u)\n")
    assert _rules(src) == []


def test_dml019_nested_function_not_laundered():
    """A closure nested inside scale_to is its own code path — the
    enclosing-name check uses the INNERMOST function, so scale_to
    cannot launder a deferred actuation through a callback."""
    src = ("class A:\n"
           "    def scale_to(self, u):\n"
           "        def later():\n"
           "            self._batcher.apply_scale(window=u)\n"
           "        return later\n")
    assert _rules(src) == ["DML019"]


def test_dml019_module_level_and_scope():
    """A module-level call is flagged; the rule applies to serve/ and
    serve.py only (tests legitimately drive fakes through the raw
    methods, and the batcher's own DEFINITION is not a call)."""
    top = "import b\nb.batcher.apply_scale(window=2)\n"
    assert _rules(top) == ["DML019"]
    bare = ("class H:\n"
            "    def poke(self, g):\n"
            "        g.drain_worker('w1')\n")
    assert _rules(bare, "serve.py") == ["DML019"]
    for rel in ("tests/test_serve_autoscale.py", "bench.py",
                "distributedmnist_tpu/analysis/harnesses.py"):
        assert _rules(bare, rel) == [], rel
    # defining apply_scale (the actuation surface itself) is not a call
    defn = ("class B:\n"
            "    def apply_scale(self, window=None):\n"
            "        return {'window': window}\n")
    assert _rules(defn) == []


def test_dml019_real_actuation_paths_are_clean():
    """The shipped actuator/batcher/gateway paths pass their own rule
    (the repo-at-HEAD gate covers this too; asserting directly keeps
    the failure local if a second actuation writer lands)."""
    root = lint.repo_root()
    for rel in ("distributedmnist_tpu/serve/autoscale.py",
                "distributedmnist_tpu/serve/batcher.py",
                "distributedmnist_tpu/serve/gateway.py", "serve.py"):
        with open(os.path.join(root, rel)) as fh:
            src = fh.read()
        found = [f.rule for f in lint.lint_source(src, rel)
                 if f.rule == "DML019"]
        assert found == [], rel


# -- allowlist pragma ------------------------------------------------------


def test_pragma_with_reason_suppresses():
    src = ("import time\n"
           "t = time.time()  # lint: allow[DML004] wall stamp for humans\n")
    assert _active_rules(src) == []
    findings = lint.lint_source(src, SERVE_REL)
    _, allowed = lint.apply_allowlist(findings, src.splitlines())
    assert allowed and allowed[0].allow_reason == "wall stamp for humans"


def test_pragma_on_preceding_line_suppresses():
    src = ("import time\n"
           "# lint: allow[DML004] wall stamp\n"
           "t = time.time()\n")
    assert _active_rules(src) == []


def test_pragma_without_reason_does_not_suppress():
    src = "import time\nt = time.time()  # lint: allow[DML004]\n"
    assert _active_rules(src) == ["DML004"]


def test_pragma_wrong_rule_does_not_suppress():
    src = ("import time\n"
           "t = time.time()  # lint: allow[DML001] wrong rule id\n")
    assert _active_rules(src) == ["DML004"]


# -- the repo gate ---------------------------------------------------------


def test_repo_at_head_is_clean():
    """The acceptance criterion: zero active findings over the repo
    (pre-existing violations are fixed or reason-allowlisted)."""
    active, allowed = lint.lint_paths(lint.repo_root())
    assert not active, "\n".join(f.format() for f in active)
    # ... and every allowlisted finding carries a reason
    assert all(f.allow_reason for f in allowed)


def test_cli_contract():
    """`python -m distributedmnist_tpu.analysis` exits 0 at HEAD and
    prints the summary; --list-rules names every rule."""
    r = subprocess.run([sys.executable, "-m",
                        "distributedmnist_tpu.analysis"],
                       capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "0 finding(s)" in r.stderr
    r2 = subprocess.run([sys.executable, "-m",
                         "distributedmnist_tpu.analysis", "--list-rules"],
                        capture_output=True, text=True, timeout=120)
    assert r2.returncode == 0
    for rule in lint.RULES:
        assert rule in r2.stdout


def test_cli_exits_nonzero_on_findings(tmp_path):
    """The exit-code contract scripts/lint.sh relies on: findings -> 1."""
    pkg = tmp_path / "distributedmnist_tpu" / "serve"
    pkg.mkdir(parents=True)
    (pkg / "bad.py").write_text("import threading\n"
                                "lock = threading.Lock()\n")
    r = subprocess.run([sys.executable, "-m",
                        "distributedmnist_tpu.analysis", "--root",
                        str(tmp_path)],
                       capture_output=True, text=True, timeout=120)
    assert r.returncode == 1
    assert "DML001" in r.stdout
