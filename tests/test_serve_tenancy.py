"""Multi-tenant, multi-model serving (ISSUE 18): the tenant spec
parser, the pure token-bucket/EDF/DRR policy pieces (with the
ISSUE-required deterministic deficit-accounting walk), the
GlobalScheduler's admission front door (429 quota + Retry-After, 503
watermark, 504 at-the-door deadline, the cache-aware shed), the admin
surface, WFQ fairness over stub models, queue.wait tenant attribution
in traces, and the two-model CPU catalog's zero-steady-state-recompile
contract with real engines."""

import threading
import time
import types

import numpy as np
import pytest

from distributedmnist_tpu.serve import (DynamicBatcher, Rejected,
                                        ServeMetrics,
                                        prometheus_exposition)
from distributedmnist_tpu.serve import scheduler as policy
from distributedmnist_tpu.serve import trace as trace_lib
from distributedmnist_tpu.serve.resilience import DeadlineExceeded
from distributedmnist_tpu.serve.tenancy import (CatalogEntry,
                                                GlobalScheduler,
                                                ModelCatalog,
                                                QuotaExceeded, SLOClass,
                                                parse_tenants,
                                                token_admit)
from tests.test_serve_batcher import StubEngine, _rows

pytestmark = pytest.mark.tenant


# -- tenant spec parsing ---------------------------------------------------


def test_parse_tenants_full_spec():
    classes = parse_tenants(
        "gold:qps=100,burst=8,deadline_ms=50,weight=4,model=lenet;"
        "free:weight=1")
    assert set(classes) == {"gold", "free", "default"}
    g = classes["gold"]
    assert (g.qps, g.burst, g.deadline_ms, g.weight, g.model) == \
        (100.0, 8.0, 50.0, 4.0, "lenet")
    # the synthesized default class: unlimited, best-effort, weight 1
    d = classes["default"]
    assert d.qps is None and d.deadline_ms is None and d.weight == 1.0


def test_parse_tenants_default_overridable_and_empty_spec():
    classes = parse_tenants("default:qps=5,weight=2")
    assert classes["default"].qps == 5.0
    assert parse_tenants("")["default"].qps is None


@pytest.mark.parametrize("spec", [
    ":qps=1",                       # empty name
    "a:nope=1",                     # unknown key
    "a:qps",                        # not k=v
    "a:qps=1;a:qps=2",              # duplicate tenant
    "a:qps=0",                      # SLOClass validation: qps > 0
    "a:burst=0.5",                  # burst >= 1
    "a:deadline_ms=0",              # deadline > 0
    "a:weight=0",                   # weight > 0
])
def test_parse_tenants_rejects_malformed(spec):
    with pytest.raises(ValueError):
        parse_tenants(spec)


# -- pure admission / scheduling policy ------------------------------------


def test_token_bucket_admission_math():
    # no rate -> inert: always admitted, nothing charged
    assert token_admit(0.0, 0.0, 100.0, None, 1.0) == (True, 0.0, 0.0)
    # refill at qps, capped at burst, one token per admission
    ok, tokens, retry = token_admit(0.0, 0.0, 10.0, 2.0, 4.0)
    assert ok and tokens == 3.0 and retry == 0.0    # capped at burst=4
    # an empty bucket refuses and quotes the EXACT refill time
    ok, tokens, retry = token_admit(0.25, 0.0, 0.0, 2.0, 4.0)
    assert not ok and tokens == 0.25
    assert retry == pytest.approx((1.0 - 0.25) / 2.0)


def test_drr_deterministic_deficit_accounting():
    """The ISSUE-required deterministic walk: fixed ring, weights 2:1,
    quantum 1s, equal 3s head costs — grant order, per-visit credit,
    post-charge balances and the rounds counter are all exact."""
    ring = ["a", "b"]
    weights = {"a": 2.0, "b": 1.0}
    deficits = {"a": 0.0, "b": 0.0}
    heads = {"a": 3.0, "b": 3.0}
    # cursor=0 (= "a" granted last), so the scan starts at "b":
    # round 0 credits b->1, a->2 (neither affords 3); round 1 credits
    # b->2, then a->4 >= 3: grant "a" after 1 full extra round
    flow, cursor, rounds = policy.drr_grant(ring, 0, deficits, weights,
                                            1.0, heads)
    assert (flow, cursor, rounds) == ("a", 0, 1)
    assert deficits == {"a": 4.0, "b": 2.0}
    policy.drr_charge(deficits, "a", 3.0)
    assert deficits == {"a": 1.0, "b": 2.0}
    # next scan starts at "b", whose banked 2 + 1 credit covers it
    flow, cursor, rounds = policy.drr_grant(ring, cursor, deficits,
                                            weights, 1.0, heads)
    assert (flow, cursor, rounds) == ("b", 1, 0)
    policy.drr_charge(deficits, "b", 3.0)
    assert deficits == {"a": 1.0, "b": 0.0}
    # and "a" again: 1 banked + 2 credit = 3 covers its head
    flow, cursor, rounds = policy.drr_grant(ring, cursor, deficits,
                                            weights, 1.0, heads)
    assert (flow, cursor, rounds) == ("a", 0, 0)
    # an idle flow's balance resets (no hoarding while absent)
    deficits["b"] = 7.5
    policy.drr_grant(ring, 0, deficits, weights, 1.0, {"a": 1.0})
    assert deficits["b"] == 0.0
    # charge clamps at zero (a re-priced run must not double-punish)
    policy.drr_charge(deficits, "a", 1e9)
    assert deficits["a"] == 0.0


def test_drr_converges_to_weight_share_and_respects_skip_bound():
    ring = ["heavy", "light"]
    weights = {"heavy": 1.0, "light": 2.0}
    deficits = {"heavy": 0.0, "light": 0.0}
    heads = {"heavy": 3.0, "light": 3.0}     # both always backlogged
    bound = policy.drr_skip_bound(2, 3.0, 1.0, 1.0)
    assert bound == 2 * (3 + 1)
    grants = {"heavy": 0, "light": 0}
    skips = {"heavy": 0, "light": 0}
    cursor = 0
    for _ in range(90):
        flow, cursor, _ = policy.drr_grant(ring, cursor, deficits,
                                           weights, 1.0, heads)
        policy.drr_charge(deficits, flow, heads[flow])
        grants[flow] += 1
        skips[flow] = 0
        other = "light" if flow == "heavy" else "heavy"
        skips[other] += 1
        assert skips[other] <= bound
    # equal costs: the grant ratio IS the weight ratio
    assert grants["light"] / grants["heavy"] == pytest.approx(2.0,
                                                              rel=0.1)


def test_edf_pick_orders_and_sheds():
    now = 10.0
    # earliest FEASIBLE deadline wins; best-effort ranks last
    pick, infeasible = policy.edf_pick(
        [("be", None, 0.01), ("late", now + 5.0, 0.01),
         ("soon", now + 1.0, 0.01)], now)
    assert pick == "soon" and infeasible == []
    # a head that cannot make its deadline even now is shed, not picked
    pick, infeasible = policy.edf_pick(
        [("doomed", now + 0.005, 0.02), ("ok", now + 5.0, 0.01)], now)
    assert pick == "ok" and infeasible == ["doomed"]
    # nothing feasible and nothing best-effort: (None, all of them)
    pick, infeasible = policy.edf_pick([("x", now + 0.001, 1.0)], now)
    assert pick is None and infeasible == ["x"]
    # best-effort is always feasible — it absorbs an all-doomed ring
    pick, _ = policy.edf_pick([("x", now + 0.001, 1.0),
                               ("be", None, 1.0)], now)
    assert pick == "be"


# -- GlobalScheduler over stub models --------------------------------------


class _FakeRouter:
    """Router-shaped double for CatalogEntry: statically live, no cost
    table (the scheduler prices by the 1 ms/row default)."""

    def __init__(self):
        self._as_images = StubEngine._as_images

    def live_version(self):
        return "v1"

    def live_infer_dtype(self):
        return "float32"

    def bucket_costs(self):
        return {}


def _stub_entry(name, cache=None, max_wait_us=200):
    eng = StubEngine(max_batch=16)
    batcher = DynamicBatcher(eng, max_wait_us=max_wait_us,
                             queue_depth=4096).start()
    return CatalogEntry(
        name=name, registry=None, router=_FakeRouter(),
        factory=types.SimpleNamespace(buckets=eng.buckets,
                                      max_batch=eng.max_batch),
        batcher=batcher, cache=cache)


def _stub_sched(spec, entries=("mlp",), caches=None, start=True,
                metrics=None, **kw):
    catalog = ModelCatalog()
    for name in entries:
        catalog.add(_stub_entry(name,
                                cache=(caches or {}).get(name)))
    sched = GlobalScheduler(catalog, parse_tenants(spec),
                            metrics=metrics, quantum_s=0.005, **kw)
    return sched.start() if start else sched


def test_quota_shed_raises_429_with_retry_after(rng):
    metrics = ServeMetrics()
    sched = _stub_sched("gold:qps=10,burst=1", metrics=metrics)
    try:
        fut = sched.submit(_rows(rng, 2), tenant="gold")
        assert fut.result(timeout=10).shape == (2, 10)
        with pytest.raises(QuotaExceeded) as ei:
            sched.submit(_rows(rng, 2), tenant="gold")
        # the bucket quotes WHEN a token exists, not just "go away"
        assert 0.0 < ei.value.retry_after_s <= 0.1
        assert ei.value.status == 429
    finally:
        sched.stop()
    bt = metrics.snapshot()["by_tenant"]["gold"]
    assert bt["quota_sheds"] == 1 and bt["requests"] == 1


def test_unknown_tenant_collapses_into_default(rng):
    sched = _stub_sched("gold:qps=100")
    try:
        fut = sched.submit(_rows(rng, 1), tenant="nobody-configured")
        assert fut.result(timeout=10).shape == (1, 10)
        snap = sched.snapshot()
        assert snap["tenants"]["default"]["granted_rows"] == 1
        assert "nobody-configured" not in snap["tenants"]
    finally:
        sched.stop()


def test_watermark_shed_raises_503(rng):
    metrics = ServeMetrics()
    # not started: submits park in the tenant queue so the watermark
    # is hit deterministically, without racing the grant loop
    sched = _stub_sched("default:qps=1000,burst=64", start=False,
                        metrics=metrics, tenant_queue_rows=4)
    try:
        sched.submit(_rows(rng, 3))
        with pytest.raises(Rejected, match="watermark"):
            sched.submit(_rows(rng, 3))
    finally:
        sched.stop(drain=False)
    assert metrics.snapshot()["by_tenant"]["default"][
        "watermark_sheds"] == 1


def test_expired_deadline_shed_504_at_the_door(rng):
    metrics = ServeMetrics()
    sched = _stub_sched("default:", start=False, metrics=metrics)
    try:
        with pytest.raises(DeadlineExceeded, match="expired"):
            sched.submit(_rows(rng, 2),
                         deadline_s=time.monotonic() - 0.01)
    finally:
        sched.stop(drain=False)
    assert metrics.snapshot()["by_tenant"]["default"][
        "deadline_sheds"] == 1


def test_cache_aware_shed_serves_hit_instead_of_429(rng):
    """The ISSUE 18 satellite: an over-quota request whose answer is
    already cached is SERVED (zero device work), never 429'd — and the
    probe of an over-quota miss counts no cache miss."""
    from distributedmnist_tpu.serve.cache import (PredictionCache,
                                                  content_key)

    cache = PredictionCache(capacity=16)
    metrics = ServeMetrics()
    sched = _stub_sched("gold:qps=10,burst=1", caches={"mlp": cache},
                        metrics=metrics)
    x = _rows(rng, 2)
    logits = np.arange(20.0).reshape(2, 10)
    cache.insert(content_key("v1", "float32",
                             StubEngine._as_images(x)),
                 logits, "v1", "float32")
    try:
        # burn the single token
        sched.submit(_rows(rng, 1), tenant="gold").result(timeout=10)
        misses_before = cache.stats()["misses"]
        # over quota + cached -> served from the probe, no exception
        fut = sched.submit(x, tenant="gold")
        np.testing.assert_array_equal(fut.result(timeout=1), logits)
        # over quota + NOT cached -> still a 429, and the probe's miss
        # was not counted against the cache's hit ratio
        with pytest.raises(QuotaExceeded):
            sched.submit(_rows(rng, 2), tenant="gold")
        assert cache.stats()["misses"] == misses_before
    finally:
        sched.stop()
    bt = metrics.snapshot()["by_tenant"]["gold"]
    assert bt["cache_hits"] == 1 and bt["quota_sheds"] == 1


def test_admin_set_quota_live_and_snapshot_shape(rng):
    sched = _stub_sched("gold:qps=10,burst=1;free:weight=2")
    try:
        sched.submit(_rows(rng, 1), tenant="gold").result(timeout=10)
        with pytest.raises(QuotaExceeded):
            sched.submit(_rows(rng, 1), tenant="gold")
        # loosen live: the bucket refills to the NEW burst immediately
        cls = sched.set_quota("gold", qps=1000.0, burst=8.0)
        assert (cls.qps, cls.burst) == (1000.0, 8.0)
        for _ in range(4):
            sched.submit(_rows(rng, 1), tenant="gold")
        with pytest.raises(KeyError):
            sched.set_quota("nobody", qps=1.0)
        snap = sched.snapshot()
        assert set(snap["tenants"]) == {"gold", "free", "default"}
        for t in snap["tenants"].values():
            for k in ("qps", "burst", "weight", "queued_rows",
                      "granted_rows", "deficit_s",
                      "consecutive_skips"):
                assert k in t
        assert snap["models"]["mlp"]["resident"] is True
        assert snap["max_skip_observed"] >= 0
    finally:
        sched.stop()


def test_wfq_grant_shares_track_weights(rng):
    """Two always-backlogged tenants at weights 2:1 over stub models:
    granted-row shares land near the weight shares and the observed
    consecutive-skip maximum respects the closed-form bound."""
    metrics = ServeMetrics()
    sched = _stub_sched(
        "light:qps=10000,burst=256,weight=2,model=mlp;"
        "heavy:qps=10000,burst=256,weight=1,model=lenet",
        entries=("mlp", "lenet"), metrics=metrics)
    try:
        futs = []
        for _ in range(30):
            futs.append(sched.submit(_rows(rng, 2), tenant="light"))
            futs.append(sched.submit(_rows(rng, 2), tenant="heavy"))
        for f in futs:
            assert f.result(timeout=30).shape == (2, 10)
    finally:
        sched.stop()
    snap = sched.snapshot()
    light, heavy = snap["tenants"]["light"], snap["tenants"]["heavy"]
    assert light["granted_rows"] == heavy["granted_rows"] == 60
    bound = policy.drr_skip_bound(
        3, 0.016, sched.quantum_s,
        min(c.weight for c in sched.classes().values()))
    assert snap["max_skip_observed"] <= bound
    # the fairness ratio's numerator lands in the metrics too
    bt = metrics.snapshot()["by_tenant"]
    assert bt["light"]["dispatched_rows"] == 60
    assert bt["light"]["dispatch_share"] == pytest.approx(0.5)


def test_queue_wait_span_carries_tenant_tag(rng):
    """The scheduler stamps {tenant, model} on every forwarded request;
    the batcher's queue.wait span (and the dispatch span) surface them
    so a trace answers WHO waited, not just how long."""
    trace_lib.uninstall()
    tracer = trace_lib.install(trace_lib.Tracer(capacity=16,
                                                sample=1.0))
    sched = _stub_sched("gold:qps=100,burst=8")
    try:
        sched.submit(_rows(rng, 2), tenant="gold").result(timeout=10)
    finally:
        sched.stop()
        trace_lib.uninstall()
    spans = [s for t in tracer.traces() for s in t["spans"]]
    waits = [s for s in spans if s["name"] == "queue.wait"]
    assert waits and all(
        s["tags"].get("tenant") == "gold" and
        s["tags"].get("model") == "mlp" for s in waits)


def test_prometheus_tenant_and_model_series(rng):
    metrics = ServeMetrics()
    sched = _stub_sched("gold:qps=10,burst=1,deadline_ms=5000",
                        metrics=metrics)
    try:
        sched.submit(_rows(rng, 2), tenant="gold").result(timeout=10)
        with pytest.raises(QuotaExceeded):
            sched.submit(_rows(rng, 1), tenant="gold")
    finally:
        sched.stop()
    text = prometheus_exposition(metrics.snapshot())
    assert 'dmnist_serve_tenant_requests_total{tenant="gold"} 1' in text
    assert ('dmnist_serve_tenant_sheds_total{kind="quota",'
            'tenant="gold"} 1') in text
    assert 'dmnist_serve_model_requests_total{model="mlp"} 1' in text
    assert ('dmnist_serve_tenant_latency_ms{quantile="0.99",'
            'tenant="gold"}') in text


def test_scheduler_refuses_bad_boot():
    catalog = ModelCatalog()
    catalog.add(_stub_entry("mlp"))
    try:
        with pytest.raises(KeyError):     # class routed to a model the
            GlobalScheduler(              # catalog does not hold
                catalog, parse_tenants("a:model=nope"))
        with pytest.raises(ValueError, match="quantum"):
            GlobalScheduler(catalog, parse_tenants(""), quantum_s=0.0)
    finally:
        catalog.stop(drain=False)


def test_submit_after_stop_refused(rng):
    sched = _stub_sched("default:")
    sched.stop()
    with pytest.raises(RuntimeError, match="stopped"):
        sched.submit(_rows(rng, 1))


# -- the two-model catalog with real engines -------------------------------


def test_two_model_catalog_zero_steady_state_recompiles(rng):
    """The ISSUE 18 acceptance contract: MLP and LeNet resident in ONE
    process, tenant traffic interleaved across both through the global
    scheduler, and — after each model's own warmup — exactly zero
    compile events while serving. Per-tenant and per-model accounting
    land in the metrics and the admin snapshot."""
    from distributedmnist_tpu.config import Config
    from distributedmnist_tpu.serve.tenancy import build_tenancy
    from distributedmnist_tpu.utils import CompileCounter

    cfg = Config(device="cpu", num_devices=8, synthetic=True,
                 model="mlp", serve_models="mlp,lenet",
                 serve_tenants=("light:qps=10000,burst=256,weight=2,"
                                "model=mlp;"
                                "heavy:qps=10000,burst=256,weight=1,"
                                "model=lenet"),
                 serve_max_batch=16, serve_max_wait_us=500,
                 log_every=0)
    metrics = ServeMetrics()
    catalog, sched = build_tenancy(cfg, metrics=metrics)
    try:
        for name in catalog.names():       # eager residency, as serve.py
            catalog.ensure_live(name, seed=cfg.seed)
        assert catalog.names() == ["mlp", "lenet"]
        assert all(e.resident() for e in catalog.entries())
        before = CompileCounter.instance().snapshot()
        futs = []
        for n in (1, 3, 7, 8, 12, 16, 5, 2) * 2:
            futs.append((n, sched.submit(_rows(rng, n),
                                         tenant="light")))
            futs.append((n, sched.submit(_rows(rng, n),
                                         tenant="heavy")))
        for n, f in futs:
            assert f.result(timeout=120).shape == (n, 10)
    finally:
        sched.stop()
    assert CompileCounter.instance().snapshot() - before == 0, (
        "steady-state tenant traffic recompiled — a bucket escaped "
        "the per-model warmup")
    snap = sched.snapshot()
    rows = sum(n for n, _ in futs) // 2
    assert snap["tenants"]["light"]["granted_rows"] == rows
    assert snap["tenants"]["heavy"]["granted_rows"] == rows
    assert snap["models"]["mlp"]["live_version"] == "v1"
    assert snap["models"]["lenet"]["live_version"] == "v1"
    s = metrics.snapshot()
    assert s["by_model"]["mlp"]["dispatched_rows"] == rows
    assert s["by_model"]["lenet"]["dispatched_rows"] == rows
    assert s["by_tenant"]["light"]["dispatch_share"] == \
        pytest.approx(0.5)


def test_scheduled_warm_path_boots_cold_model(rng):
    """A submit routed at a COLD model does not fail: the scheduler
    prices the warmup, schedules it on the warm thread, and dispatches
    once the model is live — best-effort heads just wait."""
    from distributedmnist_tpu.config import Config
    from distributedmnist_tpu.serve.tenancy import build_tenancy

    cfg = Config(device="cpu", num_devices=8, synthetic=True,
                 model="mlp", serve_models="mlp",
                 serve_tenants="", serve_max_batch=16,
                 serve_max_wait_us=500, log_every=0)
    catalog, sched = build_tenancy(cfg)
    try:
        assert not catalog.get("mlp").resident()
        fut = sched.submit(_rows(rng, 4))        # cold-model submit
        assert fut.result(timeout=120).shape == (4, 10)
        assert catalog.get("mlp").resident()
        assert sched.snapshot()["warming"] == []
    finally:
        sched.stop()
