"""serve/cache.py (ISSUE 10): the prediction cache + single-flight
front layer and the batcher's intra-batch dedup.

Covers the LRU/eviction/invalidation contract, single-flight collapse
(N concurrent identical requests -> exactly ONE engine dispatch,
asserted on the engine call log, with a stub AND a real engine), the
leader-failure semantics (followers share the leader's error, errors
are never cached), the invalidation races the stale-hit guarantee
hangs on (promote/rollback/dtype-activation concurrent with lookups
and an in-flight leader — version captured at insert, checked at
read), cache-hit observability (metrics populations + trace exemplars
are never skipped on the fast path), and the dedup fan-out. Every test
runs under the conftest serve sanitizer, so the new cache.state lock's
ordering edges are audited on every run."""

import threading
import time

import numpy as np
import pytest

from distributedmnist_tpu.serve import (DynamicBatcher, ServeMetrics,
                                        content_key)
from distributedmnist_tpu.serve import trace as trace_lib
from distributedmnist_tpu.serve.cache import CacheFront, PredictionCache
from distributedmnist_tpu.serve.resilience import DeadlineExceeded
from tests.test_serve_batcher import StubEngine

pytestmark = pytest.mark.cache


class StubRouter(StubEngine):
    """Router-shaped StubEngine: a flippable live route, version-tagged
    handles, and fetch() results that ENCODE the computing version (an
    offset per version), so a stale-version byte served under a fresh
    version tag is detectable by value."""

    OFFSETS = {"v1": 0.0, "v2": 1000.0}

    def __init__(self, **kw):
        super().__init__(**kw)
        self._route = ("v1", "float32")
        self._route_lock = threading.Lock()

    def set_live_route(self, version, infer_dtype="float32"):
        with self._route_lock:
            self._route = (version, infer_dtype)

    def live_version(self):
        return self._route[0]

    def live_infer_dtype(self):
        return self._route[1]

    def live_route(self):
        with self._route_lock:
            return self._route

    def dispatch(self, x):
        h = super().dispatch(x)
        with self._route_lock:
            h.version, h.infer_dtype = self._route
        return h

    def fetch(self, handle):
        out = super().fetch(handle)
        return out + self.OFFSETS.get(handle.version, 0.0)


def _rows(rng, n):
    return rng.integers(0, 256, (n, 28, 28, 1)).astype(np.uint8)


def _front(router, metrics=None, capacity=64, dedup=True, **batcher_kw):
    b = DynamicBatcher(router, max_wait_us=1000, queue_depth=1024,
                       metrics=metrics, dedup=dedup,
                       **batcher_kw).start()
    cache = PredictionCache(capacity)
    return CacheFront(b, router, cache, metrics=metrics), b, cache


# -- PredictionCache unit contract -----------------------------------------


def test_content_key_identity():
    rng = np.random.default_rng(0)
    x = _rows(rng, 3)
    k1 = content_key("v1", "float32", x)
    k2 = content_key("v1", "float32", x.copy())
    assert k1 == k2                       # same bytes, same key
    assert content_key("v2", "float32", x) != k1     # version in key
    assert content_key("v1", "int8", x) != k1        # dtype in key
    y = x.copy()
    y[0, 0, 0, 0] ^= 1
    assert content_key("v1", "float32", y) != k1     # content in key


def test_lru_bounds_evictions_and_recency():
    rng = np.random.default_rng(1)
    c = PredictionCache(capacity=3)
    xs = [_rows(rng, 1) for _ in range(4)]
    keys = [content_key("v1", None, x) for x in xs]
    logits = [np.full((1, 10), float(i)) for i in range(4)]
    for k, lg in zip(keys[:3], logits[:3]):
        assert c.insert(k, lg, "v1", None)
    # touch key 0 so key 1 is the LRU victim
    assert c.lookup(keys[0]) is not None
    assert c.insert(keys[3], logits[3], "v1", None)
    st = c.stats()
    assert st["entries"] == 3 and st["evictions"] == 1
    assert c.lookup(keys[1]) is None      # evicted (least recent)
    assert c.lookup(keys[0]) is not None  # refreshed survivor
    # a hit returns a COPY: mutating it must not corrupt the cache
    got = c.lookup(keys[0])
    got[:] = -1.0
    assert float(c.lookup(keys[0])[0, 0]) == 0.0


def test_insert_checks_computing_version_and_epoch():
    """Version captured at insert, checked there AND at read: a result
    computed by a version other than the key's (canary, mid-promote
    race) is refused; so is an insert whose flight predates an
    invalidation epoch bump."""
    rng = np.random.default_rng(2)
    key = content_key("v1", None, _rows(rng, 1))
    c = PredictionCache(capacity=4)
    assert not c.insert(key, np.zeros((1, 10)), "v2", None)
    assert c.stats()["stale_drops"] == 1 and c.stats()["entries"] == 0
    epoch = c.epoch()
    c.invalidate("promote")
    assert not c.insert(key, np.zeros((1, 10)), "v1", None, epoch=epoch)
    assert c.stats()["stale_drops"] == 2
    assert c.insert(key, np.zeros((1, 10)), "v1", None, epoch=c.epoch())
    assert c.stats()["entries"] == 1
    c.invalidate("rollback")
    st = c.stats()
    assert st["entries"] == 0 and st["invalidations"] == 2


def test_capacity_validated():
    with pytest.raises(ValueError, match="capacity"):
        PredictionCache(capacity=0)


# -- front layer: hit / miss / observability -------------------------------


def test_hit_serves_without_second_dispatch_and_is_byte_identical(rng):
    eng = StubRouter(max_batch=16)
    m = ServeMetrics()
    front, b, cache = _front(eng, metrics=m)
    try:
        x = _rows(rng, 3)
        first = front.submit(x).result(timeout=10)
        hit_fut = front.submit(x)
        got = hit_fut.result(timeout=10)
        assert got.tobytes() == first.tobytes()
        assert eng.calls == [3]            # ONE dispatch, the miss's
        assert hit_fut.version == "v1"     # hits stay version-tagged
        st = cache.stats()
        assert st["hits"] == 1 and st["misses"] == 1
        assert st["hit_ratio"] == 0.5
        # observability satellite: the hit recorded the SAME
        # populations a computed response gets — global requests,
        # per-version, per-dtype — plus the cache-served split
        snap = m.snapshot()
        assert snap["requests"] == 2
        assert snap["by_version"]["v1"]["requests"] == 2
        assert snap["by_dtype"]["float32"]["rows"] >= 3
        assert snap["cache_served"]["hit_requests"] == 1
    finally:
        b.stop()


def test_cache_hit_never_skips_tracing_and_over_slo_hits_are_exemplars(
        rng):
    """A hit must carry X-Trace-Id (trace_id on the future), finish its
    trace with cache.lookup/cache.hit spans, and — when over SLO —
    land in the exemplar ring like any other slow request."""
    tracer = trace_lib.install(trace_lib.Tracer(slo_ms=1e-6, seed=5))
    eng = StubRouter(max_batch=16)
    front, b, cache = _front(eng)
    try:
        x = _rows(rng, 2)
        front.submit(x).result(timeout=10)
        hit_fut = front.submit(x)
        hit_fut.result(timeout=10)
        assert hit_fut.trace_id is not None
        snap = tracer.snapshot()
        assert snap["requests_finished"] >= 2
        assert snap["open_spans"] == 0
        # an slo of 1 ns makes every request an exemplar — the hit
        # trace is retained and carries its cache spans
        hits = [t for t in tracer.traces()
                if any(s["name"] == "cache.hit" for s in t["spans"])]
        assert hits, "cache-hit trace was not retained"
        names = {s["name"] for s in hits[-1]["spans"]}
        assert {"request", "cache.lookup", "cache.hit"} <= names
        assert hits[-1]["over_slo"] is True
        # the stage histogram learned the cache stages too
        assert "cache.lookup" in snap["stages"]
    finally:
        b.stop()
        trace_lib.uninstall()


def test_front_deadline_expired_sheds_before_hashing(rng):
    eng = StubRouter(max_batch=16)
    m = ServeMetrics()
    front, b, cache = _front(eng, metrics=m)
    try:
        with pytest.raises(DeadlineExceeded):
            front.submit(_rows(rng, 1),
                         deadline_s=time.monotonic() - 0.1)
        st = cache.stats()
        assert st["hits"] == st["misses"] == 0    # never looked up
        assert m.snapshot()["resilience"]["deadline_shed_requests"] == 1
    finally:
        b.stop()


def test_front_passes_through_with_no_live_version(rng):
    """Warming server: nothing to key on — the front delegates and the
    batcher's semantics (here: a bare stub serve) are untouched."""
    eng = StubRouter(max_batch=16)
    eng.set_live_route(None, None)
    front, b, cache = _front(eng)
    try:
        out = front.submit(_rows(rng, 2)).result(timeout=10)
        assert out.shape == (2, 10)
        st = cache.stats()
        assert st["hits"] == st["misses"] == 0
    finally:
        b.stop()


# -- single-flight collapse ------------------------------------------------


def test_single_flight_exactly_one_dispatch_stub(rng):
    """ISSUE 10 acceptance, deterministic form: N concurrent identical
    misses produce exactly ONE engine dispatch (engine call log), all
    N futures resolve with the same bytes, followers are counted as
    collapsed."""
    gate = threading.Event()
    eng = StubRouter(max_batch=16, gate=gate)
    m = ServeMetrics()
    front, b, cache = _front(eng, metrics=m)
    try:
        x = _rows(rng, 2)
        futs = [front.submit(x) for _ in range(6)]
        assert eng.in_call.wait(timeout=10)
        gate.set()
        outs = [f.result(timeout=10) for f in futs]
        assert len({o.tobytes() for o in outs}) == 1
        assert eng.calls == [2], (
            f"expected ONE dispatch for 6 identical requests, got "
            f"{eng.calls}")
        st = cache.stats()
        assert st["collapsed"] == 5
        assert st["inserts"] == 1 and st["inflight_keys"] == 0
        # followers are version-tagged and metered like hits — incl.
        # the per-dtype population (the observability satellite covers
        # collapsed traffic too, not only straight hits)
        assert all(f.version == "v1" for f in futs)
        snap = m.snapshot()
        assert snap["cache_served"]["collapsed_requests"] == 5
        assert snap["by_dtype"]["float32"]["rows"] >= 10  # 5 x 2 rows
        # each future holds its OWN array: mutating one result must
        # not corrupt a concurrent identical request's bytes
        a, bb = futs[0].result(), futs[1].result()
        a[:] = -1.0
        assert bb[0, 0] != -1.0
    finally:
        b.stop()


def test_single_flight_one_dispatch_real_engine(eight_devices, rng):
    """The acceptance check against a REAL jitted engine: concurrent
    identical requests from many threads cost one engine dispatch; the
    engine call log is a counting wrapper around the live engine."""
    from distributedmnist_tpu import models
    from distributedmnist_tpu.parallel import make_mesh
    from distributedmnist_tpu.serve import EngineFactory, ModelRegistry

    factory = EngineFactory(models.build("mlp", platform="cpu"),
                            make_mesh(eight_devices), max_batch=16)
    router = factory.make_router()
    registry = ModelRegistry(factory, router)
    registry.add(factory.init_params(0), version="v1")
    registry.promote("v1")
    engine = registry.get("v1").engine
    calls = []
    real_dispatch = engine.dispatch
    engine.dispatch = lambda xs: (calls.append(1),
                                  real_dispatch(xs))[1]
    m = ServeMetrics()
    b = DynamicBatcher(router, max_wait_us=100_000, queue_depth=1024,
                       metrics=m, dedup=True).start()
    cache = PredictionCache(64)
    front = CacheFront(b, router, cache, metrics=m)
    try:
        x = _rows(rng, 3)
        futs = []
        threads = [threading.Thread(
            target=lambda: futs.append(front.submit(x)), daemon=True)
            for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=10)
        outs = [f.result(timeout=30) for f in futs]
        assert len(outs) == 8
        assert len({o.tobytes() for o in outs}) == 1
        assert len(calls) == 1, (
            f"{len(calls)} engine dispatches for 8 identical requests")
        st = cache.stats()
        assert st["hits"] + st["collapsed"] == 7
    finally:
        b.stop()


def test_leader_failure_fails_followers_and_never_caches(rng):
    """Leader error semantics: followers fail with the LEADER's error;
    nothing is cached; the next identical request elects a fresh
    leader and succeeds."""

    class BreakableRouter(StubRouter):
        def __init__(self, **kw):
            super().__init__(**kw)
            self.broken = True
            self.release = threading.Event()

        def dispatch(self, x):
            if self.broken:
                assert self.release.wait(timeout=10)
                raise RuntimeError("engine down")
            return super().dispatch(x)

    eng = BreakableRouter(max_batch=16)
    front, b, cache = _front(eng, dedup=False)
    try:
        x = _rows(rng, 2)
        futs = [front.submit(x) for _ in range(4)]
        time.sleep(0.05)          # let followers join the flight
        eng.release.set()
        for f in futs:
            with pytest.raises(RuntimeError, match="engine down"):
                f.result(timeout=10)
        st = cache.stats()
        assert st["entries"] == 0 and st["inserts"] == 0
        assert st["inflight_keys"] == 0    # flight cleaned up
        eng.broken = False
        out = front.submit(x).result(timeout=10)   # fresh leader
        assert out.shape == (2, 10)
        assert cache.stats()["inserts"] == 1
    finally:
        b.stop()


# -- invalidation races (the stale-hit guarantee) --------------------------


def test_promote_mid_flight_drops_insert_but_resolves_followers(rng):
    """A live-route change while a single-flight leader is in flight:
    the followers still resolve (their requests were admitted under
    the old route, like any in-flight batch across a promote), but the
    computed bytes are NOT cached — the epoch bump at invalidation
    refuses the insert, so no later lookup under a restored route can
    see them."""
    gate = threading.Event()
    eng = StubRouter(max_batch=16, gate=gate)
    front, b, cache = _front(eng)
    try:
        x = _rows(rng, 2)
        futs = [front.submit(x) for _ in range(3)]
        assert eng.in_call.wait(timeout=10)
        # the promote lands while the leader computes
        eng.set_live_route("v2")
        cache.invalidate("promote v1 -> v2")
        gate.set()
        outs = [f.result(timeout=10) for f in futs]
        assert len({o.tobytes() for o in outs}) == 1
        st = cache.stats()
        assert st["entries"] == 0, "stale insert survived a promote"
        assert st["stale_drops"] >= 1
        # a new identical request under v2 is a fresh miss computing v2
        eng.gate = None
        fresh_fut = front.submit(x)
        fresh = fresh_fut.result(timeout=10)
        assert fresh_fut.version == "v2"
        assert fresh.tobytes() != outs[0].tobytes()   # v2-offset bytes
    finally:
        b.stop()


def test_hammered_promotes_never_serve_stale_version_bytes(rng):
    """The satellite race test: promote/rollback flapping concurrent
    with lookups and in-flight leaders. Every response's BYTES must
    match the version its future claims (StubRouter encodes the
    computing version as a logit offset) — a stale-version hit would
    show v1 bytes under a v2 tag or vice versa."""
    eng = StubRouter(max_batch=16)
    front, b, cache = _front(eng, capacity=256)
    xs = [_rows(rng, 1) for _ in range(8)]
    base = {x.tobytes(): x.reshape(1, -1)[:, :10].astype(np.float32)
            for x in xs}
    errors: list = []
    stop = threading.Event()

    def flipper():
        v = 2
        while not stop.is_set():
            eng.set_live_route(f"v{v}")
            cache.invalidate(f"flip to v{v}")
            v = 3 - v              # v1 <-> v2
            time.sleep(0.002)

    def submitter(idx):
        r = np.random.default_rng(idx)
        for _ in range(60):
            x = xs[int(r.integers(0, len(xs)))]
            try:
                fut = front.submit(x)
                out = fut.result(timeout=10)
            except Exception as e:          # noqa: BLE001
                errors.append(f"submit died: {e!r}")
                return
            v = fut.version
            expected = base[x.tobytes()] + StubRouter.OFFSETS[v]
            if out.tobytes() != expected.astype(np.float32).tobytes():
                errors.append(
                    f"STALE HIT: bytes do not match claimed {v}")

    flip = threading.Thread(target=flipper, daemon=True)
    flip.start()
    try:
        threads = [threading.Thread(target=submitter, args=(i,),
                                    daemon=True) for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
            assert not t.is_alive()
    finally:
        stop.set()
        flip.join(timeout=10)
        b.stop()
    assert not errors, errors[:5]


def test_registry_route_changes_invalidate_cache(eight_devices, rng):
    """The registry hook (ISSUE 10): promote, rollback and dtype
    activation all invalidate the installed cache atomically with the
    routing swap — seeded entries vanish on every live-route change,
    and the epoch moves so racing inserts are refused."""
    from distributedmnist_tpu import models
    from distributedmnist_tpu.parallel import make_mesh
    from distributedmnist_tpu.serve import EngineFactory, ModelRegistry

    factory = EngineFactory(models.build("mlp", platform="cpu"),
                            make_mesh(eight_devices), max_batch=16)
    router = factory.make_router()
    registry = ModelRegistry(factory, router)
    cache = PredictionCache(capacity=8)
    registry.set_cache(cache)
    registry.add(factory.init_params(0), version="v1")
    registry.add(factory.init_params(1), version="v2")

    def seed_entry():
        live, dtype = router.live_route()
        key = content_key(live, dtype, _rows(rng, 1))
        assert cache.insert(key, np.zeros((1, 10)), live, dtype,
                            epoch=cache.epoch())

    registry.promote("v1")
    assert cache.stats()["invalidations"] == 1
    seed_entry()
    registry.promote("v2")                       # promote
    assert cache.stats()["entries"] == 0
    assert cache.stats()["invalidations"] == 2
    seed_entry()
    assert registry.rollback("v2", "test rollback") is not None
    assert cache.stats()["entries"] == 0         # rollback
    assert cache.stats()["invalidations"] == 3
    # shadow/canary routing does NOT change the live route: no flush
    registry.set_shadow("v2", 0.5)
    assert cache.stats()["invalidations"] == 3


# -- intra-batch dedup -----------------------------------------------------


def test_intra_batch_dedup_dispatches_unique_rows_once(rng):
    """Identical rows inside one coalesced drain dispatch once: the
    drain [A, A, B] runs nA+nB rows (not 2*nA+nB), every future
    resolves, and the riders' bytes equal their representative's."""
    gate = threading.Event()
    eng = StubRouter(max_batch=16, gate=gate)
    m = ServeMetrics()
    b = DynamicBatcher(eng, max_wait_us=50_000, queue_depth=256,
                       metrics=m, dedup=True).start()
    try:
        first = b.submit(_rows(rng, 1))    # occupies the window
        assert eng.in_call.wait(timeout=10)
        a = _rows(rng, 3)
        bb = _rows(rng, 2)
        fa1, fa2, fb = b.submit(a), b.submit(a.copy()), b.submit(bb)
        gate.set()
        first.result(timeout=10)
        ra1 = fa1.result(timeout=10)
        ra2 = fa2.result(timeout=10)
        rb = fb.result(timeout=10)
        assert ra1.tobytes() == ra2.tobytes()
        assert rb.shape == (2, 10)
        assert eng.calls == [1, 5], (
            f"expected the dedup'd 5-row dispatch, got {eng.calls}")
        snap = m.snapshot()
        assert snap["dedup"] == {"requests": 1, "rows": 3}
        assert snap["requests"] == 4       # riders are served requests
    finally:
        b.stop()


def test_dedup_off_by_default_dispatches_every_row(rng):
    gate = threading.Event()
    eng = StubRouter(max_batch=16, gate=gate)
    b = DynamicBatcher(eng, max_wait_us=50_000, queue_depth=256).start()
    try:
        first = b.submit(_rows(rng, 1))
        assert eng.in_call.wait(timeout=10)
        a = _rows(rng, 3)
        f1, f2 = b.submit(a), b.submit(a.copy())
        gate.set()
        first.result(timeout=10)
        f1.result(timeout=10)
        f2.result(timeout=10)
        assert eng.calls == [1, 6]         # no dedup: 3 + 3 rows
    finally:
        b.stop()


def test_dedup_failure_fails_riders_with_same_error(rng):
    class FailsSecond(StubRouter):
        def dispatch(self, x):
            if len(self.calls) >= 1:
                self.calls.append(-1)
                raise RuntimeError("poisoned drain")
            return super().dispatch(x)

    gate = threading.Event()
    eng = FailsSecond(max_batch=16, gate=gate)
    b = DynamicBatcher(eng, max_wait_us=50_000, queue_depth=256,
                       dedup=True).start()
    try:
        first = b.submit(_rows(rng, 1))
        assert eng.in_call.wait(timeout=10)
        a = _rows(rng, 2)
        f1, f2 = b.submit(a), b.submit(a.copy())
        gate.set()
        first.result(timeout=10)
        for f in (f1, f2):
            with pytest.raises(RuntimeError, match="poisoned drain"):
                f.result(timeout=10)
    finally:
        b.stop()
