"""Worker for the graceful-preemption e2e test: runs a long CPU fit with
periodic checkpointing. The parent waits for the first committed
checkpoint, sends SIGTERM, and asserts this process exits cleanly having
force-saved a resumable checkpoint at its stopping step (trainer.fit's
graceful_preemption path — SURVEY.md §5 failure recovery)."""

import json
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8")

from distributedmnist_tpu import trainer  # noqa: E402
from distributedmnist_tpu.config import Config  # noqa: E402
from distributedmnist_tpu.data import synthetic_mnist  # noqa: E402


def main() -> int:
    ckpt_dir, steps = sys.argv[1], int(sys.argv[2])
    data = synthetic_mnist(seed=0, train_n=1024, test_n=256)
    cfg = Config(device="cpu", num_devices=8, model="mlp", optimizer="sgd",
                 learning_rate=0.05, synthetic=True, batch_size=64,
                 steps=steps, eval_every=10**9, log_every=0,
                 target_accuracy=None, fused_kernels="xla",
                 checkpoint_dir=ckpt_dir, checkpoint_every=10)
    out = trainer.fit(cfg, data=data)
    print("PREEMPT " + json.dumps({
        "steps": out["steps"],
        "preempted": out["preempted"],
        "restored": out["restored"],
    }), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
