"""Shared test helper: write a synthetic dataset as REAL-format raw IDX
fixture files (the exact on-disk layout of the MNIST distribution), so any
test can exercise the full --data-dir loading path — Python parser or the
native C++ reader — without network access."""

import os
import struct

import numpy as np


def write_idx_fixtures(dirpath, src: dict) -> None:
    """Write src (a synthetic_mnist()-shaped dict) into dirpath as the four
    canonical MNIST IDX files."""
    names = {"train-images-idx3-ubyte": src["train_x"][..., 0],
             "train-labels-idx1-ubyte": src["train_y"],
             "t10k-images-idx3-ubyte": src["test_x"][..., 0],
             "t10k-labels-idx1-ubyte": src["test_y"]}
    for name, arr in names.items():
        dims = arr.shape
        with open(os.path.join(dirpath, name), "wb") as f:
            f.write(struct.pack(f">I{len(dims)}I",
                                0x0800 | len(dims), *dims))
            f.write(np.ascontiguousarray(arr, dtype=np.uint8).tobytes())
