"""analysis/explore.py + analysis/harnesses.py (ISSUE 11): the
deterministic schedule explorer.

Four layers of coverage:

1. controller semantics on tiny inline models — mutual exclusion,
   AB/BA deadlock detection (random AND systematic DFS), partial-order
   reduction actually pruning independent-lock interleavings, condition
   lost-wakeup reachability, semaphore balance accounting;
2. the four serve state-machine harnesses exploring clean at HEAD
   (bounded budgets; scripts/explore.sh runs the 500-schedule sweep);
3. the mutation self-test — an explorer that cannot find PLANTED bugs
   is theater: the skipped single-flight follower and the dropped
   invalidation epoch bump must each be found within a bounded
   schedule budget;
4. replay determinism — a failing seed re-runs to the identical
   interleaving and the identical finding, twice — plus the
   ANALYSIS_r*.json artifact contract (BENCH-style round numbering,
   emitted by both the explorer CLI and Sanitizer.assert_clean).
"""

import json
import os
import subprocess
import sys

import pytest

from distributedmnist_tpu.analysis import explore, harnesses, report
from distributedmnist_tpu.analysis.locks import (make_condition, make_lock,
                                                 make_semaphore)

pytestmark = [pytest.mark.analysis, pytest.mark.mc]


# -- tiny inline models ----------------------------------------------------


class _CounterModel:
    """Two threads increment a shared counter under one lock: always
    clean, and the trace is a pure function of the seed."""

    def __init__(self):
        self.count = 0

    def run(self, ctl):
        lock = make_lock("model.counter")

        def body():
            for _ in range(3):
                with lock:
                    self.count += 1

        a = ctl.spawn(body, "inc-a")
        b = ctl.spawn(body, "inc-b")
        a.join()
        b.join()

    def final(self, ctl):
        assert self.count == 6


class _AbBaModel:
    """The classic AB/BA lock-order deadlock, reachable only under the
    schedules where both threads hold their first lock."""

    def run(self, ctl):
        a = make_lock("model.A")
        b = make_lock("model.B")

        def t1():
            with a:
                with b:
                    pass

        def t2():
            with b:
                with a:
                    pass

        x = ctl.spawn(t1, "t1")
        y = ctl.spawn(t2, "t2")
        x.join()
        y.join()


class _IndependentModel:
    """Two threads, two unrelated locks: every interleaving is
    protocol-equivalent, so DFS-with-POR must finish in ONE schedule."""

    def run(self, ctl):
        a = make_lock("model.A")
        b = make_lock("model.B")

        def t1():
            with a:
                pass

        def t2():
            with b:
                pass

        x = ctl.spawn(t1, "t1")
        y = ctl.spawn(t2, "t2")
        x.join()
        y.join()


class _WakeupModel:
    """Producer/consumer over an UNTIMED condition wait. The correct
    variant guards the wait with a state predicate the producer sets
    under the lock — every schedule completes. The broken variant
    waits unconditionally on a bare notify: schedules where the notify
    lands before the wait are LOST WAKEUPS, which the explorer's
    untimed-wait model makes reachable deadlocks instead of stalls."""

    def __init__(self, correct: bool):
        self.correct = correct
        self.got = False

    def run(self, ctl):
        cond = make_condition("model.cv")
        state = {"flag": False}

        def producer():
            with cond:
                if self.correct:
                    state["flag"] = True
                cond.notify_all()

        def consumer():
            with cond:
                if self.correct:
                    while not state["flag"]:
                        cond.wait()
                else:
                    cond.wait()      # lost if the notify already fired
            self.got = True

        p = ctl.spawn(producer, "producer")
        c = ctl.spawn(consumer, "consumer")
        p.join()
        c.join()


class _LeakModel:
    """Semaphore acquired, never released: the controller's balance
    accounting must read the held unit at drain."""

    def run(self, ctl):
        sem = make_semaphore("model.slots", 2)

        def body():
            sem.acquire()

        t = ctl.spawn(body, "leaker")
        t.join()

    def final(self, ctl):
        assert ctl.sem_balance.get("model.slots") == 0, (
            "leaked slot")


def _explore_n(factory, name, schedules, stop=True, policy="random",
               base_seed=0):
    ex = explore.Explorer(stop_on_finding=stop)
    return ex.run(factory, name, schedules=schedules,
                  base_seed=base_seed, policy=policy)


# -- 1. controller semantics -----------------------------------------------


def test_counter_model_clean_and_deterministic():
    rep = _explore_n(_CounterModel, "counter", schedules=10, stop=False)
    assert rep.schedules == rep.completed == 10
    assert rep.findings == []
    a = explore.replay(_CounterModel, 3)
    b = explore.replay(_CounterModel, 3)
    assert a.trace == b.trace and a.trace
    assert a.finding is None


def test_ab_ba_deadlock_found_by_random():
    rep = _explore_n(_AbBaModel, "abba", schedules=50)
    assert rep.findings, "AB/BA deadlock never found in 50 schedules"
    f = rep.findings[0]
    assert f["kind"] == "deadlock"
    assert "model.A" in f["detail"] and "model.B" in f["detail"]


def test_ab_ba_deadlock_found_by_dfs():
    rep = _explore_n(_AbBaModel, "abba", schedules=200, policy="dfs")
    assert rep.findings and rep.findings[0]["kind"] == "deadlock", (
        "systematic DFS never reached the AB/BA interleaving")


def test_dfs_por_prunes_independent_interleavings():
    rep = _explore_n(_IndependentModel, "indep", schedules=100,
                     stop=False, policy="dfs")
    assert rep.findings == []
    # two unrelated locks: every interleaving commutes, so sleep sets
    # complete exactly ONE schedule and prune every sibling prefix,
    # exhausting the tree well inside the budget
    assert rep.completed == 1
    assert rep.pruned == rep.schedules - 1
    assert rep.schedules < 100, "DFS did not exhaust — POR not pruning"


def test_lost_wakeup_reachable_only_without_predicate():
    ok = _explore_n(lambda: _WakeupModel(correct=True), "wakeup-ok",
                    schedules=40, stop=False)
    assert ok.findings == []
    bad = _explore_n(lambda: _WakeupModel(correct=False), "wakeup-bad",
                     schedules=40)
    assert bad.findings and bad.findings[0]["kind"] == "deadlock"
    assert "model.cv" in bad.findings[0]["detail"]


def test_semaphore_balance_leak_detected():
    rep = _explore_n(_LeakModel, "leak", schedules=3)
    assert rep.findings
    f = rep.findings[0]
    assert f["kind"] == "invariant" and "leaked slot" in f["detail"]


def test_controller_refuses_stacking():
    ctl = explore.Controller()
    explore._active = ctl
    try:
        with pytest.raises(RuntimeError, match="already installed"):
            explore.Controller().explore(_CounterModel())
    finally:
        explore._active = None


def test_logical_clock_restored_after_run():
    import time as _time

    real = _time.monotonic
    explore.replay(_CounterModel, 0)
    assert _time.monotonic is real
    assert _time.sleep is explore._REAL_SLEEP


# -- 2. the four serve machines explore clean at HEAD ----------------------


@pytest.mark.parametrize("machine", sorted(harnesses.MACHINES))
def test_machine_explores_clean_at_head(machine):
    rep = _explore_n(harnesses.MACHINES[machine], machine,
                     schedules=40, stop=False)
    assert rep.schedules == 40
    assert rep.completed == 40, (
        f"{machine}: {rep.schedules - rep.completed} schedule(s) did "
        "not run to completion")
    assert rep.findings == [], (
        f"{machine} findings at HEAD:\n"
        + "\n".join(f["detail"] for f in rep.findings))


# -- 3. mutation self-test -------------------------------------------------


def test_mutation_skipped_follower_is_found():
    rep = _explore_n(
        lambda: harnesses.CacheMachine(mutation="skip-follower"),
        "cache-skip-follower", schedules=150)
    assert rep.findings, (
        "planted skip-follower bug not found within 150 schedules — "
        "the explorer is theater")
    f = rep.findings[0]
    # the skipped follower's future never resolves: the waiting client
    # deadlocks (or the final unresolved-future invariant trips)
    assert f["kind"] in ("deadlock", "invariant")


def test_mutation_dropped_epoch_bump_is_found():
    rep = _explore_n(
        lambda: harnesses.CacheMachine(mutation="drop-epoch-bump"),
        "cache-drop-epoch", schedules=300)
    assert rep.findings, (
        "planted dropped-epoch-bump bug not found within 300 "
        "schedules — the explorer is theater")
    f = rep.findings[0]
    assert f["kind"] == "invariant"
    assert "stale bytes" in f["detail"]


def test_mutations_do_not_leak_into_clean_machine():
    """The mutation patches are scoped to the mutated run: a clean
    machine explored right after a mutated one stays clean."""
    _explore_n(lambda: harnesses.CacheMachine(mutation="skip-follower"),
               "cache-skip-follower", schedules=30)
    rep = _explore_n(harnesses.CacheMachine, "cache", schedules=20,
                     stop=False)
    assert rep.findings == [] and rep.completed == 20


# -- 4. replay determinism + the ANALYSIS artifact -------------------------


def test_failing_seed_replays_identically_twice():
    """The ISSUE 11 contract: a failing interleaving is a replayable
    seed, not a flake — identical trace AND identical finding, twice."""
    factory = lambda: harnesses.CacheMachine(mutation="drop-epoch-bump")
    rep = _explore_n(factory, "cache-drop-epoch", schedules=300)
    assert rep.findings
    seed = rep.findings[0]["seed"]
    first = explore.replay(factory, seed)
    second = explore.replay(factory, seed)
    assert first.finding is not None
    assert first.trace == second.trace
    assert first.finding == second.finding
    # and the replays reproduce the exploration's own finding
    assert first.finding["kind"] == rep.findings[0]["kind"]
    assert first.finding["detail"] == rep.findings[0]["detail"]


def test_artifact_round_numbering(tmp_path):
    root = str(tmp_path)
    assert report.next_round(root) == 1
    p1 = report.emit_analysis({"kind": "explorer", "x": 1}, root=root)
    assert os.path.basename(p1) == "ANALYSIS_r01.json"
    p2 = report.emit_analysis({"kind": "explorer", "x": 2}, root=root)
    assert os.path.basename(p2) == "ANALYSIS_r02.json"
    rec = json.loads(open(p2).read())
    assert rec["round"] == 2 and rec["x"] == 2
    assert "generated_at" in rec


def test_assert_clean_emits_artifact(tmp_path):
    from distributedmnist_tpu.analysis import sanitize

    san = sanitize.install_sanitizer()
    try:
        san.assert_clean(artifact=str(tmp_path))
    finally:
        sanitize.uninstall_sanitizer()
    files = sorted(os.listdir(tmp_path))
    assert files == ["ANALYSIS_r01.json"]
    rec = json.loads(open(tmp_path / files[0]).read())
    assert rec["kind"] == "sanitizer" and rec["clean"] is True
    assert rec["report"]["cycles"] == []


def test_cli_smoke_subprocess():
    """The tier-1 wiring end to end: module CLI, exit 0, summary line
    per machine, no artifact without --emit."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    before = set(report.existing_rounds())
    out = subprocess.run(
        [sys.executable, "-m", "distributedmnist_tpu.analysis.explore",
         "--machines", "cache", "--schedules", "3", "--seed", "1"],
        capture_output=True, text=True, timeout=300, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    assert out.returncode == 0, out.stdout + out.stderr
    assert "explore: cache" in out.stdout and "CLEAN" in out.stdout
    assert set(report.existing_rounds()) == before
