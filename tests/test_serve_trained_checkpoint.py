"""A TRAINED checkpoint through the serving parity gate (ISSUE 19
satellite, closing the ROADMAP follow-up).

Every serving test so far boots from fresh-init params or a
synthetically "trained" state assembled in-process. This is the CI
proof for the real production path: train.py commits an orbax
checkpoint with actually-descended params, then serve.py boots a
worker FROM that checkpoint with a low-precision serving dtype — so
the registry's full boot chain runs against trained weights:
params-only restore, per-bucket warmup, the bf16 accuracy-parity gate
measured against the trained f32 reference (PARITY_GATES thresholds,
not a fresh-init logit field that any quantization trivially matches),
and the atomic promote to live.
"""

import json
import os
import subprocess
import sys
import time
import urllib.error
import urllib.request

import pytest

from conftest import committed_steps, worker_env


def test_trained_checkpoint_serves_through_parity_gate(tmp_path):
    ckpt = str(tmp_path / "trained")
    env, repo = worker_env()
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"

    # 1) real training run, to completion: 300 SGD steps on the
    # synthetic stream, periodic saves committing at least one step.
    # The step count is load-bearing for the parity gate downstream: a
    # barely-trained model has near-uniform logits, so bf16 rounding
    # flips argmax rows and the gate (argmax agreement >= 0.995)
    # correctly REFUSES the variant. Descending to confident logits is
    # exactly what makes low-precision serving safe.
    train = subprocess.run(
        [sys.executable, os.path.join(repo, "train.py"),
         "--device", "cpu", "--num-devices", "8", "--synthetic",
         "--model", "mlp", "--optimizer", "sgd",
         "--learning-rate", "0.1", "--batch-size", "64",
         "--steps", "300", "--eval-every", "1000000", "--log-every", "0",
         "--checkpoint-dir", ckpt, "--checkpoint-every", "100"],
        capture_output=True, text=True, timeout=600, env=env, cwd=repo)
    assert train.returncode == 0, train.stdout[-3000:] + train.stderr[-2000:]
    steps = committed_steps(ckpt)
    assert steps, "training committed no checkpoint"

    # 2) boot a serving worker FROM the checkpoint, bf16 live: the
    # parity gate must measure the quantized forward against the
    # trained f32 reference before any traffic lands on it
    proc = subprocess.Popen(
        [sys.executable, os.path.join(repo, "serve.py"),
         "--model", "mlp", "--device", "cpu", "--serve-max-batch", "16",
         "--checkpoint-dir", ckpt, "--serve-infer-dtype", "bfloat16",
         "--port", "0", "--metrics-every", "5"],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        env=env, cwd=repo)
    try:
        port = None
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            line = proc.stdout.readline()
            assert line, "serve.py exited before announcing readiness"
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if rec.get("metric") == "serve_ready":
                port = rec["port"]
                break
        assert port is not None, "no serve_ready line"
        base = f"http://127.0.0.1:{port}"

        # healthy flips when the f32 reference goes live; the gated
        # bf16 activation lands right after — poll for BOTH
        deadline = time.monotonic() + 300
        payload = None
        while time.monotonic() < deadline:
            try:
                with urllib.request.urlopen(f"{base}/healthz",
                                            timeout=10) as r:
                    payload = json.loads(r.read())
            except urllib.error.HTTPError as e:
                assert e.code == 503, e.code
                payload = json.loads(e.read())
            if payload["ok"] and \
                    payload["live_infer_dtype"] == "bfloat16":
                break
            time.sleep(0.5)
        else:
            pytest.fail("worker never served bf16 from the trained "
                        f"checkpoint: {payload}")
        live = payload["live_version"]

        with urllib.request.urlopen(f"{base}/models", timeout=10) as r:
            models = json.loads(r.read())
        mv = next(v for v in models["versions"] if v["version"] == live)
        # the live version IS the trained checkpoint, not fresh-init
        assert mv["source"] == f"checkpoint {ckpt}", mv["source"]
        assert mv["step"] in steps, (mv["step"], steps)
        # ...and the bf16 variant went live only THROUGH the parity
        # gate: the measured record is attached, and it passed against
        # the trained reference
        var = mv["variants"]["bfloat16"]
        assert var["state"] == "ready", var
        parity = var["parity"]
        assert parity is not None and parity["passed"] is True, parity
        assert parity["argmax_agreement"] >= 0.995, parity

        # trained params answer traffic end to end
        req = urllib.request.Request(
            f"{base}/predict", data=bytes(784),
            headers={"Content-Type": "application/octet-stream"})
        with urllib.request.urlopen(req, timeout=75) as r:
            out = json.loads(r.read())
        assert out["n"] == 1 and out["version"] == live
        assert 0 <= out["classes"][0] <= 9
    finally:
        proc.terminate()
        try:
            proc.wait(timeout=60)
        except subprocess.TimeoutExpired:
            proc.kill()
