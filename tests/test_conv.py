"""Patch-matmul (im2col) conv path vs the lax conv oracle.

The TPU conv implementation (ops/conv.py) must be a drop-in for flax
nn.Conv: identical parameter pytrees (checkpoint compatibility across
platforms) and float-tolerance-identical math in forward and backward.
"""

import flax.linen as nn
import jax
import jax.flatten_util  # not exposed by `import jax` alone
import jax.numpy as jnp
import numpy as np
import pytest

from distributedmnist_tpu import models
from distributedmnist_tpu.ops.conv import avg_pool2, im2col_conv


def _tree_shapes(tree):
    return jax.tree.map(lambda a: (a.shape, a.dtype.name), tree)


@pytest.fixture(scope="module")
def both_lenets():
    lax_m = models.build("lenet", conv="lax")
    im_m = models.build("lenet", conv="im2col")
    x = jnp.asarray(np.random.default_rng(0).normal(
        size=(4, 28, 28, 1)).astype(np.float32))
    params = lax_m.init(jax.random.PRNGKey(0), x)["params"]
    return lax_m, im_m, params, x


def test_param_trees_identical(both_lenets):
    lax_m, im_m, params, x = both_lenets
    im_params = im_m.init(jax.random.PRNGKey(0), x)["params"]
    assert _tree_shapes(params) == _tree_shapes(im_params)


def test_forward_equivalent(both_lenets):
    lax_m, im_m, params, x = both_lenets
    a = lax_m.apply({"params": params}, x)
    b = im_m.apply({"params": params}, x)   # same params, other impl
    np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                               rtol=1e-5, atol=1e-5)


def test_grads_equivalent(both_lenets):
    lax_m, im_m, params, x = both_lenets

    def loss(m):
        return lambda p: (m.apply({"params": p}, x) ** 2).mean()

    ga = jax.grad(loss(lax_m))(params)
    gb = jax.grad(loss(im_m))(params)
    flat_a, _ = jax.flatten_util.ravel_pytree(ga)
    flat_b, _ = jax.flatten_util.ravel_pytree(gb)
    np.testing.assert_allclose(np.asarray(flat_a), np.asarray(flat_b),
                               rtol=1e-4, atol=1e-5)


def test_im2col_conv_matches_lax_conv_same_and_valid():
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.normal(size=(2, 14, 14, 6)).astype(np.float32))
    w = jnp.asarray(rng.normal(size=(5, 5, 6, 16)).astype(np.float32))
    b = jnp.asarray(rng.normal(size=(16,)).astype(np.float32))
    for padding in ("VALID", "SAME"):
        ref = jax.lax.conv_general_dilated(
            x, w, window_strides=(1, 1), padding=padding,
            dimension_numbers=("NHWC", "HWIO", "NHWC")) + b
        got = im2col_conv(x, w, b, padding=padding)
        assert got.shape == ref.shape
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=1e-4, atol=1e-4)


def test_avg_pool2_matches_nn_avg_pool():
    x = jnp.asarray(np.random.default_rng(2).normal(
        size=(3, 10, 10, 16)).astype(np.float32))
    ref = nn.avg_pool(x, (2, 2), strides=(2, 2))
    np.testing.assert_allclose(np.asarray(avg_pool2(x)), np.asarray(ref),
                               rtol=1e-6, atol=1e-6)


def test_bad_conv_impl_raises():
    from distributedmnist_tpu.models import LeNet5
    with pytest.raises(ValueError, match="conv_impl"):
        LeNet5(conv_impl="im2coll").init(   # typo must not fall back to lax
            jax.random.PRNGKey(0), jnp.zeros((1, 28, 28, 1)))
    with pytest.raises(ValueError, match="conv impl"):
        models.build("lenet", conv="patch")


def test_im2col_trains_e2e(tiny_data):
    from distributedmnist_tpu import trainer
    from distributedmnist_tpu.config import Config

    out = trainer.fit(Config(
        device="cpu", num_devices=4, model="lenet", optimizer="adam",
        synthetic=True, batch_size=64, steps=30, eval_every=30,
        log_every=0, target_accuracy=None, conv_impl="im2col"),
        data=tiny_data)
    assert out["test_accuracy"] > 0.3
    assert np.isfinite(out["final_loss"])
