"""serve/metrics.py ISSUE 9 satellites: per-version breaker-trip
attribution (the version argument used to be silently dropped),
percentile computation OFF the metrics lock (a /metrics poll must not
stall the recording hooks on the dispatch hot path), and the Prometheus
text exposition (stable names, # TYPE lines, histogram cumulation,
label escaping, None-skipping)."""

import threading
import time

import pytest

from distributedmnist_tpu.serve import ServeMetrics, prometheus_exposition
from distributedmnist_tpu.serve import metrics as metrics_mod

pytestmark = pytest.mark.trace


# -- breaker trips by version ----------------------------------------------


def test_breaker_trips_attributed_per_version():
    m = ServeMetrics()
    m.record_breaker_trip("v1")
    m.record_breaker_trip("v2")
    m.record_breaker_trip("v1")
    res = m.snapshot()["resilience"]
    assert res["breaker_trips"] == 3
    assert res["breaker_trips_by_version"] == {"v1": 2, "v2": 1}


def test_breaker_trip_without_version_counts_total_only():
    m = ServeMetrics()
    m.record_breaker_trip(None)
    res = m.snapshot()["resilience"]
    assert res["breaker_trips"] == 1
    assert res["breaker_trips_by_version"] == {}


def test_breaker_trips_reset_with_window():
    m = ServeMetrics()
    m.record_breaker_trip("v1")
    m.reset()
    res = m.snapshot()["resilience"]
    assert res["breaker_trips"] == 0
    assert res["breaker_trips_by_version"] == {}


# -- snapshot off the lock -------------------------------------------------


def test_snapshot_does_not_hold_lock_through_percentiles(monkeypatch):
    """Contention regression (ISSUE 9 satellite): snapshot() used to
    compute percentiles over up-to-100k-sample deques WHILE holding
    the metrics lock, stalling every recording hook whenever /metrics
    was polled. Pin the fix: with percentile math slowed to 0.2s per
    call, a concurrent record_latency must still land in
    milliseconds."""
    m = ServeMetrics()
    for _ in range(1000):
        m.record_latency(0.001, rows=1, version="v1")

    real = metrics_mod.percentiles

    def slow_percentiles(values, qs=(50, 95, 99)):
        time.sleep(0.2)
        return real(values, qs)

    monkeypatch.setattr(metrics_mod, "percentiles", slow_percentiles)
    in_snapshot = threading.Event()

    def poll():
        in_snapshot.set()
        m.snapshot()

    t = threading.Thread(target=poll, daemon=True)
    t.start()
    assert in_snapshot.wait(timeout=5)
    time.sleep(0.05)               # the poller is now inside the math
    t0 = time.monotonic()
    m.record_latency(0.002)        # the hot-path hook under test
    record_s = time.monotonic() - t0
    t.join(timeout=30)
    assert not t.is_alive()
    assert record_s < 0.1, (
        f"record_latency blocked {record_s:.3f}s behind a snapshot — "
        "percentiles are being computed under the metrics lock again")


def test_snapshot_shape_unchanged_after_offlock_rework():
    """The off-lock rework must not change the snapshot contract the
    bench/serve surfaces read."""
    m = ServeMetrics()
    m.record_latency(0.01, rows=4, version="v1")
    m.record_dispatch(0.001, inflight=2)
    m.record_fetch(0.002)
    m.record_batch(rows=4, bucket=8, queue_depth=1, version="v1",
                   replica="r0", infer_dtype="float32")
    m.record_wait(0.0005)
    snap = m.snapshot()
    assert snap["requests"] == 1 and snap["rows"] == 4
    assert snap["latency_ms"]["p50"] == pytest.approx(10.0, rel=1e-3)
    assert snap["batch_occupancy"]["8"]["rows"] == 4
    assert snap["by_version"]["v1"]["requests"] == 1
    assert snap["by_replica"]["r0"]["batches"] == 1
    assert snap["by_dtype"]["float32"]["rows"] == 4
    assert snap["padding_waste_ratio"] == 0.5     # 4 real of 8 slots
    assert snap["effective_wait_us"]["last"] == 500.0


# -- Prometheus exposition -------------------------------------------------


def _sample_snapshot():
    m = ServeMetrics()
    for i in range(10):
        m.record_latency(0.001 * (i + 1), rows=2, version="v1")
    m.record_batch(rows=8, bucket=8, queue_depth=2, version="v1",
                   replica="r0", infer_dtype="int8")
    m.record_reject(3)
    m.record_deadline_shed(2)
    m.record_breaker_trip("v1")
    m.record_failover("fetch", "r0", "r1")
    return m.snapshot()


def test_prometheus_exposition_counters_and_types():
    text = prometheus_exposition(_sample_snapshot())
    lines = text.splitlines()
    assert "# TYPE dmnist_serve_requests_total counter" in lines
    assert "dmnist_serve_requests_total 10" in lines
    assert "dmnist_serve_rows_total 20" in lines
    assert "dmnist_serve_rejected_requests_total 1" in lines
    assert "dmnist_serve_deadline_shed_requests_total 1" in lines
    assert 'dmnist_serve_breaker_version_trips_total{version="v1"} 1' \
        in lines
    assert 'dmnist_serve_failovers_total{kind="fetch"} 1' in lines
    assert 'dmnist_serve_version_requests_total{version="v1"} 10' \
        in lines
    assert 'dmnist_serve_replica_batches_total{replica="r0"} 1' in lines
    assert 'dmnist_serve_dtype_batches_total{dtype="int8"} 1' in lines
    assert 'dmnist_serve_bucket_dispatches_total{bucket="8"} 1' in lines
    # summaries carry quantile labels, never a fabricated 0 for an
    # empty window
    assert "# TYPE dmnist_serve_latency_ms summary" in lines
    assert any(l.startswith('dmnist_serve_latency_ms{quantile="0.5"}')
               for l in lines)
    assert "dmnist_serve_latency_ms_count 10" in lines
    # every # TYPE line names a metric that actually has samples
    for i, line in enumerate(lines):
        if line.startswith("# TYPE"):
            name = line.split()[2]
            assert any(l.startswith(name) for l in lines[i + 1:]), name


def test_prometheus_empty_window_skips_none_summaries():
    text = prometheus_exposition(ServeMetrics().snapshot())
    assert "quantile" not in text          # no latency samples -> no
    assert "NaN" not in text               # summary, no fake zeros
    assert "None" not in text
    assert "dmnist_serve_requests_total 0" in text


def test_prometheus_gauges_and_label_escaping():
    text = prometheus_exposition(_sample_snapshot(),
                                 gauges={"pending_rows": 7})
    assert "# TYPE dmnist_serve_pending_rows gauge" in text
    assert "dmnist_serve_pending_rows 7" in text
    m = ServeMetrics()
    m.record_breaker_trip('v"weird\\name')
    text = prometheus_exposition(m.snapshot())
    assert r'version="v\"weird\\name"' in text


def test_prometheus_every_series_carries_help(rng=None):
    """ISSUE 10 satellite: every emitted dmnist_serve_* family gets a
    `# HELP` line alongside its `# TYPE` line — scrapers and humans
    both read the exposition. Checked structurally: each TYPE line must
    be immediately preceded by a HELP line for the SAME name."""
    from distributedmnist_tpu.serve import trace as trace_lib

    tr = trace_lib.Tracer()
    tr.add_span("queue.wait", 0.0, 0.001, rids=())
    cache_stats = {"hits": 3, "hit_rows": 3, "misses": 1,
                   "collapsed": 2, "inserts": 1, "evictions": 0,
                   "invalidations": 1, "stale_drops": 0, "entries": 1,
                   "inflight_keys": 0, "hit_ratio": 0.75,
                   "capacity": 8, "epoch": 1}
    text = prometheus_exposition(_sample_snapshot(),
                                 trace_stages=tr.snapshot()["stages"],
                                 gauges={"pending_rows": 2},
                                 cache=cache_stats)
    lines = text.splitlines()
    typed = [(i, line.split()[2]) for i, line in enumerate(lines)
             if line.startswith("# TYPE")]
    assert typed, "no TYPE lines at all"
    for i, name in typed:
        assert i > 0 and lines[i - 1].startswith(f"# HELP {name} "), (
            f"{name} has no # HELP line (line {i}: {lines[i - 1]!r})")
        # the help text is prose, not an empty stub
        assert len(lines[i - 1].split(None, 2)[2]) > 3, name


def test_prometheus_cache_series():
    """The ISSUE 10 counters + hit ratio flatten into stable
    dmnist_serve_cache_* series from the PredictionCache.stats dict;
    dedup counters come from the snapshot itself."""
    m = ServeMetrics()
    m.record_cache_hit(0.0001, rows=2, version="v1",
                       infer_dtype="float32")
    m.record_dedup(3, 9)
    stats = {"hits": 5, "hit_rows": 10, "misses": 2, "collapsed": 1,
             "inserts": 2, "evictions": 1, "invalidations": 4,
             "stale_drops": 1, "entries": 2, "inflight_keys": 0,
             "hit_ratio": 0.7143, "capacity": 8, "epoch": 4}
    text = prometheus_exposition(m.snapshot(), cache=stats)
    lines = text.splitlines()
    assert "dmnist_serve_cache_hits_total 5" in lines
    assert "dmnist_serve_cache_misses_total 2" in lines
    assert "dmnist_serve_cache_collapsed_total 1" in lines
    assert "dmnist_serve_cache_evictions_total 1" in lines
    assert "dmnist_serve_cache_invalidations_total 4" in lines
    assert "dmnist_serve_cache_stale_drops_total 1" in lines
    assert "dmnist_serve_cache_hit_ratio 0.7143" in lines
    assert "dmnist_serve_cache_entries 2" in lines
    assert "dmnist_serve_dedup_requests_total 3" in lines
    assert "dmnist_serve_dedup_rows_total 9" in lines
    # without a cache installed the series are absent, never faked
    text2 = prometheus_exposition(ServeMetrics().snapshot())
    assert "dmnist_serve_cache_hits_total" not in text2


def test_record_cache_hit_feeds_populations():
    """A cache hit is a served request: global counters, per-version
    and per-dtype populations all move (the observability satellite's
    accounting half)."""
    m = ServeMetrics()
    m.record_cache_hit(0.0002, rows=3, version="v1",
                       infer_dtype="int8")
    m.record_cache_hit(0.0001, rows=1, version="v1", collapsed=True)
    snap = m.snapshot()
    assert snap["requests"] == 2 and snap["rows"] == 4
    assert snap["by_version"]["v1"]["requests"] == 2
    assert snap["by_dtype"]["int8"]["rows"] == 3
    assert snap["cache_served"] == {"hit_requests": 1, "hit_rows": 4,
                                    "collapsed_requests": 1}
    assert snap["latency_ms"]["p99"] is not None


def test_prometheus_stage_histogram_cumulates():
    """Span-derived stage histograms flatten with CUMULATIVE buckets
    (the Prometheus histogram contract), one series per stage."""
    from distributedmnist_tpu.serve import trace as trace_lib

    tr = trace_lib.Tracer()
    tr.add_span("queue.wait", 0.0, 0.0003, rids=())      # 0.3 ms
    tr.add_span("queue.wait", 0.0, 0.002, rids=())       # 2 ms
    tr.add_span("queue.wait", 0.0, 5.0, rids=())         # 5000 ms: +Inf
    stages = tr.snapshot()["stages"]
    text = prometheus_exposition(ServeMetrics().snapshot(),
                                 trace_stages=stages)
    lines = text.splitlines()
    assert "# TYPE dmnist_serve_stage_duration_ms histogram" in lines
    get = lambda le: next(  # noqa: E731
        float(l.split()[-1]) for l in lines
        if l.startswith("dmnist_serve_stage_duration_ms_bucket")
        and f'le="{le}"' in l and 'stage="queue.wait"' in l)
    assert get("0.25") == 0
    assert get("0.5") == 1
    assert get("2.5") == 2
    assert get("1000") == 2
    assert get("+Inf") == 3
    assert ('dmnist_serve_stage_duration_ms_count{stage="queue.wait"} 3'
            in lines)