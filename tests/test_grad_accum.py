"""Gradient accumulation: A microbatches per optimizer step must produce
the same trajectory as the direct full-batch step (equal microbatch sizes
make the mean of microbatch gradients the exact full-batch gradient), in
both SPMD modes, with one allreduce per optimizer step in explicit mode.
"""

import numpy as np
import pytest

from distributedmnist_tpu import trainer
from distributedmnist_tpu.config import Config

BASE = Config(device="cpu", num_devices=8, synthetic=True, model="mlp",
              optimizer="sgd", learning_rate=0.05, fused_kernels="xla",
              batch_size=256, steps=16, eval_every=16, log_every=0,
              target_accuracy=None)


@pytest.mark.parametrize("mode", ["auto", "explicit"])
def test_grad_accum_matches_direct(mode, tiny_data):
    direct = trainer.fit(BASE.replace(spmd_mode=mode), data=tiny_data)
    accum = trainer.fit(BASE.replace(spmd_mode=mode, grad_accum=4),
                        data=tiny_data)
    # Identical batch order + exact-in-real-arithmetic mean-of-means =>
    # same trajectory. In float32 the reassociated microbatch mean drifts
    # by ~1e-7/step, compounded by 16 steps of momentum SGD on the
    # calibrated (noise=0.44) synthetic task to ~3e-4 relative — tight
    # enough to catch a wrong-scale or missing-microbatch bug (those are
    # >1e-2), loose enough not to flake on FP reassociation.
    np.testing.assert_allclose(accum["final_loss"], direct["final_loss"],
                               rtol=1e-3, atol=1e-5)
    np.testing.assert_allclose(accum["test_accuracy"],
                               direct["test_accuracy"], atol=1e-6)


def test_grad_accum_lenet_adam(tiny_data):
    out = trainer.fit(BASE.replace(model="lenet", optimizer="adam",
                                   learning_rate=1e-3, grad_accum=2,
                                   steps=12, eval_every=12),
                      data=tiny_data)
    assert out["steps"] == 12          # accumulation doesn't change steps
    assert np.isfinite(out["final_loss"])


def test_grad_accum_validation(tiny_data):
    with pytest.raises(ValueError, match="grad-accum"):
        trainer.fit(BASE.replace(grad_accum=3), data=tiny_data)  # 256%24!=0
    with pytest.raises(ValueError, match="grad_accum"):
        trainer.fit(BASE.replace(grad_accum=0), data=tiny_data)
    with pytest.raises(ValueError, match="device-resident"):
        trainer.fit(BASE.replace(grad_accum=2, data_pipeline="stream"),
                    data=tiny_data)
