"""serve/registry.py: version lifecycle (load -> pre-warm -> promote ->
rollback), the params-only checkpoint path, the Clockwork promote gate
(only warmed versions take traffic), residency eviction, and the
zero-recompile contract ACROSS a hot-swap with real engines."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributedmnist_tpu import models, optim
from distributedmnist_tpu.checkpoint import Checkpointer
from distributedmnist_tpu.parallel import make_mesh, replicated
from distributedmnist_tpu.serve import (DynamicBatcher, EngineFactory,
                                        ModelRegistry, ServeMetrics)
from distributedmnist_tpu.trainer import init_state
from distributedmnist_tpu.utils import CompileCounter


@pytest.fixture()
def factory(eight_devices):
    mesh = make_mesh(eight_devices)
    model = models.build("mlp", platform="cpu")
    return EngineFactory(model, mesh, max_batch=16)


def _registry(factory, metrics=None, **kw):
    router = factory.make_router(metrics=metrics)
    return ModelRegistry(factory, router, **kw), router


def _trained_state(factory, seed=9, step=7):
    tx = optim.build("adam", 1e-3, flat=True)
    state = init_state(jax.random.PRNGKey(seed), factory.model, tx,
                       jnp.zeros((1, 28, 28, 1)))
    state = state.replace(step=jnp.asarray(step, jnp.int32))
    return jax.device_put(state, replicated(factory.mesh))


def test_add_prewarms_and_promote_goes_live(factory, rng):
    registry, router = _registry(factory)
    assert registry.live_version() is None
    mv = registry.add(factory.init_params(0), source="fresh-init")
    assert mv.state == "ready" and mv.version == "v1"
    # pre-warm really compiled every bucket: a fresh engine costs
    # compile events, and the registry's verification pass proved a
    # second sweep costs zero
    assert mv.warmup_compile_events >= len(factory.buckets)
    registry.promote("v1")
    assert registry.get("v1").state == "live"
    assert router.live_version() == "v1"
    x = rng.integers(0, 256, (5, 784)).astype(np.uint8)
    assert router.infer(x).shape == (5, 10)


def test_promote_refuses_unwarmed_version(factory):
    registry, _ = _registry(factory)
    mv = registry.add(factory.init_params(0), version="cold")
    mv.state = "warming"          # simulate a still-warming candidate
    with pytest.raises(RuntimeError, match="warmed"):
        registry.promote("cold")
    with pytest.raises(KeyError, match="unknown version"):
        registry.promote("never-loaded")


def test_load_latest_is_params_only_and_correct(factory, tmp_path, rng):
    """A checkpoint written with FULL train state (params + optimizer
    slots) serves through the params-only restore: the loaded version's
    logits match the saved params' direct forward exactly, and the
    version is named after the checkpoint step."""
    state = _trained_state(factory, seed=9, step=7)
    ckpt = Checkpointer(str(tmp_path / "c"), async_save=False)
    ckpt.save(7, state)
    ckpt.wait()
    ckpt.close()

    registry, router = _registry(factory)
    mv = registry.load_latest(str(tmp_path / "c"))
    assert mv.version == "step-7" and mv.step == 7
    assert mv.state == "ready"
    registry.promote(mv.version)

    x = rng.integers(0, 256, (4, 28, 28, 1)).astype(np.uint8)
    got = router.infer(x)
    ref = factory.model.apply({"params": jax.device_get(state.params)},
                              x.astype(np.float32) / 255.0)
    np.testing.assert_allclose(got, np.asarray(ref), rtol=1e-5, atol=1e-5)


def test_load_latest_layout_agnostic(factory, tmp_path):
    """Serving restore must not care which optimizer-state layout the
    checkpoint was written under (config.flat_optimizer): params-only
    means the opt_state subtree is never even read."""
    for flat, sub in ((True, "flat"), (False, "perleaf")):
        tx = optim.build("adam", 1e-3, flat=flat)
        state = init_state(jax.random.PRNGKey(3), factory.model, tx,
                           jnp.zeros((1, 28, 28, 1)))
        state = jax.device_put(state, replicated(factory.mesh))
        ckpt = Checkpointer(str(tmp_path / sub), async_save=False)
        ckpt.save(1, state)
        ckpt.wait()
        ckpt.close()
        registry, _ = _registry(factory)
        mv = registry.load_latest(str(tmp_path / sub))
        assert mv.state == "ready", sub


def test_load_latest_no_checkpoint_raises(factory, tmp_path):
    registry, _ = _registry(factory)
    with pytest.raises(FileNotFoundError, match="no committed"):
        registry.load_latest(str(tmp_path / "empty"))
    with pytest.raises(ValueError, match="checkpoint directory"):
        registry.load_latest()    # no dir configured at all


def test_load_latest_is_idempotent_per_step(factory, tmp_path,
                                            monkeypatch):
    """SIGHUP can fire repeatedly: re-loading an already resident step
    returns the existing version instead of warming a duplicate — and
    without re-reading the checkpoint bytes (the residency check runs
    BEFORE the restore, so a no-new-checkpoint reload costs a
    listdir)."""
    from distributedmnist_tpu import checkpoint as ckpt_mod

    state = _trained_state(factory, step=5)
    ckpt = Checkpointer(str(tmp_path / "c"), async_save=False)
    ckpt.save(5, state)
    ckpt.wait()
    ckpt.close()
    registry, _ = _registry(factory)
    calls = []
    real = ckpt_mod.restore_latest_params
    monkeypatch.setattr(ckpt_mod, "restore_latest_params",
                        lambda *a, **k: calls.append(1) or real(*a, **k))
    mv1 = registry.load_latest(str(tmp_path / "c"))
    mv2 = registry.load_latest(str(tmp_path / "c"))
    assert mv1 is mv2
    assert len(registry.describe()["versions"]) == 1
    assert len(calls) == 1, "redundant reload re-read the checkpoint"


def test_load_latest_explicit_name_refuses_stale_step(factory,
                                                      tmp_path):
    """An explicit version name loaded at step N must not silently
    short-circuit once a newer step is committed: returning the stale
    entry as if freshly loaded would let an operator promote old
    params believing them latest."""
    ckpt = Checkpointer(str(tmp_path / "c"), async_save=False)
    ckpt.save(5, _trained_state(factory, step=5))
    ckpt.wait()
    registry, _ = _registry(factory)
    mv = registry.load_latest(str(tmp_path / "c"), version="candidate")
    assert mv.step == 5
    # same step: idempotent
    assert registry.load_latest(str(tmp_path / "c"),
                                version="candidate") is mv
    ckpt.save(9, _trained_state(factory, step=9))
    ckpt.wait()
    ckpt.close()
    with pytest.raises(ValueError, match="already holds step 5"):
        registry.load_latest(str(tmp_path / "c"), version="candidate")
    # the step-derived default name still loads the new checkpoint
    assert registry.load_latest(str(tmp_path / "c")).step == 9


def test_bootstrap_fresh_init_without_checkpoint(factory):
    registry, router = _registry(factory)
    mv = registry.bootstrap(seed=0)
    assert mv.source == "fresh-init"
    assert registry.live_version() == mv.version
    assert router.routes()["live"] == mv.version


def test_bootstrap_yields_to_a_version_already_live(factory):
    """If an admin promotion landed while the boot version warmed (the
    SIGHUP-races-boot case), bootstrap must NOT steal live back for its
    own — possibly fresh-init — params; the operator's choice wins."""
    registry, router = _registry(factory)
    registry.promote(registry.add(factory.init_params(1),
                                  version="v-admin").version)
    mv = registry.bootstrap(seed=0)
    assert router.live_version() == "v-admin"
    assert registry.get(mv.version).state == "ready"   # resident, demotable


def test_rollback_is_promote_of_previous_version(factory):
    registry, router = _registry(factory)
    registry.promote(registry.add(factory.init_params(0),
                                  version="v1").version)
    registry.promote(registry.add(factory.init_params(1),
                                  version="v2").version)
    assert registry.get("v1").state == "ready"    # demoted, resident
    registry.promote("v1")                        # rollback
    assert router.live_version() == "v1"
    assert registry.get("v2").state == "ready"


def test_eviction_keeps_live_and_caps_residency(factory):
    registry, _ = _registry(factory, max_versions=2)
    registry.promote(registry.add(factory.init_params(0),
                                  version="v1").version)
    registry.add(factory.init_params(1), version="v2")
    registry.add(factory.init_params(2), version="v3")
    names = [v["version"] for v in registry.describe()["versions"]]
    assert len(names) == 2
    assert "v1" in names          # live is never evicted
    assert "v3" in names          # the just-added version is protected
    assert "v2" not in names      # oldest routeless version dropped
    with pytest.raises(ValueError, match="max_versions"):
        ModelRegistry(factory, factory.make_router(), max_versions=1)


def test_add_refuses_when_all_residents_hold_routes(factory):
    """When live + candidates fill the cap, a further add must fail
    FAST (before any warmup is spent) instead of either evicting the
    newcomer it just warmed or blowing past the HBM cap."""
    registry, _ = _registry(factory, max_versions=2)
    registry.promote(registry.add(factory.init_params(0),
                                  version="v1").version)
    registry.add(factory.init_params(1), version="v2")
    registry.set_shadow("v2", fraction=0.5)     # both residents in route
    with pytest.raises(RuntimeError, match="registry full"):
        registry.add(factory.init_params(2), version="v3")
    names = [v["version"] for v in registry.describe()["versions"]]
    assert sorted(names) == ["v1", "v2"]        # nothing vanished


def test_describe_answers_during_warmup(factory):
    """/healthz and GET /models must not block behind a multi-second
    candidate warmup: describe() takes only the state lock, and the
    warming version is honestly visible in state 'warming'."""
    import threading

    registry, _ = _registry(factory)
    seen_during_warm = []
    orig_make = factory.make_engine

    def slow_make(params, version, replica=0):
        # runs inside add() OUTSIDE the state lock: describe() from
        # another thread must return immediately
        t = threading.Thread(target=lambda: seen_during_warm.append(
            registry.describe()))
        t.start()
        t.join(timeout=5)
        assert not t.is_alive(), "describe() blocked during warmup"
        return orig_make(params, version, replica=replica)

    factory.make_engine = slow_make
    try:
        registry.add(factory.init_params(0), version="v1")
    finally:
        factory.make_engine = orig_make
    assert seen_during_warm
    states = {v["version"]: v["state"]
              for v in seen_during_warm[0]["versions"]}
    assert states == {"v1": "warming"}


def test_describe_lists_versions_and_routes(factory):
    registry, _ = _registry(factory)
    registry.promote(registry.add(factory.init_params(0),
                                  version="v1").version)
    registry.add(factory.init_params(1), version="v2")
    registry.set_shadow("v2", fraction=0.5)
    d = registry.describe()
    assert {v["version"] for v in d["versions"]} == {"v1", "v2"}
    assert d["routes"]["live"] == "v1"
    assert d["routes"]["shadow"] == {"version": "v2", "fraction": 0.5}
    assert d["buckets"] == list(factory.buckets)


def test_candidate_roles_require_ready_state(factory):
    registry, _ = _registry(factory)
    registry.promote(registry.add(factory.init_params(0),
                                  version="v1").version)
    with pytest.raises(RuntimeError, match="non-live"):
        registry.set_shadow("v1", fraction=0.5)   # live can't shadow
    with pytest.raises(RuntimeError, match="non-live"):
        registry.set_canary("v1", fraction=0.5)


def test_zero_recompiles_through_hot_swap_under_load(factory, rng):
    """The ISSUE 3 acceptance contract with REAL engines: a mixed-size
    request stream pushed through the batcher keeps flowing across an
    atomic hot-swap with exactly zero compile events after the
    candidate's off-path warmup — and every request resolves."""
    metrics = ServeMetrics()
    registry, router = _registry(factory, metrics=metrics)
    registry.promote(registry.add(factory.init_params(0),
                                  version="v1").version)
    b = DynamicBatcher(router, max_wait_us=200, queue_depth=4096,
                       max_inflight=4, metrics=metrics).start()
    try:
        sizes = [1, 3, 7, 8, 9, 15, 16, 5, 12] * 2
        futs = [(n, b.submit(rng.integers(0, 256, (n, 28, 28, 1))
                             .astype(np.uint8))) for n in sizes]
        # load + pre-warm v2 while v1 traffic is in flight (warmup off
        # the hot path), then swap; sample the counter POST-warmup
        registry.add(factory.init_params(1), version="v2")
        before = CompileCounter.instance().snapshot()
        registry.promote("v2")
        futs += [(n, b.submit(rng.integers(0, 256, (n, 28, 28, 1))
                              .astype(np.uint8))) for n in sizes]
        for n, f in futs:
            assert f.result(timeout=60).shape == (n, 10)
    finally:
        b.stop()
    assert CompileCounter.instance().snapshot() - before == 0, (
        "hot-swap to a pre-warmed version recompiled")
    assert router.live_version() == "v2"
    # both populations are version-tagged in the metrics
    assert set(metrics.snapshot()["by_version"]) <= {"v1", "v2"}
    assert "v2" in metrics.snapshot()["by_version"]


# -- admin races (ISSUE 6 satellite) --------------------------------------


def test_sighup_reload_races_admin_promote(factory, tmp_path):
    """The serve.py coherence contract under admin_lock: a SIGHUP-style
    load-latest-then-promote (one critical section) racing admin
    promotes of another version must end with the registry and router
    agreeing — whichever got the lock last is live, the reload's
    promote paired with ITS OWN loaded version (never a stale one),
    and no operation raised."""
    import threading

    state = _trained_state(factory, seed=3, step=11)
    ckpt = Checkpointer(str(tmp_path / "c"), async_save=False)
    ckpt.save(11, state)
    ckpt.wait()
    ckpt.close()

    registry, router = _registry(
        factory, checkpoint_dir=str(tmp_path / "c"))
    base = registry.add(factory.init_params(0), version="v-base")
    registry.promote("v-base")
    admin_lock = threading.Lock()      # serve.py's handler/SIGHUP lock
    errors = []
    start = threading.Barrier(2)

    def reload_thread():               # serve.py's _reload body
        try:
            start.wait(timeout=10)
            with admin_lock:
                mv = registry.load_latest()
                registry.promote(mv.version)
        except Exception as e:         # pragma: no cover - the failure
            errors.append(e)

    def promote_thread():              # admin POST /models/promote
        try:
            start.wait(timeout=10)
            for _ in range(3):
                with admin_lock:
                    registry.promote("v-base")
        except Exception as e:         # pragma: no cover
            errors.append(e)

    threads = [threading.Thread(target=reload_thread),
               threading.Thread(target=promote_thread)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
        assert not t.is_alive(), "admin race deadlocked"
    assert not errors, errors
    live = registry.live_version()
    assert live in ("v-base", "step-11")
    # registry and router agree, and the loaded version is resident
    # and warmed regardless of who won
    assert registry.get(live).state == "live"
    assert router.live_version() == live
    loaded = registry.get("step-11")
    assert loaded.state in ("ready", "live") and loaded.engines


def test_eviction_races_concurrent_promote(factory):
    """Registry eviction (adds overflowing max_versions) racing a
    promote flip-flop between two residents: the only acceptable
    client-visible error is a KeyError for a version eviction already
    removed; afterwards the registry is coherent — live is resident,
    residency is within the cap, and no in-route version was evicted."""
    import threading

    registry, router = _registry(factory, max_versions=3)
    registry.add(factory.init_params(0), version="keep-a")
    registry.add(factory.init_params(1), version="keep-b")
    registry.promote("keep-a")
    errors = []
    stop = threading.Event()

    def promoter():
        flip = ["keep-a", "keep-b"]
        i = 0
        try:
            while not stop.is_set():
                try:
                    registry.promote(flip[i % 2])
                except KeyError:
                    pass               # evicted while routeless: allowed
                i += 1
        except Exception as e:         # pragma: no cover
            errors.append(e)

    t = threading.Thread(target=promoter)
    t.start()
    try:
        for k in range(4):             # each add may evict the oldest
            registry.add(factory.init_params(10 + k),
                         version=f"filler-{k}")
    finally:
        stop.set()
        t.join(timeout=120)
    assert not t.is_alive() and not errors, errors
    desc = registry.describe()
    residents = {v["version"] for v in desc["versions"]}
    assert len(residents) <= 3
    live = registry.live_version()
    assert live in residents, (live, residents)
    assert registry.get(live).state == "live"
    assert router.versions_in_route() <= residents
