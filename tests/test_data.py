"""Data layer tests (SURVEY.md §4): IDX round-trip, synthetic determinism,
epoch permutation semantics, and shard-partition invariants."""

import gzip
import os
import struct

import numpy as np
import pytest

from distributedmnist_tpu.data import load_mnist, synthetic_mnist
from distributedmnist_tpu.data.loader import (
    DeviceDataset, IndexStream, eval_batches)
from distributedmnist_tpu.parallel import make_mesh


def _write_idx(path, arr, gz=False):
    dims = arr.shape
    header = struct.pack(f">I{len(dims)}I", 0x0800 | len(dims), *dims)
    opener = gzip.open if gz else open
    with opener(path, "wb") as f:
        f.write(header)
        f.write(arr.astype(np.uint8).tobytes())


@pytest.mark.parametrize("gz", [False, True])
def test_idx_roundtrip(tmp_path, gz):
    rng = np.random.default_rng(0)
    data = {
        "train-images-idx3-ubyte": rng.integers(0, 255, (100, 28, 28)),
        "train-labels-idx1-ubyte": rng.integers(0, 10, (100,)),
        "t10k-images-idx3-ubyte": rng.integers(0, 255, (50, 28, 28)),
        "t10k-labels-idx1-ubyte": rng.integers(0, 10, (50,)),
    }
    for name, arr in data.items():
        _write_idx(os.path.join(tmp_path, name + (".gz" if gz else "")),
                   arr, gz=gz)
    out = load_mnist(data_dir=str(tmp_path))
    assert out["source"] == "real"
    assert out["train_x"].shape == (100, 28, 28, 1)
    np.testing.assert_array_equal(
        out["train_x"][..., 0], data["train-images-idx3-ubyte"])
    np.testing.assert_array_equal(
        out["test_y"], data["t10k-labels-idx1-ubyte"])


def test_npz_loading(tmp_path):
    rng = np.random.default_rng(0)
    np.savez(os.path.join(tmp_path, "mnist.npz"),
             x_train=rng.integers(0, 255, (64, 28, 28), dtype=np.uint8),
             y_train=rng.integers(0, 10, (64,)),
             x_test=rng.integers(0, 255, (32, 28, 28), dtype=np.uint8),
             y_test=rng.integers(0, 10, (32,)))
    out = load_mnist(data_dir=str(tmp_path))
    assert out["train_x"].shape == (64, 28, 28, 1)
    assert out["train_y"].dtype == np.int32


def test_missing_data_dir_raises(tmp_path):
    with pytest.raises(FileNotFoundError):
        load_mnist(data_dir=str(tmp_path / "nope"))


def test_synthetic_deterministic():
    a = synthetic_mnist(seed=3, train_n=256, test_n=64)
    b = synthetic_mnist(seed=3, train_n=256, test_n=64)
    np.testing.assert_array_equal(a["train_x"], b["train_x"])
    np.testing.assert_array_equal(a["test_y"], b["test_y"])
    c = synthetic_mnist(seed=4, train_n=256, test_n=64)
    assert not np.array_equal(a["train_x"], c["train_x"])


def test_synthetic_shapes_and_balance():
    d = synthetic_mnist(seed=0, train_n=4096, test_n=512)
    assert d["train_x"].shape == (4096, 28, 28, 1)
    assert d["train_x"].dtype == np.uint8
    counts = np.bincount(d["train_y"], minlength=10)
    assert counts.min() > 200  # roughly balanced classes


def test_index_stream_is_epoch_partition(tiny_data, eight_devices):
    mesh = make_mesh(eight_devices)
    n, gb = 2048, 256
    stream = IndexStream(n, gb, seed=0, mesh=mesh)
    spe = stream.steps_per_epoch
    assert spe == 8
    epoch0 = np.concatenate(
        [stream.indices_for_step(s) for s in range(spe)])
    # each epoch visits every sample exactly once (partition invariant)
    assert sorted(epoch0.tolist()) == list(range(n))
    epoch1 = np.concatenate(
        [stream.indices_for_step(spe + s) for s in range(spe)])
    assert sorted(epoch1.tolist()) == list(range(n))
    assert not np.array_equal(epoch0, epoch1)  # reshuffled between epochs


def test_index_stream_device_count_invariant(tiny_data, eight_devices):
    """Batch order must not depend on the mesh size (SURVEY.md §7.3:
    seed-for-seed 1-chip ≡ N-chip)."""
    m1 = make_mesh(eight_devices[:1])
    m8 = make_mesh(eight_devices)
    s1 = IndexStream(2048, 256, seed=5, mesh=m1)
    s8 = IndexStream(2048, 256, seed=5, mesh=m8)
    for step in (0, 1, 7, 8, 100):
        np.testing.assert_array_equal(
            s1.indices_for_step(step), s8.indices_for_step(step))


def test_index_stream_sharded_batch(eight_devices):
    mesh = make_mesh(eight_devices)
    stream = IndexStream(2048, 256, seed=0, mesh=mesh)
    idx = next(stream)
    assert idx.shape == (1, 256)  # (steps_per_call, global_batch)
    # batch axis sharded over 'data': each device holds 256/8 columns
    shard_cols = {s.data.shape[1] for s in idx.addressable_shards}
    assert shard_cols == {32}
    # block of 4 scanned steps advances the stream by 4
    blk = stream.next_block(4)
    assert blk.shape == (4, 256)
    assert stream.step == 5


def test_device_dataset_replicated(tiny_data, eight_devices):
    mesh = make_mesh(eight_devices)
    ds = DeviceDataset(tiny_data, mesh)
    assert ds.train_n == 2048 and ds.test_n == 512
    # replicated: every device holds the full array
    assert all(s.data.shape == ds.train_x.shape
               for s in ds.train_x.addressable_shards)
    assert ds.train_x.dtype == np.uint8  # stays uint8 until in-step cast


def test_eval_batches_mask():
    idx, mask = eval_batches(test_n=1000, batch=512)
    assert idx.shape == (2, 512) and mask.shape == (2, 512)
    assert mask.sum() == 1000
    valid = idx[mask]
    assert sorted(valid.tolist()) == list(range(1000))
    assert (idx[~mask] == 0).all()
