"""Tensor-parallel tests (beyond-parity capability, parallel/tp.py):
dp×tp training must be numerically equivalent to pure DP (same seed, same
global batches — TP only changes placement), and the Megatron specs must
actually land on the params."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from distributedmnist_tpu import models, optim, trainer
from distributedmnist_tpu.config import Config
from distributedmnist_tpu.parallel import make_mesh, tp


BASE = Config(device="cpu", synthetic=True, log_every=0,
              target_accuracy=None, learning_rate=0.02, batch_size=256,
              num_devices=8, steps=8, eval_every=8)


def test_mesh_2d_shape(eight_devices):
    mesh = make_mesh(eight_devices, model_parallel=2)
    assert mesh.axis_names == ("data", "model")
    assert mesh.shape == {"data": 4, "model": 2}


def test_mesh_indivisible_raises(eight_devices):
    with pytest.raises(ValueError, match="not divisible"):
        make_mesh(eight_devices[:6], model_parallel=4)


def test_state_shardings_mlp(eight_devices):
    mesh = make_mesh(eight_devices, model_parallel=2)
    model = models.build("mlp", fused="xla")
    tx = optim.build("adam", 1e-3)
    state = trainer.init_state(jax.random.PRNGKey(0), model, tx,
                               jnp.zeros((1, 28, 28, 1)))
    sh = tp.state_shardings(state, mesh, "mlp")
    assert sh.params["hidden"]["kernel"].spec == P(None, "model")
    assert sh.params["hidden"]["bias"].spec == P("model")
    assert sh.params["logits"]["kernel"].spec == P("model", None)
    assert sh.params["logits"]["bias"].spec == P()
    # adam mu mirrors the params specs via the same name rules
    mu = sh.opt_state[0].mu
    assert mu["hidden"]["kernel"].spec == P(None, "model")
    assert sh.step.spec == P()


def test_state_shardings_1d_mesh_replicated(eight_devices):
    mesh = make_mesh(eight_devices)
    model = models.build("mlp", fused="xla")
    tx = optim.build("sgd", 0.1)
    state = trainer.init_state(jax.random.PRNGKey(0), model, tx,
                               jnp.zeros((1, 28, 28, 1)))
    sh = tp.state_shardings(state, mesh, "mlp")
    assert all(s.spec == P() for s in jax.tree.leaves(
        sh, is_leaf=lambda x: hasattr(x, "spec")))


def test_indivisible_dim_falls_back_replicated(eight_devices):
    # logits bias has 10 elements; under mp=4 the P('model') candidate for
    # a hypothetical 10-wide model-sharded dim must fall back to P()
    mesh = make_mesh(eight_devices, model_parallel=4)
    model = models.build("lenet")
    tx = optim.build("sgd", 0.1)
    state = trainer.init_state(jax.random.PRNGKey(0), model, tx,
                               jnp.zeros((1, 28, 28, 1)))
    sh = tp.state_shardings(state, mesh, "lenet")
    # fc2 kernel (120, 84): 120 % 4 == 0 -> sharded on dim 0
    assert sh.params["fc2"]["kernel"].spec == P("model", None)
    # fc1 bias (120,) divisible -> sharded; conv kernels replicated
    assert sh.params["fc1"]["bias"].spec == P("model")
    assert sh.params["conv1"]["kernel"].spec == P()


@pytest.mark.parametrize("model_name", ["mlp", "lenet"])
def test_tp_matches_dp(tiny_data, model_name):
    """dp8 ≡ dp4×tp2: TP is placement-only, so trajectories are identical
    up to collective reduction order."""
    opt = "sgd" if model_name == "mlp" else "adam"
    lr = 0.02 if model_name == "mlp" else 1e-3
    a = trainer.fit(BASE.replace(model=model_name, optimizer=opt,
                                 learning_rate=lr), data=tiny_data)
    b = trainer.fit(BASE.replace(model=model_name, optimizer=opt,
                                 learning_rate=lr, model_parallel=2),
                    data=tiny_data)
    assert b["model_parallel"] == 2
    np.testing.assert_allclose(a["test_accuracy"], b["test_accuracy"],
                               atol=2e-3)


def test_tp_live_array_placement(eight_devices):
    """The intended specs must land on the LIVE arrays after device_put —
    numerics tests alone can't catch a silent fall-back to pure DP."""
    mesh = make_mesh(eight_devices, model_parallel=2)
    for name in ("mlp", "lenet"):
        model = models.build(name, fused="xla")
        tx = optim.build("adam", 1e-3)
        state = trainer.init_state(jax.random.PRNGKey(0), model, tx,
                                   jnp.zeros((1, 28, 28, 1)))
        state = jax.device_put(state, tp.state_shardings(state, mesh, name))
        p = state.params
        if name == "mlp":
            assert p["hidden"]["kernel"].sharding.spec == P(None, "model")
            assert p["logits"]["kernel"].sharding.spec == P("model", None)
            mu = state.opt_state[0].mu
            assert mu["hidden"]["kernel"].sharding.spec == P(None, "model")
        else:
            assert p["fc1"]["kernel"].sharding.spec == P(None, "model")
            assert p["fc2"]["kernel"].sharding.spec == P("model", None)
            assert p["conv1"]["kernel"].sharding.spec == P()


def test_tp_all_fallback_raises(eight_devices):
    # Every matched leaf indivisible -> the run would silently be pure DP;
    # that must raise, not warn.
    mesh = make_mesh(eight_devices, model_parallel=2)
    fake = {"hidden": {"kernel": np.zeros((7, 9))}}
    with pytest.raises(ValueError, match="fell back to replicated"):
        tp.state_shardings(fake, mesh, "mlp")


def test_tp_no_match_raises(eight_devices):
    # A layer rename that defeats the name-based rules must raise.
    mesh = make_mesh(eight_devices, model_parallel=2)
    fake = {"encoder": {"kernel": np.zeros((8, 8))}}
    with pytest.raises(ValueError, match="no parameter"):
        tp.state_shardings(fake, mesh, "mlp")


def test_tp_partial_fallback_warns(eight_devices, caplog):
    mesh = make_mesh(eight_devices, model_parallel=2)
    fake = {"hidden": {"kernel": np.zeros((4, 6)), "bias": np.zeros(7)}}
    import logging
    with caplog.at_level(logging.WARNING, logger="distributedmnist_tpu"):
        sh = tp.state_shardings(fake, mesh, "mlp")
    assert sh["hidden"]["kernel"].spec == P(None, "model")
    assert sh["hidden"]["bias"].spec == P()
    assert any("replicating this leaf" in r.message for r in caplog.records)


def test_tp_explicit_mode_rejected(tiny_data):
    with pytest.raises(ValueError, match="spmd_mode=auto"):
        trainer.fit(BASE.replace(spmd_mode="explicit", model_parallel=2),
                    data=tiny_data)


def test_tp_indivisible_chips_rejected(tiny_data):
    with pytest.raises(ValueError, match="model_parallel"):
        trainer.fit(BASE.replace(model_parallel=3), data=tiny_data)