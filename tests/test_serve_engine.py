"""serve/engine.py: bucket ladder, pad-and-slice correctness against the
direct forward, input validation, and the steady-state zero-recompile
contract (utils.CompileCounter over jax.monitoring events)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributedmnist_tpu import models
from distributedmnist_tpu.parallel import make_mesh
from distributedmnist_tpu.serve import InferenceEngine, make_buckets
from distributedmnist_tpu.trainer import init_state


def test_make_buckets_ladder():
    assert make_buckets(64, 8) == (8, 16, 32, 64)
    assert make_buckets(1, 1) == (1,)
    assert make_buckets(5, 1) == (1, 2, 4, 8)       # top covers max_batch
    assert make_buckets(100, 8) == (8, 16, 32, 64, 128)
    assert make_buckets(16, 3) == (3, 6, 12, 24)    # chips-multiple rungs


@pytest.fixture(scope="module")
def engine(eight_devices):
    mesh = make_mesh(eight_devices)
    model = models.build("mlp", platform="cpu")
    params = init_state(jax.random.PRNGKey(0), model, _sgd(),
                        jnp.zeros((1, 28, 28, 1))).params
    eng = InferenceEngine(model, params, mesh, max_batch=32)
    eng.warmup()
    return eng


def _sgd():
    from distributedmnist_tpu import optim
    return optim.build("sgd", 0.1)


def test_bucket_for_smallest_covering(engine):
    assert engine.buckets == (8, 16, 32)
    assert engine.bucket_for(1) == 8
    assert engine.bucket_for(8) == 8
    assert engine.bucket_for(9) == 16
    assert engine.bucket_for(32) == 32
    with pytest.raises(ValueError, match="top bucket"):
        engine.bucket_for(33)
    with pytest.raises(ValueError):
        engine.bucket_for(0)


def test_input_validation(engine):
    with pytest.raises(TypeError, match="uint8"):
        engine.infer(np.zeros((2, 28, 28, 1), np.float32))
    with pytest.raises(ValueError, match="images"):
        engine.infer(np.zeros((2, 27, 28, 1), np.uint8))
    # flat (n, 784) rows are accepted and reshaped
    assert engine.infer(np.zeros((2, 784), np.uint8)).shape == (2, 10)


def test_pad_and_slice_roundtrip_matches_direct_forward(engine, rng):
    """An n-row request padded to its covering bucket must return exactly
    the logits the unpadded forward computes for those n rows — padding
    can never contaminate real rows, and slicing must keep order."""
    x = rng.integers(0, 256, (11, 28, 28, 1)).astype(np.uint8)
    got = engine.infer(x)
    assert got.shape == (11, 10)

    model = models.build("mlp", platform="cpu")
    ref = model.apply({"params": jax.device_get(engine.params)},
                      x.astype(np.float32) / 255.0)
    np.testing.assert_allclose(got, np.asarray(ref), rtol=1e-5, atol=1e-5)


def test_steady_state_runs_with_zero_recompiles(engine, rng):
    """The acceptance contract: after bucket warmup, a mixed-size request
    stream stays entirely inside the compiled bucket set — the compile
    counter (jax.monitoring events) must not move."""
    # one extra pass over every bucket first: the fixture warmup already
    # compiled them, so this is pure cache-hit traffic
    before = engine.compile_events()
    for n in [1, 3, 7, 8, 9, 15, 16, 17, 30, 32, 5, 12, 27]:
        x = rng.integers(0, 256, (n, 28, 28, 1)).astype(np.uint8)
        assert engine.infer(x).shape == (n, 10)
    assert engine.compile_events() - before == 0, (
        "steady-state serving recompiled despite bucketed shapes")


def test_warmup_is_idempotent(engine):
    """A second warmup over already-compiled buckets costs zero compile
    events (in-memory jit cache hit — the restart case additionally goes
    through the persistent cache)."""
    assert engine.warmup() == 0


def test_dispatch_fetch_composes_to_infer(engine, rng):
    """The two-phase API (ISSUE 2): dispatch() returns a handle without
    fetching; fetch() yields exactly what the synchronous infer() does —
    including for a LIST of request parts, which must equal inference on
    their concatenation (the batcher's coalesced-dispatch path)."""
    parts = [rng.integers(0, 256, (n, 28, 28, 1)).astype(np.uint8)
             for n in (3, 1, 5)]
    h = engine.dispatch(parts)
    assert h.n == 9 and h.bucket == 16
    got = engine.fetch(h)
    np.testing.assert_allclose(got, engine.infer(np.concatenate(parts)),
                               rtol=1e-6, atol=1e-6)
    with pytest.raises(RuntimeError, match="already fetched"):
        engine.fetch(h)


def test_staging_pool_bounded_by_inflight_window(engine, rng):
    """Staging buffers recycle through a per-bucket free list: serial
    traffic keeps at most one buffer per bucket alive, and overlapping
    dispatches draw DISTINCT buffers (a shared one would let batch k+1's
    padding race batch k's device_put)."""
    for n in (1, 3, 9, 17, 2, 30):
        engine.infer(rng.integers(0, 256, (n, 28, 28, 1)).astype(np.uint8))
    assert all(v <= 1 for v in engine.staging_buffers().values())

    x = rng.integers(0, 256, (2, 28, 28, 1)).astype(np.uint8)
    h1, h2 = engine.dispatch(x), engine.dispatch(x)
    assert h1.staging is not h2.staging
    np.testing.assert_array_equal(engine.fetch(h1), engine.fetch(h2))
    assert engine.staging_buffers()[h1.bucket] == 2   # both recycled


def test_zero_recompiles_with_pipelining_on(engine, rng):
    """The steady-state compile-stability contract must survive the
    pipelined dispatch window: a mixed-size request stream pushed through
    a DynamicBatcher at max_inflight=4 moves the compile counter by
    exactly zero."""
    from distributedmnist_tpu.serve import DynamicBatcher

    before = engine.compile_events()
    b = DynamicBatcher(engine, max_wait_us=200, queue_depth=4096,
                       max_inflight=4).start()
    try:
        sizes = [1, 3, 7, 8, 9, 15, 16, 17, 30, 32, 5, 12, 27] * 3
        futs = [(n, b.submit(rng.integers(0, 256, (n, 28, 28, 1))
                             .astype(np.uint8))) for n in sizes]
        for n, f in futs:
            assert f.result(timeout=60).shape == (n, 10)
    finally:
        b.stop()
    assert engine.compile_events() - before == 0, (
        "pipelined serving recompiled despite bucketed shapes")
