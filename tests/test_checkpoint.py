"""Checkpoint tests (SURVEY.md §4): async save -> restore round-trips the
exact training state (params, opt_state, step), latest-step selection, and
no-checkpoint no-op."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributedmnist_tpu import models, optim
from distributedmnist_tpu.checkpoint import Checkpointer
from distributedmnist_tpu.parallel import make_mesh, replicated
from distributedmnist_tpu.trainer import TrainState, init_state


def _state(eight_devices, step=0, flat=False, optimizer="adam"):
    mesh = make_mesh(eight_devices)
    model = models.build("mlp", fused="xla")
    tx = optim.build(optimizer, 1e-3, flat=flat)
    state = init_state(jax.random.PRNGKey(7), model, tx,
                       jnp.zeros((1, 28, 28, 1)))
    state = state.replace(step=jnp.asarray(step, jnp.int32))
    return jax.device_put(state, replicated(mesh))


def _assert_tree_equal(a, b):
    for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_save_restore_roundtrip(tmp_path, eight_devices):
    state = _state(eight_devices, step=42)
    ckpt = Checkpointer(str(tmp_path / "ckpt"))
    ckpt.save(42, state)
    ckpt.wait()
    ckpt.close()

    fresh = _state(eight_devices, step=0)  # different contents (step differs)
    ckpt2 = Checkpointer(str(tmp_path / "ckpt"))
    restored, ok = ckpt2.maybe_restore(fresh)
    ckpt2.close()
    assert ok
    assert int(restored.step) == 42
    _assert_tree_equal(restored, state)
    # restore preserved shardings (replicated over the mesh)
    leaf = jax.tree.leaves(restored.params)[0]
    assert len(leaf.sharding.device_set) == 8


def test_restore_picks_latest(tmp_path, eight_devices):
    ckpt = Checkpointer(str(tmp_path / "c"))
    for step in (5, 10, 15):
        ckpt.save(step, _state(eight_devices, step=step))
    ckpt.wait()
    restored, ok = ckpt.maybe_restore(_state(eight_devices))
    ckpt.close()
    assert ok and int(restored.step) == 15


def test_restore_empty_dir_is_noop(tmp_path, eight_devices):
    state = _state(eight_devices, step=3)
    ckpt = Checkpointer(str(tmp_path / "empty"))
    restored, ok = ckpt.maybe_restore(state)
    ckpt.close()
    assert not ok
    assert restored is state


def test_max_to_keep_garbage_collects(tmp_path, eight_devices):
    ckpt = Checkpointer(str(tmp_path / "gc"), max_to_keep=2)
    for step in (1, 2, 3, 4):
        ckpt.save(step, _state(eight_devices, step=step))
    ckpt.wait()
    steps = sorted(ckpt.mgr.all_steps())
    ckpt.close()
    assert steps == [3, 4]


def _optimizer_vectors(state):
    """All float moment data in the optimizer state as one flat vector,
    layout-independent (optax.flatten concatenates in jax.tree.flatten
    order, so both layouts ravel to identical bytes)."""
    moments = [np.asarray(l).ravel()
               for l in jax.tree.leaves(state.opt_state)
               if np.asarray(l).dtype == np.float32]
    return np.concatenate(moments) if moments else np.zeros(0)


@pytest.mark.parametrize("optimizer", ["adam", "sgd"])
@pytest.mark.parametrize("saved_flat", [True, False])
def test_cross_layout_restore(tmp_path, eight_devices, saved_flat,
                              optimizer):
    """A checkpoint written with one optimizer-state layout (flat vector
    vs per-leaf) restores into a run using the OTHER layout, exactly —
    no --no-flat-optimizer operator step (round-2 verdict, item #9)."""
    saved = _state(eight_devices, step=9, flat=saved_flat,
                   optimizer=optimizer)
    # make moments non-trivial so the conversion is actually checked
    saved = saved.replace(opt_state=jax.tree.map(
        lambda l: (l + jnp.arange(l.size, dtype=l.dtype).reshape(l.shape)
                   if l.dtype == jnp.float32 else l),
        saved.opt_state))
    d = str(tmp_path / "x")
    ckpt = Checkpointer(d)
    ckpt.save(9, saved)
    ckpt.wait()
    ckpt.close()

    target = _state(eight_devices, step=0, flat=not saved_flat,
                    optimizer=optimizer)
    ckpt2 = Checkpointer(d)
    restored, ok = ckpt2.maybe_restore(target)
    ckpt2.close()
    assert ok and int(restored.step) == 9
    _assert_tree_equal(restored.params, saved.params)
    # target structure, saved values
    assert (jax.tree.structure(restored.opt_state)
            == jax.tree.structure(target.opt_state))
    np.testing.assert_array_equal(_optimizer_vectors(restored),
                                  _optimizer_vectors(saved))
    # placement: converted leaves are replicated over the mesh like the
    # target's
    leaf = [l for l in jax.tree.leaves(restored.opt_state)
            if hasattr(l, "sharding")][0]
    assert len(leaf.sharding.device_set) == 8


def test_cross_layout_resume_trajectory(tmp_path, tiny_data):
    """fit() with the converted optimizer state continues EXACTLY the
    trajectory of a run that never switched layouts."""
    from distributedmnist_tpu import trainer
    from distributedmnist_tpu.config import Config

    base = Config(device="cpu", num_devices=8, synthetic=True,
                  model="mlp", optimizer="adam", learning_rate=1e-3,
                  fused_kernels="xla", batch_size=256, log_every=0,
                  target_accuracy=None, eval_every=1000,
                  checkpoint_every=8)
    # uninterrupted 16-step run in the per-leaf layout = the oracle
    oracle = trainer.fit(base.replace(
        steps=16, flat_optimizer=False,
        checkpoint_dir=str(tmp_path / "a")), data=tiny_data)
    # 8 steps per-leaf -> resume in the FLAT layout for the final 8
    ck = str(tmp_path / "b")
    trainer.fit(base.replace(steps=8, flat_optimizer=False,
                             checkpoint_dir=ck), data=tiny_data)
    out = trainer.fit(base.replace(steps=16, flat_optimizer=True,
                                   checkpoint_dir=ck), data=tiny_data)
    assert out["restored"] is True and out["steps"] == 16
    np.testing.assert_allclose(out["test_accuracy"],
                               oracle["test_accuracy"], atol=1e-6)


@pytest.mark.parametrize("target_flat", [True, False])
def test_coincidental_flat_sized_leaf_not_converted(tmp_path,
                                                    eight_devices,
                                                    target_flat):
    """A checkpoint whose opt_state merely CONTAINS a 1-D leaf of the
    total-param size — but is not the flat optimizer layout — must fail
    loudly, not be silently 'converted' from garbage: the structural
    fingerprint gate consults the checkpoint's own tree metadata before
    any conversion (round-3 verdict, weak #4)."""
    base = _state(eight_devices, step=4)
    flat_size = sum(np.asarray(l).size
                    for l in jax.tree.leaves(base.params))
    mesh = make_mesh(eight_devices)
    weird = base.replace(opt_state={
        "scale": jax.device_put(
            jnp.arange(flat_size, dtype=jnp.float32), replicated(mesh)),
    })
    d = str(tmp_path / "w")
    ckpt = Checkpointer(d)
    ckpt.save(4, weird)
    ckpt.wait()
    ckpt.close()

    target = _state(eight_devices, step=0, flat=target_flat)
    ckpt2 = Checkpointer(d)
    with pytest.raises(ValueError, match="training-state structure"):
        ckpt2.maybe_restore(target)
    ckpt2.close()


def test_cross_layout_restore_takes_saved_moment_dtypes(tmp_path,
                                                        eight_devices):
    """The layout conversion reads each moment's dtype from the
    checkpoint's metadata POSITIONALLY, not from the params and not by
    shape lookup: a flat checkpoint with MIXED moment dtypes (mu cast to
    bfloat16, nu kept float32 — the optax mu_dtype pattern) restores
    into an f32 per-leaf target with every value preserved and the
    target's dtypes applied (round-3 advice + review)."""
    saved = _state(eight_devices, step=5, flat=True)
    flat_size = sum(np.asarray(l).size
                    for l in jax.tree.leaves(saved.params))

    seen = [0]

    def cast(l):
        if l.ndim == 1 and l.size == flat_size:
            seen[0] += 1
            if seen[0] == 1:  # mu (first flat moment) only; nu stays f32
                return l.astype(jnp.bfloat16)
        return l
    saved = saved.replace(opt_state=jax.tree.map(cast, saved.opt_state))
    assert seen[0] == 2  # adam: exactly mu and nu
    d = str(tmp_path / "bd")
    ckpt = Checkpointer(d)
    ckpt.save(5, saved)
    ckpt.wait()
    ckpt.close()

    target = _state(eight_devices, step=0, flat=False)
    ckpt2 = Checkpointer(d)
    restored, ok = ckpt2.maybe_restore(target)
    ckpt2.close()
    assert ok and int(restored.step) == 5
    # target layout and dtypes: per-leaf f32 moments
    assert (jax.tree.structure(restored.opt_state)
            == jax.tree.structure(target.opt_state))
    moments = [l for l in jax.tree.leaves(restored.opt_state)
               if hasattr(l, "dtype") and l.ndim > 0]
    assert moments and all(l.dtype == jnp.float32 for l in moments)
    # saved values positionally intact: [mu (bf16->f32), nu (exact f32)]
    mu, nu = [l for l in jax.tree.leaves(saved.opt_state)
              if getattr(l, "ndim", 0) == 1 and l.size == flat_size]
    expected = np.concatenate([
        np.asarray(mu.astype(jnp.float32)).ravel(),
        np.asarray(nu).ravel()])
    restored_vec = np.concatenate(
        [np.asarray(l).ravel() for l in moments])
    np.testing.assert_array_equal(restored_vec, expected)


def test_unrelated_mismatch_still_raises(tmp_path, eight_devices):
    """A checkpoint that is NOT a layout variant (different model) still
    fails loudly with the structure-mismatch diagnostic."""
    saved = _state(eight_devices, step=1)
    d = str(tmp_path / "m")
    ckpt = Checkpointer(d)
    ckpt.save(1, saved)
    ckpt.wait()
    ckpt.close()

    mesh = make_mesh(eight_devices)
    lenet = models.build("lenet", conv="lax")
    tx = optim.build("adam", 1e-3)
    other = jax.device_put(
        init_state(jax.random.PRNGKey(0), lenet, tx,
                   jnp.zeros((1, 28, 28, 1))), replicated(mesh))
    ckpt2 = Checkpointer(d)
    with pytest.raises(ValueError, match="training-state structure"):
        ckpt2.maybe_restore(other)
    ckpt2.close()


def test_eval_only_restores_and_reports(tmp_path, tiny_data):
    from distributedmnist_tpu import trainer
    from distributedmnist_tpu.config import Config
    import pytest

    cfg = Config(device="cpu", num_devices=8, synthetic=True, model="mlp",
                 optimizer="sgd", learning_rate=0.05, fused_kernels="xla",
                 batch_size=256, steps=20, eval_every=20, log_every=0,
                 target_accuracy=None,
                 checkpoint_dir=str(tmp_path / "ck"), checkpoint_every=10)
    trained = trainer.fit(cfg, data=tiny_data)

    ev = trainer.fit(cfg.replace(eval_only=True), data=tiny_data)
    assert ev["restored"] is True
    assert ev["steps"] == 20                     # no training happened
    np.testing.assert_allclose(ev["test_accuracy"],
                               trained["test_accuracy"], atol=1e-6)
    assert ev["final_loss"] is None              # no step ran

    # eval-only without a checkpoint is an error, not a silent cold eval
    with pytest.raises(ValueError, match="eval-only"):
        trainer.fit(cfg.replace(eval_only=True,
                                checkpoint_dir=str(tmp_path / "none")),
                    data=tiny_data)


# -- params-only serving restore (checkpoint.restore_latest_params) -------


def _abstract_params(state):
    return jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype,
                                       sharding=x.sharding), state.params)


def test_restore_latest_params_matches_saved(tmp_path, eight_devices):
    """The serving path reads ONLY the params subtree of the latest
    committed checkpoint: values bit-match the saved params, the step is
    reported, and the result lands with the requested sharding."""
    from distributedmnist_tpu.checkpoint import restore_latest_params

    ckpt = Checkpointer(str(tmp_path / "c"), async_save=False)
    states = {}
    for step in (3, 11):
        states[step] = _state(eight_devices, step=step)
        ckpt.save(step, states[step])
    ckpt.wait()
    ckpt.close()

    params, step = restore_latest_params(str(tmp_path / "c"),
                                         _abstract_params(states[11]))
    assert step == 11
    _assert_tree_equal(params, states[11].params)
    leaf = jax.tree.leaves(params)[0]
    assert len(leaf.sharding.device_set) == 8


def test_restore_latest_params_empty_dir_is_none(tmp_path, eight_devices):
    from distributedmnist_tpu.checkpoint import restore_latest_params

    params, step = restore_latest_params(
        str(tmp_path / "nothing"),
        _abstract_params(_state(eight_devices)))
    assert params is None and step is None


def test_restore_latest_params_ignores_optimizer_layout(tmp_path,
                                                        eight_devices):
    """maybe_restore needs flat<->per-leaf conversion machinery; the
    params-only path must not — the opt_state subtree is skipped, so
    either layout (and either optimizer) serves identically."""
    from distributedmnist_tpu.checkpoint import restore_latest_params

    abstract = None
    for flat, sub in ((True, "flat"), (False, "perleaf")):
        state = _state(eight_devices, step=2, flat=flat)
        abstract = abstract or _abstract_params(state)
        ckpt = Checkpointer(str(tmp_path / sub), async_save=False)
        ckpt.save(2, state)
        ckpt.wait()
        ckpt.close()
        params, step = restore_latest_params(str(tmp_path / sub), abstract)
        assert step == 2, sub
        _assert_tree_equal(params, state.params)


def test_restore_latest_params_wrong_model_raises(tmp_path, eight_devices):
    """A checkpoint whose params tree doesn't match the serving model's
    structure fails loudly, naming the directory."""
    from distributedmnist_tpu.checkpoint import restore_latest_params

    ckpt = Checkpointer(str(tmp_path / "c"), async_save=False)
    ckpt.save(1, _state(eight_devices))          # an MLP checkpoint
    ckpt.wait()
    ckpt.close()

    mesh = make_mesh(eight_devices)
    lenet = models.build("lenet", conv="lax")
    lenet_state = jax.device_put(
        init_state(jax.random.PRNGKey(0), lenet,
                   optim.build("adam", 1e-3),
                   jnp.zeros((1, 28, 28, 1))), replicated(mesh))
    with pytest.raises(ValueError, match="params"):
        restore_latest_params(str(tmp_path / "c"),
                              _abstract_params(lenet_state))
