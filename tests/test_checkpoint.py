"""Checkpoint tests (SURVEY.md §4): async save -> restore round-trips the
exact training state (params, opt_state, step), latest-step selection, and
no-checkpoint no-op."""

import jax
import jax.numpy as jnp
import numpy as np

from distributedmnist_tpu import models, optim
from distributedmnist_tpu.checkpoint import Checkpointer
from distributedmnist_tpu.parallel import make_mesh, replicated
from distributedmnist_tpu.trainer import TrainState, init_state


def _state(eight_devices, step=0):
    mesh = make_mesh(eight_devices)
    model = models.build("mlp", fused="xla")
    tx = optim.build("adam", 1e-3)
    state = init_state(jax.random.PRNGKey(7), model, tx,
                       jnp.zeros((1, 28, 28, 1)))
    state = state.replace(step=jnp.asarray(step, jnp.int32))
    return jax.device_put(state, replicated(mesh))


def _assert_tree_equal(a, b):
    for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_save_restore_roundtrip(tmp_path, eight_devices):
    state = _state(eight_devices, step=42)
    ckpt = Checkpointer(str(tmp_path / "ckpt"))
    ckpt.save(42, state)
    ckpt.wait()
    ckpt.close()

    fresh = _state(eight_devices, step=0)  # different contents (step differs)
    ckpt2 = Checkpointer(str(tmp_path / "ckpt"))
    restored, ok = ckpt2.maybe_restore(fresh)
    ckpt2.close()
    assert ok
    assert int(restored.step) == 42
    _assert_tree_equal(restored, state)
    # restore preserved shardings (replicated over the mesh)
    leaf = jax.tree.leaves(restored.params)[0]
    assert len(leaf.sharding.device_set) == 8


def test_restore_picks_latest(tmp_path, eight_devices):
    ckpt = Checkpointer(str(tmp_path / "c"))
    for step in (5, 10, 15):
        ckpt.save(step, _state(eight_devices, step=step))
    ckpt.wait()
    restored, ok = ckpt.maybe_restore(_state(eight_devices))
    ckpt.close()
    assert ok and int(restored.step) == 15


def test_restore_empty_dir_is_noop(tmp_path, eight_devices):
    state = _state(eight_devices, step=3)
    ckpt = Checkpointer(str(tmp_path / "empty"))
    restored, ok = ckpt.maybe_restore(state)
    ckpt.close()
    assert not ok
    assert restored is state


def test_max_to_keep_garbage_collects(tmp_path, eight_devices):
    ckpt = Checkpointer(str(tmp_path / "gc"), max_to_keep=2)
    for step in (1, 2, 3, 4):
        ckpt.save(step, _state(eight_devices, step=step))
    ckpt.wait()
    steps = sorted(ckpt.mgr.all_steps())
    ckpt.close()
    assert steps == [3, 4]


def test_eval_only_restores_and_reports(tmp_path, tiny_data):
    from distributedmnist_tpu import trainer
    from distributedmnist_tpu.config import Config
    import pytest

    cfg = Config(device="cpu", num_devices=8, synthetic=True, model="mlp",
                 optimizer="sgd", learning_rate=0.05, fused_kernels="xla",
                 batch_size=256, steps=20, eval_every=20, log_every=0,
                 target_accuracy=None,
                 checkpoint_dir=str(tmp_path / "ck"), checkpoint_every=10)
    trained = trainer.fit(cfg, data=tiny_data)

    ev = trainer.fit(cfg.replace(eval_only=True), data=tiny_data)
    assert ev["restored"] is True
    assert ev["steps"] == 20                     # no training happened
    np.testing.assert_allclose(ev["test_accuracy"],
                               trained["test_accuracy"], atol=1e-6)
    assert ev["final_loss"] is None              # no step ran

    # eval-only without a checkpoint is an error, not a silent cold eval
    with pytest.raises(ValueError, match="eval-only"):
        trainer.fit(cfg.replace(eval_only=True,
                                checkpoint_dir=str(tmp_path / "none")),
                    data=tiny_data)
