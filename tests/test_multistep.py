"""Scanned-superstep tests: K steps fused per dispatch via lax.scan must be
bit-equivalent to K single-step dispatches (same seed, same batch order),
in both SPMD modes."""

import jax
import numpy as np
import pytest

from distributedmnist_tpu import trainer
from distributedmnist_tpu.config import Config
from distributedmnist_tpu.data.loader import DeviceDataset, IndexStream
from distributedmnist_tpu.parallel import make_mesh, replicated
from distributedmnist_tpu import models, optim
import jax.numpy as jnp


def _run_blocks(tiny_data, devices, total_steps, block_k, mode):
    mesh = make_mesh(devices)
    ds = DeviceDataset(tiny_data, mesh)
    model = models.build("mlp", fused="xla")
    tx = optim.build("sgd", 0.05)
    state = jax.device_put(
        trainer.init_state(jax.random.PRNGKey(0), model, tx,
                           jnp.zeros((1, 28, 28, 1))),
        replicated(mesh))
    step_fn = trainer.make_train_step(model, tx, mesh, mode=mode)
    stream = IndexStream(ds.train_n, 256, seed=0, mesh=mesh)
    step = 0
    while step < total_steps:
        k = min(block_k, total_steps - step)
        state, metrics = step_fn(state, ds.train_x, ds.train_y,
                                 stream.next_block(k))
        step += k
    return state, float(metrics["loss"]), float(metrics["loss_mean"])


@pytest.mark.parametrize("mode", ["auto", "explicit"])
def test_k1_equals_k4(tiny_data, eight_devices, mode):
    s1, l1, _ = _run_blocks(tiny_data, eight_devices, 8, 1, mode)
    s4, l4, _ = _run_blocks(tiny_data, eight_devices, 8, 4, mode)
    np.testing.assert_allclose(l1, l4, rtol=1e-5)
    for a, b in zip(jax.tree.leaves(s1.params), jax.tree.leaves(s4.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)
    assert int(s1.step) == int(s4.step) == 8


def test_remainder_block(tiny_data, eight_devices):
    # 10 steps in blocks of 4 -> 4+4+2; the ragged tail must still advance
    # the step counter correctly
    s, _, _ = _run_blocks(tiny_data, eight_devices, 10, 4, "auto")
    assert int(s.step) == 10


def test_loss_mean_covers_block(tiny_data, eight_devices):
    _, last, mean = _run_blocks(tiny_data, eight_devices, 6, 6, "auto")
    # early training: loss falls within the block, so the block mean is
    # above the last-step loss
    assert mean > last


def test_fit_steps_per_call_matches_default(tiny_data):
    base = Config(device="cpu", synthetic=True, log_every=0,
                  target_accuracy=None, model="mlp", optimizer="sgd",
                  learning_rate=0.02, batch_size=256, num_devices=8,
                  steps=24, eval_every=24)
    a = trainer.fit(base, data=tiny_data)
    b = trainer.fit(base.replace(steps_per_call=6), data=tiny_data)
    np.testing.assert_allclose(a["test_accuracy"], b["test_accuracy"],
                               atol=1e-6)
    assert b["steps"] == 24


def test_pick_steps_per_call():
    cfg = Config(eval_every=200, checkpoint_every=500)
    assert trainer._pick_steps_per_call(cfg, "cpu", False) == 1
    # tpu: largest k <= 1024 dividing eval_every
    assert trainer._pick_steps_per_call(cfg, "tpu", False) == 200
    # with checkpointing: divides gcd(200, 500) = 100
    assert trainer._pick_steps_per_call(cfg, "tpu", True) == 100
    assert trainer._pick_steps_per_call(
        cfg.replace(steps_per_call=7), "tpu", True) == 7
    assert trainer._pick_steps_per_call(
        cfg.replace(eval_every=3), "tpu", False) == 3
    # the ceiling binds only above 1024 (raised from 256 in round 5:
    # 256-step blocks sit at one relay RTT of device time at b=512)
    assert trainer._pick_steps_per_call(
        cfg.replace(eval_every=2048), "tpu", False) == 1024
    assert trainer._pick_steps_per_call(
        cfg.replace(eval_every=1000), "tpu", False) == 1000
    # streaming keeps the 256 ceiling: its blocks materialize full
    # (k, B, ...) input arrays, and the in-flight window holds up to 16
    assert trainer._pick_steps_per_call(
        cfg.replace(eval_every=2048), "tpu", False, streaming=True) == 256
