"""Ops tests: loss numerics and the Pallas fused dense+relu kernel
(interpret mode on CPU) against its XLA oracle, values and gradients."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributedmnist_tpu.ops import accuracy_count, cross_entropy
from distributedmnist_tpu.ops import fused as fused_lib
from distributedmnist_tpu.ops.fused import dense_relu, dense_relu_reference


def _dr(x, w, b):
    # interpret=True: tests run on the CPU backend
    return dense_relu(x, w, b, True)


def test_resolve_modes():
    assert fused_lib.resolve("auto", "tpu") == fused_lib.PALLAS
    assert fused_lib.resolve("auto", "cpu") == fused_lib.XLA
    assert fused_lib.resolve("pallas", "cpu") == fused_lib.PALLAS_INTERPRET
    assert fused_lib.resolve("pallas", "tpu") == fused_lib.PALLAS
    assert fused_lib.resolve("xla", "tpu") == fused_lib.XLA


def test_cross_entropy_matches_manual():
    logits = jnp.array([[2.0, 0.0, -1.0], [0.5, 0.5, 0.5]])
    labels = jnp.array([0, 2])
    got = cross_entropy(logits, labels)
    p = jax.nn.log_softmax(logits)
    want = -(p[0, 0] + p[1, 2]) / 2
    np.testing.assert_allclose(got, want, rtol=1e-6)


def test_cross_entropy_bf16_logits_f32_loss():
    logits = jnp.zeros((4, 10), jnp.bfloat16)
    labels = jnp.zeros((4,), jnp.int32)
    loss = cross_entropy(logits, labels)
    assert loss.dtype == jnp.float32
    np.testing.assert_allclose(loss, np.log(10.0), rtol=1e-3)


def test_accuracy_count_with_mask():
    logits = jnp.array([[1.0, 0], [0, 1.0], [1.0, 0], [1.0, 0]])
    labels = jnp.array([0, 1, 1, 0])
    assert int(accuracy_count(logits, labels)) == 3
    mask = jnp.array([True, True, True, False])
    assert int(accuracy_count(logits, labels, mask)) == 2


@pytest.mark.parametrize("m,k,n", [(8, 784, 128), (128, 784, 128),
                                   (200, 300, 50)])
def test_fused_dense_relu_matches_xla(m, k, n):
    key = jax.random.PRNGKey(0)
    kx, kw, kb = jax.random.split(key, 3)
    x = jax.random.normal(kx, (m, k))
    w = jax.random.normal(kw, (k, n)) * 0.05
    b = jax.random.normal(kb, (n,))
    got = _dr(x, w, b)                 # interpret mode on CPU
    want = dense_relu_reference(x, w, b)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_fused_dense_relu_grads_match_xla():
    key = jax.random.PRNGKey(1)
    kx, kw, kb = jax.random.split(key, 3)
    x = jax.random.normal(kx, (32, 64))
    w = jax.random.normal(kw, (64, 16)) * 0.1
    b = jax.random.normal(kb, (16,))

    def f_fused(x, w, b):
        return _dr(x, w, b).sum()

    def f_ref(x, w, b):
        return dense_relu_reference(x, w, b).sum()

    g_fused = jax.grad(f_fused, argnums=(0, 1, 2))(x, w, b)
    g_ref = jax.grad(f_ref, argnums=(0, 1, 2))(x, w, b)
    for a, r in zip(g_fused, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(r),
                                   rtol=1e-5, atol=1e-5)


def test_fused_under_jit_and_vjp_in_train_shape():
    @jax.jit
    def step(x, w, b):
        y, vjp = jax.vjp(_dr, x, w, b)
        return vjp(jnp.ones_like(y))

    dx, dw, db = step(jnp.ones((64, 784)), jnp.ones((784, 128)) * 0.01,
                      jnp.zeros((128,)))
    assert dx.shape == (64, 784) and dw.shape == (784, 128)
    assert db.shape == (128,)
