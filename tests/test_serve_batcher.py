"""serve/batcher.py: coalescing under max_wait_us, max_batch-triggered
flush, per-request fan-out correctness, bounded-queue backpressure
(Rejected at the watermark), metrics recording, and the ISSUE 2 pipeline
invariants (in-flight window bound, drain semantics, fan-out under
overlap) — all against a stub engine with a controllable
dispatch()/fetch(), so the batching logic is tested in isolation from
jax."""

import threading
import time
from types import SimpleNamespace

import numpy as np
import pytest

from distributedmnist_tpu.serve import (DynamicBatcher, Rejected,
                                        ServeMetrics, resolve_max_inflight)
from distributedmnist_tpu.serve.engine import InferenceEngine


class StubEngine:
    """Engine-shaped test double implementing the two-phase
    dispatch()/fetch() pipeline API. fetch() returns each row's first 10
    pixel values as float 'logits', so a request's result identifies
    exactly which input rows it was served from. An optional gate Event
    makes fetch() block deterministically — the stand-in for device
    compute still running — so tests control exactly when the pipeline
    drains. Dispatched-but-unfetched depth is tracked so tests can
    assert the batcher's bounded window from the engine's side."""

    platform = "cpu"

    def __init__(self, max_batch=16, n_chips=4, gate=None, costs=None):
        self.max_batch = max_batch
        self.buckets = tuple(n_chips * 2 ** i for i in range(
            max(1, (max_batch // n_chips).bit_length())))
        while self.buckets[-1] < max_batch:
            self.buckets += (self.buckets[-1] * 2,)
        self.gate = gate
        # Optional per-bucket cost table: None (the default) means the
        # batch former sees no cost model and never splits, so the
        # pre-ISSUE-4 tests exercise exactly the single-dispatch path.
        self.costs = costs
        self.calls = []            # row counts per dispatch() call
        self.in_call = threading.Event()  # set on every dispatch()
        self.inflight = 0
        self.inflight_max = 0
        self._lock = threading.Lock()

    _as_images = staticmethod(InferenceEngine._as_images)

    def bucket_costs(self):
        return self.costs or {}

    def linear_costs(self):
        """Compute-priced buckets (cost proportional to rows): the
        regime where the batch former always prefers split over pad."""
        return {b: b * 1e-3 for b in self.buckets}

    def bucket_for(self, n):
        for b in self.buckets:
            if b >= n:
                return b
        raise ValueError(n)

    def dispatch(self, x):
        parts = ([self._as_images(p) for p in x]
                 if isinstance(x, (list, tuple))
                 else [self._as_images(x)])
        x = np.concatenate(parts) if len(parts) > 1 else parts[0]
        self.calls.append(x.shape[0])
        with self._lock:
            self.inflight += 1
            self.inflight_max = max(self.inflight_max, self.inflight)
        self.in_call.set()
        return SimpleNamespace(x=x, n=x.shape[0],
                               bucket=self.bucket_for(x.shape[0]))

    def fetch(self, handle):
        if self.gate is not None:
            assert self.gate.wait(timeout=30)
        with self._lock:
            self.inflight -= 1
        return handle.x.reshape(handle.n, -1)[:, :10].astype(np.float32)

    def infer(self, x):
        return self.fetch(self.dispatch(x))


def _rows(rng, n):
    return rng.integers(0, 256, (n, 28, 28, 1)).astype(np.uint8)


def test_coalesces_waiting_requests_into_one_dispatch(rng):
    eng = StubEngine(max_batch=16)
    gate = threading.Event()
    eng.gate = gate
    b = DynamicBatcher(eng, max_wait_us=50_000, queue_depth=256).start()
    try:
        # first submit occupies the dispatch thread at the gate; the next
        # three queue up behind it and MUST coalesce into one batch
        first = b.submit(_rows(rng, 1))
        assert eng.in_call.wait(timeout=10)
        futs = [b.submit(_rows(rng, 2)) for _ in range(3)]
        gate.set()
        first.result(timeout=10)
        for f in futs:
            assert f.result(timeout=10).shape == (2, 10)
        assert eng.calls[0] == 1
        assert eng.calls[1] == 6, (
            f"expected one coalesced 6-row dispatch, got {eng.calls}")
    finally:
        b.stop()


def test_full_batch_flushes_before_max_wait(rng):
    """max_batch rows pending dispatch immediately — a 5-second wait
    bound must NOT be paid when the batch is already full."""
    eng = StubEngine(max_batch=8)
    b = DynamicBatcher(eng, max_wait_us=5_000_000, queue_depth=256).start()
    try:
        t0 = time.monotonic()
        futs = [b.submit(_rows(rng, 4)) for _ in range(2)]   # = max_batch
        for f in futs:
            f.result(timeout=10)
        assert time.monotonic() - t0 < 2.0, (
            "a full batch waited for the coalescing deadline")
    finally:
        b.stop()


def test_lone_request_is_served_within_the_wait_bound(rng):
    eng = StubEngine(max_batch=16)
    b = DynamicBatcher(eng, max_wait_us=10_000, queue_depth=256).start()
    try:
        out = b.submit(_rows(rng, 3)).result(timeout=10)
        assert out.shape == (3, 10)
        assert eng.calls == [3]
    finally:
        b.stop()


def test_fan_out_maps_each_request_to_its_own_rows(rng):
    """Coalesce-then-slice must hand every request exactly its own rows'
    results, in its own order — the stub's identity 'logits' make any
    off-by-one or reordering visible."""
    eng = StubEngine(max_batch=32)
    gate = threading.Event()
    eng.gate = gate
    b = DynamicBatcher(eng, max_wait_us=50_000, queue_depth=256).start()
    try:
        b.submit(_rows(rng, 1))          # occupy dispatch at the gate
        assert eng.in_call.wait(timeout=10)
        xs = [_rows(rng, n) for n in (3, 1, 5)]
        futs = [b.submit(x) for x in xs]
        gate.set()
        for x, f in zip(xs, futs):
            want = x.reshape(x.shape[0], -1)[:, :10].astype(np.float32)
            np.testing.assert_array_equal(f.result(timeout=10), want)
    finally:
        b.stop()


def test_backpressure_rejects_past_watermark_and_recovers(rng):
    metrics = ServeMetrics()
    eng = StubEngine(max_batch=4)
    gate = threading.Event()
    eng.gate = gate
    b = DynamicBatcher(eng, max_wait_us=1000, queue_depth=8,
                       metrics=metrics).start()
    try:
        b.submit(_rows(rng, 4))          # in dispatch, blocked at gate
        assert eng.in_call.wait(timeout=10)
        ok = [b.submit(_rows(rng, 4)), b.submit(_rows(rng, 4))]  # 8 pending
        with pytest.raises(Rejected):
            b.submit(_rows(rng, 1))      # watermark exceeded -> shed
        assert metrics.snapshot()["rejected_requests"] == 1
        gate.set()                       # drain
        for f in ok:
            f.result(timeout=10)
        # queue drained: admission works again
        assert b.submit(_rows(rng, 2)).result(timeout=10).shape == (2, 10)
    finally:
        b.stop()


def test_oversized_request_is_a_client_error(rng):
    eng = StubEngine(max_batch=8)
    b = DynamicBatcher(eng, queue_depth=64).start()
    try:
        with pytest.raises(ValueError, match="max_batch"):
            b.submit(_rows(rng, 9))
    finally:
        b.stop()


def test_stop_without_drain_fails_pending_futures(rng):
    eng = StubEngine(max_batch=4)
    gate = threading.Event()
    eng.gate = gate
    b = DynamicBatcher(eng, max_wait_us=1000, queue_depth=64).start()
    b.submit(_rows(rng, 4))
    assert eng.in_call.wait(timeout=10)
    pending = b.submit(_rows(rng, 2))
    b.stop(drain=False)
    gate.set()
    with pytest.raises(RuntimeError, match="stopped"):
        pending.result(timeout=10)
    with pytest.raises(RuntimeError, match="stopped"):
        b.submit(_rows(rng, 1))


def test_resolve_max_inflight_rules():
    """Explicit wins; auto is 1 on CPU (no overlap to buy) and a small
    pipeline window on accelerators; <1 is a usage error."""
    assert resolve_max_inflight(3, "cpu") == 3
    assert resolve_max_inflight(1, "tpu") == 1
    assert resolve_max_inflight(None, "cpu") == 1
    assert resolve_max_inflight(None, "tpu") > 1
    assert resolve_max_inflight(None, "gpu") > 1
    with pytest.raises(ValueError, match="max_inflight"):
        resolve_max_inflight(0, "cpu")


def test_inflight_depth_never_exceeds_window(rng):
    """The pipeline-depth invariant: with fetch wedged, the dispatch
    thread may run ahead by exactly max_inflight batches — never more —
    and the engine-side dispatched-but-unfetched counter proves it."""
    eng = StubEngine(max_batch=4)
    gate = threading.Event()
    eng.gate = gate
    b = DynamicBatcher(eng, max_wait_us=1000, queue_depth=256,
                       max_inflight=2).start()
    try:
        futs = []
        # fill the window: two dispatched-but-unfetched batches
        for _ in range(2):
            eng.in_call.clear()
            futs.append(b.submit(_rows(rng, 4)))
            assert eng.in_call.wait(timeout=10)
        assert b.inflight_batches() == 2
        # more work queues up but must NOT dispatch past the window
        futs += [b.submit(_rows(rng, 4)) for _ in range(4)]
        time.sleep(0.2)
        assert eng.inflight == 2 and len(eng.calls) == 2, (
            f"dispatch ran past the window: {eng.calls}")
        gate.set()
        for f in futs:
            assert f.result(timeout=10).shape == (4, 10)
    finally:
        b.stop()
    assert eng.inflight_max == 2, (
        f"window of 2 was exceeded (peak {eng.inflight_max})")
    assert b.inflight_batches() == 0


def test_stop_drain_resolves_inflight_and_queued(rng):
    """stop(drain=True) with the window full AND requests still queued:
    every accepted future resolves with its own rows' results."""
    eng = StubEngine(max_batch=4)
    gate = threading.Event()
    eng.gate = gate
    b = DynamicBatcher(eng, max_wait_us=1000, queue_depth=256,
                       max_inflight=2).start()
    xs = []
    futs = []
    for _ in range(2):          # two in-flight batches, wedged at fetch
        eng.in_call.clear()
        xs.append(_rows(rng, 4))
        futs.append(b.submit(xs[-1]))
        assert eng.in_call.wait(timeout=10)
    for _ in range(3):          # still queued behind the full window
        xs.append(_rows(rng, 2))
        futs.append(b.submit(xs[-1]))
    threading.Timer(0.2, gate.set).start()
    b.stop(drain=True)
    for x, f in zip(xs, futs):
        want = x.reshape(x.shape[0], -1)[:, :10].astype(np.float32)
        np.testing.assert_array_equal(f.result(timeout=0), want)


def test_fan_out_correct_under_pipelined_overlap(rng):
    """Reordering pressure: a free-running window of 3 keeps dispatch,
    fetch, and fan-out overlapping across many mixed-size requests; the
    identity 'logits' prove every future resolves to exactly its own
    rows, in order, despite the concurrency."""
    eng = StubEngine(max_batch=16)
    b = DynamicBatcher(eng, max_wait_us=200, queue_depth=4096,
                       max_inflight=3).start()
    try:
        sizes = [int(rng.integers(1, 9)) for _ in range(60)]
        xs = [_rows(rng, n) for n in sizes]
        futs = [b.submit(x) for x in xs]
        for x, f in zip(xs, futs):
            want = x.reshape(x.shape[0], -1)[:, :10].astype(np.float32)
            np.testing.assert_array_equal(f.result(timeout=30), want)
    finally:
        b.stop()
    assert eng.inflight_max <= 3


def test_pipeline_metrics_split_and_depth_gauge(rng):
    """The ISSUE 2 observability additions: staging_ms / fetch_ms
    percentiles and the in-flight depth gauge are populated and the
    gauge respects the window bound."""
    metrics = ServeMetrics()
    eng = StubEngine(max_batch=8)
    b = DynamicBatcher(eng, max_wait_us=1000, queue_depth=256,
                       max_inflight=2, metrics=metrics).start()
    try:
        for _ in range(6):
            b.submit(_rows(rng, 2)).result(timeout=10)
    finally:
        b.stop()
    snap = metrics.snapshot()
    assert snap["staging_ms"]["p50"] is not None
    assert snap["fetch_ms"]["p50"] is not None
    assert 1 <= snap["inflight_max"] <= 2
    assert snap["inflight_mean"] >= 1


def test_metrics_record_occupancy_and_latency(rng):
    metrics = ServeMetrics()
    eng = StubEngine(max_batch=16)
    b = DynamicBatcher(eng, max_wait_us=5000, queue_depth=64,
                       metrics=metrics).start()
    try:
        for _ in range(4):
            b.submit(_rows(rng, 2)).result(timeout=10)
    finally:
        b.stop()
    snap = metrics.snapshot()
    assert snap["requests"] == 4 and snap["rows"] == 8
    assert snap["latency_ms"]["p50"] is not None
    assert snap["latency_ms"]["p99"] >= snap["latency_ms"]["p50"]
    occ = snap["batch_occupancy"]
    assert occ, "occupancy histogram empty"
    assert sum(v["rows"] for v in occ.values()) == 8
    for v in occ.values():
        assert 0 < v["occupancy"] <= 1


# -- ISSUE 4: cost-model batch former + adaptive coalescing ---------------


def _gated_drain(rng, eng, b, sizes):
    """Occupy the (single-slot) pipeline with a 1-row dispatch wedged at
    the fetch gate, queue `sizes` behind it, then release the gate —
    the queued requests coalesce into ONE drain that the batch former
    plans. Returns the (x, future) pairs of the queued requests."""
    first = b.submit(_rows(rng, 1))
    assert eng.in_call.wait(timeout=10)
    xs = [_rows(rng, n) for n in sizes]
    futs = [b.submit(x) for x in xs]
    eng.gate.set()
    first.result(timeout=10)
    return list(zip(xs, futs))


def test_batch_former_splits_one_drain_into_bucket_shaped_dispatches(rng):
    """With a compute-priced cost table, a 20-row drain dispatches as
    16+4 (the ISSUE example), not one padded 32 — and every request
    still gets exactly its own rows back, in order, across the split."""
    eng = StubEngine(max_batch=32)
    eng.costs = eng.linear_costs()
    gate = threading.Event()
    eng.gate = gate
    b = DynamicBatcher(eng, max_wait_us=50_000, queue_depth=256).start()
    try:
        pairs = _gated_drain(rng, eng, b, [4, 4, 4, 4, 4])
        for x, f in pairs:
            want = x.reshape(x.shape[0], -1)[:, :10].astype(np.float32)
            np.testing.assert_array_equal(f.result(timeout=10), want)
    finally:
        b.stop()
    assert eng.calls[0] == 1
    assert sorted(eng.calls[1:]) == [4, 16], (
        f"expected a 16+4 split dispatch, got {eng.calls}")


def test_split_disabled_restores_single_covering_dispatch(rng):
    """split=False is the escape hatch: the same drain that would split
    under the cost table goes out as one dispatch."""
    eng = StubEngine(max_batch=32)
    eng.costs = eng.linear_costs()
    gate = threading.Event()
    eng.gate = gate
    b = DynamicBatcher(eng, max_wait_us=50_000, queue_depth=256,
                       split=False).start()
    try:
        pairs = _gated_drain(rng, eng, b, [4, 4, 4, 4, 4])
        for _, f in pairs:
            f.result(timeout=10)
    finally:
        b.stop()
    assert eng.calls == [1, 20], eng.calls


def test_padding_accounting_exact_under_split_dispatches(rng):
    """The ISSUE 4 accounting contract: over a stream of split and
    unsplit dispatches, the metrics' padded/dispatched row counters
    equal the per-dispatch sums reconstructed from the engine's own
    call log — no double count, no leak, and the waste ratio is their
    quotient."""
    metrics = ServeMetrics()
    eng = StubEngine(max_batch=32)
    eng.costs = eng.linear_costs()
    gate = threading.Event()
    eng.gate = gate
    b = DynamicBatcher(eng, max_wait_us=50_000, queue_depth=4096,
                       metrics=metrics).start()
    try:
        pairs = _gated_drain(rng, eng, b, [3, 4, 4, 4, 4])
        for _, f in pairs:
            f.result(timeout=10)
        # a second, unsplittable lone request pads to its bucket
        b.submit(_rows(rng, 5)).result(timeout=10)
    finally:
        b.stop()
    snap = metrics.snapshot()
    dispatched = sum(eng.bucket_for(c) for c in eng.calls)
    padded = sum(eng.bucket_for(c) - c for c in eng.calls)
    assert snap["dispatched_rows"] == dispatched
    assert snap["padded_rows"] == padded
    assert snap["padding_waste_ratio"] == round(padded / dispatched, 4)
    assert sum(snap["bucket_dispatches"].values()) == len(eng.calls)
    assert snap["batches"] == len(eng.calls)
    # the depth gauge counts DISPATCHED segments only: a split drain's
    # popped-but-undispatched tail must not read as phantom overlap on
    # this serial (max_inflight=1) pipeline
    assert snap["inflight_max"] <= 1


def test_stop_drain_resolves_popped_but_undispatched_segments(rng):
    """The PR 2 drain hole, audited for the batch former: stop(drain=
    True) lands while a split drain's later segments are popped off the
    queue but NOT yet dispatched (the single window slot is held by a
    wedged fetch). Every accepted future must still resolve with its
    own rows."""
    eng = StubEngine(max_batch=32)
    eng.costs = eng.linear_costs()
    gate = threading.Event()
    eng.gate = gate
    b = DynamicBatcher(eng, max_wait_us=20_000, queue_depth=256,
                       max_inflight=1).start()
    first = b.submit(_rows(rng, 1))
    assert eng.in_call.wait(timeout=10)
    # queued behind the wedged fetch; will coalesce into one drain that
    # the former splits into >= 2 segments
    xs = [_rows(rng, n) for n in (4, 4, 4, 4, 4)]
    futs = [b.submit(x) for x in xs]
    # let the dispatch thread pop + plan the drain, then stop while its
    # later segments are still waiting on the window slot
    threading.Timer(0.3, gate.set).start()
    time.sleep(0.1)
    b.stop(drain=True)
    first.result(timeout=0)
    for x, f in zip(xs, futs):
        want = x.reshape(x.shape[0], -1)[:, :10].astype(np.float32)
        np.testing.assert_array_equal(f.result(timeout=0), want)
    assert b.pending_rows() == 0 and b.inflight_batches() == 0
    assert len(eng.calls) >= 3, eng.calls      # 1-row + a split drain


def test_effective_wait_gauge_recorded(rng):
    metrics = ServeMetrics()
    eng = StubEngine(max_batch=16)
    b = DynamicBatcher(eng, max_wait_us=5000, queue_depth=64,
                       metrics=metrics).start()
    try:
        for _ in range(3):
            b.submit(_rows(rng, 2)).result(timeout=10)
    finally:
        b.stop()
    gauge = metrics.snapshot()["effective_wait_us"]
    assert gauge["last"] is not None and gauge["last"] <= 5000
    assert gauge["mean"] is not None


def test_adaptive_controller_wired_end_to_end(rng):
    """A microsecond SLO makes every served request a violation: the
    batcher-fed controller must step the effective wait down from the
    configured cap, and the violation count must show up in its
    snapshot. --no-adaptive (adaptive=False) must leave no controller
    in the loop at all."""
    eng = StubEngine(max_batch=16)
    b = DynamicBatcher(eng, max_wait_us=10_000, queue_depth=64,
                       slo_ms=0.001).start()
    try:
        for _ in range(4):
            b.submit(_rows(rng, 2)).result(timeout=10)
    finally:
        b.stop()
    snap = b.controller.snapshot()
    assert snap["violations"] >= 4
    assert b.controller.effective_wait_s() < 10_000 / 1e6
    assert DynamicBatcher(eng, adaptive=False).controller is None
    with pytest.raises(ValueError, match="slo_ms"):
        DynamicBatcher(eng, slo_ms=0)
