"""serve/batcher.py: coalescing under max_wait_us, max_batch-triggered
flush, per-request fan-out correctness, bounded-queue backpressure
(Rejected at the watermark), and metrics recording — all against a stub
engine with a controllable infer(), so the batching logic is tested in
isolation from jax."""

import threading
import time

import numpy as np
import pytest

from distributedmnist_tpu.serve import DynamicBatcher, Rejected, ServeMetrics
from distributedmnist_tpu.serve.engine import InferenceEngine


class StubEngine:
    """Engine-shaped test double. infer() returns each row's first 10
    pixel values as float 'logits', so a request's result identifies
    exactly which input rows it was served from. An optional gate Event
    makes dispatch block deterministically (backpressure tests)."""

    def __init__(self, max_batch=16, n_chips=4, gate=None):
        self.max_batch = max_batch
        self.buckets = tuple(n_chips * 2 ** i for i in range(
            max(1, (max_batch // n_chips).bit_length())))
        while self.buckets[-1] < max_batch:
            self.buckets += (self.buckets[-1] * 2,)
        self.gate = gate
        self.calls = []            # row counts per infer() call
        self.in_call = threading.Event()

    _as_images = staticmethod(InferenceEngine._as_images)

    def bucket_for(self, n):
        for b in self.buckets:
            if b >= n:
                return b
        raise ValueError(n)

    def infer(self, x):
        self.calls.append(x.shape[0])
        self.in_call.set()
        if self.gate is not None:
            assert self.gate.wait(timeout=30)
        return x.reshape(x.shape[0], -1)[:, :10].astype(np.float32)


def _rows(rng, n):
    return rng.integers(0, 256, (n, 28, 28, 1)).astype(np.uint8)


def test_coalesces_waiting_requests_into_one_dispatch(rng):
    eng = StubEngine(max_batch=16)
    gate = threading.Event()
    eng.gate = gate
    b = DynamicBatcher(eng, max_wait_us=50_000, queue_depth=256).start()
    try:
        # first submit occupies the dispatch thread at the gate; the next
        # three queue up behind it and MUST coalesce into one batch
        first = b.submit(_rows(rng, 1))
        assert eng.in_call.wait(timeout=10)
        futs = [b.submit(_rows(rng, 2)) for _ in range(3)]
        gate.set()
        first.result(timeout=10)
        for f in futs:
            assert f.result(timeout=10).shape == (2, 10)
        assert eng.calls[0] == 1
        assert eng.calls[1] == 6, (
            f"expected one coalesced 6-row dispatch, got {eng.calls}")
    finally:
        b.stop()


def test_full_batch_flushes_before_max_wait(rng):
    """max_batch rows pending dispatch immediately — a 5-second wait
    bound must NOT be paid when the batch is already full."""
    eng = StubEngine(max_batch=8)
    b = DynamicBatcher(eng, max_wait_us=5_000_000, queue_depth=256).start()
    try:
        t0 = time.monotonic()
        futs = [b.submit(_rows(rng, 4)) for _ in range(2)]   # = max_batch
        for f in futs:
            f.result(timeout=10)
        assert time.monotonic() - t0 < 2.0, (
            "a full batch waited for the coalescing deadline")
    finally:
        b.stop()


def test_lone_request_is_served_within_the_wait_bound(rng):
    eng = StubEngine(max_batch=16)
    b = DynamicBatcher(eng, max_wait_us=10_000, queue_depth=256).start()
    try:
        out = b.submit(_rows(rng, 3)).result(timeout=10)
        assert out.shape == (3, 10)
        assert eng.calls == [3]
    finally:
        b.stop()


def test_fan_out_maps_each_request_to_its_own_rows(rng):
    """Coalesce-then-slice must hand every request exactly its own rows'
    results, in its own order — the stub's identity 'logits' make any
    off-by-one or reordering visible."""
    eng = StubEngine(max_batch=32)
    gate = threading.Event()
    eng.gate = gate
    b = DynamicBatcher(eng, max_wait_us=50_000, queue_depth=256).start()
    try:
        b.submit(_rows(rng, 1))          # occupy dispatch at the gate
        assert eng.in_call.wait(timeout=10)
        xs = [_rows(rng, n) for n in (3, 1, 5)]
        futs = [b.submit(x) for x in xs]
        gate.set()
        for x, f in zip(xs, futs):
            want = x.reshape(x.shape[0], -1)[:, :10].astype(np.float32)
            np.testing.assert_array_equal(f.result(timeout=10), want)
    finally:
        b.stop()


def test_backpressure_rejects_past_watermark_and_recovers(rng):
    metrics = ServeMetrics()
    eng = StubEngine(max_batch=4)
    gate = threading.Event()
    eng.gate = gate
    b = DynamicBatcher(eng, max_wait_us=1000, queue_depth=8,
                       metrics=metrics).start()
    try:
        b.submit(_rows(rng, 4))          # in dispatch, blocked at gate
        assert eng.in_call.wait(timeout=10)
        ok = [b.submit(_rows(rng, 4)), b.submit(_rows(rng, 4))]  # 8 pending
        with pytest.raises(Rejected):
            b.submit(_rows(rng, 1))      # watermark exceeded -> shed
        assert metrics.snapshot()["rejected_requests"] == 1
        gate.set()                       # drain
        for f in ok:
            f.result(timeout=10)
        # queue drained: admission works again
        assert b.submit(_rows(rng, 2)).result(timeout=10).shape == (2, 10)
    finally:
        b.stop()


def test_oversized_request_is_a_client_error(rng):
    eng = StubEngine(max_batch=8)
    b = DynamicBatcher(eng, queue_depth=64).start()
    try:
        with pytest.raises(ValueError, match="max_batch"):
            b.submit(_rows(rng, 9))
    finally:
        b.stop()


def test_stop_without_drain_fails_pending_futures(rng):
    eng = StubEngine(max_batch=4)
    gate = threading.Event()
    eng.gate = gate
    b = DynamicBatcher(eng, max_wait_us=1000, queue_depth=64).start()
    b.submit(_rows(rng, 4))
    assert eng.in_call.wait(timeout=10)
    pending = b.submit(_rows(rng, 2))
    b.stop(drain=False)
    gate.set()
    with pytest.raises(RuntimeError, match="stopped"):
        pending.result(timeout=10)
    with pytest.raises(RuntimeError, match="stopped"):
        b.submit(_rows(rng, 1))


def test_metrics_record_occupancy_and_latency(rng):
    metrics = ServeMetrics()
    eng = StubEngine(max_batch=16)
    b = DynamicBatcher(eng, max_wait_us=5000, queue_depth=64,
                       metrics=metrics).start()
    try:
        for _ in range(4):
            b.submit(_rows(rng, 2)).result(timeout=10)
    finally:
        b.stop()
    snap = metrics.snapshot()
    assert snap["requests"] == 4 and snap["rows"] == 8
    assert snap["latency_ms"]["p50"] is not None
    assert snap["latency_ms"]["p99"] >= snap["latency_ms"]["p50"]
    occ = snap["batch_occupancy"]
    assert occ, "occupancy histogram empty"
    assert sum(v["rows"] for v in occ.values()) == 8
    for v in occ.values():
        assert 0 < v["occupancy"] <= 1
