"""Native C++ IDX reader tests: builds via g++ (skipped when no
toolchain), asserts byte-identical parity with the Python parser."""

import gzip
import struct

import numpy as np
import pytest

from distributedmnist_tpu.data import native


requires_native = pytest.mark.skipif(
    not native.ensure_built(), reason="g++ toolchain unavailable")


def _write_idx(path, arr):
    dims = arr.shape
    header = struct.pack(f">I{len(dims)}I", 0x0800 | len(dims), *dims)
    with open(path, "wb") as f:
        f.write(header)
        f.write(arr.astype(np.uint8).tobytes())


@requires_native
def test_native_read_matches_python(tmp_path):
    rng = np.random.default_rng(0)
    arr = rng.integers(0, 255, (50, 28, 28)).astype(np.uint8)
    p = str(tmp_path / "images-idx3-ubyte")
    _write_idx(p, arr)

    got = native.read_idx(p)
    np.testing.assert_array_equal(got, arr)

    # parity with the Python parser through the public loader path
    from distributedmnist_tpu.data.mnist import _read_idx
    np.testing.assert_array_equal(_read_idx(p), arr)


@requires_native
def test_native_read_1d(tmp_path):
    labels = np.arange(100, dtype=np.uint8) % 10
    p = str(tmp_path / "labels-idx1-ubyte")
    _write_idx(p, labels)
    np.testing.assert_array_equal(native.read_idx(p), labels)


@requires_native
def test_native_rejects_bad_magic(tmp_path):
    p = str(tmp_path / "bad")
    with open(p, "wb") as f:
        f.write(b"\xde\xad\xbe\xef" + b"\x00" * 16)
    with pytest.raises(ValueError, match="idx_probe"):
        native.read_idx(p)


@requires_native
def test_gzip_still_uses_python_path(tmp_path):
    """.gz must route to the Python parser (native reads raw only)."""
    rng = np.random.default_rng(1)
    arr = rng.integers(0, 255, (10, 28, 28)).astype(np.uint8)
    raw = str(tmp_path / "x-idx3-ubyte")
    _write_idx(raw, arr)
    gz = raw + ".gz"
    with open(raw, "rb") as fin, gzip.open(gz, "wb") as fout:
        fout.write(fin.read())
    from distributedmnist_tpu.data.mnist import _read_idx
    np.testing.assert_array_equal(_read_idx(gz), arr)


def test_available_never_compiles(tmp_path, monkeypatch):
    """available() must not shell out to g++ — cold start stays fast."""
    calls = []
    monkeypatch.setattr(native.subprocess, "run",
                        lambda *a, **k: calls.append(a))
    native.available()
    assert calls == []


def test_python_fallback_when_lib_missing(monkeypatch, tmp_path):
    """With the native path forced off, the loader still works."""
    monkeypatch.setattr(native, "available", lambda: False)
    rng = np.random.default_rng(2)
    arr = rng.integers(0, 255, (5, 28, 28)).astype(np.uint8)
    p = str(tmp_path / "y-idx3-ubyte")
    _write_idx(p, arr)
    from distributedmnist_tpu.data.mnist import _read_idx
    np.testing.assert_array_equal(_read_idx(p), arr)
