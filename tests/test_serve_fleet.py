"""serve/fleet.py: the replica fleet's dispatch pick (cost-aware,
health-tracked, per-replica bounded windows), failover redispatch at
dispatch AND fetch, hedged tails, breaker exclusion + limp mode,
drain/rejoin admin, the registry's fleet-wide fan-out with real
engines, and the serve.py wiring (auto window sizing, per-replica
metrics attribution, Retry-After cap)."""

import threading
import time
from types import SimpleNamespace

import numpy as np
import pytest

from distributedmnist_tpu.serve import faults
from distributedmnist_tpu.serve.fleet import (FleetHandle,
                                              NoReplicaAvailable,
                                              ReplicaSet)
from distributedmnist_tpu.serve.resilience import HealthTracker
from distributedmnist_tpu.serve.router import NoLiveModel

pytestmark = pytest.mark.fleet


@pytest.fixture(autouse=True)
def _no_leaked_injector():
    faults.uninstall()
    yield
    faults.uninstall()


class StubRouter:
    """Router-shaped replica double: fetch() returns each row's first
    10 pixels so results identify their input rows exactly (the proof
    a failover rescue served the ORIGINAL payload). Failure switches
    and a fetch gate make death and slowness deterministic."""

    platform = "cpu"
    n_chips = 1

    def __init__(self, rid, costs=True):
        self.replica = rid
        self.max_batch = 16
        self.buckets = (4, 8, 16)
        self._costs = ({4: 1e-3, 8: 2e-3, 16: 4e-3} if costs else {})
        self.fail_dispatch = False
        self.fail_fetch = False
        self.gate = None              # Event: fetch blocks until set
        self.dispatches = 0
        self.fetches = 0
        self.live = "v1"

    def bucket_costs(self):
        return dict(self._costs)

    def bucket_costs_p95(self):
        return {b: 1.5 * c for b, c in self._costs.items()}

    def live_version(self):
        return self.live

    def dispatch(self, x):
        if self.fail_dispatch:
            raise RuntimeError(f"{self.replica} dead at dispatch")
        parts = x if isinstance(x, (list, tuple)) else [x]
        flat = np.concatenate([np.asarray(p).reshape(p.shape[0], -1)
                               for p in parts])
        self.dispatches += 1
        bucket = next(b for b in self.buckets if b >= flat.shape[0])
        return SimpleNamespace(version=self.live, n=flat.shape[0],
                               bucket=bucket, flat=flat)

    def fetch(self, rh):
        if self.gate is not None:
            assert self.gate.wait(timeout=30)
        if self.fail_fetch:
            raise RuntimeError(f"{self.replica} dead at fetch")
        self.fetches += 1
        return rh.flat[:, :10].astype(np.float32)


def _fleet(n=2, costs=True, **kw):
    routers = [StubRouter(f"r{i}", costs=costs) for i in range(n)]
    kw.setdefault("per_replica_inflight", 2)
    return ReplicaSet(routers, **kw), routers


def _req(rng, n=4):
    return rng.integers(0, 256, (n, 28, 28, 1)).astype(np.uint8)


def test_fleet_rejects_degenerate_configs():
    with pytest.raises(ValueError, match=">= 2 replicas"):
        ReplicaSet([StubRouter("r0")])
    bad = StubRouter("r1")
    bad.buckets = (2, 4)
    with pytest.raises(ValueError, match="geometry"):
        ReplicaSet([StubRouter("r0"), bad])


def test_engine_shape_and_window_total():
    fleet, _ = _fleet(n=3, per_replica_inflight=2)
    assert fleet.max_batch == 16 and fleet.buckets == (4, 8, 16)
    assert fleet.platform == "cpu"
    assert fleet.n_replicas == 3
    assert fleet.max_inflight_total == 6
    assert fleet.bucket_for(5) == 8
    assert fleet.bucket_costs() == {4: 1e-3, 8: 2e-3, 16: 4e-3}


def test_dispatch_balances_across_replicas(rng):
    """With symmetric replicas the cost-aware pick degrades to
    round-robin (dispatched_batches tiebreak): a synchronous
    dispatch-fetch loop must split the load within one batch."""
    fleet, routers = _fleet(n=2)
    for _ in range(10):
        out = fleet.infer(_req(rng))
        assert out.shape == (4, 10)
    counts = [r.dispatches for r in routers]
    assert abs(counts[0] - counts[1]) <= 1, counts


def test_pick_prefers_cheapest_outstanding_backlog(rng):
    """A replica holding reserved work is priced by the bucket cost
    table: the next dispatch goes to the idle sibling."""
    fleet, routers = _fleet(n=2)
    h1 = fleet.dispatch(_req(rng))          # lands on one replica
    h2 = fleet.dispatch(_req(rng))          # must land on the other
    assert {h1.replica, h2.replica} == {"r0", "r1"}
    fleet.fetch(h1)
    fleet.fetch(h2)


def test_per_replica_window_bounds_and_blocks(rng):
    """per_replica_inflight=1 x 2 replicas: the third dispatch blocks
    until a fetch frees a slot — the fleet's own window, independent
    of the batcher's semaphore."""
    fleet, routers = _fleet(n=2, per_replica_inflight=1)
    h1 = fleet.dispatch(_req(rng))
    h2 = fleet.dispatch(_req(rng))
    got = []
    t = threading.Thread(target=lambda: got.append(
        fleet.dispatch(_req(rng))), daemon=True)
    t.start()
    t.join(timeout=0.3)
    assert t.is_alive(), "third dispatch should block at full windows"
    fleet.fetch(h1)
    t.join(timeout=5)
    assert not t.is_alive() and got
    fleet.fetch(h2)
    fleet.fetch(got[0])
    snap = fleet.snapshot()
    assert all(r["inflight"] == 0 for r in snap["replicas"])


def test_failover_at_dispatch_rescues_batch(rng):
    fleet, routers = _fleet(n=2)
    routers[0].fail_dispatch = routers[1].fail_dispatch = False
    # force the first pick onto r0 by loading r1 with outstanding work
    hb = fleet.dispatch(_req(rng))
    victim = [r for r in routers if r.replica != hb.replica][0]
    victim.fail_dispatch = True
    x = _req(rng)
    h = fleet.dispatch(x)                   # picked victim, rescued
    assert h.replica == hb.replica
    out = fleet.fetch(h)
    np.testing.assert_array_equal(
        out, x.reshape(4, -1)[:, :10].astype(np.float32))
    fleet.fetch(hb)
    snap = fleet.snapshot()
    assert snap["failovers"]["dispatch"] == 1
    assert snap["health"][victim.replica]["failures"] == 1


def test_failover_at_fetch_redispatches_payload(rng):
    """The fetch-side death: the handle's retained payload re-runs on
    the sibling and the result still matches the ORIGINAL rows; the
    handle re-tags to the computing replica."""
    fleet, routers = _fleet(n=2)
    x = _req(rng, 6)
    h = fleet.dispatch(x)
    victim = next(r for r in routers if r.replica == h.replica)
    sibling = next(r for r in routers if r.replica != h.replica)
    victim.fail_fetch = True
    out = fleet.fetch(h)
    np.testing.assert_array_equal(
        out, x.reshape(6, -1)[:, :10].astype(np.float32))
    assert h.replica == sibling.replica       # re-tagged
    snap = fleet.snapshot()
    assert snap["failovers"]["fetch"] == 1
    assert all(r["inflight"] == 0 for r in snap["replicas"])


def test_failover_gives_up_without_sibling_and_systemic_503(rng):
    fleet, routers = _fleet(n=2)
    # no healthy sibling: both dead at dispatch -> the error propagates
    routers[0].fail_dispatch = routers[1].fail_dispatch = True
    with pytest.raises(RuntimeError, match="dead at dispatch"):
        fleet.dispatch(_req(rng))
    routers[0].fail_dispatch = routers[1].fail_dispatch = False

    # systemic 503 (no live model) must NOT failover or blame a replica
    def no_live(x):
        raise NoLiveModel("warming")

    before = fleet.snapshot()
    routers[0].dispatch = routers[1].dispatch = no_live
    with pytest.raises(NoLiveModel):
        fleet.dispatch(_req(rng))
    snap = fleet.snapshot()
    assert snap["failovers"] == before["failovers"]
    # the systemic shed added no failures beyond the real ones above
    assert sum(r["failures"] for r in snap["replicas"]) \
        == sum(r["failures"] for r in before["replicas"])


@pytest.mark.chaos
def test_injected_replica_kill_is_rescued_end_to_end(rng):
    """The chaos-bench storm in miniature: a replica.fetch rule pinned
    to one replica kills its batches; every one must be rescued on the
    sibling (futures resolve OK, failovers counted, nothing surfaces)."""
    fleet, routers = _fleet(n=2)
    faults.install(faults.FaultInjector.from_spec(
        "replica.fetch:p=1,replica=r1,count=3", seed=5))
    for _ in range(8):
        out = fleet.infer(_req(rng))
        assert out.shape == (4, 10)
    snap = fleet.snapshot()
    assert snap["failovers"]["fetch"] == 3
    assert snap["replicas"][1]["failures"] == 3


@pytest.mark.chaos
def test_killed_fetch_drains_abandoned_staging(rng):
    """A replica.fetch kill fires BEFORE the engine's own fetch runs,
    so the victim's handle still pins its staging checkout when
    failover moves the batch to the sibling. The fleet must drain the
    abandoned handle (fetch-and-discard on a daemon thread, the
    hedge-loser pattern) so the balance returns to zero — otherwise
    every killed fetch leaks one pooled buffer (the PR 5 class on the
    fleet path; the conftest sanitizer fixture would fail this test's
    teardown without the drain)."""
    from distributedmnist_tpu.analysis import sanitize

    class AccountingRouter(StubRouter):
        """StubRouter plus engine-style staging accounting: checkout at
        dispatch, recycle-in-finally at fetch, one-shot handles."""

        def dispatch(self, x):
            rh = super().dispatch(x)
            sanitize.resource_acquire("engine.staging")
            rh.staged = True
            return rh

        def fetch(self, rh):
            if not getattr(rh, "staged", False):
                raise RuntimeError("handle already fetched")
            try:
                return super().fetch(rh)
            finally:
                rh.staged = False
                sanitize.resource_release("engine.staging")

    san = sanitize.active_sanitizer()
    assert san is not None        # the conftest autouse fixture's
    routers = [AccountingRouter(f"r{i}") for i in range(2)]
    fleet = ReplicaSet(routers, per_replica_inflight=2)
    faults.install(faults.FaultInjector.from_spec(
        "replica.fetch:p=1,replica=r1,count=2", seed=5))
    try:
        for _ in range(6):
            assert fleet.infer(_req(rng)).shape == (4, 10)
    finally:
        faults.uninstall()
    assert fleet.snapshot()["failovers"]["fetch"] == 2
    # the drains run on daemon threads — give them a moment to land
    assert san.wait_drained(), (
        "killed fetches leaked their staging checkouts: "
        f"{san.balances()}")
    assert not san.resource_errors()


def test_drain_abandoned_skips_engine_fetched_handles():
    """A handle whose ENGINE fetch already ran (real fetch error: the
    engine recycled staging in its finally and Router.fetch's except
    already drained the shadow duplicate) must NOT be re-fetched by the
    abandonment drain — a second Router.fetch would double-enqueue the
    same shadow comparison and drift the router's _shadow_pending claim
    count negative. An engine-fetched InferenceHandle has staging None
    (the one-shot marker); a never-fetched one still drains."""
    fleet, routers = _fleet(n=2)
    drained = []
    routers[0].fetch = drained.append

    fetched = SimpleNamespace(handle=SimpleNamespace(staging=None))
    fleet._drain_abandoned(fleet.replicas[0], fetched)
    unfetched = SimpleNamespace(
        handle=SimpleNamespace(staging=np.zeros(1)))
    fleet._drain_abandoned(fleet.replicas[0], unfetched)

    deadline = time.monotonic() + 5.0
    while not drained and time.monotonic() < deadline:
        time.sleep(0.02)
    time.sleep(0.1)               # give a wrong extra drain time to land
    assert drained == [unfetched], (
        "drain must skip engine-fetched handles and fetch abandoned "
        f"ones exactly once; got {drained}")


def test_breaker_trip_excludes_replica_then_limp_mode(rng):
    fleet, routers = _fleet(n=2)
    # trip r1: feed it failures directly through the recording path
    r1 = fleet.replicas[1]
    for _ in range(10):
        fleet._record(r1, ok=False)
    assert fleet.breaker.in_cooldown("r1")
    snap = fleet.snapshot()
    assert snap["replica_trips"] == 1
    assert snap["replicas"][1]["healthy"] is False
    d0 = routers[0].dispatches
    for _ in range(4):
        fleet.infer(_req(rng))
    assert routers[0].dispatches == d0 + 4      # r1 never picked
    assert routers[1].dispatches == 0
    # now trip r0 too: limp mode keeps serving on least-loaded anyway
    for _ in range(10):
        fleet._record(fleet.replicas[0], ok=False)
    assert fleet.breaker.in_cooldown("r0")
    out = fleet.infer(_req(rng))
    assert out.shape == (4, 10)


def test_drain_rejoin_and_last_active_refusal(rng):
    fleet, routers = _fleet(n=2)
    snap = fleet.drain("r1")
    assert snap["state"] == "draining"
    with pytest.raises(RuntimeError, match="last active"):
        fleet.drain("r0")
    with pytest.raises(KeyError, match="unknown replica"):
        fleet.drain("r9")
    for _ in range(4):
        fleet.infer(_req(rng))
    assert routers[1].dispatches == 0           # drained: no new picks
    # rejoin wipes the health slate (pre-repair failures must not
    # re-trip the replica on its first post-rejoin batch)
    for _ in range(10):
        fleet._record(fleet.replicas[1], ok=False)
    assert fleet.breaker.in_cooldown("r1")
    snap = fleet.rejoin("r1")
    assert snap["state"] == "active" and snap["healthy"] is True
    assert not fleet.breaker.in_cooldown("r1")
    fleet.infer(_req(rng))
    assert routers[1].dispatches >= 1


def test_draining_replica_still_fetches_inflight(rng):
    """Drain during in-flight: the batch already on the draining
    replica fetches normally — only NEW picks are excluded."""
    fleet, routers = _fleet(n=2)
    x = _req(rng)
    h = fleet.dispatch(x)
    fleet.drain(h.replica)
    out = fleet.fetch(h)
    np.testing.assert_array_equal(
        out, x.reshape(4, -1)[:, :10].astype(np.float32))


def test_all_replicas_draining_is_systemic_503(rng):
    """White-box: the admin API refuses to empty the fleet, but if
    every replica is nevertheless draining (future autoscaler paths),
    dispatch sheds with 503 semantics — systemic, never bisected."""
    fleet, _ = _fleet(n=2)
    for rep in fleet.replicas:
        rep.state = "draining"
    with pytest.raises(NoReplicaAvailable) as ei:
        fleet.dispatch(_req(np.random.default_rng(0)))
    assert ei.value.status == 503


def test_hedge_races_overdue_batch_and_duplicate_wins(rng):
    """A batch past hedge_factor x p95(bucket) at fetch time races a
    duplicate on the free sibling; with the primary gated shut the
    duplicate must win, re-tagging the handle. The gated primary then
    finishes in the background without corrupting the accounting."""
    fleet, routers = _fleet(n=2, hedge=True, hedge_factor=1.0)
    gate = threading.Event()
    x = _req(rng, 5)
    h = fleet.dispatch(x)
    primary = next(r for r in routers if r.replica == h.replica)
    sibling = next(r for r in routers if r.replica != h.replica)
    primary.gate = gate
    # p95 for bucket 8 is 3ms; hedge_factor 1.0 -> threshold 3ms
    time.sleep(0.02)
    out = fleet.fetch(h)
    np.testing.assert_array_equal(
        out, x.reshape(5, -1)[:, :10].astype(np.float32))
    assert h.replica == sibling.replica
    snap = fleet.snapshot()
    assert snap["hedges"] == {"fired": 1, "wins": 1}
    gate.set()                       # let the loser finish
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline:
        if all(r["inflight"] == 0
               for r in fleet.snapshot()["replicas"]):
            break
        time.sleep(0.01)
    assert all(r["inflight"] == 0 for r in fleet.snapshot()["replicas"])


def test_hedge_not_fired_inside_threshold(rng):
    fleet, routers = _fleet(n=2, hedge=True, hedge_factor=1000.0)
    h = fleet.dispatch(_req(rng))
    fleet.fetch(h)
    assert fleet.snapshot()["hedges"]["fired"] == 0


def test_hedge_never_targets_a_tripped_sibling(rng):
    """A duplicate on a breaker-tripped replica is guaranteed wasted
    work: with the only sibling in cooldown, an overdue batch fetches
    plain — no hedge fires (unlike rescues, which may limp)."""
    fleet, routers = _fleet(n=2, hedge=True, hedge_factor=1.0)
    h = fleet.dispatch(_req(rng))
    sibling = next(rep for rep in fleet.replicas
                   if rep.rid != h.replica)
    for _ in range(10):
        fleet._record(sibling, ok=False)
    assert fleet.breaker.in_cooldown(sibling.rid)
    time.sleep(0.02)                 # past the 3ms bucket-8 threshold
    out = fleet.fetch(h)
    assert out.shape == (4, 10)
    assert fleet.snapshot()["hedges"]["fired"] == 0


def test_failover_counts_only_landed_rescues(rng):
    """A rescue that fails the same way the primary did (a fault
    present on EVERY replica, e.g. version-pinned) saved nothing and
    must not count as a failover — the counter's contract is 'batches
    redundancy saved'."""
    fleet, routers = _fleet(n=2)
    x = _req(rng)
    h = fleet.dispatch(x)
    routers[0].fail_fetch = routers[1].fail_fetch = True
    with pytest.raises(RuntimeError, match="dead at fetch"):
        fleet.fetch(h)
    snap = fleet.snapshot()
    assert snap["failovers"] == {"dispatch": 0, "fetch": 0}
    assert all(r["inflight"] == 0 for r in snap["replicas"])


def test_promote_fanout_requires_full_engine_list():
    fleet, _ = _fleet(n=2)
    with pytest.raises(ValueError, match="one engine per replica"):
        fleet.set_live([object()], "v2")


def test_health_tracker_window_and_reset():
    t = HealthTracker(window_s=0.2)
    assert t.score("r0") == 1.0
    t.record("r0", ok=False, n=3, latency_s=0.01)
    t.record("r0", ok=True, n=1)
    assert t.score("r0") == pytest.approx(0.25)
    snap = t.snapshot()["r0"]
    assert snap["volume"] == 4 and snap["failures"] == 3
    assert snap["latency_ewma_ms"] == pytest.approx(10.0)
    time.sleep(0.25)
    assert t.score("r0") == 1.0          # window slid past the failures
    t.record("r0", ok=False)
    t.reset("r0")
    assert t.score("r0") == 1.0
    with pytest.raises(ValueError):
        HealthTracker(window_s=0)


# -- batcher + metrics integration ----------------------------------------


def test_batcher_auto_window_opens_to_fleet_total(rng):
    from distributedmnist_tpu.serve import DynamicBatcher

    fleet, _ = _fleet(n=3, per_replica_inflight=2)
    b = DynamicBatcher(fleet, max_wait_us=100)
    assert b.max_inflight == 6
    # an explicit value still wins (the bench's pinned phases)
    b2 = DynamicBatcher(fleet, max_wait_us=100, max_inflight=1)
    assert b2.max_inflight == 1


def test_batcher_attributes_batches_per_replica(rng):
    from distributedmnist_tpu.serve import DynamicBatcher, ServeMetrics

    fleet, routers = _fleet(n=2)
    metrics = ServeMetrics()
    b = DynamicBatcher(fleet, max_wait_us=100, metrics=metrics).start()
    try:
        futs = [b.submit(_req(rng, 2)) for _ in range(12)]
        for f in futs:
            assert f.result(timeout=30).shape == (2, 10)
    finally:
        b.stop()
    by_replica = metrics.snapshot()["by_replica"]
    assert set(by_replica) == {"r0", "r1"}
    assert sum(s["rows"] for s in by_replica.values()) == 24


@pytest.mark.chaos
def test_batcher_failover_is_invisible_to_clients(rng):
    """Through the full batcher: a replica-pinned kill storm costs
    clients nothing — every future resolves, failovers show up only in
    the metrics, and attribution names the RESCUING replica."""
    from distributedmnist_tpu.serve import DynamicBatcher, ServeMetrics

    metrics = ServeMetrics()
    fleet, routers = _fleet(n=2, metrics=metrics)
    faults.install(faults.FaultInjector.from_spec(
        "replica.dispatch:p=1,replica=r0,count=2;"
        "replica.fetch:p=1,replica=r0,after=2,count=2", seed=11))
    # max_batch=2: one request per dispatch, so the storm's after/count
    # windows land on a predictable per-replica batch sequence instead
    # of being swallowed by coalescing
    b = DynamicBatcher(fleet, max_batch=2, max_wait_us=100,
                       metrics=metrics).start()
    try:
        futs = [b.submit(_req(rng, 2)) for _ in range(16)]
        for f in futs:
            assert f.result(timeout=30).shape == (2, 10)
    finally:
        b.stop()
    snap = metrics.snapshot()
    assert snap["fleet"]["failovers_total"] == 4
    assert snap["fleet"]["failovers"] == {"dispatch": 2, "fetch": 2}
    assert snap["fleet"]["last_failover"]["to"] == "r1"


# -- registry fan-out with real engines (the zero-recompile contract) -----


@pytest.fixture()
def fleet_factory(eight_devices):
    from distributedmnist_tpu import models
    from distributedmnist_tpu.parallel import make_mesh
    from distributedmnist_tpu.serve import EngineFactory

    mesh = make_mesh(eight_devices)
    model = models.build("mlp", platform="cpu")
    return EngineFactory(model, mesh, max_batch=16, replicas=2)


def test_factory_slices_mesh_into_disjoint_replicas(fleet_factory):
    assert len(fleet_factory.meshes) == 2
    ids = [set(d.id for d in m.devices.flat)
           for m in fleet_factory.meshes]
    assert ids[0].isdisjoint(ids[1])
    assert fleet_factory.n_chips == 4
    assert fleet_factory.total_chips == 8
    # buckets shard over one REPLICA's data-parallel width
    assert all(b % 4 == 0 for b in fleet_factory.buckets)


def test_registry_fans_warm_and_promote_fleet_wide(fleet_factory, rng):
    from distributedmnist_tpu.serve import ModelRegistry
    from distributedmnist_tpu.utils import CompileCounter

    fleet = fleet_factory.make_fleet()
    registry = ModelRegistry(fleet_factory, fleet)
    mv = registry.add(fleet_factory.init_params(0), version="v1")
    assert len(mv.engines) == 2
    assert mv.describe()["replica_engines"] == 2
    registry.promote("v1")
    assert all(rep.router.live_version() == "v1"
               for rep in fleet.replicas)
    compiles = CompileCounter.instance()
    c0 = compiles.snapshot()
    for _ in range(6):
        assert fleet.infer(rng.integers(
            0, 256, (5, 784)).astype(np.uint8)).shape == (5, 10)
    assert compiles.snapshot() - c0 == 0, (
        "steady-state fleet dispatch recompiled")
    # a roll moves the WHOLE fleet
    registry.add(fleet_factory.init_params(1), version="v2")
    registry.promote("v2")
    assert all(rep.router.live_version() == "v2"
               for rep in fleet.replicas)
    assert registry.describe()["replicas"] == 2
    # drained replica still receives the roll (rejoin can't serve stale)
    fleet.drain("r1")
    registry.promote("v1")
    assert fleet.replicas[1].router.live_version() == "v1"


# -- serve.py surface: Retry-After cap, healthz uptime, fleet admin -------


def _load_serve_mod():
    import importlib.util
    import os

    from conftest import worker_env

    spec = importlib.util.spec_from_file_location(
        "serve_mod_fleet", os.path.join(worker_env()[1], "serve.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_shed_retry_after_is_capped_integer_seconds():
    """ISSUE 6 satellite: the pipeline-derived Retry-After is emitted
    as integer seconds (RFC 9110 delay-seconds), rounded UP from the
    derived estimate, floored at 1, and capped at the configured
    ceiling — a deep window at a spiked batch cost must not tell
    clients to come back in ten minutes."""
    serve_mod = _load_serve_mod()

    class StubBatcher:
        controller = None
        max_wait_s = 0.4

        def __init__(self, inflight, costs):
            self._inflight = inflight
            self.engine = SimpleNamespace(bucket_costs=lambda: costs)

        def inflight_batches(self):
            return self._inflight

    # 0.4s wait + (2+1) * 1.2s cost = 4.0 -> exactly 4 (already whole)
    got = serve_mod.shed_retry_after_s(StubBatcher(2, {16: 1.2}),
                                       cap_s=30)
    assert got == 4 and isinstance(got, int)
    # non-integral estimate rounds UP, never down (an early retry just
    # sheds again)
    assert serve_mod.shed_retry_after_s(
        StubBatcher(1, {16: 1.0}), cap_s=30) == 3    # 0.4 + 2.0 -> 2.4
    # unbounded derivation hits the cap: 0.4 + 33 * 60s >> 30
    assert serve_mod.shed_retry_after_s(
        StubBatcher(32, {16: 60.0}), cap_s=30) == 30
    # fractional caps floor to whole header seconds
    assert serve_mod.shed_retry_after_s(
        StubBatcher(32, {16: 60.0}), cap_s=7.9) == 7
    # idle pipeline with no cost table floors at 1, never 0
    assert serve_mod.shed_retry_after_s(StubBatcher(0, {}),
                                        cap_s=30) == 1


def test_healthz_reports_started_at_and_uptime():
    """ISSUE 6 satellite: /healthz carries the process start (ISO 8601
    UTC) and a monotone-growing uptime so probes can tell a RESTARTED
    worker (uptime reset) from a RECOVERED one."""
    import datetime

    serve_mod = _load_serve_mod()

    class StubRegistry:
        def live_version(self):
            return "v1"

        def describe(self):
            return {"versions": [1]}

    class StubBatcher:
        def pending_rows(self):
            return 0

        def inflight_batches(self):
            return 0

    state = serve_mod.ServerState()
    code, payload = state.healthz(StubRegistry(), StubBatcher())
    assert code == 200
    started = datetime.datetime.fromisoformat(payload["started_at"])
    assert started.tzinfo is not None            # explicit UTC offset
    assert abs(started.timestamp() - time.time()) < 5
    assert payload["uptime_s"] >= 0
    time.sleep(0.05)
    _, later = state.healthz(StubRegistry(), StubBatcher())
    assert later["started_at"] == payload["started_at"]
    assert later["uptime_s"] > payload["uptime_s"]
    # single-replica server: no fleet block
    assert "replicas" not in payload


def test_serve_http_fleet_admin_end_to_end():
    """serve.py --serve-replicas 2: /healthz carries the per-replica
    block, POST /replicas/{id}/drain|rejoin administer the fleet (404
    unknown id, 409 for draining the last active replica), /metrics
    exposes the fleet snapshot, and /predict keeps serving through a
    drain."""
    import json
    import os
    import subprocess
    import sys
    import urllib.error
    import urllib.request

    from conftest import worker_env

    env, repo = worker_env()
    proc = subprocess.Popen(
        [sys.executable, os.path.join(repo, "serve.py"), "--model",
         "mlp", "--device", "cpu", "--serve-max-batch", "16",
         "--serve-replicas", "2", "--port", "0",
         "--metrics-every", "5"],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        env=env, cwd=repo)

    def get(path):
        return json.loads(urllib.request.urlopen(
            f"{base}{path}", timeout=30).read())

    def post(path, data=b""):
        req = urllib.request.Request(f"{base}{path}", data=data)
        return json.loads(urllib.request.urlopen(req, timeout=30).read())

    try:
        port = None
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            line = proc.stdout.readline()
            assert line, "serve.py exited before announcing readiness"
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if rec.get("metric") == "serve_ready":
                port = rec["port"]
                break
        assert port is not None
        base = f"http://127.0.0.1:{port}"
        deadline = time.monotonic() + 120
        ok = None
        while time.monotonic() < deadline:
            try:
                ok = get("/healthz")
                break
            except urllib.error.HTTPError as e:
                assert e.code == 503
                time.sleep(0.2)
        assert ok and ok["ok"] is True
        assert {r["id"] for r in ok["replicas"]} == {"r0", "r1"}
        assert ok["failovers"] == {"dispatch": 0, "fetch": 0}
        assert ok["uptime_s"] > 0 and ok["started_at"]

        body = np.zeros(784 * 3, np.uint8).tobytes()
        out = post("/predict", body)
        assert out["n"] == 3 and len(out["classes"]) == 3

        drained = post("/replicas/r1/drain")
        assert drained["replica"]["state"] == "draining"
        hz = get("/healthz")
        assert {r["id"]: r["state"] for r in hz["replicas"]} == {
            "r0": "active", "r1": "draining"}
        # serving continues on the remaining replica
        assert post("/predict", body)["n"] == 3
        # draining the last active replica is a rule refusal
        with pytest.raises(urllib.error.HTTPError) as ei:
            post("/replicas/r0/drain")
        assert ei.value.code == 409
        with pytest.raises(urllib.error.HTTPError) as ei:
            post("/replicas/r9/drain")
        assert ei.value.code == 404
        rejoined = post("/replicas/r1/rejoin")
        assert rejoined["replica"]["state"] == "active"
        m = get("/metrics")
        assert m["fleet"]["n_replicas"] == 2
        assert set(m["by_replica"]) <= {"r0", "r1"}
    finally:
        proc.terminate()
        try:
            proc.wait(timeout=30)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait(timeout=10)
