"""Localhost multi-process test of the multi-host path (BASELINE.json
config 5 minus the real DCN): 2 processes x 4 virtual devices = one
8-device global mesh, jax.distributed rendezvous, per-process global-batch
assembly, cross-process psum."""

import json
import os
import socket
import subprocess
import sys

import pytest


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


@pytest.mark.slow
def test_two_process_training(tmp_path):
    port = _free_port()
    n = 2
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)  # don't dial the TPU relay
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)  # worker sets its own device count
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    worker = os.path.join(os.path.dirname(__file__), "multihost_worker.py")
    procs = [
        subprocess.Popen(
            [sys.executable, worker, str(i), str(n), str(port)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, env=env, cwd=repo_root)
        for i in range(n)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=600)
            outs.append(out)
    finally:
        for p in procs:  # don't leak workers blocked in a rendezvous
            if p.poll() is None:
                p.kill()
    for p, out in zip(procs, outs):
        assert p.returncode == 0, f"worker failed:\n{out[-3000:]}"

    results = []
    for out in outs:
        lines = [l for l in out.splitlines() if l.startswith("MHRESULT ")]
        assert lines, f"no MHRESULT in output:\n{out[-3000:]}"
        results.append(json.loads(lines[0][len("MHRESULT "):]))

    for r in results:
        assert r["multihost"] is True
        assert r["n_processes"] == 2
        assert r["n_chips"] == 8  # 2 processes x 4 virtual devices
        assert r["steps"] == 6
    # both processes computed the identical replicated result
    assert results[0]["accuracy"] == results[1]["accuracy"]
