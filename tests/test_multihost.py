"""Localhost multi-process test of the multi-host path (BASELINE.json
config 5 minus the real DCN): 2 processes x 4 virtual devices = one
8-device global mesh, jax.distributed rendezvous, per-process global-batch
assembly, cross-process psum."""

import json
import os
import signal
import socket
import subprocess
import sys

import pytest

from conftest import wait_for_committed_checkpoint, worker_env


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def _spawn(n, port, extra=(), cmd=None):
    env, repo_root = worker_env()
    if cmd is None:
        cmd = [sys.executable,
               os.path.join(os.path.dirname(__file__),
                            "multihost_worker.py")]
    return [
        subprocess.Popen(
            cmd + [str(i), str(n), str(port), *map(str, extra)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, env=env, cwd=repo_root)
        for i in range(n)
    ]


def _launch(n, port, extra=(), cmd=None):
    procs = _spawn(n, port, extra, cmd)
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=600)
            outs.append(out)
    finally:
        for p in procs:  # don't leak workers blocked in a rendezvous
            if p.poll() is None:
                p.kill()
    for p, out in zip(procs, outs):
        assert p.returncode == 0, f"worker failed:\n{out[-3000:]}"
    return outs


def _results(outs, tag="MHRESULT "):
    results = []
    for out in outs:
        lines = [l for l in out.splitlines() if l.startswith(tag)]
        assert lines, f"no {tag!r} in output:\n{out[-3000:]}"
        results.append(lines[0][len(tag):])
    return results


@pytest.mark.slow
def test_two_process_training(tmp_path):
    outs = _launch(2, _free_port())
    results = [json.loads(r) for r in _results(outs)]
    for r in results:
        assert r["multihost"] is True
        assert r["n_processes"] == 2
        assert r["n_chips"] == 8  # 2 processes x 4 virtual devices
        assert r["steps"] == 6
    # both processes computed the identical replicated result
    assert results[0]["accuracy"] == results[1]["accuracy"]


@pytest.mark.slow
def test_two_process_checkpoint_kill_resume(tmp_path):
    """Config 5 end-to-end: multi-host async checkpoint, injected failure,
    multi-host restore, completion. orbax coordinates the save across
    processes (process 0 commits the directory)."""
    ckpt = str(tmp_path / "mh-ckpt")
    # run 1: both workers crash at step 5 (checkpoint saved at step 3)
    outs = _launch(2, _free_port(),
                   extra=("--ckpt-dir", ckpt, "--fail-at", 5))
    assert all("MHFAILED injected" in o for o in outs)
    # run 2: restore at step 3, finish steps 4-6
    outs = _launch(2, _free_port(), extra=("--ckpt-dir", ckpt))
    results = [json.loads(r) for r in _results(outs)]
    for r in results:
        assert r["restored"] is True
        assert r["steps"] == 6
    assert results[0]["accuracy"] == results[1]["accuracy"]


@pytest.mark.slow
def test_two_process_sigterm_preemption(tmp_path):
    """Graceful preemption under process_count > 1: SIGTERM delivered to
    ONE process must stop BOTH at the same checkpoint-boundary step (the
    local flags are all-gathered there — a unilateral stop would deadlock
    the collective force-save), both must exit cleanly having saved the
    same step, and a fresh 2-process run must restore it and finish."""
    ckpt = str(tmp_path / "mh-pre")
    procs = _spawn(2, _free_port(),
                   extra=("--ckpt-dir", ckpt, "--steps", "100000"))
    outs = []
    try:
        wait_for_committed_checkpoint(ckpt, procs)
        procs[0].send_signal(signal.SIGTERM)  # process 0 ONLY
        for p in procs:
            out, _ = p.communicate(timeout=300)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for p, out in zip(procs, outs):
        assert p.returncode == 0, f"worker failed:\n{out[-3000:]}"
    results = [json.loads(r) for r in _results(outs)]
    for r in results:
        assert r["preempted"] is True
        assert 0 < r["steps"] < 100000
    # the agreed stop step is identical across processes — the property
    # that makes the collective force-save line up instead of deadlock
    assert results[0]["steps"] == results[1]["steps"]
    saved_step = results[0]["steps"]

    # a fresh 2-process run restores the preemption save and finishes
    outs = _launch(2, _free_port(),
                   extra=("--ckpt-dir", ckpt,
                          "--steps", str(saved_step + 6)))
    results = [json.loads(r) for r in _results(outs)]
    for r in results:
        assert r["restored"] is True
        assert r["preempted"] is False
        assert r["steps"] == saved_step + 6


@pytest.mark.slow
def test_mh_smoke_gate_worker(tmp_path):
    """The driver gate's dp:2proc worker (parallel/mh_smoke.py, spawned by
    __graft_entry__.dryrun_multichip) runs the same rendezvous/psum/
    checkpoint path as the suite's own worker — exercised here so the gate
    leg can't bit-rot between driver runs. Mirrors the gate's two-pair
    sequence: fresh run with a coordinated save, then a fresh pair
    restoring it."""
    ckpt = str(tmp_path / "gate-ckpt")

    def run_pair(steps, port):
        outs = _launch(
            2, port,
            extra=("--devices-per-proc", "4", "--ckpt-dir", ckpt,
                   "--steps", steps),
            cmd=[sys.executable, "-m",
                 "distributedmnist_tpu.parallel.mh_smoke"])
        return [json.loads(r) for r in _results(outs, tag="MHSMOKE ")]

    r1 = run_pair(6, _free_port())
    for r in r1:
        assert r["multihost"] is True and r["n_processes"] == 2
        assert r["n_chips"] == 8 and r["steps"] == 6
        assert r["restored"] is False
    assert r1[0]["accuracy"] == r1[1]["accuracy"]

    r2 = run_pair(9, _free_port())
    for r in r2:
        assert r["restored"] is True and r["steps"] == 9
    assert r2[0]["accuracy"] == r2[1]["accuracy"]


@pytest.mark.slow
@pytest.mark.parametrize("source", ["numpy", "tfdata"])
def test_two_process_streaming_pipeline(source):
    """The streaming host pipeline under process_count > 1 — the code path
    whose entire reason to exist is multi-host scale (BASELINE.json
    north_star: "per-host tf.data pipeline feeding device-sharded global
    batches"), under BOTH host-gather backends. Asserts (a) streaming fit
    ≡ device-resident fit on the same seed, (b) for the numpy source,
    each process host-gathered ONLY rows belonging to its own addressable
    'data' shards — no process ever materialized a full global batch
    (instrumented in the worker; tfdata materializes the full block per
    host by documented design, so (b) is numpy-only)."""
    outs = _launch(2, _free_port(),
                   extra=("--data-pipeline", "stream",
                          "--stream-source", source))
    results = [json.loads(r) for r in _results(outs)]
    for r in results:
        assert r["multihost"] is True and r["n_chips"] == 8
        assert r["stream_source"] == source
        assert r["stream_steps"] == r["steps"] == 6
        # (a) trajectory equivalence, device-resident vs streamed
        assert r["stream_accuracy"] == r["accuracy"]
        if source == "numpy":
            # (b) per-process gather locality
            assert r["stream_rows_ok"] is True, r
            assert r["stream_full_batch_avoided"] is True, r
            assert (r["stream_rows_touched"]
                    == r["stream_rows_expected"] > 0)
    # both processes agree on the replicated result
    assert results[0]["stream_accuracy"] == results[1]["stream_accuracy"]
