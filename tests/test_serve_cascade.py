"""Confidence-gated model cascade (ISSUE 17, serve/cascade.py): the
softmax-margin math, the threshold calibration search + the END-TO-END
composed-accuracy gate (pass, refuse, and override paths), the
CascadeFront's partition/escalate/reassemble pipeline (byte-stable
against the single-dtype routes, asserted on stubs AND real engines),
accuracy-class cache isolation (a cheap-only answer must never be
served to an `exact` request), escalation under deadline pressure,
poison-bisection with the cascade in front (ledger exact), the
registry's cascade lifecycle (enable/threshold-set/promote-override/
refusal), and the DML016 confidence-policy lint.

Every test runs under the conftest serve sanitizer; the suite carries
the `cascade` marker (tier-1 runs it; `-m cascade` selects it alone)."""

import threading
import time

import numpy as np
import pytest

from distributedmnist_tpu.serve import (DeadlineExceeded, DynamicBatcher,
                                        ResiliencePolicy, ServeMetrics)
from distributedmnist_tpu.serve import cascade as cascade_lib
from distributedmnist_tpu.serve.cache import (CacheFront, PredictionCache,
                                              content_key)
from distributedmnist_tpu.serve.cascade import (ACCURACY_CLASSES,
                                                CascadeFront, CascadeState,
                                                cascade_label, calibrate,
                                                softmax_margin,
                                                threshold_of)
from tests.test_serve_batcher import StubEngine, _rows

pytestmark = pytest.mark.cascade


# -- margin math -----------------------------------------------------------


def test_softmax_margin_shape_range_and_shift_invariance(rng):
    logits = rng.normal(size=(32, 10)) * 3
    m = softmax_margin(logits)
    assert m.shape == (32,)
    assert np.all((m >= 0) & (m <= 1))
    # margins depend on logit GAPS only: a per-row shift (exactly what
    # the stub engines' route offsets apply) must not move them
    np.testing.assert_allclose(softmax_margin(logits + 123.0), m,
                               atol=1e-12)


def test_softmax_margin_extremes():
    confident = np.zeros((1, 10)); confident[0, 3] = 30.0
    assert softmax_margin(confident)[0] > 0.999
    uniform = np.ones((1, 10)) * 7.0
    assert softmax_margin(uniform)[0] == pytest.approx(0.0, abs=1e-12)


# -- calibration + the composed-accuracy gate ------------------------------


def _ref_logits(n):
    """Reference answers: argmax 0 on every row."""
    out = np.zeros((n, 10), np.float64)
    out[:, 0] = 5.0
    return out


def _cheap_with(n, wrong_low_margin=(), wrong_high_margin=()):
    """Cheap-stage logits agreeing with _ref_logits except on the given
    rows: `wrong_low_margin` rows disagree with a tiny margin (the
    escalatable kind), `wrong_high_margin` rows disagree CONFIDENTLY
    (no threshold short of escalate-everything catches them)."""
    out = np.zeros((n, 10), np.float64)
    out[:, 0] = 4.0 + np.linspace(0, 1, n)   # distinct margins per row
    for i in wrong_low_margin:
        out[i] = 0.0
        out[i, 1] = 0.05                     # argmax 1, margin ~0.005
    for i in wrong_high_margin:
        out[i] = 0.0
        out[i, 1] = 30.0                     # argmax 1, margin ~1
    return out


def test_calibrate_perfect_agreement_needs_no_escalation():
    rec = calibrate(_ref_logits(16), _cheap_with(16), 0.995)
    assert rec["passed"] and rec["why"] is None
    assert rec["threshold"] == 0.0
    assert rec["base_agreement"] == 1.0
    assert rec["composed_agreement"] == 1.0
    assert rec["escalation_fraction"] == 0.0
    assert rec["source"] == "calibrated"


def test_calibrate_escalates_exactly_the_uncertain_disagreements():
    ref, cheap = _ref_logits(16), _cheap_with(16,
                                              wrong_low_margin=(2, 9))
    rec = calibrate(ref, cheap, 0.995)
    assert rec["passed"], rec
    # 14/16 base agreement is under the bar; the two wrong rows carry
    # the lowest margins, so the search lands just above them
    assert rec["base_agreement"] == pytest.approx(14 / 16)
    assert rec["composed_agreement"] == 1.0
    assert rec["escalation_fraction"] == pytest.approx(2 / 16)
    margins = softmax_margin(cheap)
    esc = margins < rec["threshold"]
    assert set(np.nonzero(esc)[0]) == {2, 9}


def test_calibrate_refuses_when_cap_or_bar_unreachable():
    # a CONFIDENT disagreement is invisible to any margin threshold
    # short of escalate-everything, and escalate-everything is capped
    ref = _ref_logits(16)
    cheap = _cheap_with(16, wrong_high_margin=(5,))
    rec = calibrate(ref, cheap, 0.995, max_escalation=0.5)
    assert not rec["passed"]
    assert rec["why"]
    # an unreachable bar refuses even with perfect agreement
    rec = calibrate(ref, _cheap_with(16), 1.01)
    assert not rec["passed"]


def test_calibrate_override_is_judged_by_the_same_gate():
    ref, cheap = _ref_logits(16), _cheap_with(16,
                                              wrong_low_margin=(2, 9))
    # escalate-nothing override: base agreement 14/16 fails the bar
    rec = calibrate(ref, cheap, 0.995, threshold=0.0)
    assert not rec["passed"] and rec["source"] == "override"
    assert rec["threshold"] == 0.0
    # escalate-everything override: composed == f32, passes
    rec = calibrate(ref, cheap, 0.995, threshold=1.0)
    assert rec["passed"] and rec["source"] == "override"
    assert rec["composed_agreement"] == 1.0
    assert rec["escalation_fraction"] == 1.0


def test_threshold_accessor_and_describe():
    st = CascadeState("int8", 0.25, {"passed": True})
    assert threshold_of(st) == 0.25
    d = st.describe()
    assert d["cheap_dtype"] == "int8" and d["threshold"] == 0.25
    assert cascade_label("int8") == "cascade:int8"


# -- CascadeFront over stub engines ---------------------------------------


class CascadeStubEngine(StubEngine):
    """Route-pinnable StubEngine: dispatch() accepts the batcher's
    pinned infer_dtype and fetch() adds a per-route offset to every
    logit — which route computed a row is detectable by VALUE, while
    neither argmax nor softmax margins move (an offset shifts whole
    rows; margins are gap-only, asserted above)."""

    OFFSETS = {"float32": 0.0, "int8": 500.0}
    supports_alternates = True

    def __init__(self, **kw):
        super().__init__(**kw)
        self.route_log = []

    def live_version(self):
        return "v1"

    def live_infer_dtype(self):
        return "float32"

    def dispatch(self, x, infer_dtype=None):
        h = super().dispatch(x)
        h.infer_dtype = infer_dtype or "float32"
        h.version = "v1"
        self.route_log.append(h.infer_dtype)
        return h

    def fetch(self, handle):
        out = super().fetch(handle) / 100.0
        return out + self.OFFSETS[handle.infer_dtype]


class PlanStub:
    """cascade_plan-shaped registry double: a settable (version,
    CascadeState) plan, None = no calibrated cascade (degrade)."""

    def __init__(self, state=None, version="v1"):
        self.state = state
        self.version = version

    def cascade_plan(self):
        return None if self.state is None else (self.version, self.state)


def _state(threshold, cheap_dtype="int8"):
    return CascadeState(cheap_dtype, threshold,
                        {"passed": True, "source": "test"})


def _stub_front(engine, state, metrics=None, cache=None, **batcher_kw):
    b = DynamicBatcher(engine, max_wait_us=1000, queue_depth=1024,
                       metrics=metrics, **batcher_kw).start()
    reg = PlanStub(state)
    inner = (CacheFront(b, engine, cache, metrics=metrics)
             if cache is not None else b)
    front = CascadeFront(inner, b, engine, reg, metrics=metrics,
                         cache=cache)
    return front, b, reg


def test_unknown_accuracy_class_raises(rng):
    eng = CascadeStubEngine()
    front, b, _ = _stub_front(eng, _state(0.5))
    try:
        with pytest.raises(ValueError, match="accuracy class"):
            front.submit(_rows(rng, 2), accuracy_class="cheapest")
        assert eng.calls == []            # refused before any dispatch
    finally:
        b.stop()


def test_no_plan_degrades_to_live_route_and_is_counted(rng):
    metrics = ServeMetrics()
    eng = CascadeStubEngine()
    front, b, _ = _stub_front(eng, None, metrics=metrics)
    try:
        x = _rows(rng, 3)
        out = front.submit(x, accuracy_class="balanced").result(timeout=10)
        # the plain (unpinned) live route computed it: f32 offset
        np.testing.assert_array_equal(
            out, x.reshape(3, -1)[:, :10].astype(np.float32) / 100.0)
        snap = metrics.snapshot()["cascade"]
        assert snap["degraded_requests"] == 1
        assert dict(snap["by_class"])["balanced"] == 1
    finally:
        b.stop()


def test_exact_and_fast_pin_their_routes(rng):
    eng = CascadeStubEngine()
    front, b, _ = _stub_front(eng, _state(0.5))
    try:
        x = _rows(rng, 4)
        exact = front.submit(x, accuracy_class="exact").result(timeout=10)
        fast = front.submit(x, accuracy_class="fast").result(timeout=10)
        base = x.reshape(4, -1)[:, :10].astype(np.float32) / 100.0
        np.testing.assert_array_equal(exact, base)
        np.testing.assert_array_equal(fast, base + 500.0)
        assert eng.route_log == ["float32", "int8"]
    finally:
        b.stop()


def test_balanced_no_escalation_single_stage(rng):
    metrics = ServeMetrics()
    eng = CascadeStubEngine()
    # threshold 0: `margin < 0` escalates nothing — one cheap dispatch
    front, b, _ = _stub_front(eng, _state(0.0), metrics=metrics)
    try:
        x = _rows(rng, 4)
        out = front.submit(x, accuracy_class="balanced").result(timeout=10)
        np.testing.assert_array_equal(
            out, x.reshape(4, -1)[:, :10].astype(np.float32) / 100.0 + 500.0)
        assert eng.route_log == ["int8"]
        snap = metrics.snapshot()["cascade"]
        assert snap["escalated_requests"] == 0
        assert snap["escalation_fraction"] == 0.0
        assert dict(snap["stage_rows"])["int8"]["rows"] == 4
    finally:
        b.stop()


def test_balanced_partitions_by_margin_and_reassembles_byte_stable(rng):
    metrics = ServeMetrics()
    eng = CascadeStubEngine()
    x = _rows(rng, 8)
    base = x.reshape(8, -1)[:, :10].astype(np.float32) / 100.0
    margins = softmax_margin(base + 500.0)   # == cheap-stage margins
    thr = float(np.sort(margins)[4])         # strict <: rows 0..3 escalate
    assert len(np.unique(margins)) == 8      # distinct, split is exact
    front, b, _ = _stub_front(eng, _state(thr), metrics=metrics)
    try:
        out = front.submit(x, accuracy_class="balanced").result(timeout=10)
        esc = margins < thr
        assert int(esc.sum()) == 4
        # escalated rows carry the f32 route's exact bytes, the rest
        # the cheap route's — reassembly is row-exact
        np.testing.assert_array_equal(out[esc], base[esc])
        np.testing.assert_array_equal(out[~esc], base[~esc] + 500.0)
        assert eng.route_log == ["int8", "float32"]
        assert eng.calls == [8, 4]           # only the uncertain slice
        snap = metrics.snapshot()["cascade"]
        assert snap["escalated_requests"] == 1
        assert snap["escalated_rows"] == 4
        stage = dict(snap["stage_rows"])
        assert stage["int8"]["rows"] == 8
        assert stage["float32"]["rows"] == 4
        assert snap["escalation_fraction"] == pytest.approx(0.5)
    finally:
        b.stop()


def test_balanced_full_escalation_equals_exact(rng):
    eng = CascadeStubEngine()
    # threshold 1.0 escalates every finite-margin row
    front, b, _ = _stub_front(eng, _state(1.0))
    try:
        x = _rows(rng, 5)
        balanced = front.submit(
            x, accuracy_class="balanced").result(timeout=10)
        exact = front.submit(x, accuracy_class="exact").result(timeout=10)
        np.testing.assert_array_equal(balanced, exact)
    finally:
        b.stop()


def test_escalation_inherits_deadline_and_sheds(rng):
    """Under deadline pressure the stage-2 re-submit is shed exactly
    like any request: the gate holds stage 1 on the device past the
    request's deadline, so the escalation arrives at the batcher
    already expired — DeadlineExceeded, zero stage-2 device work."""
    gate = threading.Event()
    eng = CascadeStubEngine(gate=gate)
    front, b, _ = _stub_front(eng, _state(1.0))   # escalate everything
    try:
        fut = front.submit(_rows(rng, 2), accuracy_class="balanced",
                           deadline_s=time.monotonic() + 0.2)
        assert eng.in_call.wait(timeout=10)   # stage 1 dispatched...
        time.sleep(0.35)                      # ...and now overdue
        gate.set()
        with pytest.raises(DeadlineExceeded):
            fut.result(timeout=10)
        assert eng.route_log == ["int8"]      # stage 2 never dispatched
    finally:
        b.stop()


def test_expired_at_submit_never_reaches_stage1(rng):
    eng = CascadeStubEngine()
    front, b, _ = _stub_front(eng, _state(1.0))
    try:
        with pytest.raises(DeadlineExceeded):
            front.submit(_rows(rng, 2), accuracy_class="balanced",
                         deadline_s=time.monotonic() - 0.01)
        assert eng.calls == []
    finally:
        b.stop()


def test_stage1_failure_fails_the_composed_future(rng):
    eng = CascadeStubEngine()
    front, b, _ = _stub_front(eng, _state(1.0))
    try:
        b.stop()                              # wedge the inner pipeline
        with pytest.raises(RuntimeError):
            front.submit(_rows(rng, 2),
                         accuracy_class="balanced").result(timeout=10)
    finally:
        b.stop()


# -- accuracy-class cache isolation (ISSUE 17 satellite) -------------------


def test_cascade_results_cache_under_the_cascade_key(rng):
    """Composed answers insert under the cascade route label; repeats
    hit without device work, and the label keeps per-class populations
    from aliasing."""
    metrics = ServeMetrics()
    eng = CascadeStubEngine()
    cache = PredictionCache(64)
    front, b, _ = _stub_front(eng, _state(0.0), metrics=metrics,
                              cache=cache)
    try:
        x = _rows(rng, 3)
        first = front.submit(x, accuracy_class="balanced").result(timeout=10)
        # two entries: the stage-1 bytes under the plain "int8" label
        # (the inner CacheFront's doing) and the COMPOSED bytes under
        # the cascade label
        assert cache.stats()["entries"] == 2
        assert cache.lookup(content_key("v1", cascade_label("int8"),
                                        x)) is not None
        assert cache.lookup(content_key("v1", "int8", x)) is not None
        calls_before = list(eng.calls)
        again = front.submit(x, accuracy_class="balanced").result(timeout=10)
        np.testing.assert_array_equal(again, first)
        assert eng.calls == calls_before      # served from the cache
    finally:
        b.stop()


def test_cheap_answer_is_never_served_to_an_exact_request(rng):
    """The class-confusion test: a cascade (cheap-routed) entry and an
    `exact` request for the SAME bytes live under different cache keys
    — exact recomputes on the f32 route and gets f32 bytes."""
    eng = CascadeStubEngine()
    cache = PredictionCache(64)
    front, b, _ = _stub_front(eng, _state(0.0), cache=cache)
    try:
        x = _rows(rng, 3)
        balanced = front.submit(
            x, accuracy_class="balanced").result(timeout=10)
        exact = front.submit(x, accuracy_class="exact").result(timeout=10)
        fast = front.submit(x, accuracy_class="fast").result(timeout=10)
        base = x.reshape(3, -1)[:, :10].astype(np.float32) / 100.0
        np.testing.assert_array_equal(exact, base)          # f32 bytes
        np.testing.assert_array_equal(balanced, base + 500.0)
        np.testing.assert_array_equal(fast, base + 500.0)
        assert not np.array_equal(exact, balanced)
        # three distinct keys: cascade label, plain int8 (stage 1 —
        # which the `fast` request legitimately hit), plain f32; the
        # exact request NEVER saw a cheap-routed byte
        assert cache.stats()["entries"] == 3
        assert eng.route_log == ["int8", "float32"]
    finally:
        b.stop()


def test_stale_cascade_entry_is_invalidated_with_the_epoch(rng):
    """A threshold change invalidates composed entries: bytes cached
    under the OLD threshold must not survive into the new policy."""
    eng = CascadeStubEngine()
    cache = PredictionCache(64)
    front, b, reg = _stub_front(eng, _state(0.0), cache=cache)
    try:
        x = _rows(rng, 2)
        front.submit(x, accuracy_class="balanced").result(timeout=10)
        assert cache.stats()["entries"] == 2   # stage-1 + composed
        # what registry.set_cascade_threshold does on the live version
        reg.state = _state(1.0)
        cache.invalidate()
        assert cache.lookup(content_key("v1", cascade_label("int8"),
                                        x)) is None
        out = front.submit(x, accuracy_class="balanced").result(timeout=10)
        base = x.reshape(2, -1)[:, :10].astype(np.float32) / 100.0
        np.testing.assert_array_equal(out, base)   # escalated under new
    finally:
        b.stop()


# -- chaos: poison bisection with the cascade in front ---------------------


class PoisonCascadeStub(CascadeStubEngine):
    """CascadeStubEngine whose dispatch() raises for any cohort
    containing a marked request (first pixel == 211) — the
    resilience suite's content-deterministic poison, route-pinnable."""

    def dispatch(self, x, infer_dtype=None):
        parts = x if isinstance(x, (list, tuple)) else [x]
        if any(np.asarray(p).flat[0] == 211 for p in parts):
            self.calls.append(-sum(np.asarray(p).reshape(
                -1, 784).shape[0] for p in parts))
            raise RuntimeError("poison request in cohort")
        return super().dispatch(x, infer_dtype=infer_dtype)


def _poison_rows(n):
    x = np.full((n, 28, 28, 1), 5, np.uint8)
    x[0, 0, 0, 0] = 211
    return x


@pytest.mark.chaos
def test_bisection_ledger_exact_with_cascade_on(rng):
    """The chaos drill with the cascade in front: a poison request in
    a coalesced cascade cohort is isolated by bisection, its cohort
    siblings are rescued, and the ledger is EXACT — route-uniform
    drains mean bisection sub-dispatches inherit the cascade's pinned
    route, so the resilience machinery needs no cascade awareness."""
    gate = threading.Event()
    eng = PoisonCascadeStub(max_batch=16, gate=gate)
    metrics = ServeMetrics()
    b = DynamicBatcher(eng, max_wait_us=50_000, max_inflight=4,
                       resilience=ResiliencePolicy(bisect=True),
                       metrics=metrics).start()
    front = CascadeFront(b, b, eng, PlanStub(_state(0.0)),
                         metrics=metrics)
    try:
        first = front.submit(_rows(rng, 1), accuracy_class="balanced")
        assert eng.in_call.wait(timeout=10)   # cohort forms at the gate
        clean = [front.submit(_rows(rng, 2), accuracy_class="balanced")
                 for _ in range(2)]
        bad = front.submit(_poison_rows(2), accuracy_class="balanced")
        clean.append(front.submit(_rows(rng, 3),
                                  accuracy_class="balanced"))
        gate.set()
        assert first.result(timeout=10).shape == (1, 10)
        with pytest.raises(RuntimeError, match="poison"):
            bad.result(timeout=10)
        for i, f in enumerate(clean):
            assert f.result(timeout=10).shape[1] == 10, i
        snap = metrics.snapshot()["resilience"]
        assert snap["poison_isolated_requests"] == 1
        assert snap["poison_isolated_rows"] == 2
        assert snap["bisect_rescued_requests"] == 3
        assert snap["bisect_rescued_rows"] == 7
        assert snap["dispatch_error_requests"] == 0
        # every dispatch (including bisection sub-dispatches) stayed on
        # the cascade's pinned cheap route
        assert set(eng.route_log) == {"int8"}
    finally:
        b.stop()


# -- batcher: route-uniform drains ----------------------------------------


def test_batcher_never_coalesces_across_routes(rng):
    """One batch runs ONE engine program: requests pinned to different
    routes must never share a drain (the cascade's correctness rests
    on this, not on any cascade-aware batching)."""
    gate = threading.Event()
    eng = CascadeStubEngine(max_batch=64, gate=gate)
    b = DynamicBatcher(eng, max_wait_us=50_000, max_inflight=2).start()
    try:
        first = b.submit(_rows(rng, 1))       # holds the pipeline
        assert eng.in_call.wait(timeout=10)
        futs = [b.submit(_rows(rng, 2), route="int8"),
                b.submit(_rows(rng, 2), route="float32"),
                b.submit(_rows(rng, 2), route="int8")]
        gate.set()
        for f in [first] + futs:
            assert f.result(timeout=10).shape[1] == 10
        # the queued trio drained as int8 / float32 / int8 segments —
        # adjacent same-route requests may coalesce, different routes
        # never do
        assert eng.route_log[0] == "float32"  # the unpinned holder
        assert len(eng.route_log) == 4
        assert eng.route_log[1:] == ["int8", "float32", "int8"]
    finally:
        b.stop()


# -- registry lifecycle over real engines ----------------------------------


@pytest.fixture(scope="module")
def cascade_registry(eight_devices):
    """A bootstrapped single-replica LeNet registry with a calibrated
    int8 cascade and a batcher over its router (module-scoped: the
    bucket compiles are the slow part)."""
    import jax

    from distributedmnist_tpu import models
    from distributedmnist_tpu.parallel import make_mesh
    from distributedmnist_tpu.serve.registry import (EngineFactory,
                                                     ModelRegistry)

    mesh = make_mesh(eight_devices[:1])
    model = models.build("lenet", platform="cpu")
    factory = EngineFactory(model, mesh, max_batch=8)
    metrics = ServeMetrics()
    router = factory.make_router(metrics=metrics)
    registry = ModelRegistry(factory, router)
    registry.bootstrap(seed=0)
    state = registry.enable_cascade()        # auto -> builds + gates int8
    batcher = DynamicBatcher(router, max_wait_us=500, queue_depth=256,
                             metrics=metrics).start()
    front = CascadeFront(batcher, batcher, router, registry,
                         metrics=metrics)
    yield front, batcher, registry, router, metrics, state
    batcher.stop()


def test_enable_cascade_calibrates_and_describes(cascade_registry):
    front, _, registry, router, _, state = cascade_registry
    assert state.cheap_dtype == "int8"
    assert state.calibration["passed"] is True
    assert state.calibration["composed_agreement"] >= 0.995
    live = registry.live_version()
    plan = registry.cascade_plan()
    assert plan is not None and plan[0] == live and plan[1] is state
    desc = registry.describe()
    mv_desc = next(v for v in desc["versions"] if v["version"] == live)
    assert mv_desc["cascade"]["cheap_dtype"] == "int8"
    assert mv_desc["cascade"]["threshold"] == round(state.threshold, 6)
    assert any(e["event"] == "cascade_enabled" for e in desc["events"])
    # the cheap route is promoted as a pinned alternate
    assert "int8" in desc["routes"]["alternates"]


def test_real_engine_classes_and_partition(cascade_registry, rng):
    """End-to-end over real engines: `exact` == the f32 engine's bytes,
    `fast` == the int8 engine's, and a forced partial escalation
    composes exactly those two — escalated rows byte-equal f32."""
    front, _, registry, router, _, state = cascade_registry
    live = registry.live_version()
    x = rng.integers(0, 256, (8, 28, 28, 1)).astype(np.uint8)
    exact = front.submit(x, accuracy_class="exact").result(timeout=60)
    fast = front.submit(x, accuracy_class="fast").result(timeout=60)
    # lint: allow[DML016] test fixture computes expected margins for the assertion
    margins = softmax_margin(fast)
    assert len(np.unique(margins)) == 8
    thr = float(np.sort(margins)[4])
    old = threshold_of(state)
    registry.set_cascade_threshold(live, thr)
    try:
        out = front.submit(x, accuracy_class="balanced").result(timeout=60)
        esc = margins < thr
        assert 0 < int(esc.sum()) < 8
        np.testing.assert_array_equal(out[esc], exact[esc])
        np.testing.assert_array_equal(out[~esc], fast[~esc])
    finally:
        registry.set_cascade_threshold(live, old)


def test_full_escalation_byte_equals_f32(cascade_registry, rng):
    front, _, registry, _, _, state = cascade_registry
    live = registry.live_version()
    old = threshold_of(state)
    registry.set_cascade_threshold(live, 1.0)
    try:
        x = rng.integers(0, 256, (6, 28, 28, 1)).astype(np.uint8)
        balanced = front.submit(
            x, accuracy_class="balanced").result(timeout=60)
        exact = front.submit(x, accuracy_class="exact").result(timeout=60)
        np.testing.assert_array_equal(balanced, exact)
    finally:
        registry.set_cascade_threshold(live, old)


def test_threshold_set_refusal_keeps_previous_state(cascade_registry,
                                                    monkeypatch):
    front, _, registry, _, _, _ = cascade_registry
    live = registry.live_version()
    before = registry.cascade_plan()[1]
    monkeypatch.setattr(
        registry, "_cascade_gate",
        lambda *a, **k: {"passed": False, "why": "forced refusal",
                         "threshold": 0.9})
    with pytest.raises(RuntimeError, match="forced refusal"):
        registry.set_cascade_threshold(live, 0.9)
    assert registry.cascade_plan()[1] is before   # state intact


def test_promote_with_threshold_override_regates(cascade_registry):
    """promote(cascade_threshold=...) re-gates BEFORE the swap; the
    override lands atomically with the promote."""
    front, _, registry, _, _, _ = cascade_registry
    live = registry.live_version()
    old = threshold_of(registry.cascade_plan()[1])
    mv = registry.promote(live, cascade_threshold=1.0)
    try:
        assert mv.state == "live"
        assert threshold_of(registry.cascade_plan()[1]) == 1.0
        assert any(e["event"] == "cascade_threshold_set"
                   for e in registry.events())
    finally:
        registry.set_cascade_threshold(live, old)


def test_enable_cascade_refuses_float32_cheap_stage(cascade_registry):
    front, _, registry, _, _, _ = cascade_registry
    with pytest.raises(ValueError, match="low-precision"):
        registry.enable_cascade(registry.live_version(),
                                cheap_dtype="float32")


# -- static activation calibration rides the variant build ----------------


def test_int8_prep_carries_static_activation_scales(eight_devices):
    """Satellite 1: the Pallas int8 route's activation scales are
    calibrated once at build from the held-out batch (a 0-d f32 leaf
    in the prepared tree), not recomputed per dispatch."""
    import jax
    import jax.numpy as jnp

    from distributedmnist_tpu import models
    from distributedmnist_tpu.ops import fused
    from distributedmnist_tpu.serve import quantize as quantize_lib

    model = models.build("mlp", platform="cpu")
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 28, 28, 1)))["params"]
    calib = quantize_lib.calibration_batch()
    prep, _ = quantize_lib.prepare_inference(
        model, params, "int8", fused.PALLAS, calib_x=calib)
    scale = prep["act_scale"]
    assert np.asarray(scale).shape == ()
    assert np.asarray(scale).dtype == np.float32
    assert float(scale) > 0


def test_calibration_batch_is_deterministic_and_covers_probes():
    from distributedmnist_tpu.serve import quantize as quantize_lib

    a = quantize_lib.calibration_batch()
    b = quantize_lib.calibration_batch()
    np.testing.assert_array_equal(a, b)
    assert a.shape[0] == 128 + quantize_lib._CALIB_PROBE_ROWS
    assert a.dtype == np.uint8


# -- lint DML016: the confidence-policy fork rule --------------------------


def _lint(src, rel="distributedmnist_tpu/serve/somefile.py"):
    from distributedmnist_tpu.analysis import lint
    return [f.rule for f in lint.lint_source(src, rel)
            if f.rule == "DML016"]


def test_dml016_flags_margin_reads_and_constants():
    assert _lint("m = softmax_margin(logits)\n") == ["DML016"]
    assert _lint("esc = margins < 0.3\n") == ["DML016"]
    assert _lint("if row_margin >= 0.95:\n    pass\n") == ["DML016"]
    assert _lint("esc = self.margin < 0.5\n") == ["DML016"]


def test_dml016_allows_the_accessor_and_cascade_itself():
    assert _lint("esc = margins < threshold_of(state)\n") == []
    # cascade.py owns the policy; tests and non-serve code are out of
    # scope entirely
    src = "m = softmax_margin(x)\nesc = m < 0.5\n"
    assert _lint(src, "distributedmnist_tpu/serve/cascade.py") == []
    assert _lint(src, "tests/test_serve_cascade.py") == []
    assert _lint(src, "distributedmnist_tpu/models.py") == []
    # margin-free numeric compares in serve/ are untouched
    assert _lint("ok = fraction < 0.5\n") == []


def test_dml016_repo_is_clean():
    """The serving tree itself holds no confidence-policy forks."""
    import os

    from distributedmnist_tpu.analysis import lint

    root = lint.repo_root()
    for rel in ["serve.py"] + [
            os.path.join("distributedmnist_tpu", "serve", f)
            for f in os.listdir(os.path.join(
                root, "distributedmnist_tpu", "serve"))
            if f.endswith(".py")]:
        text = open(os.path.join(root, rel), encoding="utf-8").read()
        findings = [f for f in lint.lint_source(text, rel.replace(
            os.sep, "/")) if f.rule == "DML016"]
        active, _ = lint.apply_allowlist(findings, text.splitlines())
        assert not active, (rel, active)
