"""Model unit tests (SURVEY.md §4): pinned parameter counts, output shapes,
and one-batch overfitting (loss decreases)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from distributedmnist_tpu import models, optim
from distributedmnist_tpu.ops import cross_entropy


def _n_params(params):
    return sum(int(np.prod(p.shape)) for p in jax.tree.leaves(params))


def _init(name, **kw):
    model = models.build(name, **kw)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((2, 28, 28, 1)))["params"]
    return model, params


def test_mlp_param_count():
    # 784*128+128 + 128*10+10 — the spec's "2-layer MLP (784-128-10)"
    _, params = _init("mlp", fused="xla")
    assert _n_params(params) == 101_770


def test_mlp_fused_param_count_matches():
    _, params = _init("mlp", fused="pallas")
    assert _n_params(params) == 101_770


def test_lenet_param_count():
    _, params = _init("lenet")
    assert _n_params(params) == 61_706


@pytest.mark.parametrize("name", ["mlp", "lenet"])
def test_forward_shapes(name):
    model, params = _init(name)
    x = jnp.zeros((32, 28, 28, 1))
    logits = model.apply({"params": params}, x)
    assert logits.shape == (32, 10)
    assert jnp.isfinite(logits).all()


@pytest.mark.parametrize("name,opt", [("mlp", "sgd"), ("lenet", "adam")])
def test_overfit_one_batch(name, opt):
    model, params = _init(name)
    tx = optim.build(opt, 0.05 if opt == "sgd" else 3e-3)
    opt_state = tx.init(params)
    key = jax.random.PRNGKey(1)
    x = jax.random.uniform(key, (64, 28, 28, 1))
    y = jax.random.randint(key, (64,), 0, 10)

    @jax.jit
    def step(params, opt_state):
        loss, grads = jax.value_and_grad(
            lambda p: cross_entropy(model.apply({"params": p}, x), y))(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    losses = []
    for _ in range(80):
        params, opt_state, loss = step(params, opt_state)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.5, losses[::10]


def test_bfloat16_forward():
    model, params = _init("lenet", dtype=jnp.bfloat16)
    x = jnp.zeros((8, 28, 28, 1), jnp.bfloat16)
    logits = model.apply({"params": params}, x)
    assert logits.dtype == jnp.bfloat16
