"""ISSUE 5 resilience subsystem: the fault injector's spec/determinism/
inertness contracts (serve/faults.py), deadline propagation and shed-
before-dispatch, poison-batch bisection isolating exactly the culprit,
the sliding-window circuit breaker, auto-rollback through a REAL
registry, and last_error surfacing for failed restores/warmups.

Fault-injection-driven tests carry the `chaos` marker (fixed seeds, so
they are deterministic and cheap — tier-1 runs them)."""

import threading
import time
from types import SimpleNamespace

import numpy as np
import pytest

from distributedmnist_tpu.serve import (CircuitBreaker, DeadlineExceeded,
                                        DynamicBatcher, FaultInjector,
                                        InjectedFault, ModelRegistry,
                                        ResiliencePolicy, ServeMetrics,
                                        faults)
from distributedmnist_tpu.serve.faults import parse_spec
from tests.test_serve_batcher import StubEngine, _rows


@pytest.fixture(autouse=True)
def _no_leaked_injector():
    """Every test starts and ends with the failpoints inert — an
    injector leaked across tests would make unrelated suites flaky in
    the most confusing way possible."""
    faults.uninstall()
    yield
    faults.uninstall()


# -- faults.py: spec, determinism, inertness ------------------------------


def test_fault_spec_parses_rules():
    rules = parse_spec(
        "batch.dispatch:mode=request,p=0.02;"
        "engine.fetch:p=1,count=3,after=5,latency_ms=2,version=v1")
    assert len(rules) == 2
    assert rules[0].point == "batch.dispatch"
    assert rules[0].mode == "request" and rules[0].probability == 0.02
    assert rules[0].error  # request-mode rules default to an error
    assert rules[1].match == {"version": "v1"}
    assert rules[1].count == 3 and rules[1].after == 5
    assert rules[1].latency_ms == 2.0


def test_fault_spec_rejects_malformed():
    for bad in ("", "engine.fetch:p=2", "engine.fetch:p=",
                "engine.fetch:mode=weird", "engine.fetch:count=0",
                "engine.fetch:latency_ms=-1", "engine.fetch:p",
                # a typo'd failpoint must fail at install, never become
                # a schedule that silently injects nothing
                # lint: allow[DML003] deliberately-bad spec: this test asserts parse_spec rejects it
                "engine.fetsh:p=1", "nope:p=1"):
        with pytest.raises(ValueError):
            parse_spec(bad)


def test_failpoint_inert_without_injector():
    # must be a no-op, not an error — this is the production hot path
    faults.failpoint("engine.dispatch", version="v1", rids=[1, 2])
    inj = faults.install(FaultInjector.from_spec("engine.dispatch:p=1"))
    assert faults.active() is inj
    with pytest.raises(RuntimeError, match="already installed"):
        faults.install(FaultInjector.from_spec("engine.fetch:p=1"))
    faults.uninstall()
    faults.failpoint("engine.dispatch")    # inert again


@pytest.mark.chaos
def test_call_mode_probability_count_after_and_match():
    inj = faults.install(FaultInjector.from_spec(
        "engine.fetch:p=1,count=2,after=1,version=v1", seed=0))
    # non-matching version: never evaluated past the filter
    inj.fire("engine.fetch", version="v2")
    # first matching evaluation is skipped (after=1)
    inj.fire("engine.fetch", version="v1")
    for _ in range(2):             # then exactly `count` fires
        with pytest.raises(InjectedFault, match="engine.fetch"):
            inj.fire("engine.fetch", version="v1")
    inj.fire("engine.fetch", version="v1")   # count exhausted
    snap = inj.snapshot()
    assert snap["rules"][0]["fires"] == 2
    assert snap["rules"][0]["evaluations"] == 4   # v2 never counted


@pytest.mark.chaos
def test_request_mode_poison_is_sticky_and_seeded():
    inj = FaultInjector.from_spec("batch.dispatch:mode=request,p=0.2",
                                  seed=7)
    rids = list(range(200))
    verdicts = {}
    for rid in rids:
        try:
            inj.fire("batch.dispatch", rids=[rid])
            verdicts[rid] = False
        except InjectedFault:
            verdicts[rid] = True
    poisoned = {r for r, v in verdicts.items() if v}
    assert poisoned == inj.poisoned()
    assert 10 < len(poisoned) < 90          # ~20% of 200
    # sticky: re-evaluating any rid reproduces its verdict (bisection
    # depends on this), and a cohort fails iff it contains poison
    for rid in (min(poisoned), max(poisoned)):
        with pytest.raises(InjectedFault):
            inj.fire("batch.dispatch", rids=[rid, rid + 10_000])
    clean = [r for r, v in verdicts.items() if not v][:5]
    inj.fire("batch.dispatch", rids=clean)   # all-clean cohort passes
    # same seed -> same poison set; different seed -> (almost surely)
    # a different one
    inj2 = FaultInjector.from_spec("batch.dispatch:mode=request,p=0.2",
                                   seed=7)
    for rid in rids:
        try:
            inj2.fire("batch.dispatch", rids=[rid])
        except InjectedFault:
            pass
    assert inj2.poisoned() == poisoned


@pytest.mark.chaos
def test_latency_only_rule_delays_without_error():
    inj = FaultInjector.from_spec("engine.dispatch:p=1,latency_ms=30",
                                  seed=0)
    t0 = time.monotonic()
    inj.fire("engine.dispatch")    # must NOT raise
    assert time.monotonic() - t0 >= 0.025


# -- deadline propagation -------------------------------------------------


def test_expired_deadline_rejected_at_submit(rng):
    eng = StubEngine(max_batch=16)
    metrics = ServeMetrics()
    b = DynamicBatcher(eng, metrics=metrics).start()
    try:
        with pytest.raises(DeadlineExceeded):
            b.submit(_rows(rng, 2), deadline_s=time.monotonic() - 0.01)
        assert metrics.snapshot()["resilience"][
            "deadline_shed_requests"] == 1
        assert eng.calls == []     # zero device work
        # a live deadline still serves normally
        out = b.submit(_rows(rng, 3),
                       deadline_s=time.monotonic() + 30).result(timeout=10)
        assert out.shape == (3, 10)
    finally:
        b.stop()


def test_queued_request_shed_before_dispatch_when_deadline_expires(rng):
    """The 504-fast path: a request whose deadline passes while it
    waits in the queue fails at pop time WITHOUT being dispatched —
    and its cohort-mates still dispatch."""
    eng = StubEngine(max_batch=16)
    gate = threading.Event()
    eng.gate = gate
    metrics = ServeMetrics()
    b = DynamicBatcher(eng, max_wait_us=1000, max_inflight=1,
                       metrics=metrics).start()
    try:
        first = b.submit(_rows(rng, 1))      # occupies the single slot
        assert eng.in_call.wait(timeout=10)
        doomed = b.submit(_rows(rng, 2),
                          deadline_s=time.monotonic() + 0.02)
        ok = b.submit(_rows(rng, 3))
        time.sleep(0.05)                     # deadline passes queued
        gate.set()
        assert first.result(timeout=10).shape == (1, 10)
        with pytest.raises(DeadlineExceeded, match="shed before"):
            doomed.result(timeout=10)
        assert ok.result(timeout=10).shape == (3, 10)
        assert eng.calls == [1, 3], eng.calls   # the 2-row never ran
        snap = metrics.snapshot()["resilience"]
        assert snap["deadline_shed_requests"] == 1
        assert snap["deadline_shed_rows"] == 2
    finally:
        b.stop()


def test_whole_drain_shed_keeps_pipeline_alive(rng):
    """Every request of a drain expiring must loop the dispatch thread
    back to coalescing (not shut it down) — later traffic still
    serves, and stop() still drains clean."""
    eng = StubEngine(max_batch=16)
    gate = threading.Event()
    eng.gate = gate
    b = DynamicBatcher(eng, max_wait_us=1000, max_inflight=1).start()
    try:
        first = b.submit(_rows(rng, 1))
        assert eng.in_call.wait(timeout=10)
        doomed = [b.submit(_rows(rng, 1),
                           deadline_s=time.monotonic() + 0.02)
                  for _ in range(3)]
        time.sleep(0.05)
        gate.set()
        first.result(timeout=10)
        for f in doomed:
            with pytest.raises(DeadlineExceeded):
                f.result(timeout=10)
        out = b.submit(_rows(rng, 4)).result(timeout=10)
        assert out.shape == (4, 10)
        assert eng.calls == [1, 4]
    finally:
        b.stop()


# -- poison-batch bisection ----------------------------------------------


class PoisonStubEngine(StubEngine):
    """StubEngine whose dispatch() raises for any cohort containing a
    marked request (first pixel == 211) — a content-deterministic
    poison, independent of the fault injector."""

    def dispatch(self, x):
        parts = x if isinstance(x, (list, tuple)) else [x]
        if any(np.asarray(p).flat[0] == 211 for p in parts):
            self.calls.append(-sum(np.asarray(p).reshape(
                -1, 784).shape[0] for p in parts))
            raise RuntimeError("poison request in cohort")
        return super().dispatch(x)


def _poison_rows(n):
    x = np.full((n, 28, 28, 1), 5, np.uint8)
    x[0, 0, 0, 0] = 211
    return x


def test_bisection_isolates_poison_and_rescues_cohort(rng):
    eng = PoisonStubEngine(max_batch=16)
    gate = threading.Event()
    eng.gate = gate
    metrics = ServeMetrics()
    b = DynamicBatcher(eng, max_wait_us=50_000, max_inflight=4,
                       resilience=ResiliencePolicy(bisect=True),
                       metrics=metrics).start()
    try:
        first = b.submit(_rows(rng, 1))      # holds the pipeline at the
        assert eng.in_call.wait(timeout=10)  # gate while a cohort forms
        clean = [b.submit(_rows(rng, 2)) for _ in range(2)]
        bad = b.submit(_poison_rows(2))
        clean.append(b.submit(_rows(rng, 3)))
        gate.set()
        assert first.result(timeout=10).shape == (1, 10)
        with pytest.raises(RuntimeError, match="poison"):
            bad.result(timeout=10)
        for i, f in enumerate(clean):
            assert f.result(timeout=10).shape[1] == 10, i
        snap = metrics.snapshot()["resilience"]
        assert snap["poison_isolated_requests"] == 1
        assert snap["poison_isolated_rows"] == 2
        assert snap["bisect_rescued_requests"] == 3
        assert snap["bisect_rescued_rows"] == 7
        assert snap["bisect_splits"] >= 1
        assert snap["dispatch_error_requests"] == 0
        # the failed whole-cohort attempt, then sub-dispatches (negative
        # entries are the poison-containing attempts)
        assert [c for c in eng.calls if c < 0], eng.calls
    finally:
        b.stop()


def test_bisection_disabled_fails_whole_cohort(rng):
    eng = PoisonStubEngine(max_batch=16)
    gate = threading.Event()
    eng.gate = gate
    metrics = ServeMetrics()
    b = DynamicBatcher(eng, max_wait_us=50_000, max_inflight=4,
                       resilience=ResiliencePolicy(bisect=False),
                       metrics=metrics).start()
    try:
        first = b.submit(_rows(rng, 1))
        assert eng.in_call.wait(timeout=10)
        mates = [b.submit(_rows(rng, 2)) for _ in range(2)]
        bad = b.submit(_poison_rows(1))
        gate.set()
        first.result(timeout=10)
        for f in [bad] + mates:    # pre-ISSUE 5 behavior: all die
            with pytest.raises(RuntimeError, match="poison"):
                f.result(timeout=10)
        snap = metrics.snapshot()["resilience"]
        assert snap["dispatch_error_requests"] == 3
        assert snap["bisect_splits"] == 0
    finally:
        b.stop()


def test_all_poison_cohort_releases_window(rng):
    """Every request poisoned: bisection fails them all individually
    and must release the parent's window slot — the pipeline still
    serves afterwards (regression guard for the zero-enqueued path)."""
    eng = PoisonStubEngine(max_batch=16)
    gate = threading.Event()
    eng.gate = gate
    b = DynamicBatcher(eng, max_wait_us=50_000, max_inflight=1,
                       resilience=ResiliencePolicy(bisect=True)).start()
    try:
        first = b.submit(_rows(rng, 1))
        assert eng.in_call.wait(timeout=10)
        bad = [b.submit(_poison_rows(1)) for _ in range(2)]
        gate.set()
        first.result(timeout=10)
        for f in bad:
            with pytest.raises(RuntimeError, match="poison"):
                f.result(timeout=10)
        assert b.submit(_rows(rng, 2)).result(timeout=10).shape == (2, 10)
        assert b.pending_rows() == 0 and b.inflight_batches() == 0
    finally:
        b.stop()


@pytest.mark.chaos
def test_injected_poison_end_to_end_exact_isolation(rng):
    """The chaos contract at batcher level: with a request-sticky
    injected dispatch fault, EXACTLY the injector's poisoned rids fail
    (with InjectedFault) and every other request succeeds."""
    eng = StubEngine(max_batch=16)
    metrics = ServeMetrics()
    faults.install(FaultInjector.from_spec(
        "batch.dispatch:mode=request,p=0.12", seed=3))
    b = DynamicBatcher(eng, max_wait_us=5000, max_inflight=2,
                       resilience=ResiliencePolicy(bisect=True),
                       metrics=metrics).start()
    try:
        futs = [b.submit(_rows(rng, 1)) for _ in range(60)]
        failed = 0
        for f in futs:
            try:
                assert f.result(timeout=30).shape == (1, 10)
            except InjectedFault:
                failed += 1
        poisoned = faults.active().poisoned()
        assert failed == len(poisoned) > 0
        snap = metrics.snapshot()["resilience"]
        assert snap["poison_isolated_requests"] == failed
    finally:
        b.stop()


# -- circuit breaker + auto-rollback -------------------------------------


def test_breaker_trips_on_ratio_with_min_volume():
    br = CircuitBreaker(window_s=10.0, min_requests=10,
                        failure_ratio=0.5, cooldown_s=5.0)
    t = 100.0
    # 9 failures: under min volume, no trip
    for i in range(9):
        assert br.record("v1", ok=False, now=t + i * 0.01) is False
    # the 10th crosses volume AND ratio
    assert br.record("v1", ok=False, now=t + 0.1) is True
    assert br.trips() == 1
    # cooldown: more failures do not re-trip
    for i in range(20):
        assert br.record("v1", ok=False, now=t + 0.2 + i * 0.01) is False
    # other versions have independent windows
    for i in range(9):
        assert br.record("v2", ok=True, now=t + i * 0.01) is False
    # mostly-ok traffic never trips
    for i in range(50):
        assert br.record("v3", ok=(i % 10 != 0), now=t + i * 0.01) \
            is False


def test_breaker_window_slides():
    br = CircuitBreaker(window_s=1.0, min_requests=4, failure_ratio=0.5)
    t = 50.0
    for i in range(10):
        assert br.record("v", ok=False, now=t + i * 0.01) is not None
    # trip happened at volume 4; outside cooldown=30 default... use
    # fresh breaker for the aging assertion
    br = CircuitBreaker(window_s=1.0, min_requests=4, failure_ratio=0.5)
    br.record("v", ok=False, now=t)
    br.record("v", ok=False, now=t + 0.01)
    br.record("v", ok=False, now=t + 0.02)
    # 2s later the old failures have aged out: one failure + 3 ok is
    # volume 4 but ratio 0.25 — no trip
    for i, ok in enumerate((True, True, True, False)):
        assert br.record("v", ok=ok, now=t + 2.0 + i * 0.01) is False


def test_breaker_rejects_bad_params():
    for kw in ({"window_s": 0}, {"min_requests": 0},
               {"failure_ratio": 0}, {"failure_ratio": 1.5},
               {"cooldown_s": -1}):
        with pytest.raises(ValueError):
            CircuitBreaker(**kw)


def test_policy_trip_invokes_registry_rollback_async():
    calls = []
    done = threading.Event()

    class StubRegistry:
        def rollback(self, version, reason):
            calls.append((version, reason))
            done.set()
            return SimpleNamespace(version="v-prev")

    metrics = ServeMetrics()
    pol = ResiliencePolicy(
        bisect=True,
        breaker=CircuitBreaker(window_s=5.0, min_requests=5,
                               failure_ratio=0.5, cooldown_s=30.0),
        registry=StubRegistry(), metrics=metrics)
    pol.record_outcome(None, ok=False, n=50)   # untagged: never counted
    for _ in range(5):
        pol.record_outcome("v9", ok=False)
    assert done.wait(timeout=10), "rollback thread never ran"
    assert calls == [("v9", "circuit breaker tripped on v9")]
    snap = metrics.snapshot()["resilience"]
    assert snap["breaker_trips"] == 1
    # record_rollback lands on the rollback thread; poll briefly
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline:
        if metrics.snapshot()["resilience"]["rollbacks"] == 1:
            break
        time.sleep(0.01)
    snap = metrics.snapshot()["resilience"]
    assert snap["rollbacks"] == 1
    assert snap["last_rollback"]["from"] == "v9"
    assert snap["last_rollback"]["to"] == "v-prev"


def test_systemic_503_errors_never_bisect(rng):
    """NoLiveModel (and anything 503-shaped) is a systemic shed, not a
    request fault: the segment must fail whole without futile split
    retries, without fake poison-isolation telemetry, and without
    feeding the breaker (there is no version to blame)."""
    from distributedmnist_tpu.serve import NoLiveModel

    class WarmingEngine(StubEngine):
        def dispatch(self, x):
            parts = x if isinstance(x, (list, tuple)) else [x]
            self.calls.append(sum(np.asarray(p).reshape(-1, 784).shape[0]
                                  for p in parts))
            raise NoLiveModel("no warmed model version is live")

    eng = WarmingEngine(max_batch=16)
    metrics = ServeMetrics()
    b = DynamicBatcher(eng, max_wait_us=50_000, max_inflight=4,
                       resilience=ResiliencePolicy(bisect=True),
                       metrics=metrics).start()
    try:
        futs = [b.submit(_rows(rng, 2)) for _ in range(3)]
        time.sleep(0.02)           # let them coalesce into one drain
        for f in futs:
            with pytest.raises(NoLiveModel):
                f.result(timeout=10)
        snap = metrics.snapshot()["resilience"]
        assert snap["bisect_splits"] == 0
        assert snap["poison_isolated_requests"] == 0
        assert snap["dispatch_error_requests"] == 3
        # exactly the coalesced attempts, no split retries
        assert all(c > 0 for c in eng.calls)
        assert len(eng.calls) <= 3
    finally:
        b.stop()


def test_dispatch_failures_feed_breaker(rng):
    """An engine dying at dispatch() (not just fetch) must be able to
    trip the breaker: the failure is blamed on the engine's version
    (live target for a Router) since no handle exists yet."""
    eng = PoisonStubEngine(max_batch=16)
    eng.version = "vX"                     # bare-engine version label
    calls = []
    done = threading.Event()

    class StubRegistry:
        def rollback(self, version, reason):
            calls.append(version)
            done.set()
            return None

    pol = ResiliencePolicy(
        bisect=False,
        breaker=CircuitBreaker(window_s=10.0, min_requests=3,
                               failure_ratio=0.5, cooldown_s=30.0),
        registry=StubRegistry())
    b = DynamicBatcher(eng, max_wait_us=1000, resilience=pol).start()
    try:
        futs = [b.submit(_poison_rows(1)) for _ in range(4)]
        for f in futs:
            with pytest.raises(RuntimeError, match="poison"):
                f.result(timeout=10)
        assert done.wait(timeout=10), "dispatch failures never tripped"
        assert calls == ["vX"]
    finally:
        b.stop()


# -- registry: rollback + last_error (real engines) -----------------------


@pytest.fixture()
def factory(eight_devices):
    from distributedmnist_tpu import models
    from distributedmnist_tpu.parallel import make_mesh
    from distributedmnist_tpu.serve import EngineFactory

    mesh = make_mesh(eight_devices)
    model = models.build("mlp", platform="cpu")
    return EngineFactory(model, mesh, max_batch=16)


def test_registry_rollback_promotes_newest_healthy(factory):
    router = factory.make_router()
    registry = ModelRegistry(factory, router)
    registry.promote(registry.add(factory.init_params(0),
                                  version="v1").version)
    registry.add(factory.init_params(1), version="v2")
    registry.promote("v2")                       # v1 demoted to ready
    target = registry.rollback("v2", reason="breaker tripped on v2")
    assert target.version == "v1"
    assert registry.live_version() == "v1"
    demoted = registry.get("v2")
    assert demoted.state == "ready"
    assert "breaker tripped" in demoted.last_error
    assert demoted.last_error_at is not None
    events = registry.events()
    assert events[-1]["event"] == "rollback"
    assert events[-1]["from"] == "v2" and events[-1]["to"] == "v1"
    # describe() carries both (GET /models surface)
    desc = registry.describe()
    assert desc["events"][-1]["event"] == "rollback"
    v2 = next(v for v in desc["versions"] if v["version"] == "v2")
    assert "breaker tripped" in v2["last_error"]
    # the rolled-back-FROM version is unhealthy: a second trip on v1
    # must not bounce straight back to v2
    assert registry.rollback("v1", reason="second trip") is None
    assert registry.live_version() == "v1"
    assert registry.events()[-1]["event"] == "rollback_failed"
    # a stale trip (live already moved) is a no-op
    assert registry.rollback("v2", reason="stale") is None


def test_registry_rollback_ignores_errored_and_unwarmed(factory):
    router = factory.make_router()
    registry = ModelRegistry(factory, router)
    registry.promote(registry.add(factory.init_params(0),
                                  version="v1").version)
    mv2 = registry.add(factory.init_params(1), version="v2")
    mv2.record_error("warmup: synthetic")        # unhealthy resident
    assert registry.rollback("v1", reason="trip") is None
    assert registry.live_version() == "v1"       # better than no model


@pytest.mark.chaos
def test_registry_warmup_failure_surfaces_last_error(factory):
    faults.install(FaultInjector.from_spec("registry.warmup:p=1,"
                                           "error=warmup exploded"))
    registry = ModelRegistry(factory, factory.make_router())
    with pytest.raises(InjectedFault):
        registry.add(factory.init_params(0), version="vboom")
    mv = registry.get("vboom")
    assert mv.state == "failed" and mv.engine is None
    assert "warmup exploded" in mv.last_error
    desc = registry.describe()["versions"][0]
    assert desc["last_error"] and desc["last_error_at"]


@pytest.mark.chaos
def test_registry_restore_failure_recorded_per_version(factory,
                                                       tmp_path):
    """An injected restore failure (fired BEFORE orbax touches disk, so
    a bare committed-step directory suffices) leaves a failed version
    entry carrying last_error — GET /models tells the operator what
    died, not just the one admin response. A retry under the same name
    is allowed once the failure clears."""
    (tmp_path / "ck" / "5").mkdir(parents=True)
    registry = ModelRegistry(factory, factory.make_router(),
                             checkpoint_dir=str(tmp_path / "ck"))
    faults.install(FaultInjector.from_spec(
        "registry.restore:p=1,error=disk on fire"))
    with pytest.raises(InjectedFault):
        registry.load_latest()
    mv = registry.get("step-5")
    assert mv.state == "failed"
    assert "disk on fire" in mv.last_error
    assert mv.step == 5
    faults.uninstall()
    # the retry path deletes the failed entry first; the bare dir now
    # fails INSIDE orbax instead — a real (non-injected) error class —
    # and must re-record, not KeyError on a stale entry
    with pytest.raises(Exception) as ei:
        registry.load_latest()
    assert not isinstance(ei.value, InjectedFault)
    assert registry.get("step-5").state == "failed"
