"""Trace-replay load generation contracts (serve/workload.py, ISSUE
20): the spec grammar fails loudly on anything malformed (and bench.py
maps that to exit 2 at argparse), one seed materializes to BYTE-
identical schedules forever, legs are independent streams, every
shape's events respect its declared envelope, and the drifting-Zipf
shape measurably churns a bounded LRU versus the pinned-hot-set
control — the property the PR 10 cache bench leans on."""

import os
import subprocess
import sys
from collections import OrderedDict

import pytest

from distributedmnist_tpu.serve import workload
from tests.conftest import worker_env

pytestmark = pytest.mark.autoscale


def _run_bench(extra, timeout=120):
    env, repo = worker_env()
    return subprocess.run(
        [sys.executable, os.path.join(repo, "bench.py")] + extra,
        capture_output=True, text=True, env=env, cwd=repo,
        timeout=timeout)


# -- spec grammar ----------------------------------------------------------


def test_parse_defaults_and_overrides():
    legs = workload.parse_trace_spec(
        "square:qps=30,burst=6,period=1.5;zipf:keys=16,hot=4")
    assert [l.shape for l in legs] == ["square", "zipf"]
    sq = legs[0].params
    assert (sq["qps"], sq["burst"], sq["period"]) == (30.0, 6.0, 1.5)
    assert sq["duty"] == 0.5                      # untouched default
    zp = legs[1].params
    assert (zp["keys"], zp["hot"]) == (16, 4)
    assert workload.total_duration(legs) == pytest.approx(
        sq["duration"] + zp["duration"])
    # describe() round-trips into the bench artifact
    desc = workload.describe(legs)
    assert desc[0]["shape"] == "square"
    assert desc[1]["params"]["hot"] == 4


@pytest.mark.parametrize("spec,fragment", [
    ("bogus:qps=10", "unknown trace shape"),
    ("square:qps", "want key=value"),
    ("square:nope=3", "unknown parameter"),
    ("square:qps=fast", "want float"),
    ("", "contains no legs"),
    ("square:duty=1.5", "duty must be in (0, 1)"),
    ("square:qps=0", "qps must be > 0"),
    ("zipf:alpha=0.9", "alpha must be > 1"),
    ("zipf:hot=99,keys=8", "hot must be in [1, keys]"),
    ("spike:at=3,width=2,duration=4", "must fit inside duration"),
    ("ragged:max_rows=0", "max_rows must be >= 1"),
])
def test_parse_rejects_malformed(spec, fragment):
    with pytest.raises(ValueError) as e:
        workload.parse_trace_spec(spec)
    assert fragment in str(e.value)


def test_bench_rejects_bad_trace_spec_at_argparse():
    """A malformed --trace-replay must die at argparse (exit 2) naming
    the offending fragment — never replay *something else*; and
    --autoscale without a trace is meaningless (there is no load to
    react to)."""
    out = _run_bench(["serve", "--trace-replay", "bogus:qps=10",
                      "--no-artifact"])
    assert out.returncode == 2, out.stderr[-2000:]
    assert "unknown trace shape" in out.stderr
    out = _run_bench(["serve", "--autoscale", "--no-artifact"])
    assert out.returncode == 2, out.stderr[-2000:]
    assert "--trace-replay" in out.stderr


# -- deterministic replay --------------------------------------------------


def test_same_seed_materializes_byte_identical():
    spec = ("diurnal:qps=40,peak=4,duration=2;"
            "square:qps=30,burst=5,duration=2;"
            "zipf:qps=50,duration=2,drift_every=0.5")
    legs = workload.parse_trace_spec(spec)
    a = workload.schedule_bytes(workload.materialize(legs, seed=7))
    b = workload.schedule_bytes(
        workload.materialize(workload.parse_trace_spec(spec), seed=7))
    assert a == b, "same (spec, seed) must replay bit-identically"
    assert len(a) > 0
    c = workload.schedule_bytes(workload.materialize(legs, seed=8))
    assert a != c, "a different seed must produce a different schedule"


def test_legs_are_independent_streams():
    """Appending a leg must not perturb an earlier leg's arrivals —
    each leg draws from its own (seed, index)-derived stream, so a
    trace can be extended without invalidating the prefix."""
    one = workload.materialize(
        workload.parse_trace_spec("square:qps=40,duration=2"), seed=3)
    both = workload.materialize(
        workload.parse_trace_spec(
            "square:qps=40,duration=2;spike:qps=20,duration=2,"
            "at=0.5,width=0.5"), seed=3)
    prefix = [e for e in both if e.t < 2.0]
    assert workload.schedule_bytes(prefix) == workload.schedule_bytes(one)


# -- shape envelopes -------------------------------------------------------


def test_events_respect_the_leg_envelope():
    legs = workload.parse_trace_spec(
        "square:qps=60,burst=5,duration=3,period=1,duty=0.3,"
        "rows=4,keys=8")
    events = workload.materialize(legs, seed=11)
    assert events, "a 3 s leg at >= 60 qps produced nothing"
    assert all(0.0 <= e.t < 3.0 for e in events)
    assert all(e.t <= n.t for e, n in zip(events, events[1:])), (
        "schedule must be sorted by arrival offset")
    assert all(e.rows == 4 for e in events)
    assert all(0 <= e.key < 8 for e in events)
    # the burst phase (first 30% of each period) must be visibly denser
    # than the off phase — 5x the rate over a fixed window
    burst = sum(1 for e in events if (e.t % 1.0) < 0.3)
    off = len(events) - burst
    assert burst > off, (
        f"burst window got {burst} arrivals vs {off} off-phase — the "
        "square wave is not shaping the rate")


def test_ragged_mixes_row_sizes():
    events = workload.materialize(
        workload.parse_trace_spec(
            "ragged:qps=80,duration=2,max_rows=20"), seed=5)
    sizes = {e.rows for e in events}
    assert all(1 <= r <= 20 for r in sizes)
    assert len(sizes) >= 8, (
        f"ragged drew only {sorted(sizes)} — no adversarial size mix")


# -- the drifting hot set churns a bounded cache ---------------------------


def _lru_hit_ratio(events, capacity):
    lru, hits = OrderedDict(), 0
    for e in events:
        if e.key in lru:
            hits += 1
            lru.move_to_end(e.key)
        else:
            lru[e.key] = True
            if len(lru) > capacity:
                lru.popitem(last=False)
    return hits / max(len(events), 1)


def test_zipf_drift_churns_cache_vs_static_control():
    """The zipf shape's CONTRACT: with drift_every > 0 the hot set
    rotates, so a bounded LRU that comfortably holds the static hot
    set keeps missing after every rotation — the hit ratio drops
    measurably versus the drift_every=0 control on the SAME rate, key
    universe and skew. (This is the property that makes the shape
    worth benching the PR 10 cache under.)"""
    base = "zipf:qps=150,duration=4,keys=64,hot=8,alpha=2.0"
    static = workload.materialize(
        workload.parse_trace_spec(base + ",drift_every=0"), seed=9)
    drift = workload.materialize(
        workload.parse_trace_spec(base + ",drift_every=0.25"), seed=9)
    cap = 12                       # holds the hot set + some cold tail
    static_hits = _lru_hit_ratio(static, cap)
    drift_hits = _lru_hit_ratio(drift, cap)
    assert static_hits > drift_hits, (
        f"drifting hot set did not churn: static {static_hits:.3f} "
        f"vs drift {drift_hits:.3f}")
    assert static_hits - drift_hits > 0.08, (
        f"churn too small to bench against: static {static_hits:.3f} "
        f"vs drift {drift_hits:.3f}")
