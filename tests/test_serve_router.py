"""serve/router.py: version-pure hot-swap under concurrent load (no
request fails or mixes versions mid-swap), shadow isolation (candidate
results never reach clients; comparisons and failures are recorded),
canary population splitting with version-tagged metrics — against stub
engines whose 'logits' encode which version computed them, so any leak
or mix is visible in the output bytes."""

import threading
import time
from types import SimpleNamespace

import numpy as np
import pytest

from distributedmnist_tpu.serve import (DynamicBatcher, NoLiveModel,
                                        Router, ServeMetrics)
from distributedmnist_tpu.serve.engine import InferenceEngine

BUCKETS = (4, 8, 16)


class VersionStubEngine:
    """Engine-shaped double stamping every output row with a per-version
    constant: row r of a request gets logits full of `stamp`, so a
    client can prove exactly which version served it. Optional fail
    flags make dispatch/fetch raise (the broken-candidate case)."""

    platform = "cpu"
    max_batch = 16
    buckets = BUCKETS

    def __init__(self, stamp: float, fail_dispatch=False,
                 fail_fetch=False):
        self.stamp = stamp
        self.fail_dispatch = fail_dispatch
        self.fail_fetch = fail_fetch
        self.dispatches = 0
        self._lock = threading.Lock()

    _as_images = staticmethod(InferenceEngine._as_images)

    def bucket_for(self, n):
        for b in self.buckets:
            if b >= n:
                return b
        raise ValueError(n)

    def dispatch(self, x):
        if self.fail_dispatch:
            raise RuntimeError("candidate dispatch broke")
        parts = ([self._as_images(p) for p in x]
                 if isinstance(x, (list, tuple))
                 else [self._as_images(x)])
        n = sum(p.shape[0] for p in parts)
        with self._lock:
            self.dispatches += 1
        return SimpleNamespace(n=n, bucket=self.bucket_for(n))

    def fetch(self, handle):
        if self.fail_fetch:
            raise RuntimeError("candidate fetch broke")
        return np.full((handle.n, 10), self.stamp, np.float32)


def _router(metrics=None, seed=0):
    return Router(max_batch=16, buckets=BUCKETS, platform="cpu",
                  n_chips=4, metrics=metrics, seed=seed)


def _rows(rng, n):
    return rng.integers(0, 256, (n, 28, 28, 1)).astype(np.uint8)


def test_no_live_model_raises_503_semantics(rng):
    r = _router()
    with pytest.raises(NoLiveModel) as ei:
        r.dispatch(_rows(rng, 2))
    assert ei.value.status == 503


def test_no_live_fails_futures_not_the_pipeline(rng):
    """Submits before any version is live fail their own futures with
    NoLiveModel; a later set_live serves normally on the same batcher —
    the pipeline survives the warming window."""
    r = _router()
    b = DynamicBatcher(r, max_wait_us=200, queue_depth=256).start()
    try:
        f = b.submit(_rows(rng, 2))
        with pytest.raises(NoLiveModel):
            f.result(timeout=10)
        r.set_live(VersionStubEngine(1.0), "v1")
        out = b.submit(_rows(rng, 2)).result(timeout=10)
        assert np.all(out == 1.0)
    finally:
        b.stop()


def test_geometry_mismatch_rejected():
    r = _router()
    bad = VersionStubEngine(1.0)
    bad.buckets = (2, 4)
    with pytest.raises(ValueError, match="geometry"):
        r.set_live(bad, "bad")
    with pytest.raises(ValueError, match="geometry"):
        r.set_shadow(bad, "bad", 0.5)


def test_hot_swap_under_concurrent_load_is_version_pure(rng):
    """The mid-swap correctness contract: with client threads hammering
    the batcher while the live version swaps v1 -> v2, every request
    resolves (no failures), every result is ENTIRELY one version's
    output (a batch runs one engine's program), and requests completed
    after the swap settles are v2's."""
    r = _router()
    v1, v2 = VersionStubEngine(1.0), VersionStubEngine(2.0)
    r.set_live(v1, "v1")
    b = DynamicBatcher(r, max_wait_us=200, queue_depth=4096,
                       max_inflight=3).start()
    results, errors = [], []
    stop = threading.Event()

    def client():
        lrng = np.random.default_rng(threading.get_ident() % 2**32)
        while not stop.is_set():
            n = int(lrng.integers(1, 6))
            try:
                out = b.submit(
                    lrng.integers(0, 256, (n, 28, 28, 1))
                    .astype(np.uint8)).result(timeout=30)
                results.append(out)
            except BaseException as e:
                errors.append(e)
                return

    threads = [threading.Thread(target=client, daemon=True)
               for _ in range(4)]
    try:
        for t in threads:
            t.start()
        time.sleep(0.15)
        r.set_live(v2, "v2")              # the atomic hot-swap
        time.sleep(0.15)
        stop.set()
        for t in threads:
            t.join(timeout=30)
    finally:
        stop.set()
        b.stop()
    assert not errors, f"requests failed across the swap: {errors[:3]}"
    assert results, "no traffic flowed"
    for out in results:
        first = out[0, 0]
        assert first in (1.0, 2.0)
        assert np.all(out == first), (
            "a single request mixed model versions")
    # traffic genuinely crossed the swap: both versions served some
    stamps = {out[0, 0] for out in results}
    assert stamps == {1.0, 2.0}, f"swap never observed: {stamps}"
    # a fresh request after the swap is v2's
    b2 = DynamicBatcher(r, max_wait_us=200, queue_depth=64).start()
    try:
        assert np.all(b2.submit(_rows(rng, 2)).result(timeout=10) == 2.0)
    finally:
        b2.stop()


def test_shadow_results_never_reach_clients(rng):
    """Shadow mode duplicates traffic and COMPARES, but the client
    always gets the live result; the comparison lands in metrics."""
    metrics = ServeMetrics()
    r = _router(metrics=metrics)
    live, shadow = VersionStubEngine(1.0), VersionStubEngine(9.0)
    r.set_live(live, "v1")
    r.set_shadow(shadow, "v9", fraction=1.0)
    b = DynamicBatcher(r, max_wait_us=200, queue_depth=256,
                       metrics=metrics).start()
    try:
        for _ in range(6):
            out = b.submit(_rows(rng, 3)).result(timeout=10)
            assert np.all(out == 1.0), "shadow output leaked to a client"
    finally:
        b.stop()
    assert shadow.dispatches >= 6        # the duplicate traffic arrived
    r.drain_shadow(10)                   # comparisons land async
    snap = metrics.snapshot()
    pair = snap["shadow"]["v1->v9"]
    assert pair["rows"] >= 18
    assert pair["agreement"] is not None
    assert pair["max_abs_diff"] == pytest.approx(8.0)
    # shadow population is NOT in by_version: it served no client
    assert "v9" not in snap["by_version"]


def test_shadow_sampling_respects_fraction(rng):
    metrics = ServeMetrics()
    r = _router(metrics=metrics, seed=0)
    live, shadow = VersionStubEngine(1.0), VersionStubEngine(2.0)
    r.set_live(live, "v1")
    r.set_shadow(shadow, "v2", fraction=0.25)
    for _ in range(200):
        r.fetch(r.dispatch(_rows(rng, 1)))
    # seeded draws: the sampled share must sit near the fraction
    assert 20 <= shadow.dispatches <= 80, shadow.dispatches


def test_broken_shadow_never_breaks_live_traffic(rng):
    """A candidate that throws on dispatch AND one that throws on fetch:
    clients see only live results; the failures are counted."""
    for mode in ("fail_dispatch", "fail_fetch"):
        metrics = ServeMetrics()
        r = _router(metrics=metrics)
        r.set_live(VersionStubEngine(1.0), "v1")
        r.set_shadow(VersionStubEngine(5.0, **{mode: True}), "bad",
                     fraction=1.0)
        b = DynamicBatcher(r, max_wait_us=200, queue_depth=256,
                           metrics=metrics).start()
        try:
            for _ in range(3):
                out = b.submit(_rows(rng, 2)).result(timeout=10)
                assert np.all(out == 1.0), mode
        finally:
            b.stop()
        r.drain_shadow(10)
        assert metrics.snapshot()["shadow_errors"] >= 3, mode


@pytest.mark.chaos
def test_shadow_failpoint_injection_swallowed(rng):
    """The router.shadow failpoint (the shadow duplicate's chaos seam,
    ISSUE 12 coverage cross-check DML014): an injected shadow fault is
    swallowed and counted exactly like a real broken candidate — every
    client still gets the live bytes, the shadow engine never
    dispatches, and shadow_errors records each injection."""
    from distributedmnist_tpu.serve import faults

    metrics = ServeMetrics()
    r = _router(metrics=metrics)
    live, shadow = VersionStubEngine(1.0), VersionStubEngine(5.0)
    r.set_live(live, "v1")
    r.set_shadow(shadow, "v2", fraction=1.0)
    faults.install(faults.FaultInjector.from_spec(
        "router.shadow:p=1,error=injected shadow outage", seed=7))
    try:
        for _ in range(4):
            out = r.infer(_rows(rng, 2))
            assert np.all(out == 1.0)
    finally:
        faults.uninstall()
    assert shadow.dispatches == 0      # the fault fired BEFORE dispatch
    assert metrics.snapshot()["shadow_errors"] == 4
    # with the injector gone the same shadow serves comparisons again
    r.infer(_rows(rng, 2))
    r.drain_shadow(10)
    assert shadow.dispatches == 1


def test_slow_shadow_does_not_stall_live_fanout(rng):
    """A shadow candidate wedged in fetch must not delay live results:
    comparisons drain on their own thread, so live futures resolve at
    live speed even while the shadow's fetch blocks."""
    metrics = ServeMetrics()
    r = _router(metrics=metrics)
    gate = threading.Event()

    class SlowShadow(VersionStubEngine):
        def fetch(self, handle):
            assert gate.wait(timeout=30)
            return super().fetch(handle)

    r.set_live(VersionStubEngine(1.0), "v1")
    r.set_shadow(SlowShadow(2.0), "v2", fraction=1.0)
    b = DynamicBatcher(r, max_wait_us=200, queue_depth=256).start()
    try:
        t0 = time.monotonic()
        for _ in range(4):
            out = b.submit(_rows(rng, 2)).result(timeout=5)
            assert np.all(out == 1.0)
        assert time.monotonic() - t0 < 4.0, (
            "live results waited on the wedged shadow fetch")
        assert r.shadow_pending() >= 1   # comparisons queued, not done
        gate.set()
        r.drain_shadow(10)
        assert metrics.snapshot()["shadow"]["v1->v2"]["batches"] >= 1
    finally:
        gate.set()
        b.stop()


def test_canary_splits_traffic_with_version_tagged_metrics(rng):
    """Canary mode routes a fraction FOR REAL: both versions' outputs
    reach clients, and ServeMetrics separates the populations by
    version tag (requests/rows/latency per version)."""
    metrics = ServeMetrics()
    r = _router(metrics=metrics, seed=1)
    v1, v2 = VersionStubEngine(1.0), VersionStubEngine(2.0)
    r.set_live(v1, "v1")
    r.set_canary(v2, "v2", fraction=0.3)
    b = DynamicBatcher(r, max_wait_us=0, queue_depth=4096,
                       metrics=metrics).start()
    served = []
    try:
        for _ in range(120):
            f = b.submit(_rows(rng, 1))
            served.append((f.result(timeout=10)[0, 0],
                           getattr(f, "version", None)))
    finally:
        b.stop()
    assert {s for s, _ in served} == {1.0, 2.0}, "canary never served"
    # the future's version tag attributes each request to the version
    # that actually computed it (stub stamp 1.0 <-> v1, 2.0 <-> v2)
    for stamp, version in served:
        assert version == {1.0: "v1", 2.0: "v2"}[stamp]
    snap = metrics.snapshot()
    bv = snap["by_version"]
    assert set(bv) == {"v1", "v2"}
    total = bv["v1"]["requests"] + bv["v2"]["requests"]
    assert total == 120
    assert 0 < bv["v2"]["requests"] < bv["v1"]["requests"]
    for v in ("v1", "v2"):
        assert bv[v]["latency_ms"]["p50"] is not None


def test_shadow_duplication_bounded_by_cap(rng):
    """A wedged candidate must cost bounded memory: past shadow_cap
    outstanding duplicates, sampled batches skip the duplicate (counted
    as shadow_dropped) instead of growing the queue without bound."""
    metrics = ServeMetrics()
    r = Router(max_batch=16, buckets=BUCKETS, platform="cpu",
               n_chips=4, metrics=metrics, shadow_cap=2)
    gate = threading.Event()

    class WedgedShadow(VersionStubEngine):
        def fetch(self, handle):
            assert gate.wait(timeout=30)
            return super().fetch(handle)

    shadow = WedgedShadow(2.0)
    r.set_live(VersionStubEngine(1.0), "v1")
    r.set_shadow(shadow, "v2", fraction=1.0)
    try:
        for _ in range(10):
            r.fetch(r.dispatch(_rows(rng, 1)))   # live results flow
        assert r.shadow_pending() <= 2
        assert shadow.dispatches <= 2, (
            "duplication ran past the outstanding cap")
        assert metrics.snapshot()["shadow_dropped"] == 8
    finally:
        gate.set()
    r.drain_shadow(10)
    assert r.shadow_pending() == 0


def test_promote_clears_candidate_role(rng):
    """Promoting the canary/shadow version to live clears its candidate
    role — it can't shadow itself."""
    r = _router()
    v1, v2 = VersionStubEngine(1.0), VersionStubEngine(2.0)
    r.set_live(v1, "v1")
    r.set_canary(v2, "v2", fraction=0.5)
    r.set_live(v2, "v2")
    routes = r.routes()
    assert routes == {"live": "v2", "canary": None, "shadow": None,
                      "alternates": ["float32"]}


def test_fraction_validation():
    r = _router()
    eng = VersionStubEngine(1.0)
    for bad in (0.0, -0.1, 1.5):
        with pytest.raises(ValueError, match="fraction"):
            r.set_shadow(eng, "v", bad)
    with pytest.raises(ValueError, match="fraction"):
        r.set_canary(eng, "v", 1.0)   # canary must leave live traffic
