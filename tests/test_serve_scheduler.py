"""serve/scheduler.py: the cost-model batch former (split-vs-pad DP over
request boundaries) and the Clipper-style AIMD adaptive-coalescing
controller — pure policy, tested with synthetic cost tables and
synthetic latency/arrival streams, no jax."""

import numpy as np
import pytest

from distributedmnist_tpu.serve.scheduler import (AdaptiveController,
                                                  fit_dispatch_cost,
                                                  plan_segments)

BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128)
# Compute-dominated silicon: cost proportional to bucket rows, no
# per-dispatch overhead — the regime where splitting always pays.
LINEAR = {b: b * 1e-3 for b in BUCKETS}
# Overhead-dominated host: every dispatch costs ~the same regardless of
# rows — the regime where splitting NEVER pays.
FLAT = {b: 1e-3 for b in BUCKETS}


def _covering(n, buckets=BUCKETS):
    return next(b for b in buckets if b >= n)


def _segment_rows(sizes, counts):
    out, off = [], 0
    for c in counts:
        out.append(sum(sizes[off:off + c]))
        off += c
    return out


def test_fit_dispatch_cost_recovers_affine_model():
    o, m = fit_dispatch_cost({b: 2e-3 + 0.5e-3 * b for b in BUCKETS})
    assert o == pytest.approx(2e-3, rel=1e-6)
    assert m == pytest.approx(0.5e-3, rel=1e-6)
    o, m = fit_dispatch_cost(FLAT)
    assert o == pytest.approx(1e-3) and m == 0.0
    # negative slopes/intercepts are measurement noise: clamped, never
    # propagated into the planner as "bigger batches are cheaper"
    o, m = fit_dispatch_cost({1: 5e-3, 128: 1e-3})
    assert m == 0.0 and o >= 0.0
    with pytest.raises(ValueError):
        fit_dispatch_cost({})


def test_plan_splits_when_cost_table_says_split_beats_pad():
    """The ISSUE example: a 20-row drain on compute-priced buckets runs
    16+4, not one padded 32."""
    counts = plan_segments([4, 4, 4, 4, 4], BUCKETS, LINEAR)
    assert sum(counts) == 5 and len(counts) == 2
    assert sorted(_segment_rows([4] * 5, counts)) == [4, 16]


def test_plan_never_splits_on_flat_costs():
    """Overhead-dominated table: one extra dispatch always costs more
    than any padding it saves — the planner must keep the single
    covering dispatch."""
    assert plan_segments([4, 4, 4, 4, 4], BUCKETS, FLAT) == [5]
    assert plan_segments([1] * 20, BUCKETS, FLAT) == [20]


def test_plan_respects_request_boundaries():
    """A request's rows can never span two dispatches: every cut in the
    returned plan falls between requests, whatever the sizes."""
    rng = np.random.default_rng(0)
    for _ in range(50):
        sizes = [int(n) for n in rng.integers(1, 21, rng.integers(1, 12))]
        counts = plan_segments(sizes, BUCKETS, LINEAR)
        assert all(c >= 1 for c in counts)
        assert sum(counts) == len(sizes)
        # every segment fits its covering bucket (the dispatch the
        # batcher will actually issue)
        for rows in _segment_rows(sizes, counts):
            assert rows <= BUCKETS[-1]


def test_plan_split_reduces_padding_on_linear_costs():
    """On compute-priced buckets the planned dispatches burn strictly
    fewer padded rows than the naive covering bucket whenever a split
    exists."""
    sizes = [12, 9, 20, 15, 8, 11, 9]          # 84 rows -> covering 128
    counts = plan_segments(sizes, BUCKETS, LINEAR)
    assert len(counts) > 1
    planned_pad = sum(_covering(r) - r
                      for r in _segment_rows(sizes, counts))
    naive_pad = _covering(sum(sizes)) - sum(sizes)
    assert planned_pad < naive_pad


def test_plan_degenerate_and_fallback_cases():
    assert plan_segments([], BUCKETS, LINEAR) == []
    assert plan_segments([7], BUCKETS, LINEAR) == [1]
    # a cost table missing any rung is no cost model at all
    partial = dict(LINEAR)
    del partial[32]
    assert plan_segments([4, 4, 4, 4, 4], BUCKETS, partial) == [5]


def test_plan_pad_bias_flips_near_ties_toward_less_padding():
    """pad_bias prices padded rows above real ones: a near-tie (one
    extra dispatch's overhead vs a handful of padded rows) pads at
    bias 1 and splits at the default bias 2."""
    costs = {b: 5e-3 + 0.5e-3 * b for b in BUCKETS}   # o = 10m
    sizes = [12, 8]        # 20 rows: 32 pads 12; 16+8 costs one more o
    assert plan_segments(sizes, BUCKETS, costs, pad_bias=1.0) == [2]
    assert plan_segments(sizes, BUCKETS, costs, pad_bias=2.0) == [1, 1]


def test_aimd_moves_both_directions_within_hard_bounds():
    """The acceptance contract: SLO violations step the effective wait
    DOWN (multiplicative), sustained headroom steps it back UP
    (additive) — and at no point does the wait exceed the configured
    hard cap or go below zero (one-row immediacy)."""
    cap = 1e-3
    c = AdaptiveController(cap, slo_s=0.05, window=4)
    assert c.effective_wait_s() == cap          # starts at the cap
    # a synthetic violation stream: monotone decrease, floored at 0
    seen = [c.effective_wait_s()]
    for _ in range(200):
        c.on_latency(0.06)
        w = c.effective_wait_s()
        assert 0.0 <= w <= cap
        assert w <= seen[-1]
        seen.append(w)
    assert seen[-1] < 1e-6                      # collapsed to immediacy
    assert c.snapshot()["violations"] == 200
    # sustained comfortable headroom: creeps back up, capped
    for _ in range(500):
        c.on_latency(0.001)
        assert c.effective_wait_s() <= cap
    assert c.effective_wait_s() == cap          # fully recovered
    assert c.snapshot()["increases"] > 0


def test_aimd_headroom_requires_comfort_not_just_compliance():
    """Samples under the SLO but above the headroom fraction must NOT
    creep the wait up — barely-compliant latency is not an invitation
    to batch harder."""
    c = AdaptiveController(1e-3, slo_s=0.05, window=4, headroom=0.8)
    c.on_latency(0.06)                          # step down once
    w = c.effective_wait_s()
    for _ in range(100):
        c.on_latency(0.045)                     # compliant, no headroom
    assert c.effective_wait_s() == w


def test_arrival_rate_ewma_and_fill_time_cap():
    """The arrival-rate EWMA tracks a synthetic steady stream, and the
    fill-time cap bounds the effective wait at the time that rate needs
    to fill max_batch rows — waiting longer buys nothing."""
    c = AdaptiveController(0.05, max_batch=16)
    t = 0.0
    for _ in range(5000):                       # 1 row per ms = 1000/s
        c.on_arrival(1, now=t)
        t += 1e-3
    assert c.arrival_rate() == pytest.approx(1000.0, rel=0.05)
    # fill time = 16 rows / 1000 rows/s = 16 ms < the 50 ms static wait
    assert c.effective_wait_s() == pytest.approx(0.016, rel=0.1)
    # no SLO: on_latency is a no-op, the AIMD point never moves
    c.on_latency(99.0)
    assert c.snapshot()["violations"] == 0
    assert c.snapshot()["aimd_wait_us"] == pytest.approx(50_000.0)


def test_controller_validates_arguments():
    with pytest.raises(ValueError, match="max_wait_s"):
        AdaptiveController(-1.0)
    with pytest.raises(ValueError, match="slo_s"):
        AdaptiveController(1e-3, slo_s=0.0)
    with pytest.raises(ValueError, match="decrease"):
        AdaptiveController(1e-3, slo_s=0.1, decrease=1.5)
