"""Observability + schedule tests: jax.profiler tracing via --profile-dir
produces a trace on disk; LR schedules wire into training; bench.py's two
modes emit well-formed single-line JSON."""

import json
import os
import subprocess
import sys

import pytest

from distributedmnist_tpu import optim, trainer
from distributedmnist_tpu.config import Config
from distributedmnist_tpu.data import synthetic_mnist


BASE = Config(device="cpu", synthetic=True, log_every=0,
              target_accuracy=None, model="mlp", optimizer="sgd",
              learning_rate=0.02, batch_size=256, num_devices=8)


@pytest.fixture(scope="module")
def small_data():
    return synthetic_mnist(seed=2, train_n=2048, test_n=512)


def test_profile_dir_writes_trace(tmp_path, small_data):
    prof = str(tmp_path / "prof")
    trainer.fit(BASE.replace(steps=4, eval_every=4, profile_dir=prof),
                data=small_data)
    found = []
    for root, _, files in os.walk(prof):
        found.extend(f for f in files
                     if f.endswith((".pb", ".json.gz", ".trace.json.gz",
                                    ".xplane.pb")))
    assert found, f"no trace files under {prof}"


def test_lr_schedule_constant_vs_cosine_differ(small_data):
    a = trainer.fit(BASE.replace(steps=24, eval_every=24), data=small_data)
    b = trainer.fit(BASE.replace(steps=24, eval_every=24,
                                 lr_schedule="cosine"), data=small_data)
    # same everything except the schedule: trajectories must differ.
    # final_loss is a float32 mean — unlike test_accuracy (a multiple of
    # 1/test_n) two genuinely different trajectories can't collide on it.
    assert a["final_loss"] != b["final_loss"]


def test_lr_decay_steps_pins_horizon_independent_of_run_length(small_data):
    """cfg.lr_decay_steps decouples the cosine decay horizon from the
    run-length knobs: two same-length runs with different pinned horizons
    must differ (the field is plumbed through), and the pinned horizon
    must override the run's own total_steps."""
    kw = dict(steps=24, eval_every=24, lr_schedule="cosine")
    a = trainer.fit(BASE.replace(**kw), data=small_data)           # 24-step decay
    b = trainer.fit(BASE.replace(lr_decay_steps=10_000, **kw),
                    data=small_data)                               # ~flat LR
    assert a["final_loss"] != b["final_loss"]


def test_tta_recipe_lr_curve_invariant_to_max_epochs():
    """The bench time-to-accuracy recipe pins its cosine horizon
    (bench.TTA_DECAY_STEPS): changing the --max-epochs trial BUDGET must
    not reshape the LR schedule the 5-seed tuning grid was collected
    under (round-4 verdict, weak #2). Reconstructs the exact schedule
    trainer.fit derives from the recipe config for two budgets and
    compares the first 500 steps."""
    import argparse
    import sys as _sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    _sys.path.insert(0, repo)
    try:
        import bench
    finally:
        _sys.path.remove(repo)

    def recipe(max_epochs):
        args = argparse.Namespace(
            model="lenet", dtype="float32", data_dir=None,
            max_epochs=max_epochs, target_accuracy=0.99,
            steps_per_call=None)
        return bench.tta_config(args, gb=512)

    schedules = []
    for epochs in (5, 20, 80):
        cfg = recipe(epochs)
        assert cfg.lr_decay_steps == bench.TTA_DECAY_STEPS
        # trainer.fit wiring (trainer.py): pinned horizon wins over the
        # budget-derived total_steps (epochs x steps_per_epoch)
        total_steps = cfg.epochs * (60_000 // cfg.batch_size)
        schedules.append(optim.make_schedule(
            cfg.learning_rate, cfg.lr_schedule, cfg.warmup_steps,
            cfg.lr_decay_steps or total_steps))
    for s in range(0, 501, 50):
        lrs = {float(sch(s)) for sch in schedules}
        assert len(lrs) == 1, f"LR at step {s} varies with budget: {lrs}"


def test_make_schedule_shapes():
    s = optim.make_schedule(0.1, "warmup-cosine", warmup_steps=10,
                            total_steps=100)
    assert float(s(0)) == 0.0
    assert abs(float(s(10)) - 0.1) < 1e-6   # peak at end of warmup
    assert float(s(100)) < 1e-3             # decayed
    with pytest.raises(ValueError, match="total_steps"):
        optim.make_schedule(0.1, "cosine")
    with pytest.raises(ValueError, match="unknown"):
        optim.make_schedule(0.1, "sawtooth")
    # warmup-cosine with no warmup would silently equal plain cosine
    with pytest.raises(ValueError, match="warmup-steps"):
        optim.make_schedule(0.1, "warmup-cosine", warmup_steps=0,
                            total_steps=100)


def _run_bench(extra):
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    out = subprocess.run(
        [sys.executable, os.path.join(repo, "bench.py")] + extra,
        capture_output=True, text=True, env=env, cwd=repo, timeout=600)
    assert out.returncode == 0, out.stderr[-2000:]
    lines = [l for l in out.stdout.splitlines() if l.strip()]
    assert len(lines) == 1, f"expected ONE JSON line, got: {out.stdout!r}"
    return json.loads(lines[0])


@pytest.mark.slow
def test_bench_throughput_contract():
    rec = _run_bench(["--bench-steps", "8", "--warmup-steps", "2",
                      "--global-batch", "128"])
    assert set(rec) == {"metric", "value", "unit", "vs_baseline", "detail"}
    assert rec["metric"] == "train_images_per_sec_per_chip"
    assert rec["value"] > 0 and rec["vs_baseline"] > 0


@pytest.mark.slow
def test_train_supervised_forwards_summary():
    """--supervise runs fit in a watchdog-supervised worker and forwards
    the summary JSON line (utils/supervise.py)."""
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    out = subprocess.run(
        [sys.executable, os.path.join(repo, "train.py"), "--supervise",
         "--device", "cpu", "--synthetic", "--model", "mlp",
         "--num-devices", "8", "--batch-size", "256", "--steps", "8",
         "--eval-every", "8", "--log-every", "0"],
        capture_output=True, text=True, env=env, cwd=repo, timeout=600)
    assert out.returncode == 0, out.stderr[-2000:]
    rec = json.loads(out.stdout.splitlines()[-1])
    assert rec["steps"] == 8 and "test_accuracy" in rec


@pytest.mark.slow
def test_bench_time_to_accuracy_contract():
    rec = _run_bench(["--mode", "time-to-accuracy", "--model", "mlp",
                      "--target-accuracy", "0.5", "--global-batch", "256",
                      "--max-epochs", "2", "--trials", "2"])
    assert rec["metric"] == "wall_clock_to_target_accuracy"
    assert rec["unit"] == "seconds"
    assert rec["detail"]["reached_target"] is True
    assert rec["detail"]["final_accuracy"] >= 0.5
    d = rec["detail"]
    assert d["trials"] == 2 and len(d["trials_s"]) == 2
    assert d["min_s"] <= rec["value"] <= d["max_s"]
    # trials must run DISTINCT seeds (round-2 verdict: three runs of one
    # trajectory measure only relay latency) and every seed must reach
    # the target for vs_baseline to count
    seeds = [t["seed"] for t in d["trial_results"]]
    assert len(set(seeds)) == 2
    assert all(t["reached"] for t in d["trial_results"])
    assert rec["vs_baseline"] > 0
    # weather-invariant primaries (round-4 verdict, weak #3): step/eval
    # counts are the reproducible claim; wall seconds carry relay weather
    assert d["wall_s_is_weather_dependent"] is True
    # reached trials only — a budget-exhausted trial's step count is the
    # budget, not a time-to-target (all trials reach in this run)
    assert d["steps_to_target"] == [t["steps"] for t in d["trial_results"]
                                    if t["reached"]]
    import statistics
    assert d["steps_to_target_median"] == int(
        statistics.median(d["steps_to_target"]))
    assert d["evals_to_target"] == [t["evals"] for t in d["trial_results"]
                                    if t["reached"]]
    assert all(e >= 1 for e in d["evals_to_target"])


@pytest.mark.slow
def test_bench_sweep_contract():
    """The sweep mode's JSON contract — the artifact the 8-chip scaling
    claim (SWEEP_r*.json) is built from. Runs the real measurement inline
    on the 8-virtual-device CPU backend, tiny step counts."""
    rec = _run_bench(["--mode", "sweep", "--sweep-batches", "8,16",
                      "--bench-steps", "2", "--warmup-steps", "1",
                      "--repeats", "1", "--model", "mlp"])
    assert rec["metric"] == "predicted_8chip_images_per_sec_per_chip"
    d = rec["detail"]
    assert set(d["curve_img_s_chip"]) == {"8", "16"}
    for point in d["curve_img_s_chip"].values():
        assert point["img_s_chip"] > 0 and point["step_ms"] > 0
        assert point["steps_per_call"] >= 1
    # 8 virtual devices -> the measured step already contains the real
    # collective; the allreduce model must NOT be stacked on top
    assert d["n_chips_measured"] == 8
    assert d["allreduce_modeled"] is False
    assert d["n_params"] == 101770          # MLP 784-128-10
    assert d["strong_scaling"]["per_chip_batch"] == 8
    # weak scaling anchors at the measured curve's PEAK (the operating
    # point), whichever batch that was on this run
    peak = max(d["curve_img_s_chip"],
               key=lambda k: d["curve_img_s_chip"][k]["img_s_chip"])
    assert d["weak_scaling"]["anchor"] == "peak"
    assert str(d["weak_scaling"]["per_chip_batch"]) == peak
    # BOTH anchors are reported (round-4 advice): the fixed largest-batch
    # block rides alongside the noisy-argmax peak so cross-round
    # comparisons have a run-independent anchor too
    assert d["weak_scaling_at_largest"]["anchor"] == "largest"
    assert d["weak_scaling_at_largest"]["per_chip_batch"] == 16
    assert d["weak_scaling_at_largest"]["img_s_chip"] > 0
    # sensitivity band brackets the point estimate for both regimes
    lo, hi = d["prediction_range"]["strong_img_s_chip"]
    assert lo <= d["strong_scaling"]["img_s_chip"] <= hi
    lo, hi = d["prediction_range"]["weak_img_s_chip"]
    assert lo <= d["weak_scaling"]["img_s_chip"] <= hi


@pytest.mark.slow
def test_bench_smoke_contract():
    """The smoke gate's JSON contract (SMOKE_r*.json): all legs present,
    accuracy floor enforced, synthetic data labeled as such."""
    rec = _run_bench(["--mode", "smoke", "--model", "mlp"])
    assert rec["metric"] == "tpu_smoke" and rec["value"] == 1.0
    d = rec["detail"]
    assert d["legs"] == ["train", "eval", "checkpoint-save",
                         "restore-resume", "accuracy-floor"]
    assert d["final_accuracy"] >= 0.85
    assert d["data"] == "synthetic"
    # the throughput field is a caveated short-window number (round-4
    # verdict, weak #4) — a reader must not diff it against the
    # steady-state THROUGHPUT_r*.json
    assert d["short_window"] is True
    assert d["window_steps"] == 64


@pytest.mark.slow
def test_profile_step_contract(tmp_path):
    """scripts/profile_step.py: supervised, runnable from any cwd (it
    bootstraps the repo root onto sys.path itself), one JSON record with
    the requested variants. Guards the per-slice profiling tool the
    BASELINE.md step-anatomy claims are built from."""
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.pop("PYTHONPATH", None)   # the script must self-bootstrap
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out = subprocess.run(
        [sys.executable, os.path.join(repo, "scripts", "profile_step.py"),
         "--only", "empty", "--blocks", "1", "--repeats", "1",
         "--k", "4", "--batch", "8"],
        capture_output=True, text=True, env=env, cwd=str(tmp_path),
        timeout=600)
    assert out.returncode == 0, out.stderr[-2000:]
    lines = [l for l in out.stdout.splitlines() if l.strip()]
    assert len(lines) == 1, out.stdout
    rec = json.loads(lines[0])
    assert set(rec["ms_per_iter"]) == {"empty"}
    assert rec["ms_per_iter"]["empty"] > 0


@pytest.mark.slow
def test_bench_smoke_real_data_dir(tmp_path):
    """--data-dir plumbed through bench (not just trainer.fit): smoke
    loads REAL-format IDX fixtures and must label the run data=real."""
    from idx_util import write_idx_fixtures

    from distributedmnist_tpu.data import synthetic_mnist as synth
    write_idx_fixtures(tmp_path, synth(seed=4, train_n=4096, test_n=1024))
    rec = _run_bench(["--mode", "smoke", "--model", "mlp",
                      "--data-dir", str(tmp_path)])
    assert rec["detail"]["data"] == "real"
    assert rec["detail"]["final_accuracy"] >= 0.85
