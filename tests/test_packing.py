"""Packed-pixel layout + flat-optimizer equivalence tests.

The two round-2 step optimizations (config.py pixel_format="packed",
flat_optimizer=True) are pure re-layouts: packed rows decode to
bit-identical pixels (data/packing.py) and the flat optimizer applies the
same elementwise update to a concatenation of the leaves. Both must leave
training trajectories unchanged — pinned here against the u8/per-leaf
forms on the 8-virtual-device CPU mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributedmnist_tpu import optim, trainer
from distributedmnist_tpu.config import Config
from distributedmnist_tpu.data.packing import pack_rows, unpack_rows


BASE = Config(device="cpu", synthetic=True, log_every=0,
              target_accuracy=None, batch_size=256, num_devices=8,
              steps=12, eval_every=12)


def test_pack_unpack_roundtrip_bit_exact(rng):
    x = rng.integers(0, 256, (37, 28, 28, 1)).astype(np.uint8)
    words = pack_rows(x)
    assert words.shape == (37, 196) and words.dtype == np.int32
    back = np.asarray(unpack_rows(jnp.asarray(words)))
    np.testing.assert_array_equal(back, x.astype(np.float32) / 255.0)


def test_pack_rejects_non_uint8():
    with pytest.raises(ValueError, match="uint8"):
        pack_rows(np.zeros((2, 28, 28, 1), np.float32))


def test_unpack_batched_axes(rng):
    # (K, B, 196) blocks — the scanned superstep's shape — unpack too.
    x = rng.integers(0, 256, (6, 28, 28, 1)).astype(np.uint8)
    words = jnp.asarray(pack_rows(x)).reshape(2, 3, 196)
    out = unpack_rows(words)
    assert out.shape == (2, 3, 28, 28, 1)
    np.testing.assert_array_equal(
        np.asarray(out).reshape(6, 28, 28, 1),
        x.astype(np.float32) / 255.0)


@pytest.mark.parametrize("model", ["mlp", "lenet"])
def test_packed_matches_u8_trajectory(tiny_data, model):
    kw = dict(model=model, optimizer="adam", learning_rate=1e-3,
              flat_optimizer=False)
    a = trainer.fit(BASE.replace(pixel_format="u8", **kw), data=tiny_data)
    b = trainer.fit(BASE.replace(pixel_format="packed", **kw),
                    data=tiny_data)
    assert a["pixel_format"] == "u8" and b["pixel_format"] == "packed"
    np.testing.assert_allclose(a["final_loss"], b["final_loss"],
                               rtol=0, atol=1e-6)
    assert a["test_accuracy"] == b["test_accuracy"]


@pytest.mark.parametrize("opt", ["sgd", "adam"])
def test_flat_matches_per_leaf_trajectory(tiny_data, opt):
    kw = dict(model="lenet", optimizer=opt, learning_rate=1e-3,
              pixel_format="u8")
    a = trainer.fit(BASE.replace(flat_optimizer=False, **kw),
                    data=tiny_data)
    b = trainer.fit(BASE.replace(flat_optimizer=True, **kw),
                    data=tiny_data)
    np.testing.assert_allclose(a["final_loss"], b["final_loss"],
                               rtol=0, atol=1e-6)
    assert a["test_accuracy"] == b["test_accuracy"]


def test_production_defaults_packed_flat(tiny_data):
    # The defaults themselves (packed + flat + explicit-mode off) train.
    out = trainer.fit(BASE.replace(model="lenet", optimizer="adam",
                                   learning_rate=1e-3, steps=30,
                                   eval_every=30),
                      data=tiny_data)
    assert out["pixel_format"] == "packed"
    assert np.isfinite(out["final_loss"])


def test_packed_explicit_mode(tiny_data):
    # shard_map + local gather of packed words + pmean: the explicit SPMD
    # mode composes with the packed layout too.
    kw = dict(model="mlp", optimizer="sgd", learning_rate=0.02,
              pixel_format="packed")
    a = trainer.fit(BASE.replace(spmd_mode="auto", **kw), data=tiny_data)
    b = trainer.fit(BASE.replace(spmd_mode="explicit", **kw),
                    data=tiny_data)
    np.testing.assert_allclose(a["test_accuracy"], b["test_accuracy"],
                               atol=1e-6)


def test_grad_accum_packed(tiny_data):
    # microbatch re-gathers slice the packed dataset identically
    kw = dict(model="mlp", optimizer="sgd", learning_rate=0.02,
              pixel_format="packed")
    a = trainer.fit(BASE.replace(grad_accum=1, **kw), data=tiny_data)
    b = trainer.fit(BASE.replace(grad_accum=4, **kw), data=tiny_data)
    np.testing.assert_allclose(a["final_loss"], b["final_loss"],
                               rtol=0, atol=1e-5)


def test_unknown_pixel_format_rejected(tiny_data):
    from distributedmnist_tpu.data.loader import DeviceDataset
    from distributedmnist_tpu.parallel import make_mesh
    with pytest.raises(ValueError, match="pixel format"):
        DeviceDataset(tiny_data, make_mesh(jax.devices()[:1]),
                      pixel_format="float64")
    with pytest.raises(ValueError, match="pixel format"):
        trainer._decoder("float64", jnp.float32)
