"""Test bootstrap: force an 8-virtual-device CPU mesh (SURVEY.md §4).

All distributed tests run the REAL mesh/psum/sharding code path on 8 fake
CPU devices via --xla_force_host_platform_device_count. The env's axon
sitecustomize may have already imported jax and pinned JAX_PLATFORMS=axon,
so the platform is also overridden post-import via jax.config — that works
even when the TPU tunnel is unreachable.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8")
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402

import numpy as np  # noqa: E402

from distributedmnist_tpu.data import synthetic_mnist  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line("markers", "slow: long-running test")
    config.addinivalue_line(
        "markers",
        "chaos: deterministic fault-injection test (serve/faults.py "
        "schedules with fixed seeds; cheap and replayable, so chaos "
        "tests run in tier-1 — `-m 'not slow'` keeps them)")
    config.addinivalue_line(
        "markers",
        "fleet: replica-fleet test (serve/fleet.py: health-tracked "
        "dispatch, failover, hedging, drain/rejoin); runs in tier-1 "
        "like chaos — the marker exists for `-m fleet` selection")
    config.addinivalue_line(
        "markers",
        "quant: quantized/fused inference fast-path test "
        "(serve/quantize.py, ops/fused.py inference epilogues, the "
        "registry's dtype-variant parity gate); cheap and "
        "deterministic, so quant tests run in tier-1 — `-m 'not slow'` "
        "keeps them, `-m quant` selects just this suite "
        "(scripts/tier1.sh notes the inclusion)")
    config.addinivalue_line(
        "markers",
        "analysis: static-analysis / concurrency-sanitizer test "
        "(distributedmnist_tpu/analysis: the lock-order sanitizer, "
        "resource-balance accounting, and the AST project lint); pure "
        "python, runs in tier-1 — `-m analysis` selects just this "
        "suite")
    config.addinivalue_line(
        "markers",
        "mc: deterministic concurrency model-checker test "
        "(analysis/explore.py + analysis/harnesses.py: schedule "
        "exploration over the serve state machines, planted-mutation "
        "self-tests, replay determinism); fixed seeds and bounded "
        "budgets, runs in tier-1 — `-m mc` selects just this suite; "
        "scripts/explore.sh runs the long-budget sweep")
    config.addinivalue_line(
        "markers",
        "jaxcheck: static compile-surface auditor test "
        "(analysis/jaxcheck.py: cache-key universe closure, transfer/"
        "weak-type hazard scans, jaxpr fingerprint snapshots); "
        "abstract tracing only — no device work — so it runs in "
        "tier-1; `-m jaxcheck` selects just this suite "
        "(scripts/tier1.sh also runs the CLI gate itself after lint)")
    config.addinivalue_line(
        "markers",
        "cache: prediction-cache / request-dedup test (serve/cache.py: "
        "the content-hash LRU front layer, single-flight collapse, "
        "invalidation-race coverage, the batcher's intra-batch dedup); "
        "cheap and deterministic, runs in tier-1 under the serve "
        "sanitizer fixture — `-m cache` selects just this suite "
        "(scripts/tier1.sh notes the inclusion)")
    config.addinivalue_line(
        "markers",
        "cascade: confidence-gated cascade test (serve/cascade.py: "
        "margin math, threshold calibration + the composed-accuracy "
        "gate, the CascadeFront partition/escalate/reassemble path, "
        "registry cascade lifecycle, accuracy-class/cache isolation); "
        "cheap and deterministic, runs in tier-1 under the serve "
        "sanitizer fixture — `-m cascade` selects just this suite "
        "(scripts/tier1.sh notes the inclusion)")
    config.addinivalue_line(
        "markers",
        "tenant: multi-tenant / multi-model serving test "
        "(serve/tenancy.py: tenant spec parsing, token-bucket quotas, "
        "the deficit-round-robin grant loop, Clockwork-style EDF "
        "feasibility shedding, the ModelCatalog and the "
        "zero-steady-state-recompile guarantee); cheap and "
        "deterministic, runs in tier-1 under the serve sanitizer "
        "fixture — `-m tenant` selects just this suite "
        "(scripts/tier1.sh notes the inclusion)")
    config.addinivalue_line(
        "markers",
        "trace: request-tracing test (serve/trace.py: span trees, "
        "sampling/exemplar retention, Chrome export, stage "
        "attribution, the /trace + Prometheus surfaces); cheap and "
        "deterministic, runs in tier-1 under the serve sanitizer "
        "fixture — `-m trace` selects just this suite "
        "(scripts/tier1.sh notes the inclusion)")
    config.addinivalue_line(
        "markers",
        "gateway: horizontal scale-out gateway test (serve/gateway.py: "
        "consistent-hash ring determinism + minimal key movement, "
        "affinity routing and backpressure, worker-death failover "
        "ordering, the cluster-epoch two-phase promote and "
        "mixed-epoch rejection, plus one multi-process HTTP "
        "end-to-end); cheap and deterministic, runs in tier-1 under "
        "the serve sanitizer fixture — `-m gateway` selects just "
        "this suite (scripts/tier1.sh notes the inclusion)")
    config.addinivalue_line(
        "markers",
        "autoscale: workload-realism / autoscaling test "
        "(serve/workload.py trace-replay generation + "
        "serve/autoscale.py: the Signals pressure surface, hysteresis "
        "+ cooldown decisions, floor/ceiling enforcement, the window "
        "and gateway actuators, action pricing and the Prometheus "
        "series); cheap and deterministic, runs in tier-1 under the "
        "serve sanitizer fixture — `-m autoscale` selects just this "
        "suite (scripts/tier1.sh notes the inclusion)")
    # A DMNIST_SANITIZE=1 environment installs a process-global
    # sanitizer at import time — under pytest that instance must yield
    # to the per-test installs (the serve autouse fixture and the
    # analysis tests each install a FRESH one for isolation, and
    # install_sanitizer refuses to stack). Without this, exporting the
    # README-advertised env var would error every serve test at setup.
    from distributedmnist_tpu.analysis import sanitize
    if sanitize.active_sanitizer() is not None:
        sanitize.uninstall_sanitizer()
    # Same trap, other env var (ISSUE 11): DMNIST_ANALYSIS_ARTIFACT=1
    # makes assert_clean() emit an ANALYSIS_r*.json round record —
    # under pytest that is every serve test's autouse teardown, which
    # would litter the repo root with one artifact per test. The env
    # opt-in is for serve.py runs; the suite never emits.
    os.environ.pop("DMNIST_ANALYSIS_ARTIFACT", None)
    # And the jaxcheck sibling (ISSUE 12): DMNIST_JAXCHECK_ARTIFACT=1
    # makes the auditor CLI emit a round artifact — the test suite
    # spawns that CLI as a subprocess (worker_env inherits os.environ),
    # so the opt-in must not leak in and litter the repo root.
    os.environ.pop("DMNIST_JAXCHECK_ARTIFACT", None)


def committed_steps(ckpt_dir: str) -> list:
    """Step numbers of orbax checkpoints already COMMITTED in ckpt_dir
    (an in-progress async save lives in a suffixed tmp dir, never an
    all-digit one). Shared by the preemption tests that poll for 'a
    periodic save has landed' before signalling a worker."""
    if not os.path.isdir(ckpt_dir):
        return []
    return sorted(int(d) for d in os.listdir(ckpt_dir) if d.isdigit())


def worker_env():
    """(env, repo_root) for spawning CPU-only worker subprocesses: no TPU
    relay dial, worker-controlled device count, repo on PYTHONPATH.
    Shared by every test that launches training workers."""
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)  # don't dial the TPU relay
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)  # worker sets its own device count
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    return env, repo_root


def wait_for_committed_checkpoint(ckpt_dir: str, procs,
                                  timeout_s: float = 300.0) -> None:
    """Block until a committed orbax step exists in ckpt_dir — the signal
    that a worker's training is demonstrably past a periodic save. Fails
    the test if any worker exits first or the deadline passes."""
    import time

    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if committed_steps(ckpt_dir):
            return
        for p in procs:
            assert p.poll() is None, (
                "worker exited before any checkpoint was committed:\n"
                + p.communicate()[0][-3000:])
        time.sleep(0.2)
    pytest.fail("no checkpoint committed within the deadline")


@pytest.fixture(autouse=True)
def serve_sanitizer(request):
    """Run EVERY serve test under the installed concurrency sanitizer
    (ISSUE 8) and fail it on any finding at teardown: lock-order
    cycles (potential deadlock), blocking calls under a hot-path lock,
    and nonzero resource balances once drained (leaked staging-pool
    buffers / in-flight window slots — the PR 3/PR 5 review-round bug
    classes, asserted mechanically instead of re-found by hand).
    Serve code constructs its primitives via analysis.locks.make_*, so
    objects built inside the test are instrumented; with no sanitizer
    (every other test, and production) those factories return bare
    threading primitives."""
    if "test_serve" not in os.path.basename(str(request.node.fspath)):
        yield
        return
    from distributedmnist_tpu.analysis import sanitize

    san = sanitize.install_sanitizer()
    try:
        yield
        # Grace window first: an orderly stop() may still be fanning
        # out its last batch on daemon threads — balances settle to
        # zero as those complete (same rationale as the thread-hygiene
        # fixture below).
        san.wait_drained(timeout_s=5.0)
        try:
            san.assert_clean()
        except AssertionError as e:
            pytest.fail(str(e))
    finally:
        sanitize.uninstall_sanitizer()


@pytest.fixture(autouse=True)
def serve_thread_hygiene(request):
    """Fail any serve test that leaks a LIVE NON-DAEMON thread: the
    serving stack spins up dispatch/completion/shadow/warm threads, and
    a batcher or registry rewrite that forgets daemon=True (or loses a
    join) would otherwise strand threads silently — discovered only
    when a whole pytest process hangs at exit. Daemon threads are
    exempt: several serving threads (e.g. the shadow drain loop) are
    intentionally daemonic and park forever by design. A short grace
    window lets orderly stop() teardowns finish winding down."""
    import time as _time

    import threading

    if "test_serve" not in os.path.basename(str(request.node.fspath)):
        yield
        return
    before = set(threading.enumerate())
    yield

    def leaked():
        return [t for t in threading.enumerate()
                if t not in before and t.is_alive() and not t.daemon]

    deadline = _time.monotonic() + 5.0
    while leaked() and _time.monotonic() < deadline:
        _time.sleep(0.05)
    bad = leaked()
    if bad:
        pytest.fail(
            "serve test leaked live non-daemon thread(s): "
            f"{[t.name for t in bad]} — dispatch/completion/shadow "
            "threads must be daemons and/or joined by stop()")


@pytest.fixture(scope="session")
def eight_devices():
    devs = jax.devices()
    assert len(devs) >= 8, (
        "conftest expected 8 virtual CPU devices; got "
        f"{len(devs)} — was jax initialized before conftest ran?")
    return devs[:8]


@pytest.fixture(scope="session")
def tiny_data():
    """Small synthetic dataset shared across tests (fast)."""
    return synthetic_mnist(seed=0, train_n=2048, test_n=512)


@pytest.fixture(scope="session")
def rng():
    return np.random.default_rng(0)
