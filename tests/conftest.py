"""Test bootstrap: force an 8-virtual-device CPU mesh (SURVEY.md §4).

All distributed tests run the REAL mesh/psum/sharding code path on 8 fake
CPU devices via --xla_force_host_platform_device_count. The env's axon
sitecustomize may have already imported jax and pinned JAX_PLATFORMS=axon,
so the platform is also overridden post-import via jax.config — that works
even when the TPU tunnel is unreachable.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8")
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402

import numpy as np  # noqa: E402

from distributedmnist_tpu.data import synthetic_mnist  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line("markers", "slow: long-running test")


@pytest.fixture(scope="session")
def eight_devices():
    devs = jax.devices()
    assert len(devs) >= 8, (
        "conftest expected 8 virtual CPU devices; got "
        f"{len(devs)} — was jax initialized before conftest ran?")
    return devs[:8]


@pytest.fixture(scope="session")
def tiny_data():
    """Small synthetic dataset shared across tests (fast)."""
    return synthetic_mnist(seed=0, train_n=2048, test_n=512)


@pytest.fixture(scope="session")
def rng():
    return np.random.default_rng(0)
