"""Graceful-preemption e2e (round-3 verdict, item #8): SIGTERM a training
worker mid-run; it must exit 0 having force-saved a checkpoint at its
stopping step, and a fresh run must restore it and finish — the
checkpoint/recovery story for REAL preemptions, not just the
--fail-at-step injected-exception path."""

import json
import os
import signal
import subprocess
import sys

import pytest

from conftest import (committed_steps, wait_for_committed_checkpoint,
                      worker_env)
from distributedmnist_tpu import trainer
from distributedmnist_tpu.config import Config
from distributedmnist_tpu.data import synthetic_mnist


@pytest.mark.slow
def test_cli_redelivers_sigterm_after_summary(tmp_path):
    """train.py (the CLI boundary) re-delivers an absorbed SIGTERM after
    printing the summary line (round-4 advice): external orchestrators
    see conventional process semantics — exit status terminated-by-SIGTERM
    — while the checkpoint is saved and the summary still emitted."""
    ckpt = str(tmp_path / "cli-pre")
    env, repo_root = worker_env()
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    p = subprocess.Popen(
        [sys.executable, os.path.join(repo_root, "train.py"),
         "--device", "cpu", "--num-devices", "8", "--synthetic",
         "--model", "mlp", "--optimizer", "sgd", "--learning-rate", "0.05",
         "--batch-size", "64", "--steps", "200000",
         "--eval-every", "1000000", "--log-every", "0",
         "--checkpoint-dir", ckpt, "--checkpoint-every", "10"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True, env=env, cwd=repo_root)
    try:
        wait_for_committed_checkpoint(ckpt, [p])
        p.send_signal(signal.SIGTERM)
        out, _ = p.communicate(timeout=300)
    finally:
        if p.poll() is None:
            p.kill()
    # conventional semantics: the process dies BY the signal...
    assert p.returncode == -signal.SIGTERM, (p.returncode, out[-3000:])
    # ...but only after the summary line (with the preempted flag) and
    # the force-save made it out
    lines = [l for l in out.splitlines() if l.startswith("{")]
    assert lines, f"no summary line:\n{out[-3000:]}"
    summary = json.loads(lines[-1])
    assert summary["preempted"] is True
    assert summary["steps"] in committed_steps(ckpt)


@pytest.mark.slow
def test_sigterm_saves_and_resumes(tmp_path):
    ckpt = str(tmp_path / "pre")
    env, repo_root = worker_env()
    worker = os.path.join(os.path.dirname(__file__), "preempt_worker.py")

    total_steps = 200_000  # far more than ever runs before the SIGTERM
    p = subprocess.Popen(
        [sys.executable, worker, ckpt, str(total_steps)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True, env=env, cwd=repo_root)
    try:
        wait_for_committed_checkpoint(ckpt, [p])
        p.send_signal(signal.SIGTERM)
        out, _ = p.communicate(timeout=300)
    finally:
        if p.poll() is None:
            p.kill()
    assert p.returncode == 0, f"worker failed:\n{out[-3000:]}"
    lines = [l for l in out.splitlines() if l.startswith("PREEMPT ")]
    assert lines, f"no PREEMPT line in output:\n{out[-3000:]}"
    r = json.loads(lines[0][len("PREEMPT "):])
    assert r["preempted"] is True
    assert 10 <= r["steps"] < total_steps
    # the stopping step itself was force-saved, not just the last
    # periodic multiple of checkpoint_every
    assert r["steps"] in committed_steps(ckpt)

    # a fresh run restores the preemption save and finishes
    data = synthetic_mnist(seed=0, train_n=1024, test_n=256)
    resume_steps = r["steps"] + 10
    out2 = trainer.fit(
        Config(device="cpu", num_devices=8, model="mlp", optimizer="sgd",
               learning_rate=0.05, synthetic=True, batch_size=64,
               steps=resume_steps, eval_every=resume_steps, log_every=0,
               target_accuracy=None, fused_kernels="xla",
               checkpoint_dir=ckpt, checkpoint_every=10),
        data=data)
    assert out2["restored"] is True
    assert out2["preempted"] is False
    assert out2["steps"] == resume_steps
