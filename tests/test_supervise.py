"""utils/supervise.py unit tests — driven with tiny stub worker scripts
(no jax): acceptance only on parseable JSON records, stall kill + retry,
and the teardown-grace path where a worker produces its result but wedges
at exit.
"""

import json

from distributedmnist_tpu.utils import supervise


def _write(tmp_path, body):
    script = tmp_path / "worker.py"
    script.write_text(body)
    return str(script)


def _accept():
    return supervise.json_record_acceptor("metric")


def test_forwards_json_result(tmp_path, capfd):
    script = _write(tmp_path, """
import json
print("some banner line")
print(json.dumps({"metric": "m", "value": 1}))
""")
    rc = supervise.run_supervised(script, [], _accept(),
                                  stall_timeout=30, attempts=1)
    assert rc == 0
    out = capfd.readouterr().out.strip().splitlines()
    assert json.loads(out[-1]) == {"metric": "m", "value": 1}


def test_crash_without_result_retries_then_fails(tmp_path, capfd):
    script = _write(tmp_path, """
import sys
print("not a json result")
sys.exit(3)
""")
    rc = supervise.run_supervised(script, [], _accept(),
                                  stall_timeout=30, attempts=2)
    assert rc == 1
    err = capfd.readouterr().err
    assert "attempt 1/2" in err and "attempt 2/2" in err
    assert "exit code 3" in err


def test_silent_stall_is_killed(tmp_path, capfd):
    script = _write(tmp_path, """
import time
time.sleep(600)
""")
    rc = supervise.run_supervised(script, [], _accept(),
                                  stall_timeout=2, attempts=1)
    assert rc == 1
    assert "no output for 2s" in capfd.readouterr().err


def test_result_then_teardown_wedge_is_accepted(tmp_path, capfd):
    script = _write(tmp_path, """
import json, time
print(json.dumps({"metric": "m", "value": 2}))
time.sleep(600)                     # wedged runtime teardown
""")
    rc = supervise.run_supervised(script, [], _accept(),
                                  stall_timeout=4, attempts=1)
    assert rc == 0
    out = capfd.readouterr().out.strip().splitlines()
    assert json.loads(out[-1])["value"] == 2


def test_worker_env_marker(tmp_path, capfd):
    script = _write(tmp_path, """
import json, os
print(json.dumps({"metric": "env",
                  "worker": os.environ.get("DMNIST_SUPERVISED_WORKER")}))
""")
    assert not supervise.is_worker()
    rc = supervise.run_supervised(script, [], _accept(),
                                  stall_timeout=30, attempts=1)
    assert rc == 0
    rec = json.loads(capfd.readouterr().out.strip().splitlines()[-1])
    assert rec["worker"] == "1"


def test_fallback_env_used_after_exhausted_attempts(tmp_path, capfd):
    script = _write(tmp_path, """
import json, os, sys
if os.environ.get("FORCE_OK") != "1" or "POISON" in os.environ:
    sys.exit(7)                     # primary backend 'dead'
print(json.dumps({"metric": "m", "value": 9}))
""")
    import os
    os.environ["POISON"] = "x"      # must be UNSET by the None override
    try:
        rc = supervise.run_supervised(
            script, [], _accept(), stall_timeout=30, attempts=2,
            fallback_env={"FORCE_OK": "1", "POISON": None})
    finally:
        del os.environ["POISON"]
    assert rc == 0
    cap = capfd.readouterr()
    assert json.loads(cap.out.strip().splitlines()[-1])["value"] == 9
    assert "attempt 1/3" in cap.err and "attempt 2/3" in cap.err
    assert "fallback attempt" in cap.err


def test_accept_scans_each_line_once(tmp_path, capfd):
    """run_supervised hands accept() only NEWLY-arrived lines per poll
    (round-4 advice: the old full-buffer rescan was O(lines^2) over a
    chatty multi-hour run) — and the cached result still forwards."""
    script = _write(tmp_path, """
import json, time
for i in range(40):
    print(f"chatter {i}")
    time.sleep(0.05)                # spread output across several polls
print(json.dumps({"metric": "m", "value": 3}))
time.sleep(3)                       # keep polling after the result
""")
    calls = []
    inner = _accept()

    def spy(lines):
        calls.append(len(lines))
        return inner(lines)

    rc = supervise.run_supervised(script, [], spy,
                                  stall_timeout=30, attempts=1)
    assert rc == 0
    out = capfd.readouterr().out.strip().splitlines()
    assert json.loads(out[-1])["value"] == 3
    # every line is scanned exactly once: chunk sizes sum to the 41
    # lines printed, across more than one poll
    assert sum(calls) == 41
    assert len(calls) > 1


def test_acceptor_ignores_non_record_json():
    accept = _accept()
    assert accept(["[1, 2]\n", "42\n", '"metric"\n']) is None
    assert accept(['{"other": 1}\n']) is None
    line = '{"metric": "m"}\n'
    assert accept(["junk\n", line]) == line
